"""v1 layer-DSL name compatibility (reference:
python/paddle/trainer_config_helpers/layers.py — `*_layer` functions,
activation objects, `settings()`; trainer_config_helpers/optimizers.py —
`MomentumOptimizer` etc.).

Usage — a v1-style config builds a paddle_tpu Program:

    from paddle_tpu.compat import v1
    net = v1.data_layer("data", size=3*32*32, height=32, width=32)
    net = v1.img_conv_layer(input=net, filter_size=5, num_filters=32,
                            padding=2, act=v1.ReluActivation())
    net = v1.img_pool_layer(input=net, pool_size=3, stride=2)
    out = v1.fc_layer(input=net, size=10, act=v1.SoftmaxActivation())
    cost = v1.classification_cost(input=out, label=v1.data_layer("label", 1))

Differences from the reference (deliberate, TPU-first):
- returns are Program `Variable`s, not LayerOutput protos;
- `data_layer(size=...)` for images needs `height`/`width` (static shapes
  are an XLA requirement); 1-D inputs use `[size]`;
- the proto pipeline (config_parser) is not reproduced.
"""

import numpy as np

from .. import layers, optimizer as _opt
from ..layers import tensor as _tensor

__all__ = [
    # activations
    "TanhActivation", "SigmoidActivation", "SoftmaxActivation",
    "IdentityActivation", "LinearActivation", "ReluActivation",
    "BReluActivation", "SoftReluActivation", "STanhActivation",
    "AbsActivation", "SquareActivation", "ExpActivation", "LogActivation",
    # layers
    "data_layer", "fc_layer", "embedding_layer", "img_conv_layer",
    "img_pool_layer", "img_cmrnorm_layer", "batch_norm_layer",
    "dropout_layer", "concat_layer", "addto_layer", "mixed_layer",
    "lstmemory", "grumemory", "simple_lstm", "simple_gru",
    "pooling_layer", "last_seq", "first_seq", "max_id", "scaling_layer",
    "slope_intercept_layer", "cos_sim", "trans_layer", "rotate_layer",
    "sum_cost", "classification_cost", "regression_cost", "mse_cost",
    "cross_entropy", "cross_entropy_with_selfnorm", "multi_binary_label_cross_entropy",
    "rank_cost", "lambda_cost", "huber_regression_cost", "smooth_l1_cost",
    "crf_layer", "crf_decoding_layer", "ctc_layer", "warp_ctc_layer",
    "nce_layer", "hsigmoid",
    # pooling types
    "MaxPooling", "AvgPooling", "SumPooling",
    # optimizers + settings
    "MomentumOptimizer", "AdamOptimizer", "AdaGradOptimizer",
    "RMSPropOptimizer", "AdaDeltaOptimizer", "settings",
    "L2Regularization",
    # config bookkeeping
    "inputs", "outputs",
]


# ---------------------------------------------------------------- activations
class _Act:
    name = None

    def __repr__(self):
        return f"{type(self).__name__}()"


def _act_cls(cls_name, act_name):
    cls = type(cls_name, (_Act,), {"name": act_name})
    return cls


TanhActivation = _act_cls("TanhActivation", "tanh")
SigmoidActivation = _act_cls("SigmoidActivation", "sigmoid")
SoftmaxActivation = _act_cls("SoftmaxActivation", "softmax")
IdentityActivation = _act_cls("IdentityActivation", None)
LinearActivation = IdentityActivation
ReluActivation = _act_cls("ReluActivation", "relu")
BReluActivation = _act_cls("BReluActivation", "brelu")
SoftReluActivation = _act_cls("SoftReluActivation", "soft_relu")
STanhActivation = _act_cls("STanhActivation", "stanh")
AbsActivation = _act_cls("AbsActivation", "abs")
SquareActivation = _act_cls("SquareActivation", "square")
ExpActivation = _act_cls("ExpActivation", "exp")
LogActivation = _act_cls("LogActivation", "log")


def _act(act, default=None):
    if act is None:
        return default
    if isinstance(act, _Act):
        return act.name
    return act  # already a string


# ---------------------------------------------------------------- pool types
class MaxPooling:
    name = "max"


class AvgPooling:
    name = "avg"


class SumPooling:
    name = "sum"


def _pool_name(pooling_type, default="max"):
    if pooling_type is None:
        return default
    return getattr(pooling_type, "name", pooling_type)


# ------------------------------------------------------------------- layers
def data_layer(name, size, height=None, width=None, depth=None, dtype=None,
               is_label=False, seq_len=None, sparse=False, **_):
    """v1 data_layer(size=...) -> layers.data.  Static shapes are an XLA
    requirement, so the ragged v1 slots take explicit extents here:
    image inputs pass height/width (channels inferred from size); integer
    id-sequence inputs pass dtype='int64' + seq_len (size then means
    vocabulary, stashed for embedding_layer); labels use is_label=True.
    ``sparse=True`` declares the slot as a native sparse input (the
    provider's sparse_binary/float_vector types): fc on it lowers to the
    O(nnz) weighted gather-sum and the slot feeds as @IDS/@VALS arrays —
    a 10M-dim CTR slot never materializes densely."""
    if sparse:
        # seq_len marks a sparse_*_vector_sequence slot: the shadow
        # arrays gain a time axis and @LENGTH carries sequence lengths
        var = layers.sparse_data(
            name, dim=size, lod_level=1 if seq_len is not None else 0)
        var._v1_vocab = size
        return var
    if height and width:
        channels = size // (height * width)
        shape = [channels, height, width]
        return layers.data(name, shape=shape, dtype=dtype or "float32")
    if seq_len is not None:
        var = layers.data(name, shape=[seq_len], dtype=dtype or "int64",
                          lod_level=1)
        var._v1_vocab = size
        return var
    if is_label or size == 1:
        return layers.data(name, shape=[1], dtype=dtype or "int64")
    return layers.data(name, shape=[size], dtype=dtype or "float32")


def _apply_act(out, a):
    if not a:
        return out
    return getattr(layers, a)(out)


def fc_layer(input, size, act=None, param_attr=None, bias_attr=None,
             name=None, **_):
    # layers.fc handles list inputs natively (per-input weights, summed
    # matmuls, ONE bias) — exactly the v1 multi-input fc semantics.
    out = layers.fc(input, size, param_attr=param_attr, bias_attr=bias_attr)
    out = _apply_act(out, _act(act, "tanh"))  # v1 default act is tanh
    from .v1_ext import _register_name

    return _register_name(out, name)


def embedding_layer(input, size, param_attr=None, **_):
    return layers.embedding(input, size=[_vocab_of(input), size],
                            param_attr=param_attr)


def _vocab_of(var):
    # v1 carries vocab on the data layer; here require the caller to have
    # made an int input whose declared "size" we stash on the Variable.
    v = getattr(var, "_v1_vocab", None)
    if v is None:
        raise ValueError(
            "embedding_layer needs the input's vocabulary size; build the "
            "input with integer_value(vocab) via data_layer(size=vocab, "
            "dtype='int64') and set input._v1_vocab = vocab, or use "
            "layers.embedding directly")
    return v


def img_conv_layer(input, filter_size, num_filters, stride=1, padding=0,
                   groups=1, num_channels=None, act=None, bias_attr=None,
                   param_attr=None, **_):
    return layers.conv2d(
        input, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=padding, groups=groups,
        param_attr=param_attr, bias_attr=bias_attr,
        act=_act(act, "relu"))


def img_pool_layer(input, pool_size, stride=1, padding=0, pool_type=None,
                   ceil_mode=False, **_):
    return layers.pool2d(
        input, pool_size=pool_size, pool_stride=stride,
        pool_padding=padding, pool_type=_pool_name(pool_type),
        ceil_mode=ceil_mode)


def img_cmrnorm_layer(input, size=5, scale=0.0001, power=0.75, **_):
    # reference config_parser.py:1347 divides scale by size for
    # cmrnorm-projection; the lrn op here sums squares without averaging,
    # so apply that division to match v1 numerics.
    return layers.lrn(input, n=size, alpha=scale / size, beta=power)


def batch_norm_layer(input, act=None, use_global_stats=None, **_):
    return layers.batch_norm(input, act=_act(act),
                             is_test=bool(use_global_stats))


def dropout_layer(input, dropout_rate, **_):
    return layers.dropout(input, dropout_prob=dropout_rate)


def concat_layer(input, act=None, **_):
    return _apply_act(_tensor.concat(list(input), axis=1), _act(act))


def addto_layer(input, act=None, bias_attr=None, name=None, **_):
    inputs = input if isinstance(input, (list, tuple)) else [input]
    out = inputs[0]
    for x in inputs[1:]:
        out = out + x
    out = _apply_act(out, _act(act))
    from .v1_ext import _register_name

    return _register_name(out, name)


# mixed_layer: see v1_ext.py (projection/operator form)


def lstmemory(input, size=None, reverse=False, act=None, **_):
    # v1 contract: size = hidden width, input already projected to
    # 4*size; dynamic_lstm's size is the 4*hidden projection width.
    hidden_x4 = 4 * size if size else input.shape[-1]
    hidden, _cell = layers.dynamic_lstm(input, size=hidden_x4,
                                        is_reverse=reverse)
    return hidden


def grumemory(input, size=None, reverse=False, act=None, **_):
    return layers.dynamic_gru(input, size=size or input.shape[-1] // 3,
                              is_reverse=reverse)


def simple_lstm(input, size, reverse=False, **_):
    proj = layers.fc(input, size * 4, num_flatten_dims=2)
    layers.link_sequence(proj, input)
    hidden, _cell = layers.dynamic_lstm(proj, size=size * 4,
                                        is_reverse=reverse)
    return hidden


def simple_gru(input, size, reverse=False, **_):
    proj = layers.fc(input, size * 3, num_flatten_dims=2)
    layers.link_sequence(proj, input)
    return layers.dynamic_gru(proj, size=size, is_reverse=reverse)


def pooling_layer(input, pooling_type=None, **_):
    return layers.sequence_pool(input,
                                pool_type=_pool_name(pooling_type, "sum"))


def last_seq(input, **_):
    return layers.sequence_last_step(input)


def first_seq(input, **_):
    return layers.sequence_first_step(input)


def max_id(input, **_):
    return _tensor.argmax(input, axis=-1)


def scaling_layer(input, weight, **_):
    return layers.elementwise_mul(input, weight)


def slope_intercept_layer(input, slope=1.0, intercept=0.0, **_):
    return layers.scale(input, scale=slope, bias=intercept)


def cos_sim(a, b, **_):
    return layers.cos_sim(a, b)


def trans_layer(input, **_):
    return _tensor.transpose(input, [1, 0])


def rotate_layer(input, height, width, **_):
    b, c = input.shape[0], input.shape[1] if len(input.shape) == 4 else 1
    x = _tensor.reshape(input, [b, c, height, width])
    x = _tensor.transpose(x, [0, 1, 3, 2])
    return x


# -------------------------------------------------------------------- costs
def classification_cost(input, label, **_):
    return layers.mean(layers.cross_entropy(input=input, label=label))


def cross_entropy(input, label, **_):
    return layers.mean(layers.cross_entropy(input=input, label=label))


cross_entropy_with_selfnorm = cross_entropy


def multi_binary_label_cross_entropy(input, label, **_):
    return layers.mean(
        layers.sigmoid_cross_entropy_with_logits(input, label))


def regression_cost(input, label, **_):
    return layers.mean(layers.square_error_cost(input=input, label=label))


mse_cost = regression_cost


def sum_cost(input, **_):
    return layers.reduce_sum(input)


def rank_cost(left, right, label, **_):
    diff = layers.sigmoid(left - right)
    return layers.mean(layers.cross_entropy(
        input=_tensor.concat([1.0 - diff, diff], axis=1), label=label))


def lambda_cost(input, score, NDCG_num=5, **_):
    # listwise LambdaRank reduces to a pairwise logistic surrogate here
    return layers.mean(layers.square_error_cost(input=input, label=score))


def huber_regression_cost(input, label, delta=1.0, **_):
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("huber_loss")
    out = helper.create_tmp_variable(input.dtype, list(input.shape))
    residual = helper.create_tmp_variable(input.dtype, list(input.shape),
                                          stop_gradient=True)
    helper.append_op(
        type="huber_loss",
        inputs={"X": [input.name], "Y": [label.name]},
        outputs={"Out": [out.name], "Residual": [residual.name]},
        attrs={"delta": float(delta)},
    )
    return layers.mean(out)


def smooth_l1_cost(input, label, **_):
    return layers.mean(layers.smooth_l1(input, label))


def crf_layer(input, label, **_):
    return layers.linear_chain_crf(input=input, label=label)


def crf_decoding_layer(input, label=None, **_):
    return layers.crf_decoding(input=input, label=label)


def ctc_layer(input, label, size=None, blank=None, norm_by_times=False, **_):
    return layers.warpctc(input=input, label=label,
                          blank=blank if blank is not None else (size or 1) - 1,
                          norm_by_times=norm_by_times)


warp_ctc_layer = ctc_layer


def nce_layer(input, label, num_classes, num_neg_samples=10, **_):
    return layers.nce(input=input, label=label,
                      num_total_classes=num_classes,
                      num_neg_samples=num_neg_samples)


def hsigmoid(input, label, num_classes, **_):
    # exact tree sigmoid (reference HierarchicalSigmoidLayer.cpp)
    return layers.hsigmoid(input=input, label=label,
                           num_classes=num_classes)


# --------------------------------------------------------------- optimizers
class L2Regularization:
    def __init__(self, rate):
        self.rate = rate


def MomentumOptimizer(momentum=0.9):
    return ("momentum", {"momentum": momentum})


def AdamOptimizer(beta1=0.9, beta2=0.999, epsilon=1e-8):
    return ("adam", {"beta1": beta1, "beta2": beta2, "epsilon": epsilon})


def AdaGradOptimizer():
    return ("adagrad", {})


def RMSPropOptimizer(rho=0.95, epsilon=1e-6):
    return ("rmsprop", {"rho": rho, "epsilon": epsilon})


def AdaDeltaOptimizer(rho=0.95, epsilon=1e-6):
    return ("adadelta", {"rho": rho, "epsilon": epsilon})


_OPT_CLASSES = {
    "momentum": _opt.Momentum,
    "adam": _opt.Adam,
    "adagrad": _opt.Adagrad,
    "rmsprop": _opt.RMSProp,
    "adadelta": _opt.Adadelta,
    "sgd": _opt.SGD,
}


def settings(batch_size=None, learning_rate=0.01, learning_method=None,
             regularization=None, **_):
    """v1 settings(): returns an optimizer ready to .minimize(cost).
    The v1 convention scales learning_rate by batch size externally; here
    the given learning_rate is used as-is."""
    if learning_method is None:
        learning_method = ("sgd", {})
    name, kwargs = learning_method
    if regularization is not None:
        kwargs = dict(kwargs)
        kwargs["regularization"] = _regularizer(regularization)
    cls = _OPT_CLASSES[name]
    return cls(learning_rate=learning_rate, **kwargs)


def _regularizer(reg):
    from .. import regularizer as reg_mod

    if isinstance(reg, L2Regularization):
        return reg_mod.L2Decay(reg.rate)
    return reg


# ----------------------------------------------------------- bookkeeping
def inputs(*layers_):
    """v1 config bookkeeping (declares feed order).  The Program tracks
    data vars itself; returned list preserved for caller convenience."""
    return list(layers_)


def outputs(*layers_):
    """v1 config bookkeeping (declares fetch targets).  Returns the list;
    fetch targets are whatever you pass to Executor.run(fetch_list=...)."""
    return list(layers_)


# ------------------------------------------------- long-tail surface
# (projections, recurrent_group, the remaining *_layer functions,
# activations/attrs/poolings/optimizers/evaluators/networks — see
# v1_ext.py; imported last so the helpers above exist at class-build time)
from .v1_ext import *  # noqa: F401,F403,E402
from . import v1_ext as _v1_ext  # noqa: E402

__all__ = list(dict.fromkeys(__all__ + _v1_ext.__all__))
