"""Compatibility surfaces for the reference's older APIs.

`v1` — the trainer_config_helpers layer-DSL names (reference:
python/paddle/trainer_config_helpers/layers.py, 275 defs).  The shim maps
the commonly used subset onto the paddle_tpu layers DSL so v1-style model
configs build a Program directly; the v1 proto pipeline (config_parser →
TrainerConfig proto) is deliberately not reproduced — configuration IS
the Program here (PARITY.md "Known deliberate divergences").
"""

from . import v1

__all__ = ["v1"]
