"""Learned cost model — the READ-BACK half of the observability loop.

PR 11 made every AOT compile emit a per-op-class flops/bytes/roofline
table keyed by the tune-cache workload key, and every trainer JSONL /
bench row ships the roofline's estimate-vs-measured error
(``attr_model_err_pct``).  Until now nothing ever read those
measurements back: attribution's ``est_ms`` and the tuner's static
pruning ran on hand-set analytic coefficients forever (ROADMAP item 4
— "what's missing is the LEARNING").  This module closes the loop, the
TVM-learned-cost-model / CUDA-L2 discipline from PAPERS.md: fit the
roofline+HBM coefficients on the corpus the system already emits
(``observability.corpus``), so every run makes the next run's
estimates — and therefore pruning, preflight and regression
attribution — tighter.

Model, per ``platform`` x op class::

    est_ms(class) = a * gflops + b * gbytes + c * ops

— ``a`` is an EFFECTIVE inverse peak (ms per Gflop), ``b`` an effective
inverse HBM bandwidth (ms per GB), ``c`` the per-call overhead the
analytic roofline has no column for (on CPU the overhead term is the
whole story: the analytic model underestimates wall time by ~100x).
A platform-level TOTAL model (``a``/``b`` + one per-step constant)
serves corpus rows that carry no per-class table, and a per-platform
``hbm_scale`` (clamped to [1.0, 2.0] — the HBM bound is a PRUNE, so
calibration may only make it more conservative, never un-reject
schedules the data can't vouch for) calibrates
``tune.space.estimate_gpt_step_hbm``.

Fitting is robust least squares (IRLS with Huber weights, nonnegative
coefficients, deterministic holdout split — every ``holdout_every``-th
row).  ``holdout_err_pct`` (median absolute error on held-out rows) is
stored next to ``analytic_err_pct`` on the SAME rows: the
``--costmodel-selftest`` CI gate asserts the fitted model strictly
improves.

Persistence mirrors the tune cache's robustness contract
(``tune/cache.py``): schema-versioned JSON next to the tune cache
(``PADDLE_TPU_COSTMODEL_PATH`` overrides), atomic tmp+rename writes,
and a corrupt / truncated / schema-mismatched file degrades to the
ANALYTIC defaults — ``tune.costmodel_errors`` counts, nothing crashes,
the next fit rewrites the file.  ``PADDLE_TPU_COSTMODEL=0`` is the kill
switch: every consult point (attribution's ``_finalize_roofline``, the
tuner's ``prune_static`` and ``estimate_gpt_step_hbm``) takes exactly
today's analytic code path, bit-exact.
"""

import json
import os
import tempfile
import time

from ..observability import metrics as _obs

__all__ = [
    "COSTMODEL_SCHEMA_VERSION", "costmodel_enabled", "costmodel_path",
    "CostModel", "get_model", "reset_model", "fit_cost_model",
    "fit_and_save", "active_entry", "model_status", "current_platform",
    "predict_class_ms", "predict_row_ms", "hbm_scale_for",
    "predict_sched_ms",
]

COSTMODEL_SCHEMA_VERSION = 1
_ENV_KILL = "PADDLE_TPU_COSTMODEL"
_ENV_PATH = "PADDLE_TPU_COSTMODEL_PATH"

# hbm_scale clamp: the analytic HBM bound is a prune — calibration may
# only make it MORE conservative (scale up when measurements show the
# bound underestimates), never relax it below the hand-calibrated
# coefficients (a 0.5x scale would un-reject the BENCH_r05 class from
# toy-run evidence that never saw a capacity shape)
_HBM_SCALE_MIN, _HBM_SCALE_MAX = 1.0, 2.0


def costmodel_enabled():
    """``PADDLE_TPU_COSTMODEL=0`` kills every fitted-model consult: the
    attribution roofline, the static prune and the HBM bound all run on
    the analytic defaults, bit-exact to the pre-costmodel framework."""
    return os.environ.get(_ENV_KILL, "1").lower() not in (
        "0", "", "false", "off", "no")


def costmodel_path():
    """On-disk model location: ``PADDLE_TPU_COSTMODEL_PATH`` wins, else
    ``costmodel.json`` next to the tune cache — so a test that scopes
    ``PADDLE_TPU_TUNE_CACHE`` to a tmp dir scopes the cost model too."""
    p = os.environ.get(_ENV_PATH)
    if p:
        return os.path.expanduser(p)
    from .cache import cache_path

    return os.path.join(os.path.dirname(cache_path()), "costmodel.json")


def current_platform():
    """The platform key consults fit under — ``jax.default_backend()``
    when a backend exists, else ``"unknown"`` (pure-text attribution
    tests never initialize jax; they get the analytic path)."""
    try:
        import jax

        return jax.default_backend()
    except Exception:  # noqa: BLE001 — backendless callers
        return "unknown"


class CostModel:
    """Load/consult/persist fitted coefficients with the tune cache's
    robustness contract: a file that fails to load degrades to the
    analytic defaults (``platforms == {}``), ``stale_reason`` says why,
    ``tune.costmodel_errors`` counts it, nothing crashes."""

    def __init__(self, path=None):
        self.path = path or costmodel_path()
        self.platforms = {}
        self.version = 0
        self.git_sha = None
        self.stale_reason = None
        self._load()

    def _reject(self, reason):
        self.stale_reason = reason
        self.platforms = {}
        self.version = 0
        _obs.get_registry().counter(
            "tune.costmodel_errors",
            help="cost-model files ignored (corrupt/truncated/schema); "
                 "analytic defaults applied, next fit rewrites").inc()

    def _load(self):
        try:
            with open(self.path, "r", encoding="utf-8") as fh:
                raw = json.load(fh)
        except FileNotFoundError:
            return
        except (OSError, ValueError, UnicodeDecodeError) as e:
            self._reject(f"unreadable cost model: {type(e).__name__}: {e}")
            return
        if not isinstance(raw, dict) or not isinstance(
                raw.get("platforms"), dict):
            self._reject(
                "cost model is not a {schema_version, platforms} object")
            return
        if raw.get("schema_version") != COSTMODEL_SCHEMA_VERSION:
            self._reject(
                f"schema_version {raw.get('schema_version')!r} != "
                f"{COSTMODEL_SCHEMA_VERSION}")
            return
        plats = {}
        for plat, entry in raw["platforms"].items():
            if isinstance(entry, dict) and isinstance(
                    entry.get("total"), list) and len(entry["total"]) == 3:
                plats[plat] = entry
        self.platforms = plats
        self.version = int(raw.get("version") or 0)
        self.git_sha = raw.get("git_sha")

    def entry(self, platform=None):
        """The fitted per-platform entry, or None (analytic)."""
        e = self.platforms.get(platform or current_platform())
        return e if isinstance(e, dict) else None

    def save(self):
        """Atomic persist (tmp + rename), tune-cache style."""
        from .cache import _git_sha

        payload = {
            "schema_version": COSTMODEL_SCHEMA_VERSION,
            "version": self.version,
            "git_sha": _git_sha(),
            "created_at": time.time(),
            "platforms": self.platforms,
        }
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(prefix=".costmodel.", suffix=".tmp",
                                   dir=d)
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                json.dump(payload, fh, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return self.path


_model_singleton = []  # [(resolved_path, CostModel)]


def get_model():
    """Process-wide model bound to the CURRENT resolved path — changing
    ``PADDLE_TPU_COSTMODEL_PATH``/``PADDLE_TPU_TUNE_CACHE`` re-loads."""
    path = costmodel_path()
    if _model_singleton and _model_singleton[0][0] == path:
        return _model_singleton[0][1]
    m = CostModel(path)
    _model_singleton[:] = [(path, m)]
    return m


def reset_model():
    """Drop the in-process singleton (next get_model() re-reads disk)."""
    _model_singleton[:] = []


def active_entry(platform=None):
    """The fitted entry the consult points use, or None when the kill
    switch is set, no model file fit this platform, or the file was
    rejected — None means "take exactly the analytic code path"."""
    if not costmodel_enabled():
        return None
    try:
        return get_model().entry(platform)
    except Exception:  # noqa: BLE001 — consult must never break a compile
        return None


def model_status(platform=None):
    """The ``costmodel`` status dict recorded in ``last_step_cost`` and
    trainer JSONL: ``{"mode": "fitted"|"analytic", "version",
    "train_rows", "holdout_err_pct"}`` (analytic mode carries only the
    mode — there is nothing fitted to describe)."""
    e = active_entry(platform)
    if e is None:
        return {"mode": "analytic"}
    try:
        version = get_model().version
    except Exception:  # noqa: BLE001
        version = None
    return {"mode": "fitted", "version": version,
            "train_rows": e.get("train_rows"),
            "holdout_err_pct": e.get("holdout_err_pct")}


def hbm_scale_for(platform=None):
    """The calibrated HBM-bound scale (>= 1.0; exactly 1.0 when
    analytic, so ``estimate_gpt_step_hbm`` stays bit-exact)."""
    e = active_entry(platform)
    if e is None:
        return 1.0
    try:
        s = float(e.get("hbm_scale") or 1.0)
    except (TypeError, ValueError):
        return 1.0
    return min(max(s, _HBM_SCALE_MIN), _HBM_SCALE_MAX)


# -- prediction -----------------------------------------------------------
def _coeffs(entry, cls):
    """(a, b, c) for an op class — the class's own fit when present,
    else the platform total's a/b with zero per-call overhead (the
    per-step constant is not a per-class quantity)."""
    cl = entry.get("classes") or {}
    co = cl.get(cls)
    if isinstance(co, list) and len(co) == 3:
        return float(co[0]), float(co[1]), float(co[2])
    a, b, _c = entry["total"]
    return float(a), float(b), 0.0


def predict_class_ms(entry, cls, flops, nbytes, ops):
    """One class's fitted estimate: ``(est_ms, compute_ms, mem_ms)`` —
    the compute/memory split keeps the bound verdict meaningful."""
    a, b, c = _coeffs(entry, cls)
    compute_ms = a * (flops or 0) / 1e9
    mem_ms = b * (nbytes or 0) / 1e9
    return compute_ms + mem_ms + c * (ops or 0), compute_ms, mem_ms


def predict_row_ms(entry, row):
    """A corpus row's fitted total estimate: the per-class sum when the
    row carries a class table, else the platform total model (with its
    per-step constant)."""
    classes = row.get("classes")
    if isinstance(classes, dict) and classes:
        total = 0.0
        for cls, r in classes.items():
            if not isinstance(r, dict):
                continue
            ms, _co, _me = predict_class_ms(
                entry, cls, r.get("flops"), r.get("bytes"), r.get("ops"))
            total += ms
        return total
    a, b, c = entry["total"]
    return (a * (row.get("flops") or 0) / 1e9
            + b * (row.get("bytes") or 0) / 1e9 + c)


def predict_sched_ms(entry, sched_flops):
    """Fitted cost of a flash schedule's MXU work — the figure
    ``prune_static``'s roofline slack compares when a model is loaded.
    Monotonic in ``sched_flops`` (a >= 0), so candidate ORDERING under
    the fitted model matches the analytic flop ordering; only the slack
    RATIO moves (the per-step overhead dilutes small flop deltas)."""
    a_cls, b_cls, _c = _coeffs(entry, "pallas")
    _a, _b, c_step = entry["total"]
    return a_cls * sched_flops / 1e9 + c_step


# -- fitting --------------------------------------------------------------
def _irls_nonneg(X, y, iters=5):
    """Robust nonnegative least squares: IRLS with Huber weights over a
    ridge-stabilized normal solve, coefficients clamped >= 0 each
    round.  Deterministic (numpy only, fixed iteration count)."""
    import numpy as np

    X = np.asarray(X, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    n, k = X.shape
    w = np.ones(n)
    beta = np.zeros(k)
    ridge = 1e-9 * np.eye(k)
    for _ in range(iters):
        Xw = X * w[:, None]
        try:
            beta = np.linalg.solve(Xw.T @ X + ridge, Xw.T @ y)
        except np.linalg.LinAlgError:
            break
        beta = np.maximum(beta, 0.0)
        resid = y - X @ beta
        scale = np.median(np.abs(resid)) * 1.4826 + 1e-12
        r = np.abs(resid) / scale
        w = np.where(r <= 1.345, 1.0, 1.345 / r)
    return [float(b) for b in beta]


def _median_abs_err_pct(pairs):
    """Median |est - measured| / measured * 100 over (est, measured)."""
    errs = sorted(abs(e - m) / m * 100.0 for e, m in pairs if m > 0)
    if not errs:
        return None
    mid = len(errs) // 2
    if len(errs) % 2:
        return round(errs[mid], 2)
    return round((errs[mid - 1] + errs[mid]) / 2.0, 2)


def _row_sort_key(row):
    return (str(row.get("workload") or ""), str(row.get("run_id") or ""),
            row.get("step") or 0, str(row.get("source") or ""))


def fit_cost_model(rows, holdout_every=4):
    """Fit per-platform coefficients on corpus rows (dicts with
    ``platform`` / ``measured_ms`` / ``flops`` / ``bytes`` / optional
    ``ops`` / ``classes`` / ``est_ms``).  Returns the ``platforms``
    payload a :class:`CostModel` persists; platforms with fewer than 3
    usable rows are left unfitted (analytic).

    Split is deterministic: rows sort by (workload, run_id, step,
    source) and every ``holdout_every``-th is held out.  Per-class
    coefficients fit against PROPORTIONALLY ALLOCATED measured time
    (each class's share of the row's analytic estimate — the standard
    trick when only whole-step walls are measured); rows without a
    class table feed the platform total model only."""
    by_plat = {}
    for row in rows or []:
        if not isinstance(row, dict):
            continue
        m = row.get("measured_ms")
        if not isinstance(m, (int, float)) or m <= 0:
            continue
        by_plat.setdefault(row.get("platform") or "unknown",
                           []).append(row)
    platforms = {}
    for plat, prows in sorted(by_plat.items()):
        prows = sorted(prows, key=_row_sort_key)
        if len(prows) < 3:
            continue
        step = max(2, int(holdout_every))
        holdout = [r for i, r in enumerate(prows) if i % step == step - 1]
        train = [r for i, r in enumerate(prows) if i % step != step - 1]
        if not holdout or len(train) < 2:
            continue
        # platform TOTAL model: [gflops, gbytes, 1] -> measured_ms
        X = [[(r.get("flops") or 0) / 1e9, (r.get("bytes") or 0) / 1e9,
              1.0] for r in train]
        y = [float(r["measured_ms"]) for r in train]
        total = _irls_nonneg(X, y)
        # per-class refinement on allocated measured time
        alloc = {}  # cls -> ([features], [allocated_ms])
        for r in train:
            classes = r.get("classes")
            if not isinstance(classes, dict) or not classes:
                continue
            est_total = sum(
                (c.get("est_ms") or 0.0) for c in classes.values()
                if isinstance(c, dict))
            for cls, c in sorted(classes.items()):
                if not isinstance(c, dict):
                    continue
                if est_total > 0:
                    w = (c.get("est_ms") or 0.0) / est_total
                else:
                    nb = sum((x.get("bytes") or 0)
                             for x in classes.values()
                             if isinstance(x, dict))
                    w = ((c.get("bytes") or 0) / nb) if nb else (
                        1.0 / len(classes))
                feats, targs = alloc.setdefault(cls, ([], []))
                feats.append([(c.get("flops") or 0) / 1e9,
                              (c.get("bytes") or 0) / 1e9,
                              float(c.get("ops") or 0)])
                targs.append(float(r["measured_ms"]) * w)
        class_coeffs = {}
        for cls, (feats, targs) in sorted(alloc.items()):
            if len(feats) >= 2 and any(t > 0 for t in targs):
                class_coeffs[cls] = [
                    round(v, 10) for v in _irls_nonneg(feats, targs)]
        entry = {
            "total": [round(v, 10) for v in total],
            "classes": class_coeffs,
            "train_rows": len(train),
            "holdout_rows": len(holdout),
        }
        # post-fit calibration: the per-class fits are INDEPENDENT
        # regressions on allocated time, so their sum can drift
        # systematically from the measured wall — one median
        # measured/predicted ratio over the train rows recenters every
        # coefficient (a single positive scalar, so candidate ordering
        # under predict_sched_ms is untouched)
        cal = sorted(float(r["measured_ms"]) / p for r, p in
                     ((r, predict_row_ms(entry, r)) for r in train)
                     if p > 0)
        if cal:
            s = cal[len(cal) // 2]
            if s > 0:
                entry["total"] = [round(v * s, 10)
                                  for v in entry["total"]]
                entry["classes"] = {
                    cls: [round(v * s, 10) for v in co]
                    for cls, co in entry["classes"].items()}
        # hbm_scale: measured-vs-estimated HBM high water, where rows
        # carry both (tune-cache measured candidates under a budget)
        ratios = sorted(
            r["hbm_high_water_bytes"] / r["hbm_est_bytes"]
            for r in prows
            if isinstance(r.get("hbm_high_water_bytes"), (int, float))
            and isinstance(r.get("hbm_est_bytes"), (int, float))
            and r["hbm_est_bytes"] > 0 and r["hbm_high_water_bytes"] > 0)
        if ratios:
            mid = ratios[len(ratios) // 2]
            entry["hbm_scale"] = round(
                min(max(mid, _HBM_SCALE_MIN), _HBM_SCALE_MAX), 4)
        else:
            entry["hbm_scale"] = 1.0
        # holdout scoring: fitted vs the analytic estimate RECORDED on
        # the same rows (est_ms is what the analytic roofline said at
        # measure time — the selftest seeds the corpus pre-fit, so the
        # comparison is apples-to-apples)
        fitted_pairs, analytic_pairs = [], []
        for r in holdout:
            m = float(r["measured_ms"])
            fitted_pairs.append((predict_row_ms(entry, r), m))
            if isinstance(r.get("est_ms"), (int, float)):
                analytic_pairs.append((float(r["est_ms"]), m))
        entry["holdout_err_pct"] = _median_abs_err_pct(fitted_pairs)
        entry["analytic_err_pct"] = _median_abs_err_pct(analytic_pairs)
        platforms[plat] = entry
    return platforms


def fit_and_save(corpus_or_rows, path=None):
    """Fit on a corpus (or raw row list), persist next to the tune
    cache, and return the saved :class:`CostModel`.  The singleton is
    reset so the next consult sees the new fit."""
    rows = getattr(corpus_or_rows, "rows", corpus_or_rows)
    platforms = fit_cost_model(rows)
    m = CostModel(path)
    m.stale_reason = None
    m.platforms = platforms
    m.version = int(m.version or 0) + 1
    m.save()
    reset_model()
    return m
