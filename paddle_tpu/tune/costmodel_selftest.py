"""``python -m paddle_tpu --costmodel-selftest`` — the learned cost
model's CI gate, CPU-only (wired into tools/tier1.sh).

The whole observability->tuning loop proves itself off-accelerator:

1. SEED: two real CPU-measured toy-GPT runs (different sequence
   lengths) stream through the production ``MetricsReporter`` into
   trainer JSONL; the corpus ingests them plus a bench-artifact
   fixture built from a real attribution table, classifying (not
   crashing on) a planted non-object artifact.
2. FIT: ``fit_and_save`` on that corpus; the fitted holdout error must
   STRICTLY improve on the analytic roofline's recorded error over the
   same held-out rows (on CPU the analytic model underestimates wall
   time by ~100x — the fitted per-step constant closes it).
3. CONSULT: a fresh compile records ``costmodel: fitted`` in
   ``last_step_cost`` and its trainer JSONL rows; the t=16k flagship
   static prune still REJECTS the known-OOM BENCH_r05 config and
   selects the SAME known-good schedule as the analytic model
   (``predict_sched_ms`` is monotonic in flops — ordering preserved).
4. ROBUSTNESS: a corrupt, truncated, or schema-mismatched model file
   each degrades to the analytic defaults (``tune.costmodel_errors``
   counts, ``attribute_hlo`` stays bit-exact to the no-model baseline).
5. KILL SWITCH: ``PADDLE_TPU_COSTMODEL=0`` with a VALID fitted file on
   disk reproduces the no-model estimates bit-exact — the attribution
   table's floats, ``estimate_gpt_step_hbm``'s ints and the full
   flagship static demo.
"""

import json
import os
import tempfile
import time

__all__ = ["run_selftest"]

_TOY = dict(vocab=61, n_layer=3, n_head=2, d_model=64, batch=4,
            dtype="float32")

# a synthetic-but-wellformed optimized-HLO module: the pure-function
# currency for the bit-exactness checks (one dot, one fusion whose body
# op carries flops but no bytes, one reduce — three distinct op classes)
_TOY_HLO = """\
HloModule costmodel_selftest

%fused_add (a: f32[64,64], b: f32[64,64]) -> f32[64,64] {
  %a = f32[64,64] parameter(0)
  %b = f32[64,64] parameter(1)
  ROOT %add.9 = f32[64,64] add(%a, %b)
}

ENTRY %main (p0: f32[64,64], p1: f32[64,64]) -> f32[64] {
  %p0 = f32[64,64] parameter(0)
  %p1 = f32[64,64] parameter(1)
  %dot.1 = f32[64,64] dot(%p0, %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %fusion.2 = f32[64,64] fusion(%dot.1, %p1), kind=kLoop, calls=%fused_add
  ROOT %reduce.3 = f32[64] reduce(%fusion.2, %p1), dimensions={1}
}
"""


class EndIteration:
    """Duck-typed trainer event (reporter dispatches on the class
    NAME) — the selftest synthesizes the step stream so the production
    MetricsReporter writes genuine JSONL from real measured walls and
    real compiled cost dicts, without trainer scaffolding."""

    def __init__(self, pass_id, batch_id, cost, wall_time, step_cost,
                 samples):
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost
        self.wall_time = wall_time
        self.step_cost = step_cost
        self.samples = samples
        self.throughput = samples / wall_time if wall_time else None
        self.mfu = None
        self.reader_wait = None
        self.grad_norm = None


def _build_toy(seq_len):
    import paddle_tpu as pt
    from paddle_tpu.models import transformer

    pt.core.unique_name.reset()
    main_prog, startup = pt.Program(), pt.Program()
    main_prog.random_seed = 7
    with pt.program_guard(main_prog, startup):
        outs = transformer.build(
            vocab_size=_TOY["vocab"], n_layer=_TOY["n_layer"],
            n_head=_TOY["n_head"], d_model=_TOY["d_model"],
            max_len=seq_len, dropout_rate=0.0, dtype=_TOY["dtype"],
            fused_head=True)
        pt.memory_optimize(main_prog, policy="selective")
    return main_prog, startup, outs


def _measured_run(seq_len, steps, jsonl_path, run_id):
    """One real toy-GPT run: compile + ``steps`` measured steps, each
    streamed through a production MetricsReporter into ``jsonl_path``.
    Returns the compile's attribution table and last_step_cost."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.observability import MetricsReporter

    main_prog, startup, outs = _build_toy(seq_len)
    rng = np.random.default_rng(seq_len)
    toks = rng.integers(0, _TOY["vocab"],
                        (_TOY["batch"], seq_len)).astype(np.int64)
    feed = {"tokens": toks, "labels": np.roll(toks, -1, axis=1)}
    scope = pt.core.scope.Scope()
    pt.core.scope._scope_stack.append(scope)
    reporter = MetricsReporter(log_every_n=0, jsonl_path=jsonl_path,
                               run_meta={"run_id": run_id})
    try:
        exe = pt.Executor()
        exe.run(startup, scope=scope)
        # warmup pays the compile outside the measured walls
        exe.run(main_prog, feed=feed, fetch_list=[outs["avg_cost"]],
                scope=scope)
        for i in range(steps):
            t0 = time.perf_counter()
            loss = exe.run(main_prog, feed=feed,
                           fetch_list=[outs["avg_cost"]], scope=scope)[0]
            wall = time.perf_counter() - t0
            reporter(EndIteration(0, i, float(np.asarray(loss).ravel()[0]),
                                  wall, dict(exe.last_step_cost),
                                  _TOY["batch"]))
        return exe.last_attribution, dict(exe.last_step_cost)
    finally:
        reporter.close()
        pt.core.scope._scope_stack.pop()


def _hbm_points():
    """The estimate_gpt_step_hbm probe set for the bit-exactness check
    (flagship dims at t=16k across the policy/accum grid)."""
    from paddle_tpu.tune.space import estimate_gpt_step_hbm

    return [estimate_gpt_step_hbm(26, 5120, 40, 32000, 16384, 6,
                                  policy=p, accum=a)
            for p in ("none", "selective", "compact", "full", "offload")
            for a in (1, 2)]


def run_selftest():
    import jax

    jax.config.update("jax_platforms", "cpu")

    from paddle_tpu import tune
    from paddle_tpu.observability import attribution as attr
    from paddle_tpu.observability import get_registry
    from paddle_tpu.observability.corpus import Corpus
    from paddle_tpu.tune import costmodel as cm

    failures = []

    def check(cond, what):
        (failures.append(what) if not cond else None)
        print(("ok   " if cond else "FAIL ") + what)

    tmp = tempfile.mkdtemp(prefix="pt_costmodel_")
    old_env = {k: os.environ.get(k)
               for k in ("PADDLE_TPU_TUNE_CACHE", "PADDLE_TPU_COSTMODEL",
                         "PADDLE_TPU_COSTMODEL_PATH")}
    os.environ["PADDLE_TPU_TUNE_CACHE"] = os.path.join(tmp, "tuned.json")
    os.environ.pop("PADDLE_TPU_COSTMODEL", None)
    os.environ.pop("PADDLE_TPU_COSTMODEL_PATH", None)
    tune.reset_cache()
    cm.reset_model()
    reg = get_registry()
    try:
        # -- 0. the analytic baselines (no model file exists) -----------
        att_base = attr.attribute_hlo(_TOY_HLO)
        hbm_base = _hbm_points()
        demo_base = tune.flagship_static_demo()
        check(att_base.get("costmodel", {}).get("mode") == "analytic",
              "no model file: attribution runs analytic")

        # -- 1. seed the corpus from real measured GPT-family runs -----
        run_a = os.path.join(tmp, "run_a.jsonl")
        run_b = os.path.join(tmp, "run_b.jsonl")
        att_a, cost_a = _measured_run(128, 6, run_a, "costmodel-run-a")
        att_b, _cost_b = _measured_run(64, 6, run_b, "costmodel-run-b")
        check((cost_a.get("costmodel") or {}).get("mode") == "analytic",
              "pre-fit compile records costmodel: analytic in "
              "last_step_cost")
        co = Corpus()
        n_a = co.ingest_trainer_jsonl(run_a)
        n_b = co.ingest_trainer_jsonl(run_b)
        check(n_a == 6 and n_b == 6,
              f"trainer JSONL ingests every measured step row "
              f"({n_a} + {n_b})")
        # a bench artifact built from the real attribution table, the
        # bench.py _fold_attribution extras shape; its measured time is
        # run A's median wall so the reconstructed row is a consistent
        # 13th measurement, not an outlier
        walls = sorted(r["measured_ms"] for r in co.rows
                       if r["source"].endswith("run_a.jsonl"))
        rec = attr.reconcile(att_a, walls[len(walls) // 2] / 1e3)
        art = os.path.join(tmp, "BENCH_cm01.json")
        with open(art, "w", encoding="utf-8") as fh:
            json.dump({"n": 1, "rc": 0, "parsed": {
                "metric": "gpt_train_tokens_per_sec_per_chip",
                "value": 1.0, "unit": "tok/s",
                "extra": {
                    "gpt_attribution": {
                        "classes": att_a["classes"],
                        "workload": att_a.get("workload"),
                        "est_ms_total": att_a.get("est_ms_total")},
                    "gpt_attr_est_ms": rec["est_ms"],
                    "gpt_attr_model_err_pct": rec["err_pct"]}}}, fh)
        bad = os.path.join(tmp, "BENCH_cm02.json")
        with open(bad, "w", encoding="utf-8") as fh:
            fh.write("[1, 2, 3]")
        check(co.ingest_artifact(art) == 1,
              "bench artifact's attribution table becomes a corpus row")
        co.ingest_artifact(bad)
        check(any("not a JSON object" in reason
                  for _s, reason in co.skipped),
              f"non-object artifact classified, not crashed "
              f"({co.summary()['skip_reasons']})")
        check(len(co.rows) == 13 and all(
            r["platform"] == "cpu" for r in co.rows),
            f"corpus holds 13 cpu rows ({co.summary()})")

        # -- 2. fit: holdout error strictly beats the analytic model ---
        model = cm.fit_and_save(co)
        entry = model.entry("cpu")
        check(entry is not None and entry["train_rows"] >= 8,
              f"fit produced a cpu entry "
              f"(train_rows={entry and entry['train_rows']})")
        fit_err = entry and entry.get("holdout_err_pct")
        ana_err = entry and entry.get("analytic_err_pct")
        check(fit_err is not None and ana_err is not None
              and fit_err < ana_err,
              f"fitted holdout error strictly improves on the analytic "
              f"roofline ({fit_err}% < {ana_err}%)")
        st = cm.model_status()
        check(st.get("mode") == "fitted"
              and st.get("train_rows") == entry["train_rows"],
              f"model_status reports the fit ({st})")

        # -- 3. consult points: fitted estimates + preserved ordering --
        att_fit, cost_fit = _measured_run(
            64, 2, os.path.join(tmp, "run_c.jsonl"), "costmodel-run-c")
        check((cost_fit.get("costmodel") or {}).get("mode") == "fitted",
              "post-fit compile records costmodel: fitted in "
              "last_step_cost")
        with open(os.path.join(tmp, "run_c.jsonl"),
                  encoding="utf-8") as fh:
            crows = [json.loads(ln) for ln in fh if ln.strip()]
        csteps = [r for r in crows if r.get("event") == "step"]
        check(bool(csteps) and all(
            (r.get("costmodel") or {}).get("mode") == "fitted"
            for r in csteps),
            "trainer JSONL rows carry the fitted costmodel status")
        att_fit_hlo = attr.attribute_hlo(_TOY_HLO)
        check(att_fit_hlo["est_ms_total"] != att_base["est_ms_total"],
              f"fitted model moves the roofline estimates "
              f"({att_fit_hlo['est_ms_total']} vs analytic "
              f"{att_base['est_ms_total']} ms)")
        demo_fit = tune.flagship_static_demo()
        check("rejected" not in str(demo_fit) or demo_fit.get(
            "gpt_t16k_rejected_r05_config") is not None,
            "fitted t16k demo still runs the static prune")
        check(demo_fit.get("gpt_t16k_rejected_r05_config") is not None,
              f"fitted model still REJECTS the known-OOM BENCH_r05 "
              f"config ({demo_fit.get('gpt_t16k_rejected_r05_config')})")
        same_sel = all(
            demo_fit.get(k) == demo_base.get(k)
            for k in ("gpt_t16k_selected_policy",
                      "gpt_t16k_selected_accum",
                      "gpt_t16k_selected_block_q",
                      "gpt_t16k_selected_block_k"))
        check(same_sel and demo_base.get("gpt_t16k_selected_policy")
              is not None,
              f"tuner ordering preserved: fitted model selects the same "
              f"known-good schedule "
              f"({demo_fit.get('gpt_t16k_selected_policy')} accum="
              f"{demo_fit.get('gpt_t16k_selected_accum')})")

        # -- 4. cache robustness: corrupt/truncated/schema-mismatch ----
        path = cm.costmodel_path()
        with open(path, encoding="utf-8") as fh:
            good = fh.read()
        corruptions = [
            ("garbage", "{not json"),
            ("truncated", good[: len(good) // 2]),
            ("schema-mismatch", json.dumps(
                {"schema_version": 999, "platforms": {}})),
        ]
        for name, payload in corruptions:
            with open(path, "w", encoding="utf-8") as fh:
                fh.write(payload)
            e0 = reg.value("tune.costmodel_errors")
            cm.reset_model()
            m = cm.get_model()
            check(m.stale_reason is not None
                  and cm.active_entry("cpu") is None
                  and reg.value("tune.costmodel_errors") == e0 + 1,
                  f"{name} model file degrades to analytic defaults "
                  f"({m.stale_reason}; tune.costmodel_errors +1)")
            att_c = attr.attribute_hlo(_TOY_HLO)
            check(json.dumps(att_c, sort_keys=True)
                  == json.dumps(att_base, sort_keys=True),
                  f"{name}: attribution bit-exact to the no-model "
                  f"baseline")
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(good)
        cm.reset_model()
        check(cm.model_status().get("mode") == "fitted",
              "restoring the good file restores the fit")

        # -- 5. kill switch: bit-exact with a valid fitted file --------
        os.environ["PADDLE_TPU_COSTMODEL"] = "0"
        cm.reset_model()
        att_off = attr.attribute_hlo(_TOY_HLO)
        check(json.dumps(att_off, sort_keys=True)
              == json.dumps(att_base, sort_keys=True),
              "PADDLE_TPU_COSTMODEL=0 attribution BIT-EXACT vs the "
              "no-model baseline (fitted file on disk)")
        check(_hbm_points() == hbm_base,
              "PADDLE_TPU_COSTMODEL=0 estimate_gpt_step_hbm ints "
              "bit-exact vs the no-model baseline")
        demo_off = tune.flagship_static_demo()
        check(demo_off == demo_base,
              "PADDLE_TPU_COSTMODEL=0 flagship static demo identical "
              "to the no-model baseline")
        check(cm.model_status() == {"mode": "analytic"},
              "kill switch reports analytic status")
    finally:
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        tune.reset_cache()
        cm.reset_model()

    print("costmodel selftest " + ("FAILED" if failures else "PASSED"))
    return 1 if failures else 0
