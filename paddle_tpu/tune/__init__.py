"""paddle_tpu.tune — the autotuning engine (docs/autotune.md).

Every performance knob that decides whether a configuration compiles
and how fast it runs — flash ``block_q``/``block_k``, the ``DIAG_W``
causal sub-tile width, packed ``sub_heads`` routing, the remat/offload
policy, gradient accumulation — used to be hand-picked and global.
This package makes them MEASURED, per workload key
``(op, seq_len, d_head, n_heads, dtype, platform, remat)``:

- ``tune_gpt_step`` sweeps the candidate space, prunes statically
  (roofline via ``causal_flash_flops`` + analytic HBM bound), rejects
  OOM-doomed survivors from the COMPILED cost analysis
  (``Executor.compile_only`` + ``analysis.preflight_hbm``) before any
  step executes, times the rest median-of-k, and persists the winner
  in the on-disk cache (``PADDLE_TPU_TUNE_CACHE`` or
  ``~/.cache/paddle_tpu/tuned.json``);
- the hot path consults the cache: ``layers.multi_head_attention`` /
  ``models.transformer.build`` pick tuned flash geometry when the
  caller passes no explicit blocks, and
  ``memory_optimize(policy="auto")`` resolves the tuned remat policy;
- explicit arguments and env knobs (``BENCH_GPT_BLOCK_Q/K``,
  ``PADDLE_TPU_DIAG_W``) always win over the cache.

Modes (``PADDLE_TPU_TUNE``): ``off``/``0`` — kill switch, the framework
behaves bit-exactly as if this package did not exist; ``cached``
(default) — lookups only, a miss keeps today's defaults and NEVER
compiles; ``search`` — a miss triggers the measured search.  Lookup
traffic counts in the metrics registry (``tune.cache_hits`` /
``tune.cache_misses`` / ``tune.searches``) and is folded into
``Executor.last_step_cost``.

CI: ``python -m paddle_tpu --tune-selftest`` (tools/tier1.sh).
"""

import contextlib
import os

from ..observability import metrics as _obs
from .cache import (
    CACHE_SCHEMA_VERSION, TuneCache, cache_path, geometry_fingerprint,
    get_cache, reset_cache)
from .costmodel import (
    COSTMODEL_SCHEMA_VERSION, CostModel, costmodel_enabled,
    costmodel_path, fit_and_save, fit_cost_model, get_model,
    model_status, reset_model)
from .space import (
    POLICY_ORDER, WorkloadKey, attention_candidates,
    estimate_gpt_step_hbm, paged_attention_candidates, prune_static,
    schedule_candidates, serving_candidates, spec_candidates)
from .search import (
    PreflightRejected, flagship_dims, flagship_static_demo,
    tune_gpt_step, tune_paged_attention, tune_serving_decode,
    tune_spec_decode)

__all__ = [
    "CACHE_SCHEMA_VERSION", "TuneCache", "cache_path",
    "geometry_fingerprint", "get_cache", "reset_cache",
    "POLICY_ORDER", "WorkloadKey", "attention_candidates",
    "estimate_gpt_step_hbm", "paged_attention_candidates",
    "prune_static", "schedule_candidates",
    "serving_candidates", "spec_candidates", "PreflightRejected",
    "flagship_dims", "flagship_static_demo", "tune_gpt_step",
    "tune_paged_attention", "tune_serving_decode", "tune_spec_decode",
    "tune_mode", "attention_config", "schedule_config_for",
    "serving_decode_config", "spec_decode_config",
    "paged_attention_config",
    "forced_attention_config", "tune_stats",
    "COSTMODEL_SCHEMA_VERSION", "CostModel", "costmodel_enabled",
    "costmodel_path", "fit_and_save", "fit_cost_model", "get_model",
    "model_status", "reset_model",
]


def tune_mode():
    """The PADDLE_TPU_TUNE mode: "off" | "cached" | "search".  Default
    "cached" — consult the cache, never search in the hot path.  "0" /
    "off" / "false" is the kill switch: no lookup happens at all and
    every knob keeps its hand-picked default (bit-exact parity with the
    pre-tune framework, pinned by the selftest)."""
    v = os.environ.get("PADDLE_TPU_TUNE", "cached").strip().lower()
    if v in ("0", "off", "false", "no", ""):
        return "off"
    if v == "search":
        return "search"
    return "cached"


# test/search hook: a forced config consulted before the cache
_FORCED = []


@contextlib.contextmanager
def forced_attention_config(cfg):
    """Force :func:`attention_config` to return ``cfg`` inside the
    context — how the search measures a specific candidate's routing
    and how tests pin the hot path without a cache file."""
    _FORCED.append(dict(cfg) if cfg else None)
    try:
        yield
    finally:
        _FORCED.pop()


def _platform():
    import jax

    return jax.default_backend()


def _cache_lookup(op, seq_len, d_head, n_head, dtype, remat):
    """Counted cache lookup shared by every hot-path entry point.
    Returns the tuned config dict or None.  Zero side effects on the
    kill switch or an empty cache (the common CI case — the
    backend-initializing platform probe is skipped entirely); a real
    hit/miss counts ``tune.cache_hits``/``tune.cache_misses``."""
    if tune_mode() == "off":
        return None
    cache = get_cache()
    if not cache.entries:
        return None
    reg = _obs.get_registry()
    key = WorkloadKey(op, seq_len, d_head, n_head, dtype,
                      _platform(), remat=remat)
    entry = cache.get(key.s)
    if entry is None:
        reg.counter("tune.cache_misses",
                    help="tuned-config cache lookups missed").inc()
        return None
    reg.counter("tune.cache_hits",
                help="tuned-config cache lookups served").inc()
    return dict(entry.get("config") or {}) or None


def attention_config(seq_len, d_head, n_head, dtype, causal=True):
    """Hot-path lookup for ``layers.multi_head_attention``: the tuned
    kernel geometry ``{"block_q", "block_k", "diag_w", "packed"}`` for
    one attention shape, or None (caller keeps defaults)."""
    if _FORCED:
        return _FORCED[-1]
    if not causal or seq_len is None or int(seq_len) <= 0:
        return None
    return _cache_lookup("flash", seq_len, d_head, n_head, dtype,
                         remat="-")


def schedule_config_for(seq_len, d_head, n_head, dtype):
    """The tuned STEP schedule ``{"policy", "accum", "block_q", ...}``
    for one GPT shape, or None — consulted by
    ``memory_optimize(policy="auto")`` and bench.py's flagship path."""
    return _cache_lookup("gpt_step", seq_len, d_head, n_head, dtype,
                         remat="auto")


def serving_decode_config(max_len, d_head, n_head, dtype):
    """Hot-path lookup for ``serving.ServingEngine``: the tuned decode
    chunk size + prefill bucket geometry ``{"chunk", "min_bucket"}``
    for one serving shape (workload key ``op=serving_decode``, keyed on
    the slot KV capacity ``max_len``), or None — the engine keeps its
    hand-picked defaults.  Explicit constructor arguments always win
    (the engine only calls this when given no geometry)."""
    if max_len is None or int(max_len) <= 0:
        return None
    return _cache_lookup("serving_decode", max_len, d_head, n_head,
                         dtype, remat="-")


def paged_attention_config(seq_len, d_head, n_head, dtype):
    """Hot-path lookup for ``serving.batched_decode``'s paged
    attention: the tuned ``{"backend", "block_step"}`` for one slot KV
    capacity (workload key ``op=paged_attention``, keyed on the logical
    capacity ``T = NB * block_tokens`` like the other serving ops), or
    None — the kernel keeps its defaults (auto backend, one table entry
    per scan step).  Consulted at TRACE time, so a tuned entry costs
    one lookup per compile, never per step."""
    if seq_len is None or int(seq_len) <= 0:
        return None
    return _cache_lookup("paged_attention", seq_len, d_head, n_head,
                         dtype, remat="-")


def spec_decode_config(max_len, d_head, n_head, dtype):
    """Hot-path lookup for ``serving.ServingEngine``'s speculative
    draft window: the tuned ``{"k"}`` for one serving shape (workload
    key ``op=spec_decode``, keyed on the slot KV capacity ``max_len``
    like ``serving_decode``), or None — the engine keeps the
    hand-picked default.  Explicit ``spec_k`` always wins (the engine
    only calls this when given a draft but no window)."""
    if max_len is None or int(max_len) <= 0:
        return None
    return _cache_lookup("spec_decode", max_len, d_head, n_head,
                         dtype, remat="-")


def program_schedule_config(program):
    """The tuned schedule for a built Program, located by its flash
    attention op (shape + dtype read off the op's input var) — the
    ``memory_optimize(policy="auto")`` entry point.  None when the
    program has no flash op or the cache misses."""
    if tune_mode() == "off":
        return None
    block = program.global_block()
    for op in block.ops:
        if op.type not in ("flash_attention_packed", "flash_attention"):
            continue
        q_names = op.inputs.get("Q") or []
        var = block._find_var(q_names[0]) if q_names else None
        if var is None or len(var.shape) < 3:
            continue
        t = int(var.shape[1])
        if t <= 0:
            continue
        if op.type == "flash_attention_packed":
            n_head = int(op.attrs.get("n_head") or 0)
            if not n_head:
                continue
            d_head = int(var.shape[2]) // n_head
        else:
            n_head, d_head = int(var.shape[2]), int(var.shape[3])
        return schedule_config_for(t, d_head, n_head, var.dtype)
    return None


def tune_stats():
    """Registry snapshot for ``Executor.last_step_cost``: None when no
    tune traffic happened this process (keeps cost dicts stable for
    untuned runs)."""
    reg = _obs.get_registry()
    hits = int(reg.value("tune.cache_hits"))
    misses = int(reg.value("tune.cache_misses"))
    searches = int(reg.value("tune.searches"))
    if not (hits or misses or searches):
        return None
    return {"mode": tune_mode(), "cache_hits": hits,
            "cache_misses": misses, "searches": searches}
