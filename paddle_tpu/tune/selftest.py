"""``python -m paddle_tpu --tune-selftest`` — the autotune engine's CI
gate, CPU-only (wired into tools/tier1.sh).

A miniature measured search over a toy transformer proves the whole
loop off-accelerator:

1. SEARCH: candidates compile through the production AOT path and the
   HBM preflight REJECTS the over-budget schedules from compiled cost
   analysis alone (the BENCH_r05 class — policies that save too much
   activation exceed the planted budget and never execute a step); the
   measured winner must beat the worst measured candidate.
2. CACHE: a second invocation is a pure cache hit — zero new compiles
   (the executor's jit-cache counters pin it) and ``tune.cache_hits``
   increments.
3. KILL SWITCH: ``PADDLE_TPU_TUNE=0`` with a POPULATED cache is
   bit-exact vs the untuned defaults (empty cache), while the tuned
   path provably applies the winner's geometry to the program.
4. The t=16k flagship static demonstration rejects the BENCH_r05
   config (offload at accum=1) and selects a schedule with headroom.
"""

import json
import os
import tempfile

__all__ = ["run_selftest"]

_TOY = dict(seq_len=128, n_layer=3, d_model=64, n_head=2, vocab=61,
            batch=8, dtype="float32", fused_head=True)


def _build_toy(policy="auto"):
    import paddle_tpu as pt
    from paddle_tpu.models import transformer

    pt.core.unique_name.reset()
    main_prog, startup = pt.Program(), pt.Program()
    main_prog.random_seed = 7
    with pt.program_guard(main_prog, startup):
        outs = transformer.build(
            vocab_size=_TOY["vocab"], n_layer=_TOY["n_layer"],
            n_head=_TOY["n_head"], d_model=_TOY["d_model"],
            max_len=_TOY["seq_len"], dropout_rate=0.0,
            dtype=_TOY["dtype"], fused_head=_TOY["fused_head"])
        if policy:
            pt.memory_optimize(main_prog, policy=policy)
    return main_prog, startup, outs


def _train_bits(policy="auto", steps=3):
    """Loss trajectory as float bit patterns (the parity currency)."""
    import numpy as np
    import paddle_tpu as pt

    main_prog, startup, outs = _build_toy(policy=policy)
    rng = np.random.default_rng(5)
    toks = rng.integers(0, _TOY["vocab"],
                        (_TOY["batch"], _TOY["seq_len"])).astype(np.int64)
    feed = {"tokens": toks, "labels": np.roll(toks, -1, axis=1)}
    scope = pt.core.scope.Scope()
    pt.core.scope._scope_stack.append(scope)
    try:
        exe = pt.Executor()
        exe.run(startup, scope=scope)
        bits = []
        for _ in range(steps):
            loss = exe.run(main_prog, feed=feed,
                           fetch_list=[outs["avg_cost"]], scope=scope)[0]
            bits.append(np.asarray(loss, np.float32).tobytes())
        return bits, exe
    finally:
        pt.core.scope._scope_stack.pop()


def _flash_attrs(program):
    """(block_q, block_k) attrs of the program's first flash op."""
    for op in program.global_block().ops:
        if op.type in ("flash_attention_packed", "flash_attention"):
            return (op.attrs.get("block_q"), op.attrs.get("block_k"))
    return (None, None)


def run_selftest():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np  # noqa: F401 — jax initialized before paddle_tpu

    from paddle_tpu import tune
    from paddle_tpu.observability import get_registry

    failures = []

    def check(cond, what):
        (failures.append(what) if not cond else None)
        print(("ok   " if cond else "FAIL ") + what)

    tmp = tempfile.mkdtemp(prefix="pt_tune_")
    cache_file = os.path.join(tmp, "tuned.json")
    old_env = {k: os.environ.get(k)
               for k in ("PADDLE_TPU_TUNE", "PADDLE_TPU_TUNE_CACHE")}
    os.environ["PADDLE_TPU_TUNE_CACHE"] = cache_file
    os.environ["PADDLE_TPU_TUNE"] = "search"
    tune.reset_cache()
    reg = get_registry()
    try:
        # -- 1. measured search with a real HBM preflight ---------------
        budget = 20 << 20  # between full/compact (~15/18 MB) and
        # selective/none (~26 MB) compiled high-water on this backend
        rep = tune.tune_gpt_step(
            **_TOY, steps=2, warmup=1, repeats=2, budget_bytes=budget,
            block_caps=(64,), diag_ws=(64,),
            policies=("none", "selective", "compact", "full"),
            accums=(1,), max_measure=8)
        check(rep["source"] == "search" and rep["entry"] is not None,
              f"search ran and produced a winner ({rep['source']})")
        rejected = [m for m in rep["measured"]
                    if m["verdict"] == "preflight_rejected"]
        measured = [m for m in rep["measured"]
                    if m["verdict"] == "measured"]
        check(len(rejected) >= 1 and rep["pruned_preflight"] >= 1,
              f"HBM preflight rejected {len(rejected)} over-budget "
              f"schedule(s) from compiled cost analysis alone")
        check(any(m.get("policy") in ("none", "selective")
                  for m in rejected),
              f"the OOM-doomed save-everything schedule is among the "
              f"rejected ({sorted(m.get('policy') for m in rejected)})")
        check(all(m.get("hbm_high_water_bytes", 0) <= budget
                  for m in measured),
              "every measured candidate fit the budget")
        win = rep["entry"]["config"]
        meas = rep["entry"]["measured"]
        check(len(measured) >= 2
              and meas["median_s"] < meas["worst_median_s"],
              f"winner ({win.get('policy')}, {meas['median_s']:.4f}s) "
              f"beats the worst measured candidate "
              f"({meas['worst_median_s']:.4f}s)")

        # -- 2. second invocation: pure cache hit, zero recompiles ------
        os.environ["PADDLE_TPU_TUNE"] = "cached"
        c0 = reg.value("executor.compile_count")
        h0 = reg.value("tune.cache_hits")
        rep2 = tune.tune_gpt_step(**_TOY)
        check(rep2["source"] == "cache"
              and rep2["entry"]["config"] == win,
              "second invocation serves the winner from the cache")
        check(reg.value("executor.compile_count") == c0,
              "cache hit compiles NOTHING (jit cache counter flat)")
        check(reg.value("tune.cache_hits") > h0,
              "tune.cache_hits incremented")

        # -- 3. tuned config actually reaches the program ---------------
        main_tuned, _, _ = _build_toy(policy=None)
        bq, bk = _flash_attrs(main_tuned)
        check((bq, bk) == (win["block_q"], win["block_k"]),
              f"hot path applies the tuned geometry (attrs {bq}/{bk} == "
              f"winner {win['block_q']}/{win['block_k']})")

        # -- 4. kill-switch parity: TUNE=0 bit-exact vs untuned ---------
        os.environ["PADDLE_TPU_TUNE"] = "0"
        bits_off, exe_off = _train_bits(policy="auto")
        os.environ["PADDLE_TPU_TUNE"] = "cached"
        os.environ["PADDLE_TPU_TUNE_CACHE"] = os.path.join(
            tmp, "empty", "tuned.json")  # no file: miss -> defaults
        tune.reset_cache()
        bits_default, _ = _train_bits(policy="auto")
        check(bits_off == bits_default,
              "PADDLE_TPU_TUNE=0 with a populated cache is BIT-EXACT "
              "vs the untuned defaults (empty cache)")
        check(exe_off.last_step_cost.get("tune", {}).get("mode") in (
            None, "off"),
            "kill-switch run records no tuned lookups")
        os.environ["PADDLE_TPU_TUNE_CACHE"] = cache_file
        tune.reset_cache()
        _, exe_tuned = _train_bits(policy="auto")
        ts = exe_tuned.last_step_cost.get("tune") or {}
        check(ts.get("cache_hits", 0) > 0,
              f"tuned run folds tune stats into last_step_cost ({ts})")

        # -- 5. the t=16k flagship static demonstration -----------------
        demo = tune.flagship_static_demo()
        check("rejected" in str(demo.get("gpt_t16k_rejected_r05_config"))
              or "hbm estimate" in str(
                  demo.get("gpt_t16k_rejected_r05_config")),
              f"t16k static prune rejects the BENCH_r05 config "
              f"({demo.get('gpt_t16k_rejected_r05_config')})")
        check(demo.get("gpt_t16k_selected_policy") is not None
              and demo.get("gpt_t16k_selected_accum", 0) >= 1,
              f"t16k static prune selects a compilable schedule "
              f"({demo.get('gpt_t16k_selected_policy')} accum="
              f"{demo.get('gpt_t16k_selected_accum')})")
        print("tune demo: " + json.dumps(demo))
    finally:
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        tune.reset_cache()

    print("tune selftest " + ("FAILED" if failures else "PASSED"))
    return 1 if failures else 0
