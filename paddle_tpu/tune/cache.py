"""Persistent autotune config cache.

One JSON file maps canonical workload-key strings to winning configs and
their measured numbers::

    {"schema_version": 1,
     "fingerprint": "0f3a9c21bd04",
     "git_sha": "269de37a1b2c",
     "entries": {
        "op=gpt_step|t=16384|dh=128|h=6|dt=bfloat16|plat=tpu": {
            "config":   {"policy": "offload", "accum": 2,
                         "block_q": 512, "block_k": 1024, ...},
            "measured": {"median_s": 4.91, "tok_s": 120133.0, ...},
            "searched_at": 1754200000.0},
        "op=spec_decode|t=96|dh=64|h=8|dt=float32|plat=cpu|remat=-": {
            "config":   {"k": 4},
            "measured": {"median_s": 0.41, "accept_rate": 0.81, ...},
            "searched_at": 1754300000.0}}}

Ops currently cached: ``gpt_step`` (training schedule), ``flash``
(attention kernel geometry), ``serving_decode`` (engine chunk/bucket),
``spec_decode`` (speculative draft window k).

Location: ``PADDLE_TPU_TUNE_CACHE`` or ``~/.cache/paddle_tpu/tuned.json``.

The ``fingerprint`` is a content hash over the kernel-geometry decisions
(``DIAG_W``, ``LSE_LANES``, the ``FLASH_BWD_RESIDUALS`` contract, the
``packed_sub_heads``/``_pick_block`` decision tables): a tuned block size
is only meaningful for the kernel geometry it was measured against, so a
cache written by a different kernel generation is STALE — its entries
are ignored and the workload re-tunes (``git_sha`` rides along so a
stale file is attributable to a commit).  Robustness contract (pinned by
``tests/test_tune.py``): a corrupt/truncated file, a schema-version
mismatch, and a stale fingerprint each degrade to an EMPTY cache —
lookups miss, defaults apply, the next persisted search rewrites the
file — never a crash and never a silently-served wrong config.
"""

import hashlib
import json
import os
import tempfile
import time

from ..observability import metrics as _obs

__all__ = ["CACHE_SCHEMA_VERSION", "cache_path", "geometry_fingerprint",
           "TuneCache", "get_cache", "reset_cache"]

CACHE_SCHEMA_VERSION = 1
_ENV_PATH = "PADDLE_TPU_TUNE_CACHE"


def cache_path():
    """The on-disk cache location: ``PADDLE_TPU_TUNE_CACHE`` wins, else
    ``~/.cache/paddle_tpu/tuned.json``."""
    p = os.environ.get(_ENV_PATH)
    if p:
        return os.path.expanduser(p)
    return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                        "tuned.json")


def geometry_fingerprint():
    """Content hash of the kernel-geometry decision surface.  Any change
    to the diagonal sub-tile width, the packed-head routing table, the
    block-picking rule, or the flash backward residual contract changes
    the hash — and invalidates every cached schedule measured against
    the old geometry."""
    from ..ops import pallas_attention as pa

    basis = (
        CACHE_SCHEMA_VERSION,
        # NOT pa.DIAG_W: the sub-tile width is itself a tunable the
        # cache stores (and applies via apply_tuned_diag_w) — hashing
        # its current value would make a tuned cache invalidate itself.
        # The diagonal SCHEME is covered by sampling its decision rule:
        tuple(bool(pa._diag_subtile_live(j, kb, qs, ks, 1024, 1024,
                                         256, 256))
              for j in (0, 1, 3) for kb in (0, 1, 3)
              for qs in (0, 3) for ks in (0, 3)),
        pa.LSE_LANES,
        tuple(pa.FLASH_BWD_RESIDUALS),
        # the packed-head routing table over the geometries that matter
        tuple((h, d, pa.packed_sub_heads(h, d))
              for h in (1, 2, 3, 4, 6, 8)
              for d in (32, 64, 128, 256)),
        # the block-picking rule sampled over representative (t, cap)
        tuple(pa._pick_block(t, c)
              for t in (96, 2048, 4096, 16384)
              for c in (128, 256, 512, 1024, 2048)),
        _registry_surface(),
        # the schedule-dimension surface: which non-geometry knobs a
        # persisted gpt_step winner can carry.  Adding a dimension
        # (grad_rs joined with the true-ZeRO-3 gradient spelling,
        # docs/parallel.md rule 4) changes what an OLD winner means —
        # it was measured with the dimension pinned at its default —
        # so the fingerprint must move and retire it.
        ("policy", "accum", "fsdp", "grad_rs"),
    )
    return hashlib.sha256(repr(basis).encode()).hexdigest()[:12]


def _registry_surface():
    """The kernel-registry decision surface (docs/kernels.md): which
    backends each op class registers and the per-platform auto order.
    A tuned winner persists its kernel choice, so adding/removing a
    backend or reordering auto resolution changes what a cached config
    MEANS — the fingerprint must move with it.  Availability is
    deliberately NOT hashed: it is a host property, not a geometry
    decision (the workload key's ``plat=`` field already scopes it)."""
    try:
        from .. import kernels
    except Exception:  # mid-bootstrap partial import
        return ()
    return (
        tuple((op, tuple(sorted(b for b in kernels.BACKENDS
                                if kernels.get_kernel(op, b))))
              for op in kernels.registered_op_classes()),
        tuple(sorted((plat, order)
                     for plat, order in kernels.AUTO_ORDER.items())),
    )


def _git_sha():
    try:
        from ..observability.bench_history import run_stamp

        return run_stamp().get("git_sha")
    except Exception:  # noqa: BLE001 — identity must never block caching
        return None


class TuneCache:
    """Load/lookup/persist tuned configs with the robustness contract
    above.  ``stale_reason`` records why a file on disk was ignored
    (None when it loaded cleanly or did not exist)."""

    def __init__(self, path=None):
        self.path = path or cache_path()
        self.fingerprint = geometry_fingerprint()
        self.entries = {}
        self.stale_reason = None
        self._load()

    def _reject(self, reason):
        self.stale_reason = reason
        self.entries = {}
        _obs.get_registry().counter(
            "tune.cache_errors",
            help="tune cache files ignored (corrupt/schema/fingerprint); "
                 "defaults applied, next search rewrites").inc()

    def _load(self):
        try:
            with open(self.path, "r", encoding="utf-8") as fh:
                raw = json.load(fh)
        except FileNotFoundError:
            return
        except (OSError, ValueError, UnicodeDecodeError) as e:
            # corrupt / truncated / unreadable: empty cache, re-tune
            self._reject(f"unreadable cache: {type(e).__name__}: {e}")
            return
        if not isinstance(raw, dict) or not isinstance(
                raw.get("entries"), dict):
            self._reject("cache is not a {schema_version, entries} object")
            return
        if raw.get("schema_version") != CACHE_SCHEMA_VERSION:
            self._reject(
                f"schema_version {raw.get('schema_version')!r} != "
                f"{CACHE_SCHEMA_VERSION}")
            return
        if raw.get("fingerprint") != self.fingerprint:
            self._reject(
                f"kernel-geometry fingerprint {raw.get('fingerprint')!r} "
                f"is stale (current {self.fingerprint}, written at git "
                f"{raw.get('git_sha')!r})")
            return
        self.entries = {k: v for k, v in raw["entries"].items()
                        if isinstance(v, dict) and "config" in v}

    def get(self, key_s):
        """The entry for a canonical key string, or None."""
        e = self.entries.get(key_s)
        return e if isinstance(e, dict) else None

    def put(self, key_s, config, measured=None):
        entry = {"config": dict(config), "searched_at": time.time()}
        if measured:
            entry["measured"] = dict(measured)
        self.entries[key_s] = entry
        return entry

    def save(self):
        """Atomic persist (tmp + rename): a reader never sees a torn
        file, and a crash mid-write leaves the previous cache intact."""
        payload = {
            "schema_version": CACHE_SCHEMA_VERSION,
            "fingerprint": self.fingerprint,
            "git_sha": _git_sha(),
            "entries": self.entries,
        }
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(prefix=".tuned.", suffix=".tmp", dir=d)
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                json.dump(payload, fh, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return self.path


_cache_singleton = []  # [(resolved_path, TuneCache)]


def get_cache():
    """Process-wide cache bound to the CURRENT resolved path — changing
    ``PADDLE_TPU_TUNE_CACHE`` (tests, the selftest) re-loads."""
    path = cache_path()
    if _cache_singleton and _cache_singleton[0][0] == path:
        return _cache_singleton[0][1]
    c = TuneCache(path)
    _cache_singleton[:] = [(path, c)]
    return c


def reset_cache():
    """Drop the in-process singleton (the next get_cache() re-reads the
    file) — for tests and for re-reading a cache another process wrote."""
    _cache_singleton[:] = []
