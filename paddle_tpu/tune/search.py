"""The measured-feedback search loop (TVM-style schedule search with a
benchmark in the loop — PAPERS.md) and the flagship t=16k entry points.

``tune_gpt_step`` is the searchable workload: given a GPT training-step
shape it generates the schedule candidate space
(``space.schedule_candidates``), prunes statically (roofline + analytic
HBM bound), then for each survivor builds the Program, AOT-compiles it
through the production path (``Executor.compile_only`` ->
``lower().compile()``), runs the REAL HBM preflight on the compiled
figures (``analysis.preflight_hbm`` — an OOM-doomed candidate is
rejected from cost analysis alone, before any step executes), and times
the survivors median-of-k.  The winner persists in the on-disk cache
(``tune.cache``) under its workload key plus a companion ``op=flash``
entry so the hot-path attention lookup picks the same geometry.

Every measured candidate emits a ``tune.search`` span (category
``tune``) so a search session reads as a timeline in the Chrome trace;
``tune.searches`` / ``tune.candidates_measured`` /
``tune.pruned_static`` / ``tune.pruned_preflight`` count in the metrics
registry.
"""

import contextlib
import functools
import os
import time

import numpy as np

from ..observability import metrics as _obs
from ..observability import trace as _trace
from .cache import get_cache
from .space import (
    POLICY_ORDER, WorkloadKey, estimate_gpt_step_hbm,
    paged_attention_candidates, prune_static, schedule_candidates,
    serving_candidates, spec_candidates)

__all__ = ["tune_gpt_step", "tune_serving_decode", "tune_spec_decode",
           "tune_paged_attention", "flagship_static_demo",
           "flagship_dims", "PreflightRejected"]


class PreflightRejected(Exception):
    """A candidate whose COMPILED memory figures exceed the device
    budget — rejected after compile, before any step ran."""


@contextlib.contextmanager
def _diag_w(width):
    """Temporarily pin the causal diagonal sub-tile width while a
    candidate compiles (the kernels read ``pallas_attention.DIAG_W`` at
    trace time; the search is single-threaded).  A PADDLE_TPU_DIAG_W
    env pin wins — candidates then all run at the pinned width."""
    from ..ops import pallas_attention as pa

    if not width or int(width) == pa.DIAG_W or pa._DIAG_W_ENV:
        yield
        return
    old = pa.DIAG_W
    pa.DIAG_W = int(width)
    try:
        yield
    finally:
        pa.DIAG_W = old


@contextlib.contextmanager
def _zero3_rs_env(value):
    """Temporarily pin PADDLE_TPU_ZERO3_RS while a candidate compiles
    (``parallel.api.grad_rs_spec_for`` reads it at trace time; the
    search is single-threaded).  Restores the caller's setting —
    including absence — on exit."""
    old = os.environ.get("PADDLE_TPU_ZERO3_RS")
    os.environ["PADDLE_TPU_ZERO3_RS"] = str(value)
    try:
        yield
    finally:
        if old is None:
            os.environ.pop("PADDLE_TPU_ZERO3_RS", None)
        else:
            os.environ["PADDLE_TPU_ZERO3_RS"] = old


def flagship_dims():
    """The GPT flagship model dims (bench.py's BENCH_GPT_* envs win) —
    the ONE env-default table bench.py and the tune entry points share,
    so the searched workload key and the flagship run's lookup always
    agree."""
    return {
        "n_layer": int(os.environ.get("BENCH_GPT_LAYERS", "12")),
        "d_model": int(os.environ.get("BENCH_GPT_DMODEL", "768")),
        "n_head": int(os.environ.get("BENCH_GPT_HEADS", "6")),
        "vocab": int(os.environ.get("BENCH_GPT_VOCAB", "32768")),
        "batch": int(os.environ.get("BENCH_GPT_BATCH", "8")),
    }


def _measure_candidate(cand, *, seq_len, n_layer, d_model, n_head, vocab,
                       batch, dtype, fused_head, steps, warmup, repeats,
                       budget_bytes, learning_rate):
    """Build + AOT-compile + HBM-preflight + time ONE candidate.
    Returns ``(median_seconds, cost_dict)``; raises
    :class:`PreflightRejected` when the compiled high-water exceeds the
    budget (nothing was executed)."""
    import paddle_tpu as pt
    from paddle_tpu.analysis import preflight_hbm
    from paddle_tpu.models import transformer

    import contextlib

    from ..kernels import forced_backend

    pt.core.unique_name.reset()
    main_prog, startup = pt.Program(), pt.Program()
    main_prog.random_seed = 11
    with pt.program_guard(main_prog, startup):
        outs = transformer.build(
            vocab_size=vocab, n_layer=n_layer, n_head=n_head,
            d_model=d_model, max_len=seq_len, dropout_rate=0.0,
            dtype=dtype, fused_head=fused_head,
            learning_rate=learning_rate,
            attn_block_q=cand["block_q"], attn_block_k=cand["block_k"],
            attn_packed=cand.get("packed"))
        accum = int(cand.get("accum", 1) or 1)
        if accum > 1:
            pt.gradient_accumulation(main_prog, accum)
        policy = cand.get("policy")
        if policy and policy != "none":
            pt.memory_optimize(main_prog, policy=policy)
    if "fsdp" in cand:
        # the gather-vs-replicate schedule dimension: the executor's
        # scan body honors program._fsdp, so a replicate candidate is
        # measured truly replicated (meaningful only when the measuring
        # executor is mesh-bound with an fsdp axis — the single-chip
        # search times both spellings identically but still persists
        # the winner's choice for memory_optimize(policy="auto"))
        main_prog._fsdp = bool(cand["fsdp"])
    # the true-ZeRO-3 gradient-spelling dimension (docs/parallel.md
    # rule 4): grad_rs_spec_for reads PADDLE_TPU_ZERO3_RS at trace
    # time, so the override wraps the whole compile/measure phase —
    # like fsdp, a single-chip search times both spellings identically
    # but the winner's choice still persists for a mesh-bound consumer
    rs_ctx = (_zero3_rs_env("1" if cand["grad_rs"] else "0")
              if "grad_rs" in cand else contextlib.nullcontext())
    rng = np.random.default_rng(17)
    toks = rng.integers(0, vocab, (batch, seq_len)).astype(np.int64)
    feed = {"tokens": toks, "labels": np.roll(toks, -1, axis=1)}
    scope = pt.core.scope.Scope()
    pt.core.scope._scope_stack.append(scope)
    try:
        exe = pt.Executor()
        # the candidate's kernel-registry backend (docs/kernels.md):
        # forced for the whole compile/measure phase — kernel
        # resolution happens at TRACE time inside these runs (program
        # BUILD resolves nothing), so one context around them routes
        # every op of the step (flash AND the CE head) to the backend
        # being measured; an op the backend cannot serve falls back to
        # auto, exactly what the shipped configuration would do
        backend_ctx = (forced_backend(cand["backend"])
                       if cand.get("backend")
                       else contextlib.nullcontext())
        with backend_ctx, rs_ctx:
            exe.run(startup, scope=scope)
            with _diag_w(cand.get("diag_w")):
                cost = exe.compile_only(main_prog, feed=feed,
                                        fetch_list=[outs["avg_cost"]],
                                        scope=scope)
                findings = preflight_hbm(cost.get("hbm_high_water_bytes"),
                                         budget_bytes,
                                         context=f"candidate {cand}")
                if findings:
                    raise PreflightRejected(findings[0].message)
                run = lambda: exe.run(main_prog, feed=feed,
                                      fetch_list=[outs["avg_cost"]],
                                      scope=scope, return_numpy=False)
                for _ in range(max(0, warmup)):
                    run()
                times = []
                for _ in range(max(1, repeats)):
                    t0 = time.perf_counter()
                    out = None
                    for _ in range(max(1, steps)):
                        out = run()
                    np.asarray(out[0])  # host materialization = honest stop
                    times.append(time.perf_counter() - t0)
    finally:
        pt.core.scope._scope_stack.pop()
    return float(np.median(times)), cost


def _truncate_survivors(survivors, max_measure, report):
    """Cap the measured-candidate list at ``max_measure`` WITHOUT
    silently dropping a whole kernel backend: geometry-free backend
    candidates carry no roofline score, so a plain head-slice of the
    sorted list would cut e.g. the only xla_ref candidate and the
    "tuner picks kernels" dimension would degenerate to the pre-registry
    search with no trace.  The head keeps the statically best schedules;
    one best-ranked candidate per otherwise-dropped backend rides along
    (the budget stretches by at most the number of requested
    backends)."""
    if not max_measure or len(survivors) <= max_measure:
        return survivors
    keep = survivors[:max_measure]
    kept_backends = {c.get("backend") for c in keep}
    for c in survivors[max_measure:]:
        b = c.get("backend")
        if b is not None and b not in kept_backends:
            keep.append(c)
            kept_backends.add(b)
    report["truncated_to"] = len(keep)
    return keep


def tune_gpt_step(seq_len, n_layer, d_model, n_head, vocab, batch,
                  dtype="bfloat16", fused_head=True, steps=2, warmup=1,
                  repeats=3, budget_bytes=None, block_caps=None,
                  policies=POLICY_ORDER, accums=(1,), diag_ws=(256,),
                  fsdp_opts=(None,), grad_rs_opts=(None,),
                  backends=None, max_measure=8,
                  learning_rate=1e-3, force=False, mode=None):
    """Search (or serve from cache) the step schedule for one GPT shape.

    Returns a report dict: ``entry`` (the winning cache entry or None),
    ``source`` ("cache" | "search" | "miss"), candidate/prune counters,
    and the per-candidate ``measured`` list.  In mode "cached" (the hot
    path default) this NEVER compiles — a miss returns ``entry=None``
    and callers keep today's defaults.  Mode "search" measures on miss
    (or always, with ``force=True``) and persists the winner."""
    from . import tune_mode  # late: __init__ imports this module

    reg = _obs.get_registry()
    import jax

    key = WorkloadKey("gpt_step", seq_len, d_model // n_head, n_head,
                      dtype, jax.default_backend(), remat="auto")
    mode = mode or tune_mode()  # explicit callers (bench) may override
    report = {"key": key.s, "mode": mode, "entry": None, "source": "miss",
              "candidates": 0, "pruned_static": 0, "pruned_preflight": 0,
              "measured": []}
    if mode == "off":
        report["source"] = "off"
        return report
    cache = get_cache()
    hit = cache.get(key.s)
    if hit is not None and not force:
        reg.counter("tune.cache_hits",
                    help="tuned-config cache lookups served").inc()
        report.update(entry=hit, source="cache")
        return report
    reg.counter("tune.cache_misses",
                help="tuned-config cache lookups missed").inc()
    if mode != "search":
        return report

    reg.counter("tune.searches",
                help="measured schedule searches executed").inc()
    from ..ops import pallas_attention as pa

    if pa._DIAG_W_ENV:
        # env-pinned sub-tile width: every candidate runs (and is
        # labeled) at the pin — anything else would cache a config
        # measured at a width it does not record
        diag_ws = (pa._DIAG_W_ENV,)
    accums = tuple(a for a in accums if batch % a == 0)
    cands = schedule_candidates(seq_len, d_model // n_head, n_head,
                                block_caps=block_caps, policies=policies,
                                accums=accums or (1,), diag_ws=diag_ws,
                                fsdp_opts=fsdp_opts,
                                grad_rs_opts=grad_rs_opts,
                                backends=backends)
    report["candidates"] = len(cands)
    hbm_model = lambda c: estimate_gpt_step_hbm(
        n_layer, d_model, n_head, vocab, seq_len, batch,
        policy=c.get("policy"), accum=c.get("accum", 1))
    survivors, pruned = prune_static(
        seq_len, d_model // n_head, n_head, cands,
        hbm_budget=budget_bytes, hbm_model=hbm_model)
    report["pruned_static"] = len(pruned)
    if pruned:
        reg.counter(
            "tune.pruned_static",
            help="candidates rejected by static pruning (roofline/vmem/"
                 "analytic hbm) without compiling").inc(len(pruned))
        report["pruned_static_reasons"] = [
            (dict(c), r) for c, r in pruned[:8]]
    # cheapest-recompute-policy-first, then roofline: when the measure
    # budget truncates the list, the statically best schedules survive
    survivors.sort(key=lambda c: (
        POLICY_ORDER.index(c.get("policy") or "none"),
        c.get("accum", 1), c.get("roofline", 9.9), -c["block_q"]))
    survivors = _truncate_survivors(survivors, max_measure, report)

    tracer = _trace.get_tracer()
    measured = []
    for i, cand in enumerate(survivors):
        with tracer.span("tune.search", cat="tune", key=key.s,
                         candidate=i, **{k: v for k, v in cand.items()
                                         if k != "hbm_est_bytes"}) as sp:
            try:
                median_s, cost = _measure_candidate(
                    cand, seq_len=seq_len, n_layer=n_layer,
                    d_model=d_model, n_head=n_head, vocab=vocab,
                    batch=batch, dtype=dtype, fused_head=fused_head,
                    steps=steps, warmup=warmup, repeats=repeats,
                    budget_bytes=budget_bytes,
                    learning_rate=learning_rate)
            except PreflightRejected as e:
                reg.counter(
                    "tune.pruned_preflight",
                    help="compiled candidates rejected by the HBM "
                         "preflight before any step executed").inc()
                report["pruned_preflight"] += 1
                measured.append(dict(cand, verdict="preflight_rejected",
                                     reason=str(e)[:200]))
                sp.set(verdict="preflight_rejected")
                continue
            reg.counter("tune.candidates_measured",
                        help="schedule candidates compiled and timed").inc()
            tok_s = batch * seq_len * max(1, steps) / median_s
            rec = dict(cand, verdict="measured",
                       median_s=round(median_s, 6),
                       tok_s=round(tok_s, 1),
                       flops=cost.get("flops"),
                       bytes_accessed=cost.get("bytes_accessed"),
                       hbm_high_water_bytes=cost.get(
                           "hbm_high_water_bytes"),
                       temp_bytes=cost.get("temp_bytes"),
                       compile_seconds=round(
                           cost.get("compile_seconds") or 0.0, 3))
            # persist the backend that ACTUALLY ran, not the request:
            # forced_backend is non-strict, so an unavailable backend
            # candidate measures the auto fallback — recording the
            # requested name would cache a kernel choice that never
            # executed (the "keyed by which kernel ran" contract,
            # docs/kernels.md)
            kb = (cost.get("kernel_backends") or {}).get(
                "flash_attention")
            if cand.get("backend") and kb and kb != cand["backend"]:
                rec["backend"] = kb
                rec["backend_requested"] = cand["backend"]
            measured.append(rec)
            sp.set(verdict="measured", median_s=rec["median_s"])
    report["measured"] = measured
    timed = [m for m in measured if m["verdict"] == "measured"]
    if not timed:
        report["source"] = "exhausted"
        return report
    win = min(timed, key=lambda m: m["median_s"])
    config = {k: win[k] for k in ("block_q", "block_k", "diag_w",
                                  "packed", "policy", "accum", "fsdp",
                                  "grad_rs", "backend")
              if k in win and win[k] is not None}
    meas = {k: win[k] for k in ("median_s", "tok_s", "flops",
                                "bytes_accessed", "hbm_high_water_bytes",
                                # the analytic HBM bound the candidate
                                # was admitted under (prune_static):
                                # paired with the compiled high water
                                # above it is one hbm_scale calibration
                                # point for the learned cost model
                                "hbm_est_bytes",
                                "temp_bytes") if win.get(k) is not None}
    meas["worst_median_s"] = max(m["median_s"] for m in timed)
    meas["measured_candidates"] = len(timed)
    entry = cache.put(key.s, config, measured=meas)
    # companion kernel-geometry entry: the hot-path attention lookup
    # (layers.multi_head_attention) keys on the shape alone — it runs at
    # program BUILD time, before any remat policy is chosen
    flash_key = WorkloadKey("flash", seq_len, d_model // n_head, n_head,
                            dtype, key.platform, remat="-")
    cache.put(flash_key.s,
              {k: config[k] for k in ("block_q", "block_k", "diag_w",
                                      "packed", "backend")
               if k in config},
              measured={"from": key.s})
    cache.save()
    tracer.instant("tune.winner", cat="tune", key=key.s, **config)
    report.update(entry=entry, source="search")
    return report


def tune_serving_decode(params, n_layer, n_head, d_model, max_len,
                        dtype=None, max_slots=4, requests=6, prompt_len=5,
                        max_new=8, chunks=(2, 4, 8), min_buckets=(4, 8),
                        max_measure=6, force=False, mode=None, seed=0):
    """Search (or serve from cache) the serving engine's decode-chunk /
    prefill-bucket geometry for one model shape — the
    ``op=serving_decode`` tunable (docs/autotune.md "Adding a tunable
    op").  Each candidate builds a REAL engine (scheduler/telemetry and
    all), serves a fixed synthetic workload synchronously, and is timed
    wall-to-wall; the winner's ``{"chunk", "min_bucket"}`` persists
    under the workload key ``op=serving_decode|t=<max_len>|...|remat=-``
    and ``ServingEngine`` consults it whenever the caller passes no
    explicit geometry.  In mode "cached" (default) a miss NEVER builds
    an engine — callers keep the hand-picked defaults."""
    from . import tune_mode  # late: __init__ imports this module

    import jax

    reg = _obs.get_registry()
    if dtype is None:
        # key on the dtype the engine will SERVE in, or the persisted
        # winner lands under a key the engine's lookup never hits
        from ..models.transformer import infer_compute_dtype

        dtype = str(np.dtype(infer_compute_dtype(params)))
    key = WorkloadKey("serving_decode", max_len, d_model // n_head,
                      n_head, dtype, jax.default_backend(), remat="-")
    mode = mode or tune_mode()
    report = {"key": key.s, "mode": mode, "entry": None, "source": "miss",
              "candidates": 0, "measured": []}
    if mode == "off":
        report["source"] = "off"
        return report
    cache = get_cache()
    hit = cache.get(key.s)
    if hit is not None and not force:
        reg.counter("tune.cache_hits",
                    help="tuned-config cache lookups served").inc()
        report.update(entry=hit, source="cache")
        return report
    reg.counter("tune.cache_misses",
                help="tuned-config cache lookups missed").inc()
    if mode != "search":
        return report

    reg.counter("tune.searches",
                help="measured schedule searches executed").inc()
    from ..serving import ServingEngine

    cands = serving_candidates(max_len, chunks=chunks,
                               min_buckets=min_buckets)
    report["candidates"] = len(cands)
    if max_measure and len(cands) > max_measure:
        report["truncated_to"] = max_measure
        cands = cands[:max_measure]
    rng = np.random.default_rng(seed)
    vocab = int(np.asarray(params["tok_emb.w"]).shape[0])
    prompts = [rng.integers(1, vocab, (prompt_len,)).astype(np.int32)
               for _ in range(requests)]
    tracer = _trace.get_tracer()
    measured = []
    for i, cand in enumerate(cands):
        with tracer.span("tune.search", cat="tune", key=key.s,
                         candidate=i, **cand) as sp:
            eng = ServingEngine(
                params, n_layer, n_head, d_model, max_len=max_len,
                max_slots=max_slots, decode_chunk=cand["chunk"],
                min_bucket=cand["min_bucket"], prefix_reuse=False)
            eng.generate_many(prompts[:1], max_new_tokens=2)  # compile
            t0 = time.perf_counter()
            eng.generate_many(prompts, max_new_tokens=max_new)
            wall = time.perf_counter() - t0
            reg.counter("tune.candidates_measured",
                        help="schedule candidates compiled and timed").inc()
            tok_s = requests * max_new / wall
            rec = dict(cand, verdict="measured",
                       median_s=round(wall, 6), tok_s=round(tok_s, 1))
            measured.append(rec)
            sp.set(verdict="measured", median_s=rec["median_s"])
    report["measured"] = measured
    if not measured:
        report["source"] = "exhausted"
        return report
    win = min(measured, key=lambda m: m["median_s"])
    config = {"chunk": win["chunk"], "min_bucket": win["min_bucket"]}
    meas = {"median_s": win["median_s"], "tok_s": win["tok_s"],
            "worst_median_s": max(m["median_s"] for m in measured),
            "measured_candidates": len(measured)}
    entry = cache.put(key.s, config, measured=meas)
    cache.save()
    tracer.instant("tune.winner", cat="tune", key=key.s, **config)
    report.update(entry=entry, source="search")
    return report


def tune_spec_decode(params, draft_params, n_layer, n_head, d_model,
                     max_len, dtype=None, draft_n_layer=None,
                     max_slots=4, requests=6, prompt_len=5, max_new=8,
                     ks=(1, 2, 3, 4, 6, 8), max_measure=5, force=False,
                     mode=None, seed=0):
    """Search (or serve from cache) the speculative draft window ``k``
    for one serving shape — the ``op=spec_decode`` tunable
    (docs/autotune.md "Adding a tunable op").  The right ``k`` is a
    property of the WORKLOAD, not the model alone: it trades k + 1
    cheap draft steps against one verify forward that amortizes a
    target weight read over k + 1 positions, scaled by however often
    this draft actually agrees with this target — so each candidate
    builds a real speculative engine, serves a fixed synthetic
    workload, and is timed wall-to-wall.  The winner's ``{"k"}``
    persists under ``op=spec_decode|t=<max_len>|...|remat=-`` and
    ``ServingEngine`` consults it when constructed with a draft but no
    explicit ``spec_k``.  In mode "cached" (default) a miss NEVER
    builds an engine — the hand-picked default applies."""
    from . import tune_mode  # late: __init__ imports this module

    import jax

    reg = _obs.get_registry()
    if dtype is None:
        from ..models.transformer import infer_compute_dtype

        dtype = str(np.dtype(infer_compute_dtype(params)))
    key = WorkloadKey("spec_decode", max_len, d_model // n_head,
                      n_head, dtype, jax.default_backend(), remat="-")
    mode = mode or tune_mode()
    report = {"key": key.s, "mode": mode, "entry": None, "source": "miss",
              "candidates": 0, "measured": []}
    if mode == "off":
        report["source"] = "off"
        return report
    cache = get_cache()
    hit = cache.get(key.s)
    if hit is not None and not force:
        reg.counter("tune.cache_hits",
                    help="tuned-config cache lookups served").inc()
        report.update(entry=hit, source="cache")
        return report
    reg.counter("tune.cache_misses",
                help="tuned-config cache lookups missed").inc()
    if mode != "search":
        return report

    reg.counter("tune.searches",
                help="measured schedule searches executed").inc()
    from ..serving import ServingEngine

    cands = spec_candidates(max_len, ks=ks)
    report["candidates"] = len(cands)
    if max_measure and len(cands) > max_measure:
        report["truncated_to"] = max_measure
        cands = cands[:max_measure]
    rng = np.random.default_rng(seed)
    vocab = int(np.asarray(params["tok_emb.w"]).shape[0])
    prompts = [rng.integers(1, vocab, (prompt_len,)).astype(np.int32)
               for _ in range(requests)]
    tracer = _trace.get_tracer()
    measured = []
    for i, cand in enumerate(cands):
        with tracer.span("tune.search", cat="tune", key=key.s,
                         candidate=i, **cand) as sp:
            eng = ServingEngine(
                params, n_layer, n_head, d_model, max_len=max_len,
                max_slots=max_slots, prefix_reuse=False,
                draft_params=draft_params, draft_n_layer=draft_n_layer,
                spec_k=cand["k"])
            eng.generate_many(prompts[:1], max_new_tokens=2)  # compile
            t0 = time.perf_counter()
            eng.generate_many(prompts, max_new_tokens=max_new)
            wall = time.perf_counter() - t0
            reg.counter("tune.candidates_measured",
                        help="schedule candidates compiled and timed").inc()
            tok_s = requests * max_new / wall
            acc = (eng._spec.accepted / eng._spec.proposed
                   if eng._spec.proposed else 0.0)
            rec = dict(cand, verdict="measured",
                       median_s=round(wall, 6), tok_s=round(tok_s, 1),
                       accept_rate=round(acc, 4))
            measured.append(rec)
            sp.set(verdict="measured", median_s=rec["median_s"])
    report["measured"] = measured
    if not measured:
        report["source"] = "exhausted"
        return report
    win = min(measured, key=lambda m: m["median_s"])
    config = {"k": win["k"]}
    meas = {"median_s": win["median_s"], "tok_s": win["tok_s"],
            "accept_rate": win["accept_rate"],
            "worst_median_s": max(m["median_s"] for m in measured),
            "measured_candidates": len(measured)}
    entry = cache.put(key.s, config, measured=meas)
    cache.save()
    tracer.instant("tune.winner", cat="tune", key=key.s, **config)
    report.update(entry=entry, source="search")
    return report


def tune_paged_attention(n_head, d_head, max_len, block_tokens,
                         dtype="float32", slots=8,
                         block_steps=(1, 2, 4, 8), backends=None,
                         max_measure=8, repeats=3, force=False,
                         mode=None, seed=0):
    """Search (or serve from cache) the paged-attention block-iteration
    geometry x backend for one serving shape — the
    ``op=paged_attention`` tunable (docs/kernels.md "The tuner picks
    kernels").  Each candidate jits the registry call on a synthetic
    ragged block pool of the workload geometry (worst-case chain depth
    ``max_len / block_tokens``, per-slot positions spread across the
    capacity — the decode-step shape, W=1) under
    ``kernels.forced_backend`` and is timed median-of-``repeats``; the
    winner's ``{"backend", "block_step"}`` persists under
    ``op=paged_attention|t=<max_len>|...|remat=-`` and
    ``serving.batched_decode`` consults it at trace time.  Unavailable
    backends skip with the registry's reason.  In mode "cached"
    (default) a miss NEVER compiles."""
    from . import tune_mode  # late: __init__ imports this module

    import jax

    reg = _obs.get_registry()
    key = WorkloadKey("paged_attention", max_len, d_head, n_head,
                      str(np.dtype(dtype)), jax.default_backend(),
                      remat="-")
    mode = mode or tune_mode()
    report = {"key": key.s, "mode": mode, "entry": None, "source": "miss",
              "candidates": 0, "measured": []}
    if mode == "off":
        report["source"] = "off"
        return report
    cache = get_cache()
    hit = cache.get(key.s)
    if hit is not None and not force:
        reg.counter("tune.cache_hits",
                    help="tuned-config cache lookups served").inc()
        report.update(entry=hit, source="cache")
        return report
    reg.counter("tune.cache_misses",
                help="tuned-config cache lookups missed").inc()
    if mode != "search":
        return report

    reg.counter("tune.searches",
                help="measured schedule searches executed").inc()
    import jax.numpy as jnp

    from .. import kernels

    B = int(block_tokens)
    NB = max(1, int(max_len) // B)
    if backends is None:
        backends = tuple(
            b for b, ok, _ in kernels.available_backends("paged_attention")
            if ok)
    cands = paged_attention_candidates(NB, backends=backends,
                                       block_steps=block_steps)
    report["candidates"] = len(cands)
    if max_measure and len(cands) > max_measure:
        report["truncated_to"] = max_measure
        cands = cands[:max_measure]
    rng = np.random.default_rng(seed)
    S = int(slots)
    num_blocks = 1 + S * NB
    dt = jnp.dtype(dtype)
    q = jnp.asarray(rng.standard_normal((S, 1, n_head, d_head)), dt)
    pool_k = jnp.asarray(
        rng.standard_normal((num_blocks, B, n_head, d_head)), dt)
    pool_v = jnp.asarray(
        rng.standard_normal((num_blocks, B, n_head, d_head)), dt)
    table = jnp.asarray(
        1 + np.arange(S * NB).reshape(S, NB), jnp.int32)
    # ragged chains: per-slot live positions spread across the capacity
    pos = jnp.asarray(
        rng.integers(0, NB * B, (S, 1)), jnp.int32)
    tracer = _trace.get_tracer()
    measured = []
    for i, cand in enumerate(cands):
        with tracer.span("tune.search", cat="tune", key=key.s,
                         candidate=i, **cand) as sp:
            with kernels.forced_backend(cand["backend"],
                                        op_class="paged_attention"):
                impl = kernels.resolve("paged_attention").impl
                fn = jax.jit(functools.partial(
                    impl.call, block_step=cand["block_step"]))
                try:
                    jax.block_until_ready(
                        fn(q, pool_k, pool_v, table, pos))  # compile
                except Exception as e:  # noqa: BLE001
                    rec = dict(cand, verdict="failed", error=str(e))
                    measured.append(rec)
                    sp.set(verdict="failed")
                    continue
                walls = []
                for _ in range(int(repeats)):
                    t0 = time.perf_counter()
                    jax.block_until_ready(
                        fn(q, pool_k, pool_v, table, pos))
                    walls.append(time.perf_counter() - t0)
            reg.counter("tune.candidates_measured",
                        help="schedule candidates compiled and timed").inc()
            rec = dict(cand, verdict="measured",
                       median_s=round(float(np.median(walls)), 6))
            measured.append(rec)
            sp.set(verdict="measured", median_s=rec["median_s"])
    report["measured"] = measured
    timed = [m for m in measured if m["verdict"] == "measured"]
    if not timed:
        report["source"] = "exhausted"
        return report
    win = min(timed, key=lambda m: m["median_s"])
    config = {"backend": win["backend"], "block_step": win["block_step"]}
    meas = {"median_s": win["median_s"],
            "worst_median_s": max(m["median_s"] for m in timed),
            "measured_candidates": len(timed)}
    entry = cache.put(key.s, config, measured=meas)
    cache.save()
    tracer.instant("tune.winner", cat="tune", key=key.s, **config)
    report.update(entry=entry, source="search")
    return report


def flagship_static_demo(seq_len=16384, budget_bytes=None, batch=None):
    """The OFF-ACCELERATOR t=16k demonstration: statically prune the
    flagship schedule space against the chip budget and report which
    configs die and which survives — ``gpt_t16k_*`` keys for the bench
    row.  No compile, no measurement (a t=16k XLA compile is not a CPU
    smoke-path citizen): every figure is the analytic bound, labeled as
    an estimate.  The point on record: the BENCH_r05 config (offload at
    accum=1, default 1024 blocks) is REJECTED by the HBM prune, and a
    compilable capacity schedule (gradient accumulation + a
    lighter-recompute policy, with >=15% HBM headroom against allocator
    fragmentation) is selected instead — the same pruning the on-TPU
    search applies to real compiled figures before measuring."""
    dims = flagship_dims()
    if batch is not None:
        dims["batch"] = int(batch)
    # the t=16k capacity rounds run global batch 6 (bench memory_gate)
    elif seq_len >= 16384:
        dims["batch"] = 6
    if budget_bytes is None:
        budget_bytes = int(float(os.environ.get(
            "BENCH_HBM_BUDGET_GIB", "15.75")) * (1 << 30))
    d_head = dims["d_model"] // dims["n_head"]
    cands = schedule_candidates(
        seq_len, d_head, dims["n_head"], block_caps=(256, 512, 1024),
        policies=POLICY_ORDER, accums=(1, 2), diag_ws=(256,))
    hbm_model = lambda c: estimate_gpt_step_hbm(
        dims["n_layer"], dims["d_model"], dims["n_head"], dims["vocab"],
        seq_len, dims["batch"], policy=c.get("policy"),
        accum=c.get("accum", 1))
    survivors, pruned = prune_static(
        seq_len, d_head, dims["n_head"], cands,
        hbm_budget=budget_bytes, hbm_model=hbm_model)
    out = {
        "gpt_t16k_candidates": len(cands),
        "gpt_t16k_pruned_static": len(pruned),
        "gpt_t16k_survivors": len(survivors),
        "gpt_t16k_static_only": True,
        "gpt_t16k_budget_gib": round(budget_bytes / (1 << 30), 2),
    }
    # the BENCH_r05 configuration must be among the rejected
    r05 = [(c, r) for c, r in pruned
           if c.get("policy") == "offload" and c.get("accum", 1) == 1
           and c["block_q"] == 1024]
    if r05:
        out["gpt_t16k_rejected_r05_config"] = (
            f"offload accum=1 blocks=1024: {r05[0][1]}")
    if survivors:
        survivors.sort(key=lambda c: (
            POLICY_ORDER.index(c.get("policy") or "none"),
            c.get("accum", 1), c.get("roofline", 9.9), -c["block_q"]))
        # a capacity shape needs allocator headroom: a static estimate
        # at 90% of the budget is an OOM coin-flip once XLA fragments —
        # prefer the cheapest-recompute schedule with >= 15% margin
        room = [c for c in survivors
                if c.get("hbm_est_bytes", 0) <= 0.85 * budget_bytes]
        sel = (room or survivors)[0]
        out.update({
            "gpt_t16k_selected_policy": sel.get("policy"),
            "gpt_t16k_selected_accum": sel.get("accum", 1),
            "gpt_t16k_selected_block_q": sel["block_q"],
            "gpt_t16k_selected_block_k": sel["block_k"],
            "gpt_t16k_selected_est_hbm_gib": round(
                sel.get("hbm_est_bytes", 0) / (1 << 30), 2),
        })
    return out
