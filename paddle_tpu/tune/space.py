"""Candidate space and STATIC pruning for the autotune engine.

Two tunable workload kinds:

- ``op="flash"`` — kernel geometry for one attention shape:
  ``block_q`` / ``block_k`` flash tiles, the ``DIAG_W`` causal sub-tile
  width, and the packed-vs-4-D head routing.
- ``op="gpt_step"`` — the whole training-step schedule at one sequence
  length: the flash geometry PLUS the remat/offload policy and the
  gradient-accumulation factor (the two capacity levers that decide
  whether t=16k compiles at all — BENCH_r05).

Pruning order (cheapest test first; docs/autotune.md):

1. geometry validity — divisibility, packed availability, VMEM fit of
   the kernel's per-cell working set;
2. roofline sanity via ``causal_flash_flops()`` — candidates scheduling
   far more MXU work than the best candidate's schedule are rejected
   without ever compiling;
3. HBM — the analytic ``estimate_gpt_step_hbm`` bound when a device
   budget is known (rejects OOM-doomed schedules from arithmetic
   alone), then the REAL compiled figure
   (``Executor.compile_only`` -> ``compiled_memory_stats`` ->
   ``analysis.preflight_hbm``) in the search loop before any candidate
   executes a step.

Only survivors are measured.
"""

from ..ops.pallas_attention import (
    causal_flash_flops, packed_sub_heads, _pick_block)

__all__ = [
    "WorkloadKey", "attention_candidates", "schedule_candidates",
    "serving_candidates", "spec_candidates", "prune_static",
    "estimate_gpt_step_hbm", "POLICY_ORDER",
]

# remat policies from cheapest recompute to most; "none" = no
# memory_optimize marking at all (XLA keeps every activation)
POLICY_ORDER = ("none", "selective", "offload", "compact", "full")

# per-token-per-layer SAVED activation floats, in units of d_model —
# calibrated against the measured t=16k figures (selective bs8 ~23.5 GB
# sat the 16 GiB chip, RESULTS round 3; accum2-no-remat and bs6
# full-remat both fit under 15.75 GiB while offload at accum=1 did NOT
# — bench.py memory_gate + BENCH_r05): none keeps everything XLA can't
# free, selective keeps kernel residuals + MXU outputs (~q/k/v/o/
# att_out/ffn1[4d]/ffn2), offload moves the per-layer block-input
# residuals to pinned host, compact keeps only kernel residuals +
# segment boundaries, full keeps block inputs alone.
_ACT_FLOATS_PER_TOKEN_LAYER = {
    "none": 13.0, "selective": 10.0, "offload": 8.0,
    "compact": 3.0, "full": 1.5,
}

# one layer's LIVE forward/recompute working set (floats per token in
# units of d_model): whatever the saved set, one layer's activations —
# dominated by the two [.., 4d] FFN tensors — exist while it computes
_LIVE_LAYER_FLOATS_PER_TOKEN = 16.0


def _canon_dtype(dtype):
    """Canonical dtype string for the workload key ('bfloat16',
    'float32', ...) from a string, numpy dtype, or Program var dtype."""
    s = getattr(dtype, "name", None) or str(dtype)
    return s.split(".")[-1]


class WorkloadKey:
    """The identity a tuned config is valid for:
    ``(op, seq_len, d_head, n_heads, dtype, platform, remat[, backend])``.
    ``remat`` is the POLICY DIMENSION marker: concrete kernel keys pin
    the policy they were measured under; schedule keys (where the policy
    itself is tuned) use ``"auto"``.  ``backend`` is the kernel-registry
    backend the workload RAN on (docs/kernels.md) — appended as a
    ``|kb=`` token only when known, so pre-registry keys stay stable
    (the tuner treats the backend like the policy: a searchable config
    dimension, with the RESOLVED choice recorded on attribution/corpus
    keys).  ``.s`` is the canonical string the cache files key on."""

    __slots__ = ("op", "seq_len", "d_head", "n_heads", "dtype",
                 "platform", "remat", "backend")

    def __init__(self, op, seq_len, d_head, n_heads, dtype,
                 platform, remat="auto", backend=None):
        self.op = str(op)
        self.seq_len = int(seq_len)
        self.d_head = int(d_head)
        self.n_heads = int(n_heads)
        self.dtype = _canon_dtype(dtype)
        self.platform = str(platform)
        self.remat = str(remat)
        self.backend = None if backend is None else str(backend)

    @property
    def s(self):
        base = (f"op={self.op}|t={self.seq_len}|dh={self.d_head}"
                f"|h={self.n_heads}|dt={self.dtype}|plat={self.platform}"
                f"|remat={self.remat}")
        if self.backend:
            base += f"|kb={self.backend}"
        return base

    def __repr__(self):
        return f"WorkloadKey({self.s})"

    def __eq__(self, other):
        return isinstance(other, WorkloadKey) and self.s == other.s

    def __hash__(self):
        return hash(self.s)


def _block_choices(seq_len, caps=None):
    """Distinct exact block sizes for a sequence length: each cap maps
    through ``_pick_block`` (largest divisor <= cap) so every candidate
    tiles ``t`` exactly, toy shapes included."""
    caps = caps or (256, 512, 1024, 2048)
    return sorted({_pick_block(seq_len, int(c)) for c in caps})


def attention_candidates(seq_len, d_head, n_head, block_caps=None,
                         diag_ws=(128, 256), include_packed=True,
                         backends=None):
    """The flash kernel-geometry candidate list for one shape:
    ``{"block_q", "block_k", "diag_w", "packed"}`` dicts.

    ``backends`` adds the kernel-registry choice as a SEARCHABLE
    dimension (docs/kernels.md): each name in the tuple yields
    candidates carrying ``"backend"``.  Block/diag geometry only means
    anything to the Pallas-schedule backends — ``xla_ref`` (and any
    backend that owns its own tiling) contributes ONE candidate with
    the backend alone, so the cross product never multiplies compiles
    for knobs the backend ignores.  ``None`` (default) keeps the
    pre-registry candidate list: no ``"backend"`` key, resolution left
    to env/auto."""
    packs = [None]
    if include_packed and packed_sub_heads(n_head, d_head) is not None:
        # the packed layout is the measured win (no head transposes) but
        # the 4-D spelling is a legal schedule — let measurement decide
        packs = [True, False]
    geo = []
    for bq in _block_choices(seq_len, block_caps):
        for bk in _block_choices(seq_len, block_caps):
            for w in sorted({_pick_block(min(bq, bk), int(dw))
                             for dw in diag_ws}):
                for p in packs:
                    geo.append({"block_q": bq, "block_k": bk,
                                "diag_w": w, "packed": p})
    if not backends:
        return geo
    out = []
    for b in backends:
        if b == "pallas_tpu":
            out.extend(dict(g, backend=str(b)) for g in geo)
        elif b == "triton":
            # the triton lowering clamps blocks to its MAX_BLOCK=128
            # SRAM tiles and ignores diag_w/packed (it masks every
            # visited block; packed is a reshape) — candidates above
            # the clamp would be measured as DUPLICATE kernels and
            # VMEM-scored for tiles they never allocate, so the
            # geometry cross is generated at the clamped caps and
            # deduped
            caps = tuple(min(int(c), 128)
                         for c in (block_caps or (256, 512, 1024, 2048)))
            seen = set()
            for bq in _block_choices(seq_len, caps):
                for bk in _block_choices(seq_len, caps):
                    if (bq, bk) in seen:
                        continue
                    seen.add((bq, bk))
                    out.append({"block_q": bq, "block_k": bk,
                                "diag_w": None, "packed": None,
                                "backend": "triton"})
        else:
            # geometry-free backend: one candidate, default blocks so
            # downstream consumers (program build) still have values
            out.append({"block_q": _pick_block(seq_len, 1024),
                        "block_k": _pick_block(seq_len, 1024),
                        "diag_w": None, "packed": None,
                        "backend": str(b)})
    return out


def schedule_candidates(seq_len, d_head, n_head, block_caps=None,
                        policies=POLICY_ORDER, accums=(1, 2),
                        diag_ws=(256,), fsdp_opts=(None,),
                        grad_rs_opts=(None,), backends=None):
    """The step-schedule candidate list: kernel geometry x remat policy
    x gradient-accumulation factor (x FSDP gather-vs-replicate when the
    caller is tuning a mesh with an ``fsdp`` axis: ``fsdp_opts=(False,
    True)`` adds the dimension — TVM-style, the schedule decision stays
    inside the measured search instead of hardcoded; ``None`` entries
    leave the key off the candidate, the single-chip default; x the
    kernel-registry ``backends`` when given — the autotuner picks
    KERNELS, not just block shapes, docs/kernels.md).

    ``grad_rs_opts=(False, True)`` adds the true-ZeRO-3 gradient
    spelling (docs/parallel.md rule 4) as a measured dimension on fsdp
    candidates: reduce-scatter at the boundary cuts boundary comm bytes
    by the fsdp degree but GSPMD pays extra in-loop weight gathers for
    the shard-sized carry, so which spelling wins is geometry- and
    interconnect-dependent — measured, not derived.  Crossed only with
    ``fsdp=True`` candidates (without fsdp sharding there is no shard
    to scatter to; the dimension would measure duplicates)."""
    out = []
    for geo in attention_candidates(seq_len, d_head, n_head,
                                    block_caps=block_caps,
                                    diag_ws=diag_ws,
                                    include_packed=False,
                                    backends=backends):
        for pol in policies:
            for acc in accums:
                for fs in fsdp_opts:
                    for rs in (grad_rs_opts if fs else (None,)):
                        c = dict(geo)
                        c["policy"] = pol
                        c["accum"] = int(acc)
                        if fs is not None:
                            c["fsdp"] = bool(fs)
                        if rs is not None:
                            c["grad_rs"] = bool(rs)
                        out.append(c)
    return out


def serving_candidates(max_len, chunks=(2, 4, 8, 16, 32),
                       min_buckets=(4, 8, 16)):
    """The ``op="serving_decode"`` candidate list: the serving engine's
    decode chunk size x smallest prefill bucket —
    ``{"chunk", "min_bucket"}`` dicts (docs/autotune.md "Adding a
    tunable op").  The static prune is pure arithmetic: a chunk larger
    than the slot capacity wastes whole device calls on any request
    (every emission past ``max_len`` is discarded), and a min bucket
    beyond ``max_len`` cannot exist, so neither ever compiles."""
    out = []
    for c in chunks:
        if not 1 <= int(c) <= max_len:
            continue
        for b in min_buckets:
            if 1 <= int(b) <= max_len:
                out.append({"chunk": int(c), "min_bucket": int(b)})
    return out


def paged_attention_candidates(num_table_blocks,
                               backends=("xla_ref", "pallas_tpu",
                                         "triton"),
                               block_steps=(1, 2, 4, 8)):
    """The ``op="paged_attention"`` candidate list: block-iteration
    geometry x registry backend — ``{"backend", "block_step"}`` dicts
    (docs/kernels.md, docs/autotune.md "Adding a tunable op").

    ``block_step`` is how many table entries the ``xla_ref`` block scan
    consumes per step (``[S, block_step*B, h, dh]`` in flight): larger
    steps amortize per-iteration overhead against a bigger live tile —
    measured, not derived.  The ``pallas_tpu`` and ``triton`` lowerings
    fix their own iteration shape (one physical block per sequential
    grid step / per ``fori_loop`` iteration), so like the geometry-free
    backends in :func:`attention_candidates` each contributes ONE
    candidate with ``block_step=None``.  The static prune is pure
    arithmetic: a step beyond the chain length degenerates to the full
    gather this op class exists to kill."""
    out = []
    nb = max(1, int(num_table_blocks))
    for b in backends:
        if b == "xla_ref":
            seen = set()
            for bs in block_steps:
                bs = max(1, min(int(bs), nb))
                if bs in seen:
                    continue
                seen.add(bs)
                out.append({"backend": "xla_ref", "block_step": bs})
        else:
            out.append({"backend": str(b), "block_step": None})
    return out


def spec_candidates(max_len, ks=(1, 2, 3, 4, 6, 8)):
    """The ``op="spec_decode"`` candidate list: the speculative draft
    window ``k`` — ``{"k"}`` dicts (docs/autotune.md "Adding a tunable
    op").  The sweet spot balances draft overhead (k + 1 cheap steps)
    against verify amortization (one target read scores k + 1
    positions) and scales with the workload's acceptance rate, so it
    is measured, not derived.  The static prune is pure arithmetic: a
    window of ``max_len`` or more can never commit fully (a request
    always holds at least one prompt token), so it only wastes draft
    steps."""
    return [{"k": int(k)} for k in ks if 1 <= int(k) < max_len]


def _vmem_bytes(cand, d_head, n_head, dtype_size=2):
    """Per-grid-cell VMEM working set of the flash forward: one q block,
    one k block, one v block (packed width = every head in the feature
    dim; the 4-D path's width is one head), plus the f32 acc/m/l
    scratch."""
    width = (n_head * d_head if cand.get("packed") is not False
             and packed_sub_heads(n_head, d_head) is not None
             else d_head)
    bq, bk = cand["block_q"], cand["block_k"]
    blocks = (bq + 2 * bk) * width * dtype_size
    scratch = bq * width * 4 + 2 * bq * 128 * 4  # acc + m/l lanes
    return blocks + scratch


def estimate_gpt_step_hbm(n_layer, d_model, n_head, vocab, seq_len,
                          batch, policy="selective", accum=1,
                          dtype_size=2):
    """Analytic HBM high-water bound (bytes) for one GPT training step —
    the pre-compile prune.  Components: bf16 weights, f32 embedding
    masters, f32 Adam moments, the f32 gradient buffer, and the policy's
    SAVED activation set for one microbatch (plus one layer's recompute
    working set).  Deliberately coarse — calibrated on the measured
    t=16k round-4/5 figures (see ``_ACT_FLOATS_PER_TOKEN_LAYER``) to get
    the ORDERING right; marginal candidates are settled by the real
    compiled figure in the search loop."""
    policy = policy or "none"
    if policy not in _ACT_FLOATS_PER_TOKEN_LAYER:
        raise ValueError(f"unknown policy {policy!r}")
    p_block = 12 * d_model * d_model * n_layer  # qkv+out + 2x(d<->4d)
    p_head = vocab * d_model
    p_embed = vocab * d_model + seq_len * d_model
    params = (p_block + p_head) * dtype_size + p_embed * 4
    n_elems = p_block + p_head + p_embed
    opt_state = n_elems * 8          # two f32 Adam moments
    grads = n_elems * 4              # f32 accumulated gradient
    mb = max(1, batch // max(1, int(accum)))
    saved = (_ACT_FLOATS_PER_TOKEN_LAYER[policy]
             * d_model * n_layer * mb * seq_len * dtype_size)
    # one layer's live recompute/forward working set (whatever the
    # policy, one layer's full activations exist while it runs)
    live_layer = (_LIVE_LAYER_FLOATS_PER_TOKEN
                  * d_model * mb * seq_len * dtype_size)
    est = int(params + opt_state + grads + saved + live_layer)
    # calibrated HBM scale from the learned cost model (measured vs
    # estimated high water over the corpus, tune/costmodel.py).  The
    # scale is clamped >= 1.0 — the bound is a PRUNE, so calibration
    # may only make it more conservative — and is exactly 1.0 when no
    # fitted model is loadable or PADDLE_TPU_COSTMODEL=0 (bit-exact).
    try:
        from .costmodel import hbm_scale_for

        scale = hbm_scale_for()
    except Exception:  # noqa: BLE001 — mid-bootstrap partial import
        scale = 1.0
    if scale != 1.0:
        est = int(est * scale)
    return est


def prune_static(seq_len, d_head, n_head, candidates, dtype_size=2,
                 vmem_budget=12 << 20, roofline_slack=1.20,
                 hbm_budget=None, hbm_model=None):
    """Static pruning pass: returns ``(survivors, pruned)`` where each
    survivor dict gains ``roofline`` (scheduled/useful flop ratio) and
    each pruned entry is ``(candidate, reason)``.

    - VMEM: the kernel's per-cell working set must fit the scoped VMEM
      budget (a too-big block pair fails Mosaic at compile time — or
      worse, compiles and thrashes).
    - Roofline: ``causal_flash_flops`` simulates the kernel's exact
      block/sub-tile skip logic; a candidate scheduling more than
      ``roofline_slack`` x the best candidate's scheduled flops cannot
      win on the MXU and is rejected unmeasured.
    - HBM (optional): when ``hbm_budget`` and an ``hbm_model(cand)``
      callable are given, candidates whose analytic bound exceeds the
      budget are rejected — the BENCH_r05 class dies here, from
      arithmetic alone, before any compile."""
    scored, pruned, passthrough = [], [], []
    for c in candidates:
        if c.get("backend") not in (None, "pallas_tpu", "triton"):
            # geometry-free backend candidate (xla_ref): the VMEM and
            # block-schedule roofline models describe the Pallas
            # schedules, not XLA's own tiling — only the HBM bound
            # applies; measurement settles the rest
            if hbm_budget and hbm_model is not None:
                est = hbm_model(c)
                if est > hbm_budget:
                    pruned.append(
                        (c, f"hbm estimate {est / (1 << 30):.1f} GiB > "
                            f"budget {hbm_budget / (1 << 30):.1f} GiB"))
                    continue
                c = dict(c, hbm_est_bytes=int(est))
            passthrough.append(c)
            continue
        if seq_len % c["block_q"] or seq_len % c["block_k"]:
            pruned.append((c, "blocks do not tile t"))
            continue
        vm = _vmem_bytes(c, d_head, n_head, dtype_size)
        if vm > vmem_budget:
            pruned.append(
                (c, f"vmem {vm >> 20} MiB > {vmem_budget >> 20} MiB"))
            continue
        sched, useful = causal_flash_flops(
            seq_len, seq_len, d_head, c["block_q"], c["block_k"],
            diag_w=c.get("diag_w"))
        c = dict(c, roofline=round(sched / max(useful, 1), 4))
        scored.append((sched, c))
    if not scored:
        return passthrough, pruned
    best = min(s for s, _ in scored)
    # calibrated roofline: when a fitted cost model is loadable, the
    # slack test compares FITTED schedule costs (ms) instead of raw
    # scheduled flops — prediction is monotonic in flops so candidate
    # ordering is unchanged (the --costmodel-selftest contract); only
    # the ratio moves, because the fitted per-step overhead dilutes
    # small flop deltas.  No model / kill switch -> the flop ratio,
    # exactly as before.
    cm_entry = None
    try:
        from . import costmodel as _cm

        cm_entry = _cm.active_entry()
    except Exception:  # noqa: BLE001 — mid-bootstrap partial import
        cm_entry = None
    if cm_entry is not None:
        cost_of = lambda s: _cm.predict_sched_ms(cm_entry, s)  # noqa: E731
    else:
        cost_of = float
    best_cost = cost_of(best)
    survivors = list(passthrough)
    for sched, c in scored:
        if cost_of(sched) > best_cost * roofline_slack:
            what = ("calibrated roofline" if cm_entry is not None
                    else "roofline")
            pruned.append(
                (c, f"{what}: schedules {sched / best:.2f}x the best "
                    f"candidate's flops"))
            continue
        if hbm_budget and hbm_model is not None:
            est = hbm_model(c)
            if est > hbm_budget:
                pruned.append(
                    (c, f"hbm estimate {est / (1 << 30):.1f} GiB > "
                        f"budget {hbm_budget / (1 << 30):.1f} GiB"))
                continue
            c = dict(c, hbm_est_bytes=int(est))
        survivors.append(c)
    return survivors, pruned
