"""Process flags (reference: gflags — legacy set in utils/Flags.h:19-44,
fluid's own in executor.cc:27-30 ``do_memory_benchmark``/``check_nan_inf``
and operator.cc ``op_sync``; Python argv forwarded via init_gflags,
pybind.cc:430).

TPU-native: a tiny typed flag registry, initialized from environment
variables (``PADDLE_TPU_<FLAG>``) and/or ``init_flags(argv)``.  Consumed by
the Executor (check_nan_inf, do_memory_benchmark) and available to user
code."""

import os

__all__ = ["FLAGS", "define_flag", "init_flags"]

_DEFS = {}


class _Flags:
    def __getattr__(self, name):
        if name in _DEFS:
            return _DEFS[name]["value"]
        raise AttributeError(f"unknown flag {name!r}")

    def __setattr__(self, name, value):
        if name not in _DEFS:
            raise AttributeError(f"unknown flag {name!r}")
        _DEFS[name]["value"] = _DEFS[name]["type"](value)


FLAGS = _Flags()


def _parse_bool(v):
    if isinstance(v, bool):
        return v
    return str(v).lower() in ("1", "true", "yes", "on")


def define_flag(name, default, help="", type=None):
    if type is None:
        type = _parse_bool if isinstance(default, bool) else default.__class__
    value = default
    env = os.environ.get(f"PADDLE_TPU_{name.upper()}")
    if env is not None:
        value = type(env)
    _DEFS[name] = {"value": value, "type": type, "help": help,
                   "default": default}


def init_flags(argv):
    """Parse ``--flag=value`` / ``--flag value`` tokens (init_gflags
    analog); returns unrecognized tokens."""
    rest, i = [], 0
    while i < len(argv):
        tok = argv[i]
        if tok.startswith("--"):
            body = tok[2:]
            if "=" in body:
                k, v = body.split("=", 1)
            elif (i + 1 < len(argv) and body in _DEFS
                  and _DEFS[body]["type"] is not _parse_bool):
                # gflags semantics: only non-bool flags take the next
                # token as a value; a bare bool flag means "true"
                k, v = body, argv[i + 1]
                i += 1
            else:
                k, v = body, "true"
            if k in _DEFS:
                setattr(FLAGS, k, v)
            else:
                rest.append(tok)
        else:
            rest.append(tok)
        i += 1
    return rest


# -- the reference flag set, TPU-relevant subset ----------------------------
define_flag("check_nan_inf", False,
            "scan step outputs/state for NaN/Inf after every run "
            "(executor.cc:28 FLAGS_check_nan_inf analog)")
define_flag("do_memory_benchmark", False,
            "log live-state bytes per step (executor.cc:27)")
define_flag("log_period", 0, "print a stats line every N batches (legacy "
            "--log_period)")
define_flag("seed", 0, "global random seed default (legacy --seed)")
define_flag("use_pallas", True, "use Pallas kernels for fused hot ops")
define_flag("profile", False, "enable the op timer registry (WITH_TIMER)")
