"""Inference engine (reference: paddle/inference/inference.{h,cc} — load
__model__ + persistables, then Executor::Run; v2 inference.py infer()).

This is the one-shot Program-forward path (load an exported model dir,
feed, fetch).  For multi-tenant autoregressive LLM serving — many
concurrent variable-length decode requests over the flagship
transformer — use ``paddle_tpu.serving.ServingEngine`` (continuous
batching over the batched KV cache; ``docs/serving.md``), which
multiplexes requests into one compiled decode step instead of running
one Program per caller."""

import time

import numpy as np

from .core.executor import Executor
from .core.scope import Scope, scope_guard
from .observability import metrics as _obs
from . import io as _io
from .data_feeder import DataFeeder


class InferenceEngine:
    """Load an exported model dir and run predictions."""

    def __init__(self, dirname, place=None):
        self.exe = Executor(place)
        self.scope = Scope()
        with scope_guard(self.scope):
            (
                self.program,
                self.feed_names,
                self.fetch_vars,
            ) = _io.load_inference_model(dirname, self.exe)
        block = self.program.global_block()
        self.feed_vars = [block.var(n) for n in self.feed_names]
        self.feeder = DataFeeder(self.feed_vars, place)

    def run(self, feed=None, data=None):
        """feed: {name: ndarray} or data: list of sample tuples.

        Each call observes ``inference.run_seconds`` (a latency histogram
        — p50/p95/p99 via its snapshot) and counts
        ``inference.requests`` in the global metrics registry."""
        reg = _obs.get_registry()
        reg.counter("inference.requests").inc()
        t0 = time.perf_counter()
        try:
            if data is not None:
                feed = self.feeder.feed(data)
            with scope_guard(self.scope):
                return self.exe.run(
                    self.program, feed=feed, fetch_list=self.fetch_vars
                )
        finally:
            reg.histogram("inference.run_seconds").observe(
                time.perf_counter() - t0)


def infer(dirname, data=None, feed=None, place=None):
    return InferenceEngine(dirname, place).run(feed=feed, data=data)
