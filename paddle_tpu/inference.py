"""Inference engine (reference: paddle/inference/inference.{h,cc} — load
__model__ + persistables, then Executor::Run; v2 inference.py infer())."""

import numpy as np

from .core.executor import Executor
from .core.scope import Scope, scope_guard
from . import io as _io
from .data_feeder import DataFeeder


class InferenceEngine:
    """Load an exported model dir and run predictions."""

    def __init__(self, dirname, place=None):
        self.exe = Executor(place)
        self.scope = Scope()
        with scope_guard(self.scope):
            (
                self.program,
                self.feed_names,
                self.fetch_vars,
            ) = _io.load_inference_model(dirname, self.exe)
        block = self.program.global_block()
        self.feed_vars = [block.var(n) for n in self.feed_names]
        self.feeder = DataFeeder(self.feed_vars, place)

    def run(self, feed=None, data=None):
        """feed: {name: ndarray} or data: list of sample tuples."""
        if data is not None:
            feed = self.feeder.feed(data)
        with scope_guard(self.scope):
            return self.exe.run(
                self.program, feed=feed, fetch_list=self.fetch_vars
            )


def infer(dirname, data=None, feed=None, place=None):
    return InferenceEngine(dirname, place).run(feed=feed, data=data)
