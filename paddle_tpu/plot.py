"""Training-curve plotter (reference: ``python/paddle/v2/plot/plot.py``
Ploter — collects per-title (step, value) series and renders them; falls
back to appending CSV lines when matplotlib/display is unavailable, same as
the reference's non-notebook path)."""

__all__ = ["Ploter"]


class PlotData:
    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(float(value))

    def reset(self):
        self.step = []
        self.value = []


class Ploter:
    def __init__(self, *titles):
        self.titles = list(titles)
        self.data = {t: PlotData() for t in titles}
        try:  # headless environments: record-only mode
            import matplotlib

            matplotlib.use("Agg")
            import matplotlib.pyplot as plt

            self._plt = plt
        except Exception:
            self._plt = None

    def append(self, title, step, value):
        self.data[title].append(step, value)

    def plot(self, path=None):
        """Render all series; writes a PNG when ``path`` is given (or when
        matplotlib exists), else writes ``<path>.csv``."""
        if self._plt is not None:
            fig, ax = self._plt.subplots()
            for t in self.titles:
                d = self.data[t]
                ax.plot(d.step, d.value, label=t)
            ax.legend()
            ax.set_xlabel("step")
            if path:
                fig.savefig(path)
            self._plt.close(fig)
            return path
        if path:
            csv = path if path.endswith(".csv") else path + ".csv"
            with open(csv, "w") as f:
                for t in self.titles:
                    d = self.data[t]
                    for s, v in zip(d.step, d.value):
                        f.write(f"{t},{s},{v}\n")
            return csv
        return None

    def reset(self):
        for d in self.data.values():
            d.reset()
