"""ResNet for ImageNet (reference: benchmark/paddle/image/resnet.py —
ResNet-50/101/152 bottleneck configs; BASELINE config 2 and the bench.py
flagship).  NCHW; compute dtype bfloat16 by default (MXU-native) with
float32 BN statistics and loss."""

from .. import layers, optimizer as opt


def conv_bn_layer(input, num_filters, filter_size, stride=1, padding=None,
                  act="relu", is_test=False):
    padding = (filter_size - 1) // 2 if padding is None else padding
    conv = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=padding, bias_attr=False,
    )
    return layers.batch_norm(input=conv, act=act, is_test=is_test)


def shortcut(input, ch_out, stride, is_test=False):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, 0, act=None, is_test=is_test)
    return input


def bottleneck_block(input, num_filters, stride, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 1, 1, 0, is_test=is_test)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride, 1, is_test=is_test)
    conv2 = conv_bn_layer(conv1, num_filters * 4, 1, 1, 0, act=None, is_test=is_test)
    short = shortcut(input, num_filters * 4, stride, is_test=is_test)
    summed = layers.elementwise_add(short, conv2)
    return layers.relu(summed)


_DEPTH = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}


def basicblock(input, num_filters, stride, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 3, stride, 1, is_test=is_test)
    conv1 = conv_bn_layer(conv0, num_filters, 3, 1, 1, act=None,
                          is_test=is_test)
    short = shortcut(input, num_filters, stride, is_test=is_test)
    return layers.relu(layers.elementwise_add(short, conv1))


def resnet_cifar10(input, class_dim=10, depth=32, is_test=False):
    """Small basic-block ResNet (reference fluid book
    test_image_classification.py resnet_cifar10; depth = 6n+2)."""
    assert (depth - 2) % 6 == 0, "cifar resnet depth must be 6n+2"
    n = (depth - 2) // 6
    conv = conv_bn_layer(input, 16, 3, 1, 1, is_test=is_test)
    for stage_idx, num_filters in enumerate((16, 32, 64)):
        for i in range(n):
            stride = 2 if i == 0 and stage_idx > 0 else 1
            conv = basicblock(conv, num_filters, stride, is_test=is_test)
    pool = layers.pool2d(conv, pool_type="avg", global_pooling=True)
    return layers.fc(input=pool, size=class_dim, act="softmax")


def resnet_imagenet(input, class_dim=1000, depth=50, is_test=False):
    stages = _DEPTH[depth]
    conv = conv_bn_layer(input, 64, 7, 2, 3, is_test=is_test)
    pool = layers.pool2d(conv, pool_size=3, pool_stride=2, pool_padding=1,
                         pool_type="max")
    for stage_idx, count in enumerate(stages):
        num_filters = 64 * (2 ** stage_idx)
        for i in range(count):
            stride = 2 if i == 0 and stage_idx > 0 else 1
            pool = bottleneck_block(pool, num_filters, stride, is_test=is_test)
    pool = layers.pool2d(pool, pool_type="avg", global_pooling=True)
    return layers.fc(input=pool, size=class_dim, act="softmax")


def build(depth=50, class_dim=1000, image_shape=(3, 224, 224),
          learning_rate=0.1, momentum=0.9, dtype="bfloat16", is_test=False):
    img = layers.data("img", shape=list(image_shape), dtype=dtype)
    label = layers.data("label", shape=[1], dtype="int64")
    if depth in _DEPTH:
        prediction = resnet_imagenet(img, class_dim, depth, is_test=is_test)
    else:
        prediction = resnet_cifar10(img, class_dim, depth, is_test=is_test)
    pred32 = layers.cast(prediction, "float32")
    cost = layers.cross_entropy(input=pred32, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=pred32, label=label)
    if not is_test:
        optimizer = opt.Momentum(learning_rate=learning_rate, momentum=momentum)
        optimizer.minimize(avg_cost)
    return {
        "feed": [img, label],
        "prediction": prediction,
        "avg_cost": avg_cost,
        "accuracy": acc,
    }
