"""Decoder-only transformer language model — the long-context flagship.

The reference predates transformers (its attention is composed fc+softmax,
``trainer_config_helpers/networks.py simple_attention``); this model is the
framework's NEW long-context capability built the TPU way: fused flash
attention (``ops/pallas_attention.py``), pre-LN residual blocks, bf16
matmuls on the MXU, remat via ``memory_optimize``, and mesh-ready — batch
axis shards over ``dp`` (``parallel.data_parallel``), QKV/FFN weights
column/row-shard over ``tp`` (``parallel.shard_parameters_by_rule``), the
sequence axis over ``sp`` (``parallel.ring_attention``), experts over
``ep`` (``parallel.moe``).
"""

from .. import layers, optimizer as opt
from ..layers import tensor as ltensor


def transformer_block(x, d_model, n_head, d_ff, dropout_rate, is_test,
                      name):
    """Pre-LN block: x + MHA(LN(x)) then x + FFN(LN(x))."""
    ln1 = layers.layer_norm(x, begin_norm_axis=2, name=name + "_ln1")
    att = layers.multi_head_attention(
        ln1, ln1, ln1, d_model=d_model, n_head=n_head,
        dropout_rate=dropout_rate, causal=True, is_test=is_test,
        name=name + "_att")
    x = x + att
    ln2 = layers.layer_norm(x, begin_norm_axis=2, name=name + "_ln2")
    ff = layers.fc(ln2, d_ff, num_flatten_dims=2, act="gelu",
                   name=name + "_ffn1")
    ff = layers.fc(ff, d_model, num_flatten_dims=2, name=name + "_ffn2")
    if dropout_rate:
        ff = layers.dropout(ff, dropout_rate, is_test=is_test)
    return x + ff


def gpt(tokens, vocab_size, n_layer=4, n_head=8, d_model=256, d_ff=None,
        max_len=128, dropout_rate=0.1, is_test=False, dtype="bfloat16"):
    """Causal LM trunk: returns [batch, time, vocab] logits (float32)."""
    d_ff = d_ff or 4 * d_model
    b, t = tokens.shape[0], tokens.shape[1]
    emb = layers.embedding(tokens, size=[vocab_size, d_model],
                           param_attr="tok_emb.w")
    pos = ltensor.create_parameter([t, d_model], dtype="float32",
                                   name="pos_emb.w")
    x = emb + pos
    x = ltensor.cast(x, dtype)
    if dropout_rate:
        x = layers.dropout(x, dropout_rate, is_test=is_test)
    for i in range(n_layer):
        x = transformer_block(x, d_model, n_head, d_ff, dropout_rate,
                              is_test, name=f"block{i}")
    x = layers.layer_norm(x, begin_norm_axis=2, name="ln_f")
    logits = layers.fc(x, vocab_size, num_flatten_dims=2, bias_attr=False,
                       name="lm_head")
    return ltensor.cast(logits, "float32")


def build(vocab_size=1000, n_layer=4, n_head=8, d_model=256, d_ff=None,
          max_len=128, dropout_rate=0.1, is_test=False,
          learning_rate=1e-3, dtype="bfloat16"):
    """Next-token-prediction training program.

    Feeds: tokens [batch, max_len] int64, labels [batch, max_len] int64
    (tokens shifted left by one, label -1 = padding, masked out of the
    loss)."""
    tokens = layers.data("tokens", shape=[max_len], dtype="int64")
    labels = layers.data("labels", shape=[max_len], dtype="int64")
    logits = gpt(tokens, vocab_size, n_layer=n_layer, n_head=n_head,
                 d_model=d_model, d_ff=d_ff, max_len=max_len,
                 dropout_rate=dropout_rate, is_test=is_test, dtype=dtype)
    flat_logits = ltensor.reshape(logits, [-1, vocab_size])
    flat_labels = ltensor.reshape(labels, [-1, 1])
    mask = ltensor.cast(
        layers.greater_equal(flat_labels, ltensor.fill_constant(
            shape=[1], dtype="int64", value=0)), "float32")
    safe_labels = layers.elementwise_max(
        flat_labels, ltensor.fill_constant(shape=[1], dtype="int64",
                                           value=0))
    loss = layers.softmax_with_cross_entropy(flat_logits, safe_labels)
    masked = loss * mask
    avg_cost = layers.reduce_sum(masked) / (
        layers.reduce_sum(mask) + 1e-8)
    optimizer = opt.Adam(learning_rate=learning_rate)
    optimizer.minimize(avg_cost)
    return {"feed": [tokens, labels], "logits": logits,
            "avg_cost": avg_cost}
