"""Decoder-only transformer language model — the long-context flagship.

The reference predates transformers (its attention is composed fc+softmax,
``trainer_config_helpers/networks.py simple_attention``); this model is the
framework's NEW long-context capability built the TPU way: fused flash
attention (``ops/pallas_attention.py``), pre-LN residual blocks, bf16
matmuls on the MXU, remat via ``memory_optimize``, and mesh-ready — batch
axis shards over ``dp`` (``parallel.data_parallel``), QKV/FFN weights
column/row-shard over ``tp`` (``parallel.shard_parameters_by_rule``), the
sequence axis over ``sp`` (``parallel.ring_attention``), experts over
``ep`` (``parallel.moe``).
"""

from .. import layers, optimizer as opt
from ..layers import tensor as ltensor


def transformer_block(x, d_model, n_head, d_ff, dropout_rate, is_test,
                      name, attn_block_q=None, attn_block_k=None,
                      attn_packed=None):
    """Pre-LN block: x + MHA(LN(x)) then x + FFN(LN(x))."""
    ln1 = layers.layer_norm(x, begin_norm_axis=2, name=name + "_ln1")
    att = layers.multi_head_attention(
        ln1, ln1, ln1, d_model=d_model, n_head=n_head,
        dropout_rate=dropout_rate, causal=True, is_test=is_test,
        block_q=attn_block_q, block_k=attn_block_k, packed=attn_packed,
        name=name + "_att")
    x = x + att
    ln2 = layers.layer_norm(x, begin_norm_axis=2, name=name + "_ln2")
    ff = layers.fc(ln2, d_ff, num_flatten_dims=2, act="gelu",
                   name=name + "_ffn1")
    ff = layers.fc(ff, d_model, num_flatten_dims=2, name=name + "_ffn2")
    if dropout_rate:
        ff = layers.dropout(ff, dropout_rate, is_test=is_test)
    return x + ff


def gpt_trunk(tokens, vocab_size, n_layer=4, n_head=8, d_model=256,
              d_ff=None, max_len=128, dropout_rate=0.1, is_test=False,
              dtype="bfloat16", attn_block_q=None, attn_block_k=None,
              attn_packed=None):
    """Causal LM trunk up to the final layer norm: [batch, time, d_model]
    hidden states in ``dtype`` (the head is attached by the caller).
    ``attn_block_q``/``attn_block_k`` tune the flash-attention kernel tile
    sizes (smaller q tiles shrink the triangular diagonal band — see
    ops/pallas_attention.py causal_flash_flops)."""
    d_ff = d_ff or 4 * d_model
    b, t = tokens.shape[0], tokens.shape[1]
    emb = layers.embedding(tokens, size=[vocab_size, d_model],
                           param_attr="tok_emb.w")
    pos = ltensor.create_parameter([t, d_model], dtype="float32",
                                   name="pos_emb.w")
    x = emb + pos
    x = ltensor.cast(x, dtype)
    if dropout_rate:
        x = layers.dropout(x, dropout_rate, is_test=is_test)
    for i in range(n_layer):
        x = transformer_block(x, d_model, n_head, d_ff, dropout_rate,
                              is_test, name=f"block{i}",
                              attn_block_q=attn_block_q,
                              attn_block_k=attn_block_k,
                              attn_packed=attn_packed)
    return layers.layer_norm(x, begin_norm_axis=2, name="ln_f")


def gpt(tokens, vocab_size, n_layer=4, n_head=8, d_model=256, d_ff=None,
        max_len=128, dropout_rate=0.1, is_test=False, dtype="bfloat16",
        attn_block_q=None, attn_block_k=None, attn_packed=None):
    """Causal LM trunk: returns [batch, time, vocab] logits (float32)."""
    x = gpt_trunk(tokens, vocab_size, n_layer=n_layer, n_head=n_head,
                  d_model=d_model, d_ff=d_ff, max_len=max_len,
                  dropout_rate=dropout_rate, is_test=is_test, dtype=dtype,
                  attn_block_q=attn_block_q, attn_block_k=attn_block_k,
                  attn_packed=attn_packed)
    logits = layers.fc(x, vocab_size, num_flatten_dims=2, bias_attr=False,
                       name="lm_head")
    return ltensor.cast(logits, "float32")


def tp_rules():
    """Tensor-parallel sharding rules for the flagship transformer
    (apply with ``parallel.shard_parameters_by_rule`` on a mesh with a
    'tp' axis; requires n_head % tp == 0 and vocab % tp == 0):

    - QKV projections column-shard (= whole heads per shard: the packed
      feature dim is the head dim), so the flash kernel runs via
      shard_map over local heads with no cross-shard traffic
      (``flash_attention_packed`` op's tp path);
    - the attention out-projection and FFN2 row-shard (XLA inserts the
      one all-reduce per block pair);
    - FFN1 column-shards;
    - the LM head vocab-shards — the fused CE head merges shard
      softmaxes by logsumexp (``fused_softmax_ce_head`` op's tp path),
      so the [tokens, vocab] logits stay sharded AND off-HBM;
    - everything else (LN, embeddings, remaining biases) replicates.

    The reference's model parallelism is per-layer device placement
    (``ParallelNeuralNetwork.cpp:45``); this is the same capability as
    sharding annotations + compiler collectives instead of threads."""
    from jax.sharding import PartitionSpec as P

    return [
        (r"_att_(q|k|v)\.w$", P(None, "tp")),
        (r"_att_(q|k|v)\.b$", P("tp")),
        (r"_att_out\.w$", P("tp", None)),
        (r"_ffn1\.w$", P(None, "tp")),
        (r"_ffn1\.b$", P("tp")),
        (r"_ffn2\.w$", P("tp", None)),
        (r"^lm_head\.w$", P(None, "tp")),
    ]


def extract_params(scope=None, program=None):
    """Pull the model weights (not optimizer state) out of a scope as the
    name->array dict `generate` consumes."""
    import numpy as np

    from ..core.program import default_main_program
    from ..core.scope import global_scope

    scope = scope or global_scope()
    program = program or default_main_program()
    # weights are Parameter instances; optimizer accumulators are plain
    # persistable vars — all_parameters() is exactly the model weights.
    return {
        p.name: np.asarray(scope.get(p.name))
        for p in program.all_parameters()
        if scope.find_var(p.name) is not None
    }


def infer_compute_dtype(params):
    """The serving dtype the weights imply: the narrowest floating dtype
    among the transformer-block / lm_head MATMUL weights (``block*...w`` /
    ``lm_head.w``).  The embedding tables are deliberately f32 in training
    (master-precision rows, cast after gather), so they must not promote
    the decode; conversely a stray low-precision adapter matrix somewhere
    else in the dict (an fp8/f16 LoRA bolted on later) must not silently
    downgrade the whole decode and its KV caches — hence the scan is
    restricted to the block/head weights that actually feed the MXU.
    Falls back to any >=2-D floating weight when no block/head names
    match (renamed or weight-tied heads), then float32."""
    import numpy as np

    import jax.numpy as jnp

    def _mats(keys):
        # metadata-only inspection: never jnp.asarray the weights here
        # (that would device-transfer every array just to read dtypes)
        out = []
        for k in keys:
            v = params[k]
            if not (hasattr(v, "dtype") and hasattr(v, "shape")):
                v = np.asarray(v)
            if len(v.shape) >= 2 and jnp.issubdtype(v.dtype, jnp.floating):
                out.append(jnp.dtype(v.dtype))
        return out

    mats = _mats([k for k in params
                  if (k.startswith("block") or k.startswith("lm_head"))
                  and k.endswith(".w")])
    if not mats:
        mats = _mats(list(params))
    return (min(mats, key=lambda d: jnp.dtype(d).itemsize)
            if mats else jnp.float32)


def generate(params, prompt, max_len, n_layer, n_head, d_model,
             temperature=0.0, key=None, eps=1e-5, compute_dtype=None,
             return_logits=True):
    """Jitted autoregressive decoding with a KV cache (pure-JAX serving
    path over the trained Program parameters — train with the Program,
    serve with `jax.jit(generate)`-style incremental decode; the analog
    of the reference's RecurrentGradientMachine.generateSequence,
    `RecurrentGradientMachine.h:307`, re-designed around lax.scan).

    params   name->array mapping with the Program's parameter names
             (e.g. ``scope.to_dict()`` or ``io.load_persistables``);
             works with float32 or bfloat16 weights.
    prompt   [batch, p_len] int32/int64 prompt tokens (p_len >= 1).
    max_len  total sequence length to produce (>= p_len).
    temperature  0.0 = greedy argmax; otherwise softmax sampling
             (``key`` required).

    compute_dtype  matmul/cache dtype.  Default: the params' own dtype —
             bf16-trained weights decode in bf16 (the serving win:
             decode is HBM-bandwidth-bound on weight reads, and bf16
             halves them).  LayerNorm statistics, softmax and the
             emitted logits stay float32 regardless.
    return_logits  False skips stacking the per-step [batch, vocab]
             logits (for max_len=512/vocab=32k that is ~1 GB of scan
             output) — the serving path that only needs tokens.

    Returns ``(tokens, logits)``: tokens [batch, max_len] int32 (prompt
    prefix included verbatim), logits [batch, max_len, vocab] float32
    (position t's next-token distribution; ``None`` when
    ``return_logits=False``).
    """
    import jax
    import jax.numpy as jnp

    if temperature and key is None:
        raise ValueError("temperature > 0 sampling requires a PRNG `key`")
    if compute_dtype is None:
        # the block/lm_head matmul weights decide the serving dtype
        # (see infer_compute_dtype: f32 embedding tables must not promote
        # the decode, stray low-precision adapters must not downgrade it)
        compute_dtype = infer_compute_dtype(params)
    p = {k: jnp.asarray(v, compute_dtype) for k, v in params.items()}
    b, p_len = prompt.shape
    dh = d_model // n_head
    prompt = jnp.asarray(prompt, jnp.int32)
    table_len = p["pos_emb.w.w"].shape[0]
    if max_len > table_len:
        # XLA clamps out-of-range gathers, which would silently reuse the
        # last position embedding past the trained length — fail instead.
        raise ValueError(
            f"max_len {max_len} exceeds the trained position-embedding "
            f"table ({table_len} positions)")
    pos_emb = p["pos_emb.w.w"][:max_len]

    def ln(x, scale, bias):
        # statistics in f32 even under bf16 compute (mean/var cancellation)
        x32 = x.astype(jnp.float32)
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        xn = ((x32 - mu) / jnp.sqrt(var + eps)).astype(x.dtype)
        return xn * scale + bias

    # Layers stay UNROLLED in the step body: each layer's [b, T, h, dh]
    # cache is a separate while-loop carry that XLA updates in place.
    # (A lax.scan over stacked layers was tried and profiled 2.5x slower:
    # the stacked [L, b, T, h, dh] carry forced two full-cache copies
    # per token plus per-layer slice/update churn — 60% of decode time.
    # HLO size is not a reason to scan: pass params as jit ARGUMENTS,
    # closing over them bakes the weights into the HLO as constants.)
    def step_logits(tok, t, cache_k, cache_v):
        """One token [b] at position t -> (logits [b, vocab], caches').
        cache_k/cache_v: tuples of n_layer [b, T, h, dh] arrays."""
        x = p["tok_emb.w"][tok] + pos_emb[t]          # [b, d]
        ck_out, cv_out = [], []
        for i in range(n_layer):
            w = lambda nm: p[f"block{i}_{nm}"]
            h = ln(x, w("ln1.scale"), w("ln1.bias"))
            q = h @ w("att_q.w") + w("att_q.b")
            k = h @ w("att_k.w") + w("att_k.b")
            v = h @ w("att_v.w") + w("att_v.b")
            qh = q.reshape(b, n_head, dh)
            kh = k.reshape(b, n_head, dh)
            vh = v.reshape(b, n_head, dh)
            ck = jax.lax.dynamic_update_index_in_dim(
                cache_k[i], kh, t, axis=1)
            cv = jax.lax.dynamic_update_index_in_dim(
                cache_v[i], vh, t, axis=1)
            ck_out.append(ck)
            cv_out.append(cv)
            s = jnp.einsum("bhd,bThd->bhT", qh, ck,
                           preferred_element_type=jnp.float32)
            s = s / jnp.sqrt(float(dh))
            mask = jnp.arange(max_len)[None, None, :] <= t
            s = jnp.where(mask, s, -1e30)
            a = jax.nn.softmax(s, axis=-1).astype(ck.dtype)
            ctx = jnp.einsum("bhT,bThd->bhd", a, cv).reshape(b, d_model)
            x = x + ctx @ w("att_out.w") + w("att_out.b")
            h2 = ln(x, w("ln2.scale"), w("ln2.bias"))
            # approximate=False matches the training program's gelu op
            # (exact erf form — see ops/activation_ops.py)
            ff = jax.nn.gelu(h2 @ w("ffn1.w") + w("ffn1.b"),
                             approximate=False)
            x = x + ff @ w("ffn2.w") + w("ffn2.b")
        x = ln(x, p["ln_f.scale"], p["ln_f.bias"])
        logits = jnp.matmul(x, p["lm_head.w"],
                            preferred_element_type=jnp.float32)
        return logits, tuple(ck_out), tuple(cv_out)

    cache_k = tuple(jnp.zeros((b, max_len, n_head, dh), compute_dtype)
                    for _ in range(n_layer))
    cache_v = tuple(jnp.zeros((b, max_len, n_head, dh), compute_dtype)
                    for _ in range(n_layer))

    def scan_body(carry, t):
        tokens, cache_k, cache_v, key = carry
        tok = tokens[:, t]
        logits, cache_k, cache_v = step_logits(tok, t, cache_k, cache_v)
        if temperature and key is not None:
            key, sub = jax.random.split(key)
            nxt = jax.random.categorical(sub, logits / temperature, axis=-1)
        else:
            nxt = jnp.argmax(logits, axis=-1)
        # positions < p_len keep the prompt; after that, append samples;
        # the final step (t+1 == max_len) writes nothing (identity write
        # at the clamped index keeps the last token intact).
        write_to = jnp.minimum(t + 1, max_len - 1)
        cur = tokens[:, write_to]
        writable = ((t + 1) >= p_len) & ((t + 1) < max_len)
        new = jnp.where(writable, nxt.astype(jnp.int32), cur)
        tokens = jax.lax.dynamic_update_index_in_dim(
            tokens, new, write_to, axis=1)
        return (tokens, cache_k, cache_v, key), (
            logits if return_logits else None)

    tokens0 = jnp.zeros((b, max_len), jnp.int32)
    tokens0 = jax.lax.dynamic_update_slice(tokens0, prompt, (0, 0))
    if key is None:
        key = jax.random.PRNGKey(0)
    (tokens, _, _, _), logits = jax.lax.scan(
        scan_body, (tokens0, cache_k, cache_v, key), jnp.arange(max_len))
    if not return_logits:
        return tokens, None
    return tokens, jnp.swapaxes(logits, 0, 1)  # [b, T] , [b, T, vocab]


def build(vocab_size=1000, n_layer=4, n_head=8, d_model=256, d_ff=None,
          max_len=128, dropout_rate=0.1, is_test=False,
          learning_rate=1e-3, dtype="bfloat16", fused_head=False,
          attn_block_q=None, attn_block_k=None, attn_packed=None):
    """Next-token-prediction training program.

    Feeds: tokens [batch, max_len] int64, labels [batch, max_len] int64
    (tokens shifted left by one, label -1 = padding, masked out of the
    loss).

    ``fused_head=True`` replaces the fc + softmax_with_cross_entropy head
    with the Pallas fused head (``layers.fused_softmax_ce_head``): no
    ``[b, t, vocab]`` logits ever hit HBM, which is the difference between
    an HBM-bound and an MXU-bound loss at 32k-vocab flagship shapes.  The
    head weight keeps the name/shape ``lm_head.w [d_model, vocab]`` either
    way, so ``generate`` serves both.  With the fused head ``logits`` is
    None (not materializing them is the point)."""
    tokens = layers.data("tokens", shape=[max_len], dtype="int64")
    labels = layers.data("labels", shape=[max_len], dtype="int64")
    mask2d = ltensor.cast(
        layers.greater_equal(labels, ltensor.fill_constant(
            shape=[1], dtype="int64", value=0)), "float32")
    safe2d = layers.elementwise_max(
        labels, ltensor.fill_constant(shape=[1], dtype="int64", value=0))
    logits = None
    if fused_head:
        x = gpt_trunk(tokens, vocab_size, n_layer=n_layer, n_head=n_head,
                      d_model=d_model, d_ff=d_ff, max_len=max_len,
                      dropout_rate=dropout_rate, is_test=is_test,
                      dtype=dtype, attn_block_q=attn_block_q,
                      attn_block_k=attn_block_k,
                      attn_packed=attn_packed)
        loss = layers.fused_softmax_ce_head(x, safe2d, vocab_size,
                                            name="lm_head")
        masked = ltensor.reshape(loss, [-1, 1]) * ltensor.reshape(
            mask2d, [-1, 1])
    else:
        logits = gpt(tokens, vocab_size, n_layer=n_layer, n_head=n_head,
                     d_model=d_model, d_ff=d_ff, max_len=max_len,
                     dropout_rate=dropout_rate, is_test=is_test,
                     dtype=dtype, attn_block_q=attn_block_q,
                     attn_block_k=attn_block_k, attn_packed=attn_packed)
        flat_logits = ltensor.reshape(logits, [-1, vocab_size])
        flat_labels = ltensor.reshape(safe2d, [-1, 1])
        loss = layers.softmax_with_cross_entropy(flat_logits, flat_labels)
        masked = loss * ltensor.reshape(mask2d, [-1, 1])
    mask = ltensor.reshape(mask2d, [-1, 1])
    avg_cost = layers.reduce_sum(masked) / (
        layers.reduce_sum(mask) + 1e-8)
    optimizer = opt.Adam(learning_rate=learning_rate)
    optimizer.minimize(avg_cost)
    return {"feed": [tokens, labels], "logits": logits,
            "avg_cost": avg_cost}
