"""Seq2seq + attention NMT (reference: fluid book
test_machine_translation.py and v2 book 08.machine_translation with
simple_attention — BASELINE config 3).

Training: encoder GRU over the source, attention decoder scanned over the
target with StaticRNN (lax.scan under the hood).  Decoding: fixed-width
masked beam search (build_decode) using the while-op + beam_search ops.
"""

from .. import layers, nets, optimizer as opt
from ..layers.control_flow import StaticRNN


def encoder(src_word_id, dict_size, word_dim=256, hidden_dim=512):
    emb = layers.embedding(input=src_word_id, size=[dict_size, word_dim])
    fc1 = layers.fc(input=emb, size=hidden_dim * 3, num_flatten_dims=2,
                    bias_attr=False)
    layers.link_sequence(fc1, emb)
    enc = layers.dynamic_gru(input=fc1, size=hidden_dim)
    return enc


def train_decoder(enc_seq, trg_embedding, hidden_dim=512, target_dict_size=30000):
    enc_proj = layers.fc(input=enc_seq, size=hidden_dim, num_flatten_dims=2,
                         bias_attr=False)
    layers.link_sequence(enc_proj, enc_seq)
    init_state = layers.sequence_last_step(enc_seq)

    rnn = StaticRNN()
    with rnn.step():
        cur_word = rnn.step_input(trg_embedding)
        state = rnn.memory(init=init_state)
        context = nets.simple_attention(enc_seq, enc_proj, state, hidden_dim)
        decoder_inputs = layers.fc(
            input=[cur_word, context], size=hidden_dim * 3, bias_attr=False
        )
        new_state = layers.gru_unit(
            input=decoder_inputs, hidden=state, size=hidden_dim * 3
        )
        rnn.update_memory(state, new_state)
        out = layers.fc(input=new_state, size=target_dict_size, act="softmax")
        rnn.step_output(out)
    return rnn()


def build(src_dict_size=30000, trg_dict_size=30000, word_dim=256,
          hidden_dim=512, max_len=32, learning_rate=0.0002):
    src = layers.data("src_word_id", shape=[max_len], dtype="int64", lod_level=1)
    trg = layers.data("target_language_word", shape=[max_len], dtype="int64",
                      lod_level=1)
    trg_next = layers.data("target_language_next_word", shape=[max_len],
                           dtype="int64", lod_level=1)
    enc = encoder(src, src_dict_size, word_dim, hidden_dim)
    trg_emb = layers.embedding(input=trg, size=[trg_dict_size, word_dim])
    prediction = train_decoder(enc, trg_emb, hidden_dim, trg_dict_size)
    layers.link_sequence(prediction, trg)
    # masked token-level cross entropy over the padded batch
    cost = layers.cross_entropy(input=prediction, label=trg_next)
    cost = layers.reshape(cost, [0, -1])
    layers.link_sequence(cost, trg)
    summed = layers.sequence_pool(cost, pool_type="sum")
    avg_cost = layers.mean(summed)
    optimizer = opt.Adam(learning_rate=learning_rate)
    optimizer.minimize(avg_cost)
    return {"feed": [src, trg, trg_next], "prediction": prediction,
            "avg_cost": avg_cost, "encoder": enc}


def build_decode(src_dict_size=30000, trg_dict_size=30000, word_dim=256,
                 hidden_dim=512, max_len=32, beam_size=4, max_out_len=16,
                 end_id=1):
    """Fixed-width beam-search decode program (reference decoder_decode,
    test_machine_translation.py:85-144)."""
    import numpy as np
    from ..layers import control_flow as cf

    src = layers.data("src_word_id", shape=[max_len], dtype="int64", lod_level=1)
    enc = encoder(src, src_dict_size, word_dim, hidden_dim)
    enc_proj = layers.fc(input=enc, size=hidden_dim, num_flatten_dims=2,
                         bias_attr=False)
    layers.link_sequence(enc_proj, enc)
    init_state = layers.sequence_last_step(enc)  # [b, h]
    batch = init_state.shape[0]

    # beam state tensors [b, k]; start token id 0 (<s>)
    pre_ids = layers.fill_constant_batch_size_like(
        init_state, [1, beam_size], "int64", 0.0
    )
    pre_scores = layers.fill_constant_batch_size_like(
        init_state, [1, beam_size], "float32", 0.0
    )
    counter = layers.zeros([1], "int64")
    cond = layers.fill_constant([1], "bool", 1.0)
    # arrays [t, b, k] — batch dim taken from the (runtime) batch size
    ids_array = layers.fill_constant_batch_size_like(
        init_state, [max_out_len, 1, beam_size], "int64", 0.0,
        output_dim_idx=1,
    )
    parents_array = layers.fill_constant_batch_size_like(
        init_state, [max_out_len, 1, beam_size], "int64", 0.0,
        output_dim_idx=1,
    )
    # replicate decoder state across beams: [b, k, h]
    state = layers.expand(
        layers.reshape(init_state, [batch, 1, hidden_dim]), [1, beam_size, 1]
    )

    w = cf.While(cond)
    with w.block():
        flat_state = layers.reshape(
            state,
            [batch * beam_size if batch > 0 else -1, hidden_dim],
        )
        context = nets.simple_attention(
            _tile_seq(enc, beam_size), _tile_seq(enc_proj, beam_size),
            flat_state, hidden_dim,
        )
        cur_emb = _beam_embedding(pre_ids, trg_dict_size, word_dim)
        dec_in = layers.fc(
            input=[cur_emb, context], size=hidden_dim * 3, bias_attr=False,
            name="decode_fc",
        )
        new_state = layers.gru_unit(
            input=dec_in, hidden=flat_state, size=hidden_dim * 3
        )
        probs = layers.fc(input=new_state, size=trg_dict_size, act="softmax",
                          name="decode_out")
        log_probs = layers.log(probs)
        scores3 = layers.reshape(log_probs, [batch, beam_size, trg_dict_size])
        sel_ids, sel_scores, parents = layers.beam_search(
            pre_ids, pre_scores, scores3, beam_size, end_id
        )
        cf.array_write(sel_ids, counter, ids_array)
        cf.array_write(parents, counter, parents_array)
        layers.assign(sel_ids, pre_ids)
        layers.assign(sel_scores, pre_scores)
        # regroup state by parent beam
        st3 = layers.reshape(new_state, [batch, beam_size, hidden_dim])
        layers.assign(_gather_beams(st3, parents), state)
        layers.increment(counter, 1.0)
        # stop when all beams emit end_id or length cap reached
        limit = layers.fill_constant([1], "int64", float(max_out_len))
        running = layers.less_than(counter, limit)
        finished = layers.reduce_min(
            layers.cast(layers.equal(
                sel_ids,
                layers.fill_constant([1], "int64", float(end_id)),
            ), "float32")
        )
        not_all_done = layers.less_than(
            finished, layers.fill_constant([1], "float32", 1.0)
        )
        layers.assign(layers.logical_and(running, not_all_done), cond)

    return {"feed": [src], "ids_array": ids_array,
            "parents_array": parents_array, "scores": pre_scores,
            "steps": counter}


def _tile_seq(x, k):
    """[b, t, d] -> [b*k, t, d] sharing lengths."""
    b, t = x.shape[0], x.shape[1]
    d = x.shape[2]
    out = layers.reshape(
        layers.expand(layers.reshape(x, [b, 1, t, d]), [1, k, 1, 1]),
        [b * k if b > 0 else -1, t, d],
    )
    if x.lod_level > 0:
        ln = x.length_var()
        tiled = layers.reshape(
            layers.expand(layers.reshape(ln, [b, 1]), [1, k]), [b * k if b > 0 else -1]
        )
        out.block.vars[out.name + "@LENGTH"] = tiled
        out.lod_level = x.lod_level
    return out


def _gather_beams(x, parents):
    """Regroup [b, k, d] by parent beam indices [b, k]:
    out[b, i] = x[b, parents[b, i]] — expressed as onehot(parents) @ x so it
    stays a dense MXU matmul instead of a gather."""
    k = x.shape[1]
    onehot = layers.one_hot(parents, k)  # [b, k, k] float32
    return layers.matmul(layers.cast(onehot, x.dtype), x)


def _beam_embedding(pre_ids, dict_size, word_dim):
    flat = layers.reshape(pre_ids, [-1, 1])
    return layers.embedding(input=flat, size=[dict_size, word_dim],
                            param_attr="trg_embedding_w")


def decode_sentences(ids_array_val, parents_array_val, steps, end_id=1):
    """Host-side backtrack helper over fetched arrays (beam_search_decode's
    job when run outside the program)."""
    import numpy as np
    from ..ops.beam_search_ops import beam_search_decode

    t = int(np.asarray(steps).reshape(-1)[0])
    ids = np.asarray(ids_array_val)[:t]
    parents = np.asarray(parents_array_val)[:t]
    out = beam_search_decode(Ids=ids, ParentIdx=parents, end_id=end_id)
    return np.asarray(out["SentenceIds"])
