"""Word2vec N-gram model (reference: fluid/tests/book/test_word2vec.py)."""

from .. import layers, optimizer as opt
from ..param_attr import ParamAttr


def build(dict_size, embed_size=32, hidden_size=256, n=4, learning_rate=0.001):
    words = [
        layers.data(f"word_{i}", shape=[1], dtype="int64") for i in range(n)
    ]
    next_word = layers.data("next_word", shape=[1], dtype="int64")
    shared = ParamAttr(name="shared_w")
    embeds = [
        layers.embedding(
            input=w, size=[dict_size, embed_size], param_attr=shared
        )
        for w in words
    ]
    concat = layers.concat(input=embeds, axis=1)
    hidden = layers.fc(input=concat, size=hidden_size, act="sigmoid")
    predict = layers.fc(input=hidden, size=dict_size, act="softmax")
    cost = layers.cross_entropy(input=predict, label=next_word)
    avg_cost = layers.mean(cost)
    optimizer = opt.SGD(learning_rate=learning_rate)
    optimizer.minimize(avg_cost)
    return {"feed": words + [next_word], "prediction": predict,
            "avg_cost": avg_cost}
