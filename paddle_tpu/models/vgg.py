"""VGG-16/19 (reference: benchmark/paddle/image/vgg.py and
fluid/tests/book/test_image_classification vgg16_bn)."""

from .. import layers, nets, optimizer as opt

_GROUPS = {16: [2, 2, 3, 3, 3], 19: [2, 2, 4, 4, 4]}


def vgg_net(input, class_dim=1000, depth=16, with_bn=True):
    filters = [64, 128, 256, 512, 512]
    tmp = input
    for nf, reps in zip(filters, _GROUPS[depth]):
        tmp = nets.img_conv_group(
            input=tmp, conv_num_filter=[nf] * reps, pool_size=2,
            conv_padding=1, conv_filter_size=3, conv_act="relu",
            conv_with_batchnorm=with_bn, pool_stride=2, pool_type="max",
        )
    fc1 = layers.fc(input=tmp, size=4096, act="relu")
    drop1 = layers.dropout(fc1, dropout_prob=0.5)
    fc2 = layers.fc(input=drop1, size=4096, act="relu")
    drop2 = layers.dropout(fc2, dropout_prob=0.5)
    return layers.fc(input=drop2, size=class_dim, act="softmax")


def build(depth=16, class_dim=1000, image_shape=(3, 224, 224),
          learning_rate=0.01, dtype="bfloat16"):
    img = layers.data("img", shape=list(image_shape), dtype=dtype)
    label = layers.data("label", shape=[1], dtype="int64")
    prediction = vgg_net(img, class_dim, depth)
    pred32 = layers.cast(prediction, "float32")
    cost = layers.cross_entropy(input=pred32, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=pred32, label=label)
    optimizer = opt.Momentum(learning_rate=learning_rate, momentum=0.9)
    optimizer.minimize(avg_cost)
    return {"feed": [img, label], "prediction": prediction,
            "avg_cost": avg_cost, "accuracy": acc}
