"""GoogLeNet / Inception-v1 (reference benchmark config:
benchmark/paddle/image/googlenet.py — 9 inception blocks, avg-pool head;
BASELINE rows: 1149 ms/batch bs128 on K40m; 250.46 img/s bs64 on
2x Xeon 6148 MKL-DNN). Auxiliary classifier heads (the reference's o1/o2
branches) are included and summed into the training loss with the paper's
0.3 weights."""

from .. import layers, optimizer as opt
from ..layers import tensor as ltensor


def inception(input, filter1, filter3r, filter3, filter5r, filter5, proj):
    conv1 = layers.conv2d(input, num_filters=filter1, filter_size=1,
                          act="relu")
    conv3r = layers.conv2d(input, num_filters=filter3r, filter_size=1,
                           act="relu")
    conv3 = layers.conv2d(conv3r, num_filters=filter3, filter_size=3,
                          padding=1, act="relu")
    conv5r = layers.conv2d(input, num_filters=filter5r, filter_size=1,
                           act="relu")
    conv5 = layers.conv2d(conv5r, num_filters=filter5, filter_size=5,
                          padding=2, act="relu")
    pool = layers.pool2d(input, pool_size=3, pool_stride=1, pool_padding=1,
                         pool_type="max")
    convproj = layers.conv2d(pool, num_filters=proj, filter_size=1,
                             act="relu")
    return ltensor.concat([conv1, conv3, conv5, convproj], axis=1)


def _aux_head(input, class_dim):
    pool = layers.pool2d(input, pool_size=5, pool_stride=3, pool_type="avg")
    conv = layers.conv2d(pool, num_filters=128, filter_size=1, act="relu")
    fc = layers.fc(input=conv, size=1024, act="relu")
    drop = layers.dropout(fc, dropout_prob=0.7)
    return layers.fc(input=drop, size=class_dim, act="softmax")


def googlenet(input, class_dim=1000, with_aux_heads=True):
    # stem
    conv = layers.conv2d(input, num_filters=64, filter_size=7, stride=2,
                         padding=3, act="relu")
    pool = layers.pool2d(conv, pool_size=3, pool_stride=2, pool_type="max")
    conv = layers.conv2d(pool, num_filters=64, filter_size=1, act="relu")
    conv = layers.conv2d(conv, num_filters=192, filter_size=3, padding=1,
                         act="relu")
    pool = layers.pool2d(conv, pool_size=3, pool_stride=2, pool_type="max")

    ince3a = inception(pool, 64, 96, 128, 16, 32, 32)
    ince3b = inception(ince3a, 128, 128, 192, 32, 96, 64)
    pool3 = layers.pool2d(ince3b, pool_size=3, pool_stride=2,
                          pool_type="max")

    ince4a = inception(pool3, 192, 96, 208, 16, 48, 64)
    ince4b = inception(ince4a, 160, 112, 224, 24, 64, 64)
    ince4c = inception(ince4b, 128, 128, 256, 24, 64, 64)
    ince4d = inception(ince4c, 112, 144, 288, 32, 64, 64)
    ince4e = inception(ince4d, 256, 160, 320, 32, 128, 128)
    pool4 = layers.pool2d(ince4e, pool_size=3, pool_stride=2,
                          pool_type="max")

    ince5a = inception(pool4, 256, 160, 320, 32, 128, 128)
    ince5b = inception(ince5a, 384, 192, 384, 48, 128, 128)
    # 7x7/7 avg pool at 224 input == global average pool; stay global so
    # the net is resolution-independent.
    pool5 = layers.pool2d(ince5b, pool_type="avg", global_pooling=True)
    drop = layers.dropout(pool5, dropout_prob=0.4)
    out = layers.fc(input=drop, size=class_dim, act="softmax")
    if not with_aux_heads:
        return out, None, None
    out1 = _aux_head(ince4a, class_dim)
    out2 = _aux_head(ince4d, class_dim)
    return out, out1, out2


def build(class_dim=1000, image_shape=(3, 224, 224), learning_rate=0.01,
          dtype="bfloat16", with_aux_heads=True):
    img = layers.data("img", shape=list(image_shape), dtype=dtype)
    label = layers.data("label", shape=[1], dtype="int64")
    prediction, out1, out2 = googlenet(img, class_dim,
                                       with_aux_heads=with_aux_heads)
    pred32 = layers.cast(prediction, "float32")
    cost = layers.mean(layers.cross_entropy(input=pred32, label=label))
    if with_aux_heads:
        cost1 = layers.mean(layers.cross_entropy(
            input=layers.cast(out1, "float32"), label=label))
        cost2 = layers.mean(layers.cross_entropy(
            input=layers.cast(out2, "float32"), label=label))
        avg_cost = cost + 0.3 * cost1 + 0.3 * cost2
    else:
        avg_cost = cost
    acc = layers.accuracy(input=pred32, label=label)
    optimizer = opt.Momentum(learning_rate=learning_rate, momentum=0.9)
    optimizer.minimize(avg_cost)
    return {"feed": [img, label], "prediction": prediction,
            "avg_cost": avg_cost, "accuracy": acc}
