"""MNIST LeNet (reference: fluid/tests/book/test_recognize_digits.py conv
variant — BASELINE config 1)."""

from .. import layers, nets, optimizer as opt


def build(learning_rate=0.01, batch_size=None, dtype="float32",
          optimizer_cls=opt.Adam):
    """Build train program parts; returns dict of key variables."""
    img = layers.data("img", shape=[1, 28, 28], dtype=dtype)
    label = layers.data("label", shape=[1], dtype="int64")
    conv1 = nets.simple_img_conv_pool(
        input=img, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act="relu",
    )
    conv2 = nets.simple_img_conv_pool(
        input=conv1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act="relu",
    )
    prediction = layers.fc(input=conv2, size=10, act="softmax")
    cost = layers.cross_entropy(input=prediction, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=prediction, label=label)
    optimizer = optimizer_cls(learning_rate=learning_rate)
    optimizer.minimize(avg_cost)
    return {
        "feed": [img, label],
        "prediction": prediction,
        "avg_cost": avg_cost,
        "accuracy": acc,
    }
