"""SRL with stacked bidirectional LSTMs + CRF (reference: fluid book
test_label_semantic_roles.py — db_lstm)."""

from .. import layers, optimizer as opt
from ..param_attr import ParamAttr


def db_lstm(word_seqs, mark, word_dict_len, label_dict_len, pred_dict_len,
            mark_dict_len=2, word_dim=32, mark_dim=5, hidden_dim=512,
            depth=4):
    """word_seqs: [word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, predicate]"""
    predicate = word_seqs[-1]
    pred_emb = layers.embedding(
        input=predicate, size=[pred_dict_len, word_dim],
        param_attr=ParamAttr(name="vemb"),
    )
    word_embs = [
        layers.embedding(input=w, size=[word_dict_len, word_dim])
        for w in word_seqs[:-1]
    ]
    mark_emb = layers.embedding(input=mark, size=[mark_dict_len, mark_dim])
    emb_layers = word_embs + [pred_emb, mark_emb]
    hidden_0_layers = []
    for emb in emb_layers:
        h = layers.fc(input=emb, size=hidden_dim, num_flatten_dims=2,
                      bias_attr=False)
        layers.link_sequence(h, emb)
        hidden_0_layers.append(h)
    hidden_0 = layers.sums(input=hidden_0_layers)
    layers.link_sequence(hidden_0, emb_layers[0])
    lstm_0, _ = layers.dynamic_lstm(input=hidden_0, size=hidden_dim)
    input_tmp = [hidden_0, lstm_0]
    for i in range(1, depth):
        mix0 = layers.fc(input=input_tmp[0], size=hidden_dim,
                         num_flatten_dims=2, bias_attr=False)
        mix1 = layers.fc(input=input_tmp[1], size=hidden_dim,
                         num_flatten_dims=2, bias_attr=False)
        mix = layers.sums(input=[mix0, mix1])
        layers.link_sequence(mix, input_tmp[0])
        lstm, _ = layers.dynamic_lstm(
            input=mix, size=hidden_dim, is_reverse=(i % 2 == 1)
        )
        input_tmp = [mix, lstm]
    f0 = layers.fc(input=input_tmp[0], size=label_dict_len,
                   num_flatten_dims=2, bias_attr=False)
    f1 = layers.fc(input=input_tmp[1], size=label_dict_len,
                   num_flatten_dims=2, bias_attr=False)
    feature_out = layers.sums(input=[f0, f1])
    layers.link_sequence(feature_out, input_tmp[0])
    return feature_out


def build(word_dict_len=44068, label_dict_len=67, pred_dict_len=3162,
          max_len=64, word_dim=32, hidden_dim=512, depth=4,
          learning_rate=0.01):
    names = ["word", "ctx_n2", "ctx_n1", "ctx_0", "ctx_p1", "ctx_p2",
             "verb"]
    word_seqs = [
        layers.data(n, shape=[max_len], dtype="int64", lod_level=1)
        for n in names
    ]
    mark = layers.data("mark", shape=[max_len], dtype="int64", lod_level=1)
    target = layers.data("target", shape=[max_len], dtype="int64", lod_level=1)
    feature_out = db_lstm(
        word_seqs, mark, word_dict_len, label_dict_len, pred_dict_len,
        word_dim=word_dim, hidden_dim=hidden_dim, depth=depth,
    )
    crf_cost = layers.linear_chain_crf(
        input=feature_out, label=target,
        param_attr=ParamAttr(name="crfw", learning_rate=10.0 * learning_rate),
    )
    avg_cost = layers.mean(crf_cost)
    crf_decode = layers.crf_decoding(
        input=feature_out, param_attr=ParamAttr(name="crfw")
    )
    optimizer = opt.SGD(learning_rate=learning_rate)
    optimizer.minimize(avg_cost)
    return {"feed": word_seqs + [mark, target], "avg_cost": avg_cost,
            "feature_out": feature_out, "crf_decode": crf_decode}
