"""LSTM text classification (reference: benchmark/paddle/rnn/rnn.py IMDB
LSTM and fluid book test_understand_sentiment: stacked LSTM)."""

from .. import layers, optimizer as opt


def stacked_lstm_net(data, input_dim, class_dim=2, emb_dim=128, hid_dim=512,
                     stacked_num=2):
    emb = layers.embedding(input=data, size=[input_dim, emb_dim])
    fc1 = layers.fc(input=emb, size=hid_dim * 4, num_flatten_dims=2)
    fc1.lod_level = emb.lod_level
    fc1.block.vars.setdefault(fc1.name + "@LENGTH", data.length_var())
    hidden, cell = layers.dynamic_lstm(input=fc1, size=hid_dim * 4)
    inputs = hidden
    for i in range(1, stacked_num):
        fc = layers.fc(input=inputs, size=hid_dim * 4, num_flatten_dims=2)
        fc.lod_level = inputs.lod_level
        fc.block.vars.setdefault(fc.name + "@LENGTH", data.length_var())
        hidden, cell = layers.dynamic_lstm(
            input=fc, size=hid_dim * 4, is_reverse=(i % 2 == 1)
        )
        inputs = hidden
    last = layers.sequence_pool(input=inputs, pool_type="max")
    return layers.fc(input=last, size=class_dim, act="softmax")


def build(dict_dim, class_dim=2, emb_dim=128, hid_dim=512, stacked_num=2,
          learning_rate=0.002, max_len=128):
    data = layers.data("words", shape=[max_len], dtype="int64", lod_level=1)
    label = layers.data("label", shape=[1], dtype="int64")
    prediction = stacked_lstm_net(
        data, dict_dim, class_dim, emb_dim, hid_dim, stacked_num
    )
    cost = layers.cross_entropy(input=prediction, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=prediction, label=label)
    optimizer = opt.Adam(learning_rate=learning_rate)
    optimizer.minimize(avg_cost)
    return {"feed": [data, label], "prediction": prediction,
            "avg_cost": avg_cost, "accuracy": acc}
