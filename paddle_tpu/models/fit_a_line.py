"""Linear regression on UCI housing (reference: fluid/tests/book/
test_fit_a_line.py — the smallest end-to-end slice)."""

from .. import layers, optimizer as opt


def build(learning_rate=0.01):
    x = layers.data("x", shape=[13], dtype="float32")
    y = layers.data("y", shape=[1], dtype="float32")
    y_predict = layers.fc(input=x, size=1, act=None)
    cost = layers.square_error_cost(input=y_predict, label=y)
    avg_cost = layers.mean(cost)
    optimizer = opt.SGD(learning_rate=learning_rate)
    optimizer.minimize(avg_cost)
    return {"feed": [x, y], "prediction": y_predict, "avg_cost": avg_cost}
