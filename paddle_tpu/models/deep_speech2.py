"""DeepSpeech2-style CTC model (BASELINE config 4): conv feature frontend +
bidirectional GRU stack + row_conv lookahead + CTC loss (reference ops:
row_conv_op for the lookahead, warpctc_op for the loss; the model shape
follows Baidu DS2 as exercised by cuda/hl_sequence kernels)."""

from .. import layers, optimizer as opt


def bigru_layer(input, size):
    fc_f = layers.fc(input=input, size=size * 3, num_flatten_dims=2, bias_attr=False)
    layers.link_sequence(fc_f, input)
    fwd = layers.dynamic_gru(input=fc_f, size=size)
    fc_b = layers.fc(input=input, size=size * 3, num_flatten_dims=2, bias_attr=False)
    layers.link_sequence(fc_b, input)
    bwd = layers.dynamic_gru(input=fc_b, size=size, is_reverse=True)
    out = layers.concat([fwd, bwd], axis=2)
    layers.link_sequence(out, input)
    return out


def ds2_network(audio, feat_dim, num_rnn_layers=3, rnn_size=256,
                vocab_size=29, lookahead=4):
    """audio: [b, t, feat_dim] padded spectrogram sequence."""
    x = audio
    for _ in range(num_rnn_layers):
        x = bigru_layer(x, rnn_size)
    x = layers.row_conv(input=x, future_context_size=lookahead, act="relu")
    logits = layers.fc(input=x, size=vocab_size + 1, num_flatten_dims=2)
    layers.link_sequence(logits, audio)
    return logits


def build(feat_dim=161, max_audio_len=256, max_label_len=64, rnn_size=256,
          num_rnn_layers=3, vocab_size=29, learning_rate=5e-4):
    audio = layers.data("audio", shape=[max_audio_len, feat_dim],
                        dtype="float32", lod_level=1)
    label = layers.data("transcript", shape=[max_label_len], dtype="int64",
                        lod_level=1)
    logits = ds2_network(audio, feat_dim, num_rnn_layers, rnn_size, vocab_size)
    loss = layers.warpctc(input=logits, label=label, blank=vocab_size)
    avg_loss = layers.mean(loss)
    optimizer = opt.Adam(learning_rate=learning_rate)
    optimizer.minimize(avg_loss)
    probs = layers.softmax(logits)
    layers.link_sequence(probs, audio)
    decoded = layers.ctc_greedy_decoder(probs, blank=vocab_size)
    return {"feed": [audio, label], "logits": logits, "avg_cost": avg_loss,
            "decoded": decoded}
