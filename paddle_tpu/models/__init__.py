"""Model zoo — the reference "book" chapters + benchmark configs rebuilt on
the paddle_tpu layers DSL (reference: fluid/tests/book/*,
benchmark/paddle/image/*.py, benchmark/paddle/rnn/rnn.py)."""

from . import lenet
from . import resnet
from . import vgg
from . import alexnet
from . import googlenet
from . import smallnet
from . import text_classification
from . import seq2seq
from . import deep_speech2
from . import ctr_dnn
from . import word2vec
from . import fit_a_line
from . import label_semantic_roles
from . import recommender
from . import transformer
from . import ssd

__all__ = [
    "lenet", "resnet", "vgg", "alexnet", "googlenet", "smallnet",
    "text_classification", "seq2seq", "deep_speech2", "ctr_dnn",
    "word2vec", "fit_a_line", "label_semantic_roles", "recommender",
    "transformer",
    "ssd",
]
