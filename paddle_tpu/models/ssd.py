"""SSD single-shot detector (reference: the v1 SSD config family —
``paddle/gserver/layers/MultiBoxLossLayer.cpp``, ``PriorBox.cpp``,
``DetectionOutputLayer.cpp`` wired by ``detection_output_layer`` /
``multibox_loss_layer`` in trainer_config_helpers).

TPU-first shape discipline: ground truth arrives PADDED-DENSE —
``gt_box [b, max_gt, 4]`` (corner form, 0-1 normalized) with
``gt_label [b, max_gt]`` where entries < 0 are padding — so the whole
train step stays one static-shape jitted program (the reference used
LoD-carried variable-length box lists).

A compact two-scale detector over a small VGG-ish backbone; the
structure (multi-feature-map loc/conf heads + concatenated priors) is
exactly SSD's, scaled for tests and single-chip budgets.
"""

import numpy as np

from .. import layers, optimizer as opt
from ..layers import tensor as _tensor


def _head(feat, num_priors, num_classes, prefix):
    """Per-feature-map loc + conf heads: 3x3 convs, reshaped to
    [b, H*W*P, 4] and [b, H*W*P, C]."""
    b = feat.shape[0]
    h, w = feat.shape[2], feat.shape[3]
    loc = layers.conv2d(feat, num_filters=num_priors * 4, filter_size=3,
                        padding=1, bias_attr=True, name=f"{prefix}_loc")
    conf = layers.conv2d(feat, num_filters=num_priors * num_classes,
                         filter_size=3, padding=1, bias_attr=True,
                         name=f"{prefix}_conf")
    # NCHW -> [b, H, W, P*x] -> [b, H*W*P, x]
    loc = _tensor.transpose(loc, [0, 2, 3, 1])
    loc = _tensor.reshape(loc, [b, h * w * num_priors, 4])
    conf = _tensor.transpose(conf, [0, 2, 3, 1])
    conf = _tensor.reshape(conf, [b, h * w * num_priors, num_classes])
    return loc, conf


def build(num_classes=4, image_shape=(3, 64, 64), max_gt=8,
          learning_rate=0.001, is_test=False):
    """Build the SSD program.  Returns the feed vars plus train loss /
    inference detections."""
    c, ih, iw = image_shape
    img = layers.data("img", shape=list(image_shape), dtype="float32")

    # backbone: downsampling conv stages -> feature maps at /4 and /8
    f = layers.conv2d(img, 32, 3, padding=1, act="relu")
    f = layers.pool2d(f, pool_size=2, pool_stride=2)
    f = layers.conv2d(f, 64, 3, padding=1, act="relu")
    f = layers.pool2d(f, pool_size=2, pool_stride=2)
    feat1 = layers.conv2d(f, 64, 3, padding=1, act="relu")     # /4
    f = layers.pool2d(feat1, pool_size=2, pool_stride=2)
    feat2 = layers.conv2d(f, 128, 3, padding=1, act="relu")    # /8

    cfgs = [  # (feature map, min_size, max_size) in pixels
        (feat1, 0.15 * min(ih, iw), 0.35 * min(ih, iw)),
        (feat2, 0.35 * min(ih, iw), 0.65 * min(ih, iw)),
    ]
    locs, confs, priors, prior_vars = [], [], [], []
    for i, (feat, mn, mx) in enumerate(cfgs):
        boxes, var = layers.prior_box(
            feat, img, min_sizes=[mn], max_sizes=[mx],
            aspect_ratios=[2.0], flip=True, clip=True)
        p = boxes.shape[2]
        loc, conf = _head(feat, p, num_classes, f"head{i}")
        locs.append(loc)
        confs.append(conf)
        n_boxes = boxes.shape[0] * boxes.shape[1] * p
        priors.append(_tensor.reshape(boxes, [n_boxes, 4]))
        prior_vars.append(_tensor.reshape(var, [n_boxes, 4]))
    loc_all = _tensor.concat(locs, axis=1)        # [b, P, 4]
    conf_all = _tensor.concat(confs, axis=1)      # [b, P, C]
    # [2, P, 4]: boxes + their encode/decode variances stacked, so train
    # (multibox_loss) and inference (detection_output) use the SAME
    # variances — passing bare boxes would leave each op to its own
    # fallback and decode differently from how loc was trained.
    boxes_cat = _tensor.concat(priors, axis=0)
    vars_cat = _tensor.concat(prior_vars, axis=0)
    prior_all = _tensor.concat([
        _tensor.reshape(boxes_cat, [1, boxes_cat.shape[0], 4]),
        _tensor.reshape(vars_cat, [1, vars_cat.shape[0], 4]),
    ], axis=0)

    outs = {"feed": [img], "loc": loc_all, "conf": conf_all,
            "priors": prior_all}
    # inference head lives in the same program (nondiff, pruned away by
    # save_inference_model when exporting the train graph)
    outs["detections"] = layers.detection_output(
        loc_all, layers.softmax(conf_all), prior_all,
        keep_top_k=20, score_threshold=0.3)
    if is_test:
        return outs

    gt_box = layers.data("gt_box", shape=[max_gt, 4], dtype="float32")
    gt_label = layers.data("gt_label", shape=[max_gt], dtype="int64")
    loss = layers.multibox_loss(loc_all, conf_all, prior_all,
                                gt_box, gt_label)
    avg_loss = layers.mean(loss)
    opt.Momentum(learning_rate=learning_rate,
                 momentum=0.9).minimize(avg_loss)
    outs["feed"] += [gt_box, gt_label]
    outs["avg_cost"] = avg_loss
    return outs


def synthetic_batch(batch, image_shape=(3, 64, 64), max_gt=8, num_classes=4,
                    seed=0):
    """Tiny synthetic detection task: bright axis-aligned squares on dark
    background; the square's quadrant determines its class."""
    rng = np.random.RandomState(seed)
    c, ih, iw = image_shape
    imgs = rng.rand(batch, c, ih, iw).astype(np.float32) * 0.1
    gt_box = np.zeros((batch, max_gt, 4), np.float32)
    gt_label = np.full((batch, max_gt), -1, np.int64)
    for i in range(batch):
        n = rng.randint(1, 3)
        for j in range(n):
            s = rng.uniform(0.15, 0.3)
            x1 = rng.uniform(0.05, 0.9 - s)
            y1 = rng.uniform(0.05, 0.9 - s)
            cls = 1 + rng.randint(num_classes - 1)
            gt_box[i, j] = (x1, y1, x1 + s, y1 + s)
            gt_label[i, j] = cls
            px1, py1 = int(x1 * iw), int(y1 * ih)
            px2, py2 = int((x1 + s) * iw), int((y1 + s) * ih)
            imgs[i, :, py1:py2, px1:px2] = 0.9 + 0.1 * rng.rand(
                c, py2 - py1, px2 - px1)
    return imgs, gt_box, gt_label
