"""SmallNet — the CIFAR "quick" net (reference benchmark config:
benchmark/paddle/image/smallnet_mnist_cifar.py — three 5x5/3x3 convs with
overlapping pools, fc64 head; BASELINE row: 10.46 ms/batch bs64 K40m)."""

from .. import layers, optimizer as opt


def smallnet(input, class_dim=10):
    tmp = layers.conv2d(input, num_filters=32, filter_size=5, stride=1,
                        padding=2, act="relu")
    tmp = layers.pool2d(tmp, pool_size=3, pool_stride=2, pool_padding=1,
                        pool_type="max")
    tmp = layers.conv2d(tmp, num_filters=32, filter_size=5, stride=1,
                        padding=2, act="relu")
    tmp = layers.pool2d(tmp, pool_size=3, pool_stride=2, pool_padding=1,
                        pool_type="avg")
    tmp = layers.conv2d(tmp, num_filters=64, filter_size=3, stride=1,
                        padding=1, act="relu")
    tmp = layers.pool2d(tmp, pool_size=3, pool_stride=2, pool_padding=1,
                        pool_type="avg")
    tmp = layers.fc(input=tmp, size=64, act="relu")
    return layers.fc(input=tmp, size=class_dim, act="softmax")


def build(class_dim=10, image_shape=(3, 32, 32), learning_rate=0.01,
          dtype="float32"):
    img = layers.data("img", shape=list(image_shape), dtype=dtype)
    label = layers.data("label", shape=[1], dtype="int64")
    prediction = smallnet(img, class_dim)
    pred32 = layers.cast(prediction, "float32")
    cost = layers.cross_entropy(input=pred32, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=pred32, label=label)
    optimizer = opt.Momentum(learning_rate=learning_rate, momentum=0.9)
    optimizer.minimize(avg_cost)
    return {"feed": [img, label], "prediction": prediction,
            "avg_cost": avg_cost, "accuracy": acc}
