"""AlexNet (reference benchmark config: benchmark/paddle/image/alexnet.py —
conv1..conv5 with LRN after conv1/conv2, three FC heads with dropout;
BASELINE rows: 195 ms/batch bs64, 334 ms/batch bs128 on K40m;
399 img/s bs64 on 2x Xeon 6148 MKL-DNN)."""

from .. import layers, optimizer as opt


def alexnet(input, class_dim=1000, groups=1):
    # conv1: 11x11/4 -> LRN -> maxpool 3/2
    tmp = layers.conv2d(input, num_filters=96, filter_size=11, stride=4,
                        padding=1, act="relu")
    tmp = layers.lrn(tmp, n=5, alpha=1e-4, beta=0.75)
    tmp = layers.pool2d(tmp, pool_size=3, pool_stride=2, pool_type="max")
    # conv2: 5x5 grouped -> LRN -> maxpool
    tmp = layers.conv2d(tmp, num_filters=256, filter_size=5, stride=1,
                        padding=2, groups=groups, act="relu")
    tmp = layers.lrn(tmp, n=5, alpha=1e-4, beta=0.75)
    tmp = layers.pool2d(tmp, pool_size=3, pool_stride=2, pool_type="max")
    # conv3..conv5
    tmp = layers.conv2d(tmp, num_filters=384, filter_size=3, stride=1,
                        padding=1, act="relu")
    tmp = layers.conv2d(tmp, num_filters=384, filter_size=3, stride=1,
                        padding=1, groups=groups, act="relu")
    tmp = layers.conv2d(tmp, num_filters=256, filter_size=3, stride=1,
                        padding=1, groups=groups, act="relu")
    tmp = layers.pool2d(tmp, pool_size=3, pool_stride=2, pool_type="max")

    tmp = layers.fc(input=tmp, size=4096, act="relu")
    tmp = layers.dropout(tmp, dropout_prob=0.5)
    tmp = layers.fc(input=tmp, size=4096, act="relu")
    tmp = layers.dropout(tmp, dropout_prob=0.5)
    return layers.fc(input=tmp, size=class_dim, act="softmax")


def build(class_dim=1000, image_shape=(3, 227, 227), learning_rate=0.01,
          dtype="bfloat16", groups=1):
    img = layers.data("img", shape=list(image_shape), dtype=dtype)
    label = layers.data("label", shape=[1], dtype="int64")
    prediction = alexnet(img, class_dim, groups=groups)
    pred32 = layers.cast(prediction, "float32")
    cost = layers.cross_entropy(input=pred32, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=pred32, label=label)
    optimizer = opt.Momentum(learning_rate=learning_rate, momentum=0.9)
    optimizer.minimize(avg_cost)
    return {"feed": [img, label], "prediction": prediction,
            "avg_cost": avg_cost, "accuracy": acc}
