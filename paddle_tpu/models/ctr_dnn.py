"""CTR-DNN with large sparse embeddings (BASELINE config 5 — the go/pserver
workload: sparse embedding lookups + dense DNN tower, trained via the
distributed pserver path for cross-host sparse updates)."""

from .. import layers, optimizer as opt


def build(sparse_feature_dim=100000, num_slots=8, embedding_size=16,
          dense_dim=13, hidden=(64, 32), learning_rate=1e-3,
          is_sparse=True):
    dense = layers.data("dense_feature", shape=[dense_dim], dtype="float32")
    slots = [
        layers.data(f"slot_{i}", shape=[1], dtype="int64")
        for i in range(num_slots)
    ]
    label = layers.data("click", shape=[1], dtype="int64")
    embs = [
        layers.embedding(
            input=s, size=[sparse_feature_dim, embedding_size],
            is_sparse=is_sparse,
        )
        for s in slots
    ]
    concat = layers.concat(input=[dense] + embs, axis=1)
    x = concat
    for h in hidden:
        x = layers.fc(input=x, size=h, act="relu")
    predict = layers.fc(input=x, size=2, act="softmax")
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    auc = layers.auc(input=predict, label=label)
    optimizer = opt.Adam(learning_rate=learning_rate)
    optimizer.minimize(avg_cost)
    return {"feed": [dense] + slots + [label], "prediction": predict,
            "avg_cost": avg_cost, "auc": auc}
