"""CTR-DNN with large sparse embeddings (BASELINE config 5 — the go/pserver
workload: sparse embedding lookups + dense DNN tower, trained via the
distributed pserver path for cross-host sparse updates)."""

from .. import layers, optimizer as opt


def build(sparse_feature_dim=100000, num_slots=8, embedding_size=16,
          dense_dim=13, hidden=(64, 32), learning_rate=1e-3,
          is_sparse=True):
    dense = layers.data("dense_feature", shape=[dense_dim], dtype="float32")
    slots = [
        layers.data(f"slot_{i}", shape=[1], dtype="int64")
        for i in range(num_slots)
    ]
    label = layers.data("click", shape=[1], dtype="int64")
    embs = [
        layers.embedding(
            input=s, size=[sparse_feature_dim, embedding_size],
            is_sparse=is_sparse,
        )
        for s in slots
    ]
    concat = layers.concat(input=[dense] + embs, axis=1)
    x = concat
    for h in hidden:
        x = layers.fc(input=x, size=h, act="relu")
    predict = layers.fc(input=x, size=2, act="softmax")
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    auc = layers.auc(input=predict, label=label)
    optimizer = opt.Adam(learning_rate=learning_rate)
    optimizer.minimize(avg_cost)
    return {"feed": [dense] + slots + [label], "prediction": predict,
            "avg_cost": avg_cost, "auc": auc}


def build_sparse_slots(sparse_feature_dim=1_000_000, num_slots=4,
                       embedding_size=16, dense_dim=13, hidden=(64, 32),
                       learning_rate=1e-3):
    """The reference-style CTR config whose inputs are raw
    ``sparse_binary_vector``/``sparse_float_vector`` slots (multi-hot
    feature bags, PyDataProvider2.py:90-156) rather than single embedding
    ids.  Each slot is a native ``layers.sparse_data`` handle; the fc over
    it IS the embedding-bag (weighted sum of table rows), so vocabulary
    scale is bounded by the [dim, emb] table, never by a densified
    input row."""
    dense = layers.data("dense_feature", shape=[dense_dim], dtype="float32")
    slots = [
        layers.sparse_data(f"slot_{i}", dim=sparse_feature_dim)
        for i in range(num_slots)
    ]
    label = layers.data("click", shape=[1], dtype="int64")
    embs = [layers.fc(input=s, size=embedding_size) for s in slots]
    x = layers.concat(input=[dense] + embs, axis=1)
    for h in hidden:
        x = layers.fc(input=x, size=h, act="relu")
    predict = layers.fc(input=x, size=2, act="softmax")
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    auc = layers.auc(input=predict, label=label)
    optimizer = opt.Adam(learning_rate=learning_rate)
    optimizer.minimize(avg_cost)
    return {"feed": [dense] + slots + [label], "prediction": predict,
            "avg_cost": avg_cost, "auc": auc}
