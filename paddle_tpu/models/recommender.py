"""Personalized recommendation (reference: fluid book
test_recommender_system.py — user/movie towers + cosine similarity)."""

from .. import layers, optimizer as opt
from .. import dataset


def build(learning_rate=0.2, max_title_len=16, max_cat_len=8):
    ml = dataset.movielens
    usr = layers.data("user_id", shape=[1], dtype="int64")
    gender = layers.data("gender_id", shape=[1], dtype="int64")
    age = layers.data("age_id", shape=[1], dtype="int64")
    job = layers.data("job_id", shape=[1], dtype="int64")
    mov = layers.data("movie_id", shape=[1], dtype="int64")
    category = layers.data("category_id", shape=[max_cat_len], dtype="int64",
                           lod_level=1)
    title = layers.data("movie_title", shape=[max_title_len], dtype="int64",
                        lod_level=1)
    score = layers.data("score", shape=[1], dtype="float32")

    def tower_fc(emb):
        return layers.fc(input=emb, size=32)

    usr_emb = layers.embedding(input=usr, size=[ml.MAX_USER + 1, 32])
    usr_gender = layers.embedding(input=gender, size=[ml.NUM_GENDER, 16])
    usr_age = layers.embedding(input=age, size=[ml.NUM_AGE, 16])
    usr_job = layers.embedding(input=job, size=[ml.NUM_JOB, 16])
    usr_combined = layers.fc(
        input=layers.concat(
            [tower_fc(usr_emb), tower_fc(usr_gender), tower_fc(usr_age),
             tower_fc(usr_job)], axis=1,
        ),
        size=200, act="tanh",
    )

    mov_emb = layers.embedding(input=mov, size=[ml.MAX_MOVIE + 1, 32])
    cat_emb = layers.embedding(input=category, size=[ml.NUM_CATEGORY, 32])
    cat_pool = layers.sequence_pool(input=cat_emb, pool_type="sum")
    title_emb = layers.embedding(input=title, size=[ml.TITLE_VOCAB, 32])
    title_conv = layers.sequence_conv(
        input=title_emb, num_filters=32, filter_size=3, act="tanh"
    )
    title_pool = layers.sequence_pool(input=title_conv, pool_type="sum")
    mov_combined = layers.fc(
        input=layers.concat(
            [tower_fc(mov_emb), cat_pool, title_pool], axis=1
        ),
        size=200, act="tanh",
    )

    inference = layers.cos_sim(X=usr_combined, Y=mov_combined)
    scaled = layers.scale(inference, scale=5.0)
    cost = layers.square_error_cost(input=scaled, label=score)
    avg_cost = layers.mean(cost)
    optimizer = opt.SGD(learning_rate=learning_rate)
    optimizer.minimize(avg_cost)
    return {
        "feed": [usr, gender, age, job, mov, category, title, score],
        "prediction": scaled,
        "avg_cost": avg_cost,
    }
