"""The v1 generation driver: beam_search over a recurrent step function
with GeneratedInput (reference ``RecurrentGradientMachine.h:307-309``
generateSequence/beamSearch, ``api/SequenceGenerator.cpp``).

Golden: the lowered decode program's beams must match a handwritten
numpy beam search running the identical math on the same weights —
beam_size > 1, with parent switching and eos freezing exercised.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.compat import v1

BOS, EOS = 0, 1


def _np_softmax(x):
    e = np.exp(x - x.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def _np_beam_search(ctx, emb_w, w_e, w_c, w_h, w_o, k, T):
    """Reference decode for the config built below: per step
    h = tanh(emb @ w_e + ctx @ w_c + mem @ w_h), probs = softmax(h @ w_o);
    fixed-width beams, finished beams extend only with EOS at 0 cost."""
    b, V = ctx.shape[0], w_o.shape[1]
    h = w_h.shape[0]
    ids = np.full((b, k), BOS, np.int64)
    scores = np.full((b, k), -1e38, np.float32)
    scores[:, 0] = 0.0
    mem = np.zeros((b, k, h), np.float32)
    step_ids, step_parents = [], []
    for _ in range(T):
        emb = emb_w[ids]                                   # [b, k, e]
        ctx_k = np.repeat(ctx[:, None], k, axis=1)
        hh = np.tanh(emb @ w_e + ctx_k @ w_c + mem @ w_h)  # [b, k, h]
        logp = np.log(_np_softmax(hh @ w_o))               # [b, k, V]
        finished = ids == EOS
        step = np.where(
            finished[..., None],
            np.where(np.arange(V)[None, None] == EOS, 0.0, -1e38),
            logp)
        total = scores[..., None] + step
        flat = total.reshape(b, k * V)
        top = np.argsort(-flat, axis=1, kind="stable")[:, :k]
        scores = np.take_along_axis(flat, top, axis=1).astype(np.float32)
        parent = top // V
        ids = (top % V).astype(np.int64)
        mem = np.take_along_axis(hh, parent[..., None], axis=1)
        step_ids.append(ids.copy())
        step_parents.append(parent.copy())
    # backtrack parent pointers
    out = np.zeros((b, k, T), np.int64)
    beam = np.tile(np.arange(k), (b, 1))
    for t in range(T - 1, -1, -1):
        out[:, :, t] = np.take_along_axis(step_ids[t], beam, axis=1)
        beam = np.take_along_axis(step_parents[t], beam, axis=1)
    # pad after first EOS with EOS
    for i in range(b):
        for j in range(k):
            hit = np.where(out[i, j] == EOS)[0]
            if hit.size:
                out[i, j, hit[0]:] = EOS
    return out, scores


def test_v1_beam_search_matches_numpy_reference():
    b, d, h, e, V, k, T = 2, 4, 5, 3, 7, 3, 6

    def build():
        ctx = layers.data("ctx", shape=[d], dtype="float32")

        def step(emb, enc):
            mem = v1.memory(name="dec", size=h)
            hid = v1.mixed_layer(
                size=h,
                input=[v1.full_matrix_projection(
                           emb, size=h, param_attr=pt.ParamAttr("w_e")),
                       v1.full_matrix_projection(
                           enc, size=h, param_attr=pt.ParamAttr("w_c")),
                       v1.full_matrix_projection(
                           mem, size=h, param_attr=pt.ParamAttr("w_h"))],
                act=v1.TanhActivation(), bias_attr=False, name="dec")
            probs = v1.mixed_layer(
                size=V,
                input=[v1.full_matrix_projection(
                    hid, size=V, param_attr=pt.ParamAttr("w_o"))],
                act=v1.SoftmaxActivation(), bias_attr=False)
            return probs

        out = v1.beam_search(
            step,
            input=[v1.GeneratedInput(size=V, embedding_name="gen_emb",
                                     embedding_size=e),
                   v1.StaticInput(ctx)],
            bos_id=BOS, eos_id=EOS, beam_size=k, max_length=T)
        return out, v1.get_output_layer(out, "scores")

    main, startup = pt.Program(), pt.Program()
    main.random_seed = startup.random_seed = 5
    with pt.program_guard(main, startup):
        sent_var, score_var = build()
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(0)
    ctx = rng.randn(b, d).astype(np.float32)
    sent, scores = exe.run(main, feed={"ctx": ctx},
                           fetch_list=[sent_var, score_var], scope=scope)
    sent, scores = np.asarray(sent), np.asarray(scores)
    assert sent.shape == (b, k, T)

    weights = {n: np.asarray(scope.get(n))
               for n in ("gen_emb", "w_e", "w_c", "w_h", "w_o")}
    exp_sent, exp_scores = _np_beam_search(
        ctx, weights["gen_emb"], weights["w_e"], weights["w_c"],
        weights["w_h"], weights["w_o"], k, T)
    np.testing.assert_array_equal(sent, exp_sent)
    np.testing.assert_allclose(scores, exp_scores, rtol=1e-4, atol=1e-5)
    # beams are distinct hypotheses, best-first
    assert (np.diff(scores, axis=1) <= 1e-6).all()


def test_v1_beam_search_beam1_is_greedy():
    b, d, h, e, V, T = 3, 4, 4, 3, 6, 5

    def build():
        ctx = layers.data("ctx", shape=[d], dtype="float32")

        def step(emb, enc):
            mem = v1.memory(name="dec", size=h)
            hid = v1.mixed_layer(
                size=h,
                input=[v1.full_matrix_projection(emb, size=h),
                       v1.full_matrix_projection(enc, size=h),
                       v1.full_matrix_projection(mem, size=h)],
                act=v1.TanhActivation(), bias_attr=False, name="dec")
            return v1.mixed_layer(
                size=V, input=[v1.full_matrix_projection(hid, size=V)],
                act=v1.SoftmaxActivation(), bias_attr=False)

        return v1.beam_search(
            step,
            input=[v1.GeneratedInput(size=V, embedding_size=e),
                   v1.StaticInput(ctx)],
            bos_id=BOS, eos_id=EOS, beam_size=1, max_length=T)

    main, startup = pt.Program(), pt.Program()
    main.random_seed = startup.random_seed = 9
    with pt.program_guard(main, startup):
        sent_var = build()
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(2)
    (sent,) = exe.run(main, feed={"ctx": rng.randn(b, d).astype(np.float32)},
                      fetch_list=[sent_var], scope=scope)
    sent = np.asarray(sent)
    assert sent.shape == (b, 1, T)
    assert ((sent >= 0) & (sent < V)).all()


def test_beam_support_ops_direct():
    from tests.op_test import run_op

    ref = np.zeros((2, 3), np.float32)
    init = run_op("beam_init", {"Ref": ref},
                  attrs={"beam_size": 4, "bos_id": 7})
    np.testing.assert_array_equal(init["Ids"], np.full((2, 4), 7))
    assert (init["Scores"][:, 0] == 0).all()
    assert (init["Scores"][:, 1:] < -1e30).all()

    x = np.arange(6, dtype=np.float32).reshape(3, 2)
    ex = run_op("beam_expand", {"X": x}, attrs={"beam_size": 2})["Out"]
    np.testing.assert_array_equal(ex, np.repeat(x, 2, axis=0))

    state = np.arange(8, dtype=np.float32).reshape(4, 2)  # b=2, k=2
    parent = np.array([[1, 1], [0, 1]], np.int32)
    got = run_op("beam_gather", {"X": state, "Parent": parent})["Out"]
    np.testing.assert_array_equal(got, state[[1, 1, 2, 3]])


def test_v1_beam_search_boot_layer_from_encoder():
    """The canonical seq2seq generation pattern: decoder memory booted
    from encoder state [b, h] must beam-expand to the [b*k] decode
    batch (crashed before the beam_boot expansion)."""
    b, d, h, e, V, k, T = 2, 4, 4, 3, 6, 3, 5

    def build():
        ctx = layers.data("ctx", shape=[d], dtype="float32")
        boot = v1.mixed_layer(
            size=h, input=[v1.full_matrix_projection(ctx, size=h)],
            act=v1.TanhActivation(), bias_attr=False)

        def step(emb, enc):
            mem = v1.memory(name="dec", size=h, boot_layer=boot)
            hid = v1.mixed_layer(
                size=h,
                input=[v1.full_matrix_projection(emb, size=h),
                       v1.full_matrix_projection(mem, size=h)],
                act=v1.TanhActivation(), bias_attr=False, name="dec")
            return v1.mixed_layer(
                size=V, input=[v1.full_matrix_projection(hid, size=V)],
                act=v1.SoftmaxActivation(), bias_attr=False)

        return v1.beam_search(
            step,
            input=[v1.GeneratedInput(size=V, embedding_size=e),
                   v1.StaticInput(ctx)],
            bos_id=BOS, eos_id=EOS, beam_size=k, max_length=T)

    main, startup = pt.Program(), pt.Program()
    main.random_seed = startup.random_seed = 3
    with pt.program_guard(main, startup):
        sent_var = build()
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(4)
    (sent,) = exe.run(main,
                      feed={"ctx": rng.randn(b, d).astype(np.float32)},
                      fetch_list=[sent_var], scope=scope)
    sent = np.asarray(sent)
    assert sent.shape == (b, k, T)
    assert ((sent >= 0) & (sent < V)).all()


def test_v1_beam_search_with_ragged_sequence_context():
    """A lod_level=1 encoder sequence passed as StaticInput keeps its
    lengths through the beam expansion, so masked attention inside the
    step ignores padded encoder positions (was silently unmasked)."""
    b, t, d, h, e, V, k, T = 2, 4, 3, 4, 3, 6, 2, 4
    from paddle_tpu import nets

    def build():
        enc = layers.data("enc", shape=[t, d], dtype="float32",
                          lod_level=1)
        enc_proj = layers.fc(enc, h, num_flatten_dims=2, bias_attr=False)
        layers.link_sequence(enc_proj, enc)

        def step(emb, enc_seq, enc_proj_seq):
            mem = v1.memory(name="dec", size=h)
            ctx_vec = nets.simple_attention(enc_seq, enc_proj_seq, mem, h)
            hid = v1.mixed_layer(
                size=h,
                input=[v1.full_matrix_projection(emb, size=h),
                       v1.full_matrix_projection(ctx_vec, size=h)],
                act=v1.TanhActivation(), bias_attr=False, name="dec")
            return v1.mixed_layer(
                size=V, input=[v1.full_matrix_projection(hid, size=V)],
                act=v1.SoftmaxActivation(), bias_attr=False)

        return v1.beam_search(
            step,
            input=[v1.GeneratedInput(size=V, embedding_size=e),
                   v1.StaticInput(enc, is_seq=True),
                   v1.StaticInput(enc_proj, is_seq=True)],
            bos_id=BOS, eos_id=EOS, beam_size=k, max_length=T)

    main, startup = pt.Program(), pt.Program()
    main.random_seed = startup.random_seed = 6
    with pt.program_guard(main, startup):
        sent_var = build()
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(8)
    enc = rng.randn(b, t, d).astype(np.float32)
    lens = np.array([2, 4], np.int32)
    # padded encoder positions of sample 0 must NOT influence its decode:
    # perturbing them leaves the tokens unchanged
    (s1,) = exe.run(main, feed={"enc": enc, "enc@LENGTH": lens},
                    fetch_list=[sent_var], scope=scope)
    enc2 = enc.copy()
    enc2[0, 2:] = 99.0
    (s2,) = exe.run(main, feed={"enc": enc2, "enc@LENGTH": lens},
                    fetch_list=[sent_var], scope=scope)
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
