"""Backward-pass memory engine tests (ISSUE 4 tentpole).

Pins the three coordinated pieces of the memory engine:

- **residual slimming**: the flash custom-VJP saves EXACTLY
  ``(q, k, v, o, lse)`` (``FLASH_BWD_RESIDUALS``) — nothing stacked
  beyond that contract;
- **backward-scan locality**: for every ``memory_optimize`` policy the
  traced training step keeps its flash ``pallas_call``s inside
  ``lax.scan`` bodies — no per-layer unrolled kernel calls, no pallas
  operand with a leading layer-count axis, and the optimized HLO is
  free of the exact BENCH_r05 failure shape ``[L, t, d_model]``
  (checked via ``analysis.audit_program`` +
  ``compiled.memory_analysis()``, CPU-safe);
- **policy="offload"**: marks selective segments plus the program
  offload flag, is loss AND grad BIT-EXACT vs ``selective`` (a pure
  memory-placement change), and obeys the ``PADDLE_TPU_OFFLOAD=0`` kill
  switch.

Plus the satellites: ``hbm_high_water_bytes``/``temp_bytes`` in
``exe.last_step_cost`` and the registry, ``Executor.compile_only``
preflight, and bench.py's allocator-failure fallback contract.
"""

import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.analysis import audit_program
from paddle_tpu.core.program import GRAD_SUFFIX
from paddle_tpu.models import transformer

# layer count must differ from batch (2), heads (2) AND b*h (4) so the
# leading-axis probes are unambiguous (pallas operands are [b*h, t, d])
N_LAYER = 5
T, D = 12, 32


def _build(policy, drop=0.0, n_layer=N_LAYER, seed=11):
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    main.random_seed = seed
    with pt.program_guard(main, startup):
        outs = transformer.build(vocab_size=30, n_layer=n_layer, n_head=2,
                                 d_model=D, max_len=T, dropout_rate=drop,
                                 dtype="float32")
    if policy:
        pt.memory_optimize(main, policy=policy)
    return main, startup, outs["avg_cost"]


def _feed(seed=3):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, 30, (2, T)).astype(np.int64)
    return {"tokens": toks, "labels": np.roll(toks, -1, axis=1)}


def _step_outputs(main, startup, loss, steps=2):
    """[loss, *param grads] per optimizer step, in a private scope."""
    scope = pt.Scope()
    pt.core.scope._scope_stack.append(scope)
    try:
        exe = pt.Executor()
        exe.run(startup, scope=scope)
        known = {n for blk in main.blocks for n in blk.vars}
        gnames = [p.name + GRAD_SUFFIX for p in main.all_parameters()
                  if p.name + GRAD_SUFFIX in known]
        out = []
        for _ in range(steps):
            vals = exe.run(main, feed=_feed(), fetch_list=[loss] + gnames,
                           scope=scope)
            out.append([np.asarray(v) for v in vals])
        return out, exe
    finally:
        pt.core.scope._scope_stack.pop()


# -- offload policy ---------------------------------------------------------

def test_offload_policy_marks_program():
    """offload == selective segmentation + the program offload flag."""
    sel, _, _ = _build("selective")
    off, _, _ = _build("offload")
    assert off._remat_segments == sel._remat_segments
    assert off._offload is True
    assert sel._offload is False
    with pytest.raises(ValueError, match="offload"):
        pt.memory_optimize(_build(None)[0], policy="bogus")


def test_offload_bit_exact_vs_selective():
    """The acceptance bar: offload is a pure memory-PLACEMENT change —
    loss AND every parameter gradient BIT-EXACT vs selective across
    optimizer steps, XLA fusion on, in process."""
    sel, _ = _step_outputs(*_build("selective"))
    off, exe = _step_outputs(*_build("offload"))
    plan = exe.last_remat_plan
    assert plan and plan[0]["offload"] in ("save", "host")
    for s_step, o_step in zip(sel, off):
        for a, b in zip(s_step, o_step):
            np.testing.assert_array_equal(a, b)


def test_offload_bit_exact_with_dropout():
    """Dropout keys must be reproduced identically through the
    name-policy checkpoints (a wrong key shows at 1e-2, not ulp)."""
    sel, _ = _step_outputs(*_build("selective", drop=0.3))
    off, _ = _step_outputs(*_build("offload", drop=0.3))
    np.testing.assert_array_equal(sel[0][0], off[0][0])
    np.testing.assert_array_equal(sel[1][0], off[1][0])


def test_offload_kill_switch():
    """PADDLE_TPU_OFFLOAD=0 routes an offload program through the plain
    selective scan body (plan records offload "off"), bit-exact."""
    sel, _ = _step_outputs(*_build("selective"))
    try:
        os.environ["PADDLE_TPU_OFFLOAD"] = "0"
        off, exe = _step_outputs(*_build("offload"))
    finally:
        os.environ.pop("PADDLE_TPU_OFFLOAD", None)
    assert exe.last_remat_plan[0]["offload"] == "off"
    for a, b in zip(sel[0], off[0]):
        np.testing.assert_array_equal(a, b)


# -- backward-scan locality regression (the BENCH_r05 gate) -----------------

@pytest.mark.parametrize("policy",
                         ["selective", "compact", "full", "offload"])
def test_backward_scan_locality(policy):
    """For every policy: the full training step's flash kernel calls are
    scan-local (at most one un-grouped layer's worth outside — NOT O(L)
    unrolled), no pallas operand/result carries a leading layer-count
    axis, the optimized HLO contains no ``[L, t, d_model]`` buffer (the
    exact BENCH_r05 temp shape), the scan engine engaged without
    fallback, and memory_analysis reports real figures."""
    main, startup, loss = _build(policy)
    scope = pt.Scope()
    pt.core.scope._scope_stack.append(scope)
    try:
        exe = pt.Executor()
        exe.run(startup, scope=scope)
        rep = audit_program(main, _feed(), [loss], scope=scope,
                            layer_count=N_LAYER,
                            absent_shapes=[(N_LAYER, T, D)])
    finally:
        pt.core.scope._scope_stack.pop()
    assert rep["pallas_total"] > 0
    assert rep["pallas_outside_scan"] <= 3, rep["pallas_calls"]
    assert rep["pallas_total"] > rep["pallas_outside_scan"]
    assert not rep["layer_stacked_pallas"]
    assert all(n == 0 for n in rep["absent_shape_hits"].values()), rep[
        "absent_shape_hits"]
    plan = rep["scan_remat_plan"]
    assert plan and not any("fallback" in p for p in plan), plan
    assert rep["temp_bytes"] > 0
    assert rep["hbm_high_water_bytes"] > 0


def test_scan_fallback_records_reason_and_strict_raises():
    """A group the engine cannot classify falls back WITH the reason in
    the plan (no more silent fallbacks — BENCH_r05's failure class);
    PADDLE_TPU_SCAN_REMAT=strict turns that into a hard error."""
    main, startup, loss = _build("selective")
    # poison the cached group list with a malformed group so the scan
    # classification throws while the barrier fallback still works
    key = (main._version,
           tuple(tuple(s) for s in main._remat_segments))
    bogus = {"start": 0, "period": 1, "count": 2,
             "ext_maps": [{}, {}], "out_maps": [{}, {}]}
    main._scan_group_cache = (key, [bogus])
    out, exe = _step_outputs(main, startup, loss, steps=1)
    assert np.isfinite(out[0][0]).all()
    fallbacks = [p for p in exe.last_remat_plan if "fallback" in p]
    assert fallbacks and fallbacks[0]["fallback"]

    main2, startup2, loss2 = _build("selective")
    key2 = (main2._version,
            tuple(tuple(s) for s in main2._remat_segments))
    main2._scan_group_cache = (key2, [dict(bogus)])
    try:
        os.environ["PADDLE_TPU_SCAN_REMAT"] = "strict"
        with pytest.raises(Exception, match="strict"):
            _step_outputs(main2, startup2, loss2, steps=1)
    finally:
        os.environ.pop("PADDLE_TPU_SCAN_REMAT", None)


# -- residual slimming ------------------------------------------------------

def test_flash_residual_contract():
    """The custom-VJP forward returns residuals of EXACTLY
    FLASH_BWD_RESIDUALS — (q, k, v, o, lse) with the narrow 2-D lse —
    so nothing extra stacks per layer under a scanned group."""
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas_attention import (
        FLASH_BWD_RESIDUALS, _flash_core_fwd)

    assert FLASH_BWD_RESIDUALS == ("q", "k", "v", "o", "lse")
    bh, t, d = 4, 16, 8
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(bh, t, d)), jnp.float32)
               for _ in range(3))
    o, res = _flash_core_fwd(q, k, v, d ** -0.5, True, 8, 8, True, None)
    assert len(res) == len(FLASH_BWD_RESIDUALS)
    rq, rk, rv, ro, rlse = res
    assert rq is q and rk is k and rv is v  # inputs pass through, no copies
    assert ro.shape == o.shape
    assert rlse.shape == (bh, t)  # 2-D narrow layout, not lane-replicated


# -- telemetry satellites ---------------------------------------------------

def test_step_cost_memory_fields_and_gauges():
    """exe.last_step_cost carries hbm_high_water_bytes/temp_bytes from
    memory_analysis, mirrored into the registry gauges."""
    from paddle_tpu.observability.metrics import get_registry

    main, startup, loss = _build("selective")
    out, exe = _step_outputs(main, startup, loss, steps=1)
    sc = exe.last_step_cost
    assert isinstance(sc["temp_bytes"], int) and sc["temp_bytes"] > 0
    assert isinstance(sc["hbm_high_water_bytes"], int)
    assert sc["hbm_high_water_bytes"] >= sc["temp_bytes"]
    reg = get_registry()
    assert reg.value("executor.temp_bytes") > 0
    assert reg.value("executor.hbm_high_water_bytes") >= \
        reg.value("executor.temp_bytes")


def test_compile_only_primes_run_cache():
    """compile_only AOT-compiles into run()'s cache: it returns the cost
    dict (preflight fields included) and the following run() is a cache
    HIT — one compile total."""
    from paddle_tpu.observability.metrics import get_registry

    main, startup, loss = _build(None)
    scope = pt.Scope()
    pt.core.scope._scope_stack.append(scope)
    try:
        exe = pt.Executor()
        exe.run(startup, scope=scope)
        reg = get_registry()
        c0 = reg.value("executor.compile_count")
        cost = exe.compile_only(main, feed=_feed(), fetch_list=[loss],
                                scope=scope)
        assert cost["hbm_high_water_bytes"] > 0
        assert reg.value("executor.compile_count") == c0 + 1
        exe.run(main, feed=_feed(), fetch_list=[loss], scope=scope)
        assert reg.value("executor.compile_count") == c0 + 1  # cache hit
        assert exe.last_step_cost["cache_hit"] is True
    finally:
        pt.core.scope._scope_stack.pop()


# -- bench flagship fallback (the BENCH_r05 contract) -----------------------

_OOM_DUMP = """RESOURCE_EXHAUSTED: Out of memory while trying to allocate
  1. Size: 144.00M
     Operator: op_name="jit(step)/pallas_call"
     Shape: bf16[6,16384,768]{2,1,0:T(8,128)(2,1)}
     Allocation type: HLO temp
  2. Size: 144.00M
     Operator: op_name="jit(step)/pallas_call"
     Shape: bf16[6,16384,768]{2,1,0}
  3. Size: 100.00M
     Operator: op_name="jit(step)/fusion"
     Shape: f32[36,16384,1]{2,1,0}
  4. Size: 90.00M
     Operator: op_name="x"
     Shape: bf16[6,16384,768]{2,1,0}
  5. Size: 80.00M
     Operator: op_name="y"
     Shape: bf16[6,16384,768]{2,1,0}
  6. Size: 70.00M
     Operator: op_name="z"
     Shape: bf16[6,16384,768]{2,1,0}
"""


def test_oom_summary_truncates_dump():
    import bench

    s = bench._oom_summary(_OOM_DUMP)
    assert s.startswith("top5 temps:")
    assert "144.00M bf16[6,16384,768]" in s
    assert "70.00M" not in s  # only the top 5
    assert len(s) <= 400
    # arbitrary junk stays bounded too
    assert len(bench._oom_summary("x" * 10000)) <= 300


def test_bench_gpt_falls_back_to_smaller_t(monkeypatch):
    """An allocator failure at the requested t records
    gate_flagship_gpt in extra and retries at t/2 — a timed row still
    ships (the BENCH_r05 'flagship line always prints' contract)."""
    import bench

    calls = []

    def fake_at(seq, n_chips, mesh_factory, steps, warmup, extra):
        calls.append(seq)
        if seq > 8192:
            raise MemoryError(_OOM_DUMP)
        extra["gpt_hbm_high_water_bytes"] = 7 << 30
        return 1234.0, 0.3, 1200.0, 1300.0

    monkeypatch.setattr(bench, "_bench_gpt_at", fake_at)
    monkeypatch.setenv("BENCH_GPT_SEQ", "16384")
    extra = {}
    out = bench.bench_gpt(1, lambda *a: None, 5, 1, extra=extra)
    assert out[0] == 1234.0
    assert calls == [16384, 8192]
    assert extra["gpt_seq"] == 8192
    assert extra["gpt_seq_fallback"] == 8192
    assert extra["gate_flagship_gpt"].startswith(
        "FAILED: RESOURCE_EXHAUSTED at t=16384")
    assert "top" in extra["gate_flagship_gpt"]


def test_bench_gpt_non_oom_errors_propagate(monkeypatch):
    import bench

    def fake_at(seq, *a):
        raise ValueError("shape mismatch")

    monkeypatch.setattr(bench, "_bench_gpt_at", fake_at)
    monkeypatch.setenv("BENCH_GPT_SEQ", "16384")
    with pytest.raises(ValueError):
        bench.bench_gpt(1, lambda *a: None, 5, 1, extra={})


def test_bench_flagship_gate_failure_flips_rc(monkeypatch, capsys):
    """A flagship section that fell back still prints the JSON row with
    its numbers, but the recorded gate_flagship_gpt flips the rc."""
    import json

    import bench

    class _FakeDev:
        platform = "tpu"

    monkeypatch.setattr(bench, "detect_devices", lambda: [_FakeDev()])
    monkeypatch.setattr(bench, "bench_resnet",
                        lambda *a, **k: (100.0, 90.0, 110.0))

    def fake_gpt(n_chips, mesh_factory, steps, warmup, extra=None):
        extra["gate_flagship_gpt"] = "FAILED: RESOURCE_EXHAUSTED at t=16384"
        extra["gpt_seq"] = 8192
        return 1000.0, 0.31, 900.0, 1100.0

    monkeypatch.setattr(bench, "bench_gpt", fake_gpt)
    monkeypatch.setattr(bench, "_gate_flash", lambda: {})
    monkeypatch.setattr(bench, "grad_numeric_gates", lambda: {})
    monkeypatch.setattr(bench, "_gate_mem", lambda: {})
    monkeypatch.setenv("BENCH_MODELS", "resnet,gpt")
    monkeypatch.delenv("BENCH_SMOKE", raising=False)
    monkeypatch.delenv("BENCH_INFER", raising=False)
    rc = bench.main()
    row = json.loads(capsys.readouterr().out.strip())
    assert rc != 0
    assert row["value"] == 100.0
    assert row["extra"]["gpt_mfu"] == 0.31
    assert row["extra"]["gate_flagship_gpt"].startswith("FAILED")
