"""Fused softmax-cross-entropy head (ops/pallas_ce.py): Pallas kernels
(interpret mode on CPU) vs dense references, forward and backward, plus
the layer/program path and fused-vs-composed head equivalence on the
transformer flagship — the composed path it replaces is the reference's
``softmax_with_cross_entropy_op.cc`` after an fc projection."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.ops.pallas_ce import (
    fused_softmax_ce_head,
    fused_softmax_ce_head_reference,
)

from op_test import run_op


def _inputs(n, d, v, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(d, v)) * 0.3, jnp.float32)
    y = jnp.asarray(rng.integers(0, v, (n,)), jnp.int32)
    return x, w, y


@pytest.mark.parametrize("n,d,v", [(16, 8, 32), (64, 12, 100), (8, 5, 7)])
def test_fused_ce_forward_matches_dense(n, d, v):
    x, w, y = _inputs(n, d, v)
    got = fused_softmax_ce_head(x, w, y)
    ref = fused_softmax_ce_head_reference(x, w, y)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_fused_ce_forward_matches_numpy():
    """Independent numpy golden (not jax log_softmax)."""
    n, d, v = 12, 6, 40
    x, w, y = _inputs(n, d, v, seed=3)
    xn, wn, yn = map(np.asarray, (x, w, y))
    logits = xn @ wn
    m = logits.max(axis=1)
    lse = m + np.log(np.exp(logits - m[:, None]).sum(axis=1))
    ref = lse - logits[np.arange(n), yn]
    got = fused_softmax_ce_head(x, w, y)
    np.testing.assert_allclose(np.asarray(got), ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("n,d,v", [(16, 8, 32), (24, 10, 50)])
def test_fused_ce_grads_match_dense(n, d, v):
    x, w, y = _inputs(n, d, v, seed=1)
    g = jnp.asarray(np.random.default_rng(2).normal(size=(n,)), jnp.float32)

    def f_fused(x, w):
        return jnp.sum(fused_softmax_ce_head(x, w, y) * g)

    def f_ref(x, w):
        return jnp.sum(fused_softmax_ce_head_reference(x, w, y) * g)

    dx1, dw1 = jax.grad(f_fused, argnums=(0, 1))(x, w)
    dx2, dw2 = jax.grad(f_ref, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(dx1), np.asarray(dx2),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(dw1), np.asarray(dw2),
                               atol=2e-5, rtol=2e-5)


def test_fused_ce_ignored_labels_zero_grads():
    """ignore_index semantics: out-of-range labels with a zero cotangent
    (the mask multiplies the loss) contribute exactly zero gradient."""
    x, w, _ = _inputs(8, 8, 16, seed=4)
    y = jnp.asarray([-1, 3, -1, 5, -1, -1, 2, -1], jnp.int32)
    mask = (np.asarray(y) >= 0).astype(np.float32)
    y_safe = jnp.maximum(y, 0)

    def f(x, w):
        return jnp.sum(fused_softmax_ce_head(x, w, y_safe) * mask)

    def f_ref(x, w):
        return jnp.sum(
            fused_softmax_ce_head_reference(x, w, y_safe) * mask)

    dx1, dw1 = jax.grad(f, argnums=(0, 1))(x, w)
    dx2, dw2 = jax.grad(f_ref, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(dx1), np.asarray(dx2), atol=2e-5)
    np.testing.assert_allclose(np.asarray(dw1), np.asarray(dw2), atol=2e-5)
    # masked rows have exactly zero dx
    assert np.abs(np.asarray(dx1)[np.asarray(y) < 0]).max() == 0.0


def test_fused_ce_batched_leading_dims():
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(2, 6, 8)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 16, (2, 6)), jnp.int32)
    got = fused_softmax_ce_head(x, w, y)
    ref = fused_softmax_ce_head_reference(x, w, y)
    assert got.shape == (2, 6)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_fused_ce_bf16_inputs():
    rng = np.random.default_rng(6)
    xf = jnp.asarray(rng.normal(size=(16, 8)) * 0.5, jnp.float32)
    wf = jnp.asarray(rng.normal(size=(8, 32)) * 0.5, jnp.float32)
    y = jnp.asarray(rng.integers(0, 32, (16,)), jnp.int32)
    got = fused_softmax_ce_head(xf.astype(jnp.bfloat16),
                                wf.astype(jnp.bfloat16), y)
    ref = fused_softmax_ce_head_reference(xf, wf, y)
    assert got.dtype == jnp.float32  # loss always f32
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=5e-2, rtol=5e-2)


def test_fused_ce_op_registered():
    rng = np.random.default_rng(7)
    x = rng.normal(size=(2, 4, 8)).astype(np.float32)
    w = rng.normal(size=(8, 16)).astype(np.float32)
    y = rng.integers(0, 16, (2, 4, 1)).astype(np.int64)
    out = run_op("fused_softmax_ce_head", {"X": x, "W": w, "Label": y})
    ref = fused_softmax_ce_head_reference(
        jnp.asarray(x), jnp.asarray(w), jnp.asarray(y[..., 0]))
    assert out["Loss"].shape == (2, 4, 1)
    np.testing.assert_allclose(out["Loss"][..., 0], np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_transformer_fused_head_matches_composed():
    """The flagship trained with fused_head=True takes an identical first
    step (loss and post-step params) to the composed fc+softmax head when
    started from the same weights."""
    from paddle_tpu.core.scope import Scope, scope_guard
    from paddle_tpu.models import transformer

    rng = np.random.default_rng(0)
    toks = rng.integers(0, 50, (4, 16)).astype(np.int64)
    lbls = np.roll(toks, -1, axis=1)
    lbls[:, -1] = -1

    def run(fused, params=None):
        main, startup = pt.Program(), pt.Program()
        sc = Scope()
        with scope_guard(sc), pt.program_guard(main, startup):
            outs = transformer.build(
                vocab_size=50, n_layer=2, n_head=2, d_model=32,
                max_len=16, dropout_rate=0.0, dtype="float32",
                fused_head=fused)
            exe = pt.Executor()
            exe.run(startup)
            if params is not None:
                sc.update(params)
            snap = transformer.extract_params(sc, main)
            (cost,) = exe.run(main,
                              feed={"tokens": toks, "labels": lbls},
                              fetch_list=[outs["avg_cost"]])
            after = transformer.extract_params(sc, main)
        return float(np.asarray(cost).ravel()[0]), snap, after

    c0, params, after0 = run(False)
    c1, params1, after1 = run(True, params=params)
    assert sorted(params) == sorted(params1)  # same parameter surface
    assert abs(c0 - c1) < 1e-5, (c0, c1)
    for k in after0:
        np.testing.assert_allclose(
            np.asarray(after0[k], np.float32),
            np.asarray(after1[k], np.float32), atol=5e-5,
            err_msg=f"post-step param {k}")


def test_transformer_fused_head_all_masked_zero_loss():
    from paddle_tpu.models import transformer

    outs = transformer.build(vocab_size=20, n_layer=1, n_head=2,
                             d_model=16, max_len=8, dropout_rate=0.0,
                             dtype="float32", fused_head=True)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(1)
    toks = rng.integers(0, 20, (2, 8)).astype(np.int64)
    lbls = np.full((2, 8), -1, np.int64)
    (cost,) = exe.run(feed={"tokens": toks, "labels": lbls},
                      fetch_list=[outs["avg_cost"]])
    assert abs(float(np.asarray(cost).ravel()[0])) < 1e-6


def test_fused_head_trains_under_dp_mesh():
    """The fused CE head's Pallas call lowers under GSPMD with a
    batch-sharded dp mesh and the loss descends."""
    import jax

    from paddle_tpu.models import transformer
    from paddle_tpu.parallel import api as papi
    from paddle_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
    main = pt.default_main_program()
    startup = pt.default_startup_program()
    with pt.program_guard(main, startup):
        outs = transformer.build(vocab_size=64, n_layer=2, n_head=2,
                                 d_model=32, max_len=16, dropout_rate=0.0,
                                 dtype="float32", fused_head=True)
    papi.data_parallel(main, "dp", programs=(startup,))
    exe = pt.Executor(mesh=mesh)
    exe.run(startup)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 64, (8, 16)).astype(np.int64)
    lbls = np.roll(toks, -1, axis=1)
    lbls[:, -1] = -1
    losses = []
    for _ in range(4):
        (c,) = exe.run(main, feed={"tokens": toks, "labels": lbls},
                       fetch_list=[outs["avg_cost"]])
        losses.append(float(np.asarray(c).ravel()[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_block_chooser_preserves_flagship_and_shrinks_big_dmodel():
    """The VMEM-model block chooser returns the hand-tuned flagship
    config unchanged and shrinks (never dies in Mosaic) for d_model
    >= 1024 shapes."""
    from paddle_tpu.ops.pallas_ce import _auto_blocks

    assert _auto_blocks(32768, 768, 32768, 2, 2, 512, 1024, 2048) == (
        512, 1024, 2048)
    bn, bv, bvf = _auto_blocks(4096, 2048, 50000, 2, 2, 512, 1024, 2048)
    assert bn >= 8 and 50000 % bv == 0 and 50000 % bvf == 0
    assert bv < 1024 and bvf < 2048  # shrank to fit


@pytest.mark.slow
def test_fused_ce_d2048_v50k_interpret_matches_reference():
    """Large-d_model shape through the SAME code path (interpret mode):
    forward + dx + dW against the dense reference."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas_ce import (
        fused_softmax_ce_head, fused_softmax_ce_head_reference)

    rng = np.random.default_rng(9)
    n, d, v = 16, 2048, 50000
    x = jnp.asarray(rng.normal(size=(n, d)) * 0.1, jnp.float32)
    w = jnp.asarray(rng.normal(size=(d, v)) * 0.02, jnp.float32)
    y = jnp.asarray(rng.integers(0, v, (n,)), jnp.int32)

    loss = fused_softmax_ce_head(x, w, y)
    ref = fused_softmax_ce_head_reference(x, w, y)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)

    g = jnp.asarray(rng.normal(size=(n,)), jnp.float32)
    dxf, dwf = jax.grad(
        lambda x, w: jnp.sum(fused_softmax_ce_head(x, w, y) * g),
        (0, 1))(x, w)
    dxr, dwr = jax.grad(
        lambda x, w: jnp.sum(fused_softmax_ce_head_reference(x, w, y) * g),
        (0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(dxf), np.asarray(dxr),
                               rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(np.asarray(dwf), np.asarray(dwr),
                               rtol=2e-3, atol=2e-4)


def test_fused_ce_impossible_shape_fails_helpfully():
    from paddle_tpu.ops.pallas_ce import _auto_blocks

    with pytest.raises(ValueError, match="no block config fits"):
        # absurd d_model: even minimum blocks exceed the budget
        _auto_blocks(4096, 1 << 22, 32768, 4, 4, 512, 1024, 2048)
