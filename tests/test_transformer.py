"""Transformer LM model family (models/transformer.py) — the long-context
flagship NEW capability (the reference predates transformers; its attention
is composed fc+softmax, networks.py simple_attention)."""

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu.models import transformer

from test_book import train_steps


def _lm_batch(rng, batch, seq, vocab):
    toks = rng.integers(0, vocab, (batch, seq)).astype(np.int64)
    lbls = np.roll(toks, -1, axis=1)
    lbls[:, -1] = -1  # padding position, masked out of the loss
    return toks, lbls


def test_transformer_lm_trains():
    outs = transformer.build(vocab_size=50, n_layer=2, n_head=2, d_model=32,
                             max_len=16, dropout_rate=0.0,
                             learning_rate=1e-2, dtype="float32")
    rng = np.random.default_rng(0)
    toks, lbls = _lm_batch(rng, 4, 16, 50)
    train_steps(outs, {"tokens": toks, "labels": lbls}, steps=6)


def test_transformer_label_mask():
    """All-padding labels give zero loss: the mask really gates the loss."""
    outs = transformer.build(vocab_size=20, n_layer=1, n_head=2, d_model=16,
                             max_len=8, dropout_rate=0.0, dtype="float32")
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(1)
    toks = rng.integers(0, 20, (2, 8)).astype(np.int64)
    lbls = np.full((2, 8), -1, np.int64)
    (cost,) = exe.run(feed={"tokens": toks, "labels": lbls},
                      fetch_list=[outs["avg_cost"]])
    assert abs(float(np.asarray(cost).ravel()[0])) < 1e-6


def test_transformer_dp_tp_mesh():
    """Train step on a dp x tp mesh: batch sharded over dp, attention/FFN
    weights column-sharded over tp (GSPMD inserts the collectives)."""
    from paddle_tpu.parallel import api as papi
    from paddle_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"dp": 2, "tp": 2}, devices=jax.devices()[:4])
    main = pt.default_main_program()
    startup = pt.default_startup_program()
    with pt.program_guard(main, startup):
        outs = transformer.build(vocab_size=64, n_layer=2, n_head=2,
                                 d_model=32, max_len=16, dropout_rate=0.0,
                                 learning_rate=1e-2, dtype="float32")
    papi.data_parallel(main, "dp", programs=(startup,))
    for prog in (main, startup):
        papi.shard_parameters_by_rule(
            prog, [(r".*_ffn1\.w", P(None, "tp")),
                   (r".*_ffn2\.w", P("tp", None)),
                   (r"^lm_head\.w", P(None, "tp"))])

    exe = pt.Executor(mesh=mesh)
    exe.run(startup)
    rng = np.random.default_rng(2)
    toks, lbls = _lm_batch(rng, 8, 16, 64)
    losses = []
    for _ in range(4):
        (cost,) = exe.run(main, feed={"tokens": toks, "labels": lbls},
                          fetch_list=[outs["avg_cost"]])
        losses.append(float(np.asarray(cost).ravel()[0]))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses


def test_multi_head_attention_layer_shapes_and_grad():
    outs_dim = 24
    x = pt.layers.data("x", shape=[6, outs_dim], dtype="float32")
    y = pt.layers.multi_head_attention(x, x, x, d_model=outs_dim, n_head=4,
                                       causal=True)
    cost = pt.layers.mean(y * y)
    pt.optimizer.SGD(learning_rate=0.1).minimize(cost)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(3)
    xv = rng.normal(size=(2, 6, outs_dim)).astype(np.float32)
    (yv, cv) = exe.run(feed={"x": xv}, fetch_list=[y, cost])
    assert yv.shape == (2, 6, outs_dim)
    assert np.isfinite(cv).all()


def test_generate_matches_program_forward():
    """KV-cache incremental decode reproduces the Program forward logits
    on the prompt prefix (same weights, same math, different schedule —
    the test_NetworkCompare pattern, SURVEY section 4)."""
    vocab, nl, nh, dm, T = 40, 2, 2, 32, 12
    outs = transformer.build(vocab_size=vocab, n_layer=nl, n_head=nh,
                             d_model=dm, max_len=T, dropout_rate=0.0,
                             is_test=True, dtype="float32")
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(5)
    toks = rng.integers(0, vocab, (2, T)).astype(np.int64)
    lbls = np.roll(toks, -1, axis=1)
    # snapshot weights BEFORE the train step (the program updates them)
    params = transformer.extract_params()
    (prog_logits,) = exe.run(feed={"tokens": toks, "labels": lbls},
                             fetch_list=[outs["logits"]])
    gen_tokens, gen_logits = transformer.generate(
        params, toks, max_len=T, n_layer=nl, n_head=nh, d_model=dm)
    np.testing.assert_allclose(np.asarray(gen_logits), prog_logits,
                               rtol=2e-3, atol=2e-3)
    # full-length prompt comes back verbatim (no last-token overwrite)
    np.testing.assert_array_equal(np.asarray(gen_tokens), toks)


def test_infer_compute_dtype_ignores_stray_adapters():
    """Regression (ADVICE round 5): the serving-dtype scan is restricted
    to block/lm_head matmul weights — a stray low-precision matrix (an
    f16 adapter bolted onto the dict) must not silently downgrade the
    whole decode, and the f32 embedding tables must not promote it."""
    import jax.numpy as jnp

    base = {
        "tok_emb.w": np.zeros((8, 4), np.float32),
        "pos_emb.w.w": np.zeros((8, 4), np.float32),
        "block0_att_q.w": jnp.zeros((4, 4), jnp.bfloat16),
        "lm_head.w": jnp.zeros((4, 8), jnp.bfloat16),
    }
    assert transformer.infer_compute_dtype(base) == jnp.bfloat16
    # stray f16 adapter outside the block/head namespace: ignored
    with_adapter = dict(base, **{
        "adapter0.w": jnp.zeros((4, 4), jnp.float16)})
    assert transformer.infer_compute_dtype(with_adapter) == jnp.bfloat16
    # no block/head names at all: fall back to any >=2-D floating weight
    assert transformer.infer_compute_dtype(
        {"tok_emb.w": np.zeros((8, 4), np.float32)}) == jnp.float32


def test_generate_greedy_continuation():
    """After training next-token = (tok+1) mod vocab, greedy decode
    continues the pattern from a short prompt."""
    vocab, nl, nh, dm, T = 16, 1, 2, 32, 8
    outs = transformer.build(vocab_size=vocab, n_layer=nl, n_head=nh,
                             d_model=dm, max_len=T, dropout_rate=0.0,
                             learning_rate=5e-3, dtype="float32")
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(6)
    for _ in range(150):
        toks = rng.integers(0, vocab, (8, T)).astype(np.int64)
        lbls = (toks + 1) % vocab
        exe.run(feed={"tokens": toks, "labels": lbls},
                fetch_list=[outs["avg_cost"]])
    params = transformer.extract_params()
    prompt = np.asarray([[3, 4], [10, 11]], np.int64)
    tokens, _ = transformer.generate(params, prompt, max_len=T,
                                     n_layer=nl, n_head=nh, d_model=dm)
    tokens = np.asarray(tokens)
    expect = (prompt[:, -1:] + np.arange(1, T - 1)) % vocab
    assert (tokens[:, 2:] == expect).mean() > 0.7, tokens
