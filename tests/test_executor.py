"""Core IR + Executor tests (reference: framework C++ tests
op_registry_test, backward_test, prune_test + executor behavior)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def test_program_records_ops_and_vars():
    x = layers.data("x", shape=[4])
    y = layers.fc(input=x, size=3)
    prog = pt.default_main_program()
    assert any(op.type == "mul" for op in prog.global_block().ops)
    assert y.name in prog.global_block().vars
    params = prog.all_parameters()
    assert len(params) == 2  # weight + bias


def test_startup_initializes_scope():
    x = layers.data("x", shape=[4])
    layers.fc(input=x, size=3)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.global_scope()
    w = [n for n in scope.var_names() if n.endswith(".w")]
    assert w and np.asarray(scope.get(w[0])).shape == (4, 3)


def test_fetch_and_feed_roundtrip():
    x = layers.data("x", shape=[4])
    out = layers.scale(x, scale=3.0)
    exe = pt.Executor()
    data = np.arange(8, dtype=np.float32).reshape(2, 4)
    (got,) = exe.run(feed={"x": data}, fetch_list=[out])
    np.testing.assert_allclose(got, data * 3.0)


def test_backward_and_sgd_update():
    x = layers.data("x", shape=[2])
    y = layers.data("y", shape=[1])
    pred = layers.fc(input=x, size=1, bias_attr=False,
                     param_attr=pt.initializer.Constant(1.0))
    loss = layers.mean(layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.global_scope()
    wname = [n for n in scope.var_names() if n.endswith(".w")][0]
    w_before = np.asarray(scope.get(wname)).copy()
    exe.run(
        feed={"x": np.ones((4, 2), np.float32), "y": np.zeros((4, 1), np.float32)},
        fetch_list=[loss],
    )
    w_after = np.asarray(scope.get(wname))
    # pred=2, err=2; dL/dw = 2*2*x/1 -> w decreases
    assert np.all(w_after < w_before)
    np.testing.assert_allclose(w_after, w_before - 0.1 * 4.0, rtol=1e-5)


def test_grad_var_fetchable():
    x = layers.data("x", shape=[3])
    pred = layers.fc(input=x, size=1, bias_attr=False)
    loss = layers.mean(pred)
    pt.append_backward(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    block = pt.default_main_program().global_block()
    gname = [n for n in block.vars if n.endswith("@GRAD")][0]
    (g,) = exe.run(
        feed={"x": np.ones((5, 3), np.float32)},
        fetch_list=[block.var(gname)],
    )
    np.testing.assert_allclose(g, np.full((3, 1), 1.0), rtol=1e-5)


def test_stop_gradient_blocks_flow():
    x = layers.data("x", shape=[3])
    h = layers.fc(input=x, size=3, bias_attr=False,
                  param_attr=pt.initializer.Constant(1.0))
    h.stop_gradient = True
    out = layers.fc(input=h, size=1, bias_attr=False,
                    param_attr=pt.initializer.Constant(1.0))
    loss = layers.mean(out)
    pairs = pt.append_backward(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    first_w = pairs[0][1]
    (g0,) = exe.run(
        feed={"x": np.ones((2, 3), np.float32)}, fetch_list=[first_w]
    )
    np.testing.assert_allclose(g0, np.zeros_like(g0))


def test_clone_for_test_flips_is_test():
    x = layers.data("x", shape=[4])
    d = layers.dropout(x, dropout_prob=0.5)
    prog = pt.default_main_program()
    test_prog = prog.clone(for_test=True)
    op = [o for o in test_prog.global_block().ops if o.type == "dropout"][0]
    assert op.attrs["is_test"] is True
    op = [o for o in prog.global_block().ops if o.type == "dropout"][0]
    assert op.attrs["is_test"] is False


def test_prune_removes_unused_ops():
    x = layers.data("x", shape=[4])
    a = layers.scale(x, scale=2.0)
    b = layers.scale(x, scale=3.0)  # dead branch for target a
    pruned = pt.default_main_program().prune([a])
    kept_outs = {n for op in pruned.global_block().ops for n in op.output_names()}
    assert a.name in kept_outs
    assert b.name not in kept_outs


def test_persistable_state_survives_runs():
    """BN running stats update across steps (metrics-as-state pattern)."""
    x = layers.data("x", shape=[3, 4, 4])
    y = layers.batch_norm(input=x)
    loss = layers.mean(y)
    pt.optimizer.SGD(learning_rate=0.0).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.global_scope()
    mean_name = [n for n in scope.var_names() if n.endswith(".mean")][0]
    m0 = np.asarray(scope.get(mean_name)).copy()
    data = np.random.RandomState(0).randn(8, 3, 4, 4).astype(np.float32) + 5.0
    exe.run(feed={"x": data}, fetch_list=[loss])
    m1 = np.asarray(scope.get(mean_name))
    assert not np.allclose(m0, m1)
    assert np.all(m1 > 0)  # moved toward batch mean ~5


def test_rng_state_advances():
    x = layers.data("x", shape=[100])
    d = layers.dropout(x, dropout_prob=0.5)
    s = layers.reduce_sum(d)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    data = np.ones((2, 100), np.float32)
    (a,) = exe.run(feed={"x": data}, fetch_list=[s])
    (b,) = exe.run(feed={"x": data}, fetch_list=[s])
    assert float(a) != float(b)  # different dropout masks per step


def test_while_loop_lowering():
    from paddle_tpu.layers import control_flow as cf

    i = layers.fill_constant([1], "int64", 0)
    limit = layers.fill_constant([1], "int64", 10)
    acc = layers.fill_constant([1], "float32", 0.0)
    cond = layers.less_than(i, limit)
    w = cf.While(cond)
    with w.block():
        layers.assign(layers.elementwise_add(acc, layers.fill_constant([1], "float32", 2.0)), acc)
        layers.increment(i, 1.0)
        layers.assign(layers.less_than(i, limit), cond)
    exe = pt.Executor()
    (got, iters) = exe.run(fetch_list=[acc, i])
    assert got[0] == 20.0
    assert iters[0] == 10


def test_static_rnn_cumsum():
    from paddle_tpu.layers.control_flow import StaticRNN

    x = layers.data("x", shape=[4, 3])  # [b, t, d]
    init = layers.fill_constant_batch_size_like(x, [1, 3], "float32", 0.0)
    rnn = StaticRNN()
    with rnn.step():
        xt = rnn.step_input(x)
        mem = rnn.memory(init=init)
        new = layers.elementwise_add(mem, xt)
        rnn.update_memory(mem, new)
        rnn.step_output(new)
    out = rnn()
    exe = pt.Executor()
    data = np.arange(24, dtype=np.float32).reshape(2, 4, 3)
    (got,) = exe.run(feed={"x": data}, fetch_list=[out])
    np.testing.assert_allclose(got, np.cumsum(data, axis=1))


def test_clone_for_test_does_not_train():
    """clone(for_test=True) strips grad/optimizer/update ops: evaluating
    the clone must never mutate parameters (reference inference_optimize
    semantics; regression — Trainer.test previously ran the update)."""
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.data("y", shape=[1])
    pred = pt.layers.fc(x, 1, bias_attr=False)
    cost = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(cost)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.core.scope.global_scope()
    pname = pt.default_main_program().all_parameters()[0].name
    before = np.asarray(scope.get(pname)).copy()

    test_prog = pt.default_main_program().clone(for_test=True)
    xv = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    yv = np.random.RandomState(1).randn(8, 1).astype(np.float32)
    (c,) = exe.run(test_prog, feed={"x": xv, "y": yv}, fetch_list=[cost])
    assert np.isfinite(c).all()
    np.testing.assert_array_equal(np.asarray(scope.get(pname)), before)
    # the original program still trains
    exe.run(feed={"x": xv, "y": yv}, fetch_list=[cost])
    assert not np.allclose(np.asarray(scope.get(pname)), before)


def test_clone_for_test_freezes_lr_schedule():
    """Eval on a test clone must not advance the LR schedule's step
    counter (regression: the increment op is forward-positioned)."""
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.data("y", shape=[1])
    pred = pt.layers.fc(x, 1, bias_attr=False)
    cost = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    lr = pt.learning_rate_decay.exponential_decay(0.1, 10, 0.5)
    pt.optimizer.SGD(learning_rate=lr).minimize(cost)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.core.scope.global_scope()
    step_name = next(n for n in scope._vars if n.endswith(".step"))

    xv = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    yv = np.random.RandomState(1).randn(8, 1).astype(np.float32)
    exe.run(feed={"x": xv, "y": yv}, fetch_list=[cost])
    after_train = float(np.asarray(scope.get(step_name)).ravel()[0])
    test_prog = pt.default_main_program().clone(for_test=True)
    exe.run(test_prog, feed={"x": xv, "y": yv}, fetch_list=[cost])
    exe.run(test_prog, feed={"x": xv, "y": yv}, fetch_list=[cost])
    after_eval = float(np.asarray(scope.get(step_name)).ravel()[0])
    assert after_train == after_eval, (after_train, after_eval)
