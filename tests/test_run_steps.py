"""Executor.run_steps — N training steps fused into one jitted lax.scan
(the whole-loop compilation that replaces the reference's per-op
interpreter, executor.cc:118)."""

import jax
import numpy as np

import paddle_tpu as pt
from paddle_tpu.models import lenet


def _snapshot(scope, names):
    return {n: np.asarray(scope.get(n)) for n in names}


def test_run_steps_matches_sequential():
    """Same initial state + same per-step batches => bitwise-same loss
    trajectory and final parameters as N separate run() calls."""
    outs = lenet.build(learning_rate=0.01)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.core.scope.global_scope()
    main = pt.default_main_program()
    state_names = [v.name for v in main.persistable_vars()
                   if scope.find_var(v.name) is not None]
    state_names.append(pt.core.scope.RNG_VAR)
    snap = _snapshot(scope, state_names)

    rng = np.random.default_rng(0)
    steps = 4
    imgs = rng.normal(size=(steps, 8, 1, 28, 28)).astype(np.float32)
    lbls = rng.integers(0, 10, (steps, 8, 1)).astype(np.int64)

    seq_losses = []
    for t in range(steps):
        (c,) = exe.run(feed={"img": imgs[t], "label": lbls[t]},
                       fetch_list=[outs["avg_cost"]])
        seq_losses.append(np.asarray(c).ravel()[0])
    seq_params = _snapshot(scope, state_names)

    scope.update(snap)  # rewind
    (scan_losses,) = exe.run_steps(
        feed={"img": imgs, "label": lbls}, fetch_list=[outs["avg_cost"]])
    np.testing.assert_allclose(np.asarray(scan_losses).ravel(),
                               np.asarray(seq_losses), rtol=1e-6)
    for n in state_names:
        if n == pt.core.scope.RNG_VAR:
            np.testing.assert_array_equal(
                np.asarray(scope.get(n)), seq_params[n])
        else:
            # scan and per-step jits fuse differently; tiny float drift ok
            np.testing.assert_allclose(
                np.asarray(scope.get(n)), seq_params[n], rtol=1e-5,
                atol=1e-5)


def test_run_steps_data_parallel_mesh():
    from paddle_tpu.parallel import api as papi
    from paddle_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"dp": 8})
    outs = lenet.build(learning_rate=0.01)
    main = pt.default_main_program()
    papi.data_parallel(main, "dp",
                       programs=(pt.default_startup_program(),))
    exe = pt.Executor(mesh=mesh)
    exe.run(pt.default_startup_program())

    rng = np.random.default_rng(1)
    steps, batch = 3, 16
    imgs = rng.normal(size=(steps, batch, 1, 28, 28)).astype(np.float32)
    lbls = rng.integers(0, 10, (steps, batch, 1)).astype(np.int64)
    (losses,) = exe.run_steps(feed={"img": imgs, "label": lbls},
                              fetch_list=[outs["avg_cost"]])
    losses = np.asarray(losses).ravel()
    assert losses.shape == (steps,)
    assert np.isfinite(losses).all()


def test_run_steps_feed_validation():
    outs = lenet.build()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    img = np.zeros((2, 8, 1, 28, 28), np.float32)
    lbl = np.zeros((3, 8, 1), np.int64)
    try:
        exe.run_steps(feed={"img": img, "label": lbl},
                      fetch_list=[outs["avg_cost"]])
        assert False, "expected ValueError on mismatched steps axes"
    except ValueError as e:
        assert "steps" in str(e)
