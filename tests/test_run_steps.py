"""Executor.run_steps — N training steps fused into one jitted lax.scan
(the whole-loop compilation that replaces the reference's per-op
interpreter, executor.cc:118)."""

import jax
import numpy as np

import paddle_tpu as pt
from paddle_tpu.models import lenet


def _snapshot(scope, names):
    return {n: np.asarray(scope.get(n)) for n in names}


def test_run_steps_matches_sequential():
    """Same initial state + same per-step batches => bitwise-same loss
    trajectory and final parameters as N separate run() calls."""
    outs = lenet.build(learning_rate=0.01)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.core.scope.global_scope()
    main = pt.default_main_program()
    state_names = [v.name for v in main.persistable_vars()
                   if scope.find_var(v.name) is not None]
    state_names.append(pt.core.scope.RNG_VAR)
    snap = _snapshot(scope, state_names)

    rng = np.random.default_rng(0)
    steps = 4
    imgs = rng.normal(size=(steps, 8, 1, 28, 28)).astype(np.float32)
    lbls = rng.integers(0, 10, (steps, 8, 1)).astype(np.int64)

    seq_losses = []
    for t in range(steps):
        (c,) = exe.run(feed={"img": imgs[t], "label": lbls[t]},
                       fetch_list=[outs["avg_cost"]])
        seq_losses.append(np.asarray(c).ravel()[0])
    seq_params = _snapshot(scope, state_names)

    scope.update(snap)  # rewind
    (scan_losses,) = exe.run_steps(
        feed={"img": imgs, "label": lbls}, fetch_list=[outs["avg_cost"]])
    np.testing.assert_allclose(np.asarray(scan_losses).ravel(),
                               np.asarray(seq_losses), rtol=1e-6)
    for n in state_names:
        if n == pt.core.scope.RNG_VAR:
            np.testing.assert_array_equal(
                np.asarray(scope.get(n)), seq_params[n])
        else:
            # scan and per-step jits fuse differently; tiny float drift ok
            np.testing.assert_allclose(
                np.asarray(scope.get(n)), seq_params[n], rtol=1e-5,
                atol=1e-5)


def test_run_steps_data_parallel_mesh():
    from paddle_tpu.parallel import api as papi
    from paddle_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"dp": 8})
    outs = lenet.build(learning_rate=0.01)
    main = pt.default_main_program()
    papi.data_parallel(main, "dp",
                       programs=(pt.default_startup_program(),))
    exe = pt.Executor(mesh=mesh)
    exe.run(pt.default_startup_program())

    rng = np.random.default_rng(1)
    steps, batch = 3, 16
    imgs = rng.normal(size=(steps, batch, 1, 28, 28)).astype(np.float32)
    lbls = rng.integers(0, 10, (steps, batch, 1)).astype(np.int64)
    (losses,) = exe.run_steps(feed={"img": imgs, "label": lbls},
                              fetch_list=[outs["avg_cost"]])
    losses = np.asarray(losses).ravel()
    assert losses.shape == (steps,)
    assert np.isfinite(losses).all()


def test_run_steps_feed_validation():
    outs = lenet.build()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    img = np.zeros((2, 8, 1, 28, 28), np.float32)
    lbl = np.zeros((3, 8, 1), np.int64)
    try:
        exe.run_steps(feed={"img": img, "label": lbl},
                      fetch_list=[outs["avg_cost"]])
        assert False, "expected ValueError on mismatched steps axes"
    except ValueError as e:
        assert "steps" in str(e)


def test_trainer_steps_per_call_matches_unfused():
    """Trainer(steps_per_call=N) is the SmallNet dispatch fix: N batches
    per device call, same math, events per batch (VERDICT r4 item 8)."""
    from paddle_tpu.models import lenet

    rng = np.random.default_rng(2)
    n_batches = 5  # odd: exercises the 1-batch straggler flush
    imgs = rng.normal(size=(n_batches, 8, 1, 28, 28)).astype(np.float32)
    lbls = rng.integers(0, 10, (n_batches, 8, 1)).astype(np.int64)

    def reader():
        for t in range(n_batches):
            yield [(imgs[t][i], lbls[t][i]) for i in range(8)]

    def train(steps_per_call):
        prog, start = pt.Program(), pt.Program()
        with pt.program_guard(prog, start):
            outs = lenet.build(learning_rate=0.01)
        trainer = pt.trainer.Trainer(outs["avg_cost"], outs["feed"],
                             main_program=prog, startup_program=start)
        trainer.init_params()
        pt.core.scope.global_scope().update(
            {pt.core.scope.RNG_VAR:
             np.asarray(pt.core.scope.global_scope().get(
                 pt.core.scope.RNG_VAR))})
        seen = []
        trainer.train(reader, num_passes=1,
                      event_handler=lambda e: seen.append(e),
                      steps_per_call=steps_per_call)
        ends = [e for e in seen if isinstance(e, pt.trainer.EndIteration)]
        assert [e.batch_id for e in ends] == list(range(n_batches))
        w = np.asarray(pt.core.scope.global_scope().get(
            prog.all_parameters()[0].name))
        return [e.cost for e in ends], w

    ref_losses, ref_w = train(1)
    fused_losses, fused_w = train(2)
    np.testing.assert_allclose(fused_losses, ref_losses, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(fused_w, ref_w, rtol=1e-5, atol=1e-5)


def test_trainer_steps_per_call_auto_is_equivalent():
    """'auto' probes both schedules then commits to one — whichever it
    picks (timing-dependent), the trained math must equal the unfused
    loop and events must stay per-batch."""
    from paddle_tpu.models import lenet

    rng = np.random.default_rng(3)
    # enough to cover the probe (probe_samples=4 singles + 3 fused groups
    # of fused_group=6) AND some post-commit batches either way
    n_batches = 30
    imgs = rng.normal(size=(n_batches, 8, 1, 28, 28)).astype(np.float32)
    lbls = rng.integers(0, 10, (n_batches, 8, 1)).astype(np.int64)

    def reader():
        for t in range(n_batches):
            yield [(imgs[t][i], lbls[t][i]) for i in range(8)]

    def train(steps_per_call):
        prog, start = pt.Program(), pt.Program()
        with pt.program_guard(prog, start):
            outs = lenet.build(learning_rate=0.01)
        trainer = pt.trainer.Trainer(outs["avg_cost"], outs["feed"],
                                     main_program=prog,
                                     startup_program=start)
        trainer.init_params()
        ends = []
        trainer.train(reader, num_passes=1, steps_per_call=steps_per_call,
                      fused_group=6, probe_samples=4,
                      event_handler=lambda e: ends.append(e) if isinstance(
                          e, pt.trainer.EndIteration) else None)
        assert [e.batch_id for e in ends] == list(range(n_batches))
        w = np.asarray(pt.core.scope.global_scope().get(
            prog.all_parameters()[0].name))
        return [e.cost for e in ends], w

    ref_losses, ref_w = train(1)
    auto_losses, auto_w = train("auto")
    np.testing.assert_allclose(auto_losses, ref_losses, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(auto_w, ref_w, rtol=1e-5, atol=1e-5)
