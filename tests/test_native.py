"""Native C++ runtime tests: recordio format (native + pure-Python
cross-check), chunk indexing, the multithreaded Loader, and the buddy
allocator (reference: paddle/memory/detail/buddy_allocator tests,
go/recordio behavior via go/master partition)."""

import ctypes

import numpy as np
import pytest

from paddle_tpu import native
from paddle_tpu.native import recordio

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain"
)


def _records(n, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.bytes(int(rng.integers(1, 2000))) for _ in range(n)]


@pytest.mark.parametrize("compressor", [0, 1])
def test_recordio_roundtrip_native(tmp_path, compressor):
    path = tmp_path / "data.rio"
    recs = _records(500)
    with recordio.Writer(path, compressor=compressor,
                         max_chunk_bytes=8 * 1024) as w:
        for r in recs:
            w.write(r)
    got = list(recordio.reader(path))
    assert got == recs


@pytest.mark.parametrize("writer_native", [True, False])
@pytest.mark.parametrize("reader_native", [True, False])
def test_recordio_python_native_interop(tmp_path, writer_native,
                                        reader_native):
    """Pure-Python and native impls produce/consume the same bytes."""
    path = tmp_path / "interop.rio"
    recs = _records(100, seed=1)
    with recordio.Writer(path, compressor=1, max_chunk_bytes=4096,
                         use_native=writer_native) as w:
        for r in recs:
            w.write(r)
    assert list(recordio.reader(path, use_native=reader_native)) == recs


def test_recordio_index_and_chunks(tmp_path):
    path = tmp_path / "idx.rio"
    recs = _records(200, seed=2)
    with recordio.Writer(path, max_chunk_bytes=16 * 1024) as w:
        for r in recs:
            w.write(r)
    idx = recordio.index(path)
    assert len(idx) > 1
    assert sum(c for _, c in idx) == len(recs)
    # reading chunk-by-chunk reconstructs the file in order
    out = []
    for off, cnt in idx:
        chunk = list(recordio.read_chunk(path, off))
        assert len(chunk) == cnt
        out.extend(chunk)
    assert out == recs


def test_recordio_crc_detects_corruption(tmp_path):
    path = tmp_path / "bad.rio"
    with recordio.Writer(path) as w:
        w.write(b"hello" * 100)
    data = bytearray(path.read_bytes())
    data[-3] ^= 0xFF  # flip a payload byte
    path.write_bytes(bytes(data))
    with pytest.raises(IOError):
        list(recordio.reader(path))


def test_loader_prefetch(tmp_path):
    paths = []
    all_recs = set()
    for i in range(3):
        p = tmp_path / f"part-{i}.rio"
        with recordio.Writer(p, max_chunk_bytes=4096) as w:
            for r in _records(200, seed=10 + i):
                w.write(r)
                all_recs.add(r)
        paths.append(p)
    with native.Loader(paths, num_threads=4, queue_cap=64) as loader:
        got = list(loader)
    assert len(got) == 600
    assert set(got) == all_recs


def test_loader_shuffle_deterministic(tmp_path):
    p = tmp_path / "s.rio"
    with recordio.Writer(p, max_chunk_bytes=1024) as w:
        for r in _records(300, seed=3):
            w.write(r)
    with native.Loader(p, num_threads=1, shuffle_seed=7) as l1:
        a = list(l1)
    with native.Loader(p, num_threads=1, shuffle_seed=7) as l2:
        b = list(l2)
    assert a == b
    with native.Loader(p, num_threads=1, shuffle_seed=-1) as l3:
        ordered = list(l3)
    assert set(a) == set(ordered)
    assert a != ordered  # chunk order actually shuffled


def test_buddy_allocator_basics():
    b = native.BuddyAllocator(1 << 20)
    assert b.capacity == 1 << 20
    p1 = b.alloc(100)
    p2 = b.alloc(5000)
    assert b.used == 128 + 8192  # rounded to powers of two
    # memory is writable
    buf = (ctypes.c_uint8 * 100).from_address(p1)
    buf[:] = bytes(range(100))
    assert bytes(buf) == bytes(range(100))
    b.free(p1)
    b.free(p2)
    assert b.used == 0
    with pytest.raises(ValueError):
        b.free(p2)  # double free detected


def test_buddy_allocator_coalesce_and_exhaust():
    b = native.BuddyAllocator(1 << 16)
    # fill the arena with 1KiB blocks
    ptrs = [b.alloc(1024) for _ in range(64)]
    with pytest.raises(MemoryError):
        b.alloc(1024)
    for p in ptrs:
        b.free(p)
    # after coalescing, one max-size block is allocatable again
    big = b.alloc(1 << 16)
    b.free(big)


def test_loader_batch_assembly(tmp_path):
    """C-side batch assembly (Loader.next_batch): fixed-size records come
    back as contiguous (prefix, payload) arrays identical to the
    per-record frombuffer+stack path; malformed sizes raise."""
    from paddle_tpu.native import Loader, recordio

    payload_bytes, n_rec = 12, 37
    p = tmp_path / "batch.rio"
    rng = np.random.default_rng(0)
    recs = []
    with recordio.Writer(p, max_chunk_bytes=256) as w:
        for i in range(n_rec):
            label = np.asarray([i], "<u2").tobytes()
            body = rng.integers(0, 256, payload_bytes).astype(np.uint8)
            recs.append((i, body))
            w.write(label + body.tobytes())

    got_labels, got_payloads = [], []
    with Loader(p, num_threads=2) as ld:
        while True:
            out = ld.next_batch(8, 2, payload_bytes, prefix_dtype="<u2")
            if out is None:
                break
            lab, pay = out
            got_labels.extend(int(x) for x in lab.reshape(-1))
            got_payloads.extend(pay.copy())
    assert sorted(got_labels) == list(range(n_rec))
    by_label = {i: b for i, b in recs}
    for lab, pay in zip(got_labels, got_payloads):
        np.testing.assert_array_equal(pay, by_label[lab])

    # wrong record size -> clean error, not garbage
    with Loader(p, num_threads=1) as ld:
        with pytest.raises(IOError, match="batch assembly"):
            ld.next_batch(4, 2, payload_bytes + 1)
