"""Composite-network helpers (nets.py; reference fluid/nets.py + v2
trainer_config_helpers/networks.py)."""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, nets

from test_book import train_steps


def test_img_conv_bn_pool_and_separable():
    img = layers.data("img", shape=[3, 16, 16])
    label = layers.data("label", shape=[1], dtype="int64")
    h = nets.img_conv_bn_pool(img, num_filters=8, filter_size=3,
                              pool_size=2, pool_stride=2, conv_padding=1)
    h = nets.img_separable_conv(h, num_channels=8, num_out_channels=16,
                                filter_size=3, padding=1, act="relu")
    out = layers.fc(h, 4, act="softmax")
    cost = layers.mean(layers.cross_entropy(out, label))
    pt.optimizer.Adam(learning_rate=0.01).minimize(cost)
    rng = np.random.default_rng(0)
    feed = {"img": rng.normal(size=(4, 3, 16, 16)).astype(np.float32),
            "label": rng.integers(0, 4, (4, 1)).astype(np.int64)}
    train_steps({"avg_cost": cost}, feed, steps=4)


def test_bidirectional_lstm_and_gru():
    words = layers.data("words", shape=[6], dtype="int64", lod_level=1)
    label = layers.data("label", shape=[1], dtype="int64")
    emb = layers.embedding(words, size=[30, 8])
    proj = layers.fc(emb, 16 * 4, num_flatten_dims=2)
    layers.link_sequence(proj, emb)
    bi = nets.bidirectional_lstm(proj, size=16)
    assert bi.shape[-1] == 32
    proj_g = layers.fc(emb, 12 * 3, num_flatten_dims=2)
    layers.link_sequence(proj_g, emb)
    big = nets.bidirectional_gru(proj_g, size=12)
    assert big.shape[-1] == 24
    pooled = layers.sequence_pool(bi, pool_type="max")
    pooled_g = layers.sequence_pool(big, pool_type="max")
    out = layers.fc([pooled, pooled_g], 2, act="softmax")
    cost = layers.mean(layers.cross_entropy(out, label))
    pt.optimizer.Adam(learning_rate=0.02).minimize(cost)
    rng = np.random.default_rng(1)
    data = rng.integers(0, 30, (4, 6)).astype(np.int64)
    lens = rng.integers(2, 7, (4,)).astype(np.int32)
    lbl = rng.integers(0, 2, (4, 1)).astype(np.int64)
    train_steps({"avg_cost": cost},
                {"words": data, "words@LENGTH": lens, "label": lbl}, steps=4)


def test_dot_product_attention_matches_numpy():
    q = layers.data("q", shape=[3, 8])
    k = layers.data("k", shape=[5, 8])
    v = layers.data("v", shape=[5, 8])
    out = nets.dot_product_attention(q, k, v)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(2)
    qv = rng.normal(size=(2, 3, 8)).astype(np.float32)
    kv = rng.normal(size=(2, 5, 8)).astype(np.float32)
    vv = rng.normal(size=(2, 5, 8)).astype(np.float32)
    (ov,) = exe.run(feed={"q": qv, "k": kv, "v": vv}, fetch_list=[out])
    s = qv @ kv.transpose(0, 2, 1)
    w = np.exp(s - s.max(-1, keepdims=True))
    w /= w.sum(-1, keepdims=True)
    np.testing.assert_allclose(np.asarray(ov), w @ vv, rtol=2e-4, atol=2e-5)
