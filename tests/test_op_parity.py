"""Op-inventory parity audit: every reference operator file
(/root/reference/paddle/operators/*_op.cc, SURVEY §2.2, ~143 ops) must map
to a registered op, a named alias, or a documented deliberate divergence.
A reference op missing from all three fails the test — silent gaps can't
creep in as the registry evolves."""

from paddle_tpu.core.registry import registered_ops

# reference umbrella files -> the registered ops that carry them
ALIASES = {
    "activation": ["sigmoid", "relu", "tanh", "exp", "abs", "softplus"],
    "compare": ["less_than", "less_equal", "greater_than", "greater_equal",
                "equal", "not_equal"],
    "logical": ["logical_and", "logical_or", "logical_not", "logical_xor"],
    "conv": ["conv2d", "conv3d", "depthwise_conv2d"],
    "conv_transpose": ["conv2d_transpose", "conv3d_transpose"],
    "pool": ["pool2d", "pool3d"],
    "pool_with_index": ["max_pool2d_with_index"],
    "reduce": ["reduce_sum", "reduce_mean", "reduce_max", "reduce_min"],
    "fill": ["fill_constant"],
    "cond": ["conditional_block"],
    "recurrent": ["scan_block"],  # scan-based dynamic RNN engine
    "lookup_table": ["lookup_table"],
    "tensor_array_read_write": ["array_read", "array_write"],
    "lod_array_length": ["array_length"],
    "top_k": ["top_k"],
    "smooth_l1_loss": ["smooth_l1_loss"],
    "softmax_with_cross_entropy": ["softmax_with_cross_entropy"],
    "get_places": [],  # layers.device.get_places (mesh devices)
}

# capabilities carried by a different mechanism than an op — each entry
# names the carrier (see PARITY.md for the full rationale)
DIVERGENT = {
    "nccl": "jax.lax collectives inserted by GSPMD (parallel/api.py)",
    "send": "distributed/rpc.py + pserver client",
    "recv": "distributed/pserver.py server-side optimizer",
    "net": "Program IS the net; no grouping op needed",
    "rnn_memory_helper": "lax.scan carries step state (ops/rnn_ops.py)",
    "shrink_rnn_memory": "static shapes + length masking",
    "max_sequence_len": "@LENGTH vectors carry lengths",
    "lod_rank_table": "bucketing readers sort by length",
    "reorder_lod_tensor_by_rank": "bucketing readers",
    "lod_tensor_to_array": "lax.scan over padded time axis",
    "array_to_lod_tensor": "lax.scan stacked outputs",
    "split_lod_tensor": "batch-axis sharding (data_parallel)",
    "merge_lod_tensor": "batch-axis sharding (data_parallel)",
    "lod_reset": "@LENGTH vectors are plain tensors; assign replaces them",
    "split_selected_rows": "parallel/sparse.py rows+values wire format",
}


def _reference_ops():
    import glob
    import os

    files = glob.glob("/root/reference/paddle/operators/*_op.cc")
    return sorted(os.path.basename(f)[: -len("_op.cc")] for f in files)


def test_every_reference_op_is_carried():
    ref = _reference_ops()
    if not ref:  # reference tree not present (CI elsewhere) — skip
        import pytest

        pytest.skip("reference tree unavailable")
    ours = set(registered_ops())
    missing = []
    for name in ref:
        if name in ours or name in DIVERGENT:
            continue
        alias = ALIASES.get(name)
        if alias is not None:
            lost = [a for a in alias if a not in ours]
            if lost:
                missing.append(f"{name} (alias {lost} unregistered)")
            continue
        missing.append(name)
    assert not missing, (
        f"reference ops with no registered carrier, alias, or documented "
        f"divergence: {missing}"
    )


def test_registry_is_larger_than_reference():
    assert len(registered_ops()) >= 150


def _directly_tested_ops():
    """Scan the test suite for ops exercised by name: eager harness calls
    (run_op/check_output/check_grad), program construction
    (append_op(type=...)), and program assertions (op.type == ...)."""
    import glob
    import os
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    tested = set()
    for f in glob.glob(os.path.join(here, "test_*.py")):
        src = open(f).read()
        for pat in (
            r'(?:run_op|check_output|check_grad)\(\s*[\'"](\w+)[\'"]',
            r'type=[\'"](\w+)[\'"]',
            r'op\.type == [\'"](\w+)[\'"]',
            # parametrized case tables: ("op_name", {attrs...}, ...)
            r'\(\s*[\'"](\w+)[\'"]\s*,\s*\{',
        ):
            tested.update(m.group(1) for m in re.finditer(pat, src))
    return tested


def test_every_registered_op_has_a_direct_test():
    """VERDICT r1 item 3: tested ⊇ registered.  Every op must be exercised
    by name somewhere in the suite — eagerly via the op_test harness, or
    (for raw/structured ops) through a program that provably contains it
    (the `op.type == "x"` assertion pattern in test_ops_control_flow.py)."""
    ours = set(registered_ops())
    tested = _directly_tested_ops()
    missing = sorted(ours - tested)
    assert not missing, (
        f"{len(missing)} registered op(s) with no direct test: {missing}"
    )
