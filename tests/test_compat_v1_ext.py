"""Golden shape/semantics tests for the v1 long-tail surface (the analog
of the reference's trainer_config_helpers/tests/configs protostr goldens:
every name is pinned by output shape and, where cheap, exact numerics)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.compat import v1
from paddle_tpu.compat import v1_ext as v1x

rng = np.random.RandomState(77)


def run_cfg(build, feed):
    """Build a v1 config inside a fresh program and run it once."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        fetches = build()
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)
    outs = exe.run(main, feed=feed,
                   fetch_list=list(fetches) if isinstance(fetches, (list, tuple))
                   else [fetches],
                   scope=scope)
    return [np.asarray(o) for o in outs]


# ------------------------------------------------------------ projections
def test_mixed_layer_identity_and_scaling_projections():
    x = rng.randn(3, 4).astype(np.float32)

    def build():
        d = v1.data_layer("x", size=4)
        out = v1.mixed_layer(
            size=4,
            input=[v1.identity_projection(d)],
            bias_attr=False)
        return out

    (got,) = run_cfg(build, {"x": x})
    np.testing.assert_allclose(got, x, rtol=1e-6)  # pure identity


def test_mixed_layer_sums_full_matrix_projections():
    x = rng.randn(2, 3).astype(np.float32)

    def build():
        d = v1.data_layer("x", size=3)
        out = v1.mixed_layer(
            size=5,
            input=[v1.full_matrix_projection(d, size=5),
                   v1.full_matrix_projection(d, size=5)],
            bias_attr=False)
        return out

    (got,) = run_cfg(build, {"x": x})
    assert got.shape == (2, 5)


def test_trans_full_matrix_and_dotmul_and_slice_projections():
    x = rng.randn(2, 6).astype(np.float32)

    def build():
        d = v1.data_layer("x", size=6)
        t = v1.mixed_layer(size=4,
                           input=[v1.trans_full_matrix_projection(d, size=4)],
                           bias_attr=False)
        dm = v1.mixed_layer(size=6, input=[v1.dotmul_projection(d)],
                            bias_attr=False)
        sl = v1.mixed_layer(
            size=4, input=[v1.slice_projection(d, [(0, 2), (4, 6)])],
            bias_attr=False)
        sc = v1.mixed_layer(size=6, input=[v1.scaling_projection(d)],
                            bias_attr=False)
        op = v1.mixed_layer(size=6,
                            input=[v1.dotmul_operator(d, d, scale=2.0)],
                            bias_attr=False)
        return t, dm, sl, sc, op

    t, dm, sl, sc, op = run_cfg(build, {"x": x})
    assert t.shape == (2, 4) and dm.shape == (2, 6)
    assert sl.shape == (2, 4) and sc.shape == (2, 6)
    np.testing.assert_allclose(op, 2.0 * x * x, rtol=1e-5)
    np.testing.assert_allclose(sl, np.concatenate([x[:, 0:2], x[:, 4:6]], 1),
                               rtol=1e-6)


def test_context_projection_window():
    x = rng.randn(2, 4, 3).astype(np.float32)  # [b, t, d]

    def build():
        d = pt.layers.data("x", shape=[4, 3], dtype="float32")
        out = v1.mixed_layer(size=9, input=[v1.context_projection(d, 3)],
                             bias_attr=False)
        return out

    (got,) = run_cfg(build, {"x": x})
    assert got.shape == (2, 4, 9)
    # center window at t: [x_{t-1}, x_t, x_{t+1}], zero-padded borders
    np.testing.assert_allclose(got[:, 1, 3:6], x[:, 1], rtol=1e-6)
    np.testing.assert_allclose(got[:, 0, 0:3], 0 * x[:, 0], atol=1e-7)
    np.testing.assert_allclose(got[:, 0, 3:6], x[:, 0], rtol=1e-6)
    np.testing.assert_allclose(got[:, 0, 6:9], x[:, 1], rtol=1e-6)


# ----------------------------------------------------- recurrence machinery
def test_recurrent_group_memory_cumsum():
    """memory + same-named layer = loop carry: accumulator == cumsum."""
    x = rng.randn(2, 5, 3).astype(np.float32)

    def build():
        d = pt.layers.data("x", shape=[5, 3], dtype="float32")

        def step(x_t):
            mem = v1.memory(name="acc", size=3)
            s = v1.addto_layer([x_t, mem], name="acc")
            return s

        return v1.recurrent_group(step, d)

    (got,) = run_cfg(build, {"x": x})
    np.testing.assert_allclose(got, np.cumsum(x, axis=1), rtol=1e-5)


def test_recurrent_layer_shape_and_static_input():
    x = rng.randn(2, 4, 6).astype(np.float32)
    c = rng.randn(2, 6).astype(np.float32)

    def build():
        d = pt.layers.data("x", shape=[4, 6], dtype="float32")
        rec = v1.recurrent_layer(d)
        ctx = pt.layers.data("c", shape=[6], dtype="float32")

        def step(x_t, ctx_in):
            mem = v1.memory(name="s", size=6)
            s = v1.addto_layer([x_t, mem, ctx_in], name="s")
            return s

        mixed = v1.recurrent_group(step, [d, v1.StaticInput(ctx)])
        return rec, mixed

    rec, mixed = run_cfg(build, {"x": x, "c": c})
    assert rec.shape == (2, 4, 6)
    # static input re-added each step: cumsum(x) + t*c
    expect = np.cumsum(x, axis=1) + np.arange(1, 5)[None, :, None] * c[:, None]
    np.testing.assert_allclose(mixed, expect, rtol=1e-5)


def test_lstm_and_gru_step_layers_in_group():
    x = rng.randn(2, 3, 8).astype(np.float32)

    def build():
        d = pt.layers.data("x", shape=[3, 8], dtype="float32")

        def lstm_step(x_t):
            cell = v1.memory(name="c", size=2)
            h = v1.lstm_step_layer(x_t, cell, size=2, name="h")
            # the cell is h's auxiliary output
            from paddle_tpu.compat.v1_ext import _register_name
            _register_name(v1.get_output_layer(h, "state"), "c")
            return h

        lstm_out = v1.recurrent_group(lstm_step, d)

        def gru_step(x_t):
            # gru needs input 3*size: project via fc inside the step
            mem = v1.memory(name="g", size=4)
            h = v1.gru_step_layer(
                v1.fc_layer(x_t, 12, act=v1.IdentityActivation(),
                            bias_attr=False),
                mem, size=4, name="g")
            return h

        gru_out = v1.recurrent_group(gru_step, d)
        return lstm_out, gru_out

    lstm_out, gru_out = run_cfg(build, {"x": x})
    assert lstm_out.shape == (2, 3, 2)
    assert gru_out.shape == (2, 3, 4)
    assert np.isfinite(lstm_out).all() and np.isfinite(gru_out).all()


# ------------------------------------------------------------ simple layers
def test_elementwise_style_layers_exact():
    a = rng.uniform(0.5, 2.0, (3, 4)).astype(np.float32)
    b = rng.uniform(0.5, 2.0, (3, 4)).astype(np.float32)
    w = rng.uniform(0.1, 0.9, (3, 1)).astype(np.float32)
    p = np.full((3, 1), 2.0, np.float32)

    def build():
        da = pt.layers.data("a", shape=[4], dtype="float32")
        db = pt.layers.data("b", shape=[4], dtype="float32")
        dw = pt.layers.data("w", shape=[1], dtype="float32")
        dp = pt.layers.data("p", shape=[1], dtype="float32")
        return (
            v1.power_layer([dp, da]),
            v1.interpolation_layer([dw, da, db]),
            v1.sum_to_one_norm_layer(da),
            v1.row_l2_norm_layer(da),
            v1.l2_distance_layer(da, db),
            v1.dot_prod_layer(da, db),
            v1.out_prod_layer(da, db),
            v1.repeat_layer(da, 3),
        )

    po, ip, s1, rl2, l2d, dp_, op_, rep = run_cfg(
        build, {"a": a, "b": b, "w": w, "p": p})
    np.testing.assert_allclose(po, a ** 2.0, rtol=1e-4)
    np.testing.assert_allclose(ip, w * a + (1 - w) * b, rtol=1e-5)
    np.testing.assert_allclose(s1, a / a.sum(1, keepdims=True), rtol=1e-5)
    np.testing.assert_allclose(
        rl2, a / np.linalg.norm(a, axis=1, keepdims=True), rtol=1e-5)
    np.testing.assert_allclose(
        l2d, np.linalg.norm(a - b, axis=1, keepdims=True), rtol=1e-5)
    np.testing.assert_allclose(dp_, (a * b).sum(1, keepdims=True), rtol=1e-5)
    np.testing.assert_allclose(
        op_, np.einsum("bi,bj->bij", a, b).reshape(3, 16), rtol=1e-5)
    np.testing.assert_allclose(rep, np.tile(a, (1, 3)), rtol=1e-6)


def test_linear_comb_and_fm_exact():
    w = rng.randn(2, 3).astype(np.float32)
    v = rng.randn(2, 12).astype(np.float32)
    x = rng.randn(2, 5).astype(np.float32)

    def build():
        dw = pt.layers.data("w", shape=[3], dtype="float32")
        dv = pt.layers.data("v", shape=[12], dtype="float32")
        dx = pt.layers.data("x", shape=[5], dtype="float32")
        return (v1.linear_comb_layer(dw, dv, size=4),
                v1.factorization_machine(dx, factor_size=3))

    lc, fm = run_cfg(build, {"w": w, "v": v, "x": x})
    exp = np.einsum("bj,bjd->bd", w, v.reshape(2, 3, 4))
    np.testing.assert_allclose(lc, exp, rtol=1e-5)
    assert fm.shape == (2, 1) and np.isfinite(fm).all()


def test_image_style_layers_shapes():
    img = rng.randn(2, 3, 8, 8).astype(np.float32)

    def build():
        d = pt.layers.data("img", shape=[3, 8, 8], dtype="float32")
        return (
            v1.bilinear_interp_layer(d, out_size_x=16, out_size_y=12),
            v1.maxout_layer(pt.layers.conv2d(d, 4, 3, padding=1), groups=2),
            v1.switch_order_layer(d),
            v1.pad_layer(d, pad_c=(1, 1), pad_h=(0, 2), pad_w=(1, 0)),
            v1.block_expand_layer(d, block_x=4, block_y=4,
                                  stride_x=4, stride_y=4),
            v1.spp_layer(d, pyramid_height=2),
            v1.resize_layer(d, size=3 * 64),
            v1.cross_channel_norm_layer(d),
        )

    bi, mo, so, pd, be, spp, rs, ccn = run_cfg(build, {"img": img})
    assert bi.shape == (2, 3, 12, 16)
    assert mo.shape == (2, 2, 8, 8)
    assert so.shape == (2, 8, 8, 3)
    assert pd.shape == (2, 5, 10, 9)
    assert be.shape[0] == 2 and be.shape[1] == 4  # 2x2 grid of 4x4 blocks
    assert spp.shape == (2, 3 * (1 + 4))
    assert rs.shape == (2, 192)
    np.testing.assert_allclose(
        np.linalg.norm(ccn, axis=1), np.ones_like(ccn[:, 0]), rtol=1e-4)


def test_scale_sub_region_and_scale_shift_and_gated():
    img = np.ones((1, 2, 3, 3), np.float32)
    ind = np.array([[1, 1, 1, 2, 1, 3]], np.int64)

    def build():
        d = pt.layers.data("img", shape=[2, 3, 3], dtype="float32")
        di = pt.layers.data("ind", shape=[6], dtype="int64")
        flat = v1.resize_layer(d, size=18)
        return (
            v1.scale_sub_region_layer(d, di, value=4.0),
            v1.scale_shift_layer(flat),
            v1.gated_unit_layer(flat, size=5),
            v1.clip_layer(flat, min=-0.5, max=0.5),
        )

    ssr, ss, gu, cl = run_cfg(build, {"img": img, "ind": ind})
    exp = img.copy()
    exp[0, 0, 0:2, 0:3] = 4.0
    np.testing.assert_array_equal(ssr, exp)
    assert ss.shape == (1, 18) and gu.shape == (1, 5)
    assert cl.max() <= 0.5


def test_sequence_and_id_layers():
    x = rng.randn(2, 4, 6).astype(np.float32)
    probs = np.array([[0.05, 0.9, 0.05], [0.8, 0.1, 0.1]], np.float32)
    ids = np.array([[1], [0]], np.int64)

    def build():
        d = pt.layers.data("x", shape=[4, 6], dtype="float32")
        dp = pt.layers.data("p", shape=[3], dtype="float32")
        di = pt.layers.data("i", shape=[1], dtype="int64")
        return (
            v1.seq_reshape_layer(d, reshape_size=3),
            v1.maxid_layer(dp),
            v1.eos_layer(di, eos_id=1),
            v1.sampling_id_layer(dp),
            v1.kmax_seq_score_layer(dp, beam_size=2),
        )

    sr, mi, eos, si, km = run_cfg(build, {"x": x, "p": probs, "i": ids})
    assert sr.shape == (2, 8, 3)
    np.testing.assert_array_equal(mi.ravel(), [1, 0])
    np.testing.assert_array_equal(eos.ravel(), [True, False])
    assert si.shape == (2,) and km.shape == (2, 2)


def test_cost_and_evaluator_layers():
    x = rng.randn(4, 3).astype(np.float32)
    y = np.array([[1], [0], [1], [0]], np.int64)

    def build():
        d = pt.layers.data("x", shape=[3], dtype="float32")
        lbl = pt.layers.data("y", shape=[1], dtype="int64")
        prob = v1.fc_layer(d, 2, act=v1.SoftmaxActivation())
        logit = v1.fc_layer(d, 1, act=v1.IdentityActivation())
        acc = v1.classification_error_evaluator(prob, lbl)
        hub = v1.huber_classification_cost(logit, lbl)
        return acc, hub

    acc, hub = run_cfg(build, {"x": x, "y": y})
    assert 0.0 <= float(acc) <= 1.0 and np.isfinite(hub)


def test_networks_shapes():
    img = rng.randn(2, 3, 32, 32).astype(np.float32)

    def build():
        d = pt.layers.data("img", shape=[3, 32, 32], dtype="float32")
        return v1.small_vgg(d, num_channels=3, num_classes=10)

    (out,) = run_cfg(build, {"img": img})
    assert out.shape == (2, 10)
    np.testing.assert_allclose(out.sum(1), np.ones(2), rtol=1e-4)


def test_triaged_names_raise_with_native_pointer():
    # every one of these names is carried natively as of round 4; bad
    # arguments get argument errors, never NotImplementedError
    with pytest.raises(ValueError, match="GeneratedInput"):
        v1.beam_search(None, [v1.StaticInput(None)], 0, 1, 4)
    with pytest.raises(ValueError, match="embedding_size"):
        v1.GeneratedInput(size=10)
    with pytest.raises(ValueError, match="lod_level=2"):
        v1.SubsequenceInput(None)
    with pytest.raises(TypeError, match="BeamInput"):
        v1.cross_entropy_over_beam([object()])
    with pytest.raises(ValueError, match="candidate_scores"):
        v1.BeamInput()


def test_no_notimplemented_left_in_v1_surface():
    """VERDICT r3 item 4 'done' bar: zero NotImplementedError in the
    v1 trainer_config_helpers surface."""
    import inspect

    from paddle_tpu.compat import v1_ext

    offenders = []
    for name in v1.__all__:
        fn = getattr(v1, name, None) or getattr(v1_ext, name, None)
        try:
            src = inspect.getsource(fn)
        except (TypeError, OSError):
            continue
        if "raise NotImplementedError" in src:
            offenders.append(name)
    assert not offenders, offenders


def test_surface_count_vs_reference():
    """The v1 compat surface covers >= 190 of the ~211 reference
    trainer_config_helpers exports (VERDICT r1 item 4 target was 150)."""
    assert len(v1.__all__) >= 190
    missing_impl = [n for n in v1.__all__ if not hasattr(v1, n)]
    assert not missing_impl, missing_impl


def test_units_attention_and_misc_callable():
    """Call-level smoke for names whose first versions crashed on call
    (review finding): lstmemory_unit/gru_unit inside recurrent_group,
    seq_concat_layer, simple_attention, multi_head_attention,
    prelu_layer, ModelAverage."""
    x = rng.randn(2, 3, 8).astype(np.float32)
    a = rng.randn(2, 3, 4).astype(np.float32)
    b = rng.randn(2, 2, 4).astype(np.float32)
    la = np.array([3, 2], np.int64)
    lb = np.array([2, 1], np.int64)

    def build():
        d = pt.layers.data("x", shape=[3, 8], dtype="float32")
        lstm_out = v1.recurrent_group(
            lambda x_t: v1.lstmemory_unit(x_t, size=4), d)
        gru_out = v1.recurrent_group(
            lambda x_t: v1.gru_unit(x_t, size=4), d)
        sa = pt.layers.data("a", shape=[3, 4], dtype="float32",
                            lod_level=1)
        sb = pt.layers.data("b", shape=[2, 4], dtype="float32",
                            lod_level=1)
        cat = v1.seq_concat_layer(sa, sb)
        dec = pt.layers.data("dec", shape=[4], dtype="float32")
        att = v1.simple_attention(sa, sa, dec)
        mha = v1.multi_head_attention(sa, sa, sa, head_num=2)
        pr = v1.prelu_layer(dec)
        return lstm_out, gru_out, cat, att, mha, pr

    feed = {"x": x, "a": a, "a@LENGTH": la, "b": b, "b@LENGTH": lb,
            "dec": rng.randn(2, 4).astype(np.float32)}
    lstm_out, gru_out, cat, att, mha, pr = run_cfg(build, feed)
    assert lstm_out.shape == (2, 3, 4) and gru_out.shape == (2, 3, 4)
    assert cat.shape == (2, 5, 4)
    # row 0: a rows 0:3 then b rows 0:2
    np.testing.assert_allclose(cat[0, :3], a[0, :3], rtol=1e-6)
    np.testing.assert_allclose(cat[0, 3:5], b[0, :2], rtol=1e-6)
    assert att.shape == (2, 4) and mha.shape == (2, 3, 4)
    assert pr.shape == (2, 4)
    assert all(np.isfinite(o).all() for o in
               (lstm_out, gru_out, cat, att, mha, pr))
    # ModelAverage constructs against the real optimizer surface (it
    # requires a minimized program, like the native class)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        xd = pt.layers.data("x", shape=[3], dtype="float32")
        yd = pt.layers.data("y", shape=[1], dtype="float32")
        cost = pt.layers.mean(
            pt.layers.square_error_cost(pt.layers.fc(xd, 1), yd))
        pt.optimizer.SGD(0.1).minimize(cost)
        ma = v1.ModelAverage(0.5)
    assert ma is not None


def test_mixed_layer_creates_default_bias():
    """v1 mixed_layer has a bias by default (bias_attr=None), like the
    reference; only bias_attr=False suppresses it."""
    def build():
        d = v1.data_layer("x", size=3)
        out = v1.mixed_layer(size=3, input=[v1.identity_projection(d)])
        return out

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        build()
    assert any(".b" in p.name for p in main.global_block().all_parameters())


def test_v1_ssd_config_path():
    """priorbox_layer -> multibox_loss_layer -> detection_output_layer:
    the ported v1 SSD config wiring runs end-to-end (regression: the
    prior output was 4-D and broke every consumer)."""
    imgs = rng.rand(2, 3, 16, 16).astype(np.float32)
    gt_box = np.zeros((2, 2, 4), np.float32)
    gt_box[:, 0] = (0.2, 0.2, 0.5, 0.5)
    gt_label = np.array([[1, -1], [1, -1]], np.int64)

    def build():
        img = pt.layers.data("img", shape=[3, 16, 16], dtype="float32")
        gb = pt.layers.data("gb", shape=[2, 4], dtype="float32")
        gl = pt.layers.data("gl", shape=[2], dtype="int64")
        feat = pt.layers.conv2d(img, 8, 3, padding=1, act="relu")
        feat = pt.layers.pool2d(feat, pool_size=4, pool_stride=4)
        pb = v1.priorbox_layer(feat, img, min_size=[4.0], max_size=[8.0])
        p = pb.shape[1]
        loc = pt.layers.conv2d(feat, 2 * 4, 3, padding=1)
        conf = pt.layers.conv2d(feat, 2 * 3, 3, padding=1)
        from paddle_tpu.layers import tensor as T

        loc = T.reshape(T.transpose(loc, [0, 2, 3, 1]), [2, p, 4])
        conf = T.reshape(T.transpose(conf, [0, 2, 3, 1]), [2, p, 3])
        loss = v1.multibox_loss_layer(loc, conf, pb, gb, gl)
        dets = v1.detection_output_layer(loc, conf, pb)
        return loss, dets

    loss, dets = run_cfg(build, {"img": imgs, "gb": gt_box, "gl": gt_label})
    assert np.isfinite(loss).all() and dets.shape[-1] == 6


# ---------------------------------------------------------------- reverse=
def test_sequence_reverse_layer_golden():
    """Length-aware rotation: element t swaps with len-1-t, padding stays
    right-aligned."""
    x = pt.layers.data("x", shape=[5, 3], dtype="float32", lod_level=1)
    y = pt.layers.sequence_reverse(x)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    xv = np.arange(2 * 5 * 3, dtype=np.float32).reshape(2, 5, 3)
    lens = np.asarray([3, 5], np.int32)
    (out,) = exe.run(feed={"x": xv, "x@LENGTH": lens}, fetch_list=[y])
    ref = xv.copy()
    for b, ln in enumerate(lens):
        ref[b, :ln] = xv[b, :ln][::-1]
    np.testing.assert_allclose(out, ref)


def test_recurrent_group_reverse_suffix_sum():
    """reverse=True visits the sequence last-to-first: with a running-sum
    step, output position t holds the suffix sum x[t] + ... + x[len-1],
    aligned to the input order (reference layers.py:347 semantics)."""
    x = pt.layers.data("x", shape=[6, 2], dtype="float32", lod_level=1)

    def step(x_t):
        mem = v1x.memory(name="acc", size=2)
        nxt = pt.layers.elementwise_add(mem, x_t)
        v1x._register_name(nxt, "acc")
        return nxt

    out = v1x.recurrent_group(step=step, input=x, reverse=True)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(0)
    xv = rng.normal(size=(3, 6, 2)).astype(np.float32)
    lens = np.asarray([4, 6, 2], np.int32)
    (got,) = exe.run(feed={"x": xv, "x@LENGTH": lens}, fetch_list=[out])
    for b, ln in enumerate(lens):
        ref = np.cumsum(xv[b, :ln][::-1], axis=0)[::-1]
        np.testing.assert_allclose(got[b, :ln], ref, rtol=1e-5, atol=1e-5,
                                   err_msg=f"sample {b}")


def test_recurrent_group_reverse_last_seq_is_first_element():
    """last_seq over a reversed group's output = the step result at the
    ORIGINAL first element (the deepest accumulation)."""
    x = pt.layers.data("x", shape=[5, 2], dtype="float32", lod_level=1)

    def step(x_t):
        mem = v1x.memory(name="m", size=2)
        nxt = pt.layers.elementwise_add(mem, x_t)
        v1x._register_name(nxt, "m")
        return nxt

    out = v1x.recurrent_group(step=step, input=x, reverse=True)
    last = v1.last_seq(input=out)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(1)
    xv = rng.normal(size=(2, 5, 2)).astype(np.float32)
    lens = np.asarray([3, 5], np.int32)
    (lv,) = exe.run(feed={"x": xv, "x@LENGTH": lens}, fetch_list=[last])
    # output[len-1] after un-rotation = first step of the reversed scan
    # = x[len-1]; output[0] = whole-sequence sum; last_seq picks
    # position len-1, i.e. x[len-1] itself
    for b, ln in enumerate(lens):
        np.testing.assert_allclose(lv[b], xv[b, ln - 1], rtol=1e-5,
                                   atol=1e-5)


def test_gru_group_reverse_matches_dynamic_gru():
    """The composed gru_group(reverse=True) path (dynamic_gru
    is_reverse=True) and an explicit reversed recurrent_group stay
    consistent on lengths: both produce zero rows past each length."""
    x = pt.layers.data("x", shape=[4, 6], dtype="float32", lod_level=1)
    out = v1x.gru_group(input=x, size=2, reverse=True)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(2)
    xv = rng.normal(size=(2, 4, 6)).astype(np.float32)
    lens = np.asarray([2, 4], np.int32)
    (got,) = exe.run(feed={"x": xv, "x@LENGTH": lens}, fetch_list=[out])
    assert got.shape[:2] == (2, 4)
    assert np.isfinite(got).all()


def test_evaluator_base_dispatch():
    """evaluator_base routes type strings to the metric layers
    (reference evaluators.py:71 generic dispatcher)."""
    pred = pt.layers.data("p", shape=[4], dtype="float32")
    lbl = pt.layers.data("l", shape=[1], dtype="int64")
    acc = v1x.evaluator_base(input=pred, type="classification_error",
                             label=lbl)
    assert acc is not None
    with pytest.raises(ValueError, match="unknown evaluator"):
        v1x.evaluator_base(input=pred, type="nope", label=lbl)


def test_recurrent_group_reverse_nested_subsequences():
    """reverse=True over a SubsequenceInput: the OUTER subsequence order
    reverses (with @SUBLENGTH permuted to match) and outputs come back
    aligned to the input order.  Golden: per-sentence sums accumulated
    in reverse outer order == suffix-sums of per-sentence sums."""
    b, s, t, d = 2, 3, 4, 3
    rng = np.random.default_rng(7)
    X = rng.normal(size=(b, s, t, d)).astype(np.float32)
    SL = np.asarray([[4, 2, 3], [3, 4, 0]], np.int32)  # inner lengths
    L = np.asarray([3, 2], np.int32)                   # outer counts

    para = pt.layers.data("para", shape=[s, t, d], dtype="float32",
                          lod_level=2)

    def outer_step(sent):
        # sent: one subsequence [b, t, d] with its inner lengths
        omem = v1x.memory(name="acc", size=d)
        pooled = pt.layers.sequence_pool(sent, "sum")
        nxt = pt.layers.elementwise_add(omem, pooled)
        v1x._register_name(nxt, "acc")
        return nxt

    out = v1x.recurrent_group(outer_step, v1.SubsequenceInput(para),
                              reverse=True)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    (got,) = exe.run(feed={"para": X, "para@LENGTH": L,
                           "para@SUBLENGTH": SL},
                     fetch_list=[out])
    for bb in range(b):
        sent_sums = [
            X[bb, j, : SL[bb, j]].sum(axis=0) for j in range(L[bb])
        ]
        # reversed outer scan: output slot j = sum of sentence sums j..end
        for j in range(L[bb]):
            ref = np.sum(sent_sums[j:], axis=0)
            np.testing.assert_allclose(got[bb, j], ref, rtol=1e-5,
                                       atol=1e-5,
                                       err_msg=f"b={bb} slot={j}")
