"""Multi-backend kernel registry: the oracle suite + the registry unit
suite (docs/kernels.md).

Oracle contract: every registered backend AVAILABLE on this host is
compared against the ``xla_ref`` reference within the documented
``ORACLE_TOL`` bounds (f32 + bf16, causal + non-causal, d_head 64/128,
grads through the custom-vjp); unavailable backends SKIP with the
registry's reason.  The GPU (triton) kernels additionally run
interpret-forced so their logic is covered on CPU-only CI.  Within a
backend the contract is bit-exact run-to-run.

Paged-attention contract: every backend of the ``paged_attention`` op
class matches an independent dense gather+masked-softmax spelling over
ragged block chains (CoW fork, trash-padded tail, garbage trash block)
for W=1 decode and W>1 verify windows; tokens past ``pos`` and the
trash block are provably inert (corruption leaves output bit-equal).

Registry contract: precedence explicit arg > per-op env > global env >
auto; unknown backends raise ValueError; explicitly requested
unavailable backends raise KernelUnavailable with a reason; a global
env pin an op cannot serve degrades to auto.
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import kernels  # noqa: E402
from paddle_tpu.kernels import (  # noqa: E402
    KernelUnavailable, available_backends, forced_backend, get_kernel,
    oracle_tol, resolve_name)


def _rel_err(a, ref):
    a = jnp.asarray(a, jnp.float32)
    ref = jnp.asarray(ref, jnp.float32)
    scale = float(jnp.max(jnp.abs(ref))) or 1.0
    return float(jnp.max(jnp.abs(a - ref))) / scale


def _impl_or_skip(op, backend):
    rows = {b: (ok, reason) for b, ok, reason in available_backends(op)}
    if backend not in rows:
        pytest.skip(f"{backend} not registered for {op}")
    ok, reason = rows[backend]
    if not ok:
        pytest.skip(f"{backend} unavailable: {reason}")
    return get_kernel(op, backend).impl


def _qkv(dt, d, b=1, t=128, h=2, seed=5):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.normal(size=(b, t, h, d)) * 0.5, dt)
                 for _ in range(3))


# -- oracle suite ------------------------------------------------------------

@pytest.mark.parametrize("backend", kernels.BACKENDS)
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("d_head", [64, 128])
def test_flash_oracle_parity(backend, dtype, causal, d_head):
    impl = _impl_or_skip("flash_attention", backend)
    oracle = get_kernel("flash_attention", "xla_ref").impl
    q, k, v = _qkv(jnp.dtype(dtype), d_head)
    # explicit 64-wide blocks: t=128 then tiles 2x2, so the online-
    # softmax state actually carries across k blocks and causal cells
    # straddle the diagonal — default (1024-capped) blocks would make
    # this a degenerate single-block kernel
    got = impl.call(q, k, v, causal=causal, block_q=64, block_k=64)
    ref = oracle.call(q, k, v, causal=causal)
    assert _rel_err(got, ref) <= oracle_tol(
        "flash_attention", dtype, "fwd")


@pytest.mark.parametrize("backend", kernels.BACKENDS)
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_flash_oracle_grads_through_custom_vjp(backend, dtype):
    impl = _impl_or_skip("flash_attention", backend)
    oracle = get_kernel("flash_attention", "xla_ref").impl
    q, k, v = _qkv(jnp.dtype(dtype), 64, b=1)
    wgt = jnp.asarray(np.random.default_rng(7).normal(size=q.shape),
                      jnp.float32)

    def loss(fn, **kw):
        return lambda q, k, v: jnp.sum(
            fn(q, k, v, causal=True, **kw).astype(jnp.float32) * wgt)

    got = jax.grad(loss(impl.call, block_q=64, block_k=64),
                   (0, 1, 2))(q, k, v)
    ref = jax.grad(loss(oracle.call), (0, 1, 2))(q, k, v)
    tol = oracle_tol("flash_attention", dtype, "grad")
    for a, r in zip(got, ref):
        assert _rel_err(a, r) <= tol


def test_flash_triton_interpret_covers_kernel_logic():
    """On hosts with no GPU the triton backend skips in the registry —
    but its kernel LOGIC still runs under interpret mode, packed +
    with_lse + dlse grads included."""
    impl = get_kernel("flash_attention", "triton").impl
    oracle = get_kernel("flash_attention", "xla_ref").impl
    q, k, v = _qkv(jnp.float32, 64, t=64)
    assert _rel_err(
        impl.call(q, k, v, causal=True, block_q=32, block_k=32,
                  interpret=True),
        oracle.call(q, k, v, causal=True)) <= oracle_tol(
            "flash_attention", "float32", "fwd")
    o_t, lse_t = impl.call_with_lse(q, k, v, causal=True,
                                    interpret=True)
    o_r, lse_r = oracle.call_with_lse(q, k, v, causal=True)
    assert _rel_err(lse_t, lse_r) <= 1e-4
    wgt = jnp.asarray(np.random.default_rng(2).normal(size=q.shape),
                      jnp.float32)

    def lse_loss(fn, **kw):
        def f(q, k, v):
            o, lse = fn(q, k, v, causal=True, **kw)
            return jnp.sum(o * wgt) + 0.1 * jnp.sum(lse)
        return f

    gt = jax.grad(lse_loss(impl.call_with_lse, interpret=True),
                  (0, 1, 2))(q, k, v)
    gr = jax.grad(lse_loss(oracle.call_with_lse), (0, 1, 2))(q, k, v)
    for a, r in zip(gt, gr):
        assert _rel_err(a, r) <= oracle_tol(
            "flash_attention", "float32", "grad")
    # packed layout (any head width on the triton path)
    b, t, h, d = q.shape[0], q.shape[1], q.shape[2], q.shape[3]
    q2, k2, v2 = (x.reshape(b, t, h * d) for x in (q, k, v))
    assert _rel_err(
        impl.call_packed(q2, k2, v2, h, causal=True, interpret=True),
        oracle.call_packed(q2, k2, v2, h, causal=True)) <= oracle_tol(
            "flash_attention", "float32", "fwd")


@pytest.mark.parametrize("backend", kernels.BACKENDS)
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_ce_oracle_parity_and_grads(backend, dtype):
    impl = _impl_or_skip("fused_ce", backend)
    oracle = get_kernel("fused_ce", "xla_ref").impl
    rng = np.random.default_rng(9)
    n, d, vocab = 64, 32, 256
    dt = jnp.dtype(dtype)
    x = jnp.asarray(rng.normal(size=(n, d)) * 0.3, dt)
    w = jnp.asarray(rng.normal(size=(d, vocab)) * 0.05, dt)
    y = jnp.asarray(rng.integers(0, vocab, (n,)), jnp.int32)
    # small explicit blocks so the vocab axis actually tiles (nv=4)
    # and the row axis splits — the online-softmax carry is the thing
    # under test
    blocks = dict(block_n=32, block_v=64, block_v_fwd=64)
    assert _rel_err(impl.call(x, w, y, **blocks),
                    oracle.call(x, w, y)) <= oracle_tol(
                        "fused_ce", dtype, "fwd")
    gvec = jnp.asarray(rng.normal(size=(n,)), jnp.float32)
    got = jax.grad(lambda x, w: jnp.sum(
        impl.call(x, w, y, **blocks) * gvec), (0, 1))(x, w)
    ref = jax.grad(lambda x, w: jnp.sum(oracle.call(x, w, y) * gvec),
                   (0, 1))(x, w)
    tol = oracle_tol("fused_ce", dtype, "grad")
    for a, r in zip(got, ref):
        assert _rel_err(a, r) <= tol


def test_ce_triton_interpret_with_lse_grads():
    impl = get_kernel("fused_ce", "triton").impl
    oracle = get_kernel("fused_ce", "xla_ref").impl
    rng = np.random.default_rng(13)
    n, d, vocab = 64, 32, 128
    x = jnp.asarray(rng.normal(size=(n, d)) * 0.3, jnp.float32)
    w = jnp.asarray(rng.normal(size=(d, vocab)) * 0.05, jnp.float32)
    y = jnp.asarray(rng.integers(0, vocab, (n,)), jnp.int32)
    gvec = jnp.asarray(rng.normal(size=(n,)), jnp.float32)

    def ml(fn, **kw):
        def f(x, w):
            loss, lse = fn(x, w, y, **kw)
            return jnp.sum(loss * gvec) + 0.1 * jnp.sum(lse)
        return f

    got = jax.grad(ml(impl.call_with_lse, interpret=True), (0, 1))(x, w)
    ref = jax.grad(ml(oracle.call_with_lse), (0, 1))(x, w)
    for a, r in zip(got, ref):
        assert _rel_err(a, r) <= oracle_tol("fused_ce", "float32",
                                            "grad")


def test_decode_gather_bit_exact_across_backends():
    from paddle_tpu.kernels.pallas_gather import decode_gather

    oracle = get_kernel("decode_gather", "xla_ref").impl
    rng = np.random.default_rng(3)
    for dt in (jnp.float32, jnp.bfloat16):
        pool = jnp.asarray(rng.normal(size=(9, 4, 2, 8)), dt)
        table = jnp.asarray(rng.integers(0, 9, (3, 6)), jnp.int32)
        ref = oracle.call(pool, table)
        got = decode_gather(pool, table, interpret=True)
        assert bool(jnp.array_equal(ref, got))
        assert ref.shape == (3, 24, 2, 8)


# -- paged attention oracle suite --------------------------------------------

def _paged_case(dt, w=1, seed=11):
    """Three ragged chains over a 10-block pool: a copy-on-write fork
    (slot 2 shares slot 0's head block), a trash-padded tail (slot 1's
    last table entry is block 0), and a garbage-filled trash block so
    any masking bug surfaces as 1e3-scale output."""
    rng = np.random.default_rng(seed)
    S, NB, B, h, dh = 3, 3, 4, 2, 16
    pool_k = jnp.asarray(
        rng.normal(size=(1 + S * NB, B, h, dh)) * 0.5, dt)
    pool_v = jnp.asarray(
        rng.normal(size=(1 + S * NB, B, h, dh)) * 0.5, dt)
    pool_k = pool_k.at[0].set(1e3)
    pool_v = pool_v.at[0].set(1e3)
    table = jnp.asarray(1 + np.arange(S * NB).reshape(S, NB), jnp.int32)
    table = table.at[2, 0].set(table[0, 0])      # CoW fork
    table = table.at[1, 2].set(0)                # trash tail
    q = jnp.asarray(rng.normal(size=(S, w, h, dh)) * 0.5, dt)
    # per-slot last-visible positions; slot 1 must stay short of its
    # trash tail (chain tokens 8..11) for every window column
    base = jnp.asarray([[7], [5], [9]], jnp.int32)
    pos = base - (w - 1) + jnp.arange(w, dtype=jnp.int32)[None, :]
    return q, pool_k, pool_v, table, pos


def _paged_dense(q, pool_k, pool_v, table, pos):
    """Independent spelling: the decode_gather oracle followed by one
    dense masked softmax — exactly the materialization the paged op
    class exists to kill."""
    gather = get_kernel("decode_gather", "xla_ref").impl.call
    kb = gather(pool_k, table)
    vb = gather(pool_v, table)
    s = jnp.einsum("swhd,sthd->swht", q, kb,
                   preferred_element_type=jnp.float32)
    s = s * (1.0 / float(np.sqrt(q.shape[-1])))
    j = jnp.arange(kb.shape[1], dtype=jnp.int32)
    s = jnp.where(j[None, None, None, :] <= pos[:, :, None, None],
                  s, -1e30)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    l = jnp.sum(p, axis=-1)
    ctx = jnp.einsum("swht,sthd->swhd", p, vb.astype(jnp.float32))
    return (ctx / jnp.where(l == 0.0, 1.0, l)[..., None]).astype(q.dtype)


@pytest.mark.parametrize("backend", kernels.BACKENDS)
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("w", [1, 3])
def test_paged_oracle_parity(backend, dtype, w):
    """Every available backend matches the dense gather+softmax oracle
    within ORACLE_TOL — single-token decode (W=1) and the speculative
    verify window (W=3), CoW fork and trash masking included."""
    impl = _impl_or_skip("paged_attention", backend)
    q, pk, pv, tbl, pos = _paged_case(jnp.dtype(dtype), w=w)
    got = impl.call(q, pk, pv, tbl, pos)
    assert got.dtype == q.dtype and got.shape == q.shape
    assert _rel_err(got, _paged_dense(q, pk, pv, tbl, pos)) <= oracle_tol(
        "paged_attention", dtype, "fwd")


@pytest.mark.parametrize("backend", ["pallas_tpu", "triton"])
def test_paged_interpret_covers_kernel_logic(backend):
    """The TPU grid and GPU fori_loop lowerings run interpret-forced so
    their block-streaming logic is covered on CPU-only CI."""
    impl = get_kernel("paged_attention", backend).impl
    q, pk, pv, tbl, pos = _paged_case(jnp.float32, w=2)
    assert _rel_err(
        impl.call(q, pk, pv, tbl, pos, interpret=True),
        _paged_dense(q, pk, pv, tbl, pos)) <= oracle_tol(
            "paged_attention", "float32", "fwd")


def test_paged_block_step_invariance():
    """block_step is a pure schedule knob: every step width — including
    the clamped-to-chain one-wide-step spelling that takes the no-scan
    direct path — lands within the f32 oracle bound of the dense
    reference."""
    impl = get_kernel("paged_attention", "xla_ref").impl
    q, pk, pv, tbl, pos = _paged_case(jnp.float32, w=2)
    ref = _paged_dense(q, pk, pv, tbl, pos)
    tol = oracle_tol("paged_attention", "float32", "fwd")
    for bs in (None, 1, 2, 3, 99):
        assert _rel_err(impl.call(q, pk, pv, tbl, pos, block_step=bs),
                        ref) <= tol, bs


def test_paged_bit_exact_run_to_run():
    impl = get_kernel("paged_attention", "xla_ref").impl
    q, pk, pv, tbl, pos = _paged_case(jnp.float32)
    jf = jax.jit(lambda *a: impl.call(*a))
    assert bool(jnp.array_equal(jf(q, pk, pv, tbl, pos),
                                jf(q, pk, pv, tbl, pos)))


def test_paged_masking_ignores_future_and_trash_content():
    """Tokens past ``pos`` and the trash block never reach the output:
    corrupting them leaves the result bit-identical.  This invariant is
    what makes block-granular reservation and CoW forks safe — reserved
    tail blocks hold stale garbage by design."""
    impl = get_kernel("paged_attention", "xla_ref").impl
    q, pk, pv, tbl, pos = _paged_case(jnp.float32, w=1)
    base = impl.call(q, pk, pv, tbl, pos)
    # slot 0 (pos 7): chain block 2 entirely unused; slot 1 (pos 5):
    # tokens 6..7 of chain block 1 unused; slot 2 (pos 9): tokens
    # 10..11 of chain block 2 unused; trash block 0 always masked
    def corrupt(pool):
        return (pool.at[tbl[0, 2]].set(7e4)
                    .at[tbl[1, 1], 2:].set(7e4)
                    .at[tbl[2, 2], 2:].set(7e4)
                    .at[0].set(-9e4))
    again = impl.call(q, corrupt(pk), corrupt(pv), tbl, pos)
    assert bool(jnp.array_equal(base, again))


@pytest.mark.parametrize("backend", ["pallas_tpu", "xla_ref"])
def test_bit_exact_run_to_run_within_backend(backend):
    impl = _impl_or_skip("flash_attention", backend)
    q, k, v = _qkv(jnp.float32, 64, t=64)
    jf = jax.jit(lambda q, k, v: impl.call(q, k, v, causal=True,
                                           block_q=32, block_k=32))
    assert bool(jnp.array_equal(jf(q, k, v), jf(q, k, v)))


# -- registry unit suite -----------------------------------------------------

def test_precedence_explicit_arg_beats_env(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_KERNEL_BACKEND", "xla_ref")
    assert resolve_name("flash_attention") == "xla_ref"
    assert resolve_name("flash_attention", "pallas_tpu") == "pallas_tpu"


def test_precedence_per_op_env_beats_global(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_KERNEL_BACKEND", "xla_ref")
    monkeypatch.setenv("PADDLE_TPU_KERNEL_BACKEND_FLASH_ATTENTION",
                       "pallas_tpu")
    assert resolve_name("flash_attention") == "pallas_tpu"
    # the per-op pin does not leak to other op classes
    assert resolve_name("fused_ce") == "xla_ref"


def test_unknown_backend_raises():
    with pytest.raises(ValueError, match="unknown kernel backend"):
        resolve_name("flash_attention", "cuda_graphs")
    with pytest.raises(ValueError, match="unknown kernel backend"):
        with forced_backend("notabackend"):
            pass


def test_unavailable_backend_raises_with_reason():
    unavailable = [b for b, ok, _ in
                   available_backends("flash_attention") if not ok]
    if not unavailable:
        pytest.skip("every flash backend is available on this host")
    with pytest.raises(KernelUnavailable) as ei:
        resolve_name("flash_attention", unavailable[0])
    assert ei.value.reason


def test_global_env_fallback_to_auto(monkeypatch):
    # triton registers no decode_gather anywhere: a fleet-wide triton
    # pin must degrade that op to auto instead of crashing serving
    monkeypatch.setenv("PADDLE_TPU_KERNEL_BACKEND", "triton")
    assert resolve_name("decode_gather") in ("pallas_tpu", "xla_ref")


def test_global_env_fallback_counted_once_per_resolution(monkeypatch):
    """The degrade-to-auto path's accounting contract (ISSUE 14
    satellite): a global env pin an op cannot serve increments
    ``kernels.env_fallbacks`` EXACTLY once per resolution — no double
    count inside one resolve, no missed count across repeats — while a
    servable pin and a strict (raising) explicit request increment
    nothing."""
    from paddle_tpu.observability import get_registry

    reg = get_registry()

    def count():
        return int(reg.value("kernels.env_fallbacks") or 0)

    monkeypatch.setenv("PADDLE_TPU_KERNEL_BACKEND", "triton")
    c0 = count()
    assert resolve_name("decode_gather") in ("pallas_tpu", "xla_ref")
    assert count() == c0 + 1
    assert resolve_name("decode_gather") in ("pallas_tpu", "xla_ref")
    assert count() == c0 + 2
    # a pin the op CAN serve resolves directly: no fallback counted
    monkeypatch.setenv("PADDLE_TPU_KERNEL_BACKEND", "xla_ref")
    assert resolve_name("decode_gather") == "xla_ref"
    assert count() == c0 + 2
    # strict sources raise instead of degrading: still no count
    monkeypatch.delenv("PADDLE_TPU_KERNEL_BACKEND")
    with pytest.raises(KernelUnavailable):
        resolve_name("decode_gather", "triton")
    assert count() == c0 + 2


def test_forced_backend_scopes_and_restores():
    before = resolve_name("fused_ce")
    with forced_backend("xla_ref"):
        assert resolve_name("fused_ce") == "xla_ref"
    with forced_backend("xla_ref", op_class="fused_ce"):
        assert resolve_name("fused_ce") == "xla_ref"
        # op-scoped force does not leak across op classes
        assert resolve_name("flash_attention") == resolve_name(
            "flash_attention", None)
    assert resolve_name("fused_ce") == before


def test_selected_backends_recorded_per_compile():
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        from paddle_tpu.models import transformer

        outs = transformer.build(vocab_size=64, n_layer=1, n_head=2,
                                 d_model=32, max_len=16,
                                 dropout_rate=0.0, dtype="float32",
                                 fused_head=True)
    scope = pt.core.scope.Scope()
    pt.core.scope._scope_stack.append(scope)
    try:
        exe = pt.Executor()
        exe.run(startup, scope=scope)
        toks = np.zeros((2, 16), np.int64)
        exe.run(main, feed={"tokens": toks, "labels": toks},
                fetch_list=[outs["avg_cost"]], scope=scope)
        kb = (exe.last_step_cost or {}).get("kernel_backends")
        assert kb and kb.get("flash_attention") and kb.get("fused_ce")
        att = exe.last_attribution or {}
        assert f"|kb={kb['flash_attention']}" in att.get("workload", "")
    finally:
        pt.core.scope._scope_stack.pop()


def test_xla_ref_trainer_zero_pallas(monkeypatch):
    """The acceptance bar at toy scale: env-routed xla_ref GPT training
    step traces with zero pallas calls (the selftest covers all five
    memory_optimize policies)."""
    from paddle_tpu.analysis.jaxpr_tools import walk_report
    from paddle_tpu.models import transformer

    monkeypatch.setenv("PADDLE_TPU_KERNEL_BACKEND", "xla_ref")
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        outs = transformer.build(vocab_size=64, n_layer=2, n_head=2,
                                 d_model=32, max_len=16,
                                 dropout_rate=0.0, dtype="float32",
                                 fused_head=True)
        pt.memory_optimize(main, policy="selective")
    scope = pt.core.scope.Scope()
    pt.core.scope._scope_stack.append(scope)
    try:
        exe = pt.Executor()
        exe.run(startup, scope=scope)
        toks = np.zeros((2, 16), np.int64)
        loss = exe.run(main, feed={"tokens": toks, "labels": toks},
                       fetch_list=[outs["avg_cost"]], scope=scope)[0]
        assert np.isfinite(np.asarray(loss)).all()
        state_names = tuple(sorted(
            v.name for v in main.persistable_vars()
            if scope.find_var(v.name) is not None))
        step, _ = exe.lower(main, ["labels", "tokens"],
                            [outs["avg_cost"].name], state_names)
        state = {n: scope.get(n) for n in state_names}
        state[pt.core.scope.RNG_VAR] = scope.get(pt.core.scope.RNG_VAR)
        rep = walk_report(jax.make_jaxpr(step)(state, toks, toks))
        assert rep["pallas_total"] == 0
    finally:
        pt.core.scope._scope_stack.pop()


def test_timed_run_lint_fires_on_interpret_kernels():
    if jax.default_backend() == "tpu":
        pytest.skip("interpret planting needs a non-TPU host")
    from paddle_tpu.models import transformer

    def compile_under(env_backend):
        pt.core.unique_name.reset()
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            outs = transformer.build(
                vocab_size=64, n_layer=1, n_head=2, d_model=32,
                max_len=16, dropout_rate=0.0, dtype="float32",
                fused_head=True)
        scope = pt.core.scope.Scope()
        pt.core.scope._scope_stack.append(scope)
        try:
            if env_backend:
                os.environ["PADDLE_TPU_KERNEL_BACKEND"] = env_backend
            exe = pt.Executor()
            with kernels.timed_run():
                exe.run(startup, scope=scope)
                toks = np.zeros((2, 16), np.int64)
                exe.run(main, feed={"tokens": toks, "labels": toks},
                        fetch_list=[outs["avg_cost"]], scope=scope)
            return exe.last_step_cost or {}
        finally:
            os.environ.pop("PADDLE_TPU_KERNEL_BACKEND", None)
            pt.core.scope._scope_stack.pop()

    planted = compile_under(None)
    assert planted.get("interpret_in_timed_run") is True
    assert "jaxpr.kernel-backend" in (planted.get("lint_checks") or [])
    clean = compile_under("xla_ref")
    assert not clean.get("interpret_in_timed_run")
    assert "jaxpr.kernel-backend" not in (clean.get("lint_checks") or [])


# -- tuner integration -------------------------------------------------------

def test_attention_candidates_backend_dimension():
    from paddle_tpu.tune.space import attention_candidates, prune_static

    plain = attention_candidates(256, 64, 2)
    assert all("backend" not in c for c in plain)
    cands = attention_candidates(256, 64, 2,
                                 backends=("pallas_tpu", "xla_ref"))
    by_backend = {}
    for c in cands:
        by_backend.setdefault(c.get("backend"), []).append(c)
    assert set(by_backend) == {"pallas_tpu", "xla_ref"}
    # geometry-free backend contributes ONE candidate, not a cross
    assert len(by_backend["xla_ref"]) == 1
    # pruning keeps the xla_ref candidate (VMEM/roofline models are
    # Pallas-schedule models) while still vmem/roofline-pruning pallas
    surv, _pruned = prune_static(256, 64, 2, cands)
    assert any(c.get("backend") == "xla_ref" for c in surv)


def test_workload_key_backend_token():
    from paddle_tpu.tune.space import WorkloadKey

    plain = WorkloadKey("flash", 256, 64, 2, "bfloat16", "cpu",
                        remat="-")
    assert "kb=" not in plain.s
    keyed = WorkloadKey("flash", 256, 64, 2, "bfloat16", "cpu",
                        remat="-", backend="xla_ref")
    assert keyed.s.endswith("|kb=xla_ref")
    assert keyed.s.startswith(plain.s)


def test_tuned_winner_backend_reaches_flash_op():
    """A tuned config that persisted a kernel choice re-resolves on the
    hot path: multi_head_attention threads it into the flash op's
    ``backend`` attr."""
    from paddle_tpu import layers
    from paddle_tpu.tune import forced_attention_config

    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with forced_attention_config({"block_q": 128, "block_k": 128,
                                      "backend": "xla_ref"}):
            x = layers.data("x", shape=[2, 256, 64], dtype="float32")
            layers.multi_head_attention(x, x, x, d_model=64, n_head=1,
                                        causal=True)
    ops = [op for op in main.global_block().ops
           if op.type.startswith("flash_attention")]
    assert ops, "no flash op built"
    assert ops[0].attrs.get("backend") == "xla_ref"
    assert ops[0].attrs.get("block_q") == 128


def test_cache_fingerprint_covers_registry_surface(monkeypatch):
    from paddle_tpu.tune import cache as tcache

    base = tcache.geometry_fingerprint()
    # reordering a platform's auto preference changes what a cached
    # config resolves to -> the fingerprint must move
    monkeypatch.setitem(kernels.AUTO_ORDER, "cpu",
                        ("xla_ref", "pallas_tpu"))
    assert tcache.geometry_fingerprint() != base


def test_tune_search_measures_backend_candidate(tmp_path, monkeypatch):
    """Live regression for the backend-forced measurement window: a
    search over a backend-carrying candidate must build, compile,
    measure and persist the winner's kernel choice (the forced context
    is single-use — entering it per phase used to crash the search)."""
    from paddle_tpu.tune import reset_cache, tune_gpt_step

    monkeypatch.setenv("PADDLE_TPU_TUNE_CACHE",
                       str(tmp_path / "tuned.json"))
    monkeypatch.setenv("PADDLE_TPU_TUNE", "search")
    reset_cache()
    try:
        rep = tune_gpt_step(
            seq_len=32, n_layer=1, d_model=32, n_head=2, vocab=61,
            batch=4, dtype="float32", steps=1, warmup=0, repeats=1,
            block_caps=(32,), policies=("none",), accums=(1,),
            backends=("xla_ref", "triton"), max_measure=3,
            mode="search", force=True)
        assert rep["source"] == "search", rep
        measured = [m for m in rep["measured"]
                    if m.get("verdict") == "measured"]
        assert any(m.get("backend") == "xla_ref" for m in measured)
        if jax.default_backend() not in ("gpu", "cuda", "rocm"):
            # a triton REQUEST on a GPU-less host measures the auto
            # fallback — the record and any winner must carry the
            # backend that actually ran, never the unavailable request
            tr = [m for m in measured
                  if m.get("backend_requested") == "triton"]
            assert tr and all(m["backend"] != "triton" for m in tr), (
                measured)
        assert rep["entry"]["config"].get("backend") not in (None,
                                                             "triton")
    finally:
        reset_cache()


def test_truncate_survivors_keeps_every_backend():
    from paddle_tpu.tune.search import _truncate_survivors

    survivors = ([{"block_q": 64, "backend": "pallas_tpu", "roofline": 1.0}]
                 * 5 + [{"block_q": 64, "backend": "xla_ref"}])
    report = {}
    keep = _truncate_survivors(list(survivors), 3, report)
    assert any(c.get("backend") == "xla_ref" for c in keep)
    assert report["truncated_to"] == len(keep) == 4
    # no truncation -> untouched, no report key
    report2 = {}
    same = _truncate_survivors(list(survivors), 10, report2)
    assert len(same) == 6 and "truncated_to" not in report2


def test_paged_attention_candidates_geometry():
    from paddle_tpu.tune.space import paged_attention_candidates

    cands = paged_attention_candidates(3)
    xr = [c for c in cands if c["backend"] == "xla_ref"]
    # the default steps clamp to the 3-block chain and dedupe:
    # (1, 2, 4, 8) -> (1, 2, 3)
    assert sorted(c["block_step"] for c in xr) == [1, 2, 3]
    fixed = [c for c in cands if c["backend"] != "xla_ref"]
    # the TPU/GPU lowerings fix their own iteration shape: one
    # candidate each, no geometry cross
    assert {c["backend"] for c in fixed} == {"pallas_tpu", "triton"}
    assert all(c["block_step"] is None for c in fixed)


def test_tune_paged_attention_search_and_hot_path_lookup(tmp_path,
                                                        monkeypatch):
    """op=paged_attention end to end: a search measures xla_ref
    block-step candidates on a synthetic ragged pool, persists the
    winner, and ``tune.paged_attention_config`` (the lookup
    ``serving.batched_decode`` consults at trace time) serves it from a
    fresh cache read."""
    from paddle_tpu import tune
    from paddle_tpu.tune import reset_cache
    from paddle_tpu.tune.search import tune_paged_attention

    monkeypatch.setenv("PADDLE_TPU_TUNE_CACHE",
                       str(tmp_path / "tuned.json"))
    monkeypatch.setenv("PADDLE_TPU_TUNE", "search")
    reset_cache()
    try:
        rep = tune_paged_attention(
            n_head=2, d_head=16, max_len=16, block_tokens=4, slots=2,
            block_steps=(1, 2), backends=("xla_ref",), max_measure=4,
            repeats=1, force=True, mode="search")
        assert rep["source"] == "search", rep
        measured = [m for m in rep["measured"]
                    if m.get("verdict") == "measured"]
        assert len(measured) == 2
        cfg = rep["entry"]["config"]
        assert cfg["backend"] == "xla_ref"
        assert cfg["block_step"] in (1, 2)
        reset_cache()   # force a disk read: the entry persisted
        got = tune.paged_attention_config(16, 16, 2, "float32")
        assert got == cfg
        # cached mode on a MISS never compiles (and never invents)
        assert tune.paged_attention_config(999, 16, 2, "float32") is None
    finally:
        reset_cache()
