"""Run-telemetry subsystem: registry semantics, JSONL round trip,
executor compile/cache instrumentation, trainer step telemetry and the
MetricsReporter event handler."""

import math
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.observability import (
    Histogram, MetricsRegistry, MetricsReporter, RunLog, get_registry,
    hardware, read_jsonl,
)


# -- registry ---------------------------------------------------------------
def test_counter_gauge_histogram_semantics():
    reg = MetricsRegistry()
    c = reg.counter("c")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    # get-or-create returns the same object
    assert reg.counter("c") is c

    g = reg.gauge("g", shard="1")
    g.set(4)
    g.inc()
    g.dec(2)
    assert g.value == 3
    g.set_max(10)
    g.set_max(5)
    assert g.value == 10
    # labels are part of identity
    assert reg.gauge("g", shard="2") is not g

    h = reg.histogram("h")
    for v in [1.0, 2.0, 3.0, 4.0]:
        h.observe(v)
    assert h.count == 4 and h.total == 10.0
    assert h.min == 1.0 and h.max == 4.0 and h.mean == 2.5
    assert h.percentile(50) == 2.0
    snap = h.snapshot()
    assert snap["count"] == 4 and snap["p50"] == 2.0

    # name re-registered as a different kind is an error
    with pytest.raises(TypeError):
        reg.gauge("c")


def test_registry_reset_and_snapshot():
    reg = MetricsRegistry()
    c = reg.counter("x.n")
    h = reg.histogram("x.t")
    c.inc(5)
    h.observe(0.25)
    snap = reg.snapshot()
    assert snap["x.n"] == 5
    assert snap["x.t"]["count"] == 1
    reg.reset()
    # held handles stay valid and read zero
    assert c.value == 0 and h.count == 0
    assert math.isnan(h.percentile(50))
    reg.clear(prefix="x.t")
    assert reg.get("x.t") is None and reg.get("x.n") is not None


def test_prometheus_exposition_format():
    reg = MetricsRegistry()
    reg.counter("executor.compile_count").inc(3)
    reg.gauge("master.todo_depth", shard="0").set(7)
    h = reg.histogram("trainer.step_seconds")
    for i in range(10):
        h.observe(0.01 * (i + 1))
    text = reg.to_text()
    assert "# TYPE executor_compile_count counter" in text
    assert "executor_compile_count 3" in text
    assert 'master_todo_depth{shard="0"} 7' in text
    assert "# TYPE trainer_step_seconds summary" in text
    assert 'trainer_step_seconds{quantile="0.5"}' in text
    assert "trainer_step_seconds_count 10" in text
    assert "trainer_step_seconds_sum" in text


def test_metrics_http_endpoint():
    import urllib.request

    from paddle_tpu.observability import start_metrics_server

    reg = MetricsRegistry()
    reg.counter("scrape.me").inc(42)
    server = start_metrics_server(0, reg)
    try:
        port = server.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        assert "scrape_me 42" in body
    finally:
        server.shutdown()


# -- runlog -----------------------------------------------------------------
def test_runlog_jsonl_round_trip(tmp_path):
    p = str(tmp_path / "run.jsonl")
    with RunLog(p) as log:
        log.log("step", batch_id=0, cost=np.float32(1.5),
                arr=np.arange(3), nan=float("nan"))
        log.log("pass", pass_id=0, wall_time=1.25)
    recs = read_jsonl(p)
    assert [r["event"] for r in recs] == ["step", "pass"]
    assert recs[0]["cost"] == 1.5
    assert recs[0]["arr"] == [0, 1, 2]
    assert isinstance(recs[0]["nan"], str)  # stringified, not bare NaN
    assert recs[1]["wall_time"] == 1.25
    assert read_jsonl(p, event="pass")[0]["pass_id"] == 0
    # truncated tail line (crashed writer) is tolerated
    with open(p, "a") as fh:
        fh.write('{"event": "step", "trunca')
    assert len(read_jsonl(p)) == 2


# -- executor instrumentation ----------------------------------------------
def _tiny_program():
    x = layers.data("x", shape=[4])
    y = layers.fc(x, 2)
    return x, y


def test_executor_compile_counter_and_cache_hit():
    reg = get_registry()
    c0 = reg.value("executor.compile_count")
    _x, y = _tiny_program()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {"x": np.zeros((2, 4), np.float32)}
    exe.run(feed=feed, fetch_list=[y])
    # startup + main step = two fresh compiles
    assert reg.value("executor.compile_count") >= c0 + 2
    sc = exe.last_step_cost
    assert sc["cache_hit"] is False
    assert sc["compile_seconds"] > 0
    exe.run(feed=feed, fetch_list=[y])
    assert exe.last_step_cost["cache_hit"] is True
    # cache hit does not recompile
    assert reg.value("executor.compile_count") == c0 + 2


def test_executor_cost_analysis_flops():
    _x, y = _tiny_program()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    exe.run(feed={"x": np.ones((8, 4), np.float32)}, fetch_list=[y])
    sc = exe.last_step_cost
    # fc(8x4 @ 4x2) is at least 2*8*4*2 = 128 flops
    assert sc["flops"] is not None and sc["flops"] >= 128
    assert sc["bytes_accessed"] is not None and sc["bytes_accessed"] > 0


def test_run_steps_records_scan_cost():
    _x, y = _tiny_program()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {"x": np.ones((3, 2, 4), np.float32)}
    exe.run_steps(feed=feed, fetch_list=[y])
    sc = exe.last_step_cost
    assert sc["steps"] == 3 and sc["cache_hit"] is False
    exe.run_steps(feed=feed, fetch_list=[y])
    assert exe.last_step_cost["cache_hit"] is True


# -- hardware accounting ----------------------------------------------------
def test_mfu_and_peak_flops():
    assert hardware.mfu(1e9, 0.001, 1e12) == pytest.approx(1.0)
    assert hardware.mfu(None, 0.001, 1e12) is None
    assert hardware.mfu(1e9, 0, 1e12) is None
    # CPU devices resolve to the nominal peak so MFU stays defined
    import jax

    assert hardware.device_peak_flops(jax.devices()[0]) > 0
    assert hardware.total_peak_flops() > 0


def test_sample_memory_cpu_is_graceful():
    # CPU backends report no memory stats: no gauges, empty dict, no crash
    reg = MetricsRegistry()
    out = hardware.sample_memory(reg)
    assert out == {} or "bytes_in_use" in out


# -- trainer telemetry ------------------------------------------------------
def _lenet_trainer(extra_fetch=True):
    from paddle_tpu.models import lenet

    model = lenet.build(learning_rate=0.01)
    fetch = [model["accuracy"]] if extra_fetch else []
    return pt.trainer.Trainer(model["avg_cost"], model["feed"],
                              extra_fetch=fetch)


def _mnist_reader(batches=4, batch=8, seed=0):
    rng = np.random.default_rng(seed)

    def reader():
        for _ in range(batches):
            yield [
                (rng.normal(size=(1, 28, 28)).astype(np.float32),
                 int(rng.integers(0, 10)))
                for _ in range(batch)
            ]

    return reader


def test_end_iteration_carries_telemetry():
    trainer = _lenet_trainer()
    events = []
    trainer.train(_mnist_reader(), num_passes=1,
                  event_handler=lambda e: events.append(e))
    ends = [e for e in events if isinstance(e, pt.trainer.EndIteration)]
    assert len(ends) == 4
    for ev in ends:
        assert ev.wall_time > 0
        assert ev.samples == 8
        assert ev.throughput == pytest.approx(8 / ev.wall_time)
        assert ev.reader_wait >= 0
        assert ev.step_cost is not None
    # first step compiles, later steps hit the cache
    assert ends[0].step_cost["cache_hit"] is False
    assert ends[-1].step_cost["cache_hit"] is True
    # flops-based MFU is defined on CPU (nominal peak) and sane
    assert ends[-1].mfu is None or 0 <= ends[-1].mfu <= 1.5
    # reader stall gauge was published
    assert get_registry().get("trainer.reader_wait_seconds") is not None


def test_metrics_reporter_jsonl(tmp_path):
    p = str(tmp_path / "run.jsonl")
    lines = []
    reporter = MetricsReporter(log_every_n=2, jsonl_path=p,
                               print_fn=lines.append)
    trainer = _lenet_trainer()
    trainer.train(_mnist_reader(batches=5), num_passes=1,
                  event_handler=reporter)
    reporter.close()
    steps = read_jsonl(p, event="step")
    assert len(steps) == 5
    for rec in steps:
        assert rec["wall_time"] > 0
        assert rec["throughput"] > 0
        assert rec["samples"] == 8
        assert rec["compile_count"] >= 1
        assert "mfu" in rec and "reader_wait" in rec
    assert steps[0]["cache_hit"] is False
    assert steps[-1]["cache_hit"] is True
    passes = read_jsonl(p, event="pass")
    assert len(passes) == 1 and passes[0]["samples"] == 40
    # periodic one-line summaries fired (batches 0, 2, 4 + pass line)
    assert sum("cost=" in ln for ln in lines) == 3


def test_metrics_reporter_chain(tmp_path):
    seen = []
    reporter = MetricsReporter(log_every_n=0,
                               jsonl_path=str(tmp_path / "r.jsonl"))
    trainer = _lenet_trainer()
    trainer.train(_mnist_reader(batches=2), num_passes=1,
                  event_handler=reporter.chain(seen.append))
    reporter.close()
    assert sum(isinstance(e, pt.trainer.EndIteration) for e in seen) == 2


# -- profiler fold-in -------------------------------------------------------
def test_print_profiler_percent_column_and_strict_key(capsys):
    from paddle_tpu import profiler

    profiler.reset_profiler()
    with profiler.timer("phase_a"):
        pass
    with profiler.timer("phase_a"):
        pass
    with profiler.timer("phase_b"):
        pass
    table = profiler.print_profiler(sorted_key="calls")
    assert "%" in table.splitlines()[0]
    assert "phase_a" in table and "phase_b" in table
    # one aggregation path: the same timers live in the metrics registry
    h = get_registry().get("host_timer.phase_a")
    assert h is not None and h.count == 2
    with pytest.raises(ValueError):
        profiler.print_profiler(sorted_key="bogus")
    profiler.reset_profiler()
    assert get_registry().get("host_timer.phase_a") is None


# -- distributed surfaces ---------------------------------------------------
def test_master_metrics_surface(tmp_path):
    from paddle_tpu.distributed.master import MasterService
    from paddle_tpu.native import recordio

    path = str(tmp_path / "data.rio")
    w = recordio.Writer(path)
    for i in range(4):
        w.write(f"rec{i}".encode())
    w.close()

    # own registry: the global one accumulates across the suite's other
    # distributed tests
    svc = MasterService(timeout_sec=60, registry=MetricsRegistry())
    svc.set_dataset([path])
    m = svc.metrics()
    assert m["todo_depth"] >= 1 and m["pending_depth"] == 0
    task = svc.get_task()
    m = svc.metrics()
    assert m["pending_depth"] == 1
    assert m["tasks_dispatched"] == 1
    svc.task_finished(task["id"])
    m = svc.metrics()
    assert m["tasks_finished"] == 1 and m["pending_depth"] == 0
    assert m["last_contact_age_sec"] < 60


def test_pserver_metrics_surface():
    from paddle_tpu.distributed.pserver import ParameterServer, PServerClient

    ps = ParameterServer(index=0, num_trainers=1,
                         registry=MetricsRegistry())
    with PServerClient([ps]) as client:
        client.init_params({"w": np.zeros((4, 2), np.float32)},
                           optimizer="sgd", lr=0.1)
        client.send_grads({"w": np.ones((4, 2), np.float32)})
        m = ps.metrics()
    assert m["param_count"] == 1
    assert m["param_bytes"] == 4 * 2 * 4
    assert m["updates_applied"] == 1
    assert m["grads_received"] == 1
    assert m["last_update_age_sec"] < 60


# -- inference latency ------------------------------------------------------
def test_inference_engine_latency_histogram(tmp_path):
    reg = get_registry()
    reg.clear(prefix="inference.")
    d = str(tmp_path / "model")
    x, y = _tiny_program()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    pt.io.save_inference_model(d, ["x"], [y], exe)
    engine = pt.inference.InferenceEngine(d)
    for _ in range(3):
        engine.run(feed={"x": np.zeros((1, 4), np.float32)})
    assert reg.value("inference.requests") == 3
    h = reg.get("inference.run_seconds")
    assert h is not None and h.count == 3
    assert h.snapshot()["p50"] > 0
