"""cross_entropy_over_beam (ops/beam_ce_ops.py + v1 DSL surface) vs an
independent numpy implementation of the reference algorithm
(gserver/layers/CrossEntropyOverBeam.cpp: gold tracking, total path
expansion with parent backtracking, gold-as-extra-path when it falls
off, softmax over path scores)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.beam_ce_ops import cross_entropy_over_beam_fn

from op_test import run_op


def golden_one(scores, ids, gold):
    """Reference algorithm, plain python loops.  scores[e] [R,L] float;
    ids[e] [R,B] int (-1 pad); gold[e] int.  Returns scalar loss."""
    E = len(ids)
    # gold tracking (calValidExpandStep)
    gold_rows, gold_cols = [], []
    valid_cnt = 0
    for e in range(E):
        if e == 0:
            gr = 0
        else:
            prev = ids[e - 1].reshape(-1)
            upto = gold_rows[e - 1] * ids[e - 1].shape[1] + gold_cols[e - 1]
            gr = int(np.sum(prev[:upto] != -1))
        row = ids[e][gr]
        gc = -1
        for j, v in enumerate(row):
            if v == gold[e]:
                gc = j
                break
        gold_rows.append(gr)
        gold_cols.append(gc)
        valid_cnt += 1
        if gc == -1:
            break
    t = valid_cnt - 1
    fell = gold_cols[t] == -1

    # enumerate complete paths through expansions 0..t (reference
    # constructTotalExpansion): each valid slot of expansion t is a path;
    # row r of expansion e+1 = the r-th valid candidate of expansion e.
    paths = []  # list of per-path [slot_e for e in 0..t]
    R, B = ids[t].shape
    for r in range(R):
        for j in range(B):
            if ids[t][r, j] == -1:
                continue
            slots = [None] * (t + 1)
            slots[t] = (r, j)
            parent = r
            for e in range(t - 1, -1, -1):
                flat_e = ids[e].reshape(-1)
                valid_pos = [q for q in range(flat_e.shape[0])
                             if flat_e[q] != -1]
                q = valid_pos[parent]
                slots[e] = (q // ids[e].shape[1], q % ids[e].shape[1])
                parent = q // ids[e].shape[1]
            paths.append(slots)
    path_scores = []
    gold_idx = None
    for p, slots in enumerate(paths):
        s = 0.0
        for e, (r, j) in enumerate(slots):
            s += float(scores[e][r, ids[e][r, j]])
        path_scores.append(s)
        if not fell and slots[t] == (gold_rows[t], gold_cols[t]):
            gold_idx = p
    if fell:
        s = sum(float(scores[e][gold_rows[e], gold[e]])
                for e in range(t + 1))
        path_scores.append(s)
        gold_idx = len(path_scores) - 1
    ps = np.asarray(path_scores, np.float64)
    m = ps.max()
    lse = m + np.log(np.exp(ps - m).sum())
    return lse - ps[gold_idx]


def _tracked_case(rng, E, R, B, L, batch, fall_at=None):
    """Random but CONSISTENT beams: expansion e+1 has exactly one row
    per valid candidate of expansion e (unused rows all -1), and the
    gold is chosen along the actual tracked gold row at every step (so
    multi-step survival is exercised); ``fall_at`` forces the gold off
    the beam at that step."""
    scores, ids, gold = [], [], []
    # per-sample valid-candidate count of the previous expansion
    n_rows = [1] * batch
    for e in range(E):
        rows = 1 if e == 0 else R
        scores.append(rng.normal(size=(batch, rows, L)).astype(np.float32))
        iD = np.full((batch, rows, B), -1, np.int64)
        for b in range(batch):
            active = min(n_rows[b], rows)
            # keep V_e <= rows(e+1) while giving every active row >= 1
            budget = max(R if e + 1 < E else active * B, active)
            total = 0
            for r in range(active):
                remaining = active - r - 1
                kmax = min(B, budget - total - remaining)
                k = int(rng.integers(1, kmax + 1))
                iD[b, r, :k] = rng.choice(L, size=k, replace=False)
                total += k
            n_rows[b] = total
        ids.append(iD)
        gold.append(np.zeros((batch,), np.int64))
    for b in range(batch):
        gr, gc = 0, -1
        for e in range(E):
            row = ids[e][b, gr]
            if fall_at is not None and e == fall_at:
                g = L - 1
                while g in row:
                    g -= 1
                gold[e][b] = g
                break
            valid = row[row != -1]
            pick = int(valid[rng.integers(0, len(valid))])
            gold[e][b] = pick
            gc = int(np.where(row == pick)[0][0])
            if e + 1 < E:
                prev_flat = ids[e][b].reshape(-1)
                upto = gr * ids[e].shape[2] + gc
                gr = int(np.sum(prev_flat[:upto] != -1))
    return scores, ids, gold


@pytest.mark.parametrize("fall_at", [None, 1, 0])
def test_beam_ce_matches_golden(fall_at):
    rng = np.random.default_rng(0 if fall_at is None else 10 + fall_at)
    E, R, B, L, batch = 3, 4, 3, 6, 5
    scores, ids, gold = _tracked_case(rng, E, R, B, L, batch,
                                      fall_at=fall_at)
    got = np.asarray(cross_entropy_over_beam_fn(
        [jnp.asarray(s) for s in scores],
        [jnp.asarray(i) for i in ids],
        [jnp.asarray(g) for g in gold]))
    for b in range(batch):
        ref = golden_one([s[b] for s in scores], [i[b] for i in ids],
                         [int(g[b]) for g in gold])
        np.testing.assert_allclose(got[b], ref, rtol=1e-5, atol=1e-5,
                                   err_msg=f"sample {b} fall_at={fall_at}")


def test_beam_ce_single_expansion_equals_softmax_ce():
    """One expansion, one row: the cost reduces to plain softmax cross
    entropy over the selected candidates' scores."""
    rng = np.random.default_rng(3)
    L, B = 8, 4
    s = rng.normal(size=(1, 1, L)).astype(np.float32)
    ids = np.asarray([[[1, 4, 6, 2]]], np.int64)
    gold = np.asarray([4], np.int64)
    got = float(np.asarray(cross_entropy_over_beam_fn(
        [jnp.asarray(s)], [jnp.asarray(ids)], [jnp.asarray(gold)]))[0])
    sel = s[0, 0, [1, 4, 6, 2]]
    ref = -np.log(np.exp(sel[1]) / np.exp(sel).sum())
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_beam_ce_gradients_flow_to_scores():
    """Grad wrt scores == softmax-minus-onehot scattered along paths
    (checked against numeric finite differences of the golden)."""
    rng = np.random.default_rng(4)
    E, R, B, L = 2, 3, 2, 5
    scores, ids, gold = _tracked_case(rng, E, R, B, L, batch=1)

    def loss_fn(*flat_scores):
        return cross_entropy_over_beam_fn(
            list(flat_scores), [jnp.asarray(i) for i in ids],
            [jnp.asarray(g) for g in gold])[0]

    grads = jax.grad(loss_fn, argnums=tuple(range(E)))(
        *[jnp.asarray(s) for s in scores])
    eps = 1e-3
    for e in range(E):
        g_num = np.zeros_like(scores[e])
        for idx in np.ndindex(scores[e].shape):
            up = scores[e].copy(); up[idx] += eps
            dn = scores[e].copy(); dn[idx] -= eps
            su = [s if k != e else up for k, s in enumerate(scores)]
            sd = [s if k != e else dn for k, s in enumerate(scores)]
            fu = golden_one([s[0] for s in su], [i[0] for i in ids],
                            [int(g[0]) for g in gold])
            fd = golden_one([s[0] for s in sd], [i[0] for i in ids],
                            [int(g[0]) for g in gold])
            g_num[idx] = (fu - fd) / (2 * eps)
        np.testing.assert_allclose(np.asarray(grads[e]), g_num,
                                   atol=2e-3, err_msg=f"expansion {e}")


def test_beam_ce_op_and_v1_layer():
    """The registered op and the v1 DSL surface produce the golden."""
    import paddle_tpu as pt
    from paddle_tpu.compat import v1_ext as v1x

    rng = np.random.default_rng(5)
    E, R, B, L, batch = 2, 3, 2, 5, 3
    scores, ids, gold = _tracked_case(rng, E, R, B, L, batch)
    out = run_op("cross_entropy_over_beam",
                 {"Scores": scores, "Ids": ids,
                  "Gold": [g[:, None] for g in gold]})
    for b in range(batch):
        ref = golden_one([s[b] for s in scores], [i[b] for i in ids],
                         [int(g[b]) for g in gold])
        np.testing.assert_allclose(out["Out"][b, 0], ref, rtol=1e-5,
                                   atol=1e-5)

    # v1 DSL: BeamInput + cross_entropy_over_beam build a program
    feeds = {}
    beam_inputs = []
    for e in range(E):
        rows = scores[e].shape[1]
        sc = pt.layers.data(f"sc{e}", shape=[rows, L], dtype="float32")
        idv = pt.layers.data(f"id{e}", shape=[rows, B], dtype="int64")
        gv = pt.layers.data(f"g{e}", shape=[1], dtype="int64")
        feeds[f"sc{e}"] = scores[e]
        feeds[f"id{e}"] = ids[e]
        feeds[f"g{e}"] = gold[e][:, None]
        beam_inputs.append(v1x.BeamInput(candidate_scores=sc,
                                         selected_candidates=idv,
                                         gold=gv))
    cost = v1x.cross_entropy_over_beam(input=beam_inputs)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    (loss,) = exe.run(feed=feeds, fetch_list=[cost])
    for b in range(batch):
        ref = golden_one([s[b] for s in scores], [i[b] for i in ids],
                         [int(g[b]) for g in gold])
        np.testing.assert_allclose(loss[b, 0], ref, rtol=1e-5, atol=1e-5)
