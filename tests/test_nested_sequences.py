"""Nested (2-level LoD) sequences — the reference's recursively nested
sequence type (``lod_tensor.h:58`` LoD = vector of levels;
``Argument.subSequenceStartPositions``, Argument.h:84-86) carried as
padded [b, s, t, ...] + ``@LENGTH`` [b] + ``@SUBLENGTH`` [b, s].

The hierarchical-RNN golden follows the reference's
``gserver/tests/sequence_nest_rnn.conf`` / test_RecurrentGradientMachine
equivalence: a nested RNN whose outer memory boots each sub-sequence's
inner RNN equals a FLAT RNN over the concatenated sequence.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.compat import v1

from tests.op_test import run_op

rng = np.random.RandomState(7)


def _nested_batch(b=3, s=4, t=5, d=2, seed=0):
    r = np.random.RandomState(seed)
    X = r.randn(b, s, t, d).astype(np.float32)
    Length = np.array([4, 2, 3][:b], np.int32)
    SubLength = r.randint(1, t + 1, (b, s)).astype(np.int32)
    SubLength *= (np.arange(s)[None, :] < Length[:, None])
    return X, Length, SubLength


# ------------------------------------------------------------------- ops
def test_nested_sequence_pool_matches_loops():
    X, L, SL = _nested_batch()
    for pt_ in ("SUM", "AVERAGE", "MAX", "LAST", "FIRST", "SQRT"):
        got = run_op("nested_sequence_pool",
                     {"X": X, "Length": L, "SubLength": SL},
                     attrs={"pooltype": pt_})["Out"]
        b, s = X.shape[:2]
        exp = np.zeros((b, s, X.shape[-1]), np.float32)
        for i in range(b):
            for j in range(L[i]):
                seg = X[i, j, :SL[i, j]]
                if seg.size == 0:
                    continue
                if pt_ == "SUM":
                    exp[i, j] = seg.sum(0)
                elif pt_ == "AVERAGE":
                    exp[i, j] = seg.mean(0)
                elif pt_ == "SQRT":
                    exp[i, j] = seg.sum(0) / np.sqrt(len(seg))
                elif pt_ == "MAX":
                    exp[i, j] = seg.max(0)
                elif pt_ == "LAST":
                    exp[i, j] = seg[-1]
                elif pt_ == "FIRST":
                    exp[i, j] = seg[0]
        np.testing.assert_allclose(got, exp, rtol=1e-5, atol=1e-6,
                                   err_msg=pt_)


def test_nested_sequence_expand_and_slice():
    X, L, SL = _nested_batch()
    b, s, t, d = X.shape
    vals = rng.randn(b, s, d).astype(np.float32)
    got = run_op("nested_sequence_expand",
                 {"X": vals, "Y": X, "Length": L, "SubLength": SL})["Out"]
    assert got.shape == (b, s, t, d)
    for i in range(b):
        for j in range(s):
            n = SL[i, j] if j < L[i] else 0
            np.testing.assert_allclose(
                got[i, j, :n], np.tile(vals[i, j], (n, 1)), rtol=1e-6)
            np.testing.assert_allclose(got[i, j, n:], 0.0)

    off = np.array([1, 0, 1], np.int32)
    size = np.array([2, 1, 1], np.int32)
    sl = run_op("nested_sequence_slice",
                {"X": X, "Offset": off, "Size": size,
                 "Length": L, "SubLength": SL})
    for i in range(b):
        for j in range(size[i]):
            np.testing.assert_allclose(sl["Out"][i, j], X[i, off[i] + j])
            assert sl["OutSubLength"][i, j] == SL[i, off[i] + j]
        assert sl["OutLength"][i] == size[i]
        np.testing.assert_allclose(sl["Out"][i, size[i]:], 0.0)

    # out-of-table request: fewer sub-seqs come back, never a silently
    # duplicated clamp
    oob = run_op("nested_sequence_slice",
                 {"X": X, "Offset": np.array([3, 0, 0], np.int32),
                  "Size": np.array([3, 1, 1], np.int32),
                  "Length": L, "SubLength": SL})
    assert oob["OutLength"][0] == 1  # only sub-seq 3 exists past offset 3
    np.testing.assert_allclose(oob["Out"][0, 0], X[0, 3])
    np.testing.assert_allclose(oob["Out"][0, 1:], 0.0)


def test_sub_nested_seq_selects_sentences():
    X, L, SL = _nested_batch()
    idx = np.array([[2, 0], [1, -1], [0, 2]], np.int32)
    got = run_op("sub_nested_seq",
                 {"X": X, "Indices": idx, "Length": L, "SubLength": SL})
    for i in range(X.shape[0]):
        for k in range(idx.shape[1]):
            if idx[i, k] < 0:
                np.testing.assert_allclose(got["Out"][i, k], 0.0)
                assert got["OutSubLength"][i, k] == 0
            else:
                np.testing.assert_allclose(got["Out"][i, k], X[i, idx[i, k]])
                assert got["OutSubLength"][i, k] == SL[i, idx[i, k]]
    np.testing.assert_array_equal(got["OutLength"], [2, 1, 2])


def test_nested_rnn_equals_flat_gru_over_concatenation():
    """The reference nested-RNN equivalence (sequence_nest_rnn.conf spec):
    outer memory boots each sub-sequence's inner RNN, so the nested run
    over a split sequence == flat GRU over the concatenation."""
    b, s, t, d = 2, 3, 4, 5
    r = np.random.RandomState(1)
    W = r.randn(d, 3 * d).astype(np.float32) * 0.3
    Bias = r.randn(1, 3 * d).astype(np.float32) * 0.1
    SL = np.array([[4, 2, 3], [3, 4, 0]], np.int32)
    L = np.array([3, 2], np.int32)
    X = r.randn(b, s, t, 3 * d).astype(np.float32) * 0.5

    out = run_op("nested_rnn",
                 {"Input": X, "Weight": W, "Bias": Bias,
                  "Length": L, "SubLength": SL})

    # flat reference: concatenate each sample's valid items, run the gru
    # op over the packed sequence, compare the final + per-boundary states
    flat_len = np.array([int(SL[i, :L[i]].sum()) for i in range(b)],
                        np.int32)
    T = int(flat_len.max())
    flat = np.zeros((b, T, 3 * d), np.float32)
    for i in range(b):
        pos = 0
        for j in range(L[i]):
            n = SL[i, j]
            flat[i, pos:pos + n] = X[i, j, :n]
            pos += n
    ref = run_op("gru", {"Input": flat, "Weight": W, "Bias": Bias,
                         "Length": flat_len})["Hidden"]
    for i in range(b):
        pos = 0
        for j in range(L[i]):
            n = SL[i, j]
            if n == 0:
                continue
            pos += n
            np.testing.assert_allclose(
                out["OuterHidden"][i, j], ref[i, pos - 1],
                rtol=1e-4, atol=1e-5,
                err_msg=f"sample {i} boundary {j}")


# ----------------------------------------------------- feeder + layer DSL
def test_data_feeder_nested():
    var = layers.data("para", shape=[3], dtype="float32", lod_level=2)
    feeder = pt.DataFeeder([var], pad_multiple=2)
    sample0 = [np.ones((2, 3)), np.full((3, 3), 2.0)]
    sample1 = [np.full((1, 3), 5.0)]
    feed = feeder.feed([(sample0,), (sample1,)])
    X = feed["para"]
    assert X.shape[0] == 2 and X.ndim == 4 and X.shape[-1] == 3
    np.testing.assert_array_equal(feed["para@LENGTH"], [2, 1])
    np.testing.assert_array_equal(feed["para@SUBLENGTH"][0, :2], [2, 3])
    np.testing.assert_array_equal(feed["para@SUBLENGTH"][1, :1], [1])
    np.testing.assert_allclose(X[0, 1, :3], 2.0)
    np.testing.assert_allclose(X[1, 0, :1], 5.0)
    np.testing.assert_allclose(X[1, 1], 0.0)

    # feature-only declaration must NOT cap sub-seq count at the feature
    # dim: a 5-sub-seq sample through shape=[3] keeps all 5
    many = [np.full((1, 3), float(i)) for i in range(5)]
    feed5 = pt.DataFeeder([var], pad_multiple=1).feed([(many,)])
    np.testing.assert_array_equal(feed5["para@LENGTH"], [5])
    assert feed5["para"].shape[1] == 5

    # declared static dims wider than the batch: data, @LENGTH and
    # @SUBLENGTH must still agree on [b, s, t]
    wide = layers.data("wide", shape=[8, 10, 3], dtype="float32",
                      lod_level=2)
    feed2 = pt.DataFeeder([wide], pad_multiple=2).feed(
        [(sample0,), (sample1,)])
    assert feed2["wide"].shape == (2, 8, 10, 3)
    assert feed2["wide@SUBLENGTH"].shape == (2, 8)
    from tests.op_test import run_op as _run
    pooled = _run("nested_sequence_pool",
                  {"X": feed2["wide"], "Length": feed2["wide@LENGTH"],
                   "SubLength": feed2["wide@SUBLENGTH"]},
                  attrs={"pooltype": "SUM"})["Out"]
    assert pooled.shape == (2, 8, 3)


def test_nested_layers_end_to_end_training():
    """Paragraph classifier: nested tokens -> fc to gates -> nested_rnn
    -> last outer state -> logits; trains (loss falls) under the
    Executor with DataFeeder-produced nested feeds."""
    d, vocab_d, h = 4, 4, 6
    para = layers.data("para", shape=[3, 5, vocab_d], dtype="float32",
                       lod_level=2, append_batch_size=True)
    label = layers.data("label", shape=[1], dtype="int64")
    gates = layers.fc(para, 3 * h, num_flatten_dims=3, bias_attr=False)
    layers.link_sequence(gates, para)
    gates.lod_level = 2
    gates.block.vars[gates.name + "@SUBLENGTH"] = para.sub_length_var()
    hidden, outer = layers.nested_rnn(gates, h)
    last = layers.sequence_pool(outer, "last")
    logits = layers.fc(last, 2)
    loss = layers.mean(
        layers.softmax_with_cross_entropy(logits, label))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    r = np.random.RandomState(0)
    X = r.randn(4, 3, 5, vocab_d).astype(np.float32)
    L = np.array([3, 2, 1, 3], np.int32)
    SL = r.randint(1, 6, (4, 3)).astype(np.int32)
    SL *= (np.arange(3)[None] < L[:, None])
    y = r.randint(0, 2, (4, 1)).astype(np.int64)
    feed = {"para": X, "para@LENGTH": L, "para@SUBLENGTH": SL, "label": y}
    losses = [float(np.asarray(exe.run(feed=feed,
                                       fetch_list=[loss])[0]).ravel()[0])
              for _ in range(15)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.9, losses


# ------------------------------------------------------------- v1 compat
def test_v1_nested_recurrent_group_matches_flat():
    """The reference nested-RNN book test (sequence_nest_rnn.conf):
    an outer recurrent_group over SubsequenceInput whose inner group
    boots from the outer memory must equal the flat recurrent_group
    over the concatenated sequence."""
    b, s, t, d = 2, 3, 4, 5
    r = np.random.RandomState(3)
    X = r.randn(b, s, t, d).astype(np.float32) * 0.5
    SL = np.array([[4, 2, 3], [3, 4, 0]], np.int32)
    L = np.array([3, 2], np.int32)

    def build_nested():
        para = layers.data("para", shape=[s, t, d], dtype="float32",
                           lod_level=2)

        def outer_step(sent):
            omem = v1.memory(name="outer", size=d)

            def inner_step(x_t):
                imem = v1.memory(name="inner", size=d, boot_layer=omem)
                nxt = v1.mixed_layer(
                    size=d,
                    input=[v1.full_matrix_projection(
                               x_t, size=d,
                               param_attr=pt.ParamAttr(name="w_in")),
                           v1.full_matrix_projection(
                               imem, size=d,
                               param_attr=pt.ParamAttr(name="w_rec"))],
                    act=v1.TanhActivation(), bias_attr=False,
                    name="inner")
                return nxt

            inner_out = v1.recurrent_group(inner_step, sent)
            lastv = v1.last_seq(inner_out)
            _ = v1.mixed_layer(size=d,
                               input=[v1.identity_projection(lastv)],
                               bias_attr=False, name="outer")
            return lastv

        out = v1.recurrent_group(outer_step, v1.SubsequenceInput(para))
        return v1.last_seq(out)

    def build_flat(T):
        seq = layers.data("seq", shape=[T, d], dtype="float32",
                          lod_level=1)

        def step(x_t):
            mem = v1.memory(name="m", size=d)
            nxt = v1.mixed_layer(
                size=d,
                input=[v1.full_matrix_projection(
                           x_t, size=d,
                           param_attr=pt.ParamAttr(name="w_in")),
                       v1.full_matrix_projection(
                           mem, size=d,
                           param_attr=pt.ParamAttr(name="w_rec"))],
                act=v1.TanhActivation(), bias_attr=False, name="m")
            return nxt

        out = v1.recurrent_group(step, seq)
        return v1.last_seq(out)

    # shared weights: fix the RNG so both programs initialize identically
    flat_len = np.array([int(SL[i, :L[i]].sum()) for i in range(b)],
                        np.int32)
    T = int(flat_len.max())
    flat = np.zeros((b, T, d), np.float32)
    for i in range(b):
        pos = 0
        for j in range(L[i]):
            n = SL[i, j]
            flat[i, pos:pos + n] = X[i, j, :n]
            pos += n

    def run(build, feed, seed):
        main, startup = pt.Program(), pt.Program()
        main.random_seed = startup.random_seed = seed
        with pt.program_guard(main, startup):
            fetch = build()
        scope = pt.Scope()
        exe = pt.Executor()
        exe.run(startup, scope=scope)
        (out,) = exe.run(main, feed=feed, fetch_list=[fetch], scope=scope)
        return np.asarray(out), scope

    got, scope_n = run(lambda: build_nested(),
                       {"para": X, "para@LENGTH": L, "para@SUBLENGTH": SL},
                       seed=11)
    ref, scope_f = run(lambda: build_flat(T),
                       {"seq": flat, "seq@LENGTH": flat_len}, seed=11)
    # identical seeds -> identical [d,d] weights in both programs
    np.testing.assert_allclose(
        np.asarray(scope_n.get("w_in")), np.asarray(scope_f.get("w_in")))
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_v1_sub_nested_seq_layer():
    X, L, SL = _nested_batch()

    def build():
        para = layers.data("para", shape=list(X.shape[1:]),
                           dtype="float32", lod_level=2)
        idx = layers.data("idx", shape=[2], dtype="int64")
        sel = v1.sub_nested_seq_layer(para, idx)
        return layers.nested_sequence_pool(sel, "sum")

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        fetch = build()
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)
    idx = np.array([[1, 0], [0, -1], [2, 1]], np.int64)
    (out,) = exe.run(
        main,
        feed={"para": X, "para@LENGTH": L, "para@SUBLENGTH": SL,
              "idx": idx},
        fetch_list=[fetch], scope=scope)
    out = np.asarray(out)
    for i in range(X.shape[0]):
        for k in range(2):
            if idx[i, k] < 0:
                np.testing.assert_allclose(out[i, k], 0.0)
            else:
                np.testing.assert_allclose(
                    out[i, k], X[i, idx[i, k], :SL[i, idx[i, k]]].sum(0),
                    rtol=1e-5, atol=1e-5)


def test_sub_nested_seq_bounds_checks():
    """Indices past the sample's real sub-seq count are padding, never
    an out-of-bounds read (was NaN data + overflowed sub-length)."""
    X, L, SL = _nested_batch()
    idx = np.array([[7, 0], [1, 5], [0, 99]], np.int32)
    got = run_op("sub_nested_seq",
                 {"X": X, "Indices": idx, "Length": L, "SubLength": SL})
    assert np.isfinite(got["Out"]).all()
    np.testing.assert_allclose(got["Out"][0, 0], 0.0)   # 7 >= L[0]=4
    np.testing.assert_allclose(got["Out"][2, 1], 0.0)   # 99 out of range
    assert got["OutSubLength"][0, 0] == 0
    assert got["OutSubLength"][2, 1] == 0
    np.testing.assert_array_equal(got["OutLength"], [1, 1, 1])
