"""Direct tests for the raw (structured / meta) ops — while,
conditional_block, scan_block, parallel_do, feed/fetch, print, save/load,
and the tensor-array trio — each exercised through a real Program +
Executor lowering (these ops splice sub-blocks, so an eager run_op cannot
drive them).  VERDICT r1 item 3 coverage for the raw-op tail."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from op_test import run_op


def _run(main, startup, feed, fetches, scope=None):
    scope = scope or pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)
    return exe.run(main, feed=feed, fetch_list=fetches, scope=scope), scope


def test_array_ops_direct():
    arr = np.zeros((4, 2, 3), np.float32)
    x = np.ones((2, 3), np.float32) * 5
    i = np.array([2], np.int64)
    got = run_op("array_write", {"X": x, "I": i, "Array": arr})
    assert np.abs(got["Out"][2] - 5).max() == 0 and got["Out"][0].max() == 0
    got2 = run_op("array_read", {"Array": got["Out"], "I": i})
    np.testing.assert_array_equal(got2["Out"], x)
    got3 = run_op("array_length", {"Array": arr})
    np.testing.assert_array_equal(got3["Out"], [4])


def test_while_op_accumulates():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        limit = layers.fill_constant(shape=[1], dtype="float32", value=5.0)
        i = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
        total = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
        from paddle_tpu.layers import control_flow as cf

        cond = layers.less_than(i, limit)
        w = cf.While(cond)
        with w.block():
            layers.sums([total, i], out=total)
            layers.increment(i, 1.0)
            layers.assign(layers.less_than(i, limit), cond)
    assert any(op.type == "while" for op in main.global_block().ops)
    (out, ival), _ = _run(main, startup, {}, [total, i])
    assert float(ival) == 5.0
    assert float(out) == 0 + 1 + 2 + 3 + 4


def test_conditional_block_both_branches():
    def build(flag):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", shape=[2], dtype="float32")
            cond = layers.fill_constant(shape=[1], dtype="bool", value=flag)
            out = layers.fill_constant(shape=[1, 2], dtype="float32",
                                       value=-1.0)
            blk = main.create_block()
            main.rollback()
            # sub-block: out = x * 10
            blk.append_op(
                type="scale", inputs={"X": [x.name]},
                outputs={"Out": [out.name]}, attrs={"scale": 10.0})
            main.current_block().append_op(
                type="conditional_block",
                inputs={"Cond": [cond.name]},
                outputs={"Out": [out.name]},
                attrs={"sub_block": blk.idx})
        assert any(op.type == "conditional_block"
                   for op in main.global_block().ops)
        (got,), _ = _run(main, startup,
                         {"x": np.array([[1.0, 2.0]], np.float32)}, [out])
        return got

    np.testing.assert_allclose(build(True), [[10.0, 20.0]])
    np.testing.assert_allclose(build(False), [[-1.0, -1.0]])


def test_scan_block_via_static_rnn():
    """scan_block through the StaticRNN builder: h_t = h_{t-1} + x_t."""
    from paddle_tpu.layers import control_flow as cf

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[3, 2], dtype="float32")  # [b, t, d]
        init = layers.fill_constant(shape=[2, 2], dtype="float32", value=0.0)
        rnn = cf.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            h = rnn.memory(init)
            nh = layers.elementwise_add(h, xt)
            rnn.update_memory(h, nh)
            rnn.step_output(nh)
        out = rnn()
    assert any(op.type == "scan_block" for op in main.global_block().ops)
    xv = np.arange(12, dtype=np.float32).reshape(2, 3, 2)
    (got,), _ = _run(main, startup, {"x": xv}, [out])
    np.testing.assert_allclose(got, np.cumsum(xv, axis=1), rtol=1e-6)


def test_parallel_do_inlines_block():
    from paddle_tpu.layers import control_flow as cf

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[2], dtype="float32")
        pd = cf.ParallelDo()
        with pd.do():
            xi = pd.read_input(x)
            y = layers.scale(xi, scale=3.0)
            pd.write_output(y)
    assert any(op.type == "parallel_do" for op in main.global_block().ops)
    xv = np.array([[1.0, -2.0]], np.float32)
    (got,), _ = _run(main, startup, {"x": xv}, [y])
    np.testing.assert_allclose(got, 3.0 * xv)


def test_feed_fetch_ops_are_program_noops():
    """feed/fetch ops exist for program parity (feed_fetch_method.h); a
    program carrying them lowers and runs — the jit boundary realizes
    them."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[2], dtype="float32")
        out = layers.scale(x, scale=2.0)
        blk = main.global_block()
        blk.append_op(type="feed", inputs={}, outputs={}, attrs={})
        blk.append_op(type="fetch", inputs={}, outputs={}, attrs={})
    assert any(op.type == "feed" for op in main.global_block().ops)
    assert any(op.type == "fetch" for op in main.global_block().ops)
    xv = np.array([[3.0, 4.0]], np.float32)
    (got,), _ = _run(main, startup, {"x": xv}, [out])
    np.testing.assert_allclose(got, 2.0 * xv)


def test_print_op_passes_through(capfd):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[2], dtype="float32")
        out = layers.Print(x, message="dbg") if hasattr(layers, "Print") \
            else None
        if out is None:
            blk = main.global_block()
            out = layers.scale(x, scale=1.0)
            blk.append_op(type="print", inputs={"In": [x.name]},
                          outputs={}, attrs={"message": "dbg"})
    assert any(op.type == "print" for op in main.global_block().ops)
    xv = np.array([[1.0, 2.0]], np.float32)
    (got,), _ = _run(main, startup, {"x": xv}, [out])
    np.testing.assert_allclose(got, xv)


def test_save_load_ops_raise_with_host_side_pointer():
    """save/load ops deliberately refuse to lower (host IO can't live in a
    compiled TPU program); the host-side io module is the carrier."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.fill_constant(shape=[1], dtype="float32", value=1.0)
        main.global_block().append_op(
            type="save", inputs={"X": [x.name]}, outputs={},
            attrs={"file_path": "/tmp/x"})
    assert any(op.type == "save" for op in main.global_block().ops)
    exe = pt.Executor()
    with pytest.raises(RuntimeError, match="save_persistables"):
        exe.run(main, feed={}, fetch_list=[x], scope=pt.Scope())

    main2 = pt.Program()
    with pt.program_guard(main2, pt.Program()):
        y = layers.fill_constant(shape=[1], dtype="float32", value=1.0)
        main2.global_block().append_op(
            type="load", inputs={}, outputs={"Out": [y.name]},
            attrs={"file_path": "/tmp/x"})
    assert any(op.type == "load" for op in main2.global_block().ops)
    with pytest.raises(RuntimeError, match="load_persistables"):
        pt.Executor().run(main2, feed={}, fetch_list=[y], scope=pt.Scope())
