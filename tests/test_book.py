"""End-to-end "book" acceptance tests (reference: fluid/tests/book/ — 12
model trainings that ARE the acceptance suite, SURVEY §4).  Each test builds
a model from paddle_tpu.models on tiny shapes, trains a few steps on
synthetic data, and asserts the loss goes down and stays finite."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.models import (
    ctr_dnn,
    deep_speech2,
    fit_a_line,
    label_semantic_roles,
    lenet,
    recommender,
    resnet,
    seq2seq,
    text_classification,
    vgg,
    word2vec,
)


def train_steps(outs, feeds, steps=5, extra_fetch=()):
    """Run `steps` batches of identical data; return loss per step."""
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    fetch = [outs["avg_cost"]] + list(extra_fetch)
    losses = []
    for _ in range(steps):
        vals = exe.run(feed=feeds, fetch_list=fetch)
        losses.append(float(np.asarray(vals[0]).ravel()[0]))
    losses = np.asarray(losses)
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"
    return losses


def ragged_int(batch, max_len, high, rng):
    """Padded int64 [batch, max_len] + lengths [batch]."""
    lens = rng.integers(2, max_len + 1, size=batch)
    data = np.zeros((batch, max_len), np.int64)
    for i, ln in enumerate(lens):
        data[i, :ln] = rng.integers(0, high, size=ln)
    return data, lens.astype(np.int32)


def test_fit_a_line():
    outs = fit_a_line.build(learning_rate=0.05)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 13)).astype(np.float32)
    w = rng.normal(size=(13, 1)).astype(np.float32)
    y = x @ w
    train_steps(outs, {"x": x, "y": y}, steps=8)


def test_recognize_digits_conv():
    outs = lenet.build(learning_rate=0.001)
    rng = np.random.default_rng(1)
    img = rng.normal(size=(8, 1, 28, 28)).astype(np.float32)
    label = rng.integers(0, 10, size=(8, 1)).astype(np.int64)
    train_steps(outs, {"img": img, "label": label}, steps=5,
                extra_fetch=[outs["accuracy"]])


@pytest.mark.slow
def test_image_classification_vgg():
    outs = vgg.build(depth=16, class_dim=4, image_shape=(3, 32, 32),
                     learning_rate=0.01)
    rng = np.random.default_rng(2)
    img = rng.normal(size=(4, 3, 32, 32)).astype(np.float32)
    label = rng.integers(0, 4, size=(4, 1)).astype(np.int64)
    train_steps(outs, {"img": img, "label": label}, steps=4)


def test_image_classification_resnet():
    outs = resnet.build(depth=20, class_dim=4, image_shape=(3, 32, 32),
                        learning_rate=0.05)
    rng = np.random.default_rng(3)
    img = rng.normal(size=(4, 3, 32, 32)).astype(np.float32)
    label = rng.integers(0, 4, size=(4, 1)).astype(np.int64)
    train_steps(outs, {"img": img, "label": label}, steps=4)


def test_word2vec():
    outs = word2vec.build(dict_size=50, embed_size=8, hidden_size=16,
                          learning_rate=0.1)
    rng = np.random.default_rng(4)
    feed = {
        f"word_{i}": rng.integers(0, 50, size=(16, 1)).astype(np.int64)
        for i in range(4)
    }
    feed["next_word"] = rng.integers(0, 50, size=(16, 1)).astype(np.int64)
    train_steps(outs, feed, steps=6)


def test_machine_translation_train():
    outs = seq2seq.build(src_dict_size=40, trg_dict_size=40, word_dim=8,
                         hidden_dim=16, max_len=6, learning_rate=0.01)
    rng = np.random.default_rng(5)
    src, src_len = ragged_int(4, 6, 40, rng)
    trg, trg_len = ragged_int(4, 6, 40, rng)
    trg_next = np.roll(trg, -1, axis=1)
    feed = {
        "src_word_id": src, "src_word_id@LENGTH": src_len,
        "target_language_word": trg, "target_language_word@LENGTH": trg_len,
        "target_language_next_word": trg_next,
        "target_language_next_word@LENGTH": trg_len,
    }
    train_steps(outs, feed, steps=4)


def test_machine_translation_decode():
    outs = seq2seq.build_decode(
        src_dict_size=40, trg_dict_size=40, word_dim=8, hidden_dim=16,
        max_len=6, beam_size=3, max_out_len=5, end_id=1,
    )
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(6)
    src, src_len = ragged_int(2, 6, 40, rng)
    ids, parents, steps = exe.run(
        feed={"src_word_id": src, "src_word_id@LENGTH": src_len},
        fetch_list=[outs["ids_array"], outs["parents_array"], outs["steps"]],
    )
    n = int(np.asarray(steps).reshape(-1)[0])
    assert 1 <= n <= 5
    sentences = seq2seq.decode_sentences(ids, parents, steps, end_id=1)
    assert sentences.shape[0] == 2  # batch


def test_label_semantic_roles():
    outs = label_semantic_roles.build(
        word_dict_len=30, label_dict_len=5, pred_dict_len=8, max_len=6,
        word_dim=4, hidden_dim=8, depth=2, learning_rate=0.02,
    )
    rng = np.random.default_rng(7)
    feed = {}
    words, lens = ragged_int(3, 6, 30, rng)
    for n in ["word", "ctx_n2", "ctx_n1", "ctx_0", "ctx_p1", "ctx_p2"]:
        w, _ = ragged_int(3, 6, 30, rng)
        feed[n] = w
        feed[n + "@LENGTH"] = lens
    verb, _ = ragged_int(3, 6, 8, rng)
    feed["verb"], feed["verb@LENGTH"] = verb, lens
    mark, _ = ragged_int(3, 6, 2, rng)
    feed["mark"], feed["mark@LENGTH"] = mark, lens
    target, _ = ragged_int(3, 6, 5, rng)
    feed["target"], feed["target@LENGTH"] = target, lens
    train_steps(outs, feed, steps=4)


def test_understand_sentiment_stacked_lstm():
    outs = text_classification.build(
        dict_dim=40, class_dim=2, emb_dim=8, hid_dim=8, stacked_num=2,
        learning_rate=0.05, max_len=8,
    )
    rng = np.random.default_rng(8)
    words, lens = ragged_int(4, 8, 40, rng)
    label = rng.integers(0, 2, size=(4, 1)).astype(np.int64)
    feed = {"words": words, "words@LENGTH": lens, "label": label}
    train_steps(outs, feed, steps=4)


def test_recommender_system():
    outs = recommender.build(learning_rate=0.05, max_title_len=4,
                             max_cat_len=3)
    rng = np.random.default_rng(9)
    b = 4
    cat, cat_len = ragged_int(b, 3, 10, rng)
    title, title_len = ragged_int(b, 4, 50, rng)
    feed = {
        "user_id": rng.integers(0, 100, (b, 1)).astype(np.int64),
        "gender_id": rng.integers(0, 2, (b, 1)).astype(np.int64),
        "age_id": rng.integers(0, 7, (b, 1)).astype(np.int64),
        "job_id": rng.integers(0, 10, (b, 1)).astype(np.int64),
        "movie_id": rng.integers(0, 100, (b, 1)).astype(np.int64),
        "category_id": cat, "category_id@LENGTH": cat_len,
        "movie_title": title, "movie_title@LENGTH": title_len,
        "score": rng.uniform(1, 5, (b, 1)).astype(np.float32),
    }
    train_steps(outs, feed, steps=5)


def test_ctr_dnn():
    outs = ctr_dnn.build(sparse_feature_dim=100, num_slots=3,
                         embedding_size=4, dense_dim=5, hidden=(8, 4),
                         learning_rate=0.05)
    rng = np.random.default_rng(10)
    b = 8
    feed = {"dense_feature": rng.normal(size=(b, 5)).astype(np.float32),
            "click": rng.integers(0, 2, (b, 1)).astype(np.int64)}
    for i in range(3):
        feed[f"slot_{i}"] = rng.integers(0, 100, (b, 1)).astype(np.int64)
    train_steps(outs, feed, steps=5)


def test_deep_speech2_ctc():
    outs = deep_speech2.build(feat_dim=8, max_audio_len=12, max_label_len=6,
                              rnn_size=8, num_rnn_layers=1, vocab_size=5,
                              learning_rate=0.01)
    rng = np.random.default_rng(11)
    b = 2
    audio = rng.normal(size=(b, 12, 8)).astype(np.float32)
    audio_len = np.array([12, 9], np.int32)
    label, label_len = ragged_int(b, 6, 5, rng)
    feed = {"audio": audio, "audio@LENGTH": audio_len,
            "transcript": label, "transcript@LENGTH": label_len}
    train_steps(outs, feed, steps=4)


@pytest.mark.slow
def test_ssd_detection():
    """SSD family: multi-scale prior boxes + multibox_loss training, then
    detection_output inference recovers a planted box (the v1 SSD config
    family — MultiBoxLossLayer / DetectionOutputLayer / PriorBox)."""
    from paddle_tpu.models import ssd

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        model = ssd.build(num_classes=4, image_shape=(3, 64, 64), max_gt=8)
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)
    imgs, gt_box, gt_label = ssd.synthetic_batch(16)
    feed = {"img": imgs, "gt_box": gt_box, "gt_label": gt_label}
    losses = [
        float(np.asarray(exe.run(main, feed=feed,
                                 fetch_list=[model["avg_cost"]],
                                 scope=scope)[0]).ravel()[0])
        for _ in range(12)
    ]
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0] * 0.8, losses[::4]

    # the same program carries the inference head (nondiff branch)
    (dets,) = exe.run(main, feed=feed,
                      fetch_list=[model["detections"]], scope=scope)
    dets = np.asarray(dets)
    assert dets.shape[0] == 16 and dets.shape[-1] == 6
