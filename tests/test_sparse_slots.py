"""Native sparse input slots — the no-densify path for the reference's
``sparse_binary_vector``/``sparse_float_vector`` inputs
(PyDataProvider2.py:90-156 slot types; PyDataProvider2.cpp:195 assembles
them as sparse Arguments and fc consumes them as sparse-row × dense-matrix,
math/SparseMatrix.cpp).  TPU design: provider emits SparseRow(ids, vals),
the feeder pads @IDS/@VALS shadow arrays, sparse_fc gather-sums — nothing
of size ``dim`` is ever materialized host- or device-side."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.reader import provider as p

from op_test import check_grad, check_output, run_op


# ------------------------------------------------------------------ provider
def test_provider_emits_sparse_rows():
    @p.provider(input_types=[p.sparse_binary_vector(1_000_000),
                             p.sparse_float_vector(1_000_000)])
    def process(settings, filename):
        yield [3, 999_999], [(0, 0.5), (123_456, 2.0)]

    sb, sf = next(process()())
    assert isinstance(sb, p.SparseRow) and isinstance(sf, p.SparseRow)
    assert sb.ids.tolist() == [3, 999_999] and sb.vals.tolist() == [1.0, 1.0]
    assert sf.ids.tolist() == [0, 123_456]
    assert sf.vals.tolist() == [0.5, 2.0]
    assert sb.dim == sf.dim == 1_000_000
    # densification is available but explicit — and small-dim exact
    small = p.SparseRow([1, 3], None, 6)
    assert small.todense().tolist() == [0, 1, 0, 1, 0, 0]


def test_provider_sparse_sequence_slots():
    @p.provider(input_types=[p.sparse_binary_vector_sequence(50)])
    def process(settings, filename):
        yield ([[1, 2], [4]],)

    (seq,) = next(process()())
    assert isinstance(seq, list) and len(seq) == 2
    assert seq[0].ids.tolist() == [1, 2] and seq[1].ids.tolist() == [4]


# -------------------------------------------------------------------- feeder
def test_feeder_native_sparse_slot():
    var = layers.sparse_data("bag", dim=1_000_000,
                             main_program=pt.Program())
    feeder = pt.DataFeeder([var], pad_multiple=4)
    feed = feeder.feed([
        (p.SparseRow([5, 999_999], [1.0, 3.0], 1_000_000),),
        (p.SparseRow([7], None, 1_000_000),),
    ])
    ids, vals = feed["bag@IDS"], feed["bag@VALS"]
    assert "bag" not in feed, "handle var must never be materialized"
    assert ids.shape == (2, 4) and vals.shape == (2, 4)  # padded to multiple
    assert ids[0].tolist() == [5, 999_999, 0, 0]
    assert vals[0].tolist() == [1.0, 3.0, 0.0, 0.0]
    assert vals[1].tolist() == [1.0, 0.0, 0.0, 0.0]


def test_feeder_dense_fallback_densifies():
    prog = pt.Program()
    var = layers.data("x", shape=[6], main_program=prog)
    feed = pt.DataFeeder([var]).feed([(p.SparseRow([1, 3], None, 6),)])
    assert feed["x"].shape == (1, 6)
    assert feed["x"][0].tolist() == [0, 1, 0, 1, 0, 0]


def test_feeder_sparse_sequence_slot():
    prog = pt.Program()
    with pt.program_guard(prog, pt.Program()):
        var = layers.sparse_data("seq", dim=100, lod_level=1)
    feeder = pt.DataFeeder([var], pad_multiple=2)
    feed = feeder.feed([
        ([p.SparseRow([1], None, 100), p.SparseRow([2, 3], None, 100),
          p.SparseRow([4], None, 100)],),
        ([p.SparseRow([9], None, 100)],),
    ])
    assert feed["seq@IDS"].shape == (2, 4, 2)  # t padded 3->4, nnz 2
    assert feed["seq@LENGTH"].tolist() == [3, 1]
    assert feed["seq@IDS"][0, 1].tolist() == [2, 3]
    assert feed["seq@VALS"][1, 0].tolist() == [1.0, 0.0]


# ------------------------------------------------------------------------ op
def test_sparse_fc_matches_dense():
    rng = np.random.default_rng(0)
    W = rng.normal(size=(50, 8)).astype(np.float32)
    ids = np.array([[3, 7, 0, 0], [49, 0, 0, 0]], np.int64)
    vals = np.array([[1.0, 2.0, 0, 0], [0.5, 0, 0, 0]], np.float32)
    dense = np.zeros((2, 50), np.float32)
    dense[0, 3], dense[0, 7], dense[1, 49] = 1.0, 2.0, 0.5
    check_output("sparse_fc", {"Ids": ids, "Vals": vals, "W": W},
                 {"Out": dense @ W}, atol=1e-5)
    # leading batch dims beyond 2-D (sequence slots)
    out3 = run_op("sparse_fc", {"Ids": ids[:, None, :],
                                "Vals": vals[:, None, :], "W": W})["Out"]
    np.testing.assert_allclose(out3[:, 0], dense @ W, atol=1e-5)


def test_sparse_fc_grads():
    rng = np.random.default_rng(1)
    inputs = {
        "Ids": np.array([[2, 5, 0], [1, 1, 0]], np.int64),  # dup ids sum
        "Vals": rng.normal(size=(2, 3)).astype(np.float32),
        "W": rng.normal(size=(9, 4)).astype(np.float32),
    }
    check_grad("sparse_fc", inputs, wrt="W")
    check_grad("sparse_fc", inputs, wrt="Vals")


# ------------------------------------------------------- end-to-end training
def test_sparse_fc_program_matches_dense_fc():
    """Same math, two spellings: fc over a native sparse slot vs fc over
    the densified input — losses and the trained weight must agree (the
    reference's test_CompareTwoNets discipline)."""
    rng = np.random.default_rng(2)
    dim, size, bs = 40, 5, 6
    rows = [p.SparseRow(rng.choice(dim, rng.integers(1, 5), replace=False),
                        None, dim)
            for _ in range(bs)]
    y = rng.normal(size=(bs, 1)).astype(np.float32)

    def train(sparse):
        prog, start = pt.Program(), pt.Program()
        with pt.program_guard(prog, start):
            if sparse:
                x = layers.sparse_data("x", dim=dim)
            else:
                x = layers.data("x", shape=[dim])
            label = layers.data("y", shape=[1])
            pred = layers.fc(
                x, size,
                param_attr=pt.ParamAttr(
                    name="w", initializer=pt.initializer.Constant(0.01)),
                bias_attr=False)
            pred = layers.fc(pred, 1, param_attr=pt.ParamAttr(
                name="w2", initializer=pt.initializer.Constant(0.05)))
            loss = layers.mean(layers.square_error_cost(pred, label))
            pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = pt.Executor()
        exe.run(start)
        feeder = pt.DataFeeder([x, label])
        losses = []
        for _ in range(3):
            feed = feeder.feed([(r, yy) for r, yy in zip(rows, y)])
            losses.append(exe.run(prog, feed=feed, fetch_list=[loss])[0])
        w = np.asarray(pt.core.scope.global_scope().get("w"))
        return np.asarray(losses), w

    sl, sw = train(sparse=True)
    dl, dw = train(sparse=False)
    np.testing.assert_allclose(sl, dl, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(sw, dw, rtol=1e-5, atol=1e-6)


def test_ctr_sparse_slots_trains_at_vocab_scale():
    """The verdict's acceptance bar: a reference-style CTR config with raw
    million-dim sparse slots trains — and the host never builds anything
    of size dim (the feed arrays stay O(nnz))."""
    rng = np.random.default_rng(3)
    prog, start = pt.Program(), pt.Program()
    with pt.program_guard(prog, start):
        outs = pt.models.ctr_dnn.build_sparse_slots(
            sparse_feature_dim=1_000_000, num_slots=2, dense_dim=4,
            hidden=(16,))
    exe = pt.Executor()
    exe.run(start)
    feeder = pt.DataFeeder(outs["feed"])
    bs = 8
    losses = []
    for _ in range(3):
        batch = []
        for _ in range(bs):
            row = [rng.normal(size=4).astype(np.float32)]
            for _ in range(2):
                k = int(rng.integers(1, 40))
                row.append(p.SparseRow(
                    rng.choice(1_000_000, k, replace=False), None, 1_000_000))
            row.append(np.asarray([rng.integers(0, 2)], np.int64))
            batch.append(tuple(row))
        feed = feeder.feed(batch)
        assert all(v.size < 10_000 for v in feed.values()), \
            "feed must stay O(nnz), not O(dim)"
        losses.append(float(np.asarray(
            exe.run(prog, feed=feed,
                    fetch_list=[outs["avg_cost"]])[0]).reshape(())))
    assert np.isfinite(losses).all() and losses[-1] < losses[0] * 1.5


def test_feeder_dense_fallback_sequence():
    """Regression (round-5 review): a sparse *sequence* slot feeding a
    plain dense lod_level=1 var must densify per timestep (the pre-native
    behavior), not crash."""
    prog = pt.Program()
    with pt.program_guard(prog, pt.Program()):
        var = layers.data("x", shape=[6], lod_level=1)
    feed = pt.DataFeeder([var], pad_multiple=2).feed([
        ([p.SparseRow([1], None, 6), p.SparseRow([2, 4], None, 6)],),
        ([p.SparseRow([0], None, 6)],),
    ])
    assert feed["x"].shape == (2, 2, 6)
    assert feed["x"][0, 1].tolist() == [0, 0, 1, 0, 1, 0]
    assert feed["x@LENGTH"].tolist() == [2, 1]


def test_feeder_dense_fallback_empty_first_sequence():
    """Regression (ADVICE round 5): detection sniffed only col[0], so a
    batch whose FIRST cell is an empty sparse sequence skipped the
    SparseRow densification and crashed in the lod padding path.  Empty
    sequences must densify to [0, dim] rows."""
    prog = pt.Program()
    with pt.program_guard(prog, pt.Program()):
        var = layers.data("x", shape=[6], lod_level=1)
    feed = pt.DataFeeder([var], pad_multiple=2).feed([
        ([],),                                             # empty first
        ([p.SparseRow([1], None, 6), p.SparseRow([2, 4], None, 6)],),
    ])
    assert feed["x"].shape == (2, 2, 6)
    assert feed["x"][0].tolist() == [[0] * 6, [0] * 6]
    assert feed["x"][1, 1].tolist() == [0, 0, 1, 0, 1, 0]
    assert feed["x@LENGTH"].tolist() == [0, 2]


def test_v1_data_layer_sparse_and_sequence():
    """data_layer(sparse=True) -> native sparse handle; with seq_len it
    must declare lod_level=1 so sequence rows feed correctly."""
    from paddle_tpu.compat import v1

    prog, start = pt.Program(), pt.Program()
    with pt.program_guard(prog, start):
        flat = v1.data_layer("bag", size=1000, sparse=True)
        seq = v1.data_layer("seqbag", size=1000, sparse=True, seq_len=4)
        out = v1.fc_layer(input=flat, size=3)
    assert getattr(flat, "sparse_slot", False) and flat.lod_level == 0
    assert getattr(seq, "sparse_slot", False) and seq.lod_level == 1
    feed = pt.DataFeeder([flat, seq], pad_multiple=2).feed([
        (p.SparseRow([7], None, 1000),
         [p.SparseRow([1, 2], None, 1000), p.SparseRow([3], None, 1000)]),
    ])
    assert feed["seqbag@IDS"].shape == (1, 2, 2)
    assert feed["seqbag@LENGTH"].tolist() == [2]
    assert out.shape[-1] == 3


def test_duplicate_ids_same_both_spellings():
    """Duplicate indices ACCUMULATE identically through todense() and
    sparse_fc (round-5 review: the two spellings must agree)."""
    row = p.SparseRow([5, 5], [1.0, 2.0], 9)
    assert row.todense()[5] == 3.0
    rng = np.random.default_rng(5)
    W = rng.normal(size=(9, 4)).astype(np.float32)
    out = run_op("sparse_fc", {
        "Ids": row.ids[None], "Vals": row.vals[None], "W": W})["Out"]
    np.testing.assert_allclose(out[0], row.todense() @ W, rtol=1e-5,
                               atol=1e-6)
