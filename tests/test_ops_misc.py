"""Per-op tests for the groups not covered by the focused suites: losses,
metrics, detection, CRF (vs brute force), CTC (vs brute force), beam
search, elementwise/compare/logical, shape ops, random ops — extending the
reference's one-test-per-op convention (SURVEY §4)."""

import itertools

import numpy as np
import pytest

from tests.op_test import check_grad, check_output, run_op


# ------------------------------------------------------------------ losses
def test_hinge_loss():
    logits = np.array([[0.5], [-0.3], [2.0]], np.float32)
    labels = np.array([[1.0], [0.0], [1.0]], np.float32)
    y = labels * 2 - 1
    expected = np.maximum(1 - logits * y, 0)
    check_output("hinge_loss", {"Logits": logits, "Labels": labels},
                 {"Loss": expected})
    check_grad("hinge_loss", {"Logits": logits, "Labels": labels},
               wrt="Logits", output="Loss")


def test_huber_loss():
    x = np.random.RandomState(0).randn(8, 1).astype(np.float32)
    y = np.random.RandomState(1).randn(8, 1).astype(np.float32)
    r = y - x
    d = 1.5
    expected = np.where(np.abs(r) <= d, 0.5 * r * r, d * (np.abs(r) - 0.5 * d))
    check_output("huber_loss", {"X": x, "Y": y}, {"Out": expected},
                 attrs={"delta": d})
    check_grad("huber_loss", {"X": x, "Y": y}, wrt="X", attrs={"delta": d})


def test_log_loss():
    p = np.array([[0.2], [0.8]], np.float32)
    l = np.array([[0.0], [1.0]], np.float32)
    eps = 1e-4
    expected = -l * np.log(p + eps) - (1 - l) * np.log(1 - p + eps)
    check_output("log_loss", {"Predicted": p, "Labels": l},
                 {"Loss": expected}, attrs={"epsilon": eps})
    check_grad("log_loss", {"Predicted": p, "Labels": l}, wrt="Predicted",
               output="Loss", attrs={"epsilon": eps})


def test_rank_loss_and_margin_rank_loss():
    rng = np.random.RandomState(2)
    left = rng.randn(6, 1).astype(np.float32)
    right = rng.randn(6, 1).astype(np.float32)
    label = (rng.rand(6, 1) > 0.5).astype(np.float32)
    d = left - right
    expected = np.log1p(np.exp(d)) - label * d
    check_output("rank_loss", {"Label": label, "Left": left, "Right": right},
                 {"Out": expected})
    y = label * 2 - 1  # margin_rank uses +-1 labels
    expected2 = np.maximum(-y * (left - right) + 0.1, 0)
    check_output("margin_rank_loss",
                 {"Label": y, "X1": left, "X2": right},
                 {"Out": expected2}, attrs={"margin": 0.1})


def test_modified_huber_loss():
    x = np.array([[-2.0], [-0.5], [0.5], [2.0]], np.float32)
    yb = np.array([[0], [1], [1], [0]], np.float32)
    y = yb * 2 - 1
    z = (x * y).ravel()
    expected = np.where(z < -1, -4 * z, np.maximum(1 - z, 0) ** 2).reshape(-1, 1)
    check_output("modified_huber_loss", {"X": x, "Y": yb}, {"Out": expected})


def test_squared_l2_distance_and_norm():
    rng = np.random.RandomState(3)
    x = rng.randn(4, 5).astype(np.float32)
    y = rng.randn(4, 5).astype(np.float32)
    got = run_op("squared_l2_distance", {"X": x, "Y": y})
    np.testing.assert_allclose(
        got["Out"].ravel(), ((x - y) ** 2).sum(1), rtol=1e-5)
    got = run_op("squared_l2_norm", {"X": x})
    np.testing.assert_allclose(got["Out"].ravel(), [(x ** 2).sum()],
                               rtol=1e-5)


def test_nce_deterministic_with_key():
    import jax

    rng = np.random.RandomState(4)
    inp = rng.randn(3, 8).astype(np.float32)
    w = rng.randn(20, 8).astype(np.float32)
    lbl = np.array([[1], [5], [7]], np.int64)
    attrs = {"num_neg_samples": 4, "num_total_classes": 20,
             "_key": jax.random.PRNGKey(0)}
    a = run_op("nce", {"Input": inp, "Label": lbl, "Weight": w}, attrs)
    b = run_op("nce", {"Input": inp, "Label": lbl, "Weight": w}, attrs)
    np.testing.assert_array_equal(a["Cost"], b["Cost"])
    assert np.isfinite(a["Cost"]).all()


# ----------------------------------------------------------------- metrics
def test_accuracy_op():
    indices = np.array([[0, 2], [1, 3], [4, 0]], np.int64)
    label = np.array([[2], [0], [4]], np.int64)
    got = run_op("accuracy", {"Out": indices.astype(np.float32),
                              "Indices": indices, "Label": label})
    np.testing.assert_allclose(got["Accuracy"], [2 / 3], rtol=1e-6)
    assert got["Correct"][0] == 2 and got["Total"][0] == 3


def test_auc_perfect_and_random():
    probs = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7], [0.9, 0.1]],
                     np.float32)
    label = np.array([[1], [0], [1], [0]], np.int64)
    got = run_op("auc", {"Out": probs, "Label": label})
    assert got["AUC"][0] > 0.99  # perfectly separable
    label_bad = np.array([[0], [1], [0], [1]], np.int64)
    got = run_op("auc", {"Out": probs, "Label": label_bad})
    assert got["AUC"][0] < 0.01


def test_precision_recall_op():
    indices = np.array([[0], [0], [1], [1]], np.int64)
    labels = np.array([[0], [1], [1], [1]], np.int64)
    got = run_op(
        "precision_recall",
        {"Indices": indices, "Labels": labels},
        attrs={"class_number": 2},
    )
    # class 0: tp=1 fp=1 fn=0 -> precision .5 recall 1
    # class 1: tp=2 fp=0 fn=1 -> precision 1 recall 2/3
    macro_p = (0.5 + 1.0) / 2
    np.testing.assert_allclose(got["BatchMetrics"][0], macro_p, rtol=1e-5)


def test_positive_negative_pair():
    score = np.array([[0.9], [0.2], [0.5]], np.float32)
    label = np.array([[1], [0], [0]], np.int64)
    qid = np.array([[0], [0], [0]], np.int64)
    got = run_op("positive_negative_pair",
                 {"Score": score, "Label": label, "QueryID": qid})
    # positive item ranked above both negatives: 2 correct pairs, 0 wrong
    np.testing.assert_allclose(got["PositivePair"], [2.0])
    np.testing.assert_allclose(got["NegativePair"], [0.0])


def test_edit_distance_op():
    hyp = np.array([[1, 2, 3, 0]], np.int64)
    ref = np.array([[1, 3, 3, 2]], np.int64)
    got = run_op(
        "edit_distance",
        {"Hyps": hyp, "Refs": ref,
         "HypsLength": np.array([3], np.int64),
         "RefsLength": np.array([4], np.int64)},
    )
    # hyp [1,2,3] vs ref [1,3,3,2]: distance 2
    np.testing.assert_allclose(got["Out"].ravel(), [2.0])


# --------------------------------------------------------------- detection
def test_iou_similarity():
    a = np.array([[0, 0, 2, 2]], np.float32)
    b = np.array([[1, 1, 3, 3], [0, 0, 2, 2]], np.float32)
    got = run_op("iou_similarity", {"X": a, "Y": b})
    np.testing.assert_allclose(got["Out"], [[1 / 7, 1.0]], rtol=1e-5)


def test_bipartite_match():
    dist = np.array([[0.9, 0.1], [0.2, 0.8]], np.float32)
    got = run_op("bipartite_match", {"DistMat": dist})
    np.testing.assert_array_equal(got["ColToRowMatchIndices"], [[0, 1]])


def test_prior_box_shapes():
    image = np.zeros((1, 3, 32, 32), np.float32)
    feat = np.zeros((1, 8, 4, 4), np.float32)
    got = run_op(
        "prior_box", {"Input": feat, "Image": image},
        attrs={"min_sizes": [4.0], "max_sizes": [], "aspect_ratios": [1.0],
               "variances": [0.1, 0.1, 0.2, 0.2], "flip": False,
               "clip": True},
    )
    assert got["Boxes"].shape[:2] == (4, 4)
    assert got["Boxes"].min() >= 0 and got["Boxes"].max() <= 1


def test_roi_pool():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 0, 3, 3]], np.float32)  # batch_id, x1,y1,x2,y2
    got = run_op(
        "roi_pool", {"X": x, "ROIs": rois},
        attrs={"pooled_height": 2, "pooled_width": 2, "spatial_scale": 1.0},
    )
    np.testing.assert_allclose(got["Out"][0, 0], [[5, 7], [13, 15]])


# ---------------------------------------------------------------- crf / ctc
def _brute_crf_nll(emission, transition, labels, length):
    """Enumerate all paths for one sequence (tiny n, t)."""
    t, n = emission.shape
    start, end, trans = transition[0], transition[1], transition[2:]

    def path_score(path):
        s = start[path[0]] + emission[0, path[0]]
        for i in range(1, length):
            s += trans[path[i - 1], path[i]] + emission[i, path[i]]
        return s + end[path[length - 1]]

    scores = [
        path_score(p) for p in itertools.product(range(n), repeat=length)
    ]
    logz = np.log(np.sum(np.exp(np.array(scores))))
    return logz - path_score(labels)


def test_linear_chain_crf_vs_brute_force():
    rng = np.random.RandomState(5)
    t, n = 4, 3
    emission = rng.randn(1, t, n).astype(np.float32)
    transition = rng.randn(n + 2, n).astype(np.float32) * 0.5
    labels = np.array([[0, 2, 1, 0]], np.int64)
    got = run_op(
        "linear_chain_crf",
        {"Emission": emission, "Transition": transition, "Label": labels,
         "Length": np.array([t], np.int32)},
    )
    want = _brute_crf_nll(emission[0], transition, labels[0], t)
    np.testing.assert_allclose(got["LogLikelihood"].ravel(), [want],
                               rtol=1e-4)


def test_crf_decoding_matches_brute_force():
    rng = np.random.RandomState(6)
    t, n = 4, 3
    emission = rng.randn(1, t, n).astype(np.float32)
    transition = rng.randn(n + 2, n).astype(np.float32)
    got = run_op(
        "crf_decoding",
        {"Emission": emission, "Transition": transition,
         "Length": np.array([t], np.int32)},
    )
    start, end, trans = transition[0], transition[1], transition[2:]
    best, best_score = None, -np.inf
    for p in itertools.product(range(n), repeat=t):
        s = start[p[0]] + emission[0, 0, p[0]]
        for i in range(1, t):
            s += trans[p[i - 1], p[i]] + emission[0, i, p[i]]
        s += end[p[-1]]
        if s > best_score:
            best, best_score = p, s
    np.testing.assert_array_equal(got["ViterbiPath"][0], best)


def _brute_ctc_nll(logits, labels, blank):
    """Sum probability over all alignments (tiny T)."""
    t, v = logits.shape
    logp = logits - np.log(np.sum(np.exp(logits), axis=1, keepdims=True))

    def collapse(seq):
        out = []
        prev = None
        for s in seq:
            if s != prev and s != blank:
                out.append(s)
            prev = s
        return tuple(out)

    total = 0.0
    for seq in itertools.product(range(v), repeat=t):
        if collapse(seq) == tuple(labels):
            total += np.exp(sum(logp[i, s] for i, s in enumerate(seq)))
    return -np.log(total)


def test_warpctc_vs_brute_force():
    rng = np.random.RandomState(7)
    t, v = 4, 3  # vocab {0,1}, blank=2
    logits = rng.randn(1, t, v).astype(np.float32)
    labels = np.array([[0, 1]], np.int64)
    got = run_op(
        "warpctc",
        {"Logits": logits, "Label": labels,
         "LogitsLength": np.array([t], np.int64),
         "LabelLength": np.array([2], np.int64)},
        attrs={"blank": 2},
    )
    want = _brute_ctc_nll(logits[0], [0, 1], blank=2)
    np.testing.assert_allclose(got["Loss"].ravel(), [want], rtol=1e-4)


def test_ctc_align():
    x = np.array([[0, 0, 1, 1, 2, 0, 2, 2]], np.int64)
    got = run_op("ctc_align", {"Input": x,
                               "InputLength": np.array([8], np.int64)},
                 attrs={"blank": 0})
    # collapse repeats then remove blanks: [1, 2, 2]
    out = got["Output"][0]
    np.testing.assert_array_equal(out[:3], [1, 2, 2])


# -------------------------------------------------------------- beam search
def test_beam_search_step():
    pre_ids = np.zeros((1, 2), np.int64)
    pre_scores = np.array([[0.0, -1e9]], np.float32)  # beam 1 dead at t=0
    scores = np.log(np.array(
        [[[0.1, 0.7, 0.2], [0.3, 0.3, 0.4]]], np.float32))
    got = run_op(
        "beam_search",
        {"PreIds": pre_ids, "PreScores": pre_scores, "Scores": scores},
        attrs={"beam_size": 2, "end_id": 3},
    )
    # all mass comes from beam 0: top2 tokens are 1 (0.7) and 2 (0.2)
    np.testing.assert_array_equal(got["SelectedIds"][0], [1, 2])
    np.testing.assert_array_equal(got["ParentIdx"][0], [0, 0])


def test_top_k():
    x = np.array([[3.0, 1.0, 4.0, 1.5]], np.float32)
    got = run_op("top_k", {"X": x}, attrs={"k": 2})
    np.testing.assert_allclose(got["Out"], [[4.0, 3.0]])
    np.testing.assert_array_equal(got["Indices"], [[2, 0]])


# ------------------------------------------------- elementwise / activations
@pytest.mark.parametrize("op,fn", [
    ("elementwise_add", np.add),
    ("elementwise_div", np.divide),
    ("elementwise_min", np.minimum),
    ("elementwise_pow", np.power),
])
def test_elementwise_ops(op, fn):
    rng = np.random.RandomState(8)
    x = rng.rand(3, 4).astype(np.float32) + 0.5
    y = rng.rand(3, 4).astype(np.float32) + 0.5
    check_output(op, {"X": x, "Y": y}, {"Out": fn(x, y)})
    check_grad(op, {"X": x, "Y": y}, wrt="X")


def test_elementwise_broadcast_axis():
    x = np.random.RandomState(9).rand(2, 3, 4).astype(np.float32)
    y = np.random.RandomState(10).rand(3).astype(np.float32)
    got = run_op("elementwise_add", {"X": x, "Y": y}, attrs={"axis": 1})
    np.testing.assert_allclose(got["Out"], x + y[None, :, None], rtol=1e-6)


@pytest.mark.parametrize("op,fn", [
    ("exp", np.exp),
    ("log", np.log),
    ("sqrt", np.sqrt),
    ("reciprocal", np.reciprocal),
    ("softplus", lambda x: np.log1p(np.exp(x))),
    ("softsign", lambda x: x / (1 + np.abs(x))),
    ("hard_sigmoid", lambda x: np.clip(0.2 * x + 0.5, 0, 1)),
])
def test_more_activations(op, fn):
    x = np.random.RandomState(11).rand(4, 5).astype(np.float32) + 0.5
    check_output(op, {"X": x}, {"Out": fn(x)}, atol=1e-5)
    check_grad(op, {"X": x}, wrt="X")


# ----------------------------------------------------- compare / logical
def test_compare_and_logical_ops():
    x = np.array([1.0, 2.0, 3.0], np.float32)
    y = np.array([2.0, 2.0, 2.0], np.float32)
    assert run_op("less_than", {"X": x, "Y": y})["Out"].tolist() == [
        True, False, False]
    assert run_op("greater_equal", {"X": x, "Y": y})["Out"].tolist() == [
        False, True, True]
    a = np.array([True, False, True])
    b = np.array([True, True, False])
    assert run_op("logical_and", {"X": a, "Y": b})["Out"].tolist() == [
        True, False, False]
    assert run_op("logical_xor", {"X": a, "Y": b})["Out"].tolist() == [
        False, True, True]
    assert run_op("logical_not", {"X": a})["Out"].tolist() == [
        False, True, False]


# -------------------------------------------------------------- shape ops
def test_shape_manipulation_ops():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    got = run_op("transpose", {"X": x}, attrs={"axis": [0, 2, 1]})
    np.testing.assert_array_equal(got["Out"], x.transpose(0, 2, 1))
    got = run_op("expand", {"X": x[:1]}, attrs={"expand_times": [2, 1, 1]})
    np.testing.assert_array_equal(got["Out"], np.tile(x[:1], (2, 1, 1)))
    got = run_op("pad", {"X": x[0]},
                 attrs={"paddings": [1, 0, 0, 2], "pad_value": -1.0})
    assert got["Out"].shape == (4, 6)
    assert (got["Out"][0] == -1).all()
    got = run_op("crop", {"X": x[0]}, attrs={"offsets": [1, 1],
                                             "shape": [2, 2]})
    np.testing.assert_array_equal(got["Out"], x[0][1:3, 1:3])
    got = run_op("gather", {"X": x[0], "Index": np.array([2, 0])})
    np.testing.assert_array_equal(got["Out"], x[0][[2, 0]])
    got = run_op("scatter", {"X": np.zeros((3, 4), np.float32),
                             "Ids": np.array([1]),
                             "Updates": np.ones((1, 4), np.float32)})
    assert got["Out"][1].sum() == 4
    got = run_op("one_hot", {"X": np.array([[1], [3]], np.int64)},
                 attrs={"depth": 4})
    np.testing.assert_array_equal(
        got["Out"], [[0, 1, 0, 0], [0, 0, 0, 1]])


def test_cast_concat_split():
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    got = run_op("cast", {"X": x}, attrs={"out_dtype": "int32"})
    assert got["Out"].dtype == np.int32
    got = run_op("concat", {"X": [x, x]}, attrs={"axis": 1})
    assert got["Out"].shape == (2, 6)
    got = run_op("split", {"X": x}, attrs={"num": 3, "axis": 1})
    assert len(got["Out"]) == 3 and got["Out"][0].shape == (2, 1)


def test_multiplex():
    ids = np.array([[1], [0]], np.int32)
    a = np.full((2, 3), 1.0, np.float32)
    b = np.full((2, 3), 2.0, np.float32)
    got = run_op("multiplex", {"Ids": ids, "X": [a, b]})
    np.testing.assert_array_equal(got["Out"][0], b[0])
    np.testing.assert_array_equal(got["Out"][1], a[1])


# -------------------------------------------------------------- random ops
def test_random_ops_deterministic_and_distribution():
    import jax

    key = jax.random.PRNGKey(42)
    a = run_op("gaussian_random", {}, attrs={"shape": [1000], "mean": 1.0,
                                             "std": 2.0, "_key": key})
    b = run_op("gaussian_random", {}, attrs={"shape": [1000], "mean": 1.0,
                                             "std": 2.0, "_key": key})
    np.testing.assert_array_equal(a["Out"], b["Out"])
    assert abs(a["Out"].mean() - 1.0) < 0.3
    assert abs(a["Out"].std() - 2.0) < 0.3
    u = run_op("uniform_random", {}, attrs={"shape": [1000], "min": -1.0,
                                            "max": 1.0, "_key": key})
    assert u["Out"].min() >= -1 and u["Out"].max() <= 1
    tg = run_op("truncated_gaussian_random", {},
                attrs={"shape": [1000], "mean": 0.0, "std": 1.0, "_key": key})
    assert np.abs(tg["Out"]).max() <= 2.0 + 1e-5


def test_norm_and_spp_and_conv_shift():
    x = np.random.RandomState(12).rand(2, 3, 4).astype(np.float32)
    got = run_op("norm", {"X": x}, attrs={"axis": 1, "epsilon": 1e-10})
    np.testing.assert_allclose(
        got["Out"], x / np.sqrt((x ** 2).sum(1, keepdims=True) + 1e-10),
        rtol=1e-5)
    img = np.random.RandomState(13).rand(1, 2, 4, 4).astype(np.float32)
    got = run_op("spp", {"X": img}, attrs={"pyramid_height": 2,
                                           "pooling_type": "max"})
    assert got["Out"].shape == (1, 2 * (1 + 4))
    xs = np.random.RandomState(14).rand(2, 5).astype(np.float32)
    ker = np.random.RandomState(15).rand(2, 3).astype(np.float32)
    got = run_op("conv_shift", {"X": xs, "Y": ker})
    assert got["Out"].shape == (2, 5)


def test_beam_search_decode_layer():
    import paddle_tpu as pt

    # layers.data prepends a dynamic leading dim -> [T, b, k] feeds
    ids = pt.layers.data("bs_ids", shape=[2, 3], dtype="int64")
    parent = pt.layers.data("bs_parent", shape=[2, 3], dtype="int64")
    scores = pt.layers.data("bs_scores", shape=[2, 3], dtype="float32")
    sent, out_scores = pt.layers.beam_search_decode(
        ids, parent, scores=scores, end_id=1)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    T, b, k = 4, 2, 3
    rng = np.random.default_rng(0)
    idsv = rng.integers(2, 9, (T, b, k)).astype(np.int64)
    parentv = rng.integers(0, k, (T, b, k)).astype(np.int64)
    # scores at final step only matter
    scoresv = rng.random((T, b, k)).astype(np.float32)
    sv, scv = exe.run(feed={"bs_ids": idsv, "bs_parent": parentv,
                            "bs_scores": scoresv},
                      fetch_list=[sent, out_scores])
    assert sv.shape == (b, k, T)
    assert scv.shape == (b, k)
    # hand backtrack beam 0 of batch 0
    beam = 0
    toks = []
    for t in range(T - 1, -1, -1):
        toks.append(idsv[t, 0, beam])
        beam = parentv[t, 0, beam]
    assert sv[0, 0, :].tolist() == toks[::-1]
