"""One process of a 2-process CPU "multi-host" run (spawned by
test_distributed.py::test_multihost_two_process_cpu).  Each process joins
the JAX coordination service via paddle_tpu.distributed.launch, forms a
GLOBAL mesh spanning both processes' devices, checks a cross-process
collective, and runs two data-parallel Executor training steps — the
CPU-scale analog of the reference's multi-node trainers
(paddle/scripts/cluster_train_v2, --trainer_id flags).

Checkpoint modes (argv[4] = mode, argv[5] = ckpt dir) exercise the
multi-host sharded save/restore path on a model whose fc weight is
PARTITIONED over a tp axis that spans both processes (np.asarray on such
an array throws — io._ShardedSnap per-process shard files are the fix):

* ``ckpt_ref``    — train 3 steps straight through, print final state;
* ``ckpt_save``   — train 1 step, save_persistables (each process writes
                    its shard file), barrier, train 2 more, print final;
* ``ckpt_resume`` — fresh processes: startup, load_persistables (each
                    process reads only ITS shard file), train 2 steps,
                    print final.  Must equal both runs above bit-for-bit.

The ``ckpt_resume_midpass`` family (ISSUE 8, ROADMAP item 4's gate at
multi-host scale) upgrades this to kill-and-resume with FULL state
(``io.save_checkpoint`` + the resilience train-state sidecar carrying
the RNG key and step counter):

* ``ckpt_mid_ref``    — 4 steps straight through, print final state;
* ``ckpt_mid_kill``   — 2 steps, full-state checkpoint (per-process
                        shard files + proc-0 train-state), barrier, then
                        SIGKILL OWN PID — both ranks die mid-pass, no
                        unwinding (the parent expects rc == -SIGKILL);
* ``ckpt_mid_resume`` — fresh processes restore persistables + train
                        state + RNG, run the remaining 2 steps, print
                        final.  Must equal ``ckpt_mid_ref`` bit-for-bit.
"""

import os
import sys


def _tp_model_and_exe(launch, pt, total):
    """fc model with the weight column-sharded over a tp axis that spans
    the two processes (device-order axis 0), data-parallel over dp."""
    import numpy as np
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.parallel import api as papi

    mesh = launch.global_mesh({"tp": 2, "dp": total // 2})
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        x = pt.layers.data("x", shape=[8], dtype="float32")
        y = pt.layers.data("y", shape=[4], dtype="float32")
        h = pt.layers.fc(x, size=16, act="relu")
        pred = pt.layers.fc(h, size=4)
        cost = pt.layers.mean(pt.layers.square_error_cost(pred, y))
        pt.optimizer.Momentum(learning_rate=0.05, momentum=0.9).minimize(
            cost)
    papi.data_parallel(main_p, "dp", programs=(startup,))
    papi.shard_parameters_by_rule(main_p, [(r"fc_0\.w", P(None, "tp"))])
    papi.shard_parameters_by_rule(startup, [(r"fc_0\.w", P(None, "tp"))])
    scope = pt.Scope()
    exe = pt.Executor(mesh=mesh)
    return main_p, startup, cost, scope, exe, mesh


def _state_digest(scope, names):
    """Order-stable digest of (possibly partitioned) state: dense parts
    via np.asarray, partitioned parts via the io snapshot helper."""
    import hashlib

    import numpy as np

    from paddle_tpu.io import _host_snapshot, _ShardedSnap

    h = hashlib.sha256()
    for n in names:
        snap = _host_snapshot(scope.get(n))
        if isinstance(snap, _ShardedSnap):
            for key, data in sorted(snap.shards.items()):
                h.update(str(key).encode())
                h.update(np.ascontiguousarray(data).tobytes())
        else:
            h.update(np.ascontiguousarray(snap).tobytes())
    return h.hexdigest()


def _ckpt_mode(mode, ckpt_dir, coordinator, nproc, pid):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.distributed import launch

    launch.init_multihost(coordinator=coordinator, num_processes=nproc,
                          process_id=pid)
    total = jax.device_count()
    local = jax.local_device_count()
    main_p, startup, cost, scope, exe, mesh = _tp_model_and_exe(
        launch, pt, total)
    exe.run(startup, scope=scope)

    # the tp-sharded weight really is cross-process partitioned
    w = scope.get("fc_0.w")
    assert not w.is_fully_addressable and not w.is_fully_replicated, (
        w.sharding)
    print(f"[{pid}] fc_0.w sharding {w.sharding}", flush=True)

    # the batch shards over dp only, and dp here is WITHIN-process (tp is
    # the axis crossing processes) — so each process's local portion of
    # the global batch is the WHOLE batch: both processes must feed
    # identical data, or the two tp halves silently train on different
    # batches and replicated state diverges across ranks
    rng = np.random.RandomState(0)
    dp = total // 2
    xs = rng.randn(4 * dp, 8).astype(np.float32)
    ys = np.tile(xs.sum(axis=1, keepdims=True) * 0.1, (1, 4)).astype(
        np.float32)
    feed = {"x": xs, "y": ys}

    def step():
        (l,) = exe.run(main_p, feed=feed, fetch_list=[cost], scope=scope)
        return float(np.asarray(l))

    def barrier():
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("ckpt")

    pnames = sorted(p.name for p in main_p.all_parameters())
    if mode == "ckpt_mid_ref":
        for _ in range(4):
            loss = step()
    elif mode == "ckpt_mid_kill":
        import signal

        import paddle_tpu.io as io

        for _ in range(2):
            loss = step()
        with pt.core.scope.scope_guard(scope):
            io.save_checkpoint(exe, ckpt_dir, main_p, train_state={
                "global_step": 2, "pass_id": 0, "step_in_pass": 2,
                "rng_key": np.asarray(scope.get(pt.core.scope.RNG_VAR)),
            })
        barrier()  # every rank's shard files + markers are on disk
        print(f"MULTIHOST_KILL_READY {pid}", flush=True)
        os.kill(os.getpid(), signal.SIGKILL)
    elif mode == "ckpt_mid_resume":
        import paddle_tpu.io as io
        from paddle_tpu.resilience import checkpoint as rckpt

        with pt.core.scope.scope_guard(scope):
            io.load_persistables(exe, ckpt_dir, main_p)
        st = rckpt.load_train_state(ckpt_dir)
        assert st["global_step"] == 2, st
        scope.set(pt.core.scope.RNG_VAR,
                  jnp.asarray(np.asarray(st["rng_key"])))
        for _ in range(4 - st["global_step"]):
            loss = step()
    elif mode == "ckpt_ref":
        for _ in range(3):
            loss = step()
    elif mode == "ckpt_save":
        step()
        import paddle_tpu.io as io

        with pt.core.scope.scope_guard(scope):
            io.save_persistables(exe, ckpt_dir, main_p)
        barrier()
        for _ in range(2):
            loss = step()
    elif mode == "ckpt_resume":
        import paddle_tpu.io as io

        with pt.core.scope.scope_guard(scope):
            io.load_persistables(exe, ckpt_dir, main_p)
        for _ in range(2):
            loss = step()
    else:
        raise SystemExit(f"unknown mode {mode}")
    names = pnames
    if mode.startswith("ckpt_mid"):
        # the midpass gate digests EVERY persistable — momentum state
        # included, so a resume that lost optimizer moments cannot pass
        # on params alone
        names = sorted(
            v.name for v in main_p.global_block().vars.values()
            if v.persistable and scope.find_var(v.name) is not None)
    digest = _state_digest(scope, names)
    print(f"MULTIHOST_CKPT_OK {pid} loss={loss:.8f} state={digest}",
          flush=True)


def main():
    coordinator, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    mode = sys.argv[4] if len(sys.argv) > 4 else "train"
    if mode != "train":
        return _ckpt_mode(mode, sys.argv[5], coordinator, nproc, pid)

    import jax

    jax.config.update("jax_platforms", "cpu")

    from paddle_tpu.distributed import launch

    launch.init_multihost(coordinator=coordinator, num_processes=nproc,
                          process_id=pid)
    assert jax.process_count() == nproc, jax.process_count()
    local = jax.local_device_count()
    total = jax.device_count()
    assert total == nproc * local, (total, local)
    print(f"[{pid}] devices local={local} global={total}", flush=True)

    # cross-process collective over the global mesh
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    mesh = launch.global_mesh({"dp": total})

    @jax.jit
    def global_sum():
        def f():
            return jax.lax.psum(
                jnp.ones((), jnp.float32), "dp")

        return shard_map(f, mesh=mesh, in_specs=(), out_specs=P())()

    s = float(global_sum())
    assert s == float(total), s
    print(f"[{pid}] psum over dp = {s}", flush=True)

    # data-parallel Executor training: each process feeds its LOCAL batch
    # shard; the Executor assembles the global array over the dp mesh.
    import paddle_tpu as pt
    from paddle_tpu.parallel import api as papi

    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        x = pt.layers.data("x", shape=[8], dtype="float32")
        y = pt.layers.data("y", shape=[1], dtype="float32")
        pred = pt.layers.fc(x, size=1)
        cost = pt.layers.mean(pt.layers.square_error_cost(pred, y))
        pt.optimizer.SGD(learning_rate=0.1).minimize(cost)
    papi.data_parallel(main_p, "dp", programs=(startup,))

    scope = pt.Scope()
    exe = pt.Executor(mesh=mesh)
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(0)  # same seed: deterministic global data
    xs = rng.randn(4 * total, 8).astype(np.float32)
    ys = (xs.sum(axis=1, keepdims=True) * 0.1).astype(np.float32)
    lo = pid * 4 * local
    xs_local, ys_local = xs[lo:lo + 4 * local], ys[lo:lo + 4 * local]
    losses = []
    for _ in range(2):
        (l,) = exe.run(main_p, feed={"x": xs_local, "y": ys_local},
                       fetch_list=[cost], scope=scope)
        losses.append(float(np.asarray(l)))
    assert np.isfinite(losses).all(), losses
    assert losses[1] < losses[0], losses
    # params are replicated over the global mesh -> fully addressable here
    w = np.asarray(scope.get("fc_0.w"))
    print(f"MULTIHOST_OK {pid} loss={losses[1]:.8f} wsum={float(w.sum()):.8f}",
          flush=True)


if __name__ == "__main__":
    main()
