"""One process of a 2-process CPU "multi-host" run (spawned by
test_distributed.py::test_multihost_two_process_cpu).  Each process joins
the JAX coordination service via paddle_tpu.distributed.launch, forms a
GLOBAL mesh spanning both processes' devices, checks a cross-process
collective, and runs two data-parallel Executor training steps — the
CPU-scale analog of the reference's multi-node trainers
(paddle/scripts/cluster_train_v2, --trainer_id flags)."""

import os
import sys


def main():
    coordinator, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

    import jax

    jax.config.update("jax_platforms", "cpu")

    from paddle_tpu.distributed import launch

    launch.init_multihost(coordinator=coordinator, num_processes=nproc,
                          process_id=pid)
    assert jax.process_count() == nproc, jax.process_count()
    local = jax.local_device_count()
    total = jax.device_count()
    assert total == nproc * local, (total, local)
    print(f"[{pid}] devices local={local} global={total}", flush=True)

    # cross-process collective over the global mesh
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    mesh = launch.global_mesh({"dp": total})

    @jax.jit
    def global_sum():
        def f():
            return jax.lax.psum(
                jnp.ones((), jnp.float32), "dp")

        return shard_map(f, mesh=mesh, in_specs=(), out_specs=P())()

    s = float(global_sum())
    assert s == float(total), s
    print(f"[{pid}] psum over dp = {s}", flush=True)

    # data-parallel Executor training: each process feeds its LOCAL batch
    # shard; the Executor assembles the global array over the dp mesh.
    import paddle_tpu as pt
    from paddle_tpu.parallel import api as papi

    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        x = pt.layers.data("x", shape=[8], dtype="float32")
        y = pt.layers.data("y", shape=[1], dtype="float32")
        pred = pt.layers.fc(x, size=1)
        cost = pt.layers.mean(pt.layers.square_error_cost(pred, y))
        pt.optimizer.SGD(learning_rate=0.1).minimize(cost)
    papi.data_parallel(main_p, "dp", programs=(startup,))

    scope = pt.Scope()
    exe = pt.Executor(mesh=mesh)
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(0)  # same seed: deterministic global data
    xs = rng.randn(4 * total, 8).astype(np.float32)
    ys = (xs.sum(axis=1, keepdims=True) * 0.1).astype(np.float32)
    lo = pid * 4 * local
    xs_local, ys_local = xs[lo:lo + 4 * local], ys[lo:lo + 4 * local]
    losses = []
    for _ in range(2):
        (l,) = exe.run(main_p, feed={"x": xs_local, "y": ys_local},
                       fetch_list=[cost], scope=scope)
        losses.append(float(np.asarray(l)))
    assert np.isfinite(losses).all(), losses
    assert losses[1] < losses[0], losses
    # params are replicated over the global mesh -> fully addressable here
    w = np.asarray(scope.get("fc_0.w"))
    print(f"MULTIHOST_OK {pid} loss={losses[1]:.8f} wsum={float(w.sum()):.8f}",
          flush=True)


if __name__ == "__main__":
    main()
