"""Scan-based remat engine tests (ISSUE 3 tentpole).

The Executor runs structurally repeated remat segments (transformer
layers) as ONE ``lax.scan`` with weights stacked on the scan axis and
``jax.checkpoint`` inside the body — the spelling whose backward has
O(1)-per-layer remat temps (the t=16k capacity path).  These tests pin:

- all three ``memory_optimize`` policies x accum {1, 2} COMPILE AND RUN
  on a small transformer under JAX_PLATFORMS=cpu;
- the LOSS is bit-exact vs the unrematted step in every configuration
  (forward math unchanged, dropout keys reproduced through the scan);
- GRADIENTS are bit-exact vs the unrematted step for the full/compact
  policies when XLA fusion is disabled (subprocess), and within a few
  f32 ulps otherwise — XLA fuses the checkpoint-island boundaries
  differently from the flat graph, which reassociates a handful of
  elementwise chains (measured <= ~1e-7 absolute; a real remat bug —
  wrong mask, wrong key, wrong carry — shows up at 1e-2+);
- the scan engine is numerically invisible: scanned execution is
  bit-identical to the per-segment barrier execution of the same policy;
- the structural matcher (core/ir.py) groups what it should.
"""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.ir import (
    detect_repeated_run,
    find_uniform_groups,
    match_op_run,
)
from paddle_tpu.core.program import GRAD_SUFFIX
from paddle_tpu.models import transformer

# one-or-two-ulp bound for f32 grads across XLA fusion boundaries (see
# module docstring); NOT a model-accuracy tolerance
ULP_ATOL = 5e-7
ULP_RTOL = 5e-6


def _build(policy, accum=1, drop=0.0, n_layer=2, seed=11):
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    main.random_seed = seed
    with pt.program_guard(main, startup):
        outs = transformer.build(vocab_size=30, n_layer=n_layer, n_head=2,
                                 d_model=32, max_len=12, dropout_rate=drop,
                                 dtype="float32")
    if accum > 1:
        pt.gradient_accumulation(main, accum)
    if policy:
        pt.memory_optimize(main, policy=policy)
    return main, startup, outs["avg_cost"]


def _feed(seed=3):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, 30, (4, 12)).astype(np.int64)
    return {"tokens": toks, "labels": np.roll(toks, -1, axis=1)}


def _step_grads(main, startup, loss, steps=1):
    """Losses over ``steps`` optimizer steps plus the LAST step's param
    gradients, in a private scope."""
    scope = pt.Scope()
    pt.core.scope._scope_stack.append(scope)
    try:
        exe = pt.Executor()
        exe.run(startup, scope=scope)
        known = {n for blk in main.blocks for n in blk.vars}
        gnames = [p.name + GRAD_SUFFIX for p in main.all_parameters()
                  if p.name + GRAD_SUFFIX in known]
        losses, grads = [], {}
        for _ in range(steps):
            outs = exe.run(main, feed=_feed(),
                           fetch_list=[loss] + gnames, scope=scope)
            losses.append(np.asarray(outs[0]))
            grads = dict(zip(gnames, [np.asarray(o) for o in outs[1:]]))
        return losses, grads, exe
    finally:
        pt.core.scope._scope_stack.pop()


@pytest.mark.parametrize("accum", [1, 2])
@pytest.mark.parametrize("policy", ["full", "selective", "compact"])
def test_remat_policy_compiles_and_loss_bit_exact(policy, accum):
    """Every policy x accum compiles, runs, keeps the loss BIT-EXACT vs
    the unrematted step across optimizer steps, and keeps gradients
    within a few f32 ulps (fusion reassociation only)."""
    base_losses, base_grads, _ = _step_grads(*_build(None, accum), steps=2)
    opt_losses, opt_grads, exe = _step_grads(*_build(policy, accum), steps=2)
    for b, o in zip(base_losses, opt_losses):
        np.testing.assert_array_equal(b, o)
    assert set(base_grads) == set(opt_grads)
    for n in base_grads:
        np.testing.assert_allclose(opt_grads[n], base_grads[n],
                                   atol=ULP_ATOL, rtol=ULP_RTOL,
                                   err_msg=n)
    if policy in ("full", "selective"):
        # the 2-layer model's repeated blocks must actually hit the scan
        # engine (compact needs >= 3 layers for 2 full periods; covered
        # by test_scan_groups_selective_and_compact)
        assert exe.last_remat_plan, "scan-remat engine did not engage"
        assert exe.last_remat_plan[0]["count"] == 2


def test_remat_dropout_keys_reproduced_through_scan():
    """With dropout ON, the scanned layers must derive the SAME per-layer
    dropout keys as the unrolled trace — bit-exact loss is the proof (a
    wrong mask moves the loss at 1e-2, not 1e-7)."""
    base_losses, _, _ = _step_grads(*_build(None, drop=0.3), steps=2)
    for policy in ("full", "selective"):
        opt_losses, _, exe = _step_grads(*_build(policy, drop=0.3), steps=2)
        assert exe.last_remat_plan
        for b, o in zip(base_losses, opt_losses):
            np.testing.assert_array_equal(b, o)


def test_scan_engine_bit_identical_to_barrier_fallback():
    """The scan engine must be numerically INVISIBLE: scanned execution
    bit-identical (loss and grads) to the barrier per-segment execution
    of the same policy."""
    try:
        os.environ["PADDLE_TPU_SCAN_REMAT"] = "1"
        l1, g1, exe = _step_grads(*_build("full"))
        assert exe.last_remat_plan
        os.environ["PADDLE_TPU_SCAN_REMAT"] = "0"
        l0, g0, exe = _step_grads(*_build("full"))
        assert not exe.last_remat_plan
    finally:
        os.environ.pop("PADDLE_TPU_SCAN_REMAT", None)
    np.testing.assert_array_equal(l1[0], l0[0])
    for n in g1:
        np.testing.assert_array_equal(g1[n], g0[n], err_msg=n)


_NO_FUSION_PROBE = textwrap.dedent("""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.core.program import GRAD_SUFFIX
    from paddle_tpu.models import transformer

    def build(policy, accum):
        pt.core.unique_name.reset()
        main, startup = pt.Program(), pt.Program()
        main.random_seed = 11
        with pt.program_guard(main, startup):
            outs = transformer.build(vocab_size=30, n_layer=2, n_head=2,
                                     d_model=32, max_len=12,
                                     dropout_rate=0.0, dtype="float32")
        if accum > 1:
            pt.gradient_accumulation(main, accum)
        if policy:
            pt.memory_optimize(main, policy=policy)
        return main, startup, outs["avg_cost"]

    rng = np.random.default_rng(3)
    toks = rng.integers(0, 30, (4, 12)).astype(np.int64)
    feed = {"tokens": toks, "labels": np.roll(toks, -1, axis=1)}

    def grads(main, startup, loss):
        scope = pt.Scope()
        pt.core.scope._scope_stack.append(scope)
        try:
            exe = pt.Executor()
            exe.run(startup, scope=scope)
            known = {n for blk in main.blocks for n in blk.vars}
            gnames = [p.name + GRAD_SUFFIX for p in main.all_parameters()
                      if p.name + GRAD_SUFFIX in known]
            outs = exe.run(main, feed=feed, fetch_list=[loss] + gnames,
                           scope=scope)
            return dict(zip(["loss"] + gnames,
                            [np.asarray(o) for o in outs]))
        finally:
            pt.core.scope._scope_stack.pop()

    for accum in (1, 2):
        base = grads(*build(None, accum))
        for policy in ("full", "compact"):
            opt = grads(*build(policy, accum))
            for n in base:
                np.testing.assert_array_equal(
                    base[n], opt[n],
                    err_msg=f"{policy} accum={accum} {n}")
    print("EXACT_OK")
""")


def test_remat_loss_and_grads_bit_exact_without_fusion():
    """The acceptance-criterion exactness run: with XLA's fusion pass
    disabled (so the only difference between the two graphs is the remat
    structure itself), full and compact remat x accum {1, 2} produce
    BIT-EXACT loss AND gradients vs the unrematted step.  Subprocess
    because XLA_FLAGS is read once per process.  (selective's finer
    checkpoint islands reassociate cotangent sums in the HLO itself —
    its ulp-bound is pinned in-process above.)"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_disable_hlo_passes=fusion,cpu-fusion")
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", ""))
    res = subprocess.run([sys.executable, "-c", _NO_FUSION_PROBE],
                         capture_output=True, text=True, env=env,
                         timeout=600)
    assert res.returncode == 0, res.stderr[-3000:]
    assert "EXACT_OK" in res.stdout


def test_full_policy_layer_aligned_segments():
    """memory_optimize(policy='full') cuts at the repeated-structure
    boundaries (one transformer block per segment), tiling the forward
    prefix."""
    main, _, _ = _build("full", n_layer=3)
    segs = main._remat_segments
    bw = main.global_block().backward_index
    assert segs[0][0] == 0 and segs[-1][1] == bw
    for (a, b, _), (c, d, _) in zip(segs, segs[1:]):
        assert b == c
    sizes = [t - s for s, t, w in segs if w]
    # three equal-size block segments among the wrapped ones
    assert sizes.count(max(set(sizes), key=sizes.count)) >= 3


def test_detect_repeated_run_finds_blocks():
    main, _, _ = _build(None, n_layer=3)
    bw = main.global_block().backward_index
    rep = detect_repeated_run(main, 0, bw)
    assert rep is not None
    s0, p, count = rep
    assert count == 3


def test_match_op_run_rejects_shape_mismatch():
    """Structural matching must reject runs whose paired external inputs
    have different static shapes (stacking needs uniform operands)."""
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        from paddle_tpu import layers

        x = layers.data("x", shape=[16])
        h1 = layers.fc(input=x, size=32, act="relu")    # W [16, 32]
        h2 = layers.fc(input=h1, size=32, act="relu")   # W [32, 32]
        h3 = layers.fc(input=h2, size=32, act="relu")   # W [32, 32]
        loss = layers.mean(h3)
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    ops = main.global_block().ops
    # fc lowers to (mul, elementwise_add, relu)
    assert match_op_run(main, ops[0:3], ops[3:6]) is None  # 16x32 vs 32x32
    assert match_op_run(main, ops[3:6], ops[6:9]) is not None


def test_scan_groups_selective_and_compact():
    """find_uniform_groups recovers multi-segment periods: selective's
    per-layer [wrapped cheap-run / unwrapped kernel] pattern and
    compact's [unwrapped kernel / wrapped everything-else] pattern."""
    for policy, n_layer in (("selective", 3), ("compact", 3)):
        main, _, _ = _build(policy, n_layer=n_layer)
        groups = find_uniform_groups(main, main._remat_segments)
        assert groups, policy
        best = max(groups, key=lambda g: g["count"])
        assert best["count"] >= 2, (policy, groups)


def test_scan_remat_env_kill_switch():
    """PADDLE_TPU_SCAN_REMAT=0 must route every segment through the
    barrier fallback and still train (loss bit-exact vs baseline)."""
    base_losses, _, _ = _step_grads(*_build(None))
    try:
        os.environ["PADDLE_TPU_SCAN_REMAT"] = "0"
        losses, _, exe = _step_grads(*_build("full"))
        assert not exe.last_remat_plan
    finally:
        os.environ.pop("PADDLE_TPU_SCAN_REMAT", None)
    np.testing.assert_array_equal(base_losses[0], losses[0])


def test_scan_remat_composes_with_run_steps():
    """The scanned remat group nests inside run_steps' outer lax.scan
    (scan-in-scan) and matches step-by-step run() exactly."""
    main, startup, loss = _build("full")
    scope = pt.Scope()
    pt.core.scope._scope_stack.append(scope)
    try:
        exe = pt.Executor()
        exe.run(startup, scope=scope)
        feed = _feed()
        stacked = {n: np.stack([v, v]) for n, v in feed.items()}
        (fetched,) = exe.run_steps(main, feed=stacked, fetch_list=[loss],
                                   scope=scope)
    finally:
        pt.core.scope._scope_stack.pop()

    scope = pt.Scope()
    pt.core.scope._scope_stack.append(scope)
    try:
        exe2 = pt.Executor()
        exe2.run(startup, scope=scope)
        seq = [np.asarray(exe2.run(main, feed=_feed(), fetch_list=[loss],
                                   scope=scope)[0]) for _ in range(2)]
    finally:
        pt.core.scope._scope_stack.pop()
    np.testing.assert_array_equal(np.asarray(fetched).ravel(),
                                  np.asarray(seq).ravel())
