"""Op tests for the math group — check_output vs numpy + check_grad
(analytic vs numeric), mirroring fluid's per-op test files (SURVEY §4)."""

import numpy as np
import pytest

from op_test import check_output, check_grad, run_op

rng = np.random.RandomState(42)


def test_elementwise_add_broadcast_axis():
    x = rng.randn(2, 3, 4).astype(np.float32)
    y = rng.randn(3).astype(np.float32)
    check_output(
        "elementwise_add", {"X": x, "Y": y},
        {"Out": x + y.reshape(1, 3, 1)}, attrs={"axis": 1},
    )


def test_elementwise_ops_trailing_broadcast():
    x = rng.randn(4, 5).astype(np.float32)
    y = rng.randn(5).astype(np.float32)
    check_output("elementwise_mul", {"X": x, "Y": y}, {"Out": x * y})
    check_output("elementwise_sub", {"X": x, "Y": y}, {"Out": x - y})
    check_output("elementwise_max", {"X": x, "Y": y}, {"Out": np.maximum(x, y)})


@pytest.mark.parametrize("op,ref", [
    ("elementwise_add", lambda x, y: x + y),
    ("elementwise_mul", lambda x, y: x * y),
    ("elementwise_div", lambda x, y: x / y),
])
def test_elementwise_grad(op, ref):
    x = rng.rand(3, 4).astype(np.float32) + 0.5
    y = rng.rand(3, 4).astype(np.float32) + 0.5
    check_grad(op, {"X": x, "Y": y}, "X")
    check_grad(op, {"X": x, "Y": y}, "Y")


def test_mul_flatten():
    x = rng.randn(2, 3, 4).astype(np.float32)
    y = rng.randn(12, 5).astype(np.float32)
    out = x.reshape(2, 12) @ y
    check_output(
        "mul", {"X": x, "Y": y}, {"Out": out.reshape(2, 5)},
        attrs={"x_num_col_dims": 1, "y_num_col_dims": 1},
    )
    check_grad("mul", {"X": x, "Y": y}, "X")
    check_grad("mul", {"X": x, "Y": y}, "Y")


def test_matmul_transpose():
    x = rng.randn(3, 4).astype(np.float32)
    y = rng.randn(5, 4).astype(np.float32)
    check_output(
        "matmul", {"X": x, "Y": y}, {"Out": x @ y.T},
        attrs={"transpose_Y": True}, atol=1e-4,
    )
    check_grad("matmul", {"X": x, "Y": y}, "X", attrs={"transpose_Y": True})


def test_sum_multiple_inputs():
    xs = [rng.randn(2, 3).astype(np.float32) for _ in range(3)]
    check_output("sum", {"X": xs}, {"Out": xs[0] + xs[1] + xs[2]})


def test_reduce_ops():
    x = rng.randn(2, 3, 4).astype(np.float32)
    check_output("reduce_sum", {"X": x}, {"Out": x.sum(1)}, attrs={"dim": 1})
    check_output(
        "reduce_mean", {"X": x}, {"Out": x.mean((0, 2), keepdims=True)},
        attrs={"dim": [0, 2], "keep_dim": True},
    )
    check_output("reduce_max", {"X": x}, {"Out": x.max()}, attrs={"reduce_all": True})
    check_grad("reduce_sum", {"X": x}, "X", attrs={"dim": 1})
    check_grad("reduce_mean", {"X": x}, "X", attrs={"dim": [0, 2]})


def test_scale_clip_sign():
    x = rng.randn(3, 3).astype(np.float32)
    check_output("scale", {"X": x}, {"Out": x * 2.0 + 1.0},
                 attrs={"scale": 2.0, "bias": 1.0})
    check_output("clip", {"X": x}, {"Out": np.clip(x, -0.5, 0.5)},
                 attrs={"min": -0.5, "max": 0.5})
    check_output("sign", {"X": x}, {"Out": np.sign(x)})


def test_clip_by_norm():
    x = (rng.randn(4, 4) * 10).astype(np.float32)
    norm = np.sqrt((x ** 2).sum())
    check_output("clip_by_norm", {"X": x}, {"Out": x * (1.0 / norm)},
                 attrs={"max_norm": 1.0}, atol=1e-4)


def test_cos_sim():
    x = rng.randn(4, 8).astype(np.float32)
    y = rng.randn(4, 8).astype(np.float32)
    expected = (x * y).sum(1) / (
        np.linalg.norm(x, axis=1) * np.linalg.norm(y, axis=1)
    )
    got = run_op("cos_sim", {"X": x, "Y": y})
    np.testing.assert_allclose(got["Out"].reshape(-1), expected, rtol=1e-4)
    check_grad("cos_sim", {"X": x, "Y": y}, "X", max_relative_error=1e-2)


def test_activations_match_numpy():
    x = rng.randn(3, 4).astype(np.float32)
    check_output("sigmoid", {"X": x}, {"Out": 1 / (1 + np.exp(-x))}, atol=1e-5)
    check_output("tanh", {"X": x}, {"Out": np.tanh(x)})
    check_output("relu", {"X": x}, {"Out": np.maximum(x, 0)})
    check_output("square", {"X": x}, {"Out": x * x})
    check_output("leaky_relu", {"X": x},
                 {"Out": np.where(x > 0, x, 0.02 * x)}, attrs={"alpha": 0.02})


@pytest.mark.parametrize("op", ["sigmoid", "tanh", "softplus", "swish", "elu"])
def test_activation_grads(op):
    x = rng.randn(3, 4).astype(np.float32)
    check_grad(op, {"X": x}, "X")


def test_softmax_and_grad():
    x = rng.randn(4, 7).astype(np.float32)
    e = np.exp(x - x.max(-1, keepdims=True))
    check_output("softmax", {"X": x}, {"Out": e / e.sum(-1, keepdims=True)}, atol=1e-5)
    check_grad("softmax", {"X": x}, "X",
               loss_weights=rng.rand(4, 7).astype(np.float32))


def test_l1_norm():
    x = rng.randn(3, 4).astype(np.float32)
    check_output("l1_norm", {"X": x}, {"Out": np.abs(x).sum().reshape(1)})
    check_grad("l1_norm", {"X": x + np.sign(x) * 0.1}, "X")


def test_bilinear_tensor_product():
    b, dx, dy, size = 3, 4, 5, 2
    x = rng.randn(b, dx).astype(np.float32)
    y = rng.randn(b, dy).astype(np.float32)
    w = rng.randn(size, dx, dy).astype(np.float32)
    bias = rng.randn(size).astype(np.float32)
    want = np.einsum("bj,ijk,bk->bi", x, w, y) + bias
    check_output("bilinear_tensor_product",
                 {"X": x, "Y": y, "Weight": w, "Bias": bias},
                 {"Out": want}, atol=1e-4, rtol=1e-4)
    check_grad("bilinear_tensor_product",
               {"X": x, "Y": y, "Weight": w, "Bias": bias}, "Weight")
    check_grad("bilinear_tensor_product",
               {"X": x, "Y": y, "Weight": w, "Bias": bias}, "X")


def test_prelu():
    x = rng.randn(3, 4).astype(np.float32)
    a = np.asarray([0.25], np.float32)
    check_output("prelu", {"X": x, "Alpha": a},
                 {"Out": np.where(x >= 0, x, 0.25 * x)})
    check_grad("prelu", {"X": x + np.sign(x) * 0.1, "Alpha": a}, "Alpha")


def test_error_clip():
    """ErrorClipByValue: forward unchanged, backward error clipped at the
    marked variable (reference fluid/clip.py:37)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt

    # op level: grad of sum(10*x) through error_clip is clipped to 0.1
    from paddle_tpu.core.registry import get_op_impl

    impl = get_op_impl("error_clip").fn

    def f(x):
        y = impl(X=x, max=0.1)["Out"]
        return jnp.sum(10.0 * y)

    g = jax.grad(f)(jnp.ones((3,)))
    np.testing.assert_allclose(np.asarray(g), 0.1)

    # program level: rewrite via error_clip_callback
    x = pt.layers.data("x", shape=[4])
    h = pt.layers.fc(x, 4, bias_attr=False, name="ec_fc")
    out = pt.layers.scale(h, scale=100.0)
    cost = pt.layers.reduce_sum(out)
    clipped = pt.clip.error_clip_callback(h, pt.clip.ErrorClipByValue(0.01))
    pt.optimizer.SGD(learning_rate=1.0).minimize(cost)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.core.scope.global_scope()
    w0 = np.asarray(scope.get("ec_fc.w")).copy()
    xv = np.ones((2, 4), np.float32)
    exe.run(feed={"x": xv}, fetch_list=[cost])
    w1 = np.asarray(scope.get("ec_fc.w"))
    # dL/dW = x^T @ err, err clipped to 0.01 per element, batch 2 -> 0.02;
    # unclipped would be 100 per element
    np.testing.assert_allclose(w0 - w1, 0.02 * np.ones_like(w0),
                               rtol=1e-5, atol=1e-6)


def test_error_clip_after_minimize_keeps_backward_split():
    """Inserting the error-clip op after minimize must shift the
    forward/backward boundary so the step still lowers correctly."""
    import paddle_tpu as pt

    x = pt.layers.data("x", shape=[4])
    h = pt.layers.fc(x, 4, bias_attr=False, name="ec2_fc")
    cost = pt.layers.reduce_sum(pt.layers.scale(h, scale=10.0))
    pt.optimizer.SGD(learning_rate=1.0).minimize(cost)
    pt.clip.error_clip_callback(h, pt.clip.ErrorClipByValue(0.01))
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.core.scope.global_scope()
    w0 = np.asarray(scope.get("ec2_fc.w")).copy()
    xv = np.ones((2, 4), np.float32)
    (c,) = exe.run(feed={"x": xv}, fetch_list=[cost])
    assert np.isfinite(c).all()
    w1 = np.asarray(scope.get("ec2_fc.w"))
    np.testing.assert_allclose(w0 - w1, 0.02 * np.ones_like(w0),
                               rtol=1e-5, atol=1e-6)
