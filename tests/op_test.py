"""OpTest harness — the rebuild of the reference's single most important
test convention (SURVEY §4): python/paddle/v2/fluid/tests/op_test.py, whose
``OpTest.check_output`` runs each op's kernel and compares against a numpy
reference, and ``check_grad`` compares analytic gradients against numeric
finite differences (get_numeric_gradient, op_test.py:97).

TPU translation: ``check_output`` compares the jitted op against the
caller's numpy reference; ``check_grad`` compares jax.grad of the op (the
analytic path every training program uses) against central finite
differences computed with the same op implementation.
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import get_op_impl


def run_op(op_type, inputs, attrs=None, outputs=None):
    """Execute one op impl eagerly; returns dict of numpy outputs."""
    impl = get_op_impl(op_type)
    ins = {
        k: ([jnp.asarray(x) for x in v] if isinstance(v, list) else jnp.asarray(v))
        for k, v in inputs.items()
    }
    outs = impl.call(ins, dict(attrs or {}), None)
    result = {}
    for k, v in outs.items():
        if isinstance(v, (list, tuple)):
            result[k] = [np.asarray(x) for x in v]
        elif v is not None:
            result[k] = np.asarray(v)
    return result


def check_output(op_type, inputs, expected, attrs=None, atol=1e-5, rtol=1e-5):
    got = run_op(op_type, inputs, attrs)
    for name, exp in expected.items():
        np.testing.assert_allclose(
            got[name], exp, atol=atol, rtol=rtol,
            err_msg=f"{op_type} output {name} mismatch",
        )
    return got


def numeric_grad(op_type, inputs, attrs, wrt, output="Out", delta=1e-3,
                 loss_weights=None):
    """Central finite differences of sum(op(x) * w) wrt inputs[wrt]."""
    base = {k: np.asarray(v, np.float64) if not isinstance(v, list) else v
            for k, v in inputs.items()}
    x0 = np.asarray(base[wrt], np.float64)
    grad = np.zeros_like(x0)

    def loss_at(x):
        probe = dict(base)
        probe[wrt] = x.astype(np.float32)
        out = run_op(op_type, probe, attrs)[output]
        w = loss_weights if loss_weights is not None else 1.0
        return float(np.sum(np.asarray(out, np.float64) * w))

    flat = x0.reshape(-1)
    gflat = grad.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + delta
        up = loss_at(x0)
        flat[i] = orig - delta
        down = loss_at(x0)
        flat[i] = orig
        gflat[i] = (up - down) / (2 * delta)
    return grad


def analytic_grad(op_type, inputs, attrs, wrt, output="Out", loss_weights=None):
    impl = get_op_impl(op_type)

    def f(x):
        ins = {
            k: ([jnp.asarray(v) for v in vs] if isinstance(vs, list) else jnp.asarray(vs))
            for k, vs in inputs.items()
        }
        ins[wrt] = x
        out = impl.call(ins, dict(attrs or {}), None)[output]
        w = loss_weights if loss_weights is not None else 1.0
        return jnp.sum(out * w)

    return np.asarray(jax.grad(f)(jnp.asarray(inputs[wrt], jnp.float32)))


def check_grad(op_type, inputs, wrt, attrs=None, output="Out",
               max_relative_error=5e-3, delta=1e-3, loss_weights=None):
    """check_grad: analytic (jax.grad) vs numeric finite differences —
    the dual-path gradient validation of op_test.py:361."""
    ana = analytic_grad(op_type, inputs, attrs, wrt, output, loss_weights)
    num = numeric_grad(op_type, inputs, attrs, wrt, output, delta, loss_weights)
    abs_max = max(np.abs(num).max(), np.abs(ana).max(), 1e-3)
    diff = np.abs(ana - num).max() / abs_max
    assert diff <= max_relative_error, (
        f"{op_type} grad wrt {wrt}: max relative error {diff:.2e} > "
        f"{max_relative_error:.2e}\nanalytic:\n{ana}\nnumeric:\n{num}"
    )
