"""End-to-end tracing engine (observability/trace.py) — span runtime
semantics, disabled-mode overhead path, Chrome-trace export, trainer
step-phase spans, serving request span trees, the bench-history
regression gate (observability/bench_history.py), and the satellite
instrumentation (print_profiler JSONL fold-in, nan_guard trip
accounting, bench row stamps)."""

import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models import transformer
from paddle_tpu.observability import bench_history, get_registry, trace
from paddle_tpu.observability.runlog import RunLog, read_jsonl
from paddle_tpu.serving import ServingEngine


@pytest.fixture
def tracer():
    """A private enabled tracer installed as the global one (trainer /
    serving call sites read the global), restored on exit."""
    t = trace.Tracer(enabled=True, registry=None)
    old = trace.set_tracer(t)
    yield t
    trace.set_tracer(old)


# -- span runtime -----------------------------------------------------------
def test_span_nesting_and_attributes():
    t = trace.Tracer(enabled=True, registry=None)
    with t.span("outer", cat="unit", a=1) as sp:
        sp.set(b="two")
        with t.span("inner", cat="unit"):
            pass
    t.instant("tick", cat="unit", n=3)
    outer = t.events(name="outer")[0]
    inner = t.events(name="inner")[0]
    # nesting is by ts containment within a tid (how Chrome renders it)
    assert outer["tid"] == inner["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert outer["args"] == {"a": 1, "b": "two"}
    assert outer["cat"] == "unit"
    tick = t.events(name="tick")[0]
    assert tick["ph"] == "i" and tick["args"] == {"n": 3}


def test_disabled_mode_is_shared_null_context():
    t = trace.Tracer(enabled=False, registry=None)
    # near-zero overhead: the SAME reusable null context object, no
    # allocation, no event, no host_timer observation
    assert t.span("a") is t.span("b", cat="x", k=1)
    with t.span("a"):
        pass
    # the live-span API works verbatim when disabled: call sites using
    # `as s: s.set(...)` must not crash under PADDLE_TPU_TRACE=0
    with t.span("a") as s:
        assert s.set(batch=3) is s
    t.instant("i")
    t.add_span("r", 0.0, 1.0)
    assert t.events() == []


def test_env_flag_disables_global_tracer(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_TRACE", "0")
    assert trace.Tracer().enabled is False
    monkeypatch.setenv("PADDLE_TPU_TRACE", "1")
    assert trace.Tracer().enabled is True


def test_span_durations_feed_host_timer_namespace():
    reg = get_registry()
    reg.clear(prefix="host_timer.trace_unit")
    t = trace.Tracer(enabled=True)  # default: global registry fold-in
    with t.span("trace_unit_phase"):
        pass
    with t.span("trace_unit_phase"):
        pass
    h = reg.get("host_timer.trace_unit_phase")
    assert h is not None and h.count == 2
    # one aggregation path: print_profiler renders the same histogram
    from paddle_tpu import profiler

    table = profiler.print_profiler()
    assert "trace_unit_phase" in table
    reg.clear(prefix="host_timer.trace_unit")


def test_timer_false_skips_host_timer_fold_in():
    """add_span(timer=False) records the timeline event but NOT the
    host_timer histogram — for lane spans that re-present intervals
    already observed elsewhere (the serving request tree), which would
    otherwise multi-count the same wall seconds in the aggregate."""
    reg = get_registry()
    reg.clear(prefix="host_timer.trace_unit")
    t = trace.Tracer(enabled=True)
    t.add_span("trace_unit_lane", 0.0, 0.5, lane="req 0", timer=False)
    assert len(t.events(name="trace_unit_lane")) == 1
    assert reg.get("host_timer.trace_unit_lane") is None
    reg.clear(prefix="host_timer.trace_unit")


def test_request_lane_spans_not_in_host_timer():
    """The per-request lane tree stays timeline-only: one decode chunk
    is shared by every live request, so folding serving.req.* into
    host_timer would count the same chunk wall time once per request."""
    reg = get_registry()
    reg.clear(prefix="host_timer.serving")
    eng = ServingEngine(_make_params(), 2, 2, 32, max_len=32,
                        max_slots=2, decode_chunk=2, min_bucket=4)
    t2 = trace.Tracer(enabled=True)  # global-registry fold-in
    old = trace.set_tracer(t2)
    try:
        eng.generate_many([np.arange(1, 4, dtype=np.int32)],
                          max_new_tokens=4)
    finally:
        trace.set_tracer(old)
    assert t2.events(name="serving.request")  # the tree was emitted
    assert reg.get("host_timer.serving.request") is None
    assert reg.get("host_timer.serving.req.decode_chunk") is None
    # the driver-thread operational span DOES fold in (1:1 interval)
    assert reg.get("host_timer.serving.decode_chunk") is not None
    reg.clear(prefix="host_timer.serving")


def test_thread_ident_reuse_gets_fresh_tid():
    """tids are allocated per thread OBJECT, not per get_ident() value:
    CPython reuses idents after a thread exits, which would merge a
    later thread onto a dead thread's lane under its stale name."""
    import threading

    t = trace.Tracer(enabled=True, registry=None)
    tids = []

    def work(name):
        th = threading.Thread(
            target=lambda: t.add_span(name, 0.0, 0.001), name=name)
        th.start()
        th.join()

    work("w0")
    work("w1")  # likely the same ident as the dead w0
    e0 = t.events(name="w0")[0]
    e1 = t.events(name="w1")[0]
    assert e0["tid"] != e1["tid"]
    names = t.to_chrome_trace()["traceEvents"]
    lanes = {e["args"]["name"] for e in names
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert {"w0", "w1"} <= lanes


def test_event_buffer_bounded_drops_oldest():
    t = trace.Tracer(enabled=True, registry=None, max_events=8)
    for i in range(20):
        t.add_span(f"s{i}", 0.0, 0.001)
    assert len(t.events()) <= 8
    assert t.dropped > 0
    # the most recent event survives (flight recorder keeps the tail)
    assert t.events()[-1]["name"] == "s19"


def test_chrome_trace_export_required_fields(tmp_path):
    t = trace.Tracer(enabled=True, registry=None)
    with t.span("a", cat="unit"):
        pass
    t.add_span("lane", 0.0, 0.002, lane="virtual 0")
    t.instant("mark")
    path = str(tmp_path / "trace.json")
    n = t.save(path)
    assert n == 3
    obj = json.load(open(path))
    assert "traceEvents" in obj
    xs = [e for e in obj["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 2
    for e in xs:
        for k in ("ph", "ts", "dur", "pid", "tid", "name"):
            assert k in e, f"missing {k}: {e}"
    # virtual lane got a thread_name metadata record
    metas = [e for e in obj["traceEvents"] if e["ph"] == "M"]
    assert any(e["name"] == "thread_name"
               and e["args"]["name"] == "virtual 0" for e in metas)


# -- trainer instrumentation ------------------------------------------------
PHASES = ("trainer.reader_wait", "trainer.feed_h2d", "trainer.dispatch",
          "trainer.device_sync", "trainer.opt_boundary")


def _train_lenet(batches=3):
    from paddle_tpu.models import lenet

    model = lenet.build(learning_rate=0.01)
    trainer = pt.trainer.Trainer(model["avg_cost"], model["feed"])
    rng = np.random.default_rng(0)

    def reader():
        for _ in range(batches):
            yield [(rng.normal(size=(1, 28, 28)).astype(np.float32),
                    int(rng.integers(0, 10))) for _ in range(4)]

    trainer.train(reader, num_passes=1)


def test_trainer_step_emits_five_phase_spans(tracer):
    _train_lenet(batches=3)
    steps = tracer.events(name="trainer.step")
    assert len(steps) == 3
    for name in PHASES:
        evs = tracer.events(name=name)
        assert len(evs) == 3, f"{name}: {len(evs)} spans"
    # phases nest inside their step span (reader_wait legitimately sits
    # before the step window)
    for d in tracer.events(name="trainer.dispatch"):
        assert any(s["tid"] == d["tid"] and s["ts"] <= d["ts"]
                   and d["ts"] + d["dur"] <= s["ts"] + s["dur"] + 1e-3
                   for s in steps)
    # step spans carry pass/batch attribution
    assert {s["args"]["batch"] for s in steps} == {0, 1, 2}


def test_trainer_host_timer_aggregates_are_disjoint():
    """The phase timers are the host_timer.* aggregation; trainer.step
    (whose window IS the phases) and the old unfused-path train_batch
    (whose window was exactly feed_h2d+dispatch+device_sync) stay out —
    otherwise print_profiler's %-of-total counts every step's wall
    seconds two or three times over."""
    reg = get_registry()
    t = trace.Tracer(enabled=True)  # default: folds into the registry
    old = trace.set_tracer(t)
    try:
        reg.clear(prefix="host_timer.trainer")
        reg.clear(prefix="host_timer.train_batch")
        _train_lenet(batches=3)
        for name in PHASES:
            h = reg.get("host_timer." + name)
            assert h is not None and h.count == 3, name
        assert reg.get("host_timer.trainer.step") is None
        assert reg.get("host_timer.train_batch") is None
    finally:
        trace.set_tracer(old)
        reg.clear(prefix="host_timer.trainer")


# -- serving request span tree ----------------------------------------------
def _make_params(vocab=50, n_layer=2, n_head=2, d_model=32, max_len=32):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        transformer.build(vocab_size=vocab, n_layer=n_layer,
                          n_head=n_head, d_model=d_model, max_len=max_len,
                          dropout_rate=0.0, dtype="float32")
    exe = pt.Executor()
    exe.run(startup)
    return transformer.extract_params(program=main)


def test_serving_request_span_tree_sums_to_e2e(tracer):
    params = _make_params()
    eng = ServingEngine(params, 2, 2, 32, max_len=32, max_slots=2,
                        decode_chunk=2, min_bucket=4)
    # warm the SAME shapes the traced request will use (a length-5
    # prompt lands in the bucket-8 prefill, not the warmup-3 bucket-4
    # one) with disjoint tokens so the prefix cache cannot shortcut the
    # timed prefill — every AOT compile, including the one
    # ``fn.prepare`` pays between admission and the prefill window, is
    # spent here, outside the traced request
    eng.generate_many([np.arange(10, 15, dtype=np.int32)],
                      max_new_tokens=8)
    tracer.clear()
    req = eng.submit(np.arange(1, 6, dtype=np.int32), max_new_tokens=8)
    eng.run_until_idle()
    root = tracer.events(name="serving.request")[0]
    assert root["args"]["rid"] == req.rid
    kids = [e for e in tracer.events(cat="serving")
            if e["name"].startswith("serving.req.")
            and e["tid"] == root["tid"]]
    names = {e["name"] for e in kids}
    assert names >= {"serving.req.queue", "serving.req.prefill",
                     "serving.req.decode_chunk", "serving.req.evict"}
    # children nest within the root; the tree is built from the request
    # handle's own timestamps, so containment is exact — only the wall
    # seconds BETWEEN spans (host scheduling, compile walls) vary by
    # host, and no assertion here depends on them
    for e in kids:
        assert e["ts"] >= root["ts"] - 1e-3
        assert e["ts"] + e["dur"] <= root["ts"] + root["dur"] + 1e-3
    # the phases tile the request in order: queue ends before prefill
    # starts, decode chunks follow prefill sorted and non-overlapping,
    # and the zero-duration evict marker closes the root window
    queue = next(e for e in kids if e["name"] == "serving.req.queue")
    prefill = next(e for e in kids if e["name"] == "serving.req.prefill")
    chunks = sorted((e for e in kids
                     if e["name"] == "serving.req.decode_chunk"),
                    key=lambda e: e["ts"])
    evict = next(e for e in kids if e["name"] == "serving.req.evict")
    assert queue["ts"] + queue["dur"] <= prefill["ts"] + 1e-3
    assert chunks and prefill["ts"] + prefill["dur"] <= chunks[0]["ts"] + 1e-3
    for a, b in zip(chunks, chunks[1:]):
        assert a["ts"] + a["dur"] <= b["ts"] + 1e-3
    # 7 post-prefill tokens at decode_chunk=2 -> 4 chunks
    assert len(chunks) == 4
    assert evict["dur"] == 0
    assert evict["ts"] == pytest.approx(root["ts"] + root["dur"], abs=1.0)
    # disjoint children can never exceed the root window they tile
    cover = sum(e["dur"] for e in kids)
    assert cover <= 1.001 * root["dur"]
    # root duration IS the request e2e (microseconds vs seconds) — two
    # views of the same submit->finish timestamps
    assert root["dur"] == pytest.approx(req.e2e * 1e6, rel=0.05)


def test_request_lanes_never_shared_by_overlapping_requests():
    """Chrome/Perfetto derive nesting purely from ts/dur containment
    within a tid, so two requests whose windows overlap must NEVER land
    on one lane (they would render as one false tree); a lane is reused
    only once its previous occupant finished before the next submit."""
    import types

    class R:
        def __init__(self, submit_t, finish_t):
            self.submit_t, self.finish_t = submit_t, finish_t

    eng = types.SimpleNamespace(_req_lane_ends=[])
    lane = ServingEngine._req_lane
    # finish order: B [1,2] emits before the long-lived A [0,10]
    assert lane(eng, R(1.0, 2.0)) == 0
    assert lane(eng, R(0.0, 10.0)) == 1   # overlaps B -> own lane
    assert lane(eng, R(3.0, 4.0)) == 0    # lane 0 free again -> reused
    assert lane(eng, R(5.0, 11.0)) == 0   # still free after reuse
    assert lane(eng, R(6.0, 7.0)) == 2    # 0 and 1 both busy -> new


def test_serving_ttft_decomposition(tracer):
    params = _make_params()
    eng = ServingEngine(params, 2, 2, 32, max_len=32, max_slots=2,
                        decode_chunk=2, min_bucket=4)
    # warm the bucket-8 prefill the length-5 prompt below will use
    # (disjoint tokens: a prefix hit would change the timed suffix) so
    # the ``fn.prepare`` compile wall — which lands between admission
    # and the prefill window, i.e. inside TTFT but outside both
    # decomposition terms — is paid here
    eng.generate_many([np.arange(10, 15, dtype=np.int32)],
                      max_new_tokens=4)
    reg = get_registry()
    for nm in ("serving.ttft_seconds", "serving.queue_wait"):
        reg.get(nm).reset()
    req = eng.submit(np.arange(1, 6, dtype=np.int32), max_new_tokens=4)
    eng.run_until_idle()
    st = eng.stats()
    assert st["serving.queue_wait"]["count"] == 1
    assert st["serving.decode_chunk"]["count"] >= 1
    queue = st["serving.queue_wait"]["mean"]
    prefill = req.prefill_t1 - req.prefill_t0
    ttft = st["serving.ttft_seconds"]["mean"]
    # the histogram and the request handle observe the SAME
    # submit -> first-token window: identical up to float noise
    assert ttft == pytest.approx(req.ttft, rel=1e-6)
    # the decomposition: queue wait and prefill are disjoint
    # sub-windows of TTFT measured from the same clock, so their sum
    # can never exceed it; the residual (admission bookkeeping between
    # admit_t and prefill_t0) is host wall the engine deliberately
    # keeps OUT of both terms — bounding it would re-introduce the
    # compile/scheduler wall sensitivity this test had at seed
    assert queue >= 0 and prefill > 0
    assert queue + prefill <= ttft + 1e-6
    assert ttft <= req.e2e + 1e-6


# -- bench history ----------------------------------------------------------
def _write(d, name, data):
    with open(os.path.join(str(d), name), "w") as fh:
        json.dump(data, fh)


def _fixture(tmp_path):
    _write(tmp_path, "BENCH_r01.json",
           {"n": 1, "rc": 0, "parsed": {"metric": "m", "value": 100.0}})
    _write(tmp_path, "BENCH_r02.json",
           {"n": 2, "rc": 0, "parsed": {"metric": "m", "value": 104.0,
                                        "run_id": "abc", "git_sha": "d"}})
    _write(tmp_path, "BENCH_r03.json",
           {"n": 3, "rc": 0, "parsed": {"metric": "m", "value": 42.0}})
    _write(tmp_path, "BENCH_r04.json",
           {"n": 4, "rc": 1, "parsed": None})
    _write(tmp_path, "MULTICHIP_r01.json",
           {"n_devices": 8, "rc": 0, "ok": True})


def test_bench_history_classifies_failed_and_flags_regression(tmp_path):
    _fixture(tmp_path)
    summary, rows = bench_history.history(str(tmp_path), threshold=0.1)
    assert summary["artifacts"] == 5
    assert summary["failed"] == ["BENCH_r04.json"]
    assert "rc=1" in summary["failed_reasons"]["BENCH_r04.json"][0] or \
        any("rc=1" in r for r in summary["failed_reasons"]["BENCH_r04.json"])
    regs = summary["regressions"]
    assert len(regs) == 1
    assert regs[0]["artifact"] == "BENCH_r03.json"
    assert regs[0]["best"] == 104.0 and regs[0]["value"] == 42.0
    assert not summary["ok"]
    # row identity stamps surface in the classification
    r02 = next(r for r in rows if r["artifact"] == "BENCH_r02.json")
    assert r02["run_id"] == "abc" and r02["git_sha"] == "d"
    # small dips below the threshold do NOT flag
    summary2, _ = bench_history.history(str(tmp_path), threshold=0.7)
    assert summary2["regressions"] == []


def test_bench_history_acknowledged_failures_pass_the_gate(tmp_path):
    _fixture(tmp_path)
    # acks are scoped: failures by artifact name, regressions by
    # artifact:metric — a failure ack must not cover a regression
    known = {"BENCH_r04.json": "known OOM", "BENCH_r03.json:m": "known dip"}
    summary, _ = bench_history.history(str(tmp_path), threshold=0.1,
                                       known_failures=known)
    assert summary["failed"] == ["BENCH_r04.json"]  # still classified
    assert len(summary["regressions"]) == 1         # still flagged
    assert set(summary["acknowledged"]) == {"BENCH_r03.json:m",
                                            "BENCH_r04.json"}
    assert summary["ok"]  # ...but the gate passes
    # a bare-artifact ack does NOT green-light the regression
    summary2, _ = bench_history.history(
        str(tmp_path), threshold=0.1,
        known_failures={"BENCH_r04.json": "known OOM",
                        "BENCH_r03.json": "stale failure ack"})
    assert not summary2["ok"]


def test_bench_history_regression_exempt_metrics(tmp_path):
    """Virtual-CPU-mesh scaling_efficiency is indicative only (shared
    host cores): it shows in the trajectory but never flags."""
    _write(tmp_path, "MULTICHIP_r01.json",
           {"n_devices": 8, "rc": 0, "ok": True,
            "tail": json.dumps({"metric": "multichip_scaling",
                                "scaling_efficiency": 0.9})})
    _write(tmp_path, "MULTICHIP_r02.json",
           {"n_devices": 8, "rc": 0, "ok": True,
            "tail": json.dumps({"metric": "multichip_scaling",
                                "scaling_efficiency": 0.2})})  # 78% drop
    summary, rows = bench_history.history(str(tmp_path), threshold=0.1)
    assert [r["metrics"] for r in rows] == [
        {"scaling_efficiency": 0.9}, {"scaling_efficiency": 0.2}]
    assert "scaling_efficiency" in summary["metrics_tracked"]
    assert summary["regressions"] == [] and summary["ok"]


def test_bench_history_missing_row_keys(tmp_path):
    _write(tmp_path, "BENCH_r01.json",
           {"n": 1, "rc": 0, "parsed": {"unit": "img/s"}})
    summary, rows = bench_history.history(str(tmp_path))
    assert summary["failed"] == ["BENCH_r01.json"]
    reasons = " ".join(rows[0]["reasons"])
    assert "metric" in reasons and "value" in reasons


def test_bench_history_non_object_artifact_classifies(tmp_path):
    """Valid JSON that is not an object (truncated/corrupt write) is a
    classified rot class, not a gate crash."""
    (tmp_path / "BENCH_r03.json").write_text("[1, 2]")
    summary, rows = bench_history.history(str(tmp_path))
    assert summary["failed"] == ["BENCH_r03.json"]
    assert rows[0]["round"] == 3
    assert "not a JSON object" in rows[0]["reasons"][0]


def test_repo_artifacts_pass_the_acknowledged_gate():
    """The tier-1 contract: the REAL repo trajectory passes with the
    checked-in known-failures file (BENCH_r05 / MULTICHIP_r01 are
    root-caused and acknowledged, not silently green)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "tools",
                           "bench_known_failures.json")) as fh:
        known = json.load(fh)
    summary, _ = bench_history.history(root, known_failures=known)
    assert "BENCH_r05.json" in summary["failed"]
    assert summary["ok"], summary


def test_run_stamp_fields():
    s = bench_history.run_stamp()
    assert s["schema_version"] == bench_history.SCHEMA_VERSION == 1
    assert len(s["run_id"]) == 12
    # inside this checkout the sha resolves; elsewhere it may be None
    assert s["git_sha"] is None or len(s["git_sha"]) == 12
    assert s["run_id"] != bench_history.run_stamp()["run_id"]


# -- satellites -------------------------------------------------------------
def test_print_profiler_log_emits_profiler_event(tmp_path):
    from paddle_tpu import profiler

    profiler.reset_profiler()
    with profiler.timer("logged_phase"):
        pass
    p = str(tmp_path / "run.jsonl")
    with RunLog(p) as log:
        profiler.print_profiler(log=log)
    recs = read_jsonl(p, event="profiler")
    assert len(recs) == 1
    timers = {t["event"]: t for t in recs[0]["timers"]}
    assert timers["logged_phase"]["calls"] == 1
    assert timers["logged_phase"]["total"] >= 0
    assert "pct" in timers["logged_phase"]
    profiler.reset_profiler()


def test_nan_guard_trip_records_counter_and_instant(tracer):
    import jax.numpy as jnp

    from paddle_tpu import profiler

    reg = get_registry()
    c0 = reg.value("executor.nan_trips")
    with pytest.raises(FloatingPointError):
        with profiler.nan_guard():
            np.asarray(jnp.log(jnp.zeros(()) - 1.0))
    assert reg.value("executor.nan_trips") == c0 + 1
    trips = tracer.events(name="nan_guard_trip")
    assert len(trips) == 1 and trips[0]["ph"] == "i"


def test_executor_check_nan_inf_records_trip(tracer):
    from paddle_tpu import layers
    from paddle_tpu.flags import FLAGS

    reg = get_registry()
    c0 = reg.value("executor.nan_trips")
    x = layers.data("x", shape=[4])
    y = layers.log(x) if hasattr(layers, "log") else layers.sqrt(x)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    FLAGS.check_nan_inf = True
    try:
        with pytest.raises(FloatingPointError):
            exe.run(feed={"x": -np.ones((2, 4), np.float32)},
                    fetch_list=[y])
    finally:
        FLAGS.check_nan_inf = False
    assert reg.value("executor.nan_trips") == c0 + 1
    assert tracer.events(name="nan_guard_trip")
