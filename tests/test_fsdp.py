"""FSDP / ZeRO-3 parameter sharding inside the scan-remat body
(docs/parallel.md "FSDP"): spec composition rules, structural tagging,
the sharding_report accounting, the in-loop-gather comm contract, the
recorded replication fallbacks, and bit-exactness vs the replicated
spelling on dp x fsdp (x tp) meshes — including an indivisible-shape
model that must take the fallback and still train bit-exact."""

import os

import numpy as np
import jax
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.models import transformer
from paddle_tpu.parallel import api as papi
from paddle_tpu.parallel.mesh import make_mesh

VOCAB, HEADS, SEQ = 64, 2, 16


def _mesh(axes):
    return make_mesh(axes, devices=jax.devices()[:8])


def _m_first_tagged(program):
    return sorted(n for n, v in program.global_block().vars.items()
                  if getattr(v, "fsdp_param", False))[0]


def _build_gpt(n_layer=3, d_model=64, accum=1, memopt=True,
               dropout=0.0, vocab=VOCAB):
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 7
    with pt.program_guard(main, startup):
        outs = transformer.build(
            vocab_size=vocab, n_layer=n_layer, n_head=HEADS,
            d_model=d_model, max_len=SEQ, dropout_rate=dropout,
            dtype="float32", learning_rate=1e-2)
    if memopt:
        pt.memory_optimize(main, policy="selective")
    if accum > 1:
        pt.gradient_accumulation(main, accum)
    return main, startup, outs


def _gpt_feed(batch=16, seed=5, vocab=VOCAB):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, vocab, (batch, SEQ)).astype(np.int64)
    lbls = np.roll(toks, -1, axis=1)
    lbls[:, -1] = -1
    return {"tokens": toks, "labels": lbls}


def _train(mesh, fsdp_env, build_kwargs=None, steps=3, batch=16,
           dp_axis="dp", tp=False, grad_fetch=True, rs=None):
    """Train on ``mesh`` with PADDLE_TPU_FSDP=``fsdp_env`` (and, when
    ``rs`` is given, PADDLE_TPU_ZERO3_RS=``rs``); returns
    (losses, grads, params, cost, accum_plan, remat_plan, report, scope,
    main, tagged, comm_plan)."""
    os.environ["PADDLE_TPU_FSDP"] = fsdp_env
    if rs is not None:
        os.environ["PADDLE_TPU_ZERO3_RS"] = rs
    try:
        main, startup, outs = _build_gpt(**(build_kwargs or {}))
        if tp:
            for prog in (main, startup):
                papi.shard_parameters_by_rule(prog, transformer.tp_rules())
        if dp_axis:
            papi.data_parallel(main, dp_axis, programs=(startup,))
        tagged = papi.shard_fsdp(main, programs=(startup,))
        scope = pt.Scope()
        pt.core.scope._scope_stack.append(scope)
        try:
            exe = pt.Executor(mesh=mesh)
            exe.run(startup, scope=scope)
            fetch = [outs["avg_cost"]]
            if grad_fetch and tagged:
                fetch += [tagged[0] + "@GRAD", "lm_head.w@GRAD"]
            feed = _gpt_feed(batch=batch,
                             vocab=(build_kwargs or {}).get("vocab",
                                                            VOCAB))
            losses, grads = [], []
            for _ in range(steps):
                r = exe.run(main, feed=feed, fetch_list=fetch,
                            scope=scope)
                losses.append(np.asarray(r[0]))
                grads.append([np.asarray(g) for g in r[1:]])
            params = {v.name: np.asarray(scope.get(v.name))
                      for v in main.all_parameters()}
            return (losses, grads, params, dict(exe.last_step_cost),
                    exe.last_accum_plan,
                    list(getattr(exe, "last_remat_plan", []) or []),
                    papi.sharding_report(main, mesh), scope, main,
                    tagged, exe.last_comm_plan)
        finally:
            pt.core.scope._scope_stack.pop()
    finally:
        os.environ.pop("PADDLE_TPU_FSDP", None)
        if rs is not None:
            os.environ.pop("PADDLE_TPU_ZERO3_RS", None)


# -- fsdp_spec_for rules ----------------------------------------------------
def test_fsdp_spec_for_rules(monkeypatch):
    """Leading-axis composition with tp, divisibility fallbacks with
    recorded reasons, the kill switch, and untagged vars."""
    main, _startup, _ = _build_gpt(memopt=False)
    mesh = _mesh({"dp": 2, "fsdp": 2, "tp": 2})
    block = main.global_block()
    w = block.vars["block0_ffn1.w"]          # [64, 256]
    assert papi.fsdp_spec_for(w, mesh, block) is None  # not tagged
    w.fsdp_param = True
    assert papi.fsdp_spec_for(w, mesh, block) == P("fsdp", None)

    # composes ON TOP of a tp spec: free leading axis gains fsdp...
    w.partition_spec = P(None, "tp")
    assert papi.fsdp_spec_for(w, mesh, block) == P("fsdp", "tp")
    # ...and a tp-sharded leading axis composes into a tuple entry
    w.partition_spec = P("tp", None)
    assert papi.fsdp_spec_for(w, mesh, block) == P(("tp", "fsdp"), None)
    # _spec_for resolves the composition ahead of the explicit spec
    assert papi._spec_for(w, mesh, block) == P(("tp", "fsdp"), None)

    # indivisible leading dim: fallback recorded with the reason
    odd = block.create_var(name="odd.w", shape=[31, 8],
                           dtype="float32", persistable=True)
    odd.fsdp_param = True
    reg = pt.observability.get_registry()
    before = reg.value("parallel.shard_fallbacks") or 0
    assert papi.fsdp_spec_for(odd, mesh, block) is None
    assert papi._spec_for(odd, mesh, block) == P()
    recs = block._shard_fallbacks
    assert ("odd.w", "fsdp") in recs
    assert "31" in recs[("odd.w", "fsdp")]
    assert (reg.value("parallel.shard_fallbacks") or 0) == before + 1
    # recording is idempotent per (var, axis)
    papi.fsdp_spec_for(odd, mesh, block)
    assert (reg.value("parallel.shard_fallbacks") or 0) == before + 1

    # kill switch and meshes without an fsdp axis resolve to None
    monkeypatch.setenv("PADDLE_TPU_FSDP", "0")
    assert papi.fsdp_spec_for(w, mesh, block) is None
    monkeypatch.delenv("PADDLE_TPU_FSDP")
    assert papi.fsdp_spec_for(w, _mesh({"dp": 8}), block) is None
    assert papi.fsdp_spec_for(w, None, block) is None


def test_zero_spec_inherits_fsdp_composition():
    """An FSDP weight's optimizer accumulators shard along with it (the
    ZeRO-3 state discipline), and the skipped-dp fallback of an
    indivisible accumulator is recorded."""
    main, _startup, _ = _build_gpt(memopt=False)
    mesh = _mesh({"dp": 2, "fsdp": 4})
    block = main.global_block()
    mom = next(n for n in sorted(block.vars) if n.endswith("_moment1")
               and "ffn1.w" in n)
    var = block.vars[mom]
    pvar = block._find_var(var.zero_param)
    pvar.fsdp_param = True
    spec = papi.zero_spec_for(var, mesh, block)
    assert spec == P("fsdp", None)  # inherited; leading axis taken
    # fsdp off -> plain ZeRO-1 dp shard on the free leading axis
    os.environ["PADDLE_TPU_FSDP"] = "0"
    try:
        assert papi.zero_spec_for(var, mesh, block) == P("dp", None)
    finally:
        os.environ.pop("PADDLE_TPU_FSDP", None)
    # indivisible accumulator: dp shard skipped, reason recorded
    odd = block.create_var(name="odd_m", shape=[7, 4], dtype="float32",
                           persistable=True)
    odd.zero_param = var.zero_param
    pvar.fsdp_param = False
    assert papi.zero_spec_for(odd, mesh, block) is None
    assert ("odd_m", "dp") in block._shard_fallbacks


def test_shard_fsdp_tags_per_layer_params():
    """The structural matcher tags the per-layer (scan-stacked) weights
    PLUS the prologue/epilogue 2-D tables (embedding table, positional
    table, LM head — the fully-sharded-everything discipline, their
    gathers live outside the scan) — on the startup program too."""
    main, startup, _ = _build_gpt(n_layer=3)
    tagged = papi.shard_fsdp(main, programs=(startup,))
    # 16 per-layer params per period + tok_emb.w/pos_emb.w.w/lm_head.w
    assert len(tagged) == 3 * 16 + 3
    # the period tiling may rotate (an LN pairs with the next block's
    # attention), so ln_f can legitimately ride the last scan
    # iteration — but embeddings and the LM head never repeat
    assert sum(t.startswith("block") for t in tagged) >= 3 * 14
    for name in ("tok_emb.w", "pos_emb.w.w", "lm_head.w"):
        assert name in tagged
        var = main.global_block()._find_var(name)
        assert var is not None and var.fsdp_param
        # prologue tables carry the (fsdp, tp) composition so a free
        # tp axis joins the leading-dim shard on tp meshes
        assert var.fsdp_axes == ("fsdp", "tp")
    svar = startup.global_block()._find_var(tagged[0])
    assert svar is not None and svar.fsdp_param
    # replicate() opts a var back out
    var = main.global_block().vars[tagged[0]]
    papi.replicate(var)
    assert not var.fsdp_param


def test_shard_fsdp_without_remat_segments():
    """No memory_optimize marks: shard_fsdp falls back to the
    detect_repeated_run tiling and still finds the layer weights (and
    the prologue tables, which never depended on the segments)."""
    main, startup, _ = _build_gpt(n_layer=2, memopt=False)
    tagged = papi.shard_fsdp(main, programs=(startup,))
    assert len(tagged) == 2 * 16 + 3
    assert all(t.startswith("block") for t in tagged
               if t not in ("tok_emb.w", "pos_emb.w.w", "lm_head.w"))


def test_shard_fsdp_empty_is_recorded(monkeypatch):
    """A no-op shard_fsdp (no repeated structure, or the scan engine
    killed) records a program-level fallback instead of returning []
    silently — the 'OOM waiting to happen' discipline."""
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[16])
        y = layers.data("y", shape=[1])
        pred = layers.fc(input=layers.fc(input=x, size=8, act="tanh"),
                         size=1)
        loss = layers.mean(layers.square(pred - y))
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    assert papi.shard_fsdp(main) == []
    recs = main.global_block()._shard_fallbacks
    assert ("<program>", "fsdp") in recs
    assert "repeated" in recs[("<program>", "fsdp")]

    # scan engine killed: the segments path also records, via the SAME
    # group derivation the executor runs (_scan_groups_for)
    gpt, _startup, _ = _build_gpt(n_layer=2)
    monkeypatch.setenv("PADDLE_TPU_SCAN_REMAT", "0")
    assert papi.shard_fsdp(gpt) == []
    recs = gpt.global_block()._shard_fallbacks
    assert ("<program>", "fsdp") in recs
    monkeypatch.delenv("PADDLE_TPU_SCAN_REMAT")
    assert papi.shard_fsdp(gpt)  # engine back on: tags apply


def test_sharding_report_accounting():
    """params/opt_state/grads sections with per-device bytes under the
    resolved specs; optimizer_state_report stays the opt_state view."""
    main, startup, _ = _build_gpt(n_layer=3)
    mesh = _mesh({"dp": 2, "fsdp": 4})
    papi.shard_fsdp(main, programs=(startup,))
    rep = papi.sharding_report(main, mesh)
    p = rep["params"]
    assert p["sharded_vars"] == 3 * 16 + 3
    assert p["per_device_bytes"] * 2 <= p["total_bytes"]
    assert p["replicated_per_device_bytes"] == p["total_bytes"]
    # grads account at the boundary pin's spec: under the default
    # reduce-scatter spelling (docs/parallel.md rule 4) each chip holds
    # only its shard of every fsdp-tagged gradient...
    assert rep["grads"]["per_device_bytes"] * 2 <= (
        rep["grads"]["total_bytes"])
    # ...and the kill switch restores the replicated-grad accounting
    os.environ["PADDLE_TPU_ZERO3_RS"] = "0"
    try:
        rep_rs0 = papi.sharding_report(main, mesh)
        assert rep_rs0["grads"]["per_device_bytes"] == (
            rep_rs0["grads"]["total_bytes"])
    finally:
        os.environ.pop("PADDLE_TPU_ZERO3_RS", None)
    assert rep["total_bytes"] == (
        p["total_bytes"] + rep["opt_state"]["total_bytes"]
        + rep["grads"]["total_bytes"])
    legacy = papi.optimizer_state_report(main, mesh)
    assert legacy["total_bytes"] == rep["opt_state"]["total_bytes"]
    assert legacy["per_device_bytes"] == (
        rep["opt_state"]["per_device_bytes"])
    # meshless: everything replicated
    rep1 = papi.sharding_report(main, None)
    assert rep1["per_device_bytes"] == rep1["total_bytes"]


# -- the tentpole: in-scan gathers, bit-exactness ---------------------------
def test_fsdp_bitexact_dp_fsdp_mesh():
    """dp=2 x fsdp=4, scan-remat + accum=4 local mode: stacked layer
    weights sharded 4-way at rest, all-gathered INSIDE the scan loop,
    zero reduce-class collectives in loop bodies, and loss/grads/params
    bit-exact vs the PADDLE_TPU_FSDP=0 replicated spelling."""
    mesh = _mesh({"dp": 2, "fsdp": 4})
    kw = dict(build_kwargs={"accum": 4}, steps=3)
    l1, g1, p1, c1, plan1, remat1, rep1, scope1, _m, tagged, cp1 = (
        _train(mesh, "1", **kw))
    l0, g0, p0, c0, _plan0, remat0, rep0, _s0, _m0, _t0, _cp0 = (
        _train(mesh, "0", **kw))

    assert [g for g in remat1 if g.get("fsdp")], remat1
    assert all(not g.get("fsdp") for g in remat0), remat0
    assert plan1["mode"] == "local"
    assert c1["reduce_ops_in_loop"] == 0
    gathers_in = c1["collectives_in_loop"] - c1["reduce_ops_in_loop"]
    assert gathers_in > 0
    # boundary discipline under the default reduce-scatter spelling
    # (docs/parallel.md rule 4): every reduce stays at the boundary;
    # each fsdp-tagged grad's full-volume all-reduce@dp becomes one
    # reduce-scatter (count preserved) plus one scalar grad-norm
    # partial all-reduce@fsdp, so the set grows by exactly len(tagged)
    assert c1["reduce_ops"] == c0["reduce_ops"] + len(tagged)
    rs_ops = cp1.select(kind="reduce-scatter", axis="fsdp",
                        in_loop=False)
    assert len(rs_ops) == len(tagged)

    assert rep1["params"]["per_device_bytes"] * 2 <= (
        rep1["params"]["total_bytes"])
    assert rep0["params"]["per_device_bytes"] == (
        rep0["params"]["total_bytes"])
    wsh = str(scope1.get(tagged[0]).sharding.spec)
    assert "fsdp" in wsh, wsh

    for a, b in zip(l1, l0):
        assert np.array_equal(a, b)
    for ga, gb in zip(g1, g0):
        for a, b in zip(ga, gb):
            assert np.array_equal(a, b)
    for k in p1:
        assert np.array_equal(p1[k], p0[k]), k
    reg = pt.observability.get_registry()
    assert (reg.value("executor.fsdp_groups") or 0) > 0


def test_fsdp_bitexact_dp_fsdp_tp_mesh():
    """dp=2 x fsdp=2 x tp=2: the fsdp shard composes with the tp rules
    (qkv stay column-sharded, ffn2 row-shards over (tp, fsdp)) and the
    ZeRO bit-exactness contract still holds."""
    mesh = _mesh({"dp": 2, "fsdp": 2, "tp": 2})
    kw = dict(build_kwargs={"accum": 1}, steps=2, tp=True)
    l1, g1, p1, c1, _plan1, remat1, rep1, _s1, main, tagged, _cp1 = (
        _train(mesh, "1", **kw))
    l0, g0, p0, _c0, _plan0, _r0, rep0, _s0, _m0, _t0, _cp0 = (
        _train(mesh, "0", **kw))
    assert [g for g in remat1 if g.get("fsdp")], remat1
    block = main.global_block()
    ffn2 = block.vars["block0_ffn2.w"]
    assert papi._spec_for(ffn2, mesh, block) == P(("tp", "fsdp"), None)
    assert rep1["params"]["per_device_bytes"] < (
        rep0["params"]["per_device_bytes"])
    # under tp composition the row-sharded matmuls all-reduce over tp
    # inside the layer; fsdp changes the at-rest LAYOUT of their weight
    # operands and XLA's resulting fusion reassociates a handful of
    # gradient elements at the ulp level (~1e-8 abs) — which Adam's
    # rsqrt then amplifies without bound on near-zero-gradient elements
    # (the attention key biases have an IDENTICALLY-zero true gradient:
    # softmax shift invariance).  So tp x fsdp is "close, not
    # bit-identical, like any resharding" — the documented dp=N-vs-dp=1
    # precedent (docs/parallel.md) — while the pure dp x fsdp mesh
    # above is gated fully bit-exact.  The FIRST step is still exact:
    # identical init params through the gathered forward.
    assert np.array_equal(l1[0], l0[0])
    for a, b in zip(l1, l0):
        np.testing.assert_allclose(a, b, rtol=2e-6, atol=0)
    for ga, gb in zip(g1, g0):
        for a, b in zip(ga, gb):
            # atol admits the LM head's near-zero elements: the head is
            # now itself fsdp-sharded (fully-sharded prologue), so its
            # gradient picks up the same ulp-level reassociation
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=5e-7)
    for k in p1:
        if k.endswith("_att_k.b"):
            continue  # zero-true-gradient: trajectory is sign-of-noise
        np.testing.assert_allclose(p1[k], p0[k], rtol=1e-2, atol=1e-4,
                                   err_msg=k)


def test_fsdp_indivisible_fallback_bitexact():
    """fsdp=8 with d_model=36: the [36, .] weights cannot shard 8-way
    and must take the recorded replication fallback (the [144, 36]
    ffn2 still shards) — and training stays bit-exact vs replicated."""
    mesh = _mesh({"fsdp": 8})
    kw = dict(build_kwargs={"n_layer": 2, "d_model": 36}, steps=2,
              dp_axis=None, batch=8)
    l1, g1, p1, _c1, _plan1, remat1, rep1, _s1, main, tagged, _cp1 = (
        _train(mesh, "1", **kw))
    l0, g0, p0, *_ = _train(mesh, "0", **kw)
    block = main.global_block()
    recs = getattr(block, "_shard_fallbacks", {})
    assert any(axis == "fsdp" for (_n, axis) in recs), recs
    # the divisible ffn2 [144, 36] sharded; the [36, .] ones fell back
    assert papi.fsdp_spec_for(
        block.vars["block0_ffn2.w"], mesh, block) == P("fsdp", None)
    assert papi.fsdp_spec_for(
        block.vars["block0_ffn1.w"], mesh, block) is None
    assert rep1["params"]["per_device_bytes"] < (
        rep1["params"]["total_bytes"])
    for a, b in zip(l1, l0):
        assert np.array_equal(a, b)
    for ga, gb in zip(g1, g0):
        for a, b in zip(ga, gb):
            assert np.array_equal(a, b)
    for k in p1:
        assert np.array_equal(p1[k], p0[k]), k

    # the analysis check surfaces the fallbacks as info findings
    from paddle_tpu.analysis import lint

    report = lint(main, levels=("program",),
                  checks=("program.shard-fallback",))
    found = report.by_check("program.shard-fallback")
    assert found and all(f.severity == "info" for f in found)
    assert any("fsdp" in f.message for f in found)


# -- rule 4: the reduce-scatter gradient spelling ---------------------------
@pytest.mark.parametrize("case", ["dp_fsdp", "dp_fsdp_tp",
                                  "fsdp_only_indivisible"])
def test_zero3_rs_bitexact(case):
    """The true-ZeRO-3 gradient spelling vs its PADDLE_TPU_ZERO3_RS=0
    replicated-grad reference, bit-exact across mesh geometries
    (docs/parallel.md rule 4):

    * dp x fsdp — one boundary reduce-scatter@fsdp per tagged grad,
      zero in-loop reduces (``zero3_grad_contract``), grad bytes/device
      below replicated;
    * dp x fsdp x tp — the scatter composes with the tp rules;
    * fsdp-only with an indivisible embedding (vocab=61, d_model=36) —
      no dp axis means no boundary reduce to scatter, so the spelling
      is INERT by design (a bare scatter constraint measurably drifts
      under ``reduce_each`` accumulation), the indivisible tables take
      the recorded replication fallback, and both spellings stay
      bit-exact trivially.
    """
    if case == "dp_fsdp":
        mesh = _mesh({"dp": 2, "fsdp": 4})
        kw = dict(build_kwargs={"accum": 4}, steps=3)
    elif case == "dp_fsdp_tp":
        mesh = _mesh({"dp": 2, "fsdp": 2, "tp": 2})
        kw = dict(build_kwargs={"accum": 4}, steps=3, tp=True)
    else:
        mesh = _mesh({"fsdp": 8})
        kw = dict(build_kwargs={"n_layer": 2, "d_model": 36,
                                "vocab": 61, "accum": 4},
                  steps=2, dp_axis=None, batch=8)
    l1, g1, p1, _c1, _pl1, _r1, rep1, _s1, main, tagged, cp1 = _train(
        mesh, "1", rs="1", **kw)
    l0, g0, p0, _c0, _pl0, _r0, rep0, _s0, _m0, _t0, cp0 = _train(
        mesh, "1", rs="0", **kw)

    # the kill switch restores the replicated-grad spelling exactly
    assert not cp0.select(kind="reduce-scatter")
    if case == "dp_fsdp_tp":
        # tp grads are naturally tp-sharded either way; the scatter
        # still shrinks the per-device gradient residency further
        assert rep1["grads"]["per_device_bytes"] < (
            rep0["grads"]["per_device_bytes"])
    else:
        assert rep0["grads"]["per_device_bytes"] == (
            rep0["grads"]["total_bytes"])

    if case == "fsdp_only_indivisible":
        # no dp axis: grad_rs_spec_for resolves None, both plans agree
        assert not cp1.select(kind="reduce-scatter")
        block = main.global_block()
        assert papi.grad_rs_spec_for(
            block.vars["block0_ffn2.w"], mesh, block) is None
        assert rep1["grads"]["per_device_bytes"] == (
            rep1["grads"]["total_bytes"])
        recs = getattr(block, "_shard_fallbacks", {})
        assert ("tok_emb.w", "fsdp") in recs
        assert ("lm_head.w", "fsdp") in recs
        from paddle_tpu.analysis import lint

        report = lint(main, levels=("program",),
                      checks=("program.shard-fallback",))
        found = report.by_check("program.shard-fallback")
        # (the finding list caps at MAX_FINDINGS and this model falls
        # back a lot, so assert the check fires rather than hunting the
        # prologue entries — recs above already names them)
        assert found and all(f.severity == "info" for f in found)
        assert any(f.data.get("axis") == "fsdp" for f in found)
    else:
        from paddle_tpu.parallel.contracts import zero3_grad_contract

        viol = zero3_grad_contract(mesh).check(cp1)
        assert not viol, viol
        rs_ops = cp1.select(kind="reduce-scatter", axis="fsdp",
                            in_loop=False)
        assert rs_ops
        # one scatter per tagged grad whose spec resolved, each
        # carrying its pt_pin[grad_rs_boundary:<name>] provenance
        block = main.global_block()
        sites = {s for op in rs_ops for s in op.provenance_names()
                 if s.startswith("grad_rs_boundary:")}
        expected = {f"grad_rs_boundary:{n}" for n in tagged
                    if papi.grad_rs_spec_for(
                        block._find_var(n), mesh, block) is not None}
        assert sites == expected
        assert rep1["grads"]["per_device_bytes"] < (
            rep1["grads"]["total_bytes"])

    for a, b in zip(l1, l0):
        assert np.array_equal(a, b)
    for ga, gb in zip(g1, g0):
        for a, b in zip(ga, gb):
            assert np.array_equal(a, b)
    for k in p1:
        assert np.array_equal(p1[k], p0[k]), k


def test_grad_rs_spec_for_rules(monkeypatch):
    """Rule-4 spec resolution: needs the kill switch on, a mesh with
    both dp>1 and fsdp axes, and an fsdp-tagged divisible shape."""
    main, _startup, _ = _build_gpt(memopt=False)
    block = main.global_block()
    w = block.vars["block0_ffn1.w"]
    mesh = _mesh({"dp": 2, "fsdp": 4})
    assert papi.grad_rs_spec_for(w, mesh, block) is None  # untagged
    w.fsdp_param = True
    assert papi.grad_rs_spec_for(w, mesh, block) == P("fsdp", None)
    # the grad spec IS the parameter's composed fsdp spec
    assert papi.grad_rs_spec_for(w, mesh, block) == (
        papi.fsdp_spec_for(w, mesh, block))
    # kill switch
    monkeypatch.setenv("PADDLE_TPU_ZERO3_RS", "0")
    assert papi.grad_rs_spec_for(w, mesh, block) is None
    monkeypatch.delenv("PADDLE_TPU_ZERO3_RS")
    # a reduce-scatter needs a boundary reduce: no dp axis (or size-1
    # dp) resolves None even though the param itself shards
    assert papi.grad_rs_spec_for(w, _mesh({"fsdp": 8}), block) is None
    assert papi.fsdp_spec_for(w, _mesh({"fsdp": 8}), block) is not None
    # FSDP off entirely -> None (rides fsdp_spec_for's own gates)
    monkeypatch.setenv("PADDLE_TPU_FSDP", "0")
    assert papi.grad_rs_spec_for(w, mesh, block) is None


def test_fsdp_kill_switch_and_auto_policy(monkeypatch):
    """PADDLE_TPU_FSDP=0 and the tuner's program._fsdp=False both keep
    the scan body gather-free; schedule_candidates grows the fsdp
    dimension only when asked."""
    from paddle_tpu.tune import schedule_candidates

    base = schedule_candidates(SEQ, 16, HEADS)
    both = schedule_candidates(SEQ, 16, HEADS, fsdp_opts=(False, True))
    assert len(both) == 2 * len(base)
    assert "fsdp" not in base[0]
    assert {c["fsdp"] for c in both} == {False, True}

    mesh = _mesh({"dp": 2, "fsdp": 4})
    main, startup, outs = _build_gpt(n_layer=2)
    papi.data_parallel(main, "dp", programs=(startup,))
    main._fsdp = False  # the tuned gather-vs-replicate decision —
    # set (by memory_optimize(policy="auto")) BEFORE shard_fsdp, which
    # propagates it to the startup program so both resolve replicated
    papi.shard_fsdp(main, programs=(startup,))
    assert startup._fsdp is False
    # the opt-out reaches spec RESOLUTION too — a replicate schedule
    # measures truly replicated params, not a sharded-at-rest hybrid
    rep = papi.sharding_report(main, mesh)
    assert rep["params"]["per_device_bytes"] == (
        rep["params"]["total_bytes"])
    scope = pt.Scope()
    pt.core.scope._scope_stack.append(scope)
    try:
        exe = pt.Executor(mesh=mesh)
        exe.run(startup, scope=scope)
        exe.run(main, feed=_gpt_feed(), fetch_list=[outs["avg_cost"]],
                scope=scope)
        assert all(not g.get("fsdp") for g in exe.last_remat_plan)
        # (no reduce_ops_in_loop check here: at accum=1 a dp mesh has
        # per-layer dp reductions in the backward scan with or without
        # fsdp — the local-accum configs are where that gate applies)
        w = scope.get(_m_first_tagged(main))
        assert "fsdp" not in str(w.sharding.spec)
    finally:
        pt.core.scope._scope_stack.pop()


def test_memory_optimize_auto_applies_tuned_fsdp(monkeypatch):
    """policy='auto' threads a tuned schedule's fsdp decision onto the
    program for the executor gate."""
    import paddle_tpu.memory_optimization_transpiler as mot

    main, _startup, _ = _build_gpt(n_layer=2, memopt=False)
    monkeypatch.setattr(
        "paddle_tpu.tune.program_schedule_config",
        lambda program: {"policy": "selective", "fsdp": False})
    pt.memory_optimize(main, policy="auto")
    assert main._fsdp is False
    assert main._remat_segments


def test_tune_search_persists_fsdp_dimension(tmp_path, monkeypatch):
    """The gather-vs-replicate dimension round-trips through the
    measured search: tune_gpt_step(fsdp_opts=...) candidates carry the
    key, _measure_candidate applies it as program._fsdp, the winner
    persists it, and memory_optimize(policy='auto') hands it back."""
    from paddle_tpu import tune

    monkeypatch.setenv("PADDLE_TPU_TUNE_CACHE",
                       str(tmp_path / "tuned.json"))
    monkeypatch.setenv("PADDLE_TPU_TUNE", "search")
    tune.reset_cache()
    try:
        rep = tune.tune_gpt_step(
            seq_len=16, n_layer=2, d_model=32, n_head=2, vocab=61,
            batch=4, dtype="float32", steps=1, warmup=0, repeats=1,
            block_caps=(16,), diag_ws=(16,), policies=("none",),
            accums=(1,), fsdp_opts=(False,), max_measure=2)
        assert rep["source"] == "search", rep
        assert rep["entry"]["config"]["fsdp"] is False

        pt.core.unique_name.reset()
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            transformer.build(vocab_size=61, n_layer=2, n_head=2,
                              d_model=32, max_len=16, dropout_rate=0.0,
                              dtype="float32", learning_rate=1e-2)
        pt.memory_optimize(main, policy="auto")
        assert main._fsdp is False
    finally:
        tune.reset_cache()


def test_tune_search_persists_grad_rs_dimension(tmp_path, monkeypatch):
    """The measured grad_rs dimension (boundary reduce-scatter vs
    replicated grads — a real volume-vs-gather tradeoff on fsdp meshes)
    crosses only with fsdp=True candidates, rides _measure_candidate
    through the PADDLE_TPU_ZERO3_RS pin, and the winner persists the
    key in the tune cache."""
    from paddle_tpu import tune
    from paddle_tpu.tune import schedule_candidates

    # grad_rs never crosses with replicate-schedule candidates
    cands = schedule_candidates(SEQ, 16, HEADS, fsdp_opts=(False, True),
                                grad_rs_opts=(False, True))
    assert all("grad_rs" not in c for c in cands if not c["fsdp"])
    assert ({c["grad_rs"] for c in cands if c["fsdp"]}
            == {False, True})

    monkeypatch.setenv("PADDLE_TPU_TUNE_CACHE",
                       str(tmp_path / "tuned.json"))
    monkeypatch.setenv("PADDLE_TPU_TUNE", "search")
    tune.reset_cache()
    try:
        rep = tune.tune_gpt_step(
            seq_len=16, n_layer=2, d_model=32, n_head=2, vocab=61,
            batch=4, dtype="float32", steps=1, warmup=0, repeats=1,
            block_caps=(16,), diag_ws=(16,), policies=("none",),
            accums=(1,), fsdp_opts=(True,), grad_rs_opts=(False,),
            max_measure=2)
        assert rep["source"] == "search", rep
        assert rep["entry"]["config"]["fsdp"] is True
        assert rep["entry"]["config"]["grad_rs"] is False
    finally:
        tune.reset_cache()
