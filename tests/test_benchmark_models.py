"""Benchmark-config model tests (reference: benchmark/paddle/image/
{alexnet,googlenet,smallnet_mnist_cifar}.py — SURVEY §6 baseline configs).
Tiny-shape trainings: loss finite and decreasing, like tests/test_book.py."""

import pytest

import numpy as np

from paddle_tpu.models import alexnet, googlenet, smallnet

from test_book import train_steps


def test_alexnet():
    outs = alexnet.build(class_dim=4, image_shape=(3, 96, 96),
                         learning_rate=0.01, dtype="float32")
    rng = np.random.default_rng(10)
    img = rng.normal(size=(4, 3, 96, 96)).astype(np.float32)
    label = rng.integers(0, 4, size=(4, 1)).astype(np.int64)
    train_steps(outs, {"img": img, "label": label}, steps=4,
                extra_fetch=[outs["accuracy"]])


@pytest.mark.slow
def test_googlenet():
    outs = googlenet.build(class_dim=4, image_shape=(3, 128, 128),
                           learning_rate=0.001, dtype="float32")
    rng = np.random.default_rng(11)
    img = rng.normal(size=(2, 3, 128, 128)).astype(np.float32)
    label = rng.integers(0, 4, size=(2, 1)).astype(np.int64)
    train_steps(outs, {"img": img, "label": label}, steps=4)


def test_smallnet():
    outs = smallnet.build(class_dim=10, learning_rate=0.002)
    rng = np.random.default_rng(12)
    img = rng.normal(size=(8, 3, 32, 32)).astype(np.float32)
    label = rng.integers(0, 10, size=(8, 1)).astype(np.int64)
    train_steps(outs, {"img": img, "label": label}, steps=5,
                extra_fetch=[outs["accuracy"]])
