"""Distributed-layer tests, following the reference's in-process patterns:
client+servers in one process (pserver/test/test_ParameterServer2.cpp), RPC
layer alone (test_ProtoServer.cpp), master with the in-mem store
(go/master/service_internal_test.go), TTL'd discovery
(go/pserver/etcd_client_test.go)."""

import os
import pickle
import subprocess
import sys
import threading
import time

import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.distributed import rpc
from paddle_tpu.distributed.master import MasterClient, MasterService
from paddle_tpu.distributed.pserver import (
    ParameterServer,
    PServerClient,
    assign_server,
)
from paddle_tpu.distributed.store import (
    FileStore,
    InMemStore,
    discover_services,
    register_service,
)
from paddle_tpu.distributed.transpiler import (
    DistributedTrainer,
    DistributeTranspiler,
)
from paddle_tpu.native import recordio


# ------------------------------------------------------------------ rpc
class _Echo:
    def echo(self, x):
        return x

    def add(self, a, b=0):
        return a + b

    def boom(self):
        raise ValueError("boom")


def test_rpc_roundtrip_and_errors():
    server = rpc.Server(_Echo()).start()
    try:
        c = rpc.Client(server.endpoint)
        assert c.call("echo", {"a": np.arange(3)})["a"].tolist() == [0, 1, 2]
        assert c.call("add", 2, b=3) == 5
        with pytest.raises(RuntimeError, match="boom"):
            c.call("boom")
        # still usable after a remote error
        assert c.call("add", 1, b=1) == 2
        c.close()
    finally:
        server.stop()


def test_rpc_large_payload():
    server = rpc.Server(_Echo()).start()
    try:
        c = rpc.Client(server.endpoint)
        big = np.random.rand(1 << 20)  # 8 MB
        np.testing.assert_array_equal(c.call("echo", big), big)
        c.close()
    finally:
        server.stop()


# ---------------------------------------------------------------- store
def test_inmem_store_ttl_and_cas():
    s = InMemStore()
    s.put("a", 1)
    assert s.get("a") == 1
    s.put("b", 2, ttl=0.05)
    assert s.get("b") == 2
    time.sleep(0.1)
    assert s.get("b") is None
    assert s.cas("a", 1, 10)
    assert not s.cas("a", 1, 20)
    assert s.get("a") == 10
    assert s.keys() == ["a"]


def test_file_store(tmp_path):
    s = FileStore(str(tmp_path))
    s.put("x/y", {"v": 1})
    assert s.get("x/y") == {"v": 1}
    assert s.keys("x/") == ["x/y"]
    s.delete("x/y")
    assert s.get("x/y") is None


def test_service_discovery_ttl():
    s = InMemStore()
    stop = register_service(s, "pserver", "127.0.0.1:9000", ttl=0.3)
    time.sleep(0.05)
    assert discover_services(s, "pserver") == ["127.0.0.1:9000"]
    stop()
    time.sleep(0.1)
    assert discover_services(s, "pserver") == []


# --------------------------------------------------------------- master
def _write_dataset(tmp_path, n_files=2, recs_per_file=40):
    paths, all_recs = [], []
    for i in range(n_files):
        p = tmp_path / f"data-{i:05d}"
        with recordio.Writer(p, max_chunk_bytes=256) as w:
            for j in range(recs_per_file):
                rec = pickle.dumps((i, j))
                w.write(rec)
                all_recs.append(rec)
        paths.append(str(p))
    return paths, all_recs


def test_master_chunk_partition_and_pass(tmp_path):
    paths, all_recs = _write_dataset(tmp_path)
    svc = MasterService(timeout_sec=60)
    svc.set_dataset(paths)
    n_chunks = sum(len(recordio.index(p)) for p in paths)
    assert len(svc.todo) == n_chunks

    client = MasterClient(svc)
    client.set_dataset(paths)
    got = []
    while True:
        r = client.next_record()
        if r is None:
            break
        got.append(r)
    assert sorted(got) == sorted(all_recs)
    # next pass serves everything again
    assert svc.num_passes_finished() >= 0
    got2 = []
    while True:
        r = client.next_record()
        if r is None:
            break
        got2.append(r)
    assert sorted(got2) == sorted(all_recs)


def test_master_failure_poison_drop(tmp_path):
    paths, _ = _write_dataset(tmp_path, n_files=1, recs_per_file=4)
    svc = MasterService(timeout_sec=60, failure_max=2)
    svc.set_dataset(paths)
    t1 = svc.get_task()
    assert svc.task_failed(t1["id"])
    t2 = svc.get_task()
    assert t2["id"] == t1["id"]  # requeued
    svc.task_failed(t2["id"])
    # failure_max reached -> dropped to failed, not todo
    assert all(t.id != t1["id"] for t in svc.todo)
    assert any(t.id == t1["id"] for t in svc.failed)


def test_master_timeout_requeue(tmp_path):
    paths, _ = _write_dataset(tmp_path, n_files=1, recs_per_file=4)
    svc = MasterService(timeout_sec=0.2, failure_max=5)
    svc.set_dataset(paths)
    t = svc.get_task()
    deadline = time.time() + 5
    while not svc.todo and time.time() < deadline:
        time.sleep(0.05)
    assert any(x.id == t["id"] for x in svc.todo), "task not requeued"


def test_master_snapshot_recover(tmp_path):
    paths, all_recs = _write_dataset(tmp_path, n_files=1, recs_per_file=10)
    store = InMemStore()
    svc = MasterService(store=store, timeout_sec=60)
    svc.set_dataset(paths)
    leased = svc.get_task()
    assert leased is not None
    # master dies; a new one recovers from the store: pending -> todo
    svc2 = MasterService(store=store, timeout_sec=60)
    ids = {t.id for t in svc2.todo}
    assert leased["id"] in ids


def test_master_save_model_election():
    svc = MasterService(timeout_sec=60)
    assert svc.request_save_model("t0", block_sec=5)
    assert not svc.request_save_model("t1", block_sec=5)
    assert svc.request_save_model("t0", block_sec=5)


def test_cloud_reader(tmp_path):
    from paddle_tpu.reader.creator import cloud_reader

    paths, all_recs = _write_dataset(tmp_path, n_files=1, recs_per_file=12)
    svc = MasterService(timeout_sec=60)
    reader = cloud_reader(paths, etcd_endpoints=svc)
    got = list(reader())
    assert sorted(map(str, got)) == sorted(
        str(pickle.loads(r)) for r in all_recs
    )


# -------------------------------------------------------------- pserver
def test_pserver_sync_barrier_two_trainers():
    ps = ParameterServer(num_trainers=2, sync=True)
    ps.init_param("w", np.zeros(4, np.float32), optimizer="sgd", lr=0.5)
    ps.finish_init_params()

    def trainer(grad):
        ps.send_grad("w", np.full(4, grad, np.float32))

    t1 = threading.Thread(target=trainer, args=(1.0,))
    t2 = threading.Thread(target=trainer, args=(3.0,))
    t1.start(); t2.start(); t1.join(); t2.join()
    # averaged grad = 2.0, lr 0.5 -> w = -1
    np.testing.assert_allclose(ps.get_param("w"), -np.ones(4), rtol=1e-6)


def test_pserver_async_and_sparse():
    ps = ParameterServer(num_trainers=1, sync=False)
    ps.init_param("emb", np.ones((10, 2), np.float32), optimizer="sgd", lr=1.0)
    ps.finish_init_params()
    ps.send_sparse_grad("emb", np.array([1, 3]), np.ones((2, 2), np.float32))
    p = ps.get_param("emb")
    np.testing.assert_allclose(p[1], [0, 0])
    np.testing.assert_allclose(p[0], [1, 1])
    rows = ps.get_param_rows("emb", [3])
    np.testing.assert_allclose(rows, [[0, 0]])


def test_pserver_adam_server_side():
    ps = ParameterServer(num_trainers=1, sync=True)
    w0 = np.ones(3, np.float32)
    ps.init_param("w", w0, optimizer="adam", lr=0.1)
    ps.finish_init_params()
    ps.send_grad("w", np.ones(3, np.float32))
    w1 = ps.get_param("w")
    assert np.all(w1 < w0)  # moved against the gradient
    assert np.isfinite(w1).all()


def test_pserver_checkpoint_recover(tmp_path):
    store = InMemStore()
    ps = ParameterServer(index=0, num_trainers=1, sync=False, store=store,
                         checkpoint_dir=str(tmp_path),
                         checkpoint_every_n_updates=1)
    ps.init_param("w", np.zeros(2, np.float32), optimizer="momentum", lr=0.1,
                  attrs={"mu": 0.9})
    ps.finish_init_params()
    ps.send_grad("w", np.ones(2, np.float32))
    w_after = ps.get_param("w").copy()
    # new server instance on same store+dir recovers params AND momentum
    ps2 = ParameterServer(index=0, num_trainers=1, sync=False, store=store,
                          checkpoint_dir=str(tmp_path))
    assert ps2.ready()
    np.testing.assert_allclose(ps2.get_param("w"), w_after)
    ps2.send_grad("w", np.ones(2, np.float32))
    # momentum state survived: second step larger than first
    step2 = np.abs(ps2.get_param("w") - w_after)
    assert np.all(step2 > np.abs(w_after) * 1.5)


def test_pserver_client_over_rpc_sharded():
    servers = [ParameterServer(index=i, num_trainers=1) for i in range(2)]
    rpc_servers = [rpc.Server(s).start() for s in servers]
    try:
        client = PServerClient([s.endpoint for s in rpc_servers])
        params = {f"p{i}": np.full(2, float(i), np.float32) for i in range(5)}
        client.init_params(params, optimizer="sgd", lr=1.0)
        client.send_grads({n: np.ones(2, np.float32) for n in params})
        fresh = client.get_params(list(params))
        for i in range(5):
            np.testing.assert_allclose(fresh[f"p{i}"], float(i) - 1.0)
        # shards actually split across the two servers
        counts = [len(s.params) for s in servers]
        assert sum(counts) == 5 and all(c > 0 for c in counts)
    finally:
        for s in rpc_servers:
            s.stop()


# ----------------------------------------------------------- transpiler
def test_transpiler_end_to_end_training():
    """fit_a_line via 2 in-process pservers: the fluid transpiler book-test
    pattern (book_distribute/notest_*_dist.py) without real processes."""
    x = layers.data("x", shape=[3])
    y = layers.data("y", shape=[1])
    pred = layers.fc(input=x, size=1, bias_attr=False)
    loss = layers.mean(layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    main = pt.default_main_program()

    t = DistributeTranspiler()
    t.transpile(main, pservers=2, trainers=1)
    # optimizer ops stripped from the trainer half
    trainer_prog = t.get_trainer_program()
    assert all(op.type != "sgd" for op in trainer_prog.global_block().ops)
    # every param assigned to some pserver; both halves cover all params
    cfg0 = t.get_pserver_config(0)
    cfg1 = t.get_pserver_config(1)
    assert set(cfg0) | set(cfg1) == set(t.optimize_info)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    servers = [ParameterServer(index=i, num_trainers=1) for i in range(2)]
    dt = DistributedTrainer(t, exe, servers, learning_rate=0.05)
    dt.init_params_on_pservers()

    rng = np.random.default_rng(0)
    xs = rng.normal(size=(16, 3)).astype(np.float32)
    w_true = np.array([[1.0], [-2.0], [0.5]], np.float32)
    ys = xs @ w_true
    losses = []
    for _ in range(10):
        out = dt.train_step({"x": xs, "y": ys}, extra_fetch=[loss])
        losses.append(float(np.asarray(out[0]).ravel()[0]))
    assert losses[-1] < losses[0] * 0.7, losses


def test_assign_server_stable():
    assert assign_server("w", 4) == assign_server("w", 4)
    spread = {assign_server(f"p{i}", 4) for i in range(32)}
    assert len(spread) == 4


def test_transpiler_conv_model_dist():
    """recognize_digits_conv via the pserver path (reference
    book_distribute/notest_recognize_digits_conv_dist.py): a real conv
    model's params sharded over 2 in-process pservers, server-side SGD."""
    from paddle_tpu.models import lenet

    outs = lenet.build(learning_rate=0.003)
    main = pt.default_main_program()

    t = DistributeTranspiler()
    t.transpile(main, pservers=2, trainers=1)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    servers = [ParameterServer(index=i, num_trainers=1) for i in range(2)]
    dt = DistributedTrainer(t, exe, servers, learning_rate=0.003)
    dt.init_params_on_pservers()

    rng = np.random.default_rng(3)
    img = rng.normal(size=(8, 1, 28, 28)).astype(np.float32)
    lbl = rng.integers(0, 10, (8, 1)).astype(np.int64)
    losses = []
    for _ in range(6):
        out = dt.train_step({"img": img, "label": lbl},
                            extra_fetch=[outs["avg_cost"]])
        losses.append(float(np.asarray(out[0]).ravel()[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_launch_single_host_and_mesh():
    from paddle_tpu.distributed import launch

    launch.init_multihost()  # single host: no-op success
    assert launch.is_initialized()
    mesh = launch.global_mesh({"dp": -1, "tp": 2})
    assert mesh.shape["tp"] == 2
    assert mesh.shape["dp"] * 2 == len(jax.devices())
    with pytest.raises(ValueError, match="devices"):
        launch.global_mesh({"dp": 3, "tp": 5})
    with pytest.raises(ValueError, match="one mesh axis"):
        launch.global_mesh({"dp": -1, "tp": -1})


def _reap(procs):
    """Terminate subprocess(es), never raising out of a finally block."""
    if not isinstance(procs, (list, tuple)):
        procs = [procs]
    for p in procs:
        p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait(timeout=10)


def _spawn_cli(cli_args, store_path):
    """Spawn `python -m paddle_tpu <args>` and wait (bounded even if the
    child hangs silently: stdout is drained on a helper thread) for its
    'serving on <endpoint>' line; returns (proc, endpoint)."""
    import os
    import queue
    import re
    import sys

    repo_root = os.path.dirname(os.path.dirname(__file__))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    prev = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = repo_root + (os.pathsep + prev if prev else "")
    p = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu", *cli_args,
         "--store", str(store_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)

    q = queue.Queue()

    def drain():
        for line in p.stdout:
            q.put(line)
        q.put(None)

    threading.Thread(target=drain, daemon=True).start()
    deadline = time.time() + 60
    lines = []
    while time.time() < deadline:
        try:
            line = q.get(timeout=max(0.1, deadline - time.time()))
        except queue.Empty:
            break
        if line is None:
            break
        lines.append(line)
        m = re.search(r"serving on (\S+)", line)
        if m:
            return p, m.group(1)
    _reap(p)
    raise AssertionError(f"no endpoint from {cli_args}: {lines!r}")


def test_cli_pserver_processes_end_to_end(tmp_path):
    """REAL multi-process distributed training: two `python -m paddle_tpu
    pserver` subprocesses over TCP, trainer in this process (the reference
    book_distribute pattern with actual processes, SURVEY §4)."""
    procs, endpoints = [], []
    try:
        for i in range(2):
            p, ep = _spawn_cli(
                ["pserver", "--index", str(i), "--num-trainers", "1",
                 "--port", "0"], tmp_path / "store")
            procs.append(p)
            endpoints.append(ep)

        client = PServerClient(endpoints)
        rng = np.random.default_rng(0)
        w = {"w_a": rng.normal(size=(4,)).astype(np.float32),
             "w_b": rng.normal(size=(3,)).astype(np.float32)}
        client.init_params(w, optimizer="sgd", lr=0.1, attrs={})
        for _ in range(3):
            grads = {k: np.ones_like(v) for k, v in w.items()}
            client.send_grads(grads)
        fresh = client.get_params(list(w))
        for k in w:
            np.testing.assert_allclose(
                fresh[k], w[k] - 0.1 * 3 * np.ones_like(w[k]), rtol=1e-5)
    finally:
        _reap(procs)


def test_cli_master_process_end_to_end(tmp_path):
    """`python -m paddle_tpu master` subprocess serving a RecordIO dataset
    over TCP; records consumed via MasterClient from this process."""
    paths, all_recs = _write_dataset(tmp_path, n_files=2, recs_per_file=10)
    p, endpoint = _spawn_cli(
        ["master", "--port", "0", "--dataset", *paths], tmp_path / "store")
    try:
        client = MasterClient(endpoint)
        got = []
        while True:
            rec = client.next_record()
            if rec is None:
                break
            got.append(rec)
        assert sorted(got) == sorted(all_recs)
    finally:
        _reap(p)


def test_multihost_two_process_cpu(tmp_path):
    """REAL 2-process multi-host run over the JAX coordination service
    (CPU backend): launch.init_multihost on each process, a global mesh
    spanning both, a cross-process psum, and 2 data-parallel Executor
    steps whose replicated state agrees bit-for-bit across processes
    (reference analog: cluster_train_v2 launchers + --trainer_id)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"

    env = dict(os.environ)
    for k in list(env):
        if "AXON" in k or k.startswith("TPU_") or k.startswith("PJRT_"):
            env.pop(k)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONSAFEPATH", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        [repo] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=2")
    env["XLA_FLAGS"] = " ".join(flags)

    runner = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "multihost_runner.py")
    procs = [
        subprocess.Popen(
            [sys.executable, runner, coordinator, "2", str(i)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
        if any("Multiprocess computations aren't implemented" in out
               for out in outs):
            # this image's jaxlib CPU backend cannot execute
            # cross-process computations at all (the PJRT CPU client
            # raises UNIMPLEMENTED on the first collective) — the same
            # environment limitation test_multihost_midpass_kill_resume
            # already skips on.  Nothing in-repo can fix a jaxlib
            # build; ROADMAP item 4c tracks running this gate on a
            # capable jaxlib.
            pytest.skip("this jaxlib's CPU backend cannot run "
                        "cross-process computations")
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"rank {i} failed:\n{out}"
        oks = [
            [l for l in out.splitlines() if l.startswith("MULTIHOST_OK")]
            for out in outs
        ]
        assert all(len(o) == 1 for o in oks), outs
        # replicated loss and params identical across the two processes
        assert oks[0][0].split()[2:] == oks[1][0].split()[2:], oks
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


# ------------------------------------------------- block sharding (round 3)
def test_split_param_plan_balance():
    """[1e6, 64] embedding over 4 servers: 4 contiguous row blocks within
    one row of even (reference split_dense_variable,
    distribute_transpiler.py:106-145), all servers used, deterministic."""
    from paddle_tpu.distributed.pserver import split_param

    plan = split_param("emb.w", (1_000_000, 64), 4)
    assert len(plan) == 4
    assert {s for s, _, _ in plan} == {0, 1, 2, 3}
    sizes = [r1 - r0 for _, r0, r1 in plan]
    assert max(sizes) - min(sizes) <= 1
    spans = sorted((r0, r1) for _, r0, r1 in plan)
    assert spans[0][0] == 0 and spans[-1][1] == 1_000_000
    for (a0, a1), (b0, b1) in zip(spans, spans[1:]):
        assert a1 == b0
    assert plan == split_param("emb.w", (1_000_000, 64), 4)
    # small params stay whole (min_block guard)
    assert len(split_param("fc.b", (10,), 4)) == 1
    assert len(split_param("w", (3, 3), 4)) == 1


def test_block_sharded_init_fetch_train():
    """A [100, 8] param splits into 4 blocks on 4 servers; fetch
    reassembles exactly; a dense SGD step applies blockwise."""
    servers = [ParameterServer(index=i, num_trainers=1) for i in range(4)]
    client = PServerClient(servers, min_block_elems=64)
    w = np.arange(100 * 8, dtype=np.float32).reshape(100, 8)
    client.init_params({"w": w}, optimizer="sgd", lr=0.5)
    assert [len(s.params) for s in servers] == [1, 1, 1, 1]
    np.testing.assert_array_equal(client.get_params(["w"])["w"], w)
    client.send_grads({"w": np.ones_like(w)})
    np.testing.assert_allclose(client.get_params(["w"])["w"], w - 0.5)


def test_block_sharded_training_matches_single_server():
    """Same gradient stream through a 1-server client and a 4-server
    block-sharded client (momentum): bit-equal trajectories."""
    rng = np.random.default_rng(0)
    w0 = rng.normal(size=(64, 4)).astype(np.float32)
    single = PServerClient([ParameterServer(index=0, num_trainers=1)])
    sharded = PServerClient(
        [ParameterServer(index=i, num_trainers=1) for i in range(4)],
        min_block_elems=32)
    for c in (single, sharded):
        c.init_params({"w": w0.copy()}, optimizer="momentum", lr=0.1,
                      attrs={"mu": 0.9})
    for step in range(5):
        g = rng.normal(size=w0.shape).astype(np.float32)
        single.send_grads({"w": g})
        sharded.send_grads({"w": g})
    np.testing.assert_array_equal(single.get_params(["w"])["w"],
                                  sharded.get_params(["w"])["w"])


def test_parallel_scatter_overlaps_servers():
    """The client's scatter/gather overlaps across servers (the
    sendParallel analog, ParameterClient2.cpp:146): measured by a
    max-in-flight counter across 4 slow servers, not wall-clock (which
    flakes under CI load)."""
    in_flight = [0]
    peak = [0]
    lock = threading.Lock()

    class SlowServer(ParameterServer):
        def send_grad(self, name, grad):
            with lock:
                in_flight[0] += 1
                peak[0] = max(peak[0], in_flight[0])
            time.sleep(0.03)
            try:
                return super().send_grad(name, grad)
            finally:
                with lock:
                    in_flight[0] -= 1

    servers = [SlowServer(index=i, num_trainers=1) for i in range(4)]
    client = PServerClient(servers, min_block_elems=32)
    w = np.zeros((64, 4), np.float32)
    client.init_params({"w": w}, optimizer="sgd", lr=0.1)
    client.send_grads({"w": np.ones_like(w)})
    assert peak[0] >= 2, f"sends never overlapped (peak={peak[0]})"


def test_sparse_rows_adam_matches_dense_when_all_rows_touched():
    """Lazy sparse adam == dense adam when every row is touched every
    step (per-row pows advance in lockstep with the global pow)."""
    from paddle_tpu.distributed.pserver import _OptimizerState

    rng = np.random.default_rng(1)
    n, d = 12, 4
    p_dense = rng.normal(size=(n, d)).astype(np.float32)
    p_sparse = p_dense.copy()
    os_d = _OptimizerState("adam", 0.01, {})
    os_s = _OptimizerState("adam", 0.01, {})
    for _ in range(5):
        g = rng.normal(size=(n, d)).astype(np.float32)
        p_dense = os_d.step(p_dense, g)
        os_s.step_rows(p_sparse, np.arange(n), g)
    np.testing.assert_allclose(p_sparse, p_dense, rtol=1e-5, atol=1e-6)


def test_sparse_rows_lazy_per_row_state():
    """Rows touched at different rates carry their OWN bias correction:
    row 5 touched 3x must equal a dense adam run of 3 steps on that row
    alone; untouched rows stay bit-identical."""
    from paddle_tpu.distributed.pserver import _OptimizerState

    rng = np.random.default_rng(2)
    n, d = 8, 3
    p = rng.normal(size=(n, d)).astype(np.float32)
    p0 = p.copy()
    os_s = _OptimizerState("adam", 0.05, {})
    grads = [rng.normal(size=(1, d)).astype(np.float32) for _ in range(3)]
    for g in grads:
        os_s.step_rows(p, np.array([5]), g)
    # dense single-row reference
    ref = p0[5:6].copy()
    os_d = _OptimizerState("adam", 0.05, {})
    for g in grads:
        ref = os_d.step(ref, g)
    np.testing.assert_allclose(p[5:6], ref, rtol=1e-5, atol=1e-6)
    mask = np.ones(n, bool)
    mask[5] = False
    np.testing.assert_array_equal(p[mask], p0[mask])


def test_sparse_rows_generic_optimizer_and_merge():
    """The pow-free path runs the registered op impl on row slices
    (momentum), and duplicate rows merge-add first (SelectedRows merge);
    negative rows (padding) are dropped."""
    from paddle_tpu.distributed.pserver import _OptimizerState

    p = np.zeros((4, 2), np.float32)
    st = _OptimizerState("momentum", 1.0, {"mu": 0.5})
    st.step_rows(p, np.array([1, 1, -1]),
                 np.array([[1., 1.], [2., 2.], [9., 9.]], np.float32))
    # merged grad = 3 -> velocity 3 -> p = -3
    np.testing.assert_allclose(p[1], [-3., -3.])
    np.testing.assert_array_equal(p[0], [0., 0.])
    st.step_rows(p, np.array([1]), np.ones((1, 2), np.float32))
    # velocity = 0.5*3 + 1 = 2.5 -> p = -5.5
    np.testing.assert_allclose(p[1], [-5.5, -5.5])


def test_pserver_dense_adamax_and_proximal():
    """Every optimizer the transpiler routes to the pserver has dense
    state slots (adamax/proximal_* were missing)."""
    for opt, attrs in [("adamax", {}), ("proximal_gd", {}),
                       ("proximal_adagrad", {})]:
        ps = ParameterServer(num_trainers=1, sync=False)
        w0 = np.ones(3, np.float32)
        ps.init_param("w", w0, optimizer=opt, lr=0.1, attrs=attrs)
        ps.finish_init_params()
        ps.send_grad("w", np.ones(3, np.float32))
        w1 = ps.get_param("w")
        assert np.isfinite(w1).all() and np.all(w1 < w0), (opt, w1)


def test_sparse_rows_handles_readonly_param():
    """np.asarray views of jax Arrays are read-only and pickle PRESERVES
    that flag — a sparse update on a param that arrived as such a view
    must copy, not crash (caught driving the RPC path end-to-end)."""
    from paddle_tpu.distributed.pserver import _OptimizerState

    p = np.zeros((4, 2), np.float32)
    p.setflags(write=False)
    st = _OptimizerState("adam", 0.1, {})
    out = st.step_rows(p, np.array([1]), np.ones((1, 2), np.float32))
    assert out.flags.writeable
    assert np.all(out[1] < 0)


def test_pserver_sparse_send_respects_configured_optimizer():
    """send_sparse_grad no longer hardcodes SGD: an adagrad server's
    sparse update uses the adagrad rule."""
    ps = ParameterServer(num_trainers=1, sync=False)
    ps.init_param("emb", np.ones((4, 2), np.float32),
                  optimizer="adagrad", lr=1.0, attrs={"epsilon": 1e-6})
    ps.finish_init_params()
    g = np.full((1, 2), 2.0, np.float32)
    ps.send_sparse_grad("emb", np.array([2]), g)
    # adagrad: moment = 4, update = 2/sqrt(4) = 1 -> 1 - 1 = 0
    np.testing.assert_allclose(ps.get_param("emb")[2], [0., 0.], atol=1e-5)
    np.testing.assert_allclose(ps.get_param("emb")[0], [1., 1.])


def test_ctr_dnn_distributed_sparse_matches_local_adam():
    """CTR-DNN via the block-sharded sparse pserver path vs the SAME
    program trained locally: with every vocab row touched each step the
    lazy sparse adam must match local dense adam (VERDICT round-2 item 3
    acceptance).  Embeddings go through prefetch + send_sparse_grad;
    the dense tower through blockwise send_grads."""
    from paddle_tpu.models import ctr_dnn

    vocab, emb, slots = 16, 4, 2
    outs = ctr_dnn.build(sparse_feature_dim=vocab, num_slots=slots,
                         embedding_size=emb, dense_dim=3, hidden=(8,),
                         learning_rate=1e-2)
    main = pt.default_main_program()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.core.scope.global_scope()
    emb_params = [p.name for p in main.all_parameters()
                  if tuple(p.shape) == (vocab, emb)]
    assert len(emb_params) == slots
    snapshot = {p.name: np.array(scope.get(p.name))
                for p in main.all_parameters()}

    rng = np.random.default_rng(0)
    batch = vocab  # every row of every slot appears in every batch
    feeds = []
    for _ in range(4):
        feed = {"dense_feature":
                rng.normal(size=(batch, 3)).astype(np.float32),
                "click": rng.integers(0, 2, (batch, 1)).astype(np.int64)}
        for s in range(slots):
            ids = np.arange(vocab)
            rng.shuffle(ids)
            feed[f"slot_{s}"] = ids.reshape(-1, 1).astype(np.int64)
        feeds.append(feed)

    # local run
    for feed in feeds:
        exe.run(main, feed=feed, fetch_list=[outs["avg_cost"]])
    local = {n: np.array(scope.get(n)) for n in snapshot}

    # reset scope, distributed run (4 servers, sparse embeddings)
    for n, v in snapshot.items():
        scope.set(n, v.copy())
    t = DistributeTranspiler()
    t.transpile(main, pservers=4, trainers=1)
    servers = [ParameterServer(index=i, num_trainers=1) for i in range(4)]
    dt = DistributedTrainer(
        t, exe, servers, learning_rate=1e-2,
        sparse_params={p: f"slot_{i}" for i, p in enumerate(emb_params)})
    dt.init_params_on_pservers()
    for feed in feeds:
        dt.train_step(feed)
    # every param (sparse and dense) lives on the servers; fetch back
    for name in snapshot:
        got = dt.client.get_params([name])[name]
        np.testing.assert_allclose(
            got, local[name], rtol=2e-4, atol=2e-5,
            err_msg=f"param {name} diverged between local and sparse-PS")


def _multihost_env(n_virtual=2):
    env = dict(os.environ)
    for k in list(env):
        if "AXON" in k or k.startswith("TPU_") or k.startswith("PJRT_"):
            env.pop(k)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONSAFEPATH", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        [repo] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append(f"--xla_force_host_platform_device_count={n_virtual}")
    # bit-identical runs need load-independent reduction splits: XLA CPU
    # partitions multithreaded reductions by available threads, so a busy
    # machine changes summation order and the last few mantissa bits
    flags.append("--xla_cpu_multi_thread_eigen=false")
    env["XLA_FLAGS"] = " ".join(flags)
    env["OMP_NUM_THREADS"] = "1"
    return env


def _run_multihost_phase(mode, ckpt_dir, env):
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"
    runner = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "multihost_runner.py")
    procs = [
        subprocess.Popen(
            [sys.executable, runner, coordinator, "2", str(i), mode,
             str(ckpt_dir)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"{mode} rank {i} failed:\n{out}"
    oks = [[l for l in out.splitlines()
            if l.startswith("MULTIHOST_CKPT_OK")] for out in outs]
    assert all(len(o) == 1 for o in oks), outs
    return [o[0].split()[2:] for o in oks]  # [loss=..., state=...] per rank


def test_multihost_sharded_checkpoint_resume(tmp_path):
    """Multi-host-safe checkpoint of cross-process PARTITIONED state
    (round-2 VERDICT item 5): a 2-process run whose fc weight is
    tp-sharded across the processes saves at step 1 (one shard file per
    process), the processes die, fresh processes restore (each reading
    only ITS shard) and continue — final params bit-identical to an
    uninterrupted 3-step run on both ranks."""
    env = _multihost_env(2)
    ckpt = tmp_path / "ckpt"
    try:
        ref = _run_multihost_phase("ckpt_ref", ckpt, env)
    except AssertionError as e:
        # same jaxlib limitation as test_multihost_two_process_cpu /
        # test_multihost_midpass_kill_resume: the CPU PJRT client
        # raises UNIMPLEMENTED on any cross-process computation
        if "Multiprocess computations aren't implemented" in str(e):
            pytest.skip("this jaxlib's CPU backend cannot run "
                        "cross-process computations")
        raise
    saved = _run_multihost_phase("ckpt_save", ckpt, env)
    # the checkpoint really is per-process shard files
    files = os.listdir(ckpt)
    assert any(".shard0." in f for f in files), files
    assert any(".shard1." in f for f in files), files
    resumed = _run_multihost_phase("ckpt_resume", ckpt, env)
    # all three runs agree on final loss and state digest, per rank
    assert ref == saved == resumed, (ref, saved, resumed)
    # and the replicated loss agrees ACROSS ranks (one global SPMD
    # computation, not two process-local ones)
    assert ref[0][0] == ref[1][0], ref


def _run_multihost_kill_phase(mode, ckpt_dir, env):
    """Like _run_multihost_phase but EXPECTS both ranks to die by
    SIGKILL after checkpointing; returns the outputs."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"
    runner = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "multihost_runner.py")
    procs = [
        subprocess.Popen(
            [sys.executable, runner, coordinator, "2", str(i), mode,
             str(ckpt_dir)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    import signal

    for i, (p, out) in enumerate(zip(procs, outs)):
        assert "MULTIHOST_KILL_READY" in out, \
            f"{mode} rank {i} died before checkpointing:\n{out}"
        assert p.returncode == -signal.SIGKILL, \
            f"{mode} rank {i} rc={p.returncode} (expected SIGKILL):\n{out}"
    return outs


def test_multihost_midpass_kill_resume(tmp_path):
    """ISSUE 8 satellite (ROADMAP item 4's gate at multi-host scale):
    kill-and-resume across the 2-process tp-sharded mesh.  Both ranks
    save a FULL-state checkpoint (per-process shard files + RNG/step
    sidecar) at step 2 of 4, SIGKILL themselves mid-pass, and fresh
    processes restore + finish — final loss and the digest over EVERY
    persistable (momentum included) bit-identical to the uninterrupted
    4-step run on both ranks."""
    env = _multihost_env(2)
    ckpt = tmp_path / "ckpt"
    try:
        ref = _run_multihost_phase("ckpt_mid_ref", ckpt, env)
    except AssertionError as e:
        if "Multiprocess computations aren't implemented" in str(e):
            pytest.skip("this jaxlib's CPU backend cannot run "
                        "cross-process computations")
        raise
    _run_multihost_kill_phase("ckpt_mid_kill", ckpt, env)
    # the checkpoint on disk is per-process shard files + the sidecar
    files = os.listdir(ckpt)
    assert any(".shard0." in f for f in files), files
    assert any(".shard1." in f for f in files), files
    assert "__train_state__.pkl" in files, files
    resumed = _run_multihost_phase("ckpt_mid_resume", ckpt, env)
    assert ref == resumed, (ref, resumed)
    # one global SPMD computation: the replicated loss agrees ACROSS ranks
    assert ref[0][0] == ref[1][0], ref


def test_late_attach_client_recovers_block_plan():
    """A client that never called init_params (eval-only trainer)
    rebuilds the block plan from the hash server's param meta and
    fetches/updates a block-sharded param correctly."""
    servers = [ParameterServer(index=i, num_trainers=1) for i in range(4)]
    first = PServerClient(servers, min_block_elems=64)
    w = np.arange(100 * 8, dtype=np.float32).reshape(100, 8)
    first.init_params({"w": w}, optimizer="sgd", lr=0.5)
    # the late client has a DIFFERENT (default) block-size knob: the plan
    # must come from the initializer's recorded meta, not local config
    late = PServerClient(servers)
    np.testing.assert_array_equal(late.get_params(["w"])["w"], w)
    rows = late.get_param_rows("w", np.array([0, 50, 99]))
    np.testing.assert_array_equal(rows, w[[0, 50, 99]])
    # empty query returns (0, row_width) once the plan/shape is known
    empty = late.get_param_rows("w", np.array([], np.int64))
    assert empty.shape == (0, 8)
    with pytest.raises(IndexError):
        late.send_sparse_grad("w", np.array([100]),
                              np.ones((1, 8), np.float32))
    late.close()
    first.close()


def test_client_handles_scalar_and_aliasing():
    """0-d (scalar) params go whole (no row slicing), and in-process
    servers must COPY init values — a sparse update must never mutate
    the caller's original array."""
    servers = [ParameterServer(index=i, num_trainers=1) for i in range(2)]
    client = PServerClient(servers, min_block_elems=4)
    w = np.zeros((8, 2), np.float32)
    s = np.float32(2.0)
    client.init_params({"w": w, "step": s}, optimizer="sgd", lr=1.0)
    client.send_grads({"step": np.float32(1.0)})
    np.testing.assert_allclose(client.get_params(["step"])["step"], 1.0)
    client.send_sparse_grad("w", np.array([3]), np.ones((1, 2), np.float32))
    np.testing.assert_array_equal(w, np.zeros((8, 2), np.float32))
    np.testing.assert_allclose(client.get_params(["w"])["w"][3], [-1, -1])
    client.close()


def test_checkpoint_completion_markers(tmp_path):
    """A checkpoint missing a process's completion marker (writer died
    mid-save) must refuse to load rather than restore torn state."""
    x = layers.data("x", shape=[3])
    pred = layers.fc(input=x, size=2)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    d = str(tmp_path / "ck")
    pt.io.save_persistables(exe, d, pt.default_main_program())
    # healthy load works
    pt.io.load_persistables(exe, d, pt.default_main_program())
    os.remove(os.path.join(d, "__done0__"))
    with pytest.raises(IOError, match="incomplete checkpoint"):
        pt.io.load_persistables(exe, d, pt.default_main_program())


def test_recovered_legacy_whole_param_server():
    """Servers recovered from a pre-block-sharding checkpoint hold params
    WHOLE under bare names: a round-3 client must detect the meta refusal
    and route whole, not to block keys that don't exist."""
    # pick a name whose hash server is index 0
    name = next(n for n in (f"w{i}" for i in range(64))
                if assign_server(n, 4) == 0)
    legacy = ParameterServer(index=0, num_trainers=1, sync=False)
    legacy.init_param(name, np.zeros((100, 8), np.float32),
                      optimizer="sgd", lr=0.5)
    legacy.finish_init_params()  # = recovered: whole param, no meta
    servers = [legacy] + [ParameterServer(index=i, num_trainers=1,
                                          sync=False) for i in range(1, 4)]
    client = PServerClient(servers, min_block_elems=64)
    client.init_params({name: np.zeros((100, 8), np.float32)},
                       optimizer="sgd", lr=0.5)
    client.send_grads({name: np.ones((100, 8), np.float32)})
    np.testing.assert_allclose(client.get_params([name])[name], -0.5)


# --------------------------------------------- pipelined updater + delta fetch
def test_delta_fetch_moves_zero_bytes_when_idle():
    """get_params_delta (the version check the reference's dense trainer
    lacks): a second fetch with no server-side update omits the param
    and transfers zero payload; an update makes it move again."""
    server = ParameterServer(index=0, num_trainers=1)
    client = PServerClient([server])
    w = np.arange(12, dtype=np.float32).reshape(3, 4)
    client.init_params({"w": w}, optimizer="sgd", lr=0.1)

    first = client.get_params_delta(["w"])
    np.testing.assert_allclose(first["w"], w)
    assert client.last_delta_bytes == w.nbytes

    second = client.get_params_delta(["w"])
    assert second == {}
    assert client.last_delta_bytes == 0

    client.send_grads({"w": np.ones_like(w)})
    third = client.get_params_delta(["w"])
    np.testing.assert_allclose(third["w"], w - 0.1)
    assert client.last_delta_bytes == w.nbytes
    np.testing.assert_allclose(third["w"], client.get_params(["w"])["w"])
    client.close()


def test_delta_fetch_refetches_after_server_restart():
    """Version epochs: a restarted server (recovered params, fresh
    counters) must NOT be mistaken for 'unchanged'."""
    server = ParameterServer(index=0, num_trainers=1)
    client = PServerClient([server])
    w = np.ones((4, 2), np.float32)
    client.init_params({"w": w}, optimizer="sgd", lr=0.1)
    client.get_params_delta(["w"])
    assert client.get_params_delta(["w"]) == {}

    # simulate restart: new server object with the same params
    server2 = ParameterServer(index=0, num_trainers=1)
    server2.init_param("w", w * 3)
    server2.finish_init_params()
    client._shards[0] = server2
    again = client.get_params_delta(["w"])
    np.testing.assert_allclose(again["w"], w * 3)
    client.close()


def _fit_line_setup(mode, lr=0.05, n_servers=2):
    x = layers.data("x", shape=[3])
    y = layers.data("y", shape=[1])
    pred = layers.fc(input=x, size=1, bias_attr=False)
    loss = layers.mean(layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=lr).minimize(loss)
    main = pt.default_main_program()
    t = DistributeTranspiler()
    t.transpile(main, pservers=n_servers, trainers=1)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    servers = [ParameterServer(index=i, num_trainers=1)
               for i in range(n_servers)]
    dt = DistributedTrainer(t, exe, servers, learning_rate=lr, mode=mode)
    dt.init_params_on_pservers()
    return dt, loss, servers


def test_pipelined_trainer_converges_and_flush_syncs():
    """Pipelined mode (ConcurrentRemoteParameterUpdater design): params
    are one step stale, training still converges, and flush() makes the
    local scope bit-match the servers."""
    dt, loss, servers = _fit_line_setup("pipelined")
    rng = np.random.default_rng(1)
    xs = rng.normal(size=(16, 3)).astype(np.float32)
    ys = xs @ np.array([[1.0], [-2.0], [0.5]], np.float32)
    losses = []
    for _ in range(12):
        out = dt.train_step({"x": xs, "y": ys}, extra_fetch=[loss])
        losses.append(float(np.asarray(out[0]).ravel()[0]))
    dt.flush()
    assert losses[-1] < losses[0] * 0.7, losses
    # after flush the scope view equals the server state exactly
    from paddle_tpu.core.scope import global_scope
    fresh = dt.client.get_params(dt.dense_names)
    for n in dt.dense_names:
        np.testing.assert_array_equal(
            np.asarray(global_scope().get(n), np.float32), fresh[n])
    dt.close()


def test_pipelined_overlaps_rpc_with_compute():
    """The RPC round trip of step N runs WHILE step N+1's compute runs
    (VERDICT r3 item 3 'done' bar: step ~ max(compute, RPC), not the
    sum).  Asserted via interval overlap between server calls and
    executor compute — not wall-clock ratios, which flake under CI load
    (the test_parallel_scatter_overlaps_servers convention)."""
    import time as _time

    delay = 0.05
    rpc_spans = []
    exe_spans = []

    class SlowServer(ParameterServer):
        """Server whose round-trip-bound calls carry a DCN-like delay
        and record their active interval."""

        def send_grad(self, *a, **k):
            t0 = _time.perf_counter()
            _time.sleep(delay)
            r = super().send_grad(*a, **k)
            rpc_spans.append((t0, _time.perf_counter()))
            return r

    class SlowExe:
        def __init__(self, inner):
            self._inner = inner

        def run(self, *a, **k):
            t0 = _time.perf_counter()
            _time.sleep(delay)
            r = self._inner.run(*a, **k)
            exe_spans.append((t0, _time.perf_counter()))
            return r

        def __getattr__(self, name):
            return getattr(self._inner, name)

    def overlap_count():
        return sum(
            1 for r0, r1 in rpc_spans for e0, e1 in exe_spans
            if max(r0, e0) < min(r1, e1)
        )

    rng = np.random.default_rng(2)
    xs = rng.normal(size=(8, 3)).astype(np.float32)
    ys = xs @ np.array([[1.0], [-2.0], [0.5]], np.float32)

    def run_mode(mode):
        rpc_spans.clear()
        exe_spans.clear()
        pt.core.unique_name.reset()
        main, startup = pt.Program(), pt.Program()
        scope = pt.Scope()
        pt.core.scope._scope_stack.append(scope)
        try:
            with pt.program_guard(main, startup):
                x = layers.data("x", shape=[3])
                y = layers.data("y", shape=[1])
                pred = layers.fc(input=x, size=1, bias_attr=False)
                loss = layers.mean(layers.square_error_cost(pred, y))
                pt.optimizer.SGD(learning_rate=0.01).minimize(loss)
            t = DistributeTranspiler()
            t.transpile(main, pservers=1, trainers=1)
            exe = pt.Executor()
            exe.run(startup)
            servers = [SlowServer(index=0, num_trainers=1)]
            dt = DistributedTrainer(t, SlowExe(exe), servers,
                                    learning_rate=0.01, mode=mode)
            dt.init_params_on_pservers()
            rpc_spans.clear()
            exe_spans.clear()
            for _ in range(5):
                dt.train_step({"x": xs, "y": ys})
            dt.flush()
            dt.close()
            return overlap_count()
        finally:
            pt.core.scope._scope_stack.pop()

    # serial: every RPC strictly between compute phases — zero overlap
    assert run_mode("serial") == 0
    # pipelined: the in-flight round trip spans the next step's compute
    assert run_mode("pipelined") >= 3


def test_pipelined_bytes_drop_when_idle_servers():
    """last_step_fetch_bytes reflects the conditional fetch: training
    steps move bytes; a step against already-converged (zero-grad)
    params still moves bytes only if the optimizer changed them."""
    dt, loss, servers = _fit_line_setup("serial", lr=0.0)
    rng = np.random.default_rng(3)
    xs = rng.normal(size=(4, 3)).astype(np.float32)
    ys = xs @ np.array([[1.0], [-2.0], [0.5]], np.float32)
    dt.train_step({"x": xs, "y": ys})
    first_bytes = dt.last_step_fetch_bytes
    # lr=0: SGD with zero learning rate still bumps the version (an
    # update ran), so bytes move; now fetch again with NO update at all
    dt.client.get_params_delta(dt.dense_names)
    assert dt.client.last_delta_bytes == 0
    assert first_bytes > 0
    dt.close()


def test_dense_step_preserves_param_dtype():
    """Regression: the numpy dense optimizer must not drift a non-f32
    param to float32 (the step_rows contract applies to step too)."""
    server = ParameterServer(index=0, num_trainers=1)
    client = PServerClient([server])
    w = np.ones((4, 4), np.float16)
    client.init_params({"w": w}, optimizer="adam", lr=0.01)
    client.send_grads({"w": np.ones_like(w, np.float32)})
    got = client.get_params(["w"])["w"]
    assert got.dtype == np.float16, got.dtype


def test_delta_fetch_degrades_on_legacy_server():
    """A server build without get_param_if_newer must degrade to the
    full fetch (the _meta_lookup missing-method discipline), not crash."""
    class LegacyServer(ParameterServer):
        def __getattribute__(self, name):
            if name == "get_param_if_newer":
                raise AttributeError(name)
            return super().__getattribute__(name)

    server = LegacyServer(index=0, num_trainers=1)
    client = PServerClient([server])
    w = np.arange(8, dtype=np.float32).reshape(2, 4)
    client.init_params({"w": w}, optimizer="sgd", lr=0.1)
    out = client.get_params_delta(["w"])
    np.testing.assert_allclose(out["w"], w)
    assert client.last_delta_bytes == w.nbytes
    # degraded mode: always a full fetch, bytes never drop to 0
    out2 = client.get_params_delta(["w"])
    np.testing.assert_allclose(out2["w"], w)
    assert client.last_delta_bytes == w.nbytes
    client.close()
