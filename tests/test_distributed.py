"""Distributed-layer tests, following the reference's in-process patterns:
client+servers in one process (pserver/test/test_ParameterServer2.cpp), RPC
layer alone (test_ProtoServer.cpp), master with the in-mem store
(go/master/service_internal_test.go), TTL'd discovery
(go/pserver/etcd_client_test.go)."""

import os
import pickle
import subprocess
import sys
import threading
import time

import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.distributed import rpc
from paddle_tpu.distributed.master import MasterClient, MasterService
from paddle_tpu.distributed.pserver import (
    ParameterServer,
    PServerClient,
    assign_server,
)
from paddle_tpu.distributed.store import (
    FileStore,
    InMemStore,
    discover_services,
    register_service,
)
from paddle_tpu.distributed.transpiler import (
    DistributedTrainer,
    DistributeTranspiler,
)
from paddle_tpu.native import recordio


# ------------------------------------------------------------------ rpc
class _Echo:
    def echo(self, x):
        return x

    def add(self, a, b=0):
        return a + b

    def boom(self):
        raise ValueError("boom")


def test_rpc_roundtrip_and_errors():
    server = rpc.Server(_Echo()).start()
    try:
        c = rpc.Client(server.endpoint)
        assert c.call("echo", {"a": np.arange(3)})["a"].tolist() == [0, 1, 2]
        assert c.call("add", 2, b=3) == 5
        with pytest.raises(RuntimeError, match="boom"):
            c.call("boom")
        # still usable after a remote error
        assert c.call("add", 1, b=1) == 2
        c.close()
    finally:
        server.stop()


def test_rpc_large_payload():
    server = rpc.Server(_Echo()).start()
    try:
        c = rpc.Client(server.endpoint)
        big = np.random.rand(1 << 20)  # 8 MB
        np.testing.assert_array_equal(c.call("echo", big), big)
        c.close()
    finally:
        server.stop()


# ---------------------------------------------------------------- store
def test_inmem_store_ttl_and_cas():
    s = InMemStore()
    s.put("a", 1)
    assert s.get("a") == 1
    s.put("b", 2, ttl=0.05)
    assert s.get("b") == 2
    time.sleep(0.1)
    assert s.get("b") is None
    assert s.cas("a", 1, 10)
    assert not s.cas("a", 1, 20)
    assert s.get("a") == 10
    assert s.keys() == ["a"]


def test_file_store(tmp_path):
    s = FileStore(str(tmp_path))
    s.put("x/y", {"v": 1})
    assert s.get("x/y") == {"v": 1}
    assert s.keys("x/") == ["x/y"]
    s.delete("x/y")
    assert s.get("x/y") is None


def test_service_discovery_ttl():
    s = InMemStore()
    stop = register_service(s, "pserver", "127.0.0.1:9000", ttl=0.3)
    time.sleep(0.05)
    assert discover_services(s, "pserver") == ["127.0.0.1:9000"]
    stop()
    time.sleep(0.1)
    assert discover_services(s, "pserver") == []


# --------------------------------------------------------------- master
def _write_dataset(tmp_path, n_files=2, recs_per_file=40):
    paths, all_recs = [], []
    for i in range(n_files):
        p = tmp_path / f"data-{i:05d}"
        with recordio.Writer(p, max_chunk_bytes=256) as w:
            for j in range(recs_per_file):
                rec = pickle.dumps((i, j))
                w.write(rec)
                all_recs.append(rec)
        paths.append(str(p))
    return paths, all_recs


def test_master_chunk_partition_and_pass(tmp_path):
    paths, all_recs = _write_dataset(tmp_path)
    svc = MasterService(timeout_sec=60)
    svc.set_dataset(paths)
    n_chunks = sum(len(recordio.index(p)) for p in paths)
    assert len(svc.todo) == n_chunks

    client = MasterClient(svc)
    client.set_dataset(paths)
    got = []
    while True:
        r = client.next_record()
        if r is None:
            break
        got.append(r)
    assert sorted(got) == sorted(all_recs)
    # next pass serves everything again
    assert svc.num_passes_finished() >= 0
    got2 = []
    while True:
        r = client.next_record()
        if r is None:
            break
        got2.append(r)
    assert sorted(got2) == sorted(all_recs)


def test_master_failure_poison_drop(tmp_path):
    paths, _ = _write_dataset(tmp_path, n_files=1, recs_per_file=4)
    svc = MasterService(timeout_sec=60, failure_max=2)
    svc.set_dataset(paths)
    t1 = svc.get_task()
    assert svc.task_failed(t1["id"])
    t2 = svc.get_task()
    assert t2["id"] == t1["id"]  # requeued
    svc.task_failed(t2["id"])
    # failure_max reached -> dropped to failed, not todo
    assert all(t.id != t1["id"] for t in svc.todo)
    assert any(t.id == t1["id"] for t in svc.failed)


def test_master_timeout_requeue(tmp_path):
    paths, _ = _write_dataset(tmp_path, n_files=1, recs_per_file=4)
    svc = MasterService(timeout_sec=0.2, failure_max=5)
    svc.set_dataset(paths)
    t = svc.get_task()
    deadline = time.time() + 5
    while not svc.todo and time.time() < deadline:
        time.sleep(0.05)
    assert any(x.id == t["id"] for x in svc.todo), "task not requeued"


def test_master_snapshot_recover(tmp_path):
    paths, all_recs = _write_dataset(tmp_path, n_files=1, recs_per_file=10)
    store = InMemStore()
    svc = MasterService(store=store, timeout_sec=60)
    svc.set_dataset(paths)
    leased = svc.get_task()
    assert leased is not None
    # master dies; a new one recovers from the store: pending -> todo
    svc2 = MasterService(store=store, timeout_sec=60)
    ids = {t.id for t in svc2.todo}
    assert leased["id"] in ids


def test_master_save_model_election():
    svc = MasterService(timeout_sec=60)
    assert svc.request_save_model("t0", block_sec=5)
    assert not svc.request_save_model("t1", block_sec=5)
    assert svc.request_save_model("t0", block_sec=5)


def test_cloud_reader(tmp_path):
    from paddle_tpu.reader.creator import cloud_reader

    paths, all_recs = _write_dataset(tmp_path, n_files=1, recs_per_file=12)
    svc = MasterService(timeout_sec=60)
    reader = cloud_reader(paths, etcd_endpoints=svc)
    got = list(reader())
    assert sorted(map(str, got)) == sorted(
        str(pickle.loads(r)) for r in all_recs
    )


# -------------------------------------------------------------- pserver
def test_pserver_sync_barrier_two_trainers():
    ps = ParameterServer(num_trainers=2, sync=True)
    ps.init_param("w", np.zeros(4, np.float32), optimizer="sgd", lr=0.5)
    ps.finish_init_params()

    def trainer(grad):
        ps.send_grad("w", np.full(4, grad, np.float32))

    t1 = threading.Thread(target=trainer, args=(1.0,))
    t2 = threading.Thread(target=trainer, args=(3.0,))
    t1.start(); t2.start(); t1.join(); t2.join()
    # averaged grad = 2.0, lr 0.5 -> w = -1
    np.testing.assert_allclose(ps.get_param("w"), -np.ones(4), rtol=1e-6)


def test_pserver_async_and_sparse():
    ps = ParameterServer(num_trainers=1, sync=False)
    ps.init_param("emb", np.ones((10, 2), np.float32), optimizer="sgd", lr=1.0)
    ps.finish_init_params()
    ps.send_sparse_grad("emb", np.array([1, 3]), np.ones((2, 2), np.float32))
    p = ps.get_param("emb")
    np.testing.assert_allclose(p[1], [0, 0])
    np.testing.assert_allclose(p[0], [1, 1])
    rows = ps.get_param_rows("emb", [3])
    np.testing.assert_allclose(rows, [[0, 0]])


def test_pserver_adam_server_side():
    ps = ParameterServer(num_trainers=1, sync=True)
    w0 = np.ones(3, np.float32)
    ps.init_param("w", w0, optimizer="adam", lr=0.1)
    ps.finish_init_params()
    ps.send_grad("w", np.ones(3, np.float32))
    w1 = ps.get_param("w")
    assert np.all(w1 < w0)  # moved against the gradient
    assert np.isfinite(w1).all()


def test_pserver_checkpoint_recover(tmp_path):
    store = InMemStore()
    ps = ParameterServer(index=0, num_trainers=1, sync=False, store=store,
                         checkpoint_dir=str(tmp_path),
                         checkpoint_every_n_updates=1)
    ps.init_param("w", np.zeros(2, np.float32), optimizer="momentum", lr=0.1,
                  attrs={"mu": 0.9})
    ps.finish_init_params()
    ps.send_grad("w", np.ones(2, np.float32))
    w_after = ps.get_param("w").copy()
    # new server instance on same store+dir recovers params AND momentum
    ps2 = ParameterServer(index=0, num_trainers=1, sync=False, store=store,
                          checkpoint_dir=str(tmp_path))
    assert ps2.ready()
    np.testing.assert_allclose(ps2.get_param("w"), w_after)
    ps2.send_grad("w", np.ones(2, np.float32))
    # momentum state survived: second step larger than first
    step2 = np.abs(ps2.get_param("w") - w_after)
    assert np.all(step2 > np.abs(w_after) * 1.5)


def test_pserver_client_over_rpc_sharded():
    servers = [ParameterServer(index=i, num_trainers=1) for i in range(2)]
    rpc_servers = [rpc.Server(s).start() for s in servers]
    try:
        client = PServerClient([s.endpoint for s in rpc_servers])
        params = {f"p{i}": np.full(2, float(i), np.float32) for i in range(5)}
        client.init_params(params, optimizer="sgd", lr=1.0)
        client.send_grads({n: np.ones(2, np.float32) for n in params})
        fresh = client.get_params(list(params))
        for i in range(5):
            np.testing.assert_allclose(fresh[f"p{i}"], float(i) - 1.0)
        # shards actually split across the two servers
        counts = [len(s.params) for s in servers]
        assert sum(counts) == 5 and all(c > 0 for c in counts)
    finally:
        for s in rpc_servers:
            s.stop()


# ----------------------------------------------------------- transpiler
def test_transpiler_end_to_end_training():
    """fit_a_line via 2 in-process pservers: the fluid transpiler book-test
    pattern (book_distribute/notest_*_dist.py) without real processes."""
    x = layers.data("x", shape=[3])
    y = layers.data("y", shape=[1])
    pred = layers.fc(input=x, size=1, bias_attr=False)
    loss = layers.mean(layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    main = pt.default_main_program()

    t = DistributeTranspiler()
    t.transpile(main, pservers=2, trainers=1)
    # optimizer ops stripped from the trainer half
    trainer_prog = t.get_trainer_program()
    assert all(op.type != "sgd" for op in trainer_prog.global_block().ops)
    # every param assigned to some pserver; both halves cover all params
    cfg0 = t.get_pserver_config(0)
    cfg1 = t.get_pserver_config(1)
    assert set(cfg0) | set(cfg1) == set(t.optimize_info)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    servers = [ParameterServer(index=i, num_trainers=1) for i in range(2)]
    dt = DistributedTrainer(t, exe, servers, learning_rate=0.05)
    dt.init_params_on_pservers()

    rng = np.random.default_rng(0)
    xs = rng.normal(size=(16, 3)).astype(np.float32)
    w_true = np.array([[1.0], [-2.0], [0.5]], np.float32)
    ys = xs @ w_true
    losses = []
    for _ in range(10):
        out = dt.train_step({"x": xs, "y": ys}, extra_fetch=[loss])
        losses.append(float(np.asarray(out[0]).ravel()[0]))
    assert losses[-1] < losses[0] * 0.7, losses


def test_assign_server_stable():
    assert assign_server("w", 4) == assign_server("w", 4)
    spread = {assign_server(f"p{i}", 4) for i in range(32)}
    assert len(spread) == 4


def test_transpiler_conv_model_dist():
    """recognize_digits_conv via the pserver path (reference
    book_distribute/notest_recognize_digits_conv_dist.py): a real conv
    model's params sharded over 2 in-process pservers, server-side SGD."""
    from paddle_tpu.models import lenet

    outs = lenet.build(learning_rate=0.003)
    main = pt.default_main_program()

    t = DistributeTranspiler()
    t.transpile(main, pservers=2, trainers=1)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    servers = [ParameterServer(index=i, num_trainers=1) for i in range(2)]
    dt = DistributedTrainer(t, exe, servers, learning_rate=0.003)
    dt.init_params_on_pservers()

    rng = np.random.default_rng(3)
    img = rng.normal(size=(8, 1, 28, 28)).astype(np.float32)
    lbl = rng.integers(0, 10, (8, 1)).astype(np.int64)
    losses = []
    for _ in range(6):
        out = dt.train_step({"img": img, "label": lbl},
                            extra_fetch=[outs["avg_cost"]])
        losses.append(float(np.asarray(out[0]).ravel()[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_launch_single_host_and_mesh():
    from paddle_tpu.distributed import launch

    launch.init_multihost()  # single host: no-op success
    assert launch.is_initialized()
    mesh = launch.global_mesh({"dp": -1, "tp": 2})
    assert mesh.shape["tp"] == 2
    assert mesh.shape["dp"] * 2 == len(jax.devices())
    with pytest.raises(ValueError, match="devices"):
        launch.global_mesh({"dp": 3, "tp": 5})
    with pytest.raises(ValueError, match="one mesh axis"):
        launch.global_mesh({"dp": -1, "tp": -1})


def _reap(procs):
    """Terminate subprocess(es), never raising out of a finally block."""
    if not isinstance(procs, (list, tuple)):
        procs = [procs]
    for p in procs:
        p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait(timeout=10)


def _spawn_cli(cli_args, store_path):
    """Spawn `python -m paddle_tpu <args>` and wait (bounded even if the
    child hangs silently: stdout is drained on a helper thread) for its
    'serving on <endpoint>' line; returns (proc, endpoint)."""
    import os
    import queue
    import re
    import sys

    repo_root = os.path.dirname(os.path.dirname(__file__))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    prev = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = repo_root + (os.pathsep + prev if prev else "")
    p = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu", *cli_args,
         "--store", str(store_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)

    q = queue.Queue()

    def drain():
        for line in p.stdout:
            q.put(line)
        q.put(None)

    threading.Thread(target=drain, daemon=True).start()
    deadline = time.time() + 60
    lines = []
    while time.time() < deadline:
        try:
            line = q.get(timeout=max(0.1, deadline - time.time()))
        except queue.Empty:
            break
        if line is None:
            break
        lines.append(line)
        m = re.search(r"serving on (\S+)", line)
        if m:
            return p, m.group(1)
    _reap(p)
    raise AssertionError(f"no endpoint from {cli_args}: {lines!r}")


def test_cli_pserver_processes_end_to_end(tmp_path):
    """REAL multi-process distributed training: two `python -m paddle_tpu
    pserver` subprocesses over TCP, trainer in this process (the reference
    book_distribute pattern with actual processes, SURVEY §4)."""
    procs, endpoints = [], []
    try:
        for i in range(2):
            p, ep = _spawn_cli(
                ["pserver", "--index", str(i), "--num-trainers", "1",
                 "--port", "0"], tmp_path / "store")
            procs.append(p)
            endpoints.append(ep)

        client = PServerClient(endpoints)
        rng = np.random.default_rng(0)
        w = {"w_a": rng.normal(size=(4,)).astype(np.float32),
             "w_b": rng.normal(size=(3,)).astype(np.float32)}
        client.init_params(w, optimizer="sgd", lr=0.1, attrs={})
        for _ in range(3):
            grads = {k: np.ones_like(v) for k, v in w.items()}
            client.send_grads(grads)
        fresh = client.get_params(list(w))
        for k in w:
            np.testing.assert_allclose(
                fresh[k], w[k] - 0.1 * 3 * np.ones_like(w[k]), rtol=1e-5)
    finally:
        _reap(procs)


def test_cli_master_process_end_to_end(tmp_path):
    """`python -m paddle_tpu master` subprocess serving a RecordIO dataset
    over TCP; records consumed via MasterClient from this process."""
    paths, all_recs = _write_dataset(tmp_path, n_files=2, recs_per_file=10)
    p, endpoint = _spawn_cli(
        ["master", "--port", "0", "--dataset", *paths], tmp_path / "store")
    try:
        client = MasterClient(endpoint)
        got = []
        while True:
            rec = client.next_record()
            if rec is None:
                break
            got.append(rec)
        assert sorted(got) == sorted(all_recs)
    finally:
        _reap(p)


def test_multihost_two_process_cpu(tmp_path):
    """REAL 2-process multi-host run over the JAX coordination service
    (CPU backend): launch.init_multihost on each process, a global mesh
    spanning both, a cross-process psum, and 2 data-parallel Executor
    steps whose replicated state agrees bit-for-bit across processes
    (reference analog: cluster_train_v2 launchers + --trainer_id)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"

    env = dict(os.environ)
    for k in list(env):
        if "AXON" in k or k.startswith("TPU_") or k.startswith("PJRT_"):
            env.pop(k)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONSAFEPATH", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        [repo] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=2")
    env["XLA_FLAGS"] = " ".join(flags)

    runner = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "multihost_runner.py")
    procs = [
        subprocess.Popen(
            [sys.executable, runner, coordinator, "2", str(i)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"rank {i} failed:\n{out}"
        oks = [
            [l for l in out.splitlines() if l.startswith("MULTIHOST_OK")]
            for out in outs
        ]
        assert all(len(o) == 1 for o in oks), outs
        # replicated loss and params identical across the two processes
        assert oks[0][0].split()[2:] == oks[1][0].split()[2:], oks
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
