"""Autotune engine tests (ISSUE 9): cache robustness (corrupt /
truncated / schema-version mismatch / stale kernel-geometry
fingerprint must each fall back to defaults and re-tune, never crash
or serve a wrong config), the candidate space + static pruning, the
hot-path wiring, and the bench-history un-ack logic."""

import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import tune
from paddle_tpu.tune import cache as tcache
from paddle_tpu.tune import space as tspace


@pytest.fixture
def tmp_cache(tmp_path, monkeypatch):
    """A fresh cache file path + singleton reset around each test (and
    a DIAG_W restore: the hot path may apply a tuned width)."""
    from paddle_tpu.ops import pallas_attention as pa

    monkeypatch.setattr(pa, "DIAG_W", pa.DIAG_W)
    path = tmp_path / "tuned.json"
    monkeypatch.setenv("PADDLE_TPU_TUNE_CACHE", str(path))
    monkeypatch.setenv("PADDLE_TPU_TUNE", "cached")
    tune.reset_cache()
    yield path
    tune.reset_cache()


def _seed_entry(path, **overrides):
    """Write a VALID cache file with one flash entry, then apply
    overrides (None deletes a field)."""
    c = tcache.TuneCache(str(path))
    key = tspace.WorkloadKey("flash", 64, 32, 2, "float32", "cpu",
                             remat="-")
    c.put(key.s, {"block_q": 32, "block_k": 16, "diag_w": 16,
                  "packed": None})
    c.save()
    if overrides:
        data = json.loads(path.read_text())
        for k, v in overrides.items():
            if v is None:
                data.pop(k, None)
            else:
                data[k] = v
        path.write_text(json.dumps(data))
    tune.reset_cache()
    return key


# -- cache robustness (the satellite contract) ---------------------------

def test_cache_roundtrip(tmp_cache):
    key = _seed_entry(tmp_cache)
    got = tune.get_cache().get(key.s)
    assert got["config"]["block_q"] == 32
    assert tune.attention_config(64, 32, 2, "float32") == {
        "block_q": 32, "block_k": 16, "diag_w": 16, "packed": None}


def test_corrupt_cache_falls_back_to_defaults(tmp_cache):
    _seed_entry(tmp_cache)
    tmp_cache.write_bytes(b"\x00garbage not json{{{")
    tune.reset_cache()
    c = tune.get_cache()
    assert c.entries == {} and "unreadable" in c.stale_reason
    assert tune.attention_config(64, 32, 2, "float32") is None
    # re-tune rewrites a valid file over the garbage
    c.put("k", {"block_q": 8})
    c.save()
    tune.reset_cache()
    assert tune.get_cache().get("k")["config"]["block_q"] == 8


def test_truncated_cache_falls_back(tmp_cache):
    _seed_entry(tmp_cache)
    full = tmp_cache.read_text()
    tmp_cache.write_text(full[: len(full) // 2])
    tune.reset_cache()
    c = tune.get_cache()
    assert c.entries == {} and c.stale_reason is not None


def test_schema_version_mismatch_ignored(tmp_cache):
    key = _seed_entry(tmp_cache, schema_version=999)
    c = tune.get_cache()
    assert c.get(key.s) is None
    assert "schema_version" in c.stale_reason


def test_stale_fingerprint_retunes(tmp_cache, monkeypatch):
    """A cache written against a different kernel geometry is stale:
    entries are ignored (defaults apply) and the next save stamps the
    CURRENT fingerprint."""
    key = _seed_entry(tmp_cache)
    from paddle_tpu.ops import pallas_attention as pa

    monkeypatch.setattr(pa, "LSE_LANES", 256)  # kernel geometry changed
    tune.reset_cache()
    c = tune.get_cache()
    assert c.get(key.s) is None
    assert "fingerprint" in c.stale_reason
    c.put(key.s, {"block_q": 64})
    c.save()
    tune.reset_cache()
    assert tune.get_cache().get(key.s)["config"]["block_q"] == 64
    # and the old-geometry process would in turn see THIS file as stale
    monkeypatch.undo()
    tune.reset_cache()
    assert tune.get_cache().get(key.s) is None


def test_non_object_entries_ignored(tmp_cache):
    _seed_entry(tmp_cache, entries={"bad": [1, 2], "worse": "x"})
    assert tune.get_cache().entries == {}


def test_kill_switch_skips_lookup(tmp_cache, monkeypatch):
    key = _seed_entry(tmp_cache)
    monkeypatch.setenv("PADDLE_TPU_TUNE", "0")
    assert tune.tune_mode() == "off"
    assert tune.attention_config(64, 32, 2, "float32") is None
    monkeypatch.setenv("PADDLE_TPU_TUNE", "cached")
    assert tune.attention_config(64, 32, 2, "float32") is not None
    assert key.s in tune.get_cache().entries


# -- workload key + candidate space + static pruning ---------------------

def test_workload_key_canonical_string():
    k = tspace.WorkloadKey("flash", 4096, 128, 6, np.dtype("float32"),
                           "tpu", remat="-")
    assert k.s == "op=flash|t=4096|dh=128|h=6|dt=float32|plat=tpu|remat=-"
    assert k == tspace.WorkloadKey("flash", 4096, 128, 6, "float32",
                                   "tpu", remat="-")
    assert tspace.WorkloadKey("flash", 4096, 128, 6, "bfloat16", "tpu",
                              remat="-") != k


def test_candidates_tile_exactly():
    for c in tspace.attention_candidates(4096, 128, 6):
        assert 4096 % c["block_q"] == 0 and 4096 % c["block_k"] == 0
        assert c["block_q"] % c["diag_w"] == 0 or \
            c["diag_w"] <= min(c["block_q"], c["block_k"])
    # toy t: blocks shrink to exact divisors instead of disappearing
    toys = tspace.attention_candidates(96, 32, 2, block_caps=(32, 64))
    assert toys and all(96 % c["block_q"] == 0 for c in toys)


def test_prune_static_roofline_and_vmem():
    cands = tspace.attention_candidates(4096, 128, 2,
                                        block_caps=(512, 1024, 4096))
    survivors, pruned = tspace.prune_static(4096, 128, 2, cands)
    assert survivors, "something must survive"
    assert all("roofline" in c for c in survivors)
    # a 4096x4096 block pair blows the VMEM budget and must be pruned
    vmem_pruned = [r for _, r in pruned if "vmem" in r]
    assert vmem_pruned, f"expected a vmem rejection, got {pruned}"


def test_hbm_model_ordering_matches_measured_reality():
    """The analytic bound must reproduce the measured t=16k facts:
    selective/offload at accum=1 exceed the 15.75 GiB chip (BENCH_r05),
    while accum2-no-remat, offload+accum2 and bs6 full-remat fit
    (bench.py memory_gate)."""
    G = 1 << 30
    est = lambda pol, acc: tspace.estimate_gpt_step_hbm(
        12, 768, 6, 32768, 16384, 6, policy=pol, accum=acc)
    assert est("selective", 1) > 15.75 * G
    assert est("offload", 1) > 15.75 * G
    assert est("none", 2) < 15.75 * G
    assert est("offload", 2) < 15.75 * G
    assert est("full", 1) < 15.75 * G
    # monotone in the levers
    assert est("offload", 2) < est("offload", 1)
    assert est("full", 1) < est("selective", 1) < est("none", 1)


def test_prune_static_hbm_budget_rejects_r05_config():
    demo = tune.flagship_static_demo()
    assert "gpt_t16k_rejected_r05_config" in demo
    assert demo["gpt_t16k_selected_policy"] in tspace.POLICY_ORDER
    sel_est = demo["gpt_t16k_selected_est_hbm_gib"]
    assert 0 < sel_est <= 0.85 * demo["gpt_t16k_budget_gib"]


# -- hot-path wiring -----------------------------------------------------

def _flash_op(program):
    for op in program.global_block().ops:
        if op.type in ("flash_attention_packed", "flash_attention"):
            return op
    return None


def _build_gpt(**kw):
    from paddle_tpu.models import transformer

    pt.core.unique_name.reset()
    main_prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_prog, startup):
        transformer.build(vocab_size=61, n_layer=2, n_head=2, d_model=64,
                          max_len=64, dropout_rate=0.0, dtype="float32",
                          **kw)
    return main_prog


def test_multi_head_attention_applies_tuned_geometry(tmp_cache):
    _seed_entry(tmp_cache)  # flash t=64 dh=32 h=2 float32 cpu
    main_prog = _build_gpt()
    op = _flash_op(main_prog)
    assert op.attrs.get("block_q") == 32 and op.attrs.get("block_k") == 16


def test_explicit_blocks_win_over_cache(tmp_cache):
    _seed_entry(tmp_cache)
    main_prog = _build_gpt(attn_block_q=8, attn_block_k=8)
    op = _flash_op(main_prog)
    assert op.attrs.get("block_q") == 8 and op.attrs.get("block_k") == 8


def test_kill_switch_builds_default_program(tmp_cache, monkeypatch):
    _seed_entry(tmp_cache)
    monkeypatch.setenv("PADDLE_TPU_TUNE", "0")
    op = _flash_op(_build_gpt())
    assert "block_q" not in op.attrs and "block_k" not in op.attrs


def test_forced_attention_config_context():
    with tune.forced_attention_config({"block_q": 16, "block_k": 16}):
        op = _flash_op(_build_gpt())
        assert op.attrs.get("block_q") == 16
    op = _flash_op(_build_gpt())
    assert op.attrs.get("block_q") != 16


def test_memory_optimize_auto_consults_cache(tmp_cache):
    """policy='auto' resolves the tuned winner; a miss (or winner
    'none') degrades sanely."""
    main_prog = _build_gpt()
    # miss -> selective segmentation applied
    segs = pt.memory_optimize(main_prog, policy="auto")
    assert segs and getattr(main_prog, "_offload", False) is False
    # seed a gpt_step winner with policy none -> program left unmarked
    c = tune.get_cache()
    key = tspace.WorkloadKey("gpt_step", 64, 32, 2, "float32", "cpu",
                             remat="auto")
    c.put(key.s, {"policy": "none", "accum": 1,
                  "block_q": 32, "block_k": 32})
    c.save()
    tune.reset_cache()
    main_prog = _build_gpt()
    assert pt.memory_optimize(main_prog, policy="auto") == []
    # and an offload winner sets the offload flag through the normal path
    c = tune.get_cache()
    c.put(key.s, {"policy": "offload", "accum": 1,
                  "block_q": 32, "block_k": 32})
    c.save()
    tune.reset_cache()
    main_prog = _build_gpt()
    pt.memory_optimize(main_prog, policy="auto")
    assert getattr(main_prog, "_offload", False) is True


def test_tune_stats_reaches_last_step_cost(tmp_cache):
    from paddle_tpu.observability import get_registry

    _seed_entry(tmp_cache)
    main_prog = _build_gpt()  # lookup hit increments the counter
    # a tiny real compile to fold stats into last_step_cost
    pt.core.unique_name.reset()
    mp, sp = pt.Program(), pt.Program()
    with pt.program_guard(mp, sp):
        from paddle_tpu import layers

        x = layers.data("x", shape=[4])
        y = layers.fc(x, 2)
        exe = pt.Executor()
        exe.run(sp)
        exe.run(mp, feed={"x": np.zeros((2, 4), np.float32)},
                fetch_list=[y])
    ts = exe.last_step_cost.get("tune")
    assert ts and ts["cache_hits"] >= 1


# -- cached mode never searches / search mode persists -------------------

def test_cached_mode_never_compiles_on_miss(tmp_cache):
    from paddle_tpu.observability import get_registry

    reg = get_registry()
    c0 = reg.value("executor.compile_count")
    rep = tune.tune_gpt_step(seq_len=64, n_layer=2, d_model=64, n_head=2,
                             vocab=61, batch=4, dtype="float32")
    assert rep["source"] == "miss" and rep["entry"] is None
    assert reg.value("executor.compile_count") == c0


def test_fingerprint_is_stable_and_geometry_sensitive(monkeypatch):
    f1 = tune.geometry_fingerprint()
    assert f1 == tune.geometry_fingerprint()
    from paddle_tpu.ops import pallas_attention as pa

    monkeypatch.setattr(pa, "LSE_LANES", 256)
    assert tune.geometry_fingerprint() != f1
    monkeypatch.undo()
    # DIAG_W is a TUNABLE the cache stores — applying a tuned width
    # must NOT invalidate the cache that set it
    monkeypatch.setattr(pa, "DIAG_W", 512)
    assert tune.geometry_fingerprint() == f1


def test_tuned_diag_w_applied_and_env_pin_wins(tmp_cache, monkeypatch):
    """The winner's diag_w reaches the kernels (module global, set by
    the hot-path lookup); a PADDLE_TPU_DIAG_W env pin beats the cache."""
    from paddle_tpu.ops import pallas_attention as pa

    _seed_entry(tmp_cache)  # carries diag_w=16
    _build_gpt()
    assert pa.DIAG_W == 16
    monkeypatch.setattr(pa, "DIAG_W", 256)
    monkeypatch.setattr(pa, "_DIAG_W_ENV", 128)
    _build_gpt()
    assert pa.DIAG_W == 256  # env-pinned: the cache may not move it


# -- bench-history: the t16k un-ack machinery ----------------------------

def _write_artifact(d, name, data):
    with open(os.path.join(d, name), "w") as fh:
        json.dump(data, fh)


def test_bench_history_t16k_evidence_resolves_failure(tmp_path):
    from paddle_tpu.observability import bench_history as bh

    _write_artifact(tmp_path, "BENCH_r05.json", {
        "n": 5, "rc": 1, "parsed": None,
        "tail": "Shape: bf16[6,16384,768]... RESOURCE_EXHAUSTED"})
    _write_artifact(tmp_path, "BENCH_r06.json", {
        "n": 6, "rc": 0, "parsed": {
            "metric": "smoke_train_images_per_sec", "value": 900.0,
            "unit": "img/s",
            "extra": {"gpt_t16k_selected_policy": "offload",
                      "gpt_t16k_static_only": True}}})
    summary, rows = bh.history(str(tmp_path))
    assert summary["ok"] is True
    assert "BENCH_r05.json" in summary["resolved"]
    assert summary["failed"] == ["BENCH_r05.json"]
    # a stale ack for the resolved artifact flags as a warning, not rot
    summary2, _ = bh.history(str(tmp_path),
                             known_failures={"BENCH_r05.json": "old"})
    assert summary2["ok"] is True
    assert summary2["stale_acks"] == ["BENCH_r05.json"]


def test_bench_history_failure_without_evidence_still_fails(tmp_path):
    from paddle_tpu.observability import bench_history as bh

    _write_artifact(tmp_path, "BENCH_r05.json", {
        "n": 5, "rc": 1, "parsed": None,
        "tail": "Shape: bf16[6,16384,768] Allocation type: HLO temp"})
    summary, _ = bh.history(str(tmp_path))
    assert summary["ok"] is False  # no evidence round -> ack required
    # evidence in an EARLIER round does not resolve a later failure
    _write_artifact(tmp_path, "BENCH_r04.json", {
        "n": 4, "rc": 0, "parsed": {
            "metric": "m", "value": 1.0,
            "extra": {"gpt_t16k_selected_policy": "offload"}}})
    summary, _ = bh.history(str(tmp_path))
    assert summary["ok"] is False
    # a t=16384 mention WITHOUT an allocator signature is NOT the rot
    # class — a future unrelated t=16k failure must not auto-resolve
    _write_artifact(tmp_path, "BENCH_r05.json", {
        "n": 5, "rc": 1, "parsed": None,
        "tail": "driver crash at step 16384"})
    summary, _ = bh.history(str(tmp_path))
    assert summary["ok"] is False
    _write_artifact(tmp_path, "BENCH_r05.json", {
        "n": 5, "rc": 1, "parsed": None,
        "tail": "Shape: bf16[6,16384,768] Allocation type: HLO temp"})
    # a non-t16k failure class is never evidence-resolved
    _write_artifact(tmp_path, "BENCH_r06.json", {
        "n": 6, "rc": 0, "parsed": {
            "metric": "m", "value": 1.0,
            "extra": {"gpt_t16k_selected_policy": "offload"}}})
    _write_artifact(tmp_path, "BENCH_r07.json", {
        "n": 7, "rc": 1, "parsed": None, "tail": "segfault"})
    summary, _ = bh.history(str(tmp_path))
    assert "BENCH_r07.json" not in summary["resolved"]
    assert summary["ok"] is False


def test_bench_history_rung_metric_flags_fallback_row(tmp_path):
    """A t/2 fallback row halves gate_flagship_gpt_seq — the regression
    flagging catches it (the satellite: a fallback row can never
    impersonate a true t=16k row)."""
    from paddle_tpu.observability import bench_history as bh

    _write_artifact(tmp_path, "BENCH_r06.json", {
        "n": 6, "rc": 0, "parsed": {
            "metric": "m", "value": 1.0,
            "extra": {"gate_flagship_gpt_seq": 16384}}})
    _write_artifact(tmp_path, "BENCH_r07.json", {
        "n": 7, "rc": 0, "parsed": {
            "metric": "m", "value": 1.0,
            "extra": {"gate_flagship_gpt_seq": 8192}}})
    summary, _ = bh.history(str(tmp_path))
    regs = [r for r in summary["regressions"]
            if r["metric"] == "gate_flagship_gpt_seq"]
    assert regs and regs[0]["artifact"] == "BENCH_r07.json"
    assert summary["ok"] is False


def test_bench_history_regression_ack_not_stale_while_flagged(tmp_path):
    """An 'artifact:metric' ack for a STILL-FLAGGED regression on an
    otherwise-ok artifact is the normal state — it must not report as
    stale (following a bogus delete-me warning would break the gate)."""
    from paddle_tpu.observability import bench_history as bh

    _write_artifact(tmp_path, "BENCH_r01.json", {
        "n": 1, "rc": 0,
        "parsed": {"metric": "m", "value": 100.0, "unit": "u"}})
    _write_artifact(tmp_path, "BENCH_r02.json", {
        "n": 2, "rc": 0,
        "parsed": {"metric": "m", "value": 50.0, "unit": "u"}})
    known = {"BENCH_r02.json:m": "known dip, root-caused"}
    summary, _ = bh.history(str(tmp_path), known_failures=known)
    assert summary["ok"] is True
    assert summary["stale_acks"] == []
    # once the regression heals (value recovers), the ack IS stale
    _write_artifact(tmp_path, "BENCH_r03.json", {
        "n": 3, "rc": 0,
        "parsed": {"metric": "m", "value": 101.0, "unit": "u"}})
    _write_artifact(tmp_path, "BENCH_r02.json", {
        "n": 2, "rc": 0,
        "parsed": {"metric": "m", "value": 99.0, "unit": "u"}})
    summary, _ = bh.history(str(tmp_path), known_failures=known)
    assert summary["stale_acks"] == ["BENCH_r02.json:m"]


def test_bench_history_resnet_regression_exempt(tmp_path):
    """The r04 ResNet dip class (shared-runner noise) is exempt with a
    recorded reason — it shows in the trajectory, never flags."""
    from paddle_tpu.observability import bench_history as bh

    m = "resnet50_train_images_per_sec_per_chip"
    assert m in bh._REGRESSION_EXEMPT
    assert "noise" in bh._REGRESSION_EXEMPT[m]
    _write_artifact(tmp_path, "BENCH_r01.json", {
        "n": 1, "rc": 0,
        "parsed": {"metric": m, "value": 2403.0, "unit": "img/s"}})
    _write_artifact(tmp_path, "BENCH_r02.json", {
        "n": 2, "rc": 0,
        "parsed": {"metric": m, "value": 1500.0, "unit": "img/s"}})
    summary, _ = bh.history(str(tmp_path))
    assert summary["regressions"] == [] and summary["ok"] is True
    assert m in summary["metrics_tracked"]
