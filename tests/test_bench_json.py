"""bench.py output-contract tests (ISSUE 3 satellite): the flagship JSON
line must print — parseable, non-null value — even when a numeric gate
fails; gate failures land as "gate_<name>": "FAILED: ..." strings in
extra and only flip the rc."""

import json

import numpy as np
import pytest

import bench


class _FakeDev:
    platform = "tpu"


@pytest.fixture
def flagship_env(monkeypatch):
    """Pretend an accelerator exists and both flagships produce numbers,
    without running any real benchmark."""
    monkeypatch.setattr(bench, "detect_devices", lambda: [_FakeDev()])
    monkeypatch.setattr(bench, "bench_resnet",
                        lambda *a, **k: (100.0, 90.0, 110.0))
    monkeypatch.setattr(bench, "bench_gpt",
                        lambda *a, **k: (1000.0, 0.31, 900.0, 1100.0))
    monkeypatch.setenv("BENCH_MODELS", "resnet,gpt")
    monkeypatch.delenv("BENCH_SMOKE", raising=False)
    monkeypatch.delenv("BENCH_INFER", raising=False)
    monkeypatch.delenv("BENCH_SERVING", raising=False)


def _run_main(capsys):
    rc = bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, out
    return rc, json.loads(out[0])


def test_flagship_line_survives_failing_gate(flagship_env, monkeypatch,
                                             capsys):
    """Inject a failing gate: the flagship JSON line still prints with a
    non-null value; the failure is a string in extra; rc is nonzero."""
    def boom():
        raise RuntimeError("injected gate failure")

    monkeypatch.setattr(bench, "_gate_flash", boom)
    monkeypatch.setattr(bench, "grad_numeric_gates", lambda: {"g": 1.0})
    monkeypatch.setattr(bench, "_gate_mem", lambda: {"m": 1.0})
    rc, row = _run_main(capsys)
    assert rc != 0
    assert row["metric"] == "resnet50_train_images_per_sec_per_chip"
    assert row["value"] == 100.0  # NOT zeroed out by the gate failure
    assert row["extra"]["gate_flash"].startswith("FAILED: RuntimeError")
    assert row["extra"]["g"] == 1.0  # later gates still ran
    assert row["extra"]["m"] == 1.0
    assert row["extra"]["gpt_mfu"] == 0.31


def test_every_gate_failing_still_prints_numbers(flagship_env, monkeypatch,
                                                 capsys):
    def boom(*a, **k):
        raise MemoryError("RESOURCE_EXHAUSTED: 144 MB remat temps")

    monkeypatch.setattr(bench, "_gate_flash", boom)
    monkeypatch.setattr(bench, "grad_numeric_gates", boom)
    monkeypatch.setattr(bench, "_gate_mem", boom)
    rc, row = _run_main(capsys)
    assert rc != 0
    assert row["value"] == 100.0
    for g in ("gate_flash", "gate_grad", "gate_mem"):
        assert row["extra"][g].startswith("FAILED: MemoryError")


def test_all_gates_passing_rc_zero(flagship_env, monkeypatch, capsys):
    monkeypatch.setattr(bench, "_gate_flash",
                        lambda: {"flash_max_rel_err": 1e-6})
    monkeypatch.setattr(bench, "grad_numeric_gates", lambda: {"g": 1.0})
    monkeypatch.setattr(bench, "_gate_mem", lambda: {"m": 1.0})
    rc, row = _run_main(capsys)
    assert rc == 0
    assert row["extra"]["flash_max_rel_err"] == 1e-6
    assert not [k for k in row["extra"] if k.startswith("gate_")]


def test_infer_rows_behind_env_guard(flagship_env, monkeypatch, capsys):
    """BENCH_INFER=1 folds the benchmarks/inference.py rows into extra;
    a failing row is isolated as a string like the gates."""
    monkeypatch.setattr(bench, "_gate_flash", lambda: {})
    monkeypatch.setattr(bench, "grad_numeric_gates", lambda: {})
    monkeypatch.setattr(bench, "_gate_mem", lambda: {})

    calls = []

    def fake_rows(extra):
        calls.append(True)
        extra["infer_resnet_bs16_img_s"] = 250.0
        extra["infer_capi"] = "FAILED: OSError: no libpaddle_tpu_capi"
        return ["capi"]

    monkeypatch.setattr(bench, "infer_rows", fake_rows)
    rc, row = _run_main(capsys)
    assert not calls  # guard off -> not invoked
    monkeypatch.setenv("BENCH_INFER", "1")
    rc, row = _run_main(capsys)
    assert calls
    assert rc != 0  # a failed row flips the rc like a failed gate
    assert row["extra"]["infer_resnet_bs16_img_s"] == 250.0
    assert row["extra"]["infer_capi"].startswith("FAILED:")


def test_serving_rows_behind_env_guard(flagship_env, monkeypatch, capsys):
    """BENCH_SERVING=1 folds the continuous-batching throughput row into
    extra under the serving_* keys --bench-history tracks."""
    monkeypatch.setattr(bench, "_gate_flash", lambda: {})
    monkeypatch.setattr(bench, "grad_numeric_gates", lambda: {})
    monkeypatch.setattr(bench, "_gate_mem", lambda: {})

    calls = []

    def fake_rows(extra):
        calls.append(True)
        extra["serving_tok_s"] = 1300.0
        extra["serving_speedup"] = 1.9
        return []

    monkeypatch.setattr(bench, "serving_rows", fake_rows)
    rc, row = _run_main(capsys)
    assert not calls  # guard off -> not invoked
    monkeypatch.setenv("BENCH_SERVING", "1")
    rc, row = _run_main(capsys)
    assert calls and rc == 0
    assert row["extra"]["serving_tok_s"] == 1300.0
    assert row["extra"]["serving_speedup"] == 1.9


def test_serving_rows_parses_subprocess_row(monkeypatch):
    """serving_rows extracts the tracked keys from the smoke row's last
    stdout line; a nonzero rc / error row is isolated like a gate."""
    import subprocess

    class _P:
        def __init__(self, rc, out):
            self.returncode, self.stdout, self.stderr = rc, out, ""

    good = json.dumps({"metric": "serving_tok_s", "tok_s": 1332.7,
                       "speedup": 1.92, "ttft_p50_ms": 121.0,
                       "queue_wait_p50_ms": 106.2})
    monkeypatch.setattr(subprocess, "run",
                        lambda *a, **k: _P(0, "noise\n" + good + "\n"))
    extra = {}
    assert bench.serving_rows(extra) == []
    assert extra == {"serving_tok_s": 1332.7, "serving_speedup": 1.92,
                     "serving_ttft_p50_ms": 121.0,
                     "serving_queue_wait_p50_ms": 106.2}

    bad = json.dumps({"metric": "serving_tok_s", "error": "boom"})
    monkeypatch.setattr(subprocess, "run", lambda *a, **k: _P(1, bad))
    extra = {}
    assert bench.serving_rows(extra) == ["serving_smoke"]
    assert extra["serving_smoke"].startswith("FAILED:")

    # a row that parses but has no numeric tok_s would silently END the
    # serving trajectory in --bench-history (regression flagging never
    # sees a disappeared metric) — it must fail loudly instead
    renamed = json.dumps({"metric": "serving_tok_s",
                          "tokens_per_s": 1332.7})
    monkeypatch.setattr(subprocess, "run",
                        lambda *a, **k: _P(0, renamed))
    extra = {}
    assert bench.serving_rows(extra) == ["serving_smoke"]
    assert "no numeric tok_s" in extra["serving_smoke"]

    # crash before any row printed: the rc + stderr tail must surface,
    # not an IndexError from parsing empty stdout
    class _PErr(_P):
        def __init__(self):
            super().__init__(1, "")
            self.stderr = "Traceback ...\nImportError: no jax\n"

    monkeypatch.setattr(subprocess, "run", lambda *a, **k: _PErr())
    extra = {}
    assert bench.serving_rows(extra) == ["serving_smoke"]
    assert "rc=1" in extra["serving_smoke"]
    assert "ImportError" in extra["serving_smoke"]


def test_smoke_fallback_when_no_accelerator(monkeypatch, capsys):
    """No accelerator: the CPU smoke row still prints one parseable JSON
    line (the pre-existing contract, kept)."""
    class _Cpu:
        platform = "cpu"

    monkeypatch.setattr(bench, "detect_devices", lambda: [_Cpu()])
    monkeypatch.setattr(bench, "bench_smoke", lambda: 42.0)
    rc = bench.main()
    row = json.loads(capsys.readouterr().out.strip())
    assert row["metric"] == "smoke_train_images_per_sec"
    assert row["value"] == 42.0
    assert rc == 0


# -- the repaired BENCH_r05 "always ship a row" contract (ISSUE 6) ----------

_OOM = ("RESOURCE_EXHAUSTED: Out of memory while trying to allocate\n"
        "  1. Size: 144.00M\n     Operator: op_name=\"jit(step)/pallas\"\n"
        "     Shape: bf16[6,16384,768]{2,1,0}\n")


def test_wrapped_oom_classifies_and_retries(monkeypatch):
    """An OOM raised at jit(step) compile time inside the gate/preflight
    path arrives wrapped (the Executor's op lowering re-raises as
    RuntimeError); the cause-chain walk must still classify it and fire
    the t/2 retry."""
    calls = []

    def fake_at(seq, n_chips, mesh_factory, steps, warmup, extra):
        calls.append(seq)
        if seq > 2048:
            try:
                raise MemoryError(_OOM)          # the root allocator error
            except MemoryError as root:
                raise RuntimeError(
                    "error lowering Op(flash_attention)") from root
        return 500.0, 0.2, 480.0, 520.0

    monkeypatch.setattr(bench, "_bench_gpt_at", fake_at)
    monkeypatch.setenv("BENCH_GPT_SEQ", "8192")
    extra = {}
    out = bench.bench_gpt(1, lambda *a: None, 5, 1, extra=extra)
    assert out[0] == 500.0
    assert calls == [8192, 4096, 2048]
    assert extra["gpt_seq_fallback"] == 2048
    # the gate string keeps the most recent failure (t=4096, the last
    # level that OOMed before the floor fit) and summarizes the CHAIN
    # MEMBER carrying the buffer table, not the "error lowering" wrapper
    assert extra["gate_flagship_gpt"].startswith(
        "FAILED: RESOURCE_EXHAUSTED at t=4096")
    assert "144.00M bf16[6,16384,768]" in extra["gate_flagship_gpt"]


def test_floor_oom_still_ships_row_with_gate(monkeypatch, capsys):
    """The BENCH_r05 regression: GPT OOMs at EVERY t down to the floor
    and ResNet fails too — the (smoke-fallback) row must still print,
    parseable, carrying gate_flagship_gpt and the retry trail.  Uses the
    REAL bench_gpt retry loop (only _bench_gpt_at is stubbed)."""
    calls = []

    def fake_at(seq, n_chips, mesh_factory, steps, warmup, extra):
        calls.append(seq)
        raise MemoryError(_OOM)

    def resnet_boom(*a, **k):
        raise RuntimeError("resnet also failed")

    monkeypatch.setattr(bench, "detect_devices", lambda: [_FakeDev()])
    monkeypatch.setattr(bench, "_bench_gpt_at", fake_at)
    monkeypatch.setattr(bench, "bench_resnet", resnet_boom)
    monkeypatch.setattr(bench, "bench_smoke", lambda: 33.0)
    monkeypatch.setattr(bench, "run_gates", lambda extra: [])
    monkeypatch.setenv("BENCH_MODELS", "resnet,gpt")
    monkeypatch.delenv("BENCH_SMOKE", raising=False)
    monkeypatch.delenv("BENCH_INFER", raising=False)
    monkeypatch.delenv("BENCH_SERVING", raising=False)
    monkeypatch.setenv("BENCH_GPT_SEQ", "8192")
    rc, row = _run_main(capsys)
    assert rc != 0
    assert calls == [8192, 4096, 2048]        # the retry trail ran
    assert row["value"] == 33.0               # a parseable row shipped
    assert row["extra"]["gate_flagship_gpt"].startswith(
        "FAILED: RESOURCE_EXHAUSTED at t=2048")
    assert "gpt" in row["extra"]["errors"]


def test_unexpected_exception_still_prints_row(flagship_env, monkeypatch,
                                               capsys):
    """An exception escaping the per-section isolation (the class that
    produced BENCH_r05's rc=1-with-no-row) degrades to the smoke row,
    never to a bare stack trace."""
    def boom(extra):
        raise RuntimeError("escaped the gate isolation")

    monkeypatch.setattr(bench, "run_gates", boom)
    monkeypatch.setattr(bench, "bench_smoke", lambda: 21.0)
    rc, row = _run_main(capsys)
    assert rc != 0
    assert row["value"] == 21.0
    assert "escaped the gate isolation" in \
        row["extra"]["errors"]["unexpected"]


def test_alloc_failure_cause_chain_and_spellings():
    try:
        raise MemoryError("RESOURCE_EXHAUSTED")
    except MemoryError as root:
        wrapped = RuntimeError("error lowering op")
        wrapped.__cause__ = root
    assert bench._is_alloc_failure(wrapped)
    assert bench._is_alloc_failure(
        RuntimeError("Allocation of 16.5G exceeds the memory capacity"))
    assert bench._is_alloc_failure(
        RuntimeError("Failed to allocate request for 144.0MiB"))
    assert not bench._is_alloc_failure(ValueError("shape mismatch"))
    # `raise X from None` suppresses the implicit context: a genuine
    # bug raised while an OOM was in flight must NOT classify (and be
    # silently retried) as an allocator failure
    try:
        try:
            raise MemoryError("RESOURCE_EXHAUSTED")
        except MemoryError:
            raise ValueError("real bug") from None
    except ValueError as suppressed:
        assert not bench._is_alloc_failure(suppressed)
