"""bench.py output-contract tests (ISSUE 3 satellite): the flagship JSON
line must print — parseable, non-null value — even when a numeric gate
fails; gate failures land as "gate_<name>": "FAILED: ..." strings in
extra and only flip the rc."""

import json

import numpy as np
import pytest

import bench


class _FakeDev:
    platform = "tpu"


@pytest.fixture
def flagship_env(monkeypatch):
    """Pretend an accelerator exists and both flagships produce numbers,
    without running any real benchmark."""
    monkeypatch.setattr(bench, "detect_devices", lambda: [_FakeDev()])
    monkeypatch.setattr(bench, "bench_resnet",
                        lambda *a, **k: (100.0, 90.0, 110.0))
    monkeypatch.setattr(bench, "bench_gpt",
                        lambda *a, **k: (1000.0, 0.31, 900.0, 1100.0))
    monkeypatch.setenv("BENCH_MODELS", "resnet,gpt")
    monkeypatch.delenv("BENCH_SMOKE", raising=False)
    monkeypatch.delenv("BENCH_INFER", raising=False)


def _run_main(capsys):
    rc = bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, out
    return rc, json.loads(out[0])


def test_flagship_line_survives_failing_gate(flagship_env, monkeypatch,
                                             capsys):
    """Inject a failing gate: the flagship JSON line still prints with a
    non-null value; the failure is a string in extra; rc is nonzero."""
    def boom():
        raise RuntimeError("injected gate failure")

    monkeypatch.setattr(bench, "_gate_flash", boom)
    monkeypatch.setattr(bench, "grad_numeric_gates", lambda: {"g": 1.0})
    monkeypatch.setattr(bench, "_gate_mem", lambda: {"m": 1.0})
    rc, row = _run_main(capsys)
    assert rc != 0
    assert row["metric"] == "resnet50_train_images_per_sec_per_chip"
    assert row["value"] == 100.0  # NOT zeroed out by the gate failure
    assert row["extra"]["gate_flash"].startswith("FAILED: RuntimeError")
    assert row["extra"]["g"] == 1.0  # later gates still ran
    assert row["extra"]["m"] == 1.0
    assert row["extra"]["gpt_mfu"] == 0.31


def test_every_gate_failing_still_prints_numbers(flagship_env, monkeypatch,
                                                 capsys):
    def boom(*a, **k):
        raise MemoryError("RESOURCE_EXHAUSTED: 144 MB remat temps")

    monkeypatch.setattr(bench, "_gate_flash", boom)
    monkeypatch.setattr(bench, "grad_numeric_gates", boom)
    monkeypatch.setattr(bench, "_gate_mem", boom)
    rc, row = _run_main(capsys)
    assert rc != 0
    assert row["value"] == 100.0
    for g in ("gate_flash", "gate_grad", "gate_mem"):
        assert row["extra"][g].startswith("FAILED: MemoryError")


def test_all_gates_passing_rc_zero(flagship_env, monkeypatch, capsys):
    monkeypatch.setattr(bench, "_gate_flash",
                        lambda: {"flash_max_rel_err": 1e-6})
    monkeypatch.setattr(bench, "grad_numeric_gates", lambda: {"g": 1.0})
    monkeypatch.setattr(bench, "_gate_mem", lambda: {"m": 1.0})
    rc, row = _run_main(capsys)
    assert rc == 0
    assert row["extra"]["flash_max_rel_err"] == 1e-6
    assert not [k for k in row["extra"] if k.startswith("gate_")]


def test_infer_rows_behind_env_guard(flagship_env, monkeypatch, capsys):
    """BENCH_INFER=1 folds the benchmarks/inference.py rows into extra;
    a failing row is isolated as a string like the gates."""
    monkeypatch.setattr(bench, "_gate_flash", lambda: {})
    monkeypatch.setattr(bench, "grad_numeric_gates", lambda: {})
    monkeypatch.setattr(bench, "_gate_mem", lambda: {})

    calls = []

    def fake_rows(extra):
        calls.append(True)
        extra["infer_resnet_bs16_img_s"] = 250.0
        extra["infer_capi"] = "FAILED: OSError: no libpaddle_tpu_capi"
        return ["capi"]

    monkeypatch.setattr(bench, "infer_rows", fake_rows)
    rc, row = _run_main(capsys)
    assert not calls  # guard off -> not invoked
    monkeypatch.setenv("BENCH_INFER", "1")
    rc, row = _run_main(capsys)
    assert calls
    assert rc != 0  # a failed row flips the rc like a failed gate
    assert row["extra"]["infer_resnet_bs16_img_s"] == 250.0
    assert row["extra"]["infer_capi"].startswith("FAILED:")


def test_smoke_fallback_when_no_accelerator(monkeypatch, capsys):
    """No accelerator: the CPU smoke row still prints one parseable JSON
    line (the pre-existing contract, kept)."""
    class _Cpu:
        platform = "cpu"

    monkeypatch.setattr(bench, "detect_devices", lambda: [_Cpu()])
    monkeypatch.setattr(bench, "bench_smoke", lambda: 42.0)
    rc = bench.main()
    row = json.loads(capsys.readouterr().out.strip())
    assert row["metric"] == "smoke_train_images_per_sec"
    assert row["value"] == 42.0
    assert rc == 0
