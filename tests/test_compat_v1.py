"""v1 trainer_config_helpers name-compat shim (paddle_tpu/compat/v1.py;
reference: python/paddle/trainer_config_helpers/layers.py).  A v1-style
config should build a Program and train."""

import numpy as np

import paddle_tpu as pt
from paddle_tpu.compat import v1

from test_book import train_steps


def test_v1_smallnet_config_trains():
    """The reference benchmark/paddle/image/smallnet_mnist_cifar.py shape,
    written with v1 names."""
    net = v1.data_layer("data", size=3 * 32 * 32, height=32, width=32)
    label = v1.data_layer("label", size=1, is_label=True)
    net = v1.img_conv_layer(input=net, filter_size=5, num_filters=32,
                            stride=1, padding=2, act=v1.ReluActivation())
    net = v1.img_pool_layer(input=net, pool_size=3, stride=2, padding=1)
    net = v1.img_conv_layer(input=net, filter_size=5, num_filters=32,
                            stride=1, padding=2, act=v1.ReluActivation())
    net = v1.img_pool_layer(input=net, pool_size=3, stride=2, padding=1,
                            pool_type=v1.AvgPooling())
    net = v1.fc_layer(input=net, size=64, act=v1.ReluActivation())
    out = v1.fc_layer(input=net, size=10, act=v1.SoftmaxActivation())
    cost = v1.classification_cost(input=out, label=label)
    opt = v1.settings(batch_size=8, learning_rate=0.002,
                      learning_method=v1.MomentumOptimizer(0.9),
                      regularization=v1.L2Regularization(1e-4))
    opt.minimize(cost)

    rng = np.random.default_rng(0)
    img = rng.normal(size=(8, 3, 32, 32)).astype(np.float32)
    lbl = rng.integers(0, 10, (8, 1)).astype(np.int64)
    train_steps({"avg_cost": cost}, {"data": img, "label": lbl}, steps=5)


def test_v1_lstm_text_config_trains():
    """The benchmark/paddle/rnn/rnn.py shape with v1 names: embedding ->
    simple_lstm -> seq pooling -> fc."""
    words = v1.data_layer("words", size=50, dtype="int64", seq_len=12)
    label = v1.data_layer("label", size=1, is_label=True)
    emb = v1.embedding_layer(input=words, size=16)
    lstm = v1.simple_lstm(input=emb, size=16)
    pooled = v1.pooling_layer(input=lstm, pooling_type=v1.MaxPooling())
    out = v1.fc_layer(input=pooled, size=2, act=v1.SoftmaxActivation())
    cost = v1.classification_cost(input=out, label=label)
    v1.settings(learning_rate=0.05,
                learning_method=v1.AdamOptimizer()).minimize(cost)

    rng = np.random.default_rng(1)
    data = rng.integers(0, 50, (4, 12)).astype(np.int64)
    lens = np.full((4,), 12, np.int32)
    lbl = rng.integers(0, 2, (4, 1)).astype(np.int64)
    train_steps({"avg_cost": cost},
                {"words": data, "words@LENGTH": lens, "label": lbl},
                steps=5)


def test_v1_misc_layers():
    a = v1.data_layer("a", size=8)
    b = v1.data_layer("b", size=8)
    s = v1.addto_layer([a, b], act=v1.TanhActivation())
    c = v1.concat_layer([a, b])
    sim = v1.cos_sim(a, b)
    scaled = v1.slope_intercept_layer(a, slope=2.0, intercept=1.0)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    av = np.ones((2, 8), np.float32)
    bv = np.full((2, 8), 2.0, np.float32)
    sv, cv, simv, scv = exe.run(feed={"a": av, "b": bv},
                                fetch_list=[s, c, sim, scaled])
    assert np.allclose(sv, np.tanh(3.0))
    assert cv.shape == (2, 16)
    assert np.allclose(simv, 1.0, atol=1e-5)
    assert np.allclose(scv, 3.0)


def test_v1_inputs_outputs_bookkeeping():
    a = v1.data_layer("a", size=4)
    out = v1.fc_layer(input=a, size=2, act=v1.SoftmaxActivation())
    assert v1.inputs(a) == [a]
    assert v1.outputs(out) == [out]
