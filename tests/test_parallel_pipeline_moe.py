"""Pipeline (pp) and expert (ep) parallelism tests on the 8-device CPU mesh.

Reference has neither (SURVEY §2.3 "TP/PP/CP/EP: ABSENT"); these validate
the new first-class capabilities: GPipe microbatch pipeline == sequential
stage application (fwd and grad), MoE all_to_all dispatch == the dense
per-token expert compute it approximates.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.pipeline import pipeline, stack_stage_params
from paddle_tpu.parallel.moe import init_moe_params, moe_ffn


def _stage_fn(params, h):
    return jnp.tanh(h @ params["w"] + params["b"])


def _make_stages(n, d, seed=0):
    rng = np.random.RandomState(seed)
    return [
        {"w": jnp.asarray(rng.randn(d, d).astype(np.float32) * 0.3),
         "b": jnp.asarray(rng.randn(d).astype(np.float32) * 0.1)}
        for _ in range(n)
    ]


class TestPipeline:
    def test_matches_sequential(self):
        pp, d, batch = 4, 16, 8
        mesh = make_mesh({"pp": pp}, devices=jax.devices()[:pp])
        stages = _make_stages(pp, d)
        x = jnp.asarray(np.random.RandomState(1).randn(batch, d),
                        jnp.float32)

        want = x
        for p in stages:
            want = _stage_fn(p, want)

        got = pipeline(_stage_fn, stack_stage_params(stages), x, mesh,
                       num_microbatches=4)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_microbatch_count_irrelevant(self):
        pp, d, batch = 2, 8, 12
        mesh = make_mesh({"pp": pp}, devices=jax.devices()[:pp])
        stages = _make_stages(pp, d, seed=3)
        sp = stack_stage_params(stages)
        x = jnp.asarray(np.random.RandomState(2).randn(batch, d), jnp.float32)
        o2 = pipeline(_stage_fn, sp, x, mesh, num_microbatches=2)
        o6 = pipeline(_stage_fn, sp, x, mesh, num_microbatches=6)
        np.testing.assert_allclose(np.asarray(o2), np.asarray(o6),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_matches_sequential(self):
        pp, d, batch = 4, 8, 8
        mesh = make_mesh({"pp": pp}, devices=jax.devices()[:pp])
        stages = _make_stages(pp, d, seed=5)
        sp = stack_stage_params(stages)
        x = jnp.asarray(np.random.RandomState(4).randn(batch, d), jnp.float32)

        def loss_pipe(sp):
            return jnp.sum(pipeline(_stage_fn, sp, x, mesh,
                                    num_microbatches=4) ** 2)

        def loss_seq(sp):
            h = x
            for i in range(pp):
                h = _stage_fn(jax.tree.map(lambda l: l[i], sp), h)
            return jnp.sum(h ** 2)

        g_pipe = jax.grad(loss_pipe)(sp)
        g_seq = jax.grad(loss_seq)(sp)
        for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_seq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_under_jit(self):
        pp, d, batch = 4, 8, 8
        mesh = make_mesh({"pp": pp}, devices=jax.devices()[:pp])
        sp = stack_stage_params(_make_stages(pp, d))
        x = jnp.ones((batch, d), jnp.float32)
        f = jax.jit(lambda sp, x: pipeline(_stage_fn, sp, x, mesh))
        out = f(sp, x)
        assert out.shape == (batch, d)
        assert np.isfinite(np.asarray(out)).all()


class TestMoE:
    def _dense_reference(self, params, x, capacity):
        """Per-token top-2 expert compute with the same capacity rule,
        computed densely without any collective."""
        from paddle_tpu.parallel.moe import _top2_dispatch
        logits = x @ params["gate"]
        dispatch, combine, _ = _top2_dispatch(logits, capacity)
        expert_in = jnp.einsum("nec,nd->ecd", dispatch, x)
        h = jax.nn.relu(jnp.einsum("end,edf->enf", expert_in, params["w1"])
                        + params["b1"][:, None, :])
        y = jnp.einsum("enf,efd->end", h, params["w2"]) + params["b2"][:, None, :]
        return jnp.einsum("nec,ecd->nd", combine, y)

    def test_matches_dense_single_shard(self):
        # ep=1: the all_to_all is identity, so sharded == dense exactly.
        mesh = make_mesh({"ep": 1}, devices=jax.devices()[:1])
        d, f, e, n = 8, 16, 4, 32
        params = init_moe_params(jax.random.PRNGKey(0), e, d, f)
        x = jnp.asarray(np.random.RandomState(0).randn(n, d), jnp.float32)
        y, aux = moe_ffn(params, x, mesh, capacity_factor=2.0)
        cap = int(2.0 * n / e)
        want = self._dense_reference(params, x, cap)
        np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)
        assert float(aux) > 0

    def test_multi_shard_finite_and_shaped(self):
        ep = 4
        mesh = make_mesh({"ep": ep}, devices=jax.devices()[:ep])
        d, f, e, n = 8, 16, 8, 64
        params = init_moe_params(jax.random.PRNGKey(1), e, d, f)
        x = jnp.asarray(np.random.RandomState(1).randn(n, d), jnp.float32)
        y, aux = moe_ffn(params, x, mesh, capacity_factor=2.0)
        assert y.shape == (n, d)
        assert np.isfinite(np.asarray(y)).all()
        # aux loss ~ O(1): perfectly balanced routing gives exactly 1.0
        assert 0.5 < float(aux) < 8.0

    def test_multi_shard_matches_dense(self):
        """ep=4, e=8 (e_local=2): with capacity high enough that no token
        drops, the all_to_all path must equal per-shard dense expert
        compute — guards the shard/expert axis ordering in the dispatch
        reshape."""
        ep = 4
        mesh = make_mesh({"ep": ep}, devices=jax.devices()[:ep])
        d, f, e, n = 8, 16, 8, 32
        params = init_moe_params(jax.random.PRNGKey(4), e, d, f)
        x = jnp.asarray(np.random.RandomState(5).randn(n, d), jnp.float32)
        cf = float(2 * e)  # local cap = cf*n_local/e = 2*n_local: no drops
        y, _ = moe_ffn(params, x, mesh, capacity_factor=cf)
        # dense reference shard by shard (capacity applies per token shard)
        n_local = n // ep
        cap = int(cf * n_local / e)
        wants = [
            self._dense_reference(
                params, x[i * n_local:(i + 1) * n_local], cap)
            for i in range(ep)
        ]
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(jnp.concatenate(wants)),
            rtol=1e-4, atol=1e-4)

    def test_high_capacity_token_conservation(self):
        """With capacity >= n every token is routed; combine weights sum
        to 1 so output magnitude is expert-mixture, not dropped."""
        ep = 2
        mesh = make_mesh({"ep": ep}, devices=jax.devices()[:ep])
        d, f, e, n = 4, 8, 2, 16
        params = init_moe_params(jax.random.PRNGKey(2), e, d, f)
        x = jnp.asarray(np.random.RandomState(2).randn(n, d), jnp.float32)
        y_lo, _ = moe_ffn(params, x, mesh, capacity_factor=8.0)
        y_hi, _ = moe_ffn(params, x, mesh, capacity_factor=16.0)
        # once nothing overflows, more capacity changes nothing
        np.testing.assert_allclose(np.asarray(y_lo), np.asarray(y_hi),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_flows(self):
        ep = 2
        mesh = make_mesh({"ep": ep}, devices=jax.devices()[:ep])
        d, f, e, n = 4, 8, 4, 16
        params = init_moe_params(jax.random.PRNGKey(3), e, d, f)
        x = jnp.asarray(np.random.RandomState(3).randn(n, d), jnp.float32)

        def loss(params):
            y, aux = moe_ffn(params, x, mesh, capacity_factor=4.0)
            return jnp.sum(y ** 2) + 0.01 * aux

        g = jax.grad(loss)(params)
        flat = jax.tree.leaves(g)
        assert all(np.isfinite(np.asarray(l)).all() for l in flat)
        assert any(float(jnp.abs(l).sum()) > 0 for l in flat)
