"""Pipeline (pp) and expert (ep) parallelism tests on the 8-device CPU mesh.

Reference has neither (SURVEY §2.3 "TP/PP/CP/EP: ABSENT"); these validate
the new first-class capabilities: GPipe microbatch pipeline == sequential
stage application (fwd and grad), MoE all_to_all dispatch == the dense
per-token expert compute it approximates.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.pipeline import pipeline, stack_stage_params
from paddle_tpu.parallel.moe import init_moe_params, moe_ffn


def _stage_fn(params, h):
    return jnp.tanh(h @ params["w"] + params["b"])


def _make_stages(n, d, seed=0):
    rng = np.random.RandomState(seed)
    return [
        {"w": jnp.asarray(rng.randn(d, d).astype(np.float32) * 0.3),
         "b": jnp.asarray(rng.randn(d).astype(np.float32) * 0.1)}
        for _ in range(n)
    ]


class TestPipeline:
    def test_matches_sequential(self):
        pp, d, batch = 4, 16, 8
        mesh = make_mesh({"pp": pp}, devices=jax.devices()[:pp])
        stages = _make_stages(pp, d)
        x = jnp.asarray(np.random.RandomState(1).randn(batch, d),
                        jnp.float32)

        want = x
        for p in stages:
            want = _stage_fn(p, want)

        got = pipeline(_stage_fn, stack_stage_params(stages), x, mesh,
                       num_microbatches=4)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_microbatch_count_irrelevant(self):
        pp, d, batch = 2, 8, 12
        mesh = make_mesh({"pp": pp}, devices=jax.devices()[:pp])
        stages = _make_stages(pp, d, seed=3)
        sp = stack_stage_params(stages)
        x = jnp.asarray(np.random.RandomState(2).randn(batch, d), jnp.float32)
        o2 = pipeline(_stage_fn, sp, x, mesh, num_microbatches=2)
        o6 = pipeline(_stage_fn, sp, x, mesh, num_microbatches=6)
        np.testing.assert_allclose(np.asarray(o2), np.asarray(o6),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_matches_sequential(self):
        pp, d, batch = 4, 8, 8
        mesh = make_mesh({"pp": pp}, devices=jax.devices()[:pp])
        stages = _make_stages(pp, d, seed=5)
        sp = stack_stage_params(stages)
        x = jnp.asarray(np.random.RandomState(4).randn(batch, d), jnp.float32)

        def loss_pipe(sp):
            return jnp.sum(pipeline(_stage_fn, sp, x, mesh,
                                    num_microbatches=4) ** 2)

        def loss_seq(sp):
            h = x
            for i in range(pp):
                h = _stage_fn(jax.tree.map(lambda l: l[i], sp), h)
            return jnp.sum(h ** 2)

        g_pipe = jax.grad(loss_pipe)(sp)
        g_seq = jax.grad(loss_seq)(sp)
        for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_seq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_under_jit(self):
        pp, d, batch = 4, 8, 8
        mesh = make_mesh({"pp": pp}, devices=jax.devices()[:pp])
        sp = stack_stage_params(_make_stages(pp, d))
        x = jnp.ones((batch, d), jnp.float32)
        f = jax.jit(lambda sp, x: pipeline(_stage_fn, sp, x, mesh))
        out = f(sp, x)
        assert out.shape == (batch, d)
        assert np.isfinite(np.asarray(out)).all()


class TestMoE:
    def _dense_reference(self, params, x, capacity):
        """Per-token top-2 expert compute with the same capacity rule,
        computed densely without any collective."""
        from paddle_tpu.parallel.moe import _top2_dispatch
        logits = x @ params["gate"]
        dispatch, combine, _ = _top2_dispatch(logits, capacity)
        expert_in = jnp.einsum("nec,nd->ecd", dispatch, x)
        h = jax.nn.relu(jnp.einsum("end,edf->enf", expert_in, params["w1"])
                        + params["b1"][:, None, :])
        y = jnp.einsum("enf,efd->end", h, params["w2"]) + params["b2"][:, None, :]
        return jnp.einsum("nec,ecd->nd", combine, y)

    def test_matches_dense_single_shard(self):
        # ep=1: the all_to_all is identity, so sharded == dense exactly.
        mesh = make_mesh({"ep": 1}, devices=jax.devices()[:1])
        d, f, e, n = 8, 16, 4, 32
        params = init_moe_params(jax.random.PRNGKey(0), e, d, f)
        x = jnp.asarray(np.random.RandomState(0).randn(n, d), jnp.float32)
        y, aux = moe_ffn(params, x, mesh, capacity_factor=2.0)
        cap = int(2.0 * n / e)
        want = self._dense_reference(params, x, cap)
        np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)
        assert float(aux) > 0

    def test_multi_shard_finite_and_shaped(self):
        ep = 4
        mesh = make_mesh({"ep": ep}, devices=jax.devices()[:ep])
        d, f, e, n = 8, 16, 8, 64
        params = init_moe_params(jax.random.PRNGKey(1), e, d, f)
        x = jnp.asarray(np.random.RandomState(1).randn(n, d), jnp.float32)
        y, aux = moe_ffn(params, x, mesh, capacity_factor=2.0)
        assert y.shape == (n, d)
        assert np.isfinite(np.asarray(y)).all()
        # aux loss ~ O(1): perfectly balanced routing gives exactly 1.0
        assert 0.5 < float(aux) < 8.0

    def test_multi_shard_matches_dense(self):
        """ep=4, e=8 (e_local=2): with capacity high enough that no token
        drops, the all_to_all path must equal per-shard dense expert
        compute — guards the shard/expert axis ordering in the dispatch
        reshape."""
        ep = 4
        mesh = make_mesh({"ep": ep}, devices=jax.devices()[:ep])
        d, f, e, n = 8, 16, 8, 32
        params = init_moe_params(jax.random.PRNGKey(4), e, d, f)
        x = jnp.asarray(np.random.RandomState(5).randn(n, d), jnp.float32)
        cf = float(2 * e)  # local cap = cf*n_local/e = 2*n_local: no drops
        y, _ = moe_ffn(params, x, mesh, capacity_factor=cf)
        # dense reference shard by shard (capacity applies per token shard)
        n_local = n // ep
        cap = int(cf * n_local / e)
        wants = [
            self._dense_reference(
                params, x[i * n_local:(i + 1) * n_local], cap)
            for i in range(ep)
        ]
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(jnp.concatenate(wants)),
            rtol=1e-4, atol=1e-4)

    def test_high_capacity_token_conservation(self):
        """With capacity >= n every token is routed; combine weights sum
        to 1 so output magnitude is expert-mixture, not dropped."""
        ep = 2
        mesh = make_mesh({"ep": ep}, devices=jax.devices()[:ep])
        d, f, e, n = 4, 8, 2, 16
        params = init_moe_params(jax.random.PRNGKey(2), e, d, f)
        x = jnp.asarray(np.random.RandomState(2).randn(n, d), jnp.float32)
        y_lo, _ = moe_ffn(params, x, mesh, capacity_factor=8.0)
        y_hi, _ = moe_ffn(params, x, mesh, capacity_factor=16.0)
        # once nothing overflows, more capacity changes nothing
        np.testing.assert_allclose(np.asarray(y_lo), np.asarray(y_hi),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_flows(self):
        ep = 2
        mesh = make_mesh({"ep": ep}, devices=jax.devices()[:ep])
        d, f, e, n = 4, 8, 4, 16
        params = init_moe_params(jax.random.PRNGKey(3), e, d, f)
        x = jnp.asarray(np.random.RandomState(3).randn(n, d), jnp.float32)

        def loss(params):
            y, aux = moe_ffn(params, x, mesh, capacity_factor=4.0)
            return jnp.sum(y ** 2) + 0.01 * aux

        g = jax.grad(loss)(params)
        flat = jax.tree.leaves(g)
        assert all(np.isfinite(np.asarray(l)).all() for l in flat)
        assert any(float(jnp.abs(l).sum()) > 0 for l in flat)


# ---- round 2: interleaved schedule + in-pipeline embed/head -------------

def _mlp_stage_r2(params, h):
    return h + jnp.tanh(h @ params["w"] + params["b"])


def _make_stages_r2(n, d, key=0):
    ks = jax.random.split(jax.random.PRNGKey(key), n)
    return [{"w": jax.random.normal(k, (d, d)) * 0.3,
             "b": jnp.full((d,), 0.01)} for k in ks]


@pytest.mark.parametrize("m", [4, 8])
def test_pipeline_interleaved_matches_sequential(m):
    """virtual_stages=2: 8 stages on a 4-device pp ring, every microbatch
    making 2 laps; output must equal the sequential 8-stage composition,
    and grads must match too."""
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.pipeline import pipeline, stack_stage_params

    pp, v, d, b = 4, 2, 8, 2 * m
    mesh = make_mesh({"pp": pp}, devices=jax.devices()[:pp])
    stages = _make_stages_r2(v * pp, d)
    sp = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(9), (b, d))

    def seq(sp, x):
        h = x
        for s in range(v * pp):
            h = _mlp_stage_r2(jax.tree.map(lambda p: p[s], sp), h)
        return h

    got = jax.jit(lambda sp, x: pipeline(
        _mlp_stage_r2, sp, x, mesh, num_microbatches=m, virtual_stages=v))(
            sp, x)
    want = seq(sp, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    def loss_pipe(sp):
        out = pipeline(_mlp_stage_r2, sp, x, mesh, num_microbatches=m,
                       virtual_stages=v)
        return jnp.mean(out ** 2)

    def loss_seq(sp):
        return jnp.mean(seq(sp, x) ** 2)

    g1 = jax.jit(jax.grad(loss_pipe))(sp)
    g2 = jax.grad(loss_seq)(sp)
    for a, e in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=2e-4, atol=2e-5)


def test_pipeline_interleaved_needs_enough_microbatches():
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.pipeline import pipeline, stack_stage_params

    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    sp = stack_stage_params(_make_stages_r2(8, 4))
    x = jnp.zeros((4, 4))
    with pytest.raises(ValueError, match="num_microbatches >= pp"):
        pipeline(_mlp_stage_r2, sp, x, mesh, num_microbatches=2,
                 virtual_stages=2)


def test_pipeline_lm_embed_and_head_inside():
    """Unequal first/last layers INSIDE the pipelined region: token
    embedding on stage 0, loss head on the final stage; loss and all
    grads (embed, blocks, head) match the sequential model."""
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.pipeline import pipeline_lm, stack_stage_params

    pp, d, vocab, tlen, m = 4, 8, 12, 5, 4
    b = 2 * m
    mesh = make_mesh({"pp": pp}, devices=jax.devices()[:pp])
    stages = _make_stages_r2(pp, d, key=3)
    sp = stack_stage_params(stages)
    emb = {"table": jax.random.normal(jax.random.PRNGKey(4), (vocab, d)) * 0.2}
    head = {"w": jax.random.normal(jax.random.PRNGKey(5), (d, vocab)) * 0.2}
    tok = jax.random.randint(jax.random.PRNGKey(6), (b, tlen), 0, vocab)
    tgt = jax.random.randint(jax.random.PRNGKey(7), (b, tlen), 0, vocab)

    def embed_fn(p, tok):
        return p["table"][tok]

    def head_loss_fn(p, h, tgt):
        logits = h @ p["w"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(
            jnp.take_along_axis(logp, tgt[..., None], axis=-1))

    def seq_loss(emb, sp, head):
        h = embed_fn(emb, tok.reshape(m, b // m, tlen))
        # sequential over microbatches to mirror per-microbatch mean
        losses = []
        for j in range(m):
            hj = h[j]
            for s in range(pp):
                hj = _mlp_stage_r2(jax.tree.map(lambda p: p[s], sp), hj)
            losses.append(head_loss_fn(
                head, hj, tgt.reshape(m, b // m, tlen)[j]))
        return jnp.mean(jnp.stack(losses))

    def pipe_loss(emb, sp, head):
        return pipeline_lm(embed_fn, _mlp_stage_r2, head_loss_fn,
                           emb, sp, head, tok, tgt, mesh,
                           num_microbatches=m)

    lp = jax.jit(pipe_loss)(emb, sp, head)
    ls = seq_loss(emb, sp, head)
    np.testing.assert_allclose(float(lp), float(ls), rtol=2e-5)

    gp = jax.jit(jax.grad(pipe_loss, argnums=(0, 1, 2)))(emb, sp, head)
    gs = jax.grad(seq_loss, argnums=(0, 1, 2))(emb, sp, head)
    for a, e in zip(jax.tree.leaves(gp), jax.tree.leaves(gs)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=2e-4, atol=2e-5)


def test_pipeline_lm_composes_with_dp():
    """pp=2 x dp=2: pipeline_lm over a 2-axis mesh with the batch sharded
    over dp; loss equals the pp-only value on the same data."""
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.pipeline import pipeline_lm, stack_stage_params

    pp, d, vocab, tlen, m = 2, 4, 6, 3, 2
    b = 4
    stages = _make_stages_r2(pp, d, key=8)
    sp = stack_stage_params(stages)
    emb = {"table": jax.random.normal(jax.random.PRNGKey(1), (vocab, d))}
    head = {"w": jax.random.normal(jax.random.PRNGKey(2), (d, vocab))}
    tok = jax.random.randint(jax.random.PRNGKey(3), (b, tlen), 0, vocab)
    tgt = jax.random.randint(jax.random.PRNGKey(4), (b, tlen), 0, vocab)

    def embed_fn(p, tok):
        return p["table"][tok]

    def head_loss_fn(p, h, tgt):
        logits = h @ p["w"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(
            jnp.take_along_axis(logp, tgt[..., None], axis=-1))

    mesh_pp = make_mesh({"pp": pp}, devices=jax.devices()[:pp])
    l_ref = pipeline_lm(embed_fn, _mlp_stage_r2, head_loss_fn, emb, sp, head,
                        tok, tgt, mesh_pp, num_microbatches=m)
    mesh2 = make_mesh({"pp": pp, "dp": 2}, devices=jax.devices()[:4])
    l_dp = pipeline_lm(embed_fn, _mlp_stage_r2, head_loss_fn, emb, sp, head,
                       tok, tgt, mesh2, num_microbatches=m,
                       batch_axis="dp")
    np.testing.assert_allclose(float(l_dp), float(l_ref), rtol=2e-5)


def test_pipeline_lm_interleaved():
    """pipeline_lm with virtual_stages=2 (shared schedule machinery):
    loss matches the sequential 2*pp-stage model."""
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.pipeline import pipeline_lm, stack_stage_params

    pp, v, d, vocab, tlen, m = 2, 2, 4, 6, 3, 4
    b = 2 * m
    mesh = make_mesh({"pp": pp}, devices=jax.devices()[:pp])
    stages = _make_stages_r2(v * pp, d, key=13)
    sp = stack_stage_params(stages)
    emb = {"table": jax.random.normal(jax.random.PRNGKey(1), (vocab, d))}
    head = {"w": jax.random.normal(jax.random.PRNGKey(2), (d, vocab))}
    tok = jax.random.randint(jax.random.PRNGKey(3), (b, tlen), 0, vocab)
    tgt = jax.random.randint(jax.random.PRNGKey(4), (b, tlen), 0, vocab)

    def embed_fn(p, tok):
        return p["table"][tok]

    def head_loss_fn(p, h, tgt):
        logp = jax.nn.log_softmax(h @ p["w"])
        return -jnp.mean(jnp.take_along_axis(logp, tgt[..., None], axis=-1))

    lp = pipeline_lm(embed_fn, _mlp_stage_r2, head_loss_fn, emb, sp, head,
                     tok, tgt, mesh, num_microbatches=m, virtual_stages=v)

    # interleaved placement: stage s = r*pp + d executes in order
    # lap 0 (stages 0..pp-1), then lap 1 (stages pp..2pp-1)
    losses = []
    tok_m = tok.reshape(m, b // m, tlen)
    tgt_m = tgt.reshape(m, b // m, tlen)
    for j in range(m):
        h = embed_fn(emb, tok_m[j])
        for s in range(v * pp):
            h = _mlp_stage_r2(jax.tree.map(lambda p: p[s], sp), h)
        losses.append(head_loss_fn(head, h, tgt_m[j]))
    np.testing.assert_allclose(float(lp), float(jnp.mean(jnp.stack(losses))),
                               rtol=2e-5)


def test_ring_attention_flash_impl_matches_dense():
    """ring_attention(impl='flash'): the Pallas inner-block path must match
    the dense-impl ring AND the global reference, values and grads, causal
    and not (8-device sp mesh, interpret-mode kernels on CPU)."""
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.ring_attention import ring_attention
    from paddle_tpu.ops.pallas_attention import attention_reference

    sp = 8
    mesh = make_mesh({"sp": sp}, devices=jax.devices()[:sp])
    b, t, h, d = 2, 8 * 16, 2, 8
    rng_ = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rng_.randn(b, t, h, d) * 0.5, jnp.float32)
               for _ in range(3))

    for causal in (False, True):
        o_flash = ring_attention(q, k, v, mesh, causal=causal,
                                 impl="flash", block_q=16, block_k=16)
        o_ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(o_flash), np.asarray(o_ref),
                                   rtol=2e-4, atol=2e-4)
        # bf16 inputs (the TPU configuration) must also run
        o_bf = ring_attention(q.astype(jnp.bfloat16),
                              k.astype(jnp.bfloat16),
                              v.astype(jnp.bfloat16), mesh, causal=causal,
                              impl="flash", block_q=16, block_k=16)
        np.testing.assert_allclose(
            np.asarray(o_bf.astype(jnp.float32)), np.asarray(o_ref),
            rtol=5e-2, atol=5e-2)
        with pytest.raises(ValueError, match="impl"):
            ring_attention(q, k, v, mesh, impl="falsh")

        def loss(fn):
            return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

        ga = jax.grad(loss(lambda q, k, v: ring_attention(
            q, k, v, mesh, causal=causal, impl="flash", block_q=16,
            block_k=16)), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss(lambda q, k, v: attention_reference(
            q, k, v, causal=causal)), argnums=(0, 1, 2))(q, k, v)
        for a, r in zip(ga, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=2e-3, atol=2e-4)


def test_pipeline_composes_with_ring_attention_pp_sp():
    """pp x sp composition (round-3 dryrun axis): attention stages
    pipelined over pp=2 while each stage rings the sequence over sp=4,
    vs the same stages applied sequentially with dense attention on one
    logical device.  Fwd values and grads must match."""
    from paddle_tpu.parallel.ring_attention import ring_attention_local
    from paddle_tpu.ops.pallas_attention import attention_reference

    pp, sp = 2, 4
    mesh = make_mesh({"pp": pp, "sp": sp})
    b, t, heads, dh = 2, 16, 2, 4
    d = heads * dh

    def stage_fn(params, h):
        mb, tl, _ = h.shape
        qkv = h @ params["w_qkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shp = (mb, tl, heads, dh)
        o = ring_attention_local(q.reshape(shp), k.reshape(shp),
                                 v.reshape(shp), sp, axis_name="sp",
                                 causal=True)
        return h + o.reshape(mb, tl, d) @ params["w_o"]

    def stage_ref(params, h):
        mb, tl, _ = h.shape
        qkv = h @ params["w_qkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shp = (mb, tl, heads, dh)
        o = attention_reference(q.reshape(shp), k.reshape(shp),
                                v.reshape(shp), causal=True)
        return h + o.reshape(mb, tl, d) @ params["w_o"]

    keys = jax.random.split(jax.random.PRNGKey(0), pp)
    stages = [{"w_qkv": jax.random.normal(k, (d, 3 * d)) * 0.1,
               "w_o": jax.random.normal(k, (d, d)) * 0.1} for k in keys]
    sp_params = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (b, t, d))
    y = jax.random.normal(jax.random.PRNGKey(2), (b, t, d))

    def loss_pp(params):
        out = pipeline(stage_fn, params, x, mesh,
                       num_microbatches=2, wire_spec=("sp", None))
        return jnp.mean((out - y) ** 2)

    def loss_ref(params):
        h = x
        for i in range(pp):
            h = stage_ref(jax.tree.map(lambda p: p[i], params), h)
        return jnp.mean((h - y) ** 2)

    l1, g1 = jax.value_and_grad(loss_pp)(sp_params)
    l2, g2 = jax.value_and_grad(loss_ref)(sp_params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    for a, r in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=1e-4, atol=1e-6)
