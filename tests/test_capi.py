"""C inference API test: export a model, compile the example C program
against libpaddle_tpu_capi.so, run it as a real external process, and check
the numbers (the reference's capi/examples pattern as a test)."""

import os
import subprocess
import sys
import sysconfig

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.native import build as nbuild

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def capi_lib():
    try:
        return nbuild.build_capi()
    except RuntimeError as e:
        pytest.skip(f"capi unavailable: {e}")


def test_capi_end_to_end(tmp_path, capi_lib):
    # 1) export a deterministic linear model with TWO fetch targets:
    #    y = x @ W (W = const 0.5) and z = 2*y (multi-output fetch)
    x = layers.data("x", shape=[4])
    pred = layers.fc(input=x, size=2, bias_attr=False,
                     param_attr=pt.initializer.Constant(0.5))
    doubled = layers.scale(pred, scale=2.0)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    model_dir = tmp_path / "model"
    pt.io.save_inference_model(str(model_dir), ["x"], [pred, doubled], exe)

    # 2) compile the example C program
    exe_path = tmp_path / "infer"
    include = os.path.join(REPO, "paddle_tpu", "native", "include")
    src = os.path.join(REPO, "paddle_tpu", "native", "examples", "infer.c")
    libdir = os.path.dirname(capi_lib)
    cc = os.environ.get("CC", "gcc")
    subprocess.run(
        [cc, "-O2", src, f"-I{include}", f"-L{libdir}",
         "-lpaddle_tpu_capi", "-o", str(exe_path),
         f"-Wl,-rpath,{libdir}"],
        check=True, capture_output=True, text=True,
    )

    # 3) run it as an external process
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PADDLE_TPU_PLATFORM"] = "cpu"
    env["LD_LIBRARY_PATH"] = (
        libdir + ":" + sysconfig.get_config_var("LIBDIR")
        + ":" + env.get("LD_LIBRARY_PATH", "")
    )
    vals = ["1", "2", "3", "4", "5", "6", "7", "8"]
    r = subprocess.run(
        [str(exe_path), REPO, str(model_dir), "x", "2", "4", *vals],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    # introspection lines on stderr: feed surface + both fetch targets
    assert "input 0: x rank=2" in r.stderr, r.stderr
    assert "output 0:" in r.stderr and "output 1:" in r.stderr, r.stderr
    # stdout: "<output_index> <value>" per element, both outputs
    rows = [line.split() for line in r.stdout.strip().splitlines()]
    out0 = np.array([float(v) for i, v in rows if i == "0"]).reshape(2, 2)
    out1 = np.array([float(v) for i, v in rows if i == "1"]).reshape(2, 2)
    want = np.array([[1, 2, 3, 4], [5, 6, 7, 8]], np.float32) @ np.full(
        (4, 2), 0.5, np.float32
    )
    np.testing.assert_allclose(out0, want, rtol=1e-5)
    np.testing.assert_allclose(out1, 2.0 * want, rtol=1e-5)


def test_capi_int_sequence_inputs(tmp_path, capi_lib):
    """Serve an NLP (word-id) model through the C API: int64 ids in via
    pt_engine_run_all_typed (the reference paddle_ivector path,
    capi/vector.h), float32 class distribution out, checked against the
    in-process InferenceEngine on the same ids."""
    vocab, emb, t = 20, 8, 5
    toks = layers.data("tokens", shape=[t], dtype="int64")
    e = layers.embedding(toks, size=[vocab, emb])
    pooled = layers.reduce_mean(e, dim=1)
    pred = layers.fc(input=pooled, size=3, act="softmax")
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    model_dir = tmp_path / "seqmodel"
    pt.io.save_inference_model(str(model_dir), ["tokens"], [pred], exe)

    # reference output from the python engine
    ids = np.asarray([[3, 7, 1, 19, 0]], np.int64)
    from paddle_tpu.inference import InferenceEngine

    ref = InferenceEngine(str(model_dir)).run({"tokens": ids})[0]

    exe_path = tmp_path / "infer_seq"
    include = os.path.join(REPO, "paddle_tpu", "native", "include")
    src = os.path.join(REPO, "paddle_tpu", "native", "examples",
                       "infer_seq.c")
    libdir = os.path.dirname(capi_lib)
    cc = os.environ.get("CC", "gcc")
    subprocess.run(
        [cc, "-O2", src, f"-I{include}", f"-L{libdir}",
         "-lpaddle_tpu_capi", "-o", str(exe_path),
         f"-Wl,-rpath,{libdir}"],
        check=True, capture_output=True, text=True,
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PADDLE_TPU_PLATFORM"] = "cpu"
    env["LD_LIBRARY_PATH"] = (
        libdir + ":" + sysconfig.get_config_var("LIBDIR")
        + ":" + env.get("LD_LIBRARY_PATH", "")
    )
    r = subprocess.run(
        [str(exe_path), str(model_dir), REPO, str(t),
         *[str(int(x)) for x in ids.ravel()]],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    line = next(l for l in r.stdout.splitlines() if l.startswith("out0:"))
    got = np.array([float(v) for v in line.split()[1:]], np.float32)
    np.testing.assert_allclose(got, np.asarray(ref).ravel(), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(got.sum(), 1.0, rtol=1e-4)  # softmax row
