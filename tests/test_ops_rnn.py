"""Op tests: LSTM/GRU family — shape, mask-freezing, gradient checks
(reference: test_lstm_op.py, test_gru_op.py, gserver test_LayerGrad RNN
suites)."""

import pytest

import numpy as np

from op_test import check_grad, run_op

rng = np.random.RandomState(5)


def test_lstm_shapes_and_mask():
    b, t, d = 2, 5, 3
    x = rng.randn(b, t, 4 * d).astype(np.float32)
    w = (rng.randn(d, 4 * d) * 0.1).astype(np.float32)
    lens = np.asarray([3, 5], np.int32)
    got = run_op("lstm", {"Input": x, "Weight": w, "Length": lens})
    assert got["Hidden"].shape == (b, t, d)
    # hidden state frozen after sequence end
    np.testing.assert_allclose(got["Hidden"][0, 2], got["Hidden"][0, 3])
    np.testing.assert_allclose(got["Hidden"][0, 3], got["Hidden"][0, 4])


def test_lstm_reverse_runs_backward():
    b, t, d = 1, 4, 2
    x = rng.randn(b, t, 4 * d).astype(np.float32)
    w = (rng.randn(d, 4 * d) * 0.1).astype(np.float32)
    fwd = run_op("lstm", {"Input": x, "Weight": w})["Hidden"]
    rev = run_op("lstm", {"Input": x, "Weight": w}, {"is_reverse": True})["Hidden"]
    # reverse of reversed input equals forward on reversed sequence
    fwd_flip = run_op("lstm", {"Input": x[:, ::-1], "Weight": w})["Hidden"]
    np.testing.assert_allclose(rev, fwd_flip[:, ::-1], rtol=1e-5)


@pytest.mark.slow
def test_lstm_grad():
    b, t, d = 2, 3, 2
    x = rng.randn(b, t, 4 * d).astype(np.float32)
    w = (rng.randn(d, 4 * d) * 0.2).astype(np.float32)
    lens = np.asarray([2, 3], np.int32)
    check_grad("lstm", {"Input": x, "Weight": w, "Length": lens}, "Input",
               output="Hidden", max_relative_error=1e-2)
    check_grad("lstm", {"Input": x, "Weight": w, "Length": lens}, "Weight",
               output="Hidden", max_relative_error=1e-2)


def test_lstm_peephole_bias():
    b, t, d = 1, 3, 2
    x = rng.randn(b, t, 4 * d).astype(np.float32)
    w = (rng.randn(d, 4 * d) * 0.2).astype(np.float32)
    bias = (rng.randn(1, 7 * d) * 0.1).astype(np.float32)
    got = run_op("lstm", {"Input": x, "Weight": w, "Bias": bias},
                 {"use_peepholes": True})
    assert got["Hidden"].shape == (b, t, d)


@pytest.mark.slow
def test_gru_shapes_mask_and_grad():
    b, t, d = 2, 4, 3
    x = rng.randn(b, t, 3 * d).astype(np.float32)
    w = (rng.randn(d, 3 * d) * 0.2).astype(np.float32)
    lens = np.asarray([2, 4], np.int32)
    got = run_op("gru", {"Input": x, "Weight": w, "Length": lens})
    assert got["Hidden"].shape == (b, t, d)
    np.testing.assert_allclose(got["Hidden"][0, 1], got["Hidden"][0, 3])
    check_grad("gru", {"Input": x, "Weight": w, "Length": lens}, "Input",
               output="Hidden", max_relative_error=1e-2)


def test_lstmp_projection_shape():
    b, t, d, p = 2, 3, 4, 2
    x = rng.randn(b, t, 4 * d).astype(np.float32)
    w = (rng.randn(p, 4 * d) * 0.2).astype(np.float32)
    pw = (rng.randn(d, p) * 0.2).astype(np.float32)
    got = run_op("lstmp", {"Input": x, "Weight": w, "ProjWeight": pw})
    assert got["Projection"].shape == (b, t, p)


def test_lstm_unit_matches_manual():
    b, d = 2, 3
    x = rng.randn(b, 4 * d).astype(np.float32)
    c = rng.randn(b, d).astype(np.float32)
    got = run_op("lstm_unit", {"X": x, "C_prev": c})
    gi, gf, gc, go = np.split(x, 4, axis=1)
    sig = lambda v: 1 / (1 + np.exp(-v))
    c_new = sig(gf) * c + sig(gi) * np.tanh(gc)
    h_new = sig(go) * np.tanh(c_new)
    np.testing.assert_allclose(got["C"], c_new, rtol=1e-5)
    np.testing.assert_allclose(got["H"], h_new, rtol=1e-5)


def test_gru_unit_step_equals_full_gru_first_step():
    b, d = 2, 3
    x = rng.randn(b, 3 * d).astype(np.float32)
    w = (rng.randn(d, 3 * d) * 0.2).astype(np.float32)
    h0 = np.zeros((b, d), np.float32)
    unit = run_op("gru_unit", {"Input": x, "HiddenPrev": h0, "Weight": w})
    full = run_op("gru", {"Input": x[:, None, :], "Weight": w})
    np.testing.assert_allclose(unit["Hidden"], full["Hidden"][:, 0], rtol=1e-5)
