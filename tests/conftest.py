"""Test config: run on CPU with 8 virtual devices so multi-chip sharding
paths are exercised without TPU hardware (SURVEY environment notes)."""

import os

# force CPU: the session env pins JAX_PLATFORMS=axon (the TPU tunnel) and the
# axon plugin overrides the env var at import, so set the config explicitly.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

import paddle_tpu as pt

assert jax.devices()[0].platform == "cpu", jax.devices()


@pytest.fixture(autouse=True)
def fresh_programs():
    """Each test gets fresh default programs, scope and name counters."""
    main, startup = pt.Program(), pt.Program()
    prev_main = pt.core.program.switch_main_program(main)
    prev_startup = pt.core.program.switch_startup_program(startup)
    scope = pt.Scope()
    pt.core.scope._scope_stack.append(scope)
    pt.core.unique_name.reset()
    np.random.seed(0)
    yield
    pt.core.scope._scope_stack.pop()
    pt.core.program.switch_main_program(prev_main)
    pt.core.program.switch_startup_program(prev_startup)
