"""The sharding & communication contract analyzer (ISSUE 14):
CommPlan extraction (replica-group parsing, mesh-axis recovery, loop
membership, phase classification, provenance), the declarative
CommContract API, ``comm_diff``, the new checks
(``hlo.comm-contract`` / ``hlo.accidental-reshard`` /
``hlo.axis-attribution`` / ``program.spec-conflict`` /
``jaxpr.constraint-placement``), the Executor fold-in
(``exe.last_comm_plan`` + ``last_step_cost["comm_plan"]``), and the
schema-versioned ``--lint --json`` output contract."""

import json
import os
import subprocess
import sys

import numpy as np
import jax
import pytest

import paddle_tpu as pt
from paddle_tpu import analysis, layers
from paddle_tpu.analysis.comm import (
    CommContract,
    CommOp,
    CommPlan,
    attach_comm_contract,
    comm_diff,
    extract_comm_plan,
    mesh_axis_groups,
)
from paddle_tpu.analysis.comm.plan import (
    _axes_for_groups,
    _parse_replica_groups,
)
from paddle_tpu.parallel import api as papi
from paddle_tpu.parallel import contracts as pcontracts
from paddle_tpu.parallel.mesh import make_mesh
from jax.sharding import PartitionSpec as P


# -- replica-group parsing --------------------------------------------------

def test_parse_replica_groups_explicit():
    assert _parse_replica_groups("{{0,1,2,3},{4,5,6,7}}") == [
        [0, 1, 2, 3], [4, 5, 6, 7]]
    assert _parse_replica_groups("{{0,4},{1,5},{2,6},{3,7}}") == [
        [0, 4], [1, 5], [2, 6], [3, 7]]
    assert _parse_replica_groups("{}") == []
    assert _parse_replica_groups(None) is None
    assert _parse_replica_groups("garbage") is None


def test_parse_replica_groups_iota():
    # [2,4]<=[8]: iota(8).reshape(2,4) — rows are groups
    assert _parse_replica_groups("[2,4]<=[8]") == [
        [0, 1, 2, 3], [4, 5, 6, 7]]
    # the transposed form: iota(8).reshape(2,4).T.reshape(4,2)
    assert _parse_replica_groups("[4,2]<=[2,4]T(1,0)") == [
        [0, 4], [1, 5], [2, 6], [3, 7]]
    assert _parse_replica_groups("[8]<=[8]") == [
        [0, 1, 2, 3, 4, 5, 6, 7]]


def test_mesh_axis_recovery():
    mesh = make_mesh({"dp": 2, "fsdp": 4})
    groups = mesh_axis_groups(mesh)
    assert set(groups) == {("dp",), ("fsdp",), ("dp", "fsdp")}
    # on the row-major 8-device mesh: fsdp varies within a dp row
    assert _axes_for_groups([[0, 1, 2, 3], [4, 5, 6, 7]], groups,
                            8) == ("fsdp",)
    assert _axes_for_groups([[0, 4], [1, 5], [2, 6], [3, 7]], groups,
                            8) == ("dp",)
    # one all-devices group = the full-axis subset; {} spells the same
    assert _axes_for_groups([[0, 1, 2, 3, 4, 5, 6, 7]], groups,
                            8) == ("dp", "fsdp")
    assert _axes_for_groups([], groups, 8) == ("dp", "fsdp")
    # a partition matching NO axis subset: GSPMD invented a resharding
    assert _axes_for_groups([[0, 1], [2, 3], [4, 5], [6, 7]], groups,
                            8) is None
    # size-1 groups = no communication, not an invention
    assert _axes_for_groups([[k] for k in range(8)], groups, 8) == ()


# -- extraction from planted HLO --------------------------------------------

_PLANTED_HLO = """\
HloModule planted, entry_computation_layout={(f32[8])->f32[8]}

%body.1 (p: (s32[], f32[8])) -> (s32[], f32[8]) {
  %p = (s32[], f32[8]) parameter(0)
  %g = f32[8] get-tuple-element((s32[], f32[8]) %p), index=1
  %ag = f32[8,4]{1,0} all-gather(f32[2,4]{1,0} %g), channel_id=1, replica_groups=[2,4]<=[8], dimensions={0}, use_global_device_ids=true, metadata={op_name="jit(step)/jvp(while)/body/pt_pin[fsdp_gather:w0]/squeeze"}
  %ar = f32[8] all-reduce(f32[8] %g), channel_id=2, replica_groups=[2,4]<=[8], to_apply=%sum.2, metadata={op_name="jit(step)/transpose(jvp(while))/body/dot_general"}
}

%cond.3 (p: (s32[], f32[8])) -> pred[] {
  %p = (s32[], f32[8]) parameter(0)
}

ENTRY %main.4 (a: f32[8]) -> f32[8] {
  %a = f32[8] parameter(0)
  %w = (s32[], f32[8]) while((s32[], f32[8]) %t), condition=%cond.3, body=%body.1
  %out = f32[4096] all-reduce(f32[4096] %gte), channel_id=3, replica_groups=[4,2]<=[2,4]T(1,0), to_apply=%sum.2, metadata={op_name="jit(step)/pt_pin[grad_boundary:fc.w]/add"}
  %rs = f32[2048] reduce-scatter(f32[4096] %gte), channel_id=4, replica_groups={{0,1},{2,3},{4,5},{6,7}}, dimensions={0}, to_apply=%sum.2, metadata={op_name="jit(step)/pt_shard[h_act]/dot_general"}
}
"""


@pytest.fixture
def planted_plan():
    mesh = make_mesh({"dp": 2, "fsdp": 4})
    return extract_comm_plan(_PLANTED_HLO, mesh=mesh)


def test_extract_kinds_loop_phase(planted_plan):
    plan = planted_plan
    assert len(plan) == 4
    by_kind = {op.kind: op for op in plan}
    ag = by_kind["all-gather"]
    assert ag.in_loop and ag.phase == "fwd-scan"
    assert ag.axes == ("fsdp",)
    assert ag.provenance == {"site": "fsdp_gather:w0"}
    ar_loop = [op for op in plan
               if op.kind == "all-reduce" and op.in_loop][0]
    # the transpose( autodiff marker classifies the backward scan
    assert ar_loop.phase == "bwd-scan"
    ar_boundary = [op for op in plan
                   if op.kind == "all-reduce" and not op.in_loop][0]
    assert ar_boundary.phase == "boundary"
    assert ar_boundary.axes == ("dp",)
    assert ar_boundary.bytes == 4096 * 4
    assert ar_boundary.provenance == {"site": "grad_boundary:fc.w"}
    rs = by_kind["reduce-scatter"]
    # {{0,1},{2,3},...} matches no axis subset of the dp2 x fsdp4 mesh
    assert rs.axes is None
    assert rs.provenance == {"var": "h_act"}
    assert plan.unattributed() == [rs]


def test_plan_select_and_summary(planted_plan):
    plan = planted_plan
    assert len(plan.select(kind="reduce")) == 3
    assert len(plan.select(kind="reduce", in_loop=True)) == 1
    assert len(plan.select(kind="gather")) == 1
    # the in-loop all-gather AND the in-loop all-reduce both span fsdp
    assert len(plan.select(axis="fsdp")) == 2
    assert len(plan.select(phase="boundary")) == 2
    assert len(plan.select(provenance=r"^h_")) == 1
    rows = plan.summary()
    assert all(set(r) == {"kind", "axes", "phase", "in_loop", "count",
                          "bytes"} for r in rows)
    assert json.loads(json.dumps(plan.to_dict()))  # JSON-able


def test_phase_label_override():
    mesh = make_mesh({"dp": 2, "fsdp": 4})
    plan = extract_comm_plan(_PLANTED_HLO, mesh=mesh,
                             label="serving_prefill_b4")
    assert {op.phase for op in plan} == {"prefill"}


def test_extract_without_mesh_keeps_axes_unresolved():
    plan = extract_comm_plan(_PLANTED_HLO, mesh=None)
    assert len(plan) == 4
    assert all(op.axes is None for op in plan)
    assert plan.mesh_axes == {}


# -- contracts --------------------------------------------------------------

def _mini_plan():
    return CommPlan([
        CommOp("all-reduce", 1024, ("dp",), False, "boundary"),
        CommOp("all-gather", 2048, ("fsdp",), True, "fwd-scan",
               provenance={"site": "fsdp_gather:w"}),
        CommOp("all-gather", 512, ("dp",), True, "fwd-scan",
               provenance={"var": "h_act"}),
    ], mesh_axes={"dp": 2, "fsdp": 4})


def test_contract_expect_and_forbid():
    plan = _mini_plan()
    c = (CommContract("good")
         .expect(kind="reduce", axis="dp", count=1, in_loop=False)
         .expect(kind="all-gather", axis="fsdp", min_count=1,
                 in_loop=True)
         .forbid(kind="reduce", in_loop=True))
    assert c.check(plan) == []
    bad = CommContract("bad").expect(kind="reduce", axis="dp", count=3)
    (v,) = bad.check(plan)
    assert "expected exactly 3" in v["message"] and v["op_count"] == 1
    forb = CommContract("noloop").forbid(kind="gather", in_loop=True)
    (v2,) = forb.check(plan)
    assert v2["op_count"] == 2 and "forbidden" in v2["message"]
    with pytest.raises(ValueError):
        CommContract("x").expect(kind="no-such-kind")


def test_contract_forbid_reshard_and_covered():
    plan = _mini_plan()
    c = CommContract("no-act").forbid_reshard(r"^h_")
    (v,) = c.check(plan)
    assert "h_act" in v["message"]
    # pin-site provenance does not match a var pattern scoped to ^h_
    assert v["op_count"] == 1
    cov = (CommContract("cover")
           .expect(kind="all-gather", axis="fsdp", in_loop=True))
    assert {op.kind for op in cov.covered(plan)} == {"all-gather"}
    with pytest.raises(Exception):
        CommContract("x").forbid_reshard("(unclosed")


def test_attach_comm_contract_accumulates():
    prog = pt.Program()
    a = attach_comm_contract(prog, CommContract("a"))
    attach_comm_contract(prog, CommContract("b"))
    from paddle_tpu.analysis.comm import comm_contracts

    assert [c.name for c in comm_contracts(prog)] == ["a", "b"]
    assert a.name == "a"
    assert comm_contracts(None) == []


def test_canned_training_contracts():
    mesh = make_mesh({"dp": 2, "fsdp": 4})
    cs = pcontracts.training_step_contract(mesh, accum=True, fsdp=True)
    assert [c.name for c in cs] == ["one-boundary-reduce",
                                    "fsdp-scan-gathers"]
    plan = _mini_plan()
    assert all(c.check(plan) == [] for c in cs)
    # a plan with an in-loop reduce violates both
    bad = CommPlan(plan.ops + [
        CommOp("all-reduce", 64, ("dp",), True, "bwd-scan")],
        mesh_axes=plan.mesh_axes)
    assert any(c.check(bad) for c in cs)


def test_collective_with_done_operand_still_counted():
    """Async comm overlap produces values named %all-gather-done.N; a
    real collective CONSUMING one must still land in the plan (the
    -done op itself never parses — the regex requires '(' right after
    the kind)."""
    text = (
        "HloModule m\n\n"
        "ENTRY %main (a: f32[8]) -> f32[8] {\n"
        "  %ar.5 = f32[1024] all-reduce(f32[1024] %all-gather-done.3),"
        " channel_id=2, replica_groups={}, to_apply=%sum,"
        ' metadata={op_name="jit(step)/add"}\n'
        "  %d = (f32[8]) all-gather-done((f32[8]) %s), channel_id=3\n"
        "}\n")
    plan = extract_comm_plan(text)
    assert [op.kind for op in plan] == ["all-reduce"]
    assert plan.ops[0].bytes == 1024 * 4


def test_anchored_forbid_reshard_hits_multi_output_provenance():
    """A multi-output producer's pt_shard scope joins its annotated
    outputs with commas; an anchored pattern (^h_) must still fire on
    the second name."""
    plan = CommPlan([
        CommOp("all-gather", 64, ("dp",), True, "fwd-scan",
               provenance={"var": "a_out,h_act"})],
        mesh_axes={"dp": 8})
    assert len(plan.select(provenance=r"^h_")) == 1
    (v,) = CommContract("x").forbid_reshard(r"^h_").check(plan)
    assert "h_act" in v["message"] and "a_out" not in str(v["message"])


def test_comm_report_derivation_matches_hlo_comm_report():
    """``CommPlan.comm_report()`` (what the Executor's fold-in ships)
    is key-for-key identical to the legacy text parser on the same
    HLO — one parse serves both shapes."""
    from paddle_tpu.analysis.hlo_tools import hlo_comm_report

    mesh = make_mesh({"dp": 2, "fsdp": 4})
    derived = extract_comm_plan(_PLANTED_HLO, mesh=mesh).comm_report()
    assert derived == hlo_comm_report(_PLANTED_HLO)
    assert derived["reduce_ops_in_loop"] == 1
    assert derived["collectives_in_loop"] == 2
    assert extract_comm_plan("", mesh=mesh).comm_report()[
        "collective_count"] == 0


def test_fused_compiles_still_evaluate_forbid_reshard():
    """The in_loop_expected exemption drops loop/phase selectors but
    NOT forbid_reshard — provenance rules are loop-insensitive, and a
    forbidden activation reshard must not hide behind run_steps'
    fused-loop production path."""
    from paddle_tpu.analysis.comm.checks import comm_contract

    prog = pt.Program()
    c = (CommContract("mixed")
         .forbid(kind="reduce", in_loop=True)   # confounded by fusion
         .forbid_reshard(r"^h_"))               # loop-insensitive
    attach_comm_contract(prog, c)
    fused = CommPlan([
        CommOp("all-reduce", 64, ("dp",), True, "fwd-scan"),
        CommOp("all-gather", 64, ("dp",), True, "fwd-scan",
               provenance={"var": "h_act"}),
    ], mesh_axes={"dp": 8})
    mesh = make_mesh({"dp": 8})
    ctx = analysis.CheckContext(prog, mesh=mesh, in_loop_expected=True)
    ctx.seed("comm_plan", fused)
    fs = list(comm_contract(ctx))
    assert len(fs) == 1
    assert "h_act" in fs[0].message  # the reshard rule fired
    assert "forbidden reduce" not in fs[0].message


def test_contract_check_skips_fused_run_steps_compiles():
    """run_steps fuses N optimizer steps into ONE while loop — the
    boundary reduce is structurally in-loop there, so contract
    in_loop/phase selectors would false-fire.  The hlo.comm-contract
    check applies the same in_loop_expected exemption as
    hlo.inloop-collective."""
    from paddle_tpu.analysis.comm.checks import comm_contract

    prog = pt.Program()
    attach_comm_contract(
        prog, CommContract("c").forbid(kind="reduce", in_loop=True))
    fused = CommPlan([
        CommOp("all-reduce", 64, ("dp",), True, "fwd-scan")],
        mesh_axes={"dp": 8})
    mesh = make_mesh({"dp": 8})
    ctx = analysis.CheckContext(prog, mesh=mesh, in_loop_expected=True)
    ctx.seed("comm_plan", fused)
    assert list(comm_contract(ctx)) == []
    ctx2 = analysis.CheckContext(prog, mesh=mesh)
    ctx2.seed("comm_plan", fused)
    assert [f.check for f in comm_contract(ctx2)] == [
        "hlo.comm-contract"]


def test_constraint_placement_exempts_declared_pt_shard():
    """A shard_activation annotation on a var produced INSIDE a scanned
    layer group traces as an in-scan constraint under pt_shard[var] —
    a declared annotation, policed by the reshard/contract checks, not
    flagged as a rogue unblessed pin."""
    from paddle_tpu.models import transformer

    mesh = make_mesh({"dp": 2, "fsdp": 4})
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 7
    with pt.program_guard(main, startup):
        outs = transformer.build(vocab_size=64, n_layer=3, n_head=2,
                                 d_model=32, max_len=16,
                                 dropout_rate=0.0, dtype="float32")
    pt.memory_optimize(main, policy="selective")
    papi.data_parallel(main, "dp", programs=(startup,))
    blk = main.global_block()
    act = blk.vars["block1_att_out.tmp_0"]
    papi.shard_activation(act, P(*([None] * (len(act.shape) - 1)),
                                 "fsdp"))
    toks = np.zeros((4, 16), np.int64)
    feed = {"tokens": toks, "labels": toks}
    rep = analysis.lint(main, feed=feed,
                        fetch_list=[outs["avg_cost"]], mesh=mesh,
                        levels=("jaxpr",))
    assert rep.by_check("jaxpr.constraint-placement") == []


# -- comm_diff --------------------------------------------------------------

def test_comm_diff_explains_moved_op():
    base = _mini_plan()
    moved = CommPlan(base.ops + [
        CommOp("all-reduce", 4096, ("fsdp",), True, "bwd-scan"),
        CommOp("all-reduce", 4096, ("fsdp",), True, "bwd-scan"),
    ], mesh_axes=base.mesh_axes)
    diff = comm_diff(base, moved, "good", "bad")
    assert not diff["same"]
    (c,) = diff["changed"]
    assert c["kind"] == "all-reduce" and c["axes"] == "fsdp"
    assert c["in_loop"] and c["count_a"] == 0 and c["count_b"] == 2
    assert "good -> bad" in diff["text"][0]
    assert comm_diff(base, base)["same"]


# -- program.spec-conflict --------------------------------------------------

def test_spec_conflict_flags_indivisible_dims():
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[6])
        y = layers.fc(x, 3, name="odd")
    blk = main.global_block()
    # 3 does not divide over fsdp=4: annotated on the [6, 3] weight's
    # output axis
    blk.vars["odd.w"].partition_spec = P(None, "fsdp")
    mesh = make_mesh({"dp": 2, "fsdp": 4})
    rep = analysis.lint(main, fetch_list=[y], mesh=mesh,
                        levels=("program",))
    sc = rep.by_check("program.spec-conflict")
    assert sc and sc[0].severity == "warning"
    assert sc[0].data["var"] == "odd.w"
    assert sc[0].data["product"] == 4
    # a genuinely divisible spec is quiet: 6 % dp=2 == 0
    blk.vars["odd.w"].partition_spec = P("dp", None)
    rep2 = analysis.lint(main, fetch_list=[y], mesh=mesh,
                         levels=("program",))
    assert rep2.by_check("program.spec-conflict") == []


def test_spec_conflict_fsdp_composition():
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[6])
        y = layers.fc(x, 3, name="f")
    blk = main.global_block()
    blk.vars["f.w"].fsdp_param = True  # [6, 3]: 6 % fsdp=4 != 0
    mesh = make_mesh({"dp": 2, "fsdp": 4})
    rep = analysis.lint(main, fetch_list=[y], mesh=mesh,
                        levels=("program",))
    sc = rep.by_check("program.spec-conflict")
    assert sc and "fsdp" in sc[0].message
    # without a mesh the check is silent
    rep2 = analysis.lint(main, fetch_list=[y], levels=("program",))
    assert rep2.by_check("program.spec-conflict") == []


# -- executor fold-in + end-to-end on the 8-device mesh ---------------------

def _tiny_net(mesh):
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[16])
        yv = layers.data("y", shape=[1])
        h = layers.fc(x, 32, act="relu", name="h1")
        loss = layers.reduce_mean(
            layers.square(layers.fc(h, 1, name="out") - yv))
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    papi.data_parallel(main, "dp", programs=(startup,))
    return main, startup, loss


def test_executor_folds_comm_plan():
    mesh = make_mesh({"dp": 8})
    main, startup, loss = _tiny_net(mesh)
    exe = pt.Executor(mesh=mesh)
    exe.run(startup)
    feed = {"x": np.zeros((8, 16), np.float32),
            "y": np.zeros((8, 1), np.float32)}
    exe.run(main, feed=feed, fetch_list=[loss])
    plan = exe.last_comm_plan
    assert plan is not None and len(plan) > 0
    # the dp gradient reduction sits at the boundary, attributed to dp
    reduces = plan.select(kind="reduce", in_loop=False)
    assert reduces and all(op.axes == ("dp",) for op in reduces)
    assert not plan.unattributed()
    rows = exe.last_step_cost.get("comm_plan")
    assert rows == plan.summary()
    # the canned contract holds on this step
    (c,) = pcontracts.training_step_contract(mesh)
    assert c.check(plan) == []


def test_contract_violation_surfaces_in_compile_lint():
    mesh = make_mesh({"dp": 8})
    main, startup, loss = _tiny_net(mesh)
    # a contract this step cannot satisfy: forbid the boundary reduce
    attach_comm_contract(
        main, CommContract("impossible").forbid(kind="reduce"))
    exe = pt.Executor(mesh=mesh)
    exe.run(startup)
    feed = {"x": np.zeros((8, 16), np.float32),
            "y": np.zeros((8, 1), np.float32)}
    exe.run(main, feed=feed, fetch_list=[loss])
    cost = exe.last_step_cost
    assert cost["lint_errors"] >= 1
    assert "hlo.comm-contract" in (cost.get("lint_checks") or [])


def test_shard_activation_provenance_and_reshard_check():
    mesh = make_mesh({"dp": 8})
    main, startup, loss = _tiny_net(mesh)
    blk = main.global_block()
    act = blk.vars["h1.tmp_1"]
    papi.shard_activation(act, P(None, "dp"))  # feature-shard: reshard
    feed = {"x": np.zeros((8, 16), np.float32),
            "y": np.zeros((8, 1), np.float32)}
    rep = analysis.lint(main, feed=feed, fetch_list=[loss], mesh=mesh,
                        levels=("hlo",))
    ar = rep.by_check("hlo.accidental-reshard")
    assert ar and ar[0].severity == "warning"
    assert ar[0].data["var"] == "h1.tmp_1"
    assert ar[0].data["op_count"] > 0
    # shard_activation refuses persistables and data feeds
    with pytest.raises(ValueError):
        papi.shard_activation(blk.vars["x"], P("dp"))
    with pytest.raises(ValueError):
        papi.shard_activation(blk.vars["h1.w"], P("dp", None))


def test_constraint_placement_quiet_on_clean_programs():
    """The blessed pt_pin sites (boundary grad pin, accum carry, fsdp
    pins) never fire the constraint-placement check on a clean
    accumulation step."""
    mesh = make_mesh({"dp": 8})
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[16])
        yv = layers.data("y", shape=[1])
        h = layers.fc(x, 32, act="relu", name="h1")
        loss = layers.reduce_mean(
            layers.square(layers.fc(h, 1, name="out") - yv))
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    pt.gradient_accumulation(main, 2)
    papi.data_parallel(main, "dp", programs=(startup,))
    feed = {"x": np.zeros((16, 16), np.float32),
            "y": np.zeros((16, 1), np.float32)}
    rep = analysis.lint(main, feed=feed, fetch_list=[loss], mesh=mesh,
                        levels=("jaxpr",))
    assert rep.by_check("jaxpr.constraint-placement") == []


# -- the schema-versioned --lint --json contract ----------------------------

def test_lint_json_schema_round_trip():
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        y = layers.fc(x, 2, name="live")
        layers.fc(x, 3, name="dead")  # planted dead code
        blk = main.global_block()
        blk.create_var(name="orphan", shape=(3,), dtype="float32")
    rep = analysis.lint(main, fetch_list=[y], levels=("program",))
    assert len(rep) > 0
    obj = analysis.report_json(rep, levels=("program",))
    # stable top-level keys + per-finding keys (data always present)
    assert set(obj) == {"schema_version", "levels", "findings",
                        "counts", "ok"}
    assert obj["schema_version"] == analysis.LINT_JSON_SCHEMA_VERSION
    assert obj["levels"] == ["program"]
    keys = {"check", "severity", "level", "location", "message",
            "hint", "data"}
    assert all(set(f) == keys for f in obj["findings"])
    # sorted: severity rank desc, then check id / location / message
    ranks = [("error", "warning", "info").index(f["severity"])
             for f in obj["findings"]]
    assert ranks == sorted(ranks)
    for a, b in zip(obj["findings"], obj["findings"][1:]):
        if a["severity"] == b["severity"]:
            assert (a["check"], a["location"], a["message"]) <= (
                b["check"], b["location"], b["message"])
    # the round trip: serialize -> parse -> rebuild -> identical JSON
    wire = json.dumps(obj)
    rebuilt = analysis.report_from_json(json.loads(wire))
    assert analysis.report_json(rebuilt, levels=("program",)) == obj
    # newer schema versions refuse instead of misreading
    with pytest.raises(ValueError):
        analysis.report_from_json(
            {"schema_version": analysis.LINT_JSON_SCHEMA_VERSION + 1,
             "findings": []})


@pytest.mark.slow
def test_lint_json_cli_contract():
    """``python -m paddle_tpu --lint <config> --json`` emits exactly one
    JSON object honoring the schema contract (subprocess: the CLI is
    what CI consumers actually parse)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = os.path.join(repo, "examples", "train_mnist.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu", "--lint", cfg, "--json",
         "--levels", "program"],
        capture_output=True, text=True, env=env, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    obj = json.loads(proc.stdout)
    assert obj["schema_version"] == analysis.LINT_JSON_SCHEMA_VERSION
    assert obj["ok"] is True and obj["levels"] == ["program"]
    rebuilt = analysis.report_from_json(obj)
    assert analysis.report_json(
        rebuilt, levels=("program",)) == obj
