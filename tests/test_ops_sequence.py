"""Op tests: the sequence group — the LoD-replacement semantics (padded +
lengths) must reproduce the reference's ragged behavior."""

import numpy as np
import pytest

from op_test import check_output, check_grad, run_op

rng = np.random.RandomState(3)


def test_sequence_pool_types():
    x = rng.randn(2, 4, 3).astype(np.float32)
    lens = np.asarray([2, 4], np.int32)
    got = run_op("sequence_pool", {"X": x, "Length": lens}, {"pooltype": "SUM"})
    np.testing.assert_allclose(got["Out"][0], x[0, :2].sum(0), rtol=1e-5)
    np.testing.assert_allclose(got["Out"][1], x[1].sum(0), rtol=1e-5)
    got = run_op("sequence_pool", {"X": x, "Length": lens}, {"pooltype": "AVERAGE"})
    np.testing.assert_allclose(got["Out"][0], x[0, :2].mean(0), rtol=1e-5)
    got = run_op("sequence_pool", {"X": x, "Length": lens}, {"pooltype": "MAX"})
    np.testing.assert_allclose(got["Out"][0], x[0, :2].max(0), rtol=1e-5)
    got = run_op("sequence_pool", {"X": x, "Length": lens}, {"pooltype": "LAST"})
    np.testing.assert_allclose(got["Out"][0], x[0, 1], rtol=1e-5)
    got = run_op("sequence_pool", {"X": x, "Length": lens}, {"pooltype": "FIRST"})
    np.testing.assert_allclose(got["Out"][1], x[1, 0], rtol=1e-5)


def test_sequence_pool_grad_masked():
    x = rng.randn(2, 4, 3).astype(np.float32)
    lens = np.asarray([2, 3], np.int32)
    check_grad("sequence_pool", {"X": x, "Length": lens}, "X",
               attrs={"pooltype": "SUM"})


def test_sequence_softmax_masked():
    x = rng.randn(2, 5).astype(np.float32)
    lens = np.asarray([3, 5], np.int32)
    got = run_op("sequence_softmax", {"X": x, "Length": lens})["Out"]
    np.testing.assert_allclose(got[0, :3].sum(), 1.0, rtol=1e-5)
    assert np.all(got[0, 3:] == 0)
    e = np.exp(x[0, :3] - x[0, :3].max())
    np.testing.assert_allclose(got[0, :3], e / e.sum(), rtol=1e-5)


def test_sequence_conv_context():
    x = rng.randn(1, 4, 2).astype(np.float32)
    f = rng.randn(6, 3).astype(np.float32)  # context 3 * dim 2
    lens = np.asarray([4], np.int32)
    got = run_op(
        "sequence_conv", {"X": x, "Filter": f, "Length": lens},
        {"contextLength": 3, "contextStart": -1},
    )["Out"]
    # position 0: context rows [-1 (zero), 0, 1]
    ctx0 = np.concatenate([np.zeros(2, np.float32), x[0, 0], x[0, 1]])
    np.testing.assert_allclose(got[0, 0], ctx0 @ f, rtol=1e-4)


def test_sequence_expand():
    x = rng.randn(2, 3).astype(np.float32)
    y = rng.randn(2, 4, 5).astype(np.float32)
    ylen = np.asarray([2, 4], np.int32)
    got = run_op("sequence_expand", {"X": x, "Y": y, "YLength": ylen})["Out"]
    assert got.shape == (2, 4, 3)
    np.testing.assert_allclose(got[0, 0], x[0])
    np.testing.assert_allclose(got[0, 1], x[0])
    assert np.all(got[0, 2:] == 0)


def test_sequence_erase_and_ctc_align():
    x = np.asarray([[1, 1, 0, 2, 2, 0, 3, 0]], np.int64)
    lens = np.asarray([8], np.int32)
    got = run_op("ctc_align", {"Input": x, "Length": lens},
                 {"blank": 0, "merge_repeated": True})
    np.testing.assert_array_equal(got["Output"][0, :3], [1, 2, 3])
    assert got["OutputLength"][0] == 3

    got = run_op("sequence_erase", {"X": x, "Length": lens}, {"tokens": [0, 1]})
    np.testing.assert_array_equal(got["Out"][0, :3], [2, 2, 3])
    assert got["OutLength"][0] == 3


def test_edit_distance():
    hyp = np.asarray([[1, 2, 3, 0]], np.int64)
    ref = np.asarray([[1, 3, 3, 4]], np.int64)
    got = run_op(
        "edit_distance",
        {"Hyps": hyp, "Refs": ref,
         "HypsLength": np.asarray([3], np.int32),
         "RefsLength": np.asarray([4], np.int32)},
    )
    # kitten-style: [1,2,3] vs [1,3,3,4] = sub(2->3)? dist: 1 sub + 1 ins = 2
    assert got["Out"][0, 0] == 2.0


@pytest.mark.slow
def test_warpctc_loss_and_grad():
    b, t, v, l = 2, 6, 5, 2
    logits = rng.randn(b, t, v).astype(np.float32)
    labels = np.asarray([[1, 2], [3, 0]], np.int64)
    lab_len = np.asarray([2, 1], np.int32)
    log_len = np.asarray([6, 4], np.int32)
    got = run_op(
        "warpctc",
        {"Logits": logits, "Label": labels, "LogitsLength": log_len,
         "LabelLength": lab_len},
        {"blank": 0},
    )
    assert got["Loss"].shape == (2, 1)
    assert np.all(got["Loss"] > 0)
    check_grad(
        "warpctc",
        {"Logits": logits, "Label": labels, "LogitsLength": log_len,
         "LabelLength": lab_len},
        "Logits", attrs={"blank": 0}, output="Loss", max_relative_error=1e-2,
    )


def test_ctc_loss_simple_case():
    """T=1, single label: loss = -log softmax(logits)[label]."""
    logits = rng.randn(1, 1, 4).astype(np.float32)
    labels = np.asarray([[2]], np.int64)
    got = run_op(
        "warpctc",
        {"Logits": logits, "Label": labels,
         "LogitsLength": np.asarray([1], np.int32),
         "LabelLength": np.asarray([1], np.int32)},
        {"blank": 0},
    )
    e = np.exp(logits[0, 0] - logits[0, 0].max())
    expected = -np.log(e[2] / e.sum())
    np.testing.assert_allclose(got["Loss"][0, 0], expected, rtol=1e-4)


def test_linear_chain_crf_uniform_is_log_numtags():
    """Zero emissions+transitions: nll = T * 0 ... = log(paths)."""
    b, t, n = 1, 3, 4
    em = np.zeros((b, t, n), np.float32)
    trans = np.zeros((n + 2, n), np.float32)
    lbl = np.zeros((b, t), np.int64)
    lens = np.asarray([t], np.int32)
    got = run_op(
        "linear_chain_crf",
        {"Emission": em, "Transition": trans, "Label": lbl, "Length": lens},
    )
    np.testing.assert_allclose(
        got["LogLikelihood"][0, 0], t * np.log(n), rtol=1e-5
    )


def test_crf_decoding_picks_best_path():
    n = 3
    em = np.asarray([[[5, 0, 0], [0, 5, 0], [0, 0, 5]]], np.float32)
    trans = np.zeros((n + 2, n), np.float32)
    got = run_op(
        "crf_decoding",
        {"Emission": em, "Transition": trans,
         "Length": np.asarray([3], np.int32)},
    )
    np.testing.assert_array_equal(got["ViterbiPath"][0], [0, 1, 2])


@pytest.mark.slow
def test_crf_grad():
    b, t, n = 2, 4, 3
    em = rng.randn(b, t, n).astype(np.float32)
    trans = rng.randn(n + 2, n).astype(np.float32) * 0.1
    lbl = rng.randint(0, n, (b, t)).astype(np.int64)
    lens = np.asarray([3, 4], np.int32)
    check_grad(
        "linear_chain_crf",
        {"Emission": em, "Transition": trans, "Label": lbl, "Length": lens},
        "Emission", output="LogLikelihood", max_relative_error=1e-2,
    )
    check_grad(
        "linear_chain_crf",
        {"Emission": em, "Transition": trans, "Label": lbl, "Length": lens},
        "Transition", output="LogLikelihood", max_relative_error=1e-2,
    )


def test_chunk_eval_iob():
    # tags: B-0=0, I-0=1, O=2 (num_chunk_types=1)
    inf = np.asarray([[0, 1, 2, 0, 2]], np.int64)
    lab = np.asarray([[0, 1, 2, 0, 2]], np.int64)
    got = run_op("chunk_eval", {"Inference": inf, "Label": lab},
                 {"num_chunk_types": 1, "chunk_scheme": "IOB"})
    assert got["NumInferChunks"][0] == 2
    assert got["NumLabelChunks"][0] == 2
    assert got["NumCorrectChunks"][0] == 2
    np.testing.assert_allclose(got["F1-Score"][0], 1.0)
    # now a partial match: second chunk extends
    inf2 = np.asarray([[0, 1, 2, 0, 1]], np.int64)
    got = run_op("chunk_eval", {"Inference": inf2, "Label": lab},
                 {"num_chunk_types": 1, "chunk_scheme": "IOB"})
    assert got["NumCorrectChunks"][0] == 1


# ---- chunk_eval full-scheme parity (chunk_eval_op.h:27-198) -------------

_SCHEMES = {
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _ref_segments(labels, num_chunk_types, scheme):
    """Direct port of the reference GetSegments stateful walk."""
    n_tags, t_beg, t_in, t_end, t_sin = _SCHEMES[scheme]
    other = num_chunk_types

    def chunk_end(pt, pty, tg, ty):
        if pty == other: return False
        if ty == other: return True
        if ty != pty: return True
        if pt == t_beg: return tg in (t_beg, t_sin)
        if pt == t_in: return tg in (t_beg, t_sin)
        if pt == t_end: return True
        if pt == t_sin: return True
        return False

    def chunk_begin(pt, pty, tg, ty):
        if pty == other: return ty != other
        if ty == other: return False
        if ty != pty: return True
        if tg == t_beg: return True
        if tg == t_in: return pt in (t_end, t_sin)
        if tg == t_end: return pt in (t_end, t_sin)
        if tg == t_sin: return True
        return False

    segs, in_chunk, start = [], False, 0
    tag, typ = -1, other
    for i, lab in enumerate(labels):
        pt, pty = tag, typ
        tag, typ = lab % n_tags, lab // n_tags
        if in_chunk and chunk_end(pt, pty, tag, typ):
            segs.append((start, i - 1, pty))
            in_chunk = False
        if chunk_begin(pt, pty, tag, typ):
            start, in_chunk = i, True
    if in_chunk:
        segs.append((start, len(labels) - 1, typ))
    return segs


def _ref_chunk_eval(inf, lab, lengths, num_chunk_types, scheme, excluded=()):
    ni = nl = nc = 0
    for i in range(inf.shape[0]):
        L = lengths[i] if lengths is not None else inf.shape[1]
        si = [s for s in _ref_segments(list(inf[i, :L]), num_chunk_types, scheme)
              if s[2] not in excluded]
        sl = [s for s in _ref_segments(list(lab[i, :L]), num_chunk_types, scheme)
              if s[2] not in excluded]
        ni += len(si); nl += len(sl)
        nc += len(set(si) & set(sl))
    p = nc / ni if ni else 0.0
    r = nc / nl if nl else 0.0
    f = 2 * p * r / (p + r) if nc else 0.0
    return p, r, f, ni, nl, nc


@pytest.mark.parametrize("scheme", ["IOB", "IOE", "IOBES", "plain"])
def test_chunk_eval_schemes_fuzz_vs_reference_walk(scheme):
    import zlib
    rng_ = np.random.RandomState(zlib.crc32(scheme.encode()))
    n_types = 3
    n_tags = _SCHEMES[scheme][0]
    hi = n_types * n_tags + 1  # inclusive of the outside label
    for trial in range(8):
        b, t = rng_.randint(1, 5), rng_.randint(3, 12)
        inf = rng_.randint(0, hi, (b, t)).astype(np.int64)
        lab = rng_.randint(0, hi, (b, t)).astype(np.int64)
        lengths = rng_.randint(1, t + 1, (b,)).astype(np.int64)
        exp = _ref_chunk_eval(inf, lab, lengths, n_types, scheme)
        got = run_op(
            "chunk_eval",
            {"Inference": inf, "Label": lab, "Length": lengths},
            {"num_chunk_types": n_types, "chunk_scheme": scheme},
        )
        np.testing.assert_allclose(
            [float(got["Precision"][0]), float(got["Recall"][0]),
             float(got["F1-Score"][0])], exp[:3], atol=1e-6,
            err_msg=f"{scheme} trial {trial}\ninf={inf}\nlab={lab}\nlen={lengths}")
        assert (int(got["NumInferChunks"][0]), int(got["NumLabelChunks"][0]),
                int(got["NumCorrectChunks"][0])) == exp[3:], (
            f"{scheme} trial {trial}: counts {got} != {exp}")


def test_chunk_eval_excluded_chunk_types():
    inf = np.array([[0, 1, 4, 2, 4]], np.int64)   # B0 I0 O B1 O
    lab = np.array([[0, 1, 4, 2, 4]], np.int64)
    exp = _ref_chunk_eval(inf, lab, None, 2, "IOB", excluded=(1,))
    got = run_op("chunk_eval", {"Inference": inf, "Label": lab},
                 {"num_chunk_types": 2, "chunk_scheme": "IOB",
                  "excluded_chunk_types": (1,)})
    assert int(got["NumInferChunks"][0]) == exp[3] == 1
    assert int(got["NumCorrectChunks"][0]) == exp[5] == 1


def test_sequence_reverse_op():
    """Length-aware rotation (sequence_reverse): element t swaps with
    len-1-t, padding stays right-aligned; no Length = full flip."""
    x = np.arange(2 * 4 * 3, dtype=np.float32).reshape(2, 4, 3)
    lens = np.asarray([2, 4], np.int32)
    out = run_op("sequence_reverse", {"X": x, "Length": lens})["Out"]
    ref = x.copy()
    for b, ln in enumerate(lens):
        ref[b, :ln] = x[b, :ln][::-1]
    np.testing.assert_array_equal(out, ref)
    full = run_op("sequence_reverse", {"X": x})["Out"]
    np.testing.assert_array_equal(full, x[:, ::-1])
    check_grad("sequence_reverse", {"X": x, "Length": lens}, "X",
               max_relative_error=1e-3)
