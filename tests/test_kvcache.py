"""Paged prefix-reuse KV cache (paddle_tpu/serving/kvcache.py) — block
pool refcount lifecycle, prefix-trie match/insert/copy-on-write fork,
LRU eviction under capacity pressure, and the engine-level bit-exact
served-vs-single-stream identity parameterized over prefix reuse on/off
and f32/bf16.  The slow tail additionally proves the
``PADDLE_TPU_PAGED_ATTN`` kill switch: the paged_attention kernel and
the decode_gather + dense-softmax spelling serve bit-identical tokens,
including through the speculative verify window.  All on the CPU
backend (conftest), tiny model shapes."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models import transformer
from paddle_tpu.observability import metrics as _obs
from paddle_tpu.serving import ServingEngine
from paddle_tpu.serving.kvcache import BlockPool, PoolExhausted, PrefixTrie


# -- block pool: refcount lifecycle -----------------------------------------

def test_pool_alloc_ref_deref_free():
    pool = BlockPool(num_blocks=6, block_tokens=4)
    assert pool.free_blocks == 5 and pool.blocks_in_use == 0
    a, b = pool.alloc(2)
    assert pool.blocks_in_use == 2
    assert pool.refcount(a) == pool.refcount(b) == 1
    pool.ref(a)                       # a second owner (trie or slot)
    assert pool.refcount(a) == 2
    pool.deref(a)
    assert pool.blocks_in_use == 2    # still held once
    pool.deref(a)
    pool.deref(b)
    assert pool.blocks_in_use == 0 and pool.free_blocks == 5


def test_pool_trash_block_pinned():
    pool = BlockPool(num_blocks=4, block_tokens=2)
    assert pool.refcount(BlockPool.TRASH) == 1
    pool.ref(BlockPool.TRASH)         # no-ops: trash is unaccounted
    pool.deref(BlockPool.TRASH)
    assert pool.refcount(BlockPool.TRASH) == 1
    got = pool.alloc(3)               # every real block
    assert BlockPool.TRASH not in got


def test_pool_exhausted_is_all_or_nothing():
    pool = BlockPool(num_blocks=4, block_tokens=2)
    pool.alloc(2)
    with pytest.raises(PoolExhausted):
        pool.alloc(2)                 # only 1 free
    assert pool.free_blocks == 1      # the failed alloc took nothing


def test_pool_double_free_rejected():
    pool = BlockPool(num_blocks=4, block_tokens=2)
    (b,) = pool.alloc(1)
    pool.deref(b)
    with pytest.raises(ValueError):
        pool.deref(b)
    with pytest.raises(ValueError):
        pool.ref(b)                   # can't revive a freed block


# -- prefix trie: match / insert / CoW / LRU --------------------------------

def _trie(num_blocks=32, block_tokens=4, budget=16):
    pool = BlockPool(num_blocks, block_tokens)
    return pool, PrefixTrie(pool, budget)


def test_trie_insert_then_full_match():
    pool, trie = _trie()
    toks = list(range(100, 112))      # 3 full blocks of 4
    bids = pool.alloc(3)
    assert trie.insert(toks, bids) == 3
    # trie holds one ref each; our allocation still holds the other
    assert all(pool.refcount(b) == 2 for b in bids)
    shared, cow, hit = trie.match(toks, limit=len(toks) - 1)
    # limit 11 caps the match at 2 full blocks + a 3-token CoW tail
    assert shared == bids[:2]
    assert cow == (bids[2], 3)
    assert hit == 11
    # an unrelated prompt misses entirely
    shared, cow, hit = trie.match(list(range(50, 62)), limit=11)
    assert shared == [] and cow is None and hit == 0


def test_trie_cow_partial_match():
    pool, trie = _trie()
    toks = list(range(100, 108))
    bids = pool.alloc(2)
    trie.insert(toks, bids)
    # diverge inside the second block: first block shared, second CoW
    fork = toks[:6] + [999, 998]
    shared, cow, hit = trie.match(fork, limit=len(fork) - 1)
    assert shared == [bids[0]]
    assert cow == (bids[1], 2)        # 2 common tokens into the block
    assert hit == 6
    # diverge inside the FIRST block: pure CoW, nothing fully shared
    fork2 = toks[:3] + [999] * 5
    shared, cow, hit = trie.match(fork2, limit=len(fork2) - 1)
    assert shared == [] and cow == (bids[0], 3) and hit == 3


def test_trie_duplicate_insert_keeps_existing():
    pool, trie = _trie()
    toks = list(range(100, 108))
    first = pool.alloc(2)
    trie.insert(toks, first)
    dup = pool.alloc(2)
    assert trie.insert(toks, dup) == 0      # chunks already cached
    assert all(pool.refcount(b) == 1 for b in dup)  # ours stays private
    shared, _, _ = trie.match(toks, limit=7)
    assert shared == [first[0]]


def test_trie_refcount_lifecycle_through_release():
    """The engine pattern: match -> ref -> (serve) -> deref leaves the
    trie's own references intact; clear() releases them."""
    pool, trie = _trie()
    toks = list(range(100, 108))
    bids = pool.alloc(2)
    trie.insert(toks, bids)
    for b in bids:                    # slot releases its own refs
        pool.deref(b)
    assert all(pool.refcount(b) == 1 for b in bids)   # trie-only now
    assert pool.blocks_in_use == 2
    trie.clear()
    assert pool.blocks_in_use == 0    # refcount zero -> freed


def test_trie_lru_eviction_under_capacity_pressure():
    pool, trie = _trie(num_blocks=32, block_tokens=4, budget=4)
    # insert three 2-block chains; budget 4 trie-only blocks forces the
    # LEAST RECENTLY USED chain's tail out
    chains = []
    for base in (100, 200, 300):
        toks = list(range(base, base + 8))
        bids = pool.alloc(2)
        trie.insert(toks, bids)
        for b in bids:
            pool.deref(b)             # trie-only
        trie.enforce_budget()         # the engine's release-path call
        chains.append((toks, bids))
    # chain 0 was least recently touched: its blocks evicted first
    assert len(trie) == 4
    s0, _, _ = trie.match(chains[0][0], limit=7)
    assert s0 == []                   # fully evicted
    s2, _, _ = trie.match(chains[2][0], limit=7)
    assert s2 == [chains[2][1][0]]    # most recent survives
    # every surviving trie block is still accounted, none leaked
    assert pool.blocks_in_use == len(trie)


def test_trie_never_evicts_slot_referenced_chain():
    pool, trie = _trie(num_blocks=32, block_tokens=4, budget=1)
    toks = list(range(100, 108))
    bids = pool.alloc(2)              # "slot" keeps its refs live
    trie.insert(toks, bids)
    trie.enforce_budget()             # budget 1 < 2 cached blocks, but
    shared, _, _ = trie.match(toks, limit=7)
    assert shared == [bids[0]]        # referenced chain untouched
    for b in bids:
        pool.deref(b)                 # slot leaves -> now evictable
    trie.enforce_budget()
    assert trie._trie_only_count() <= 1


def test_trie_evict_lru_frees_for_alloc():
    pool, trie = _trie(num_blocks=6, block_tokens=4, budget=8)
    bids = pool.alloc(4)
    trie.insert(list(range(100, 116)), bids)
    for b in bids:
        pool.deref(b)
    assert pool.free_blocks == 1
    with pytest.raises(PoolExhausted):
        pool.alloc(3)
    freed = trie.evict_lru(2)
    assert freed == 2
    assert len(pool.alloc(3)) == 3    # now fits


# -- engine-level: bit-exact identity with reuse on/off, f32 + bf16 ---------

VOCAB, NL, NH, DM, T = 50, 2, 2, 32, 32


def _make_params(dtype="float32"):
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        transformer.build(vocab_size=VOCAB, n_layer=NL, n_head=NH,
                          d_model=DM, max_len=T, dropout_rate=0.0,
                          dtype=dtype)
    exe = pt.Executor()
    exe.run(startup)
    return transformer.extract_params(program=main)


@pytest.fixture(autouse=True)
def fresh_serving_metrics():
    _obs.get_registry().clear(prefix="serving.")
    yield


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("reuse", [True, False])
def test_served_equals_single_stream_with_prefix_traffic(dtype, reuse):
    """The acceptance bar, now over the PAGED cache: shared-prefix
    traffic (full-block hits AND copy-on-write forks when reuse is on)
    through the batched engine produces exactly the tokens of running
    each request ALONE through transformer.generate — greedy, same
    weights, prefix reuse on or off, f32 and bf16."""
    params = _make_params(dtype)
    if dtype == "bfloat16":
        import jax.numpy as jnp

        params = {k: (jnp.asarray(v, jnp.bfloat16)
                      if (k.startswith("block") or k.startswith("lm_head"))
                      and k.endswith(".w") else v)
                  for k, v in params.items()}
    eng = ServingEngine(params, NL, NH, DM, max_len=T, max_slots=3,
                        decode_chunk=5, min_bucket=4, block_tokens=4,
                        prefix_reuse=reuse)
    rng = np.random.default_rng(7)
    base = rng.integers(1, VOCAB, (12,)).astype(np.int32)
    prompts = [
        base.copy(),                                   # cold
        base.copy(),                                   # full-block hits
        np.concatenate([base[:6],                      # CoW fork at 6
                        rng.integers(1, VOCAB, (5,)).astype(np.int32)]),
        rng.integers(1, VOCAB, (9,)).astype(np.int32),  # unrelated
        base[:10].copy(),                              # shorter re-serve
    ]
    # two waves so later requests hit chains the first wave cached
    outs = eng.generate_many(prompts[:2], max_new_tokens=8)
    outs += eng.generate_many(prompts[2:], max_new_tokens=8)
    for p, o in zip(prompts, outs):
        ref, _ = transformer.generate(params, p[None], max_len=T,
                                      n_layer=NL, n_head=NH, d_model=DM,
                                      return_logits=False)
        np.testing.assert_array_equal(o, np.asarray(ref)[0][: len(p) + 8])
    st = eng.stats()
    if reuse:
        assert st["serving.prefix_hit_rate"] > 0
        assert st["serving.cow_copies"] >= 1
    else:
        assert st.get("serving.prefix_hit_rate", 0.0) == 0.0
        assert eng.prefix_trie is None


# -- engine-level: paged-attention kill switch -------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("reuse", [True, False])
def test_paged_kill_switch_engine_bit_exact(monkeypatch, reuse):
    """PADDLE_TPU_PAGED_ATTN=0 (the decode_gather + dense-softmax
    oracle spelling) and =1 (the paged_attention kernel) serve
    bit-identical tokens, both equal to single-stream generate —
    prefix reuse on and off, CoW-fork traffic included.  The env var is
    read at trace time, so each setting gets a fresh engine; the
    kernel-backend recording proves which spelling actually compiled."""
    params = _make_params()
    rng = np.random.default_rng(21)
    base = rng.integers(1, VOCAB, (11,)).astype(np.int32)
    prompts = [
        base.copy(),
        np.concatenate([base[:6],                      # CoW fork at 6
                        rng.integers(1, VOCAB, (4,)).astype(np.int32)]),
        rng.integers(1, VOCAB, (8,)).astype(np.int32),
    ]

    def serve(env):
        _obs.get_registry().clear(prefix="serving.")
        monkeypatch.setenv("PADDLE_TPU_PAGED_ATTN", env)
        eng = ServingEngine(params, NL, NH, DM, max_len=T, max_slots=3,
                            decode_chunk=4, min_bucket=4, block_tokens=4,
                            prefix_reuse=reuse)
        return eng.generate_many(prompts, max_new_tokens=7), eng

    paged_outs, paged_eng = serve("1")
    assert any("paged_attention" in sel
               for sel in paged_eng.kernel_backends.values())
    assert paged_eng.stats()["serving.paged_attn_compiles"] >= 1
    gather_outs, gather_eng = serve("0")
    assert all("paged_attention" not in sel
               for sel in gather_eng.kernel_backends.values())
    assert "serving.paged_attn_compiles" not in gather_eng.stats()
    for p, a, b in zip(prompts, paged_outs, gather_outs):
        np.testing.assert_array_equal(a, b)
        ref, _ = transformer.generate(params, p[None], max_len=T,
                                      n_layer=NL, n_head=NH, d_model=DM,
                                      return_logits=False)
        np.testing.assert_array_equal(a, np.asarray(ref)[0][: len(p) + 7])


@pytest.mark.slow
def test_spec_parity_through_paged_verify_window(monkeypatch):
    """Speculative decoding scores its draft windows through the paged
    kernel (W = k+1 is the multi-token shape): committed tokens are
    identical to the PADDLE_TPU_PAGED_ATTN=0 spec engine and to plain
    greedy decode, with speculative rounds actually run."""
    from paddle_tpu.serving import speculative as spec

    params = _make_params()
    rng = np.random.default_rng(22)
    prompts = [rng.integers(1, VOCAB, (l,)).astype(np.int32)
               for l in (5, 9, 7)]

    def serve(env):
        _obs.get_registry().clear(prefix="serving.")
        monkeypatch.setenv("PADDLE_TPU_PAGED_ATTN", env)
        eng = ServingEngine(params, NL, NH, DM, max_len=T, max_slots=3,
                            decode_chunk=4, min_bucket=4, block_tokens=4,
                            draft_params=spec.depth_draft(params, 1),
                            spec_k=3)
        outs = eng.generate_many(prompts, max_new_tokens=8)
        assert eng._spec.proposed > 0
        return outs

    paged, gather = serve("1"), serve("0")
    for p, a, b in zip(prompts, paged, gather):
        np.testing.assert_array_equal(a, b)
        ref, _ = transformer.generate(params, p[None], max_len=T,
                                      n_layer=NL, n_head=NH, d_model=DM,
                                      return_logits=False)
        np.testing.assert_array_equal(a, np.asarray(ref)[0][: len(p) + 8])


def test_engine_pool_accounting_no_leak():
    """Every served request returns its blocks: with reuse OFF the pool
    drains to zero; with reuse ON exactly the trie-held blocks remain
    and clear() returns the pool to empty."""
    params = _make_params()
    rng = np.random.default_rng(8)
    prompts = [rng.integers(1, VOCAB, (l,)).astype(np.int32)
               for l in (9, 9, 12, 5, 7)]
    for reuse in (False, True):
        _obs.get_registry().clear(prefix="serving.")
        eng = ServingEngine(params, NL, NH, DM, max_len=T, max_slots=2,
                            decode_chunk=4, min_bucket=4, block_tokens=4,
                            prefix_reuse=reuse)
        eng.generate_many(prompts, max_new_tokens=6)
        # the gauge tracks the pool at every engine release point
        st = eng.stats()
        assert st["serving.blocks_in_use"] == eng.kv_pool.blocks_in_use
        if reuse:
            assert eng.kv_pool.blocks_in_use == len(eng.prefix_trie)
            eng.prefix_trie.clear()
        assert eng.kv_pool.blocks_in_use == 0
        assert eng.kv_pool.free_blocks == eng.kv_pool.num_blocks - 1


def test_engine_trie_respects_cache_budget():
    """cache_blocks is a hard budget on trie-only blocks: heavy
    distinct-prefix traffic cannot grow the cache past it (LRU chains
    evict instead)."""
    params = _make_params()
    eng = ServingEngine(params, NL, NH, DM, max_len=T, max_slots=2,
                        decode_chunk=4, min_bucket=4, block_tokens=4,
                        cache_blocks=3, prefix_reuse=True)
    rng = np.random.default_rng(9)
    prompts = [rng.integers(1, VOCAB, (12,)).astype(np.int32)
               for _ in range(6)]
    eng.generate_many(prompts, max_new_tokens=4)
    assert eng.prefix_trie._trie_only_count() <= 3
    assert eng.kv_pool.blocks_in_use == len(eng.prefix_trie)


def test_engine_prefix_hit_reduces_prefill_tokens():
    """The compute claim behind reuse: identical prompts the second
    time around scan strictly fewer prefill tokens, bit-exactness
    already covered above."""
    params = _make_params()
    rng = np.random.default_rng(10)
    base = rng.integers(1, VOCAB, (12,)).astype(np.int32)

    def served_prefill_tokens(reuse):
        _obs.get_registry().clear(prefix="serving.")
        eng = ServingEngine(params, NL, NH, DM, max_len=T, max_slots=2,
                            decode_chunk=4, min_bucket=4, block_tokens=4,
                            prefix_reuse=reuse)
        eng.generate_many([base.copy()], max_new_tokens=4)
        eng.generate_many([base.copy(), base.copy()], max_new_tokens=4)
        return eng.stats()["serving.prefill_tokens"]

    assert served_prefill_tokens(True) < served_prefill_tokens(False)
