"""Static-analysis engine tests (ISSUE 6 tentpole).

Every seeded check fires on a small deliberately-broken Program with the
exact finding id and severity; the clean GPT benchmark program lints to
ZERO findings; strict mode raises; the memaudit compatibility shims
still answer; and the Executor folds compile-time findings into
``last_step_cost`` / the trainer JSONL.  CPU-only, nothing executes a
training step — the engine's whole point is static judgment
(docs/analysis.md).
"""

import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import analysis, layers
from paddle_tpu.models import transformer

# layer count must differ from batch (2), heads (2) AND b*h (4) so the
# leading-axis probes are unambiguous (the test_memory_engine convention)
N_LAYER = 5
T, D = 12, 32


def _small_gpt(policy=None, dtype="float32", n_layer=N_LAYER):
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 7
    with pt.program_guard(main, startup):
        outs = transformer.build(vocab_size=29, n_layer=n_layer, n_head=2,
                                 d_model=D, max_len=T, dropout_rate=0.0,
                                 dtype=dtype)
    if policy:
        pt.memory_optimize(main, policy=policy)
    return main, startup, outs["avg_cost"]


def _feed(seed=3):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, 29, (2, T)).astype(np.int64)
    return {"tokens": toks, "labels": np.roll(toks, -1, axis=1)}


# -- program-level checks ---------------------------------------------------

def _planted_program():
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        y = layers.fc(x, 2, name="live")
        layers.fc(x, 3, name="deadfc")
        blk = main.global_block()
        blk.create_var(name="orphan", shape=(3,), dtype="float32")
        a = blk.create_var(name="a", shape=(-1, 4), dtype="float32")
        b = blk.create_var(name="b", shape=(-1, 8), dtype="float32")
        c = blk.create_var(name="c", shape=(-1, 4), dtype="float32")
        blk.append_op("elementwise_add", {"X": [a.name], "Y": [b.name]},
                      {"Out": [c.name]})
        blk.append_op("relu", {"X": [x.name]}, {"Out": [y.name]})
    return main, y


def test_dead_code_ops_and_vars():
    main, y = _planted_program()
    rep = analysis.lint(main, fetch_list=[y], levels=("program",))
    dead = rep.by_check("program.dead-code")
    assert dead and all(f.severity == "warning" for f in dead)
    msgs = " ".join(f.message for f in dead)
    assert "deadfc" in msgs          # the dead op chain
    assert "orphan" in msgs          # the orphan declaration
    assert all(f.level == "program" for f in dead)


def test_shape_dtype_mismatch_is_error():
    main, y = _planted_program()
    rep = analysis.lint(main, fetch_list=[y], levels=("program",))
    sd = rep.by_check("program.shape-dtype")
    assert len(sd) == 1 and sd[0].severity == "error"
    assert "4" in sd[0].message and "8" in sd[0].message


def test_read_before_write_is_error():
    main, y = _planted_program()
    rep = analysis.lint(main, fetch_list=[y], levels=("program",))
    rbw = rep.by_check("program.read-before-write")
    assert {f.severity for f in rbw} == {"error"}
    read = " ".join(f.message for f in rbw)
    assert "'a'" in read and "'b'" in read


def test_fetch_overwritten_warning():
    main, y = _planted_program()
    rep = analysis.lint(main, fetch_list=[y], levels=("program",))
    fo = rep.by_check("program.fetch-overwritten")
    assert len(fo) == 1 and fo[0].severity == "warning"
    assert "LAST write" in fo[0].message


def test_grad_reads_after_backward_marker_allowed():
    """Optimizer ops read <param>@GRAD which no op writes — the Executor
    injects them; the read-before-write check must not fire."""
    main, _startup, loss = _small_gpt()
    rep = analysis.lint(main, fetch_list=[loss], levels=("program",))
    assert rep.by_check("program.read-before-write") == []


def test_strict_mode_raises():
    main, y = _planted_program()
    with pytest.raises(analysis.AnalysisError) as ei:
        analysis.lint(main, fetch_list=[y], levels=("program",),
                      strict=True)
    assert "program.read-before-write" in str(ei.value)
    # warnings alone never raise
    main2, _s, loss = _small_gpt()
    analysis.lint(main2, fetch_list=[loss], levels=("program",),
                  strict=True)


# -- jaxpr-level checks -----------------------------------------------------

def test_scan_locality_fires_when_scan_engine_off(monkeypatch):
    main, _startup, loss = _small_gpt("selective")
    monkeypatch.setenv("PADDLE_TPU_SCAN_REMAT", "0")
    rep = analysis.lint(main, feed=_feed(), fetch_list=[loss],
                        levels=("jaxpr",), layer_count=N_LAYER)
    sl = rep.by_check("jaxpr.scan-locality")
    assert sl and sl[0].severity == "error"
    assert "outside" in " ".join(f.message for f in sl)


def test_scan_locality_clean_when_engine_on():
    main, _startup, loss = _small_gpt("selective")
    rep = analysis.lint(main, feed=_feed(), fetch_list=[loss],
                        levels=("jaxpr",), layer_count=N_LAYER)
    assert rep.by_check("jaxpr.scan-locality") == []


def test_bf16_accum_scan_carry():
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("xb", shape=[16, 8], dtype="bfloat16")
        init = layers.reduce_mean(x, dim=1)
        rnn = layers.StaticRNN(name="acc")
        with rnn.step():
            xt = rnn.step_input(x)
            acc = rnn.memory(init)
            new = acc + xt
            rnn.update_memory(acc, new)
            rnn.step_output(new)
        tot = layers.reduce_sum(rnn())
    rep = analysis.lint(main, fetch_list=[tot], levels=("jaxpr",))
    ba = rep.by_check("jaxpr.bf16-accum")
    assert len(ba) == 1 and ba[0].severity == "warning"
    assert "bfloat16 carry" in ba[0].message
    assert ba[0].data["scan_length"] == 16


def test_bf16_accum_quiet_on_f32_carry():
    """The same accumulator carried in f32 (the framework's own
    spelling) must not fire."""
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("xf", shape=[16, 8], dtype="float32")
        init = layers.reduce_mean(x, dim=1)
        rnn = layers.StaticRNN(name="acc")
        with rnn.step():
            xt = rnn.step_input(x)
            acc = rnn.memory(init)
            new = acc + xt
            rnn.update_memory(acc, new)
            rnn.step_output(new)
        tot = layers.reduce_sum(rnn())
    rep = analysis.lint(main, fetch_list=[tot], levels=("jaxpr",))
    assert rep.by_check("jaxpr.bf16-accum") == []


def test_tanh_gelu_reassociation_hazard():
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[16])
        h = x
        for i in range(4):
            h = layers.fc(h, 16, act="tanh", name=f"l{i}")
        loss = layers.reduce_mean(layers.fc(h, 1, name="head"))
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    pt.memory_optimize(main, policy="full")
    rep = analysis.lint(main, fetch_list=[loss], levels=("jaxpr",))
    tg = rep.by_check("jaxpr.tanh-gelu")
    assert len(tg) == 1 and tg[0].severity == "warning"
    assert "erf" in tg[0].hint


def test_kernel_residual_offload_degraded():
    """offload on a program with no uniform scan group silently degrades
    to selective — the lint surfaces it."""
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[16])
        h = layers.fc(x, 12, act="relu", name="a1")
        h = layers.fc(h, 6, act="sigmoid", name="b1")
        loss = layers.reduce_mean(layers.fc(h, 1, name="c1"))
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    pt.memory_optimize(main, policy="offload")
    rep = analysis.lint(main, fetch_list=[loss], levels=("jaxpr",))
    kr = rep.by_check("jaxpr.kernel-residual")
    assert kr and kr[0].severity == "warning"
    from paddle_tpu.analysis.jaxpr_tools import BLOCK_INPUT_TAG

    assert BLOCK_INPUT_TAG in kr[0].message


def test_kernel_residual_quiet_on_clean_offload():
    main, _startup, loss = _small_gpt("offload")
    rep = analysis.lint(main, feed=_feed(), fetch_list=[loss],
                        levels=("jaxpr",), layer_count=N_LAYER)
    assert rep.by_check("jaxpr.kernel-residual") == []


# -- hlo-level checks -------------------------------------------------------

def test_hbm_preflight_over_budget():
    main, _startup, loss = _small_gpt()
    rep = analysis.lint(main, feed=_feed(), fetch_list=[loss],
                        levels=("hlo",), hbm_budget=1)
    hp = rep.by_check("hlo.hbm-preflight")
    assert len(hp) == 1 and hp[0].severity == "error"
    assert hp[0].message.startswith("RESOURCE_EXHAUSTED (preflight)")
    assert hp[0].data["budget_bytes"] == 1


def test_preflight_hbm_helper():
    assert analysis.preflight_hbm(None, 100) == []
    assert analysis.preflight_hbm(50, None) == []
    assert analysis.preflight_hbm(50, 100) == []
    (f,) = analysis.preflight_hbm(200, 100, context="t=16384")
    assert f.check == "hlo.hbm-preflight" and f.severity == "error"
    assert "t=16384" in f.message


def test_donation_findings_pure():
    fire = analysis.donation_findings(
        {"argument_bytes": 5 << 20, "alias_bytes": 0}, True)
    assert [f.check for f in fire] == ["hlo.donation-alias"]
    assert fire[0].severity == "warning"
    # aliased, tiny, or donation-off: quiet
    assert analysis.donation_findings(
        {"argument_bytes": 5 << 20, "alias_bytes": 4 << 20}, True) == []
    assert analysis.donation_findings(
        {"argument_bytes": 1 << 10, "alias_bytes": 0}, True) == []
    assert analysis.donation_findings(
        {"argument_bytes": 5 << 20, "alias_bytes": 0}, False) == []


_INLOOP_HLO = """\
HloModule planted, entry_computation_layout={(f32[8])->f32[8]}

%body.1 (p: (s32[], f32[8])) -> (s32[], f32[8]) {
  %p = (s32[], f32[8]) parameter(0)
  %g = f32[8] get-tuple-element((s32[], f32[8]) %p), index=1
  %ar = f32[8] all-reduce(f32[8] %g), replica_groups={}, to_apply=%sum.2
}

%cond.3 (p: (s32[], f32[8])) -> pred[] {
  %p = (s32[], f32[8]) parameter(0)
}

ENTRY %main.4 (a: f32[8]) -> f32[8] {
  %a = f32[8] parameter(0)
  %w = (s32[], f32[8]) while((s32[], f32[8]) %t), condition=%cond.3, body=%body.1
  %out = f32[8] all-reduce(f32[8] %gte), replica_groups={}, to_apply=%sum.2
}
"""


def test_inloop_collective_error_and_expected():
    from paddle_tpu.analysis.hlo_tools import hlo_comm_report

    comm = hlo_comm_report(_INLOOP_HLO)
    assert comm["reduce_ops_in_loop"] == 1 and comm["reduce_ops"] == 2
    ctx = analysis.CheckContext(None).seed("comm", comm)
    from paddle_tpu.analysis.hlo_checks import inloop_collective

    fs = list(inloop_collective(ctx))
    assert [f.check for f in fs] == ["hlo.inloop-collective"]
    assert fs[0].severity == "error"
    # run_steps fuses steps into one loop: the expected in-loop reduce
    # must produce NO finding (not even the gather-class info)
    ctx2 = analysis.CheckContext(None, in_loop_expected=True)
    ctx2.seed("comm", comm)
    assert list(inloop_collective(ctx2)) == []
    # genuine gather-class in-loop collectives still report as info
    ctx3 = analysis.CheckContext(None, in_loop_expected=True)
    ctx3.seed("comm", dict(comm, collectives_in_loop=3))
    fs3 = list(inloop_collective(ctx3))
    assert [f.severity for f in fs3] == ["info"]


# -- the clean program ------------------------------------------------------

@pytest.mark.parametrize("policy", [None, "selective", "offload"])
def test_clean_gpt_zero_findings(policy):
    """The GPT benchmark program lints to ZERO findings at every level,
    under no policy and under the remat policies the flagship runs."""
    main, _startup, loss = _small_gpt(policy)
    rep = analysis.lint(main, feed=_feed(), fetch_list=[loss],
                        layer_count=N_LAYER)
    assert rep.findings == [], [repr(f) for f in rep.findings]


# -- framework / registry ---------------------------------------------------

def test_registry_has_seeded_checks():
    ids = {s.id for s in analysis.registered_checks()}
    assert {
        "program.dead-code", "program.shape-dtype",
        "program.read-before-write", "program.fetch-overwritten",
        "jaxpr.scan-locality", "jaxpr.kernel-residual",
        "jaxpr.bf16-accum", "jaxpr.tanh-gelu",
        "hlo.inloop-collective", "hlo.donation-alias",
        "hlo.hbm-preflight",
    } <= ids
    by_level = {lvl: [s for s in analysis.registered_checks(lvl)]
                for lvl in analysis.LEVELS}
    assert all(by_level.values())
    with pytest.raises(ValueError):
        analysis.register_check("program.dead-code", "program")(
            lambda ctx: [])


def test_unknown_level_rejected():
    """A typo'd level must raise, not silently run zero checks and
    report success."""
    main, y = _planted_program()
    with pytest.raises(ValueError, match="porgram"):
        analysis.lint(main, fetch_list=[y], levels=("porgram",))


def test_report_api_and_serialization():
    main, y = _planted_program()
    rep = analysis.lint(main, fetch_list=[y], levels=("program",))
    assert not rep.ok and len(rep.errors) >= 1
    d = rep.to_dict()
    assert d["ok"] is False
    assert len(d["findings"]) == len(rep)
    assert "error" in rep.summary()
    f = rep.findings[0]
    assert set(f.to_dict()) >= {"check", "severity", "level", "location",
                                "message", "hint"}


def test_artifact_failure_reported_not_raised():
    """A program whose trace fails (read of a missing var) must not kill
    lint — jaxpr/hlo checks report one artifact-skip info finding."""
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        blk = main.global_block()
        out = blk.create_var(name="o", shape=(4,), dtype="float32")
        blk.append_op("relu", {"X": ["never_written"]},
                      {"Out": [out.name]})
    rep = analysis.lint(main, fetch_list=[out])
    assert rep.by_check("program.read-before-write")  # the root cause
    art = rep.by_check("analysis.artifact")
    assert art and all(f.severity == "info" for f in art)


# -- the retired memaudit shim surface --------------------------------------

def test_memaudit_shims_deleted():
    """The deprecated ``core/memaudit.py`` shim module is GONE (ISSUE 14
    satellite — PR 11 had already migrated every in-repo caller): the
    module neither exists on disk nor imports, and no in-repo file
    mentions it in an import statement.  The analysis package no longer
    re-exports its parity surface either — tools import from
    ``analysis.hlo_tools`` / ``analysis.jaxpr_tools`` directly."""
    import importlib
    import re

    with pytest.raises(ImportError):
        importlib.import_module("paddle_tpu.core.memaudit")
    root = os.path.dirname(os.path.dirname(os.path.abspath(
        pt.__file__)))
    assert not os.path.exists(os.path.join(
        root, "paddle_tpu", "core", "memaudit.py"))
    offenders = []
    for dirpath, _dirs, files in os.walk(root):
        if any(part in dirpath for part in
               ("__pycache__", ".git", "/.claude", ".venv", "venv",
                "site-packages", "node_modules", "/build")):
            continue
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            if fn == "test_analysis.py":
                continue  # this contract test
            src = open(path, "r", encoding="utf-8",
                       errors="ignore").read()
            if re.search(r"^\s*(from|import)\s+[\w.]*memaudit",
                         src, re.MULTILINE):
                offenders.append(os.path.relpath(path, root))
    assert not offenders, offenders
    # the memaudit-parity names no longer ride the package namespace
    for gone in ("hlo_comm_report", "comm_report",
                 "compiled_memory_stats", "jaxpr_report", "walk_report",
                 "KERNEL_RESIDUAL_TAG", "BLOCK_INPUT_TAG",
                 "REDUCE_COLLECTIVES", "shape_pattern"):
        assert not hasattr(analysis, gone), gone


def test_audit_program_entry_point():
    """``analysis.audit_program`` (the real PR-4 audit entry point, not
    a shim) keeps its contract after the shim deletion."""
    main, startup, loss = _small_gpt("selective")
    scope = pt.Scope()
    with pt.core.scope.scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup, scope=scope)
        rep = analysis.audit_program(main, _feed(), [loss], scope=scope,
                                     layer_count=N_LAYER,
                                     absent_shapes=[(N_LAYER, T, D)])
    assert rep["pallas_total"] > 0
    assert not rep["layer_stacked_pallas"]
    assert rep["temp_bytes"] > 0 and rep["hbm_high_water_bytes"] > 0
    assert all(v == 0 for v in rep["absent_shape_hits"].values())
    assert any("fallback" not in p for p in rep["scan_remat_plan"])


# -- executor / reporter fold-in --------------------------------------------

def test_executor_folds_findings_into_step_cost():
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        y = layers.fc(x, 2, name="live")
        layers.fc(x, 3, name="deadfc")  # dead, but lowerable
    scope = pt.Scope()
    with pt.core.scope.scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup, scope=scope)
        exe.run(main, feed={"x": np.zeros((2, 4), np.float32)},
                fetch_list=[y], scope=scope)
    cost = exe.last_step_cost
    assert cost["lint_findings"] >= 1
    assert "program.dead-code" in cost.get("lint_checks", [])
    assert cost["lint_errors"] == 0


def test_executor_lint_kill_switch(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_LINT", "0")
    main, _startup, loss = _small_gpt()
    scope = pt.Scope()
    with pt.core.scope.scope_guard(scope):
        exe = pt.Executor()
        exe.run(_startup, scope=scope)
        exe.run(main, feed=_feed(), fetch_list=[loss], scope=scope)
    assert "lint_findings" not in exe.last_step_cost


def test_reporter_jsonl_carries_lint_fields(tmp_path):
    from paddle_tpu.observability import MetricsReporter, read_jsonl

    class EndIteration:
        pass

    ev = EndIteration()
    ev.pass_id, ev.batch_id, ev.cost, ev.metrics = 0, 0, 0.5, []
    ev.wall_time, ev.samples, ev.throughput = 0.01, 4, 400.0
    ev.mfu, ev.reader_wait = None, None
    ev.step_cost = {"cache_hit": False, "lint_findings": 2,
                    "lint_errors": 1,
                    "lint_checks": ["program.dead-code"]}
    path = str(tmp_path / "run.jsonl")
    rep = MetricsReporter(log_every_n=0, jsonl_path=path)
    rep(ev)
    rep.close()
    recs = [r for r in read_jsonl(path) if r.get("event") == "step"]
    assert recs[0]["lint_findings"] == 2
    assert recs[0]["lint_errors"] == 1
    assert recs[0]["lint_checks"] == ["program.dead-code"]
