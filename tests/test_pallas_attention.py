"""Flash-attention kernel tests: Pallas (interpret mode on CPU) vs the dense
reference, forward and backward — the cross-device comparison pattern of the
reference's function/*OpTest.cpp suites."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas_attention import (
    attention_reference,
    flash_attention,
)


def _inputs(b=2, tq=16, tk=16, h=2, d=8, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, tq, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, tk, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, tk, h, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _inputs()
    out = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_cross_attention_shapes():
    q, k, v = _inputs(tq=8, tk=24)
    out = flash_attention(q, k, v, block_q=4, block_k=8)
    ref = attention_reference(q, k, v)
    assert out.shape == (2, 8, 2, 8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_uneven_block_fallback():
    # t not divisible by requested block: _pick_block shrinks to a divisor
    q, k, v = _inputs(tq=12, tk=20)
    out = flash_attention(q, k, v, block_q=8, block_k=8)
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_reference(causal):
    q, k, v = _inputs(b=1, tq=8, tk=8, h=1, d=4)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=4, block_k=4)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = attention_reference(q, k, v, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-5, rtol=5e-4,
            err_msg=f"grad wrt {name}",
        )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bf16_and_d128(causal):
    """bf16 inputs take the bf16 MXU-feed path; d=128 heads (the MFU
    config) must be numerically sound fwd+bwd vs an f32 dense reference."""
    rng = np.random.default_rng(7)
    b, t, h, d = 1, 64, 2, 128
    qf, kf, vf = (jnp.asarray(rng.normal(size=(b, t, h, d)) * 0.5,
                              jnp.float32) for _ in range(3))
    q, k, v = (x.astype(jnp.bfloat16) for x in (qf, kf, vf))
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    ref = attention_reference(qf, kf, vf, causal=causal)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2, rtol=3e-2)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(qf, kf, vf)
    for gf, gr, nm in zip(g_flash, g_ref, "qkv"):
        # bf16 ~ 3 decimal digits; compare against the row scale
        scale = np.maximum(np.abs(np.asarray(gr)).max(), 1.0)
        np.testing.assert_allclose(
            np.asarray(gf, np.float32) / scale, np.asarray(gr) / scale,
            atol=4e-2, err_msg=f"grad wrt {nm}")


def test_flash_attention_op_registered():
    from tests.op_test import run_op

    q, k, v = _inputs(b=1, tq=8, tk=8, h=1, d=4)
    out = run_op(
        "flash_attention",
        {"Q": np.asarray(q), "K": np.asarray(k), "V": np.asarray(v)},
        attrs={"causal": True},
    )
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out["Out"], np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


def test_flash_attention_jit_under_program():
    """The kernel works inside a jitted step function."""
    q, k, v = _inputs(b=1, tq=8, tk=8, h=1, d=4)

    @jax.jit
    def step(q, k, v):
        return flash_attention(q, k, v)

    np.testing.assert_allclose(
        np.asarray(step(q, k, v)),
        np.asarray(attention_reference(q, k, v)),
        atol=2e-5, rtol=2e-5,
    )


def test_flash_cross_attention_causal_tq_gt_tk():
    """Regression: causal cross-attention with t_q > t_k — q blocks whose
    diagonal lies beyond the last k block must still finalize (the 3-D
    grid kernel's last_kb needs clamping to nk-1)."""
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(2, 16, 2, 8) * 0.5, jnp.float32)
    k = jnp.asarray(rng.randn(2, 8, 2, 8) * 0.5, jnp.float32)
    v = jnp.asarray(rng.randn(2, 8, 2, 8), jnp.float32)
    o = flash_attention(q, k, v, causal=True, block_q=8, block_k=8,
                        interpret=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    ga = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=True, block_q=8, block_k=8, interpret=True)),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(lambda q, k, v: attention_reference(
        q, k, v, causal=True)), argnums=(0, 1, 2))(q, k, v)
    for a, r in zip(ga, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=2e-4, atol=2e-5)


def test_flash_attention_with_lse_matches_dense_including_lse_grads():
    """o, lse, and gradients THROUGH lse (the ring-merge path) vs dense."""
    rng = np.random.RandomState(0)
    b, t, h, d = 2, 64, 2, 16
    q, k, v = (jnp.asarray(rng.randn(b, t, h, d) * 0.5, jnp.float32)
               for _ in range(3))
    from paddle_tpu.ops.pallas_attention import flash_attention_with_lse

    def dense_with_lse(q, k, v, causal):
        scale = d ** -0.5
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        if causal:
            mask = jnp.tril(jnp.ones((t, t), bool))
            s = jnp.where(mask[None, None], s, -1e30)
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        p = jnp.exp(s - lse[..., None])
        return jnp.einsum("bhqk,bkhd->bqhd", p, v), lse

    for causal in (False, True):
        o1, l1 = flash_attention_with_lse(q, k, v, causal=causal,
                                          block_q=16, block_k=16,
                                          interpret=True)
        o2, l2 = dense_with_lse(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                                   rtol=1e-5, atol=1e-5)

        def loss(fn):
            def f(q, k, v):
                o, lse = fn(q, k, v)
                return jnp.sum(o ** 2) + jnp.sum(jnp.sin(lse))
            return f

        ga = jax.grad(loss(lambda q, k, v: flash_attention_with_lse(
            q, k, v, causal=causal, block_q=16, block_k=16,
            interpret=True)), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss(lambda q, k, v: dense_with_lse(q, k, v, causal)),
                      argnums=(0, 1, 2))(q, k, v)
        for a, r in zip(ga, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_split_bwd_matches_fused(causal, monkeypatch):
    """The long-context backward (split dq + dkv kernels, used when the
    fused kernel's dq partials exceed budget) stays in lockstep with the
    fused backward and the dense reference."""
    from paddle_tpu.ops import pallas_attention as pa

    q, k, v = _inputs(b=1, tq=16, tk=16, h=2, d=4)

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=4, block_k=4)
        return jnp.sum(o * jnp.cos(o))

    g_fused = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setattr(pa, "FUSED_BWD_PARTIAL_BYTES", 0)
    g_split = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    def loss_ref(q, k, v):
        o = attention_reference(q, k, v, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gs, gr, name in zip(g_fused, g_split, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gs), np.asarray(gr),
                                   atol=5e-5, rtol=5e-4,
                                   err_msg=f"split grad wrt {name}")
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gs),
                                   atol=5e-5, rtol=5e-4,
                                   err_msg=f"fused vs split wrt {name}")


@pytest.mark.parametrize("causal", [False, True])
def test_flash_packed_matches_4d_values_and_grads(causal):
    """The packed-layout kernel ([b, t, h*d], heads as lane slices in the
    block index maps) is bit-identical to the 4-D path (same math,
    same blocks — only block index maps differ), values and gradients."""
    from paddle_tpu.ops.pallas_attention import flash_attention_packed

    b, t, h, d = 2, 64, 2, 8
    q, k, v = _inputs(b=b, tq=t, tk=t, h=h, d=d, seed=3)
    pk = lambda x: x.reshape(b, t, h * d)

    out4 = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    outp = flash_attention_packed(pk(q), pk(k), pk(v), h, causal=causal,
                                  block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(outp), np.asarray(pk(out4)),
                               atol=1e-6, rtol=1e-5)

    def l4(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, block_q=16,
                                       block_k=16) ** 2)

    def lp(q, k, v):
        return jnp.sum(flash_attention_packed(q, k, v, h, causal=causal,
                                              block_q=16, block_k=16) ** 2)

    g4 = jax.grad(l4, (0, 1, 2))(q, k, v)
    gp = jax.grad(lp, (0, 1, 2))(pk(q), pk(k), pk(v))
    for a, b_ in zip(g4, gp):
        np.testing.assert_allclose(np.asarray(b_), np.asarray(pk(a)),
                                   atol=1e-5, rtol=1e-5)


def test_flash_packed_split_bwd_matches_fused(monkeypatch):
    """Packed layout through the long-context split dq/dkv kernels (budget
    forced to 0) agrees with the fused backward."""
    import paddle_tpu.ops.pallas_attention as pa

    b, t, h, d = 1, 64, 2, 8
    q, k, v = _inputs(b=b, tq=t, tk=t, h=h, d=d, seed=5)
    pk = lambda x: x.reshape(b, t, h * d)

    def lp(q, k, v):
        return jnp.sum(pa.flash_attention_packed(
            q, k, v, h, causal=True, block_q=16, block_k=16) ** 2)

    g_fused = jax.grad(lp, (0, 1, 2))(pk(q), pk(k), pk(v))
    monkeypatch.setattr(pa, "FUSED_BWD_PARTIAL_BYTES", 0)
    g_split = jax.grad(lp, (0, 1, 2))(pk(q), pk(k), pk(v))
    for a, b_ in zip(g_fused, g_split):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-5, rtol=1e-5)


def test_flash_packed_head_width_guard():
    """d_head not lane-aligned (and n_head > 1) is a clear error, not a
    Mosaic crash."""
    from paddle_tpu.ops.pallas_attention import flash_attention_packed

    x = jnp.zeros((1, 16, 2 * 8), jnp.float32)
    with pytest.raises(ValueError, match="d_head % 128"):
        flash_attention_packed(x, x, x, 2, interpret=False)


def test_flash_attention_packed_op_registered():
    from tests.op_test import run_op

    b, t, h, d = 1, 16, 1, 4
    q, k, v = _inputs(b=b, tq=t, tk=t, h=h, d=d)
    pk = lambda x: np.asarray(x).reshape(b, t, h * d)
    out = run_op(
        "flash_attention_packed",
        {"Q": pk(q), "K": pk(k), "V": pk(v)},
        attrs={"n_head": h, "causal": True},
    )
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out["Out"], pk(ref), atol=2e-5, rtol=2e-5)


def test_packed_geometry_paths_pinned():
    """THE geometry decision table (ISSUE 3): which code path each
    (n_head, d_head) takes — one lane-aligned head per slice, two paired
    d=64 heads per slice, or no packed spelling at all (4-D fallback)."""
    from paddle_tpu.ops.pallas_attention import packed_sub_heads

    assert packed_sub_heads(6, 128) == 1    # flagship: lane-aligned
    assert packed_sub_heads(1, 8) == 1      # single head: whole feature
    assert packed_sub_heads(12, 64) == 2    # d64: two heads per slice
    assert packed_sub_heads(4, 64) == 2
    assert packed_sub_heads(3, 64) is None  # odd head count can't pair
    assert packed_sub_heads(2, 8) is None   # narrow heads: 4-D fallback
    assert packed_sub_heads(2, 256) == 1

    # the layer builder must route accordingly
    import paddle_tpu as pt
    from paddle_tpu import layers

    def attn_ops(d_model, n_head):
        pt.core.unique_name.reset()
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", shape=[16, d_model])
            layers.multi_head_attention(x, x, x, d_model=d_model,
                                        n_head=n_head, causal=True)
        return {op.type for op in main.global_block().ops}

    assert "flash_attention_packed" in attn_ops(256, 2)   # dh=128
    assert "flash_attention_packed" in attn_ops(128, 2)   # dh=64 paired
    assert "flash_attention" in attn_ops(48, 3)           # dh=16 fallback
    assert "flash_attention_packed" not in attn_ops(48, 3)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_packed_d64_paired_matches_reference(causal):
    """d_head=64 packed layout (two heads per 128-lane slice, sub_heads=2
    kernels): values and gradients vs the dense reference."""
    from paddle_tpu.ops.pallas_attention import flash_attention_packed

    rng = np.random.default_rng(9)
    b, t, h, d = 2, 32, 4, 64
    q4, k4, v4 = (jnp.asarray(rng.normal(size=(b, t, h, d)) * 0.5,
                              jnp.float32) for _ in range(3))
    pk = lambda x: x.reshape(b, t, h * d)
    outp = flash_attention_packed(pk(q4), pk(k4), pk(v4), h, causal=causal,
                                  block_q=16, block_k=16)
    ref = attention_reference(q4, k4, v4, causal=causal)
    np.testing.assert_allclose(np.asarray(outp), np.asarray(pk(ref)),
                               atol=2e-5, rtol=2e-5)

    def lp(q, k, v):
        return jnp.sum(flash_attention_packed(
            q, k, v, h, causal=causal, block_q=16, block_k=16) ** 2)

    def lr(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

    gp = jax.grad(lp, (0, 1, 2))(pk(q4), pk(k4), pk(v4))
    gr = jax.grad(lr, (0, 1, 2))(q4, k4, v4)
    for a, r, nm in zip(gp, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(pk(r)),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"paired grad wrt {nm}")


def test_flash_packed_d64_split_bwd_matches_fused(monkeypatch):
    """d64 paired layout through the long-context split dq/dkv kernels."""
    import paddle_tpu.ops.pallas_attention as pa

    rng = np.random.default_rng(11)
    b, t, h, d = 1, 32, 2, 64
    q = jnp.asarray(rng.normal(size=(b, t, h * d)) * 0.5, jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, t, h * d)) * 0.5, jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, t, h * d)), jnp.float32)

    def lp(q, k, v):
        return jnp.sum(pa.flash_attention_packed(
            q, k, v, h, causal=True, block_q=16, block_k=16) ** 2)

    g_fused = jax.grad(lp, (0, 1, 2))(q, k, v)
    monkeypatch.setattr(pa, "FUSED_BWD_PARTIAL_BYTES", 0)
    g_split = jax.grad(lp, (0, 1, 2))(q, k, v)
    for a, b_ in zip(g_fused, g_split):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-5, rtol=1e-5)


def test_causal_triangular_no_masked_half_flops():
    """Flop accounting via the kernel's OWN sub-tile skip predicate
    (``_diag_subtile_live`` is shared between the kernel and
    ``causal_flash_flops``): the masked halves of diagonal blocks are
    never scheduled — only the DIAG_W-wide band along the diagonal
    remains, and no scheduled sub-tile lies fully above the diagonal."""
    from paddle_tpu.ops.pallas_attention import (
        DIAG_W, causal_flash_flops, _diag_subtile_live)

    # flagship geometry: t=4096, 1024 blocks.  Old full-tile + select
    # spelling scheduled ~1.25x the useful flops; triangular must be
    # within the diagonal band bound (~1 + DIAG_W/t + slack).
    sched, useful = causal_flash_flops(4096, 4096, 128, 1024, 1024)
    assert sched / useful < 1.08, sched / useful
    # old spelling for comparison: every cell at/below the block diagonal
    # fully computed
    nq = nk = 4096 // 1024
    old = sum(min(((j + 1) * 1024 - 1) // 1024, nk - 1) + 1
              for j in range(nq)) * 1024 * 1024 * 4 * 128
    assert sched < 0.9 * old

    # grid-shape assertion: a sub-tile fully above the diagonal is never
    # live, and every unmasked sub-tile below it is
    bq = bk = 1024
    w = DIAG_W
    for j in range(4):
        for kb in range(4):
            for qs in range(bq // w):
                for ks in range(bk // w):
                    row_last = j * bq + (qs + 1) * w - 1
                    col0 = kb * bk + ks * w
                    assert _diag_subtile_live(
                        j, kb, qs, ks, bq, bk, w, w) == (col0 <= row_last)


def test_causal_triangular_multi_subtile_matches_reference(monkeypatch):
    """Force the multi-sub-tile triangular path (DIAG_W smaller than the
    block) and check the forward against the dense reference — the
    sub-tiled online softmax must reduce to the same attention."""
    import paddle_tpu.ops.pallas_attention as pa

    monkeypatch.setattr(pa, "DIAG_W", 32)
    rng = np.random.default_rng(13)
    b, t, h, d = 1, 256, 2, 16
    q, k, v = (jnp.asarray(rng.normal(size=(b, t, h, d)) * 0.5,
                           jnp.float32) for _ in range(3))
    o = pa.flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # uneven aspect: q blocks narrower than k blocks
    o2 = pa.flash_attention(q, k, v, causal=True, block_q=64, block_k=128)
    np.testing.assert_allclose(np.asarray(o2), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_causal_triangular_multi_subtile_grads(monkeypatch):
    """Gradients through the sub-tiled diagonal cells of BOTH backward
    spellings (fused, and split dq/dkv with the partial budget forced to
    0), vs the dense reference — the triangular pass covers the whole
    causal step, not just the forward."""
    import paddle_tpu.ops.pallas_attention as pa

    monkeypatch.setattr(pa, "DIAG_W", 32)
    rng = np.random.default_rng(17)
    b, t, h, d = 1, 128, 2, 16
    q, k, v = (jnp.asarray(rng.normal(size=(b, t, h, d)) * 0.5,
                           jnp.float32) for _ in range(3))

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) * jnp.cos(fn(q, k, v)))

    flash = lambda q, k, v: pa.flash_attention(
        q, k, v, causal=True, block_q=64, block_k=64)
    dense = lambda q, k, v: attention_reference(q, k, v, causal=True)
    g_ref = jax.grad(loss(dense), (0, 1, 2))(q, k, v)
    g_fused = jax.grad(loss(flash), (0, 1, 2))(q, k, v)
    monkeypatch.setattr(pa, "FUSED_BWD_PARTIAL_BYTES", 0)
    g_split = jax.grad(loss(flash), (0, 1, 2))(q, k, v)
    for gf, gs, gr, nm in zip(g_fused, g_split, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=5e-5, rtol=5e-4,
                                   err_msg=f"fused tri grad wrt {nm}")
        np.testing.assert_allclose(np.asarray(gs), np.asarray(gr),
                                   atol=5e-5, rtol=5e-4,
                                   err_msg=f"split tri grad wrt {nm}")

    # d64 paired (sub_heads=2) through the sub-tiled diagonal as well
    b, t, h, d = 1, 64, 2, 64
    q2, k2, v2 = (jnp.asarray(rng.normal(size=(b, t, h, d)) * 0.5,
                              jnp.float32) for _ in range(3))
    pk = lambda x: x.reshape(b, t, h * d)

    def lp(q, k, v):
        return jnp.sum(pa.flash_attention_packed(
            q, k, v, h, causal=True, block_q=64, block_k=64) ** 2)

    def lr(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    gp = jax.grad(lp, (0, 1, 2))(pk(q2), pk(k2), pk(v2))
    gr = jax.grad(lr, (0, 1, 2))(q2, k2, v2)
    for a, r, nm in zip(gp, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(pk(r)),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"paired tri grad wrt {nm}")


def test_packed_op_tp_odd_local_heads_falls_back_to_4d():
    """TP regression: global n_head packs (d=64, 6 heads -> pairs) but
    the per-shard count does not (6/2 = 3 local heads can't pair) — the
    op must route each shard through the 4-D kernel instead of raising
    at trace time, and still match the dense reference."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.ops.pallas_attention import (
        flash_attention_packed_op, packed_sub_heads)
    from paddle_tpu.parallel.mesh import make_mesh

    h, d = 6, 64
    assert packed_sub_heads(h, d) == 2
    assert packed_sub_heads(h // 2, d) is None

    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])

    class _Exe:
        pass

    class _Ctx:
        executor = _Exe()

    _Ctx.executor.mesh = mesh
    rng = np.random.default_rng(21)
    b, t = 2, 16
    q4, k4, v4 = (jnp.asarray(rng.normal(size=(b, t, h, d)) * 0.5,
                              jnp.float32) for _ in range(3))
    pk = lambda x: jax.device_put(
        x.reshape(b, t, h * d), NamedSharding(mesh, P(None, None, "tp")))
    out = flash_attention_packed_op(
        pk(q4), pk(k4), pk(v4), n_head=h, causal=True, _ctx=_Ctx())["Out"]
    ref = attention_reference(q4, k4, v4, causal=True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(ref.reshape(b, t, h * d)),
                               atol=2e-5, rtol=2e-5)
