"""Flash-attention kernel tests: Pallas (interpret mode on CPU) vs the dense
reference, forward and backward — the cross-device comparison pattern of the
reference's function/*OpTest.cpp suites."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas_attention import (
    attention_reference,
    flash_attention,
)


def _inputs(b=2, tq=16, tk=16, h=2, d=8, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, tq, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, tk, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, tk, h, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _inputs()
    out = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_cross_attention_shapes():
    q, k, v = _inputs(tq=8, tk=24)
    out = flash_attention(q, k, v, block_q=4, block_k=8)
    ref = attention_reference(q, k, v)
    assert out.shape == (2, 8, 2, 8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_uneven_block_fallback():
    # t not divisible by requested block: _pick_block shrinks to a divisor
    q, k, v = _inputs(tq=12, tk=20)
    out = flash_attention(q, k, v, block_q=8, block_k=8)
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_reference(causal):
    q, k, v = _inputs(b=1, tq=8, tk=8, h=1, d=4)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=4, block_k=4)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = attention_reference(q, k, v, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-5, rtol=5e-4,
            err_msg=f"grad wrt {name}",
        )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bf16_and_d128(causal):
    """bf16 inputs take the bf16 MXU-feed path; d=128 heads (the MFU
    config) must be numerically sound fwd+bwd vs an f32 dense reference."""
    rng = np.random.default_rng(7)
    b, t, h, d = 1, 64, 2, 128
    qf, kf, vf = (jnp.asarray(rng.normal(size=(b, t, h, d)) * 0.5,
                              jnp.float32) for _ in range(3))
    q, k, v = (x.astype(jnp.bfloat16) for x in (qf, kf, vf))
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    ref = attention_reference(qf, kf, vf, causal=causal)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2, rtol=3e-2)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(qf, kf, vf)
    for gf, gr, nm in zip(g_flash, g_ref, "qkv"):
        # bf16 ~ 3 decimal digits; compare against the row scale
        scale = np.maximum(np.abs(np.asarray(gr)).max(), 1.0)
        np.testing.assert_allclose(
            np.asarray(gf, np.float32) / scale, np.asarray(gr) / scale,
            atol=4e-2, err_msg=f"grad wrt {nm}")


def test_flash_attention_op_registered():
    from tests.op_test import run_op

    q, k, v = _inputs(b=1, tq=8, tk=8, h=1, d=4)
    out = run_op(
        "flash_attention",
        {"Q": np.asarray(q), "K": np.asarray(k), "V": np.asarray(v)},
        attrs={"causal": True},
    )
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out["Out"], np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


def test_flash_attention_jit_under_program():
    """The kernel works inside a jitted step function."""
    q, k, v = _inputs(b=1, tq=8, tk=8, h=1, d=4)

    @jax.jit
    def step(q, k, v):
        return flash_attention(q, k, v)

    np.testing.assert_allclose(
        np.asarray(step(q, k, v)),
        np.asarray(attention_reference(q, k, v)),
        atol=2e-5, rtol=2e-5,
    )


def test_flash_cross_attention_causal_tq_gt_tk():
    """Regression: causal cross-attention with t_q > t_k — q blocks whose
    diagonal lies beyond the last k block must still finalize (the 3-D
    grid kernel's last_kb needs clamping to nk-1)."""
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(2, 16, 2, 8) * 0.5, jnp.float32)
    k = jnp.asarray(rng.randn(2, 8, 2, 8) * 0.5, jnp.float32)
    v = jnp.asarray(rng.randn(2, 8, 2, 8), jnp.float32)
    o = flash_attention(q, k, v, causal=True, block_q=8, block_k=8,
                        interpret=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    ga = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=True, block_q=8, block_k=8, interpret=True)),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(lambda q, k, v: attention_reference(
        q, k, v, causal=True)), argnums=(0, 1, 2))(q, k, v)
    for a, r in zip(ga, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=2e-4, atol=2e-5)


def test_flash_attention_with_lse_matches_dense_including_lse_grads():
    """o, lse, and gradients THROUGH lse (the ring-merge path) vs dense."""
    rng = np.random.RandomState(0)
    b, t, h, d = 2, 64, 2, 16
    q, k, v = (jnp.asarray(rng.randn(b, t, h, d) * 0.5, jnp.float32)
               for _ in range(3))
    from paddle_tpu.ops.pallas_attention import flash_attention_with_lse

    def dense_with_lse(q, k, v, causal):
        scale = d ** -0.5
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        if causal:
            mask = jnp.tril(jnp.ones((t, t), bool))
            s = jnp.where(mask[None, None], s, -1e30)
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        p = jnp.exp(s - lse[..., None])
        return jnp.einsum("bhqk,bkhd->bqhd", p, v), lse

    for causal in (False, True):
        o1, l1 = flash_attention_with_lse(q, k, v, causal=causal,
                                          block_q=16, block_k=16,
                                          interpret=True)
        o2, l2 = dense_with_lse(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                                   rtol=1e-5, atol=1e-5)

        def loss(fn):
            def f(q, k, v):
                o, lse = fn(q, k, v)
                return jnp.sum(o ** 2) + jnp.sum(jnp.sin(lse))
            return f

        ga = jax.grad(loss(lambda q, k, v: flash_attention_with_lse(
            q, k, v, causal=causal, block_q=16, block_k=16,
            interpret=True)), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss(lambda q, k, v: dense_with_lse(q, k, v, causal)),
                      argnums=(0, 1, 2))(q, k, v)
        for a, r in zip(ga, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_split_bwd_matches_fused(causal, monkeypatch):
    """The long-context backward (split dq + dkv kernels, used when the
    fused kernel's dq partials exceed budget) stays in lockstep with the
    fused backward and the dense reference."""
    from paddle_tpu.ops import pallas_attention as pa

    q, k, v = _inputs(b=1, tq=16, tk=16, h=2, d=4)

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=4, block_k=4)
        return jnp.sum(o * jnp.cos(o))

    g_fused = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setattr(pa, "FUSED_BWD_PARTIAL_BYTES", 0)
    g_split = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    def loss_ref(q, k, v):
        o = attention_reference(q, k, v, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gs, gr, name in zip(g_fused, g_split, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gs), np.asarray(gr),
                                   atol=5e-5, rtol=5e-4,
                                   err_msg=f"split grad wrt {name}")
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gs),
                                   atol=5e-5, rtol=5e-4,
                                   err_msg=f"fused vs split wrt {name}")


@pytest.mark.parametrize("causal", [False, True])
def test_flash_packed_matches_4d_values_and_grads(causal):
    """The packed-layout kernel ([b, t, h*d], heads as lane slices in the
    block index maps) is bit-identical to the 4-D path (same math,
    same blocks — only block index maps differ), values and gradients."""
    from paddle_tpu.ops.pallas_attention import flash_attention_packed

    b, t, h, d = 2, 64, 2, 8
    q, k, v = _inputs(b=b, tq=t, tk=t, h=h, d=d, seed=3)
    pk = lambda x: x.reshape(b, t, h * d)

    out4 = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    outp = flash_attention_packed(pk(q), pk(k), pk(v), h, causal=causal,
                                  block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(outp), np.asarray(pk(out4)),
                               atol=1e-6, rtol=1e-5)

    def l4(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, block_q=16,
                                       block_k=16) ** 2)

    def lp(q, k, v):
        return jnp.sum(flash_attention_packed(q, k, v, h, causal=causal,
                                              block_q=16, block_k=16) ** 2)

    g4 = jax.grad(l4, (0, 1, 2))(q, k, v)
    gp = jax.grad(lp, (0, 1, 2))(pk(q), pk(k), pk(v))
    for a, b_ in zip(g4, gp):
        np.testing.assert_allclose(np.asarray(b_), np.asarray(pk(a)),
                                   atol=1e-5, rtol=1e-5)


def test_flash_packed_split_bwd_matches_fused(monkeypatch):
    """Packed layout through the long-context split dq/dkv kernels (budget
    forced to 0) agrees with the fused backward."""
    import paddle_tpu.ops.pallas_attention as pa

    b, t, h, d = 1, 64, 2, 8
    q, k, v = _inputs(b=b, tq=t, tk=t, h=h, d=d, seed=5)
    pk = lambda x: x.reshape(b, t, h * d)

    def lp(q, k, v):
        return jnp.sum(pa.flash_attention_packed(
            q, k, v, h, causal=True, block_q=16, block_k=16) ** 2)

    g_fused = jax.grad(lp, (0, 1, 2))(pk(q), pk(k), pk(v))
    monkeypatch.setattr(pa, "FUSED_BWD_PARTIAL_BYTES", 0)
    g_split = jax.grad(lp, (0, 1, 2))(pk(q), pk(k), pk(v))
    for a, b_ in zip(g_fused, g_split):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-5, rtol=1e-5)


def test_flash_packed_head_width_guard():
    """d_head not lane-aligned (and n_head > 1) is a clear error, not a
    Mosaic crash."""
    from paddle_tpu.ops.pallas_attention import flash_attention_packed

    x = jnp.zeros((1, 16, 2 * 8), jnp.float32)
    with pytest.raises(ValueError, match="d_head % 128"):
        flash_attention_packed(x, x, x, 2, interpret=False)


def test_flash_attention_packed_op_registered():
    from tests.op_test import run_op

    b, t, h, d = 1, 16, 1, 4
    q, k, v = _inputs(b=b, tq=t, tk=t, h=h, d=d)
    pk = lambda x: np.asarray(x).reshape(b, t, h * d)
    out = run_op(
        "flash_attention_packed",
        {"Q": pk(q), "K": pk(k), "V": pk(v)},
        attrs={"n_head": h, "causal": True},
    )
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out["Out"], pk(ref), atol=2e-5, rtol=2e-5)
