"""Every optimizer converges on a quadratic (reference: per-optimizer op
tests + FirstOrderOptimizer unit tests)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _run_optimizer(opt, steps=60):
    x = layers.data("x", shape=[4])
    pred = layers.fc(input=x, size=1, bias_attr=False,
                     param_attr=pt.initializer.Constant(2.0))
    loss = layers.mean(layers.square(pred))
    opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    data = np.ones((8, 4), np.float32)
    losses = []
    for _ in range(steps):
        (l,) = exe.run(feed={"x": data}, fetch_list=[loss])
        losses.append(float(l[0]))
    return losses


@pytest.mark.parametrize("make_opt", [
    lambda: pt.optimizer.SGD(learning_rate=0.01),
    lambda: pt.optimizer.Momentum(learning_rate=0.01, momentum=0.9),
    lambda: pt.optimizer.Adagrad(learning_rate=0.5),
    lambda: pt.optimizer.Adam(learning_rate=0.3),
    lambda: pt.optimizer.Adamax(learning_rate=0.3),
    lambda: pt.optimizer.DecayedAdagrad(learning_rate=0.3),
    lambda: pt.optimizer.Adadelta(learning_rate=1.0, rho=0.5, epsilon=1e-2),
    lambda: pt.optimizer.RMSProp(learning_rate=0.1),
    lambda: pt.optimizer.Ftrl(learning_rate=0.5),
])
def test_optimizer_decreases_loss(make_opt):
    losses = _run_optimizer(make_opt())
    assert losses[-1] < losses[0] * 0.2, losses[::10]


def test_weight_decay_shrinks_weights():
    x = layers.data("x", shape=[4])
    pred = layers.fc(
        input=x, size=1, bias_attr=False,
        param_attr=pt.ParamAttr(
            initializer=pt.initializer.Constant(1.0),
            regularizer=pt.regularizer.L2Decay(0.5),
        ),
    )
    loss = layers.mean(pred) * 0.0  # zero data gradient; only decay acts
    loss = layers.mean(loss)
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.global_scope()
    wname = [n for n in scope.var_names() if n.endswith(".w")][0]
    exe.run(feed={"x": np.zeros((2, 4), np.float32)}, fetch_list=[loss])
    w = np.asarray(scope.get(wname))
    np.testing.assert_allclose(w, 0.95 * np.ones_like(w), rtol=1e-5)


def test_global_norm_clip():
    x = layers.data("x", shape=[4])
    pred = layers.fc(input=x, size=1, bias_attr=False,
                     param_attr=pt.initializer.Constant(1.0))
    loss = layers.mean(pred)
    opt = pt.optimizer.SGD(
        learning_rate=1.0,
        global_clip=pt.clip.GradientClipByGlobalNorm(0.001),
    )
    opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.global_scope()
    wname = [n for n in scope.var_names() if n.endswith(".w")][0]
    w0 = np.asarray(scope.get(wname)).copy()
    exe.run(feed={"x": np.ones((2, 4), np.float32) * 100}, fetch_list=[loss])
    w1 = np.asarray(scope.get(wname))
    # update magnitude bounded by clip norm
    assert np.abs(w1 - w0).sum() < 0.01


def test_lr_decay_schedule():
    lr = pt.learning_rate_decay.exponential_decay(
        learning_rate=1.0, decay_steps=1, decay_rate=0.5
    )
    x = layers.data("x", shape=[2])
    pred = layers.fc(input=x, size=1, bias_attr=False,
                     param_attr=pt.initializer.Constant(1.0))
    loss = layers.mean(pred)
    pt.optimizer.SGD(learning_rate=lr).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    vals = []
    for _ in range(3):
        (v,) = exe.run(feed={"x": np.ones((2, 2), np.float32)}, fetch_list=[lr])
        vals.append(float(v[0]))
    np.testing.assert_allclose(vals, [0.5, 0.25, 0.125], rtol=1e-5)


def test_model_average_ema_and_apply():
    """ModelAverage (reference AverageOptimizer.h:23): EMA updated inside
    the jitted step; apply() swaps averages in for eval and restores."""
    import paddle_tpu as pt
    from paddle_tpu import layers

    x = layers.data("x", shape=[4])
    y = layers.data("y", shape=[1])
    pred = layers.fc(x, 1, bias_attr=False)
    cost = layers.mean(layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(cost)
    ma = pt.optimizer.ModelAverage(0.9)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.core.scope.global_scope()
    rng = np.random.default_rng(0)
    xv = rng.normal(size=(16, 4)).astype(np.float32)
    yv = xv @ np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32)
    for _ in range(20):
        exe.run(feed={"x": xv, "y": yv}, fetch_list=[cost])

    p, e = ma.pairs[0]
    pv, ev = np.asarray(scope.get(p)), np.asarray(scope.get(e))
    assert not np.allclose(pv, ev)  # ema lags the raw weights
    with ma.apply():
        np.testing.assert_allclose(np.asarray(scope.get(p)), ev)
    np.testing.assert_allclose(np.asarray(scope.get(p)), pv)


def test_model_average_matches_hand_rolled_ema():
    """The in-step EMA must equal decay*ema + (1-decay)*param applied to
    the POST-update parameter each step."""
    import paddle_tpu as pt
    from paddle_tpu import layers

    x = layers.data("x", shape=[2])
    y = layers.data("y", shape=[1])
    pred = layers.fc(x, 1, bias_attr=False)
    cost = layers.mean(layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.05).minimize(cost)
    ma = pt.optimizer.ModelAverage(0.8)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.core.scope.global_scope()
    pname, ename = ma.pairs[0]

    rng = np.random.default_rng(1)
    xv = rng.normal(size=(8, 2)).astype(np.float32)
    yv = xv @ np.array([[2.0], [-1.0]], np.float32)
    hand = np.asarray(scope.get(pname)).copy()  # startup seeds ema = param
    for _ in range(6):
        exe.run(feed={"x": xv, "y": yv}, fetch_list=[cost])
        hand = 0.8 * hand + 0.2 * np.asarray(scope.get(pname))
    np.testing.assert_allclose(np.asarray(scope.get(ename)), hand,
                               rtol=1e-5, atol=1e-6)


def test_model_average_requires_minimize_first():
    import paddle_tpu as pt
    from paddle_tpu import layers

    layers.fc(layers.data("x", shape=[2]), 1)
    try:
        pt.optimizer.ModelAverage(0.9)
        assert False, "expected RuntimeError before minimize"
    except RuntimeError as e:
        assert "minimize" in str(e)
