"""Direct tests for the remaining previously-untested registered ops
(VERDICT r1 item 3: one direct test per op) — elementwise/compare/logical,
tensor/fill/shape, lookup/embedding-grad, sequence, random, attention,
detection, and beam-search-decode ops, each vs an independent numpy
reference."""

import numpy as np
import pytest

from op_test import check_output, check_grad, run_op

rng = np.random.RandomState(23)


# ---------------- elementwise / compare / logical -----------------------

def test_elementwise_div_min_pow():
    x = rng.uniform(1.0, 3.0, (3, 4)).astype(np.float32)
    y = rng.uniform(1.0, 2.0, (3, 4)).astype(np.float32)
    check_output("elementwise_div", {"X": x, "Y": y}, {"Out": x / y},
                 atol=1e-5)
    check_output("elementwise_min", {"X": x, "Y": y},
                 {"Out": np.minimum(x, y)}, atol=1e-6)
    check_output("elementwise_pow", {"X": x, "Y": y}, {"Out": x ** y},
                 atol=1e-4, rtol=1e-4)
    check_grad("elementwise_div", {"X": x, "Y": y}, "X",
               max_relative_error=5e-3)
    check_grad("elementwise_div", {"X": x, "Y": y}, "Y",
               max_relative_error=5e-3)


def test_elementwise_broadcast_axis():
    """axis semantics of the reference elementwise ops: Y's dims align to
    X starting at `axis`."""
    x = rng.rand(2, 3, 4).astype(np.float32)
    y = rng.uniform(1.0, 2.0, (3,)).astype(np.float32)
    got = run_op("elementwise_div", {"X": x, "Y": y}, {"axis": 1})
    np.testing.assert_allclose(got["Out"], x / y[None, :, None], rtol=1e-5)


def test_compare_ops():
    x = rng.randint(0, 3, (4, 3)).astype(np.float32)
    y = rng.randint(0, 3, (4, 3)).astype(np.float32)
    check_output("equal", {"X": x, "Y": y}, {"Out": x == y})
    check_output("not_equal", {"X": x, "Y": y}, {"Out": x != y})
    check_output("greater_than", {"X": x, "Y": y}, {"Out": x > y})
    check_output("less_equal", {"X": x, "Y": y}, {"Out": x <= y})


def test_logical_or():
    x = rng.rand(3, 3) > 0.5
    y = rng.rand(3, 3) > 0.5
    check_output("logical_or", {"X": x, "Y": y},
                 {"Out": np.logical_or(x, y)})


def test_minus_dot_mean():
    x = rng.randn(3, 4).astype(np.float32)
    y = rng.randn(3, 4).astype(np.float32)
    check_output("minus", {"X": x, "Y": y}, {"Out": x - y}, atol=1e-6)
    check_output("dot", {"X": x, "Y": y},
                 {"Out": np.sum(x * y, axis=-1, keepdims=True)}, atol=1e-5)
    check_output("mean", {"X": x}, {"Out": np.mean(x).reshape(1)},
                 atol=1e-6)
    check_grad("mean", {"X": x}, "X", max_relative_error=5e-3)


# ---------------- tensor / fill / shape ---------------------------------

def test_fill_and_assign_family():
    got = run_op("fill_constant", {}, {"shape": (2, 3), "dtype": "float32",
                                       "value": 2.5})
    np.testing.assert_array_equal(got["Out"], np.full((2, 3), 2.5, np.float32))

    ref = rng.randn(5, 4).astype(np.float32)
    got = run_op("fill_constant_batch_size_like",
                 {"Input": ref},
                 {"shape": (1, 7), "dtype": "float32", "value": 1.0,
                  "input_dim_idx": 0, "output_dim_idx": 0})
    assert got["Out"].shape == (5, 7) and (got["Out"] == 1.0).all()

    x = rng.randn(2, 2).astype(np.float32)
    np.testing.assert_array_equal(run_op("assign", {"X": x})["Out"], x)

    got = run_op("assign_value", {},
                 {"shape": (2, 2), "dtype": "float32",
                  "values": (1.0, 2.0, 3.0, 4.0)})
    np.testing.assert_array_equal(
        got["Out"], np.array([[1, 2], [3, 4]], np.float32))


def test_shape_argmax_argmin_increment_isempty():
    x = rng.randn(3, 5).astype(np.float32)
    np.testing.assert_array_equal(
        run_op("shape", {"Input": x})["Out"], np.array([3, 5], np.int32))
    np.testing.assert_array_equal(
        run_op("arg_max", {"X": x}, {"axis": 1})["Out"],
        np.argmax(x, axis=1).astype(np.int32))
    np.testing.assert_array_equal(
        run_op("arg_min", {"X": x}, {"axis": 0})["Out"],
        np.argmin(x, axis=0).astype(np.int32))
    np.testing.assert_allclose(
        run_op("increment", {"X": np.array([2.0], np.float32)},
               {"step": 3.0})["Out"], [5.0])
    assert not bool(np.asarray(run_op("is_empty", {"X": x})["Out"]))
    assert bool(np.asarray(
        run_op("is_empty", {"X": np.zeros((0, 2), np.float32)})["Out"]))


def test_reshape_reduce_min_prod():
    x = rng.uniform(0.5, 2.0, (2, 6)).astype(np.float32)
    got = run_op("reshape", {"X": x}, {"shape": (3, 4)})
    np.testing.assert_array_equal(got["Out"], x.reshape(3, 4))
    check_output("reduce_min", {"X": x}, {"Out": np.min(x, axis=None)},
                 attrs={"reduce_all": True}, atol=1e-6)
    got = run_op("reduce_min", {"X": x}, {"dim": 1})
    np.testing.assert_allclose(got["Out"], np.min(x, axis=1), rtol=1e-6)
    got = run_op("reduce_prod", {"X": x}, {"dim": 1})
    np.testing.assert_allclose(got["Out"], np.prod(x, axis=1), rtol=1e-4)
    check_grad("reduce_prod", {"X": x}, "X", attrs={"dim": 1},
               max_relative_error=5e-3)


def test_lookup_table_and_grad_rows():
    w = rng.randn(10, 4).astype(np.float32)
    ids = np.array([[1], [7], [1], [9]], np.int64)
    got = run_op("lookup_table", {"W": w, "Ids": ids})
    np.testing.assert_allclose(got["Out"], w[ids.ravel()], rtol=1e-6)

    # padding_idx rows come back zero (lookup_table_op.cc padding_idx)
    got = run_op("lookup_table", {"W": w, "Ids": ids}, {"padding_idx": 7})
    exp = w[ids.ravel()].copy()
    exp[1] = 0.0
    np.testing.assert_allclose(got["Out"], exp, rtol=1e-6)

    # embedding_grad_rows scatter-adds duplicate ids (SelectedRows merge)
    g = rng.randn(4, 4).astype(np.float32)
    got = run_op("embedding_grad_rows", {"Grad": g, "Ids": ids},
                 {"table_height": 10})
    exp = np.zeros((10, 4), np.float32)
    for row, i in zip(g, ids.ravel()):
        exp[i] += row
    np.testing.assert_allclose(got["Out"], exp, rtol=1e-5, atol=1e-6)


def test_error_clip_clips_cotangent_not_value():
    x = rng.randn(3, 3).astype(np.float32) * 10
    got = run_op("error_clip", {"X": x}, {"max": 0.5})
    np.testing.assert_array_equal(got["Out"], x)  # identity forward
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core.registry import get_op_impl

    impl = get_op_impl("error_clip")

    def f(x):
        return jnp.sum(impl.call({"X": x}, {"max": 0.5}, None)["Out"] * 10.0)

    g = jax.grad(f)(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(g), np.full_like(x, 0.5))


# ---------------- random ops --------------------------------------------

def test_dropout_train_and_test_mode():
    x = np.ones((200, 50), np.float32)
    got = run_op("dropout", {"X": x},
                 {"dropout_prob": 0.3, "fix_seed": True, "seed": 7})
    keep_rate = got["Mask"].mean()
    assert 0.6 < keep_rate < 0.8  # ~0.7
    np.testing.assert_array_equal(got["Out"], x * got["Mask"])
    # v0.11 semantics: test mode scales by (1-p), train does NOT rescale
    got = run_op("dropout", {"X": x}, {"dropout_prob": 0.3, "is_test": True})
    np.testing.assert_allclose(got["Out"], x * 0.7, rtol=1e-6)


def test_random_crop():
    x = rng.randn(8, 8, 3).astype(np.float32)
    got = run_op("random_crop", {"X": x}, {"shape": (5, 5, 3)})
    out = np.asarray(got["Out"])
    assert out.shape == (5, 5, 3)
    # the crop must be a contiguous sub-block of x
    found = any(
        np.array_equal(out, x[i:i + 5, j:j + 5])
        for i in range(4) for j in range(4)
    )
    assert found


# ---------------- attention / conv3d ------------------------------------

def test_flash_attention_op_vs_naive():
    b, t, h, d = 2, 16, 2, 8
    q = rng.randn(b, t, h, d).astype(np.float32) * 0.5
    k = rng.randn(b, t, h, d).astype(np.float32) * 0.5
    v = rng.randn(b, t, h, d).astype(np.float32)

    def naive(q, k, v, causal):
        scale = d ** -0.5
        logits = np.einsum("bqhd,bkhd->bhqk", q, k) * scale
        if causal:
            mask = np.tril(np.ones((t, t), bool))
            logits = np.where(mask[None, None], logits, -1e30)
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        return np.einsum("bhqk,bkhd->bqhd", p, v)

    for causal in (False, True):
        got = run_op("flash_attention", {"Q": q, "K": k, "V": v},
                     {"causal": causal})
        np.testing.assert_allclose(got["Out"], naive(q, k, v, causal),
                                   rtol=2e-3, atol=2e-3)


def test_conv3d_vs_loop_reference():
    x = rng.randn(1, 2, 4, 4, 4).astype(np.float32)
    w = rng.randn(3, 2, 2, 2, 2).astype(np.float32)
    got = run_op("conv3d", {"Input": x, "Filter": w},
                 {"strides": (1, 1, 1), "paddings": (0, 0, 0),
                  "dilations": (1, 1, 1), "groups": 1})
    out = np.zeros((1, 3, 3, 3, 3), np.float32)
    for z in range(3):
        for i in range(3):
            for j in range(3):
                patch = x[:, :, z:z + 2, i:i + 2, j:j + 2]
                out[:, :, z, i, j] = np.einsum("ncdhw,ocdhw->no", patch, w)
    np.testing.assert_allclose(got["Output"], out, rtol=1e-3, atol=1e-4)


# ---------------- sequence ops ------------------------------------------

def test_sequence_concat_lengths_add():
    x1 = rng.randn(2, 3, 2).astype(np.float32)
    x2 = rng.randn(2, 4, 2).astype(np.float32)
    l1 = np.array([2, 3], np.int32)
    l2 = np.array([4, 1], np.int32)
    got = run_op("sequence_concat",
                 {"X": [x1, x2], "Length": [l1, l2]}, {"axis": 1})
    np.testing.assert_array_equal(got["OutLength"], [6, 4])
    # row 0: x1[0,:2] then x2[0,:4]
    np.testing.assert_allclose(got["Out"][0, :2], x1[0, :2], rtol=1e-6)
    np.testing.assert_allclose(got["Out"][0, 2:6], x2[0, :4], rtol=1e-6)
    # row 1: x1[1,:3] then x2[1,:1]
    np.testing.assert_allclose(got["Out"][1, :3], x1[1, :3], rtol=1e-6)
    np.testing.assert_allclose(got["Out"][1, 3:4], x2[1, :1], rtol=1e-6)


def test_sequence_reshape_rescales_lengths():
    x = rng.randn(2, 4, 6).astype(np.float32)
    ln = np.array([4, 2], np.int32)
    got = run_op("sequence_reshape", {"X": x, "Length": ln}, {"new_dim": 3})
    assert got["Out"].shape == (2, 8, 3)
    np.testing.assert_array_equal(got["OutLength"], [8, 4])
    np.testing.assert_allclose(got["Out"][0].ravel(), x[0].ravel(),
                               rtol=1e-6)


def test_sequence_scale_and_slice():
    x = rng.randn(2, 5, 3).astype(np.float32)
    s = np.array([2.0, -1.0], np.float32)
    got = run_op("sequence_scale", {"X": x, "Scales": s})
    np.testing.assert_allclose(got["Out"][0], 2.0 * x[0], rtol=1e-6)
    np.testing.assert_allclose(got["Out"][1], -x[1], rtol=1e-6)

    off = np.array([[1], [0]], np.int64)
    ln = np.array([[3], [2]], np.int64)
    got = run_op("sequence_slice", {"X": x, "Offset": off, "SeqLength": ln})
    np.testing.assert_array_equal(got["OutLength"], [3, 2])
    np.testing.assert_allclose(got["Out"][0, :3], x[0, 1:4], rtol=1e-6)
    np.testing.assert_allclose(got["Out"][1, :2], x[1, 0:2], rtol=1e-6)
    assert np.abs(got["Out"][0, 3:]).max() == 0.0


# ---------------- beam search decode / detection ------------------------

def test_beam_search_decode_backtracks():
    # T=3, b=1, k=2: hand-built beams.
    # step0: ids [[5, 6]], parents [[0, 1]]
    # step1: ids [[7, 8]], parents [[0, 0]]   (both continue beam 0)
    # step2: ids [[9, 1]], parents [[1, 0]]   (end_id=1 ends slot 1)
    ids = np.array([[[5, 6]], [[7, 8]], [[9, 1]]], np.int32)
    parents = np.array([[[0, 1]], [[0, 0]], [[1, 0]]], np.int32)
    got = run_op("beam_search_decode", {"Ids": ids, "ParentIdx": parents},
                 {"end_id": 1})
    sent = np.asarray(got["SentenceIds"])
    # slot 0 backtracks: step2 id 9 <- parent 1 -> step1 id 8 <- parent 0
    # -> step0 id 5
    np.testing.assert_array_equal(sent[0, 0], [5, 8, 9])
    # slot 1: step2 id 1(end) <- parent 0 -> step1 id 7 <- step0 id 5
    np.testing.assert_array_equal(sent[0, 1], [5, 7, 1])


def test_detection_output_decodes_and_nms():
    # one prior, one foreground class, trivially decodable
    prior = np.array([[0.2, 0.2, 0.4, 0.4]], np.float32)
    loc = np.zeros((1, 1, 4), np.float32)  # zero offsets -> box == prior
    conf = np.array([[[0.1, 0.9]]], np.float32)  # background, class1
    got = run_op("detection_output",
                 {"Loc": loc, "Conf": conf, "PriorBox": prior},
                 {"background_label": 0, "score_threshold": 0.5})
    out = np.asarray(got["Out"])
    rows = out[0] if out.ndim == 3 else out
    kept = rows[rows[:, 0] >= 0]
    assert len(kept) == 1
    assert kept[0][0] == 1.0 and abs(kept[0][1] - 0.9) < 1e-5
    np.testing.assert_allclose(kept[0][2:], prior[0], atol=1e-5)


# ---------------- round-2 additions: v1 long-tail carrier ops ------------

def test_bilinear_interp_align_corners():
    x = np.array([[[[0.0, 1.0], [2.0, 3.0]]]], np.float32)
    got = run_op("bilinear_interp", {"X": x}, {"out_h": 3, "out_w": 3})
    exp = np.array([[0, 0.5, 1], [1, 1.5, 2], [2, 2.5, 3]], np.float32)
    np.testing.assert_allclose(got["Out"][0, 0], exp, atol=1e-6)
    check_grad("bilinear_interp", {"X": rng.rand(1, 2, 3, 3).astype(np.float32)},
               "X", attrs={"out_h": 5, "out_w": 4}, max_relative_error=5e-3)


def test_sampling_id_follows_distribution():
    p = np.array([[0.999, 0.001], [0.001, 0.999]], np.float32)
    got = run_op("sampling_id", {"X": p})
    assert got["Out"][0] == 0 and got["Out"][1] == 1
    # statistically: ~uniform over many rows
    p2 = np.full((2000, 4), 0.25, np.float32)
    ids = run_op("sampling_id", {"X": p2})["Out"]
    counts = np.bincount(np.asarray(ids), minlength=4) / 2000
    assert np.abs(counts - 0.25).max() < 0.06, counts


def test_scale_sub_region():
    x = np.ones((1, 2, 3, 3), np.float32)
    ind = np.array([[1, 1, 2, 3, 1, 2]], np.int32)
    got = run_op("scale_sub_region", {"X": x, "Indices": ind},
                 {"value": 5.0})
    exp = x.copy()
    exp[0, 0, 1:3, 0:2] = 5.0
    np.testing.assert_array_equal(got["Out"], exp)


def test_multibox_loss_matching_and_mining():
    prior = np.array([[0.1, 0.1, 0.3, 0.3], [0.6, 0.6, 0.9, 0.9],
                      [0.4, 0.1, 0.5, 0.2]], np.float32)
    gt = np.array([[[0.1, 0.1, 0.3, 0.3], [0, 0, 0, 0]]], np.float32)
    gl = np.array([[1, -1]], np.int32)  # one real box, one padding
    loc = np.zeros((1, 3, 4), np.float32)
    conf_good = np.zeros((1, 3, 3), np.float32)
    conf_good[0, 0, 1] = 8.0   # matched prior confident in class 1
    conf_good[0, 1, 0] = 8.0   # negatives confident background
    conf_good[0, 2, 0] = 8.0
    conf_bad = np.zeros((1, 3, 3), np.float32)
    conf_bad[0, 0, 0] = 8.0    # matched prior says background
    good = run_op("multibox_loss",
                  {"Loc": loc, "Conf": conf_good, "PriorBox": prior,
                   "GtBox": gt, "GtLabel": gl})["Loss"]
    bad = run_op("multibox_loss",
                 {"Loc": loc, "Conf": conf_bad, "PriorBox": prior,
                  "GtBox": gt, "GtLabel": gl})["Loss"]
    assert float(good) < 0.1 < float(bad)
    # zero loc offsets on an exactly-matching prior: loc loss ~ 0, so the
    # good case is nearly pure (tiny) conf loss
    assert np.isfinite(good).all() and np.isfinite(bad).all()


def test_multibox_loss_bipartite_not_clobbered_by_padding():
    # A valid gt whose best-overlap prior is index 0 with IoU below the
    # threshold (0.33): only the bipartite stage can match it. Padded gts
    # also argmax to prior 0 — their scatter writes must be dropped, not
    # clobber the forced match.
    prior = np.array([[0.1, 0.1, 0.3, 0.3], [0.6, 0.6, 0.9, 0.9],
                      [0.4, 0.1, 0.5, 0.2]], np.float32)
    gt = np.array([[[0.2, 0.1, 0.4, 0.3], [0, 0, 0, 0]]], np.float32)
    gl = np.array([[1, -1]], np.int32)  # one real box, one padding
    loc = np.zeros((1, 3, 4), np.float32)
    conf_good = np.zeros((1, 3, 3), np.float32)
    conf_good[0, 0, 1] = 8.0   # forced-matched prior confident in class 1
    conf_good[0, 1, 0] = 8.0
    conf_good[0, 2, 0] = 8.0
    conf_bad = conf_good.copy()
    conf_bad[0, 0] = [8.0, 0.0, 0.0]  # forced prior says background
    good = run_op("multibox_loss",
                  {"Loc": loc, "Conf": conf_good, "PriorBox": prior,
                   "GtBox": gt, "GtLabel": gl})["Loss"]
    bad = run_op("multibox_loss",
                 {"Loc": loc, "Conf": conf_bad, "PriorBox": prior,
                  "GtBox": gt, "GtLabel": gl})["Loss"]
    # if the forced match were clobbered, prior 0 would count as a negative
    # and the "bad" conf (background there) would score LOW
    assert float(bad[0, 0]) > float(good[0, 0])
    assert float(bad[0, 0]) > 1.0
