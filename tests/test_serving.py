"""Continuous-batching serving engine (paddle_tpu/serving/) — slot
lifecycle, EOS eviction, bucketed-prefill compile bound, token-identity
vs the single-stream decode, and serving.* metrics exposure.  All on the
CPU mesh (conftest), tiny model shapes."""

import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models import transformer
from paddle_tpu.observability import metrics as _obs
from paddle_tpu.serving import ServingEngine


def _make_params(vocab=50, n_layer=2, n_head=2, d_model=32, max_len=32,
                 dtype="float32", seed=7):
    """Randomly initialized flagship weights (serving doesn't need a
    trained model: greedy chains over random weights are deterministic)."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        transformer.build(vocab_size=vocab, n_layer=n_layer, n_head=n_head,
                          d_model=d_model, max_len=max_len,
                          dropout_rate=0.0, dtype=dtype)
    exe = pt.Executor()
    exe.run(startup)
    return transformer.extract_params(program=main)


VOCAB, NL, NH, DM, T = 50, 2, 2, 32, 32


@pytest.fixture
def params():
    return _make_params(VOCAB, NL, NH, DM, T)


@pytest.fixture(autouse=True)
def fresh_serving_metrics():
    _obs.get_registry().clear(prefix="serving.")
    yield


def _engine(params, **kw):
    kw.setdefault("max_len", T)
    kw.setdefault("max_slots", 4)
    kw.setdefault("decode_chunk", 4)
    kw.setdefault("min_bucket", 4)
    return ServingEngine(params, NL, NH, DM, **kw)


def test_slot_admit_free_lifecycle(params):
    """More requests than slots: all admitted (continuous batching waves),
    every slot freed at the end, queue drained, counters consistent."""
    eng = _engine(params, max_slots=2)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, VOCAB, (l,)) for l in (3, 5, 2, 4, 6)]
    reqs = [eng.submit(p, max_new_tokens=6) for p in prompts]
    assert eng.stats()["serving.queue_depth"] == 5
    eng.run_until_idle()
    assert all(r.done for r in reqs)
    assert eng.active_slots == 0 and eng.idle
    st = eng.stats()
    assert st["serving.queue_depth"] == 0
    assert st["serving.slots_active"] == 0
    assert st["serving.admitted"] == 5
    assert st["serving.completed"] == 5
    # every request got exactly its token budget (no EOS configured)
    for r, p in zip(reqs, prompts):
        out = r.result(timeout=0)
        assert out.shape == (len(p) + 6,)
        np.testing.assert_array_equal(out[: len(p)], p)
    # finished handles surface through results() exactly once
    done = eng.results()
    assert {r.rid for r in done} == {r.rid for r in reqs}
    assert eng.results() == []


def test_eos_evicts_slot_early(params):
    """A request whose greedy chain hits EOS frees its slot early and its
    output stops AT the EOS token."""
    rng = np.random.default_rng(1)
    prompt = rng.integers(1, VOCAB, (4,))
    # learn the chain once without EOS, then re-serve with eos_id set to
    # a token the chain is known to emit
    eng = _engine(params)
    full = eng.generate_many([prompt], max_new_tokens=12)[0]
    gen = full[4:]
    eos = int(gen[len(gen) // 2])  # a mid-stream token
    cut = list(gen).index(eos)

    _obs.get_registry().clear(prefix="serving.")  # counters are global
    eng2 = _engine(params)
    out = eng2.generate_many([prompt], max_new_tokens=12, eos_id=eos)[0]
    np.testing.assert_array_equal(out, full[: 4 + cut + 1])
    assert out[-1] == eos
    assert eng2.active_slots == 0
    # fewer decode tokens than the no-EOS run (the slot really left)
    assert eng2.stats()["serving.completed"] == 1


def test_bucketed_prefill_bounds_compiles(params):
    """50+ mixed-length requests: executables == used prefill buckets + 1
    decode chunk, regardless of request count."""
    eng = _engine(params, max_slots=8, min_bucket=4)
    rng = np.random.default_rng(2)
    n = 52
    lens = rng.integers(1, 14, n)  # buckets {4, 8, 16}
    prompts = [rng.integers(1, VOCAB, (int(l),)) for l in lens]
    outs = eng.generate_many(prompts, max_new_tokens=4)
    assert len(outs) == n
    buckets = {eng.bucket_for(int(l)) for l in lens}
    st = eng.stats()
    assert st["serving.prefill_compiles"] == len(buckets) <= 3
    assert st["serving.decode_compiles"] == 1
    assert st["serving.admitted"] == n
    assert st["serving.completed"] == n
    # the counters must reflect REAL jit-cache entries: one executable
    # per bucket callable / per decode chunk, no silent retraces
    assert eng._decode_fn._cache_size() == 1
    assert sorted(eng._prefill_fns) == sorted(buckets)
    assert all(f._cache_size() == 1 for f in eng._prefill_fns.values())


def test_batched_decode_token_identical_to_single_stream(params):
    """The acceptance bar: any request served through the batched engine
    produces exactly the tokens of running it ALONE through
    transformer.generate (greedy, same weights) — mixed lengths, slot
    reuse, mid-stream admissions and all."""
    eng = _engine(params, max_slots=3, decode_chunk=5)
    rng = np.random.default_rng(3)
    specs = [(3, 8), (7, 12), (1, 20), (9, 5), (4, 16), (12, 9), (2, 11)]
    prompts = [rng.integers(1, VOCAB, (pl,)) for pl, _ in specs]
    max_new = [mn for _, mn in specs]
    outs = eng.generate_many(prompts, max_new)
    for p, m, o in zip(prompts, max_new, outs):
        ref, _ = transformer.generate(params, p[None], max_len=T,
                                      n_layer=NL, n_head=NH, d_model=DM,
                                      return_logits=False)
        np.testing.assert_array_equal(o, np.asarray(ref)[0][: len(p) + m])


def test_bf16_weights_serve_in_bf16_and_match(params):
    """bf16 block weights: the engine infers bf16 compute (cache
    discipline) and still matches the single-stream bf16 decode."""
    import jax.numpy as jnp

    p16 = {k: (jnp.asarray(v, jnp.bfloat16)
               if (k.startswith("block") or k.startswith("lm_head"))
               and k.endswith(".w") else v)
           for k, v in params.items()}
    eng = _engine(p16)
    assert eng.compute_dtype == jnp.bfloat16
    rng = np.random.default_rng(4)
    prompts = [rng.integers(1, VOCAB, (l,)) for l in (3, 6)]
    outs = eng.generate_many(prompts, max_new_tokens=8)
    for p, o in zip(prompts, outs):
        ref, _ = transformer.generate(p16, p[None], max_len=T, n_layer=NL,
                                      n_head=NH, d_model=DM,
                                      return_logits=False)
        np.testing.assert_array_equal(o, np.asarray(ref)[0][: len(p) + 8])


def test_serving_metrics_exposed(params):
    """The telemetry contract: TTFT/e2e histograms count one observation
    per request, token counter matches emitted tokens, and everything
    reaches the Prometheus exposition."""
    eng = _engine(params)
    rng = np.random.default_rng(5)
    prompts = [rng.integers(1, VOCAB, (l,)) for l in (2, 5, 3)]
    reqs = [eng.submit(p, max_new_tokens=5) for p in prompts]
    eng.run_until_idle()
    st = eng.stats()
    assert st["serving.ttft_seconds"]["count"] == 3
    assert st["serving.e2e_seconds"]["count"] == 3
    assert st["serving.tokens"] >= 3 * 5  # budget + discarded mid-chunk
    assert st["serving.step_seconds"]["count"] >= 1
    assert st["serving.prefill_seconds"]["count"] == 3
    assert st["serving.slots_total"] == 4
    for r in reqs:
        assert r.ttft is not None and r.e2e is not None
        assert 0 <= r.ttft <= r.e2e
    text = _obs.get_registry().to_text()
    for frag in ("serving_ttft_seconds", "serving_tok_s",
                 "serving_queue_depth", "serving_admitted"):
        assert frag in text, frag


def test_submit_validation(params):
    eng = _engine(params)
    with pytest.raises(ValueError):
        eng.submit([], max_new_tokens=4)
    with pytest.raises(ValueError):
        eng.submit([1, 2], max_new_tokens=0)
    with pytest.raises(ValueError):  # p_len + max_new > max_len
        eng.submit(np.ones(20, np.int32), max_new_tokens=T)


def test_engine_abort_fails_pending_requests(params):
    """A device error mid-serve is fatal (donated caches are gone): the
    engine aborts, waiters wake with ``error`` set instead of hanging,
    and further submits raise."""
    eng = _engine(params)

    def boom():
        raise RuntimeError("device gone")

    eng._admit = boom
    eng.start()
    try:
        req = eng.submit(np.asarray([1, 2, 3]), max_new_tokens=4)
        assert req.wait(timeout=60), "abort did not wake the waiter"
        assert req.error is not None
        with pytest.raises(RuntimeError):
            req.result(timeout=0)
        with pytest.raises(RuntimeError):
            eng.submit([1], max_new_tokens=1)
        (failed,) = eng.results()
        assert failed is req
        assert eng.stats()["serving.aborted"] == 1
    finally:
        eng.stop()


def test_driver_thread_death_fails_pending_requests(params):
    """ISSUE 8 satellite: a driver thread that DIES (an exception
    ``step()`` does not turn into an abort — here a ``BaseException``
    escaping the loop) must fail every pending/queued request with the
    captured exception so ``result(timeout=None)`` returns instead of
    hanging forever, and ``submit()`` after the death raises
    immediately."""
    import threading

    eng = _engine(params)

    class DriverKilled(BaseException):  # escapes step()'s Exception catch
        pass

    def boom():
        raise DriverKilled("driver thread killed")

    eng._admit = boom
    eng.start()
    try:
        req = eng.submit(np.asarray([1, 2, 3]), max_new_tokens=4)
        # result(timeout=None) is the hang the supervision removes: run
        # it on a side thread with a bounded join so a regression fails
        # the test instead of wedging the suite
        got = {}

        def wait_forever():
            try:
                got["val"] = req.result(timeout=None)
            except BaseException as e:  # noqa: BLE001
                got["err"] = e

        t = threading.Thread(target=wait_forever, daemon=True)
        t.start()
        t.join(timeout=60)
        assert not t.is_alive(), \
            "result(timeout=None) still hangs after driver death"
        assert isinstance(got.get("err"), RuntimeError)
        assert isinstance(req.error, DriverKilled)
        # the dead driver is observable and rejects new work
        for _ in range(200):
            if not eng.driver_alive():
                break
            time.sleep(0.01)
        assert not eng.driver_alive()
        with pytest.raises(RuntimeError):
            eng.submit([1], max_new_tokens=1)
        assert _obs.get_registry().value("serving.driver_deaths") == 1
    finally:
        eng.stop()  # must not hang on the drain either


def test_background_thread_driver(params):
    """start()/stop() + concurrent submit: the Poisson-load path the
    serving benchmark uses."""
    eng = _engine(params, max_slots=2)
    eng.start()
    try:
        rng = np.random.default_rng(6)
        reqs = [eng.submit(rng.integers(1, VOCAB, (3,)), max_new_tokens=6)
                for _ in range(5)]
        for r in reqs:
            assert r.wait(timeout=60), "request did not finish"
        done = eng.results()
        assert {r.rid for r in done} == {r.rid for r in reqs}
    finally:
        eng.stop()
    assert eng.idle


# -- SLO budgets + goodput (ISSUE 11: goodput-under-SLO measurement) --------

def test_slo_violations_counted(params):
    """An impossibly tight TTFT budget: every completed request is a
    violation, goodput stays zero, and each handle carries its
    verdict."""
    eng = _engine(params, ttft_slo_s=1e-9)
    rng = np.random.default_rng(2)
    prompts = [rng.integers(1, VOCAB, (4,)) for _ in range(3)]
    eng.generate_many(prompts, max_new_tokens=4)
    st = eng.stats()
    assert st["serving.slo_violations"] == 3
    assert st["serving.goodput_tok_s"] == 0.0
    assert all(r.slo_ok is False for r in eng.results())


def test_goodput_counts_slo_met_tokens(params):
    """Generous budgets: zero violations, goodput > 0, verdicts True."""
    eng = _engine(params, ttft_slo_s=600.0, e2e_slo_s=600.0)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, VOCAB, (4,)) for _ in range(3)]
    eng.generate_many(prompts, max_new_tokens=4)
    st = eng.stats()
    assert st.get("serving.slo_violations", 0) == 0
    assert st["serving.goodput_tok_s"] > 0
    assert all(r.slo_ok is True for r in eng.results())


def test_no_slo_configured_leaves_verdict_none(params):
    eng = _engine(params)
    eng.generate_many([np.arange(1, 4, dtype=np.int32)],
                      max_new_tokens=3)
    st = eng.stats()
    assert "serving.slo_violations" not in st
    assert all(r.slo_ok is None for r in eng.results())


def test_reset_slo_accounting_reopens_window(params):
    """The bench warm-pass contract: resetting after warm requests
    zeroes the violation counter and the goodput window."""
    eng = _engine(params, ttft_slo_s=1e-9)
    eng.generate_many([np.arange(1, 4, dtype=np.int32)],
                      max_new_tokens=3)
    assert eng.stats()["serving.slo_violations"] == 1
    eng.reset_slo_accounting()
    assert eng.stats()["serving.slo_violations"] == 0
    assert eng.stats()["serving.goodput_tok_s"] == 0.0
    eng.ttft_slo_s = 600.0
    eng.e2e_slo_s = 600.0
    eng.generate_many([np.arange(1, 4, dtype=np.int32)],
                      max_new_tokens=3)
    st = eng.stats()
    assert st["serving.slo_violations"] == 0
    assert st["serving.goodput_tok_s"] > 0


def test_slo_budget_validation(params):
    with pytest.raises(ValueError):
        _engine(params, ttft_slo_s=0)
    with pytest.raises(ValueError):
        _engine(params, e2e_slo_s=-1.0)
    with pytest.raises(ValueError):  # undersized pool must not
        _engine(params, cache_blocks=-1)  # construct-then-abort


def test_reset_slo_accounting_rearms_window_origin(params):
    """ISSUE 12 small fix: the goodput window ORIGIN must re-arm on
    reset — after a warm pass plus a dead gap, the timed run's
    ``serving.goodput_tok_s`` denominator starts at the timed run's
    first submit, not back at the warm pass's (which would understate
    goodput by the whole gap)."""
    eng = _engine(params, ttft_slo_s=600.0, e2e_slo_s=600.0)
    eng.generate_many([np.arange(1, 4, dtype=np.int32)],
                      max_new_tokens=3)  # warm pass (opens a window)
    time.sleep(0.3)                      # the dead gap between passes
    eng.reset_slo_accounting()
    assert eng._first_submit_t is None   # origin re-armed
    t0 = time.perf_counter()
    eng.generate_many([np.arange(1, 4, dtype=np.int32)],
                      max_new_tokens=3)
    timed_window = time.perf_counter() - t0
    good = eng.stats()["serving.goodput_tok_s"]
    # 3 good tokens over (at most) the timed window; a stale origin
    # would divide by >= 0.3s extra and land far below this bound
    assert good >= 3 / (timed_window + 0.15), \
        f"goodput {good} suggests the window origin was not re-armed"
    # the reset also zeroes the shed/prefix/CoW accounting windows
    eng.reset_slo_accounting()
    st = eng.stats()
    assert st.get("serving.prefix_hit_rate", 0.0) == 0.0
    assert st.get("serving.shed_total", 0) == 0
    assert st.get("serving.cow_copies", 0) == 0


# -- SLO scheduler: predictor, reorder, shed (ISSUE 12 control half) --------

def test_predictor_learns_and_predicts():
    from paddle_tpu.serving.scheduler import TtftPredictor

    p = TtftPredictor()
    assert not p.ready
    p.observe_prefill(8, 0.10)
    p.observe_chunk(0.05, steps=4)
    assert p.ready
    assert p.prefill_s(8) == pytest.approx(0.10)
    # unseen bucket scales by token ratio off the nearest observed one
    assert p.prefill_s(16) == pytest.approx(0.20)
    # 9 new tokens: 1 rides prefill, 8 more need 2 chunks of 4
    assert p.decode_s(9) == pytest.approx(0.10)
    assert p.min_service_s(8, 9) == pytest.approx(0.20)


def test_slo_scheduler_reorders_by_slack_and_sheds():
    import collections
    import types

    from paddle_tpu.serving.scheduler import SloScheduler, TtftPredictor

    pred = TtftPredictor()
    pred.observe_prefill(8, 0.1)
    pred.observe_chunk(0.1, steps=4)
    budgets = types.SimpleNamespace(ttft_slo_s=None, e2e_slo_s=None)
    sched = SloScheduler(pred, budgets)

    def req(rid, age, ttft_b=None, e2e_b=None, max_new=8):
        r = types.SimpleNamespace(
            rid=rid, submit_t=-age, max_new=max_new,
            ttft_slo_s=ttft_b, e2e_slo_s=e2e_b,
            prompt=np.zeros(4, np.int32))
        return r

    # tight-budget request jumps the queue (least slack first)
    q = collections.deque([req(0, age=0.0, ttft_b=10.0),
                           req(1, age=0.0, ttft_b=0.5),
                           req(2, age=0.0)])          # unbudgeted: last
    pick, shed = sched.pick(q, now=0.0, bucket_of=lambda r: 8)
    assert pick.rid == 1 and shed == []
    assert [r.rid for r in q] == [0, 2]

    # a request whose age + optimistic service already exceeds its e2e
    # budget is shed; the rest survive
    q = collections.deque([req(3, age=5.0, e2e_b=1.0),
                           req(4, age=0.0, e2e_b=60.0)])
    pick, shed = sched.pick(q, now=0.0, bucket_of=lambda r: 8)
    assert [r.rid for r in shed] == [3]
    assert pick.rid == 4 and not q

    # a COLD predictor never sheds (optimistic-bound contract)
    cold = SloScheduler(TtftPredictor(), budgets)
    q = collections.deque([req(5, age=5.0, e2e_b=0.001)])
    pick, shed = cold.pick(q, now=0.0, bucket_of=lambda r: 8)
    assert pick.rid == 5 and shed == []


def test_engine_sheds_doomed_requests(params):
    """End-to-end shed: with a warmed predictor and an impossible e2e
    budget, queued requests are refused — ``shed`` True, ``result()``
    raises SheddedRequest, ``serving.shed_total`` counts — while the
    admissible request is served."""
    from paddle_tpu.serving import SheddedRequest

    eng = _engine(params, max_slots=1)
    rng = np.random.default_rng(11)
    eng.generate_many([rng.integers(1, VOCAB, (4,))],
                      max_new_tokens=8)   # warm the predictor
    assert eng.predictor.ready
    doomed = eng.submit(rng.integers(1, VOCAB, (4,)), max_new_tokens=8,
                        e2e_slo_s=1e-6)
    fine = eng.submit(rng.integers(1, VOCAB, (4,)), max_new_tokens=8)
    eng.run_until_idle()
    assert doomed.shed and doomed.slo_ok is False
    with pytest.raises(SheddedRequest):
        doomed.result(timeout=0)
    np.testing.assert_array_equal(
        fine.result(timeout=0)[:4], fine.prompt)
    st = eng.stats()
    assert st["serving.shed_total"] == 1
    assert st["serving.completed"] == 2  # warm + fine (shed excluded)
    assert eng.idle and eng.kv_pool.blocks_in_use >= 0


def test_fifo_scheduler_is_pr2_spelling(params):
    """scheduler="fifo" + prefix_reuse=False: arrival order, no shed,
    no trie — the benchmark baseline — still token-identical."""
    eng = _engine(params, scheduler="fifo", prefix_reuse=False,
                  max_slots=2)
    assert eng.prefix_trie is None
    rng = np.random.default_rng(12)
    prompts = [rng.integers(1, VOCAB, (l,)) for l in (3, 5, 4)]
    outs = eng.generate_many(prompts, max_new_tokens=6)
    for p, o in zip(prompts, outs):
        ref, _ = transformer.generate(params, p[None], max_len=T,
                                      n_layer=NL, n_head=NH, d_model=DM,
                                      return_logits=False)
        np.testing.assert_array_equal(o, np.asarray(ref)[0][: len(p) + 6])
    assert eng.stats().get("serving.shed_total", 0) == 0


def test_per_request_budgets_override_engine_defaults(params):
    """submit(ttft_slo_s=, e2e_slo_s=) wins over the engine defaults in
    the SLO verdict."""
    eng = _engine(params, ttft_slo_s=600.0, e2e_slo_s=600.0)
    rng = np.random.default_rng(13)
    loose = eng.submit(rng.integers(1, VOCAB, (4,)), max_new_tokens=4)
    tight = eng.submit(rng.integers(1, VOCAB, (4,)), max_new_tokens=4,
                       ttft_slo_s=1e-9)
    eng.run_until_idle()
    assert loose.slo_ok is True
    assert tight.slo_ok is False
    assert eng.stats()["serving.slo_violations"] == 1


def test_generate_many_is_never_shed(params):
    """The synchronous batch front-end waits for every result, so its
    requests are exempt from scheduler shedding — an impossible e2e
    budget yields N complete outputs (judged as violations), never a
    SheddedRequest destroying the batch."""
    eng = _engine(params, e2e_slo_s=1e-6, max_slots=1)
    rng = np.random.default_rng(15)
    eng.generate_many([rng.integers(1, VOCAB, (4,))],
                      max_new_tokens=4)   # warm the predictor
    assert eng.predictor.ready
    prompts = [rng.integers(1, VOCAB, (4,)) for _ in range(3)]
    outs = eng.generate_many(prompts, max_new_tokens=4)
    assert len(outs) == 3 and all(o.shape == (8,) for o in outs)
    st = eng.stats()
    assert st.get("serving.shed_total", 0) == 0
    assert st["serving.slo_violations"] == 4  # warm + 3, all judged


def test_sched_bucket_is_reuse_aware(params):
    """The scheduler's prefill estimate probes the trie (without
    touching LRU clocks): a mostly-cached prompt is costed at its
    suffix bucket, so the shed bound stays optimistic — a request reuse
    would save is never refused on full-prefill cost."""
    eng = _engine(params, block_tokens=4)
    rng = np.random.default_rng(16)
    base = rng.integers(1, VOCAB, (12,)).astype(np.int32)
    req = eng.submit(base.copy(), max_new_tokens=4)
    assert eng._sched_bucket(req) == eng.bucket_for(12)  # cold: full
    eng.run_until_idle()
    req2 = eng.submit(base.copy(), max_new_tokens=4)
    # 11 of 12 tokens cached (2 full blocks + 3-token CoW) -> suffix 1
    assert eng._sched_bucket(req2) == eng.bucket_for(1)
    def all_clocks(trie):
        out, stack = {}, list(trie._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            out[id(n)] = n.last_used
        return out

    before = all_clocks(eng.prefix_trie)
    eng.prefix_trie.peek_hit(base, 11)
    assert all_clocks(eng.prefix_trie) == before  # LRU untouched
    eng.run_until_idle()


def test_pool_backpressure_requeues_and_counts_wait_once(params):
    """PoolExhausted at admission re-queues the victim at the front and
    retries once decode frees blocks; its serving.queue_wait is
    observed exactly once, at the admission that sticks."""
    eng = _engine(params, max_slots=2, block_tokens=4, cache_blocks=0,
                  prefix_reuse=False)
    rng = np.random.default_rng(17)
    a = eng.submit(rng.integers(1, VOCAB, (9,)), max_new_tokens=8)
    eng.step()                         # A admitted and decoding
    hoard = eng.kv_pool.alloc(eng.kv_pool.free_blocks)  # starve the pool
    b = eng.submit(rng.integers(1, VOCAB, (9,)), max_new_tokens=8)
    eng.step()                         # B hits PoolExhausted, re-queued
    assert not b.done and b.admit_t is None
    with eng._qlock:
        assert eng._queue[0] is b
    for blk in hoard:
        eng.kv_pool.deref(blk)
    eng.run_until_idle()
    assert a.error is None and b.error is None
    assert eng.stats()["serving.queue_wait"]["count"] == 2  # once each
    assert eng.kv_pool.blocks_in_use == 0


# -- slot-death fault injection (ISSUE 12 satellite) ------------------------

def test_slot_death_reclaims_blocks_and_driver_survives(params):
    """PADDLE_TPU_FAULT=slot_death:n kills one active request
    mid-decode: its KV blocks and slot are reclaimed (pool accounting
    returns to baseline — no block leak), the victim's handle completes
    with ``error`` set, and the background driver keeps serving the
    rest of the load."""
    import os

    from paddle_tpu.resilience import faults

    eng = _engine(params, max_slots=3, prefix_reuse=False)
    rng = np.random.default_rng(14)
    baseline_in_use = eng.kv_pool.blocks_in_use
    os.environ["PADDLE_TPU_FAULT"] = "slot_death:2"
    faults.reset()
    eng.start()
    try:
        reqs = [eng.submit(rng.integers(1, VOCAB, (5,)),
                           max_new_tokens=10) for _ in range(6)]
        for r in reqs:
            assert r.wait(timeout=120), "request did not finish"
    finally:
        eng.stop()
        os.environ.pop("PADDLE_TPU_FAULT", None)
        faults.reset()
    dead = [r for r in reqs if r.error is not None]
    ok = [r for r in reqs if r.error is None]
    assert len(dead) == 1 and len(ok) == 5
    # the victim's tokens stopped mid-stream; the survivors are exact
    for r in ok:
        ref, _ = transformer.generate(params, r.prompt[None], max_len=T,
                                      n_layer=NL, n_head=NH, d_model=DM,
                                      return_logits=False)
        np.testing.assert_array_equal(
            r.result(timeout=0),
            np.asarray(ref)[0][: len(r.prompt) + 10])
    # no block leak: pool accounting back to baseline, table zeroed
    assert eng.kv_pool.blocks_in_use == baseline_in_use == 0
    assert (eng._table == 0).all()
    st = eng.stats()
    assert st["serving.slot_deaths"] == 1
    assert st["serving.completed"] == 5
    assert eng.idle


# -- tuned decode geometry (op=serving_decode, ISSUE 12 satellite) ----------

def test_engine_consults_tuned_serving_geometry(params, tmp_path,
                                                monkeypatch):
    """docs/autotune.md "Adding a tunable op": a measured
    tune_serving_decode search persists {chunk, min_bucket} under
    op=serving_decode, and an engine constructed with NO explicit
    geometry picks the winner up; explicit arguments still win; the
    kill switch keeps the hand-picked defaults."""
    from paddle_tpu import tune

    monkeypatch.setenv("PADDLE_TPU_TUNE_CACHE",
                       str(tmp_path / "tuned.json"))
    monkeypatch.setenv("PADDLE_TPU_TUNE", "search")
    tune.reset_cache()
    try:
        report = tune.tune_serving_decode(
            params, NL, NH, DM, max_len=T, max_slots=2, requests=3,
            prompt_len=4, max_new=4, chunks=(2, 4), min_buckets=(4,),
            max_measure=4)
        assert report["source"] == "search"
        win = report["entry"]["config"]
        assert set(win) == {"chunk", "min_bucket"}

        # default-geometry engine resolves the tuned winner
        monkeypatch.setenv("PADDLE_TPU_TUNE", "cached")
        eng = _engine(params, decode_chunk=None, min_bucket=None)
        assert eng.decode_chunk == win["chunk"]
        assert eng.min_bucket == win["min_bucket"]

        # explicit args always win
        eng2 = _engine(params, decode_chunk=7, min_bucket=16)
        assert eng2.decode_chunk == 7 and eng2.min_bucket == 16

        # kill switch: hand-picked defaults, no lookup at all
        monkeypatch.setenv("PADDLE_TPU_TUNE", "off")
        eng3 = _engine(params, decode_chunk=None, min_bucket=None)
        assert eng3.decode_chunk == 4 and eng3.min_bucket == 8

        # the search keys on the dtype the engine will SERVE in: bf16
        # weights must land under dt=bfloat16, the key the engine's
        # lookup queries (a float32 default would be a silent miss)
        import jax.numpy as jnp

        monkeypatch.setenv("PADDLE_TPU_TUNE", "cached")
        p16 = {k: (jnp.asarray(v, jnp.bfloat16)
                   if (k.startswith("block") or k.startswith("lm_head"))
                   and k.endswith(".w") else v)
               for k, v in params.items()}
        rep16 = tune.tune_serving_decode(p16, NL, NH, DM, max_len=T)
        assert "dt=bfloat16" in rep16["key"]
    finally:
        tune.reset_cache()
