"""Continuous-batching serving engine (paddle_tpu/serving/) — slot
lifecycle, EOS eviction, bucketed-prefill compile bound, token-identity
vs the single-stream decode, and serving.* metrics exposure.  All on the
CPU mesh (conftest), tiny model shapes."""

import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models import transformer
from paddle_tpu.observability import metrics as _obs
from paddle_tpu.serving import ServingEngine


def _make_params(vocab=50, n_layer=2, n_head=2, d_model=32, max_len=32,
                 dtype="float32", seed=7):
    """Randomly initialized flagship weights (serving doesn't need a
    trained model: greedy chains over random weights are deterministic)."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        transformer.build(vocab_size=vocab, n_layer=n_layer, n_head=n_head,
                          d_model=d_model, max_len=max_len,
                          dropout_rate=0.0, dtype=dtype)
    exe = pt.Executor()
    exe.run(startup)
    return transformer.extract_params(program=main)


VOCAB, NL, NH, DM, T = 50, 2, 2, 32, 32


@pytest.fixture
def params():
    return _make_params(VOCAB, NL, NH, DM, T)


@pytest.fixture(autouse=True)
def fresh_serving_metrics():
    _obs.get_registry().clear(prefix="serving.")
    yield


def _engine(params, **kw):
    kw.setdefault("max_len", T)
    kw.setdefault("max_slots", 4)
    kw.setdefault("decode_chunk", 4)
    kw.setdefault("min_bucket", 4)
    return ServingEngine(params, NL, NH, DM, **kw)


def test_slot_admit_free_lifecycle(params):
    """More requests than slots: all admitted (continuous batching waves),
    every slot freed at the end, queue drained, counters consistent."""
    eng = _engine(params, max_slots=2)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, VOCAB, (l,)) for l in (3, 5, 2, 4, 6)]
    reqs = [eng.submit(p, max_new_tokens=6) for p in prompts]
    assert eng.stats()["serving.queue_depth"] == 5
    eng.run_until_idle()
    assert all(r.done for r in reqs)
    assert eng.active_slots == 0 and eng.idle
    st = eng.stats()
    assert st["serving.queue_depth"] == 0
    assert st["serving.slots_active"] == 0
    assert st["serving.admitted"] == 5
    assert st["serving.completed"] == 5
    # every request got exactly its token budget (no EOS configured)
    for r, p in zip(reqs, prompts):
        out = r.result(timeout=0)
        assert out.shape == (len(p) + 6,)
        np.testing.assert_array_equal(out[: len(p)], p)
    # finished handles surface through results() exactly once
    done = eng.results()
    assert {r.rid for r in done} == {r.rid for r in reqs}
    assert eng.results() == []


def test_eos_evicts_slot_early(params):
    """A request whose greedy chain hits EOS frees its slot early and its
    output stops AT the EOS token."""
    rng = np.random.default_rng(1)
    prompt = rng.integers(1, VOCAB, (4,))
    # learn the chain once without EOS, then re-serve with eos_id set to
    # a token the chain is known to emit
    eng = _engine(params)
    full = eng.generate_many([prompt], max_new_tokens=12)[0]
    gen = full[4:]
    eos = int(gen[len(gen) // 2])  # a mid-stream token
    cut = list(gen).index(eos)

    _obs.get_registry().clear(prefix="serving.")  # counters are global
    eng2 = _engine(params)
    out = eng2.generate_many([prompt], max_new_tokens=12, eos_id=eos)[0]
    np.testing.assert_array_equal(out, full[: 4 + cut + 1])
    assert out[-1] == eos
    assert eng2.active_slots == 0
    # fewer decode tokens than the no-EOS run (the slot really left)
    assert eng2.stats()["serving.completed"] == 1


def test_bucketed_prefill_bounds_compiles(params):
    """50+ mixed-length requests: executables == used prefill buckets + 1
    decode chunk, regardless of request count."""
    eng = _engine(params, max_slots=8, min_bucket=4)
    rng = np.random.default_rng(2)
    n = 52
    lens = rng.integers(1, 14, n)  # buckets {4, 8, 16}
    prompts = [rng.integers(1, VOCAB, (int(l),)) for l in lens]
    outs = eng.generate_many(prompts, max_new_tokens=4)
    assert len(outs) == n
    buckets = {eng.bucket_for(int(l)) for l in lens}
    st = eng.stats()
    assert st["serving.prefill_compiles"] == len(buckets) <= 3
    assert st["serving.decode_compiles"] == 1
    assert st["serving.admitted"] == n
    assert st["serving.completed"] == n
    # the counters must reflect REAL jit-cache entries: one executable
    # per bucket callable / per decode chunk, no silent retraces
    assert eng._decode_fn._cache_size() == 1
    assert sorted(eng._prefill_fns) == sorted(buckets)
    assert all(f._cache_size() == 1 for f in eng._prefill_fns.values())


def test_batched_decode_token_identical_to_single_stream(params):
    """The acceptance bar: any request served through the batched engine
    produces exactly the tokens of running it ALONE through
    transformer.generate (greedy, same weights) — mixed lengths, slot
    reuse, mid-stream admissions and all."""
    eng = _engine(params, max_slots=3, decode_chunk=5)
    rng = np.random.default_rng(3)
    specs = [(3, 8), (7, 12), (1, 20), (9, 5), (4, 16), (12, 9), (2, 11)]
    prompts = [rng.integers(1, VOCAB, (pl,)) for pl, _ in specs]
    max_new = [mn for _, mn in specs]
    outs = eng.generate_many(prompts, max_new)
    for p, m, o in zip(prompts, max_new, outs):
        ref, _ = transformer.generate(params, p[None], max_len=T,
                                      n_layer=NL, n_head=NH, d_model=DM,
                                      return_logits=False)
        np.testing.assert_array_equal(o, np.asarray(ref)[0][: len(p) + m])


def test_bf16_weights_serve_in_bf16_and_match(params):
    """bf16 block weights: the engine infers bf16 compute (cache
    discipline) and still matches the single-stream bf16 decode."""
    import jax.numpy as jnp

    p16 = {k: (jnp.asarray(v, jnp.bfloat16)
               if (k.startswith("block") or k.startswith("lm_head"))
               and k.endswith(".w") else v)
           for k, v in params.items()}
    eng = _engine(p16)
    assert eng.compute_dtype == jnp.bfloat16
    rng = np.random.default_rng(4)
    prompts = [rng.integers(1, VOCAB, (l,)) for l in (3, 6)]
    outs = eng.generate_many(prompts, max_new_tokens=8)
    for p, o in zip(prompts, outs):
        ref, _ = transformer.generate(p16, p[None], max_len=T, n_layer=NL,
                                      n_head=NH, d_model=DM,
                                      return_logits=False)
        np.testing.assert_array_equal(o, np.asarray(ref)[0][: len(p) + 8])


def test_serving_metrics_exposed(params):
    """The telemetry contract: TTFT/e2e histograms count one observation
    per request, token counter matches emitted tokens, and everything
    reaches the Prometheus exposition."""
    eng = _engine(params)
    rng = np.random.default_rng(5)
    prompts = [rng.integers(1, VOCAB, (l,)) for l in (2, 5, 3)]
    reqs = [eng.submit(p, max_new_tokens=5) for p in prompts]
    eng.run_until_idle()
    st = eng.stats()
    assert st["serving.ttft_seconds"]["count"] == 3
    assert st["serving.e2e_seconds"]["count"] == 3
    assert st["serving.tokens"] >= 3 * 5  # budget + discarded mid-chunk
    assert st["serving.step_seconds"]["count"] >= 1
    assert st["serving.prefill_seconds"]["count"] == 3
    assert st["serving.slots_total"] == 4
    for r in reqs:
        assert r.ttft is not None and r.e2e is not None
        assert 0 <= r.ttft <= r.e2e
    text = _obs.get_registry().to_text()
    for frag in ("serving_ttft_seconds", "serving_tok_s",
                 "serving_queue_depth", "serving_admitted"):
        assert frag in text, frag


def test_submit_validation(params):
    eng = _engine(params)
    with pytest.raises(ValueError):
        eng.submit([], max_new_tokens=4)
    with pytest.raises(ValueError):
        eng.submit([1, 2], max_new_tokens=0)
    with pytest.raises(ValueError):  # p_len + max_new > max_len
        eng.submit(np.ones(20, np.int32), max_new_tokens=T)


def test_engine_abort_fails_pending_requests(params):
    """A device error mid-serve is fatal (donated caches are gone): the
    engine aborts, waiters wake with ``error`` set instead of hanging,
    and further submits raise."""
    eng = _engine(params)

    def boom():
        raise RuntimeError("device gone")

    eng._admit = boom
    eng.start()
    try:
        req = eng.submit(np.asarray([1, 2, 3]), max_new_tokens=4)
        assert req.wait(timeout=60), "abort did not wake the waiter"
        assert req.error is not None
        with pytest.raises(RuntimeError):
            req.result(timeout=0)
        with pytest.raises(RuntimeError):
            eng.submit([1], max_new_tokens=1)
        (failed,) = eng.results()
        assert failed is req
        assert eng.stats()["serving.aborted"] == 1
    finally:
        eng.stop()


def test_driver_thread_death_fails_pending_requests(params):
    """ISSUE 8 satellite: a driver thread that DIES (an exception
    ``step()`` does not turn into an abort — here a ``BaseException``
    escaping the loop) must fail every pending/queued request with the
    captured exception so ``result(timeout=None)`` returns instead of
    hanging forever, and ``submit()`` after the death raises
    immediately."""
    import threading

    eng = _engine(params)

    class DriverKilled(BaseException):  # escapes step()'s Exception catch
        pass

    def boom():
        raise DriverKilled("driver thread killed")

    eng._admit = boom
    eng.start()
    try:
        req = eng.submit(np.asarray([1, 2, 3]), max_new_tokens=4)
        # result(timeout=None) is the hang the supervision removes: run
        # it on a side thread with a bounded join so a regression fails
        # the test instead of wedging the suite
        got = {}

        def wait_forever():
            try:
                got["val"] = req.result(timeout=None)
            except BaseException as e:  # noqa: BLE001
                got["err"] = e

        t = threading.Thread(target=wait_forever, daemon=True)
        t.start()
        t.join(timeout=60)
        assert not t.is_alive(), \
            "result(timeout=None) still hangs after driver death"
        assert isinstance(got.get("err"), RuntimeError)
        assert isinstance(req.error, DriverKilled)
        # the dead driver is observable and rejects new work
        for _ in range(200):
            if not eng.driver_alive():
                break
            time.sleep(0.01)
        assert not eng.driver_alive()
        with pytest.raises(RuntimeError):
            eng.submit([1], max_new_tokens=1)
        assert _obs.get_registry().value("serving.driver_deaths") == 1
    finally:
        eng.stop()  # must not hang on the drain either


def test_background_thread_driver(params):
    """start()/stop() + concurrent submit: the Poisson-load path the
    serving benchmark uses."""
    eng = _engine(params, max_slots=2)
    eng.start()
    try:
        rng = np.random.default_rng(6)
        reqs = [eng.submit(rng.integers(1, VOCAB, (3,)), max_new_tokens=6)
                for _ in range(5)]
        for r in reqs:
            assert r.wait(timeout=60), "request did not finish"
        done = eng.results()
        assert {r.rid for r in done} == {r.rid for r in reqs}
    finally:
        eng.stop()
    assert eng.idle


# -- SLO budgets + goodput (ISSUE 11: goodput-under-SLO measurement) --------

def test_slo_violations_counted(params):
    """An impossibly tight TTFT budget: every completed request is a
    violation, goodput stays zero, and each handle carries its
    verdict."""
    eng = _engine(params, ttft_slo_s=1e-9)
    rng = np.random.default_rng(2)
    prompts = [rng.integers(1, VOCAB, (4,)) for _ in range(3)]
    eng.generate_many(prompts, max_new_tokens=4)
    st = eng.stats()
    assert st["serving.slo_violations"] == 3
    assert st["serving.goodput_tok_s"] == 0.0
    assert all(r.slo_ok is False for r in eng.results())


def test_goodput_counts_slo_met_tokens(params):
    """Generous budgets: zero violations, goodput > 0, verdicts True."""
    eng = _engine(params, ttft_slo_s=600.0, e2e_slo_s=600.0)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, VOCAB, (4,)) for _ in range(3)]
    eng.generate_many(prompts, max_new_tokens=4)
    st = eng.stats()
    assert st.get("serving.slo_violations", 0) == 0
    assert st["serving.goodput_tok_s"] > 0
    assert all(r.slo_ok is True for r in eng.results())


def test_no_slo_configured_leaves_verdict_none(params):
    eng = _engine(params)
    eng.generate_many([np.arange(1, 4, dtype=np.int32)],
                      max_new_tokens=3)
    st = eng.stats()
    assert "serving.slo_violations" not in st
    assert all(r.slo_ok is None for r in eng.results())


def test_reset_slo_accounting_reopens_window(params):
    """The bench warm-pass contract: resetting after warm requests
    zeroes the violation counter and the goodput window."""
    eng = _engine(params, ttft_slo_s=1e-9)
    eng.generate_many([np.arange(1, 4, dtype=np.int32)],
                      max_new_tokens=3)
    assert eng.stats()["serving.slo_violations"] == 1
    eng.reset_slo_accounting()
    assert eng.stats()["serving.slo_violations"] == 0
    assert eng.stats()["serving.goodput_tok_s"] == 0.0
    eng.ttft_slo_s = 600.0
    eng.e2e_slo_s = 600.0
    eng.generate_many([np.arange(1, 4, dtype=np.int32)],
                      max_new_tokens=3)
    st = eng.stats()
    assert st["serving.slo_violations"] == 0
    assert st["serving.goodput_tok_s"] > 0


def test_slo_budget_validation(params):
    with pytest.raises(ValueError):
        _engine(params, ttft_slo_s=0)
    with pytest.raises(ValueError):
        _engine(params, e2e_slo_s=-1.0)
