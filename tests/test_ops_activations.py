"""Direct tests for every activation op vs its numpy formula (reference
activation_op.h/.cc — each functor's exact definition) + numeric-grad
checks for the smooth ones (VERDICT r1: one direct test per op)."""

import math

import numpy as np
import pytest

from op_test import check_output, check_grad

rng = np.random.RandomState(42)


def _x(lo=-3.0, hi=3.0, shape=(3, 7)):
    return rng.uniform(lo, hi, shape).astype(np.float32)


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


# (op, attrs, numpy reference, input, atol)
CASES = [
    ("abs", {}, np.abs, _x(), 1e-6),
    ("exp", {}, np.exp, _x(), 1e-5),
    ("log", {}, np.log, _x(0.1, 5.0), 1e-5),
    ("sqrt", {}, np.sqrt, _x(0.01, 9.0), 1e-5),
    ("ceil", {}, np.ceil, _x(), 1e-6),
    ("floor", {}, np.floor, _x(), 1e-6),
    ("round", {}, np.round, _x(), 1e-6),
    ("reciprocal", {}, lambda x: 1.0 / x, _x(0.2, 4.0), 1e-5),
    ("pow", {"factor": 3.0}, lambda x: x ** 3.0, _x(0.1, 2.0), 1e-4),
    ("softplus", {}, lambda x: np.log1p(np.exp(x)), _x(), 1e-5),
    ("softsign", {}, lambda x: x / (1.0 + np.abs(x)), _x(), 1e-6),
    ("logsigmoid", {}, lambda x: np.log(_sigmoid(x)), _x(), 1e-5),
    ("tanh_shrink", {}, lambda x: x - np.tanh(x), _x(), 1e-5),
    ("brelu", {"t_min": -1.0, "t_max": 2.0},
     lambda x: np.clip(x, -1.0, 2.0), _x(), 1e-6),
    ("relu6", {"threshold": 6.0},
     lambda x: np.minimum(np.maximum(x, 0.0), 6.0), _x(-2, 8), 1e-6),
    ("soft_relu", {"threshold": 40.0},
     lambda x: np.log1p(np.exp(np.clip(x, -40.0, 40.0))), _x(), 1e-5),
    ("stanh", {"scale_a": 2.0 / 3.0, "scale_b": 1.7159},
     lambda x: 1.7159 * np.tanh(2.0 / 3.0 * x), _x(), 1e-5),
    ("hard_shrink", {"threshold": 0.5},
     lambda x: np.where(np.abs(x) > 0.5, x, 0.0), _x(), 1e-6),
    ("softshrink", {"lambda_": 0.5},
     lambda x: np.where(x > 0.5, x - 0.5, np.where(x < -0.5, x + 0.5, 0.0)),
     _x(), 1e-6),
    ("hard_sigmoid", {"slope": 0.2, "offset": 0.5},
     lambda x: np.clip(0.2 * x + 0.5, 0.0, 1.0), _x(), 1e-6),
    ("elu", {"alpha": 1.5},
     lambda x: np.where(x > 0, x, 1.5 * (np.exp(x) - 1.0)), _x(), 1e-5),
    ("swish", {"beta": 1.0}, lambda x: x * _sigmoid(x), _x(), 1e-5),
    ("thresholded_relu", {"threshold": 1.0},
     lambda x: np.where(x > 1.0, x, 0.0), _x(), 1e-6),
    ("gelu", {},
     lambda x: 0.5 * x * (1.0 + np.vectorize(math.erf)(x / math.sqrt(2.0))),
     _x(), 1e-3),
    ("log_softmax", {},
     lambda x: x - np.log(np.sum(np.exp(x), axis=-1, keepdims=True))
     - np.max(x * 0, axis=-1, keepdims=True), _x(), 1e-5),
]


@pytest.mark.parametrize("op,attrs,ref,x,atol",
                         CASES, ids=[c[0] for c in CASES])
def test_activation_output(op, attrs, ref, x, atol):
    check_output(op, {"X": x}, {"Out": ref(x).astype(np.float32)},
                 attrs=attrs, atol=atol, rtol=1e-4)


SMOOTH = ["exp", "log", "sqrt", "softplus", "logsigmoid", "tanh_shrink",
          "soft_relu", "stanh", "swish", "gelu", "log_softmax",
          "reciprocal", "softsign"]


@pytest.mark.parametrize("op", SMOOTH)
def test_activation_grad(op):
    lo, hi = (-2.0, 2.0)
    if op in ("log", "sqrt", "reciprocal"):
        lo, hi = 0.5, 3.0
    x = rng.uniform(lo, hi, (2, 5)).astype(np.float32)
    attrs = next(a for o, a, *_ in CASES if o == op)
    check_grad(op, {"X": x}, "X", attrs=attrs, max_relative_error=5e-3)


def test_isfinite_and_fill_zeros_like():
    from op_test import run_op

    x = np.array([[1.0, np.inf], [np.nan, -2.0]], np.float32)
    got = run_op("isfinite", {"X": x})
    # reference isfinite_op reduces to ONE bool: "contains only finite"
    out = np.asarray(got["Out"]).reshape(-1)
    assert out.shape == (1,) and not bool(out[0])
    ok = run_op("isfinite", {"X": np.ones((2, 2), np.float32)})
    assert bool(np.asarray(ok["Out"]).reshape(-1)[0])

    z = run_op("fill_zeros_like", {"X": x})["Out"]
    np.testing.assert_array_equal(np.asarray(z), np.zeros_like(x))
