"""Gradient accumulation: same math as the big batch, different schedule —
the reference's ``test_CompareTwoNets.cpp`` contract (same network, two
execution schedules, compared numerically)."""

import numpy as np
import pytest

import paddle_tpu as pt


def _build_mlp(lr=0.1):
    model = {}
    img = pt.layers.data("x", shape=[16], dtype="float32")
    lbl = pt.layers.data("y", shape=[1], dtype="int64")
    h = pt.layers.fc(img, 32, act="tanh")
    pred = pt.layers.fc(h, 4, act="softmax")
    cost = pt.layers.cross_entropy(pred, lbl)
    avg = pt.layers.mean(cost)
    opt = pt.optimizer.SGD(learning_rate=lr)
    opt.minimize(avg)
    model["feed"] = [img, lbl]
    model["avg_cost"] = avg
    model["pred"] = pred
    return model


def _train(accum, steps=3, seed=7):
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 11
    with pt.program_guard(main, startup):
        model = _build_mlp()
    if accum > 1:
        pt.gradient_accumulation(main, accum)
    scope = pt.core.scope.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope)
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(8, 16)).astype(np.float32)
    y = rng.integers(0, 4, (8, 1)).astype(np.int64)
    losses, preds = [], None
    for _ in range(steps):
        loss, preds = exe.run(main, feed={"x": x, "y": y},
                              fetch_list=[model["avg_cost"], model["pred"]],
                              scope=scope)
        losses.append(float(np.asarray(loss)))
    params = {
        p.name: np.asarray(scope.get(p.name))
        for p in main.all_parameters()
    }
    return losses, np.asarray(preds), params


def test_accum_matches_big_batch():
    """accum=4 over an 8-row batch == one 8-row step: losses, the
    concatenated batch-shaped fetch, and the updated parameters."""
    l1, p1, w1 = _train(1)
    l4, p4, w4 = _train(4)
    np.testing.assert_allclose(l1, l4, rtol=2e-5, atol=1e-6)
    np.testing.assert_allclose(p1, p4, rtol=2e-4, atol=1e-5)
    # param names are auto-numbered per process (fc_0 vs fc_2...); the two
    # builds produce the same parameters in the same creation order
    assert len(w1) == len(w4)
    for (n1, a), (n4, b) in zip(sorted(w1.items()), sorted(w4.items())):
        assert a.shape == b.shape, (n1, n4)
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-5,
                                   err_msg=f"{n1} vs {n4}")


def test_accum_indivisible_batch_errors():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        model = _build_mlp()
    pt.gradient_accumulation(main, 3)
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.core.scope.Scope()
    exe.run(startup, scope=scope)
    x = np.zeros((8, 16), np.float32)
    y = np.zeros((8, 1), np.int64)
    with pytest.raises(Exception, match="not divisible"):
        exe.run(main, feed={"x": x, "y": y},
                fetch_list=[model["avg_cost"]], scope=scope)


def test_accum_with_remat_policy():
    """gradient_accumulation composes with memory_optimize segments."""
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 5
    with pt.program_guard(main, startup):
        model = _build_mlp()
    ref_main, ref_startup = pt.Program(), pt.Program()
    ref_main.random_seed = 5
    with pt.program_guard(ref_main, ref_startup):
        ref_model = _build_mlp()
    pt.gradient_accumulation(main, 2)
    pt.memory_optimize(main, policy="full", min_segment=1)

    def run(prog, startup, model):
        scope = pt.core.scope.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 16)).astype(np.float32)
        y = rng.integers(0, 4, (4, 1)).astype(np.int64)
        for _ in range(2):
            loss, = exe.run(prog, feed={"x": x, "y": y},
                            fetch_list=[model["avg_cost"]], scope=scope)
        return float(np.asarray(loss))

    la = run(main, startup, model)
    lb = run(ref_main, ref_startup, ref_model)
    np.testing.assert_allclose(la, lb, rtol=2e-5, atol=1e-6)


def test_accum_bn_stats_thread_through_microbatches():
    """Forward-written persistables (BN running stats) must see each
    microbatch sequentially — the final stats equal running the two
    microbatches as two separate steps."""

    def build():
        x = pt.layers.data("x", shape=[6], dtype="float32")
        lbl = pt.layers.data("y", shape=[1], dtype="float32")
        h = pt.layers.fc(x, 8)
        h = pt.layers.batch_norm(h)
        pred = pt.layers.fc(h, 1)
        cost = pt.layers.mean(pt.layers.square_error_cost(pred, lbl))
        pt.optimizer.SGD(learning_rate=0.0).minimize(cost)
        return cost

    rng = np.random.default_rng(3)
    x = rng.normal(size=(8, 6)).astype(np.float32) * 3.0
    y = rng.normal(size=(8, 1)).astype(np.float32)

    # accum=2 on the full batch
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 1
    with pt.program_guard(main, startup):
        cost = build()
    pt.gradient_accumulation(main, 2)
    s1 = pt.core.scope.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=s1)
    exe.run(main, feed={"x": x, "y": y}, fetch_list=[cost], scope=s1)

    # two sequential half-batch steps (lr=0 so only BN stats move)
    main2, startup2 = pt.Program(), pt.Program()
    main2.random_seed = 1
    with pt.program_guard(main2, startup2):
        cost2 = build()
    s2 = pt.core.scope.Scope()
    exe.run(startup2, scope=s2)
    exe.run(main2, feed={"x": x[:4], "y": y[:4]}, fetch_list=[cost2],
            scope=s2)
    exe.run(main2, feed={"x": x[4:], "y": y[4:]}, fetch_list=[cost2],
            scope=s2)

    def stats(scope):
        # auto-numbered names differ between the two builds; sort by the
        # (suffix, name) so mean pairs with mean, variance with variance
        names = sorted(
            (n for n in scope.var_names() if "batch_norm" in n
             and ("mean" in n or "variance" in n)),
            key=lambda n: n.rsplit(".", 1)[-1])
        return [(n, np.asarray(scope.get(n))) for n in names]

    st1, st2 = stats(s1), stats(s2)
    assert st1 and len(st1) == len(st2)
    for (n1, a), (n2, b) in zip(st1, st2):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6,
                                   err_msg=f"{n1} vs {n2}")


def test_accum_sum_and_composite_metrics_not_inflated():
    """Regression (round-5 review): a fetched reduce_sum OVER the batch
    must SUM across microbatches; a composite scalar built from means
    (layers.sums of two mean costs) must NOT be multiplied by accum."""

    def build():
        x = pt.layers.data("x", shape=[4], dtype="float32")
        lbl = pt.layers.data("y", shape=[1], dtype="float32")
        pred = pt.layers.fc(x, 1)
        sq = pt.layers.square_error_cost(pred, lbl)
        batch_sum = pt.layers.reduce_sum(sq)        # sums over the batch
        m = pt.layers.mean(sq)
        twice = pt.layers.sums([m, m])              # composite of means
        pt.optimizer.SGD(learning_rate=0.0).minimize(m)
        return batch_sum, m, twice

    rng = np.random.default_rng(1)
    x = rng.normal(size=(8, 4)).astype(np.float32)
    y = rng.normal(size=(8, 1)).astype(np.float32)

    def run(accum):
        pt.core.unique_name.reset()
        main, startup = pt.Program(), pt.Program()
        main.random_seed = 3
        with pt.program_guard(main, startup):
            fetches = build()
        if accum > 1:
            pt.gradient_accumulation(main, accum)
        scope = pt.core.scope.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        vals = exe.run(main, feed={"x": x, "y": y},
                       fetch_list=list(fetches), scope=scope)
        return [float(np.asarray(v).sum()) for v in vals]

    ref = run(1)
    got = run(2)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_accum_composite_of_sums_and_mixed_raises():
    """Round-5 review follow-up: an ADDITIVE composite of two batch
    reduce_sums must also SUM across microbatches (transitive
    classification), and a sum+mean MIX — which has no exact reassembly —
    must raise instead of silently returning 1/accum of the truth."""

    def build(mixed):
        x = pt.layers.data("x", shape=[4], dtype="float32")
        lbl = pt.layers.data("y", shape=[1], dtype="float32")
        pred = pt.layers.fc(x, 1)
        sq = pt.layers.square_error_cost(pred, lbl)
        s1 = pt.layers.reduce_sum(sq)
        s2 = pt.layers.reduce_sum(pt.layers.square(sq))
        m = pt.layers.mean(sq)
        comp = pt.layers.sums([s1, m] if mixed else [s1, s2])
        pt.optimizer.SGD(learning_rate=0.0).minimize(m)
        return comp, m

    rng = np.random.default_rng(4)
    x = rng.normal(size=(8, 4)).astype(np.float32)
    y = rng.normal(size=(8, 1)).astype(np.float32)

    def run(accum, mixed=False):
        pt.core.unique_name.reset()
        main, startup = pt.Program(), pt.Program()
        main.random_seed = 3
        with pt.program_guard(main, startup):
            fetches = build(mixed)
        if accum > 1:
            pt.gradient_accumulation(main, accum)
        scope = pt.core.scope.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        vals = exe.run(main, feed={"x": x, "y": y},
                       fetch_list=list(fetches), scope=scope)
        return [float(np.asarray(v).sum()) for v in vals]

    np.testing.assert_allclose(run(2), run(1), rtol=1e-5, atol=1e-6)
    import pytest

    with pytest.raises(ValueError, match="mixes batch-sum"):
        run(2, mixed=True)
