"""Op tests: conv / pool / norm / losses (reference test_conv2d_op.py,
test_pool2d_op.py, test_batch_norm_op.py, test_cross_entropy_op.py …)."""

import numpy as np
import pytest

from op_test import check_output, check_grad, run_op

rng = np.random.RandomState(7)


def _conv2d_ref(x, w, stride, pad):
    n, cin, h, wd = x.shape
    cout, _, kh, kw = w.shape
    oh = (h + 2 * pad - kh) // stride + 1
    ow = (wd + 2 * pad - kw) // stride + 1
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    out = np.zeros((n, cout, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i * stride : i * stride + kh, j * stride : j * stride + kw]
            out[:, :, i, j] = np.einsum("nchw,ochw->no", patch, w)
    return out


def test_conv2d_vs_reference_impl():
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32)
    expected = _conv2d_ref(x, w, 1, 1)
    check_output(
        "conv2d", {"Input": x, "Filter": w}, {"Output": expected},
        attrs={"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
               "groups": 1},
        atol=1e-3, rtol=1e-3,
    )


def test_conv2d_grad():
    x = rng.randn(1, 2, 5, 5).astype(np.float32)
    w = rng.randn(3, 2, 3, 3).astype(np.float32)
    attrs = {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1], "groups": 1}
    check_grad("conv2d", {"Input": x, "Filter": w}, "Input", attrs=attrs,
               output="Output", max_relative_error=1e-2)
    check_grad("conv2d", {"Input": x, "Filter": w}, "Filter", attrs=attrs,
               output="Output", max_relative_error=1e-2)


def test_depthwise_conv2d_shape():
    x = rng.randn(2, 4, 8, 8).astype(np.float32)
    w = rng.randn(4, 1, 3, 3).astype(np.float32)
    out = run_op("depthwise_conv2d", {"Input": x, "Filter": w},
                 {"strides": [1, 1], "paddings": [1, 1]})
    assert out["Output"].shape == (2, 4, 8, 8)


def test_conv2d_transpose_shape():
    x = rng.randn(2, 3, 4, 4).astype(np.float32)
    w = rng.randn(3, 5, 2, 2).astype(np.float32)
    out = run_op("conv2d_transpose", {"Input": x, "Filter": w},
                 {"strides": [2, 2], "paddings": [0, 0]})
    assert out["Output"].shape == (2, 5, 8, 8)


def test_pool2d_max_avg():
    x = rng.randn(2, 3, 6, 6).astype(np.float32)
    out = run_op("pool2d", {"X": x},
                 {"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0],
                  "pooling_type": "max"})
    expected = x.reshape(2, 3, 3, 2, 3, 2).max(axis=(3, 5))
    np.testing.assert_allclose(out["Out"], expected, rtol=1e-6)
    out = run_op("pool2d", {"X": x},
                 {"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0],
                  "pooling_type": "avg"})
    expected = x.reshape(2, 3, 3, 2, 3, 2).mean(axis=(3, 5))
    np.testing.assert_allclose(out["Out"], expected, rtol=1e-5)


def test_pool2d_grad():
    x = rng.randn(1, 2, 4, 4).astype(np.float32)
    attrs = {"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0],
             "pooling_type": "max"}
    check_grad("pool2d", {"X": x}, "X", attrs=attrs, max_relative_error=1e-2)


def test_batch_norm_train_stats():
    x = rng.randn(4, 3, 5, 5).astype(np.float32)
    scale = np.ones(3, np.float32)
    bias = np.zeros(3, np.float32)
    mean = np.zeros(3, np.float32)
    var = np.ones(3, np.float32)
    out = run_op(
        "batch_norm",
        {"X": x, "Scale": scale, "Bias": bias, "Mean": mean, "Variance": var},
        {"momentum": 0.9, "epsilon": 1e-5, "is_test": False},
    )
    y = out["Y"]
    # normalized output has ~zero mean, ~unit variance per channel
    np.testing.assert_allclose(y.mean(axis=(0, 2, 3)), 0, atol=1e-5)
    np.testing.assert_allclose(y.var(axis=(0, 2, 3)), 1, atol=1e-3)
    batch_mean = x.mean(axis=(0, 2, 3))
    np.testing.assert_allclose(
        out["MeanOut"], 0.9 * mean + 0.1 * batch_mean, atol=1e-5
    )


def test_batch_norm_is_test_uses_running_stats():
    x = rng.randn(4, 3, 2, 2).astype(np.float32)
    mean = rng.randn(3).astype(np.float32)
    var = np.abs(rng.randn(3)).astype(np.float32) + 0.5
    out = run_op(
        "batch_norm",
        {"X": x, "Scale": np.ones(3, np.float32), "Bias": np.zeros(3, np.float32),
         "Mean": mean, "Variance": var},
        {"is_test": True},
    )
    expected = (x - mean.reshape(1, 3, 1, 1)) / np.sqrt(
        var.reshape(1, 3, 1, 1) + 1e-5
    )
    np.testing.assert_allclose(out["Y"], expected, atol=1e-4)


def test_layer_norm():
    x = rng.randn(4, 10).astype(np.float32)
    out = run_op("layer_norm", {"X": x}, {"begin_norm_axis": 1})
    np.testing.assert_allclose(out["Y"].mean(1), 0, atol=1e-5)
    np.testing.assert_allclose(out["Y"].std(1), 1, atol=1e-3)


def test_cross_entropy():
    p = np.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1]], np.float32)
    lbl = np.asarray([[0], [1]], np.int64)
    expected = -np.log(np.asarray([[0.7], [0.8]], np.float32))
    check_output("cross_entropy", {"X": p, "Label": lbl}, {"Y": expected},
                 atol=1e-5)
    check_grad("cross_entropy", {"X": p, "Label": lbl}, "X", output="Y")


def test_softmax_with_cross_entropy_matches_composition():
    logits = rng.randn(4, 6).astype(np.float32)
    lbl = rng.randint(0, 6, (4, 1)).astype(np.int64)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    sm = e / e.sum(-1, keepdims=True)
    expected = -np.log(np.take_along_axis(sm, lbl, 1))
    got = run_op("softmax_with_cross_entropy", {"Logits": logits, "Label": lbl})
    np.testing.assert_allclose(got["Loss"], expected, atol=1e-5)
    check_grad("softmax_with_cross_entropy", {"Logits": logits, "Label": lbl},
               "Logits", output="Loss")


def test_sigmoid_cross_entropy_with_logits():
    x = rng.randn(3, 4).astype(np.float32)
    z = rng.randint(0, 2, (3, 4)).astype(np.float32)
    expected = np.maximum(x, 0) - x * z + np.log1p(np.exp(-np.abs(x)))
    check_output("sigmoid_cross_entropy_with_logits", {"X": x, "Label": z},
                 {"Out": expected}, atol=1e-5)


def test_smooth_l1_and_huber():
    x = rng.randn(3, 4).astype(np.float32)
    y = rng.randn(3, 4).astype(np.float32)
    got = run_op("smooth_l1_loss", {"X": x, "Y": y}, {"sigma": 1.0})
    d = x - y
    ad = np.abs(d)
    loss = np.where(ad < 1, 0.5 * d * d, ad - 0.5).sum(1, keepdims=True)
    np.testing.assert_allclose(got["Out"], loss, rtol=1e-5)
    check_grad("smooth_l1_loss", {"X": x, "Y": y}, "X")


def test_lrn_shape_and_grad():
    x = rng.randn(2, 8, 4, 4).astype(np.float32)
    out = run_op("lrn", {"X": x}, {"n": 5})
    assert out["Out"].shape == x.shape
    check_grad("lrn", {"X": x}, "X", attrs={"n": 5}, max_relative_error=1e-2)


def test_maxout():
    x = rng.randn(2, 6, 3, 3).astype(np.float32)
    out = run_op("maxout", {"X": x}, {"groups": 2})
    expected = x.reshape(2, 3, 2, 3, 3).max(2)
    np.testing.assert_allclose(out["Out"], expected)


def test_im2sequence():
    x = rng.randn(2, 3, 4, 4).astype(np.float32)
    out = run_op("im2sequence", {"X": x},
                 {"kernels": [2, 2], "strides": [2, 2], "paddings": [0, 0, 0, 0]})
    assert out["Out"].shape == (2, 4, 12)


def test_row_conv_masks_tail():
    x = rng.randn(2, 6, 4).astype(np.float32)
    f = rng.randn(3, 4).astype(np.float32)
    lens = np.asarray([4, 6], np.int32)
    out = run_op("row_conv", {"X": x, "Filter": f, "Length": lens})["Out"]
    assert np.all(out[0, 4:] == 0)
    expected_00 = (x[0, 0] * f[0] + x[0, 1] * f[1] + x[0, 2] * f[2])
    np.testing.assert_allclose(out[0, 0], expected_00, rtol=1e-5)


def test_conv2d_transpose_dilated_shape():
    # regression: implicit padding must use the DILATED kernel extent
    x = rng.randn(1, 3, 5, 5).astype(np.float32)
    w = rng.randn(3, 4, 3, 3).astype(np.float32)
    out = run_op("conv2d_transpose", {"Input": x, "Filter": w},
                 {"strides": [1, 1], "paddings": [0, 0], "dilations": [2, 2]})
    # oh = (i-1)*s - 2p + (k-1)*d + 1 = 4 + 4 + 1 = 9
    assert out["Output"].shape == (1, 4, 9, 9)


def test_unpool_roundtrip_overlapping_window():
    # regression: unpool must invert the ORIGINAL extent, incl. ksize!=stride
    x = rng.randn(1, 2, 9, 9).astype(np.float32)
    pooled = run_op("max_pool2d_with_index", {"X": x},
                    {"ksize": [3, 3], "strides": [2, 2], "paddings": [0, 0]})
    up = run_op("unpool", {"X": pooled["Out"], "Indices": pooled["Mask"]},
                {"ksize": [3, 3], "strides": [2, 2], "paddings": [0, 0]})
    assert up["Out"].shape == (1, 2, 9, 9)
    # every pooled max value must land somewhere in the unpooled map
    for nmax in np.asarray(pooled["Out"]).reshape(2, -1).max(axis=1):
        assert nmax in np.asarray(up["Out"])


def test_batch_norm_large_mean_no_nan():
    # regression: E[x^2]-E[x]^2 cancellation produced negative variance
    x = (rng.randn(4, 3, 2, 2) * 1e-3 + 500.0).astype(np.float32)
    out = run_op(
        "batch_norm",
        {"X": x, "Scale": np.ones(3, np.float32),
         "Bias": np.zeros(3, np.float32),
         "Mean": np.zeros(3, np.float32),
         "Variance": np.ones(3, np.float32)},
        {"is_test": False})
    assert np.isfinite(np.asarray(out["Y"])).all()


def test_unpool_explicit_output_size():
    # non-tiling input: 10x10 with k3/s2 pools to 4x4 and is only exactly
    # invertible via output_size
    x = rng.randn(1, 1, 10, 10).astype(np.float32)
    x[0, 0, 8, 8] = 100.0
    pooled = run_op("max_pool2d_with_index", {"X": x},
                    {"ksize": [3, 3], "strides": [2, 2], "paddings": [0, 0]})
    up = run_op("unpool", {"X": pooled["Out"], "Indices": pooled["Mask"]},
                {"ksize": [3, 3], "strides": [2, 2], "paddings": [0, 0],
                 "output_size": [10, 10]})
    assert up["Out"].shape == (1, 1, 10, 10)
    assert np.asarray(up["Out"])[0, 0, 8, 8] == 100.0


def test_pool3d_max_and_avg():
    x = rng.randn(2, 3, 4, 6, 6).astype(np.float32)
    out = run_op("pool3d", {"X": x},
                 attrs={"ksize": (2, 2, 2), "strides": (2, 2, 2),
                        "paddings": (0, 0, 0), "pooling_type": "max"})["Out"]
    want = x.reshape(2, 3, 2, 2, 3, 2, 3, 2).max(axis=(3, 5, 7))
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6, atol=1e-6)
    out = run_op("pool3d", {"X": x},
                 attrs={"ksize": (2, 2, 2), "strides": (2, 2, 2),
                        "paddings": (0, 0, 0), "pooling_type": "avg"})["Out"]
    want = x.reshape(2, 3, 2, 2, 3, 2, 3, 2).mean(axis=(3, 5, 7))
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5, atol=1e-6)
    check_grad("pool3d", {"X": x},
               "X", attrs={"pooling_type": "avg"})


def test_conv3d_transpose_shape_and_grad():
    x = rng.randn(1, 2, 3, 4, 4).astype(np.float32)
    w = rng.randn(2, 3, 2, 2, 2).astype(np.float32)  # (Cin, Cout, D, H, W)
    out = run_op("conv3d_transpose", {"Input": x, "Filter": w},
                 attrs={"strides": (2, 2, 2)})["Output"]
    assert np.asarray(out).shape == (1, 3, 6, 8, 8)
    check_grad("conv3d_transpose", {"Input": x, "Filter": w}, "Filter",
               attrs={"strides": (2, 2, 2)}, output="Output")


def test_conv3d_pool3d_layers():
    import paddle_tpu as pt

    x = pt.layers.data("x3", shape=[2, 6, 8, 8], dtype="float32")
    h = pt.layers.conv3d(x, num_filters=4, filter_size=3, padding=1,
                         act="relu")
    p = pt.layers.pool3d(h, pool_size=2, pool_stride=2)
    cost = pt.layers.mean(p * p)
    pt.optimizer.SGD(learning_rate=0.01).minimize(cost)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    xv = np.random.RandomState(0).randn(2, 2, 6, 8, 8).astype(np.float32)
    (pv, cv) = exe.run(feed={"x3": xv}, fetch_list=[p, cost])
    assert pv.shape == (2, 4, 3, 4, 4)
    assert np.isfinite(cv).all()
