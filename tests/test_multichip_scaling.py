"""Multi-chip scaling engine on the 8-device CPU mesh: ZeRO-1
optimizer-state sharding (bit-exact vs the replicated spelling),
comm-aware gradient accumulation (one cross-chip gradient reduction per
optimizer step, audited on compiled HLO), the compile_shardings
resolution contract, pre-sharded prefetch, and the scaling-benchmark
row.  docs/parallel.md documents every invariant pinned here."""

import importlib.util
import os

import numpy as np
import jax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.analysis.hlo_tools import hlo_comm_report
from paddle_tpu.core.scope import RNG_VAR
from paddle_tpu.models import transformer
from paddle_tpu.parallel import api as papi
from paddle_tpu.parallel.mesh import axis_size, make_mesh


VOCAB, LAYERS, HEADS, DMODEL, SEQ = 128, 2, 2, 32, 16
BATCH = 32  # accum=4 on dp=8: microbatch 8, one sample per device group


def _mesh(n=8):
    return make_mesh({"dp": n}, devices=jax.devices()[:n])


def _build_gpt(accum=1, dropout=0.0):
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 7
    with pt.program_guard(main, startup):
        outs = transformer.build(
            vocab_size=VOCAB, n_layer=LAYERS, n_head=HEADS,
            d_model=DMODEL, max_len=SEQ, dropout_rate=dropout,
            dtype="float32", learning_rate=1e-2)
    if accum > 1:
        pt.gradient_accumulation(main, accum)
    return main, startup, outs


def _build_mlp(make_opt):
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[16])
        y = layers.data("y", shape=[1])
        h = layers.fc(input=x, size=24, act="tanh")
        pred = layers.fc(input=h, size=1)
        loss = layers.mean(layers.square(pred - y))
        make_opt().minimize(loss)
    return main, startup, loss


def _gpt_feed(batch=BATCH, seed=5):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, VOCAB, (batch, SEQ)).astype(np.int64)
    lbls = np.roll(toks, -1, axis=1)
    lbls[:, -1] = -1
    return {"tokens": toks, "labels": lbls}


def _train(build, feed, loss_name, mesh, steps=2, zero=True):
    """(losses, params, last_step_cost, accum_plan, scope arrays fn)."""
    os.environ["PADDLE_TPU_ZERO"] = "1" if zero else "0"
    try:
        main, startup, outs = build()
        loss = outs[loss_name] if isinstance(outs, dict) else outs
        if mesh is not None:
            papi.data_parallel(main, "dp", programs=(startup,))
        scope = pt.Scope()
        pt.core.scope._scope_stack.append(scope)
        try:
            exe = pt.Executor(mesh=mesh)
            exe.run(startup, scope=scope)
            losses = [np.asarray(exe.run(main, feed=feed,
                                         fetch_list=[loss], scope=scope)[0])
                      for _ in range(steps)]
            params = {v.name: np.asarray(scope.get(v.name))
                      for v in main.all_parameters()}
            state = {n: scope.get(n) for n in
                     (v.name for v in main.global_block().vars.values())
                     if scope.find_var(n) is not None}
            return (losses, params, dict(exe.last_step_cost),
                    exe.last_accum_plan, main, state)
        finally:
            pt.core.scope._scope_stack.pop()
    finally:
        os.environ.pop("PADDLE_TPU_ZERO", None)


# -- compile_shardings resolution -------------------------------------------
def test_compile_shardings_resolution():
    """Feeds shard over dp, fetches and RNG replicate, ZeRO accumulators
    shard their leading axis, beta-pow scalars replicate, an explicit
    partition_spec wins, and out_state_names may diverge from
    state_names (startup-created persistables)."""
    main, startup, outs = _build_gpt()
    mesh = _mesh()
    papi.data_parallel(main, "dp", programs=(startup,))
    block = main.global_block()
    moments = sorted(n for n in block.vars if n.endswith("_moment1"))
    betas = sorted(n for n in block.vars if n.startswith("beta1_pow"))
    assert moments and betas
    pinned = moments[-1]
    block.vars[pinned].partition_spec = P()  # explicit spec wins

    state_names = [moments[0], betas[0], pinned]
    (state_sh, *feed_sh), (out_state, fetch_sh) = papi.compile_shardings(
        mesh, main, ["labels", "tokens"], [outs["avg_cost"].name],
        state_names, out_state_names=state_names + [moments[1]])
    assert all(sh.spec[0] == "dp" for sh in feed_sh)
    assert fetch_sh[0].spec == P()
    assert state_sh[RNG_VAR].spec == P()
    assert out_state[RNG_VAR].spec == P()
    assert state_sh[moments[0]].spec[0] == "dp"
    assert state_sh[betas[0]].spec == P()       # scalar: replicated
    assert state_sh[pinned].spec == P()         # explicit spec wins
    assert moments[1] not in state_sh
    assert out_state[moments[1]].spec[0] == "dp"  # divergent out_state


def test_zero_spec_fallback_rules(monkeypatch):
    """Leading-dim divisibility gates the dp shard; the accumulator
    inherits its parameter's tp spec; PADDLE_TPU_ZERO=0 kills it all."""
    main, startup, _ = _build_gpt()
    mesh = _mesh()
    block = main.global_block()
    mom = next(n for n in sorted(block.vars) if n.endswith("_moment1")
               and len(block.vars[n].shape) == 2)
    var = block.vars[mom]
    assert papi.zero_spec_for(var, mesh, block)[0] == "dp"

    odd = block.create_var(name="odd_moment", shape=[7, 3],
                           dtype="float32", persistable=True)
    odd.zero_param = var.zero_param
    assert papi.zero_spec_for(odd, mesh, block) is None  # 7 % 8 != 0

    # tp-sharded parameter: the accumulator inherits P(None, 'tp') and
    # still gains the dp leading shard
    pvar = block._find_var(var.zero_param)
    pvar.partition_spec = P(None, "tp")
    spec = papi.zero_spec_for(var, mesh, block)
    assert spec == P("dp", "tp")
    pvar.partition_spec = P("dp", None)  # leading axis taken: no double-dp
    assert papi.zero_spec_for(var, mesh, block) == P("dp", None)

    monkeypatch.setenv("PADDLE_TPU_ZERO", "0")
    assert papi.zero_spec_for(var, mesh, block) is None
    monkeypatch.delenv("PADDLE_TPU_ZERO")
    assert papi.zero_spec_for(var, None, block) is None  # no mesh


def test_optimizer_state_report_static():
    """Pure-metadata accounting: dp=8 shards the moments ~8x, the lr /
    beta-pow scalars stay replicated, and the per-device figure clears
    the replicated/4 acceptance bound without touching any array."""
    main, startup, _ = _build_gpt()
    mesh = _mesh()
    rep = papi.optimizer_state_report(main, mesh)
    assert rep["sharded_vars"] > 0 and rep["replicated_vars"] >= 3
    assert rep["per_device_bytes"] * 4 <= rep["total_bytes"]
    rep1 = papi.optimizer_state_report(main, None)
    assert rep1["per_device_bytes"] == rep1["total_bytes"]


# -- ZeRO-1 bit-exactness ---------------------------------------------------
@pytest.mark.parametrize(
    "axes", [{"dp": 8}, {"dp": 4, "tp": 2}, {"dp": 2, "fsdp": 2, "tp": 2}],
    ids=["dp8", "dp4xtp2", "dp2xfsdp2xtp2"])
def test_zero_bitexact_adam_dp8(axes):
    """ZeRO-1 sharded Adam state vs the replicated spelling on the SAME
    mesh — parameterized over dp, dp x tp, and dp x fsdp x tp: loss and
    updated params bit-exact (the gradient pin at the backward/optimizer
    boundary isolates the backward from the accumulator shardings), and
    the live moment arrays really are sharded."""
    feed = _gpt_feed()
    mesh = make_mesh(axes, devices=jax.devices()[:8])

    def build():
        main, startup, outs = _build_gpt()
        if "tp" in axes:
            for prog in (main, startup):
                papi.shard_parameters_by_rule(
                    prog, transformer.tp_rules())
        if "fsdp" in axes:
            papi.shard_fsdp(main, programs=(startup,))
        return main, startup, outs

    lz, pz, _cost, _plan, main, state = _train(
        build, feed, "avg_cost", mesh, zero=True)
    lr, pr, _cost_r, _plan_r, _main_r, _state_r = _train(
        build, feed, "avg_cost", mesh, zero=False)
    for a, b in zip(lz, lr):
        assert np.array_equal(a, b)
    for k in pz:
        assert np.array_equal(pz[k], pr[k]), k
    moments = [n for n in sorted(state) if n.endswith("_moment1")]
    sharded = [str(state[n].sharding.spec) for n in moments
               if state[n].sharding.spec != P()]
    assert sharded, moments
    if "fsdp" not in axes:
        assert any("dp" in s for s in sharded), sharded
    else:
        assert any("fsdp" in s for s in sharded), sharded
    beta = next(n for n in sorted(state) if n.startswith("beta1_pow"))
    assert state[beta].sharding.spec == P()


def test_zero_bitexact_momentum_dp8():
    feed_rng = np.random.default_rng(11)
    feed = {"x": feed_rng.normal(size=(BATCH, 16)).astype(np.float32),
            "y": feed_rng.normal(size=(BATCH, 1)).astype(np.float32)}
    mesh = _mesh()

    def build():
        return _build_mlp(lambda: pt.optimizer.Momentum(
            learning_rate=0.05, momentum=0.9))

    lz, pz, _c, _p, _m, state = _train(build, feed, 2, mesh, zero=True)
    lr, pr, *_ = _train(build, feed, 2, mesh, zero=False)
    for a, b in zip(lz, lr):
        assert np.array_equal(a, b)
    for k in pz:
        assert np.array_equal(pz[k], pr[k]), k
    vel = next(n for n in sorted(state) if n.endswith("_velocity"))
    assert "dp" in str(state[vel].sharding.spec)


def test_zero_dp8_matches_dp1():
    """dp=8 ZeRO training tracks the single-device run (different
    cross-chip reduction order: close, not bit-identical)."""
    feed = _gpt_feed()
    l8, p8, *_ = _train(lambda: _build_gpt(), feed, "avg_cost", _mesh())
    l1, p1, *_ = _train(lambda: _build_gpt(), feed, "avg_cost", None)
    np.testing.assert_allclose(
        np.ravel(l8).astype(np.float64), np.ravel(l1).astype(np.float64),
        rtol=1e-5, atol=1e-6)
    for k in p8:
        np.testing.assert_allclose(p8[k], p1[k], rtol=5e-4, atol=5e-5,
                                   err_msg=k)


# -- comm-aware gradient accumulation ---------------------------------------
def test_local_accum_one_reduce_per_step():
    """accum_steps=4 on dp=8: the compiled HLO carries ZERO reduce-class
    collectives inside loop bodies (each gradient is cross-chip-reduced
    exactly once per optimizer step, at the boundary) and the static
    reduce set does not grow with accum."""
    feed = _gpt_feed()
    mesh = _mesh()
    _l, _p, cost4, plan4, _m, _s = _train(
        lambda: _build_gpt(accum=4), feed, "avg_cost", mesh)
    assert plan4["mode"] == "local" and plan4["dp"] == 8
    assert cost4["reduce_ops_in_loop"] == 0
    assert cost4["reduce_ops"] > 0
    _l1, _p1, cost1, _plan1, _m1, _s1 = _train(
        lambda: _build_gpt(accum=1), feed, "avg_cost", mesh)
    assert cost1["reduce_ops_in_loop"] == 0
    # one reduction per param per STEP: accum must not multiply the
    # boundary reduce set (fusion may merge a couple of scalars)
    assert cost4["reduce_ops"] <= cost1["reduce_ops"] + 2


def test_local_accum_matches_dp1():
    """Comm-aware dp=8 accumulation vs the dp=1 accumulation reference:
    same equal-weight-mean contract, close numerics (the device-group
    lanes change float summation order)."""
    feed = _gpt_feed()
    l8, p8, _c, plan, _m, _s = _train(
        lambda: _build_gpt(accum=4), feed, "avg_cost", _mesh())
    assert plan["mode"] == "local"
    l1, p1, _c1, plan1, _m1, _s1 = _train(
        lambda: _build_gpt(accum=4), feed, "avg_cost", None)
    assert plan1["mode"] == "reduce_each"  # dp=0: reference spelling
    np.testing.assert_allclose(
        np.ravel(l8).astype(np.float64), np.ravel(l1).astype(np.float64),
        rtol=2e-5, atol=2e-6)
    for k in p8:
        np.testing.assert_allclose(p8[k], p1[k], rtol=1e-3, atol=1e-4,
                                   err_msg=k)


def test_local_accum_fallback_reasons(monkeypatch):
    """Ineligible programs fall back to the reference spelling with the
    reason recorded — never silently."""
    mesh = _mesh()
    # stateful rng (dropout) -> vmapped lanes would share one key stream
    _l, _p, _c, plan, _m, _s = _train(
        lambda: _build_gpt(accum=4, dropout=0.3), _gpt_feed(), "avg_cost",
        mesh, steps=1)
    assert plan["mode"] == "reduce_each"
    assert "rng" in plan["reason"]
    # microbatch not divisible by dp
    _l, _p, _c, plan, _m, _s = _train(
        lambda: _build_gpt(accum=4), _gpt_feed(batch=16), "avg_cost",
        mesh, steps=1)
    assert plan["mode"] == "reduce_each"
    assert "divisible" in plan["reason"]
    # kill switch
    monkeypatch.setenv("PADDLE_TPU_LOCAL_ACCUM", "0")
    _l, _p, _c, plan, _m, _s = _train(
        lambda: _build_gpt(accum=4), _gpt_feed(), "avg_cost", mesh,
        steps=1)
    assert plan["mode"] == "reduce_each"
    assert "PADDLE_TPU_LOCAL_ACCUM" in plan["reason"]


# -- the comm audit itself --------------------------------------------------
def test_hlo_comm_report_parser():
    text = """\
HloModule jit_step, entry_computation_layout={()->f32[8]{0}}

%add (a: f32[], b: f32[]) -> f32[] {
  ROOT %r = f32[] add(f32[] %a, f32[] %b)
}

%wide.body (p: (s32[], f32[64,32])) -> (s32[], f32[64,32]) {
  %ar.1 = f32[64,32]{1,0} all-reduce(f32[64,32] %x), to_apply=%add
  %ag.7 = f32[8,4]{1,0} all-gather(f32[1,4] %y), dimensions={0}
}

%wide.cond (p: (s32[], f32[64,32])) -> pred[] {
}

ENTRY %main (a: f32[64,32]) -> f32[8] {
  %w = (s32[], f32[64,32]) while((s32[], f32[64,32]) %t), \
condition=%wide.cond, body=%wide.body
  %ar.2 = f32[64,32]{1,0} all-reduce(f32[64,32] %z), to_apply=%add
  %rs = f32[8,32]{1,0} reduce-scatter(f32[64,32] %z2), dimensions={0}
  %agd = f32[64,32]{1,0} all-gather-done(f32[8,32] %h)
}
"""
    rep = hlo_comm_report(text)
    assert rep["collective_ops"] == {
        "all-reduce": 2, "all-gather": 1, "reduce-scatter": 1}
    assert rep["reduce_ops"] == 3
    assert rep["reduce_ops_in_loop"] == 1
    assert rep["collectives_in_loop"] == 2
    assert rep["reduce_bytes_in_loop"] == 64 * 32 * 4
    assert rep["collective_bytes"] == (
        2 * 64 * 32 * 4 + 8 * 4 * 4 + 8 * 32 * 4)


def test_executor_cost_carries_comm_fields():
    feed = _gpt_feed()
    _l, _p, cost, _plan, _m, _s = _train(
        lambda: _build_gpt(), feed, "avg_cost", _mesh(), steps=1)
    for k in ("collective_count", "collective_bytes",
              "collective_op_kinds", "reduce_ops", "reduce_bytes",
              "reduce_ops_in_loop"):
        assert k in cost, k
    assert isinstance(cost["collective_op_kinds"], dict)
    reg = pt.observability.get_registry()
    assert reg.value("executor.collective_bytes") >= cost[
        "collective_bytes"]


# -- pre-sharded prefetch ---------------------------------------------------
def test_prefetch_to_device_sharding():
    mesh = _mesh()
    sh = NamedSharding(mesh, P("dp"))

    def reader():
        for i in range(3):
            yield {"x": np.full((8, 4), i, np.float32),
                   "aux": np.float32(i)}

    got = list(pt.reader.prefetch_to_device(
        reader, 2, sharding={"x": sh})())
    assert len(got) == 3
    for i, item in enumerate(got):
        assert item["x"].sharding == sh
        assert float(item["x"][0, 0]) == i
        assert isinstance(item["aux"], jax.Array)  # default put


def test_trainer_prefetch_lands_sharded_batches():
    """Trainer(prefetch=N) on a mesh-bound executor threads the feed
    shardings into prefetch_to_device: the step consumes dp-pre-sharded
    device arrays (the executor accepts them as-is) and still trains."""
    mesh = _mesh()
    main, startup, loss = _build_mlp(
        lambda: pt.optimizer.SGD(learning_rate=0.05))
    papi.data_parallel(main, "dp", programs=(startup,))
    with pt.program_guard(main, startup):
        trainer = pt.trainer.Trainer(loss, [
            main.global_block().vars["x"], main.global_block().vars["y"]],
            mesh=mesh)
        sh = trainer._feed_shardings()
        assert sh["x"].spec[0] == "dp" and sh["y"].spec[0] == "dp"
        rng = np.random.default_rng(0)

        def reader():
            for _ in range(4):
                yield [(rng.normal(size=(16,)).astype(np.float32),
                        rng.normal(size=(1,)).astype(np.float32))
                       for _ in range(BATCH)]

        costs = []
        trainer.train(
            reader, num_passes=1, prefetch=2,
            event_handler=lambda ev: costs.append(ev.cost)
            if isinstance(ev, pt.trainer.EndIteration) else None)
    assert len(costs) == 4 and np.isfinite(costs).all()


# -- the scaling benchmark row ----------------------------------------------
def test_multichip_bench_row():
    """benchmarks/multichip.py --smoke in-process: one row with the
    scaling facts, every structural gate green on the CPU mesh."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks", "multichip.py")
    spec = importlib.util.spec_from_file_location("_bench_multichip", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    row = mod.run_smoke(devices=8)
    assert "error" not in row, row
    for k in ("dp1_step_ms", "dp_step_ms", "scaling_efficiency",
              "collective_bytes", "reduce_ops", "reduce_ops_in_loop",
              "opt_state_bytes_per_device", "opt_state_bytes_replicated",
              "accum_plan", "dp_fsdp_step_ms", "param_bytes_per_device",
              "param_bytes_replicated", "fsdp_gathers_in_loop"):
        assert k in row, (k, row)
    assert not [k for k in row if k.startswith("gate_")], row
    assert row["reduce_ops_in_loop"] == 0
    assert row["opt_state_bytes_per_device"] * 4 <= row[
        "opt_state_bytes_replicated"]
    assert row["accum_plan"]["mode"] == "local"
    # the FSDP gate facts: params sharded at rest, gathers in loop
    assert row["param_bytes_per_device"] * 2 <= row[
        "param_bytes_replicated"]
    assert row["fsdp_gathers_in_loop"] > 0
    assert row["fsdp_reduce_ops_in_loop"] == 0
    assert row["fsdp_groups"] > 0


def test_comm_overlap_flags(monkeypatch):
    assert papi.comm_overlap_flags("cpu") == ()
    assert any("latency_hiding" in f
               for f in papi.comm_overlap_flags("tpu"))
    monkeypatch.setenv("XLA_FLAGS", "--xla_foo=1")
    applied = papi.enable_comm_overlap("tpu")
    assert applied and all(
        f.split("=")[0] in os.environ["XLA_FLAGS"] for f in applied)
    assert os.environ["XLA_FLAGS"].startswith("--xla_foo=1")
    # one flag's key is a PREFIX of another's: a pre-set longer flag must
    # not swallow the shorter one (keys compare tokenized, not substring)
    monkeypatch.setenv(
        "XLA_FLAGS",
        "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=false")
    papi.enable_comm_overlap("tpu")
    assert ("--xla_tpu_enable_async_collective_fusion=true"
            in os.environ["XLA_FLAGS"].split())
    monkeypatch.setenv("PADDLE_TPU_COMM_OVERLAP", "0")
    assert papi.enable_comm_overlap("tpu") == ()
    # cpu platform never touches the env (unknown flags abort XLA init)
    monkeypatch.setenv("PADDLE_TPU_COMM_OVERLAP", "1")
    monkeypatch.setenv("XLA_FLAGS", "")
    assert papi.enable_comm_overlap("cpu") == ()
    assert os.environ["XLA_FLAGS"] == ""
