"""Per-op attribution engine + crash flight recorder (ISSUE 11) —
HLO-walk table math on planted text, coverage on a real compiled GPT
step, roofline bound classification, regression attribution over a
planted two-artifact fixture, flight-bundle dumps via the PR-8 injected
faults, grad-norm telemetry, and serving goodput accounting."""

import json
import math
import os
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.models import transformer
from paddle_tpu.observability import attribution as attr
from paddle_tpu.observability import bench_history as bh
from paddle_tpu.observability import flight
from paddle_tpu.observability import metrics as _obs


# -- HLO walk on planted text ------------------------------------------------

_PLANTED_HLO = """\
HloModule planted

%fused_computation.1 (p0: f32[64,64]) -> f32[64,64] {
  %p0 = f32[64,64]{1,0} parameter(0)
  %exp.0 = f32[64,64]{1,0} exponential(f32[64,64]{1,0} %p0)
  ROOT %add.9 = f32[64,64]{1,0} add(f32[64,64]{1,0} %exp.0, f32[64,64]{1,0} %p0)
}

ENTRY %main (a: f32[64,32], b: f32[32,64]) -> f32[64,64] {
  %a = f32[64,32]{1,0} parameter(0)
  %b = f32[32,64]{1,0} parameter(1)
  %dot.1 = f32[64,64]{1,0} dot(f32[64,32]{1,0} %a, f32[32,64]{1,0} %b), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %fus.1 = f32[64,64]{1,0} fusion(f32[64,64]{1,0} %dot.1), kind=kLoop, calls=%fused_computation.1
  %ar.0 = f32[64,64]{1,0} all-reduce(f32[64,64]{1,0} %fus.1), replica_groups={}, to_apply=%sum
  %kern.0 = f32[64,64]{1,0} multiply(f32[64,64]{1,0} %ar.0, f32[64,64]{1,0} %ar.0), metadata={op_name="flash" source_file="/repo/paddle_tpu/ops/pallas_attention.py" source_line=1}
  ROOT %cp.0 = f32[64,64]{1,0} copy(f32[64,64]{1,0} %kern.0)
}
"""


def test_attribute_hlo_planted_table():
    att = attr.attribute_hlo(_PLANTED_HLO, peak_flops=1e12, hbm_bw=1e11)
    cls = att["classes"]
    # dot: 2 * 64*64 * 32 contraction width — exact
    assert cls["matmul"]["flops"] == 2 * 64 * 64 * 32
    # the fusion body's add counts flops (one per element) but NO bytes
    # (fusion intermediates never touch HBM); the exponential is a
    # transcendental — its own column, excluded from flops
    assert cls["elementwise"]["flops"] == 64 * 64  # body add only
    assert cls["elementwise"]["transcendentals"] == 64 * 64
    # the fusion op line carries the boundary bytes
    assert cls["elementwise"]["bytes"] == 2 * 64 * 64 * 4
    # collective classed by kind
    assert cls["collective.all-reduce"]["ops"] == 1
    assert cls["collective.all-reduce"]["bytes"] == 2 * 64 * 64 * 4
    # the multiply whose source_file is pallas_attention belongs to the
    # KERNEL, not to elementwise
    assert cls["pallas"]["ops"] == 1
    assert cls["pallas"]["flops"] == 64 * 64
    # shares sum to ~1 and every class has a bound verdict
    assert abs(sum(r["share"] for r in cls.values()) - 1.0) < 0.01
    assert all(r["bound"] in ("compute", "memory") for r in cls.values())


def test_roofline_bound_classification():
    # compute-heavy: flops/peak dominates bytes/bw
    hlo = """\
ENTRY %m (a: f32[512,512], b: f32[512,512]) -> f32[512,512] {
  %a = f32[512,512]{1,0} parameter(0)
  %b = f32[512,512]{1,0} parameter(1)
  ROOT %dot.1 = f32[512,512]{1,0} dot(f32[512,512]{1,0} %a, f32[512,512]{1,0} %b), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""
    att = attr.attribute_hlo(hlo, peak_flops=1e12, hbm_bw=1e12)
    assert att["classes"]["matmul"]["bound"] == "compute"
    # memory-heavy: same table against a slow-memory roofline flips
    att2 = attr.attribute_hlo(hlo, peak_flops=1e15, hbm_bw=1e9)
    assert att2["classes"]["matmul"]["bound"] == "memory"


def _small_gpt(policy="selective", n_layer=3, t=16, d=32, vocab=64):
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 7
    with pt.program_guard(main, startup):
        outs = transformer.build(vocab_size=vocab, n_layer=n_layer,
                                 n_head=2, d_model=d, max_len=t,
                                 dropout_rate=0.0, dtype="float32")
    if policy:
        pt.memory_optimize(main, policy=policy)
    return main, startup, outs["avg_cost"]


@pytest.fixture
def gpt_compiled():
    main, startup, loss = _small_gpt()
    scope = pt.Scope()
    with pt.core.scope.scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup, scope=scope)
        rng = np.random.default_rng(5)
        toks = rng.integers(0, 64, (2, 16)).astype(np.int64)
        feed = {"tokens": toks, "labels": np.roll(toks, -1, axis=1)}
        cost = exe.compile_only(main, feed=feed, fetch_list=[loss],
                                scope=scope)
    return exe, cost


def test_attribution_coverage_on_compiled_gpt(gpt_compiled):
    """The real compiled step's table covers >= 95% of the
    executable's own cost-analysis flops — the selftest contract at
    test granularity."""
    exe, cost = gpt_compiled
    att = exe.last_attribution
    assert att is not None
    assert att["coverage"] is not None and att["coverage"] >= 0.95
    assert "matmul" in att["classes"] and "pallas" in att["classes"]
    # interpret-mode pallas: the kernel's dots are attributed to it
    assert att["classes"]["pallas"]["flops"] > 0
    assert att["workload"].startswith("op=step|t=16|")
    assert "remat=selective" in att["workload"]


def test_attribution_summary_in_cost_dict(gpt_compiled):
    exe, cost = gpt_compiled
    summ = cost.get("attribution")
    assert summ and summ["top"] and summ["coverage"] == \
        exe.last_attribution["coverage"]
    # top entries are [class, share, bound] sorted by estimated time
    assert all(len(e) == 3 for e in summ["top"])


def test_attribution_kill_switch(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_ATTR", "0")
    main, startup, loss = _small_gpt(policy=None, n_layer=2)
    scope = pt.Scope()
    with pt.core.scope.scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup, scope=scope)
        rng = np.random.default_rng(5)
        toks = rng.integers(0, 64, (2, 16)).astype(np.int64)
        feed = {"tokens": toks, "labels": np.roll(toks, -1, axis=1)}
        cost = exe.compile_only(main, feed=feed, fetch_list=[loss],
                                scope=scope)
    assert exe.last_attribution is None
    assert "attribution" not in cost


def test_finalize_roofline_recomputes_shares_after_flop_patch():
    """The TPU path patches opaque-kernel flops AFTER the walk; the
    re-finalize must move est_ms/bound/share, or a flash slowdown
    would never show in the pallas share (review finding)."""
    hlo = """\
ENTRY %m (a: f32[64,64]) -> f32[64,64] {
  %a = f32[64,64]{1,0} parameter(0)
  %cc.0 = f32[64,64]{1,0} custom-call(f32[64,64]{1,0} %a), custom_call_target="tpu_custom_call"
  ROOT %add.0 = f32[64,64]{1,0} add(f32[64,64]{1,0} %cc.0, f32[64,64]{1,0} %a)
}
"""
    att = attr.attribute_hlo(hlo, peak_flops=1e9, hbm_bw=1e12)
    before = att["classes"]["pallas"]["share"]
    assert att["classes"]["pallas"]["bound"] == "memory"
    att["classes"]["pallas"]["flops"] = 10 ** 9  # a 1s kernel estimate
    attr._finalize_roofline(att)
    after = att["classes"]["pallas"]
    assert after["share"] > before and after["share"] > 0.9
    assert after["bound"] == "compute"
    assert att["hlo_flops_total"] >= 10 ** 9


def test_reconcile_error_pct():
    att = {"est_ms_total": 2.0}
    rec = attr.reconcile(att, 0.004)  # measured 4 ms
    assert rec["measured_ms"] == 4.0
    assert rec["err_pct"] == -50.0
    assert attr.reconcile(att, None) is None
    assert attr.reconcile({}, 0.01) is None


# -- regression attribution over bench history -------------------------------

def _att_extra(shares):
    return {"classes": {c: {"flops": 1, "bytes": 1, "est_ms": s,
                            "share": s, "bound": "memory"}
                        for c, s in shares.items()},
            "workload": "k", "coverage": 0.99, "est_ms_total": 1.0}


def test_regression_attribution_planted_fixture(tmp_path):
    rows = [
        ("BENCH_r01.json", 100.0,
         {"matmul": 0.6, "elementwise": 0.3,
          "collective.all-reduce": 0.1}),
        ("BENCH_r02.json", 40.0,
         {"matmul": 0.34, "elementwise": 0.3,
          "collective.all-reduce": 0.36}),
    ]
    for i, (name, value, shares) in enumerate(rows):
        (tmp_path / name).write_text(json.dumps({
            "n": i + 1, "rc": 0, "parsed": {
                "metric": "gpt_train_tokens_per_sec_per_chip",
                "value": value, "unit": "tok/s",
                "extra": {"gpt_attribution": _att_extra(shares)}}}))
    summary, rws = bh.history(str(tmp_path))
    assert summary["regressions"]
    key = "BENCH_r02.json:gpt_train_tokens_per_sec_per_chip"
    moved = summary["regression_attribution"][key]
    # the biggest mover is named first: the collective share grew
    assert moved[0]["op_class"] == "collective.all-reduce"
    assert moved[0]["delta"] > 0
    # matmul's share shrank and is also named
    assert any(m["op_class"] == "matmul" and m["delta"] < 0
               for m in moved)


def test_regression_without_tables_has_no_attribution(tmp_path):
    for i, v in enumerate((100.0, 40.0)):
        (tmp_path / f"BENCH_r0{i+1}.json").write_text(json.dumps({
            "n": i + 1, "rc": 0, "parsed": {
                "metric": "m", "value": v, "unit": "u"}}))
    summary, _ = bh.history(str(tmp_path))
    assert summary["regressions"]
    assert summary["regression_attribution"] == {}


def test_bench_history_tracks_serving_goodput(tmp_path):
    """serving_goodput_under_slo is a tracked metric: a >10% drop vs
    best-so-far flags like tok_s does."""
    for i, v in enumerate((500.0, 300.0)):
        (tmp_path / f"BENCH_r0{i+1}.json").write_text(json.dumps({
            "n": i + 1, "rc": 0, "parsed": {
                "metric": "m", "value": 1.0, "unit": "u",
                "extra": {"serving_goodput_under_slo": v,
                          "serving_tok_s": 600.0}}}))
    summary, _ = bh.history(str(tmp_path))
    assert any(r["metric"] == "serving_goodput_under_slo"
               for r in summary["regressions"])


# -- flight recorder ---------------------------------------------------------

@pytest.fixture
def recorder(tmp_path):
    rec = flight.FlightRecorder(capacity=5, out_dir=str(tmp_path))
    old = flight.set_recorder(rec)
    yield rec
    flight.set_recorder(old)


def test_flight_ring_bounded_and_dump_loadable(recorder, tmp_path):
    for i in range(12):
        recorder.record_step(step=i, loss=float(i), grad_norm=0.5 * i)
    steps = recorder.steps()
    assert len(steps) == 5 and steps[0]["step"] == 7  # newest window
    path = recorder.dump("watchdog", age_s=1.5)
    assert path and os.path.exists(path)
    b = flight.load_bundle(path)
    assert b["reason"] == "watchdog"
    assert b["context"]["age_s"] == 1.5
    assert [s["step"] for s in b["steps"]] == [7, 8, 9, 10, 11]
    assert b["grad_norm_window"] == [0.5 * i for i in range(7, 12)]
    assert "metrics" in b and "spans" in b


def test_flight_kill_switch(recorder, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FLIGHT", "0")
    recorder.record_step(step=1)
    assert recorder.steps() == []
    assert recorder.dump("watchdog") is None
    assert recorder.dumps == []


def test_flight_dump_cap(recorder):
    recorder.max_dumps = 2
    assert recorder.dump("watchdog") is not None
    assert recorder.dump("watchdog") is not None
    assert recorder.dump("watchdog") is None  # storm guard
    assert len(recorder.dumps) == 2


def test_classify_exception():
    assert flight.classify_exception(MemoryError("x")) == "oom"
    assert flight.classify_exception(
        RuntimeError("RESOURCE_EXHAUSTED: out of memory")) == "oom"
    assert flight.classify_exception(
        FloatingPointError("NaN detected")) == "nan_trip"
    assert flight.classify_exception(
        ValueError("bad shape")) == "trainer_exception"
    # cause chains are walked
    try:
        try:
            raise RuntimeError("Failed to allocate 1G")
        except RuntimeError as inner:
            raise RuntimeError("error lowering op") from inner
    except RuntimeError as outer:
        assert flight.classify_exception(outer) == "oom"


def _tiny_trainer():
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[8])
        y = layers.data("y", shape=[1])
        h = layers.fc(x, 8, act="relu")
        loss = layers.reduce_mean(layers.square(layers.fc(h, 1) - y))
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        trainer = pt.trainer.Trainer(loss, [x, y])
    return main, trainer


def _reader(n=4, batch=4):
    rng = np.random.default_rng(0)

    def reader():
        for _ in range(n):
            yield [(rng.normal(size=(8,)).astype(np.float32),
                    rng.normal(size=(1,)).astype(np.float32))
                   for _ in range(batch)]

    return reader


def test_injected_nan_fault_dumps_flight_bundle(recorder, monkeypatch):
    """The PR-8 nan_grad injection point gates the flight recorder: the
    poisoned step's bundle carries the triggering step record and the
    grad-norm window."""
    from paddle_tpu.resilience import faults

    faults.reset()
    main, trainer = _tiny_trainer()
    monkeypatch.setenv("PADDLE_TPU_FAULT", "nan_grad:2")
    with pt.program_guard(main, pt.Program()):
        trainer.train(_reader(), num_passes=1)
    nan_dumps = [p for p in recorder.dumps if "nan_trip" in p]
    assert nan_dumps, recorder.dumps
    b = flight.load_bundle(nan_dumps[0])
    assert b["reason"] == "nan_trip"
    assert any(isinstance(s.get("loss"), float)
               and math.isnan(s["loss"]) for s in b["steps"])
    assert b["grad_norm_window"]
    # phase durations recorded per step
    assert all("phase_dispatch" in s for s in b["steps"])


def test_trainer_exception_dumps_flight_bundle(recorder, monkeypatch):
    """An exception escaping the train loop (the injected reader fault)
    dumps a classified bundle before propagating."""
    from paddle_tpu.resilience import faults

    faults.reset()
    main, trainer = _tiny_trainer()
    monkeypatch.setenv("PADDLE_TPU_FAULT", "reader_err:3")
    with pt.program_guard(main, pt.Program()):
        with pytest.raises(RuntimeError):
            trainer.train(_reader(), num_passes=1)
    assert any("trainer_exception" in p for p in recorder.dumps)
    b = flight.load_bundle(
        [p for p in recorder.dumps if "trainer_exception" in p][0])
    assert b["steps"]  # the pre-crash history survived


def test_watchdog_trip_dumps_flight_bundle(recorder):
    from paddle_tpu.resilience.watchdog import Watchdog

    wd = Watchdog(deadline=0.1, label="attr-test", interval=0.02)
    try:
        time.sleep(0.5)
    finally:
        wd.stop()
    wd_dumps = [p for p in recorder.dumps if "watchdog" in p]
    assert wd_dumps
    b = flight.load_bundle(wd_dumps[0])
    assert b["reason"] == "watchdog"
    assert b["context"]["label"] == "attr-test"


# -- training-dynamics telemetry ---------------------------------------------

def test_grad_norm_recorded_per_step(recorder):
    main, trainer = _tiny_trainer()
    seen = []

    def handler(ev):
        if type(ev).__name__ == "EndIteration":
            seen.append(ev.grad_norm)

    with pt.program_guard(main, pt.Program()):
        trainer.train(_reader(), num_passes=1, event_handler=handler)
    assert len(seen) == 4
    assert all(isinstance(g, float) and g > 0 for g in seen)
    assert _obs.get_registry().value("trainer.grad_norm") > 0
    # the flight ring carries the same stream
    assert all(s.get("grad_norm") for s in recorder.steps())


def test_grad_norm_kill_switch(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_GRADNORM", "0")
    main, trainer = _tiny_trainer()
    seen = []

    def handler(ev):
        if type(ev).__name__ == "EndIteration":
            seen.append(ev.grad_norm)

    with pt.program_guard(main, pt.Program()):
        trainer.train(_reader(n=2), num_passes=1, event_handler=handler)
    assert seen and all(g is None for g in seen)


def test_loss_zscore_in_jsonl(tmp_path):
    from paddle_tpu.observability import MetricsReporter, read_jsonl

    main, trainer = _tiny_trainer()
    path = str(tmp_path / "run.jsonl")
    reporter = MetricsReporter(log_every_n=0, jsonl_path=path)
    with pt.program_guard(main, pt.Program()):
        trainer.train(_reader(n=12), num_passes=1,
                      event_handler=reporter)
    reporter.close()
    recs = read_jsonl(path, event="step")
    assert len(recs) == 12
    assert all("grad_norm" in r and r["grad_norm"] > 0 for r in recs)
    # z-score appears once the window holds 8 samples
    assert any(r.get("loss_zscore") is not None for r in recs[8:])
    # attribution summary rides the same records
    assert any(r.get("attr_est_ms") for r in recs)
    assert any(r.get("attr_model_err_pct") is not None for r in recs)


# -- serving goodput (the engine-side accounting) ----------------------------

VOCAB, NL, NH, DM, T = 50, 2, 2, 32, 32


@pytest.fixture
def serving_params():
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        transformer.build(vocab_size=VOCAB, n_layer=NL, n_head=NH,
                          d_model=DM, max_len=T, dropout_rate=0.0,
                          dtype="float32")
        exe = pt.Executor()
        exe.run(startup)
        return transformer.extract_params(program=main)


def test_goodput_counts_only_slo_met_tokens(serving_params):
    from paddle_tpu.serving import ServingEngine

    _obs.get_registry().clear(prefix="serving.")
    eng = ServingEngine(serving_params, NL, NH, DM, max_len=T,
                        max_slots=4, decode_chunk=2, min_bucket=4,
                        ttft_slo_s=600.0, e2e_slo_s=600.0)
    prompts = [np.arange(1, 5, dtype=np.int32)] * 3
    outs = eng.generate_many(prompts, max_new_tokens=4)
    st = eng.stats()
    assert st.get("serving.slo_violations", 0) == 0
    assert st["serving.goodput_tok_s"] > 0
    # every request judged, all within budget
    assert len(outs) == 3
