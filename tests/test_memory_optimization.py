"""memory_optimize tests (reference: book_memory_optimization/ re-runs
models under memory_optimize() and expects identical training — here remat
must leave the math bit-identical while trading FLOPs for memory)."""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.memory_optimization_transpiler import (
    ControlFlowGraph,
    memory_optimize,
    release_memory,
)


def _mlp_program(seed=0):
    pt.core.unique_name.reset()  # identical var names across the two builds
    main, startup = pt.Program(), pt.Program()
    main.random_seed = seed
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[16])
        y = layers.data("y", shape=[1], dtype="int64")
        h = x
        for i in range(4):
            h = layers.fc(input=h, size=32, act="relu")
        h = layers.dropout(h, dropout_prob=0.3)
        pred = layers.fc(input=h, size=4, act="softmax")
        loss = layers.mean(layers.cross_entropy(input=pred, label=y))
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def test_liveness_analysis():
    main, _, _ = _mlp_program()
    block = main.global_block()
    g = ControlFlowGraph(main, 0, block.ops[: block.backward_index])
    # data vars are live-in to the first op that uses them
    assert "x" in g.live_in[0]
    # last op's live_out contains nothing defined only for intermediate use
    assert g.peak_live_bytes() > 0
    # every use of a temp var appears in live ranges
    for i, op in enumerate(g.ops):
        for n in op.input_names():
            assert n in g.live_in[i]


def _train(main, startup, loss, steps=4):
    scope = pt.Scope()
    pt.core.scope._scope_stack.append(scope)
    try:
        exe = pt.Executor()
        exe.run(startup, scope=scope)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 16)).astype(np.float32)
        y = rng.integers(0, 4, size=(8, 1)).astype(np.int64)
        losses = [
            float(np.asarray(
                exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss],
                        scope=scope)[0]).ravel()[0])
            for _ in range(steps)
        ]
        params = {
            n: np.asarray(scope.get(n))
            for n in scope.var_names() if n.endswith(".w")
        }
        return losses, params
    finally:
        pt.core.scope._scope_stack.pop()


def test_remat_matches_baseline_exactly():
    base_main, base_startup, base_loss = _mlp_program(seed=7)
    opt_main, opt_startup, opt_loss = _mlp_program(seed=7)
    segs = memory_optimize(opt_main)
    assert len(segs) >= 2
    # segments tile the forward prefix exactly
    bw = opt_main.global_block().backward_index
    assert segs[0][0] == 0 and segs[-1][1] == bw
    for (a, b, _), (c, d, _) in zip(segs, segs[1:]):
        assert b == c

    base_losses, base_params = _train(base_main, base_startup, base_loss)
    opt_losses, opt_params = _train(opt_main, opt_startup, opt_loss)
    # same seeds + remat => identical math (incl. dropout masks)
    np.testing.assert_allclose(base_losses, opt_losses, rtol=1e-6)
    for n in base_params:
        np.testing.assert_allclose(base_params[n], opt_params[n], rtol=1e-5,
                                   err_msg=n)


def test_memory_optimize_small_program_noop():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        y = layers.data("y", shape=[1])
        loss = layers.mean(layers.square_error_cost(
            layers.fc(input=x, size=1), y))
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    segs = memory_optimize(main)
    # tiny program: no segmentation
    assert segs == [] or len(segs) >= 1
    assert release_memory(main) is main


def test_remat_on_resnet_cifar():
    """The book_memory_optimization pattern: a conv net still trains under
    memory_optimize."""
    from paddle_tpu.models import resnet

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        outs = resnet.build(depth=8, class_dim=4, image_shape=(3, 16, 16),
                            learning_rate=0.05, dtype="float32")
    memory_optimize(main)
    scope = pt.Scope()
    pt.core.scope._scope_stack.append(scope)
    try:
        exe = pt.Executor()
        exe.run(startup, scope=scope)
        rng = np.random.default_rng(1)
        img = rng.normal(size=(4, 3, 16, 16)).astype(np.float32)
        label = rng.integers(0, 4, size=(4, 1)).astype(np.int64)
        losses = [
            float(np.asarray(exe.run(
                main, feed={"img": img, "label": label},
                fetch_list=[outs["avg_cost"]], scope=scope)[0]).ravel()[0])
            for _ in range(4)
        ]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]
    finally:
        pt.core.scope._scope_stack.pop()


def test_memory_optimize_transformer_remat():
    """Remat composes with the flash-attention transformer: marked
    segments recompute under jax.checkpoint and training still descends
    (the long-context memory lever, SURVEY §5 memory_optimization)."""
    from paddle_tpu.models import transformer

    outs = transformer.build(vocab_size=40, n_layer=2, n_head=2,
                             d_model=32, max_len=16, dropout_rate=0.0,
                             learning_rate=1e-2, dtype="float32")
    main = pt.default_main_program()
    segs = pt.memory_optimize(main)
    assert segs, "no remat segments marked"
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 40, (4, 16)).astype(np.int64)
    lbls = np.roll(toks, -1, axis=1)
    losses = []
    for _ in range(5):
        (c,) = exe.run(feed={"tokens": toks, "labels": lbls},
                       fetch_list=[outs["avg_cost"]])
        losses.append(float(np.asarray(c).ravel()[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_selective_policy_keeps_flash_unwrapped():
    """The selective policy (VERDICT r3 item 2): flash_attention ops land
    in unwrapped segments (residuals saved, kernel never re-run); the
    cheap runs between them are wrapped."""
    from paddle_tpu.models import transformer

    outs = transformer.build(vocab_size=40, n_layer=2, n_head=2,
                             d_model=32, max_len=16, dropout_rate=0.0,
                             dtype="float32")
    main = pt.default_main_program()
    segs = pt.memory_optimize(main)  # selective is the default
    block = main.global_block()
    bw = block.backward_index
    # tiles the forward prefix
    assert segs[0][0] == 0 and segs[-1][1] == bw
    for (a, b, _), (c, d, _) in zip(segs, segs[1:]):
        assert b == c
    flash_idx = [i for i in range(bw)
                 if block.ops[i].type == "flash_attention"]
    assert flash_idx, "transformer forward has no flash ops?"
    for i in flash_idx:
        (seg,) = [s for s in segs if s[0] <= i < s[1]]
        assert not seg[2], f"flash op {i} inside wrapped segment {seg}"
    assert any(w for _, _, w in segs), "nothing wrapped at all"


def test_selective_remat_matches_no_remat_exactly():
    """Selective remat must not change the math: same seeds, identical
    losses and updated params vs the un-optimized program."""
    from paddle_tpu.models import transformer

    def build(opt):
        pt.core.unique_name.reset()
        main, startup = pt.Program(), pt.Program()
        main.random_seed = 11
        with pt.program_guard(main, startup):
            outs = transformer.build(vocab_size=30, n_layer=2, n_head=2,
                                     d_model=32, max_len=12,
                                     dropout_rate=0.0, dtype="float32")
        if opt:
            segs = memory_optimize(main)
            assert any(not w for _, _, w in segs)
        return main, startup, outs["avg_cost"]

    rng = np.random.default_rng(3)
    toks = rng.integers(0, 30, (4, 12)).astype(np.int64)
    lbls = np.roll(toks, -1, axis=1)

    def train(main, startup, loss):
        scope = pt.Scope()
        pt.core.scope._scope_stack.append(scope)
        try:
            exe = pt.Executor()
            exe.run(startup, scope=scope)
            return [
                float(np.asarray(exe.run(
                    main, feed={"tokens": toks, "labels": lbls},
                    fetch_list=[loss], scope=scope)[0]).ravel()[0])
                for _ in range(4)
            ]
        finally:
            pt.core.scope._scope_stack.pop()

    base = train(*build(False))
    opt = train(*build(True))
    np.testing.assert_allclose(base, opt, rtol=1e-6)


def test_error_clip_shifts_3tuple_segments():
    """Regression: error_clip_callback re-indexes remat segments; they are
    (start, end, wrapped) 3-tuples and the wrap flag must survive."""
    from paddle_tpu.clip import ErrorClipByValue, error_clip_callback

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[8])
        y = layers.data("y", shape=[1])
        h = layers.fc(input=x, size=8, act="relu")
        h2 = layers.fc(input=h, size=8, act="relu")
        loss = layers.mean(layers.square_error_cost(
            layers.fc(input=h2, size=1), y))
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    segs = memory_optimize(main, policy="full")
    assert segs
    # clip the gradient path through a forward var (inserts an op and
    # must shift segment indices without dropping the wrap flag)
    error_clip_callback(h, ErrorClipByValue(max=1.0))
    shifted = main._remat_segments
    assert len(shifted) == len(segs)
    for (s0, t0, w0), (s1, t1, w1) in zip(segs, shifted):
        assert w1 == w0  # wrap flag preserved
        assert (s1, t1) in ((s0, t0), (s0, t0 + 1), (s0 + 1, t0 + 1))


def test_memory_optimize_rejects_bad_policy():
    import pytest as _pytest

    main, _, _ = _mlp_program()
    with _pytest.raises(ValueError, match="policy"):
        memory_optimize(main, policy="selectiv")


def test_selective_remat_with_dropout_matches_exactly():
    """RNG pinning through the custom-VJP remat segments: with dropout
    ON, selective remat must still be bit-identical to no-remat (the
    recompute derives the same per-op keys)."""
    from paddle_tpu.models import transformer

    def build(opt):
        pt.core.unique_name.reset()
        main, startup = pt.Program(), pt.Program()
        main.random_seed = 21
        with pt.program_guard(main, startup):
            outs = transformer.build(vocab_size=30, n_layer=2, n_head=2,
                                     d_model=32, max_len=12,
                                     dropout_rate=0.2, dtype="float32")
        if opt:
            memory_optimize(main)
        return main, startup, outs["avg_cost"]

    rng = np.random.default_rng(5)
    toks = rng.integers(0, 30, (4, 12)).astype(np.int64)
    lbls = np.roll(toks, -1, axis=1)

    def train(main, startup, loss):
        scope = pt.Scope()
        pt.core.scope._scope_stack.append(scope)
        try:
            exe = pt.Executor()
            exe.run(startup, scope=scope)
            return [
                float(np.asarray(exe.run(
                    main, feed={"tokens": toks, "labels": lbls},
                    fetch_list=[loss], scope=scope)[0]).ravel()[0])
                for _ in range(4)
            ]
        finally:
            pt.core.scope._scope_stack.pop()

    base = train(*build(False))
    opt = train(*build(True))
    np.testing.assert_allclose(base, opt, rtol=1e-6)
