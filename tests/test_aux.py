"""Auxiliary-subsystem tests: flags, check_nan_inf, net_drawer, Parameters
tar io, plot, CLI (version/dump_config/merge_model), new datasets."""

import io
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def test_flags_env_and_argv(monkeypatch):
    from paddle_tpu import flags

    assert pt.FLAGS.check_nan_inf is False
    rest = flags.init_flags(["--check_nan_inf=true", "--unknown", "pos"])
    try:
        assert pt.FLAGS.check_nan_inf is True
        assert rest == ["--unknown", "pos"]
    finally:
        pt.FLAGS.check_nan_inf = False


def test_check_nan_inf_raises():
    x = layers.data("x", shape=[2])
    out = layers.log(x)  # log of negative -> nan
    exe = pt.Executor()
    pt.FLAGS.check_nan_inf = True
    try:
        with pytest.raises(FloatingPointError, match="NaN/Inf"):
            exe.run(feed={"x": np.array([[-1.0, 2.0]], np.float32)},
                    fetch_list=[out])
    finally:
        pt.FLAGS.check_nan_inf = False


def test_net_drawer_dot():
    x = layers.data("x", shape=[4])
    y = layers.fc(input=x, size=3, act="relu")
    loss = layers.mean(y)
    dot = pt.net_drawer.draw_graph(pt.default_main_program())
    assert dot.startswith("digraph")
    assert "mul" in dot and "var_x" in dot


def test_parameters_tar_roundtrip():
    x = layers.data("x", shape=[4])
    layers.fc(input=x, size=3)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    params = pt.parameters.create()
    assert len(params) == 2
    orig = {n: params[n].copy() for n in params}
    buf = io.BytesIO()
    params.to_tar(buf)
    # perturb, then restore
    for n in params:
        params[n] = np.zeros_like(orig[n])
    buf.seek(0)
    params.from_tar(buf)
    for n in params:
        np.testing.assert_array_equal(params[n], orig[n])


def test_ploter_records():
    p = pt.plot.Ploter("train", "test")
    p.append("train", 0, 1.0)
    p.append("train", 1, 0.5)
    p.append("test", 0, 0.9)
    assert p.data["train"].value == [1.0, 0.5]
    p.reset()
    assert p.data["train"].value == []


def test_new_datasets_schema():
    from paddle_tpu.dataset import flowers, imikolov, sentiment, voc2012

    d = imikolov.build_dict()
    sample = next(imikolov.train(d, n=5)())
    assert len(sample) == 5 and all(isinstance(w, int) for w in sample)

    ids, label = next(sentiment.train()())
    assert label in (0, 1) and len(ids) > 0

    img, lbl = next(flowers.train()())
    assert img.shape == (3, 224, 224) and 0 <= lbl < flowers.CLASS_NUM

    img, seg = next(voc2012.train()())
    assert img.shape[0] == 3 and seg.shape == img.shape[1:]
    assert seg.max() < voc2012.CLASS_NUM


def _run_cli(*args, cwd=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu", *args],
        capture_output=True, text=True, env=env, cwd=cwd, timeout=240,
    )


def test_cli_version():
    r = _run_cli("version")
    assert r.returncode == 0, r.stderr
    assert "paddle_tpu" in r.stdout


def test_cli_dump_config_and_train(tmp_path):
    cfg = tmp_path / "config.py"
    cfg.write_text(
        "import numpy as np\n"
        "from paddle_tpu import layers, optimizer\n"
        "def build():\n"
        "    x = layers.data('x', shape=[4])\n"
        "    y = layers.data('y', shape=[1])\n"
        "    pred = layers.fc(input=x, size=1)\n"
        "    cost = layers.mean(layers.square_error_cost(pred, y))\n"
        "    optimizer.SGD(learning_rate=0.05).minimize(cost)\n"
        "    return {'feed': [x, y], 'avg_cost': cost}\n"
        "def train_reader():\n"
        "    rng = np.random.RandomState(0)\n"
        "    for _ in range(64):\n"
        "        x = rng.rand(4).astype('float32')\n"
        "        yield x, np.array([x.sum()], 'float32')\n"
    )
    r = _run_cli("dump_config", str(cfg))
    assert r.returncode == 0, r.stderr
    assert "mul" in r.stdout
    r = _run_cli("dump_config", "--dot", str(cfg))
    assert r.returncode == 0 and "digraph" in r.stdout
    r = _run_cli("train", str(cfg), "--batch-size", "16",
                 "--num-passes", "2")
    assert r.returncode == 0, r.stderr
    assert "pass 1 done" in r.stdout


def test_cli_merge_model(tmp_path):
    x = layers.data("x", shape=[4])
    pred = layers.fc(input=x, size=2, act="softmax")
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    model_dir = tmp_path / "model"
    pt.io.save_inference_model(str(model_dir), ["x"], [pred], exe)
    out = tmp_path / "bundle.tar"
    r = _run_cli("merge_model", str(model_dir), str(out))
    assert r.returncode == 0, r.stderr
    assert out.exists() and out.stat().st_size > 0


def test_mq2007_dataset_formats():
    from paddle_tpu.dataset import mq2007

    score, feat = next(mq2007.train("pointwise")())
    assert feat.shape == (46,) and np.isfinite(score)
    label, better, worse = next(mq2007.train("pairwise")())
    assert label.shape == (1,) and better.shape == worse.shape == (46,)
    scores, feats = next(mq2007.test("listwise")())
    assert feats.shape == (len(scores), 46)


def test_provider_decorator_protocol():
    """PyDataProvider2 @provider shim: typed slots, dict rows, caching."""
    from paddle_tpu.reader import provider as p

    calls = {"n": 0}

    @p.provider(input_types={"img": p.dense_vector(4),
                             "label": p.integer_value(10)},
                cache=p.CacheType.CACHE_PASS_IN_MEM)
    def process(settings, filename):
        assert settings.input_types is not None
        calls["n"] += 1
        for i in range(5):
            yield {"label": i, "img": [i] * 4}

    reader = process([None])
    rows = list(reader())
    assert len(rows) == 5
    img, label = rows[2]
    assert img.dtype == np.float32 and img.shape == (4,)
    assert label.dtype == np.int64 and int(label) == 2
    rows2 = list(reader())  # second pass: served from the in-mem cache
    assert calls["n"] == 1, "generator re-entered despite CACHE_PASS_IN_MEM"
    assert all((a[0] == b[0]).all() and a[1] == b[1]
               for a, b in zip(rows, rows2))


def test_provider_sparse_and_sequence_slots():
    from paddle_tpu.reader import provider as p

    @p.provider(input_types=[p.sparse_binary_vector(6),
                             p.integer_value_sequence(100),
                             p.sparse_float_vector(5)])
    def process(settings, filename):
        yield [1, 3], [7, 8, 9], [(0, 0.5), (4, 2.0)]

    sb, seq, sf = next(process()())
    # sparse slots stay sparse (SparseRow); todense() is the explicit
    # small-dim escape hatch (test_sparse_slots.py covers the native path)
    assert sb.todense().tolist() == [0, 1, 0, 1, 0, 0]
    assert seq.tolist() == [7, 8, 9] and seq.dtype == np.int64
    assert sf.todense().tolist() == [0.5, 0, 0, 0, 2.0]


def test_async_checkpointer_roundtrip(tmp_path):
    """AsyncCheckpointer writes load_persistables-compatible checkpoints
    atomically from a background thread."""
    import paddle_tpu as pt
    from paddle_tpu.models import fit_a_line

    outs = fit_a_line.build(learning_rate=0.05)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 13)).astype(np.float32)
    y = (x @ rng.normal(size=(13, 1))).astype(np.float32)
    exe.run(feed={"x": x, "y": y}, fetch_list=[outs["avg_cost"]])

    ckpt = pt.io.AsyncCheckpointer()
    d = str(tmp_path / "ck")
    ckpt.save(d)
    ckpt.close()

    scope = pt.core.scope.global_scope()
    want = {p.name: np.asarray(scope.get(p.name))
            for p in pt.default_main_program().all_parameters()}
    # clobber and restore
    for n, v in want.items():
        scope.update({n: np.zeros_like(v)})
    pt.io.load_persistables(exe, d)
    for n, v in want.items():
        np.testing.assert_allclose(np.asarray(scope.get(n)), v)


def test_trainer_async_checkpoint(tmp_path):
    import paddle_tpu as pt
    from paddle_tpu.models import fit_a_line

    outs = fit_a_line.build(learning_rate=0.05)
    rng = np.random.default_rng(1)
    x = rng.normal(size=(16, 13)).astype(np.float32)
    y = (x @ rng.normal(size=(13, 1))).astype(np.float32)

    tr = pt.trainer.Trainer(outs["avg_cost"], outs["feed"])
    tr.train(pt.reader.batch(lambda: iter([list(zip(x, y))]), 16),
             num_passes=3, checkpoint_dir=str(tmp_path),
             async_checkpoint=True)
    import os
    assert sorted(os.listdir(tmp_path)) == ["pass_0", "pass_1", "pass_2"]
    # every published dir is complete (manifest present, crc valid)
    for p in os.listdir(tmp_path):
        assert os.path.exists(tmp_path / p / "__manifest__.pkl")


def test_auc_evaluator_exact():
    """Rank-sum AUC matches the closed-form on a hand case with ties."""
    from paddle_tpu.evaluator import Auc

    auc = Auc()
    auc.update([0.9, 0.8, 0.8, 0.1], [1, 0, 1, 0])
    # pairs (pos, neg): (0.9 vs 0.8)=1, (0.9 vs 0.1)=1, (0.8 vs 0.8)=0.5,
    # (0.8 vs 0.1)=1 -> 3.5/4
    assert abs(auc.eval() - 3.5 / 4) < 1e-9
    auc.reset()
    auc.update([0.2, 0.7], [0, 1])
    assert auc.eval() == 1.0


def test_detection_map_evaluator():
    from paddle_tpu.evaluator import DetectionMAP

    m = DetectionMAP(overlap_threshold=0.5, ap_version="integral")
    # image 0: one GT of class 1; two detections — the higher-scored one
    # matches (IoU=1), the lower is a false positive
    m.update(detections=[[1, 0.9, 0, 0, 10, 10], [1, 0.6, 50, 50, 60, 60]],
             gt_boxes=[[0, 0, 10, 10]], gt_labels=[1])
    # precision/recall: after det1 tp (P=1, R=1), after det2 fp (P=0.5, R=1)
    # integral AP = 1.0
    assert abs(m.eval() - 1.0) < 1e-9
    # add a second class with a miss: class 2 GT never detected -> AP 0
    m.update(detections=[], gt_boxes=[[0, 0, 5, 5]], gt_labels=[2])
    assert abs(m.eval() - 0.5) < 1e-9
    # 11-point version on the same data
    m11 = DetectionMAP(ap_version="11point")
    m11.update(detections=[[1, 0.9, 0, 0, 10, 10]],
               gt_boxes=[[0, 0, 10, 10]], gt_labels=[1])
    assert abs(m11.eval() - 1.0) < 1e-9


def test_edit_distance_evaluator():
    """In-program accumulation across two batches of decoded vs label
    sequences."""
    from paddle_tpu.evaluator import EditDistance

    hyp = pt.layers.data("hyp", shape=[4], dtype="int64", lod_level=1)
    ref = pt.layers.data("ref", shape=[4], dtype="int64", lod_level=1)
    ev = EditDistance(hyp, ref)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    ev.reset()

    def feed(h, hl, r, rl):
        return {"hyp": np.asarray(h, np.int64),
                "hyp@LENGTH": np.asarray(hl, np.int32),
                "ref": np.asarray(r, np.int64),
                "ref@LENGTH": np.asarray(rl, np.int32)}

    # batch 1: [1,2,3] vs [1,2,3] (d=0); [1,1,0,0] vs [2,2] (d=4... compute)
    exe.run(feed=feed([[1, 2, 3, 0], [1, 1, 0, 0]], [3, 4],
                      [[1, 2, 3, 0], [2, 2, 0, 0]], [3, 2]),
            fetch_list=[ev.metrics[0]])
    # batch 2: [5] vs [5,6] (d=1)
    exe.run(feed=feed([[5, 0, 0, 0]], [1], [[5, 6, 0, 0]], [2]),
            fetch_list=[ev.metrics[0]])
    avg_dist, err_rate = ev.eval()
    # distances: 0, edit([1,1,0,0],[2,2])=4, 1 -> avg 5/3; errors 2/3
    assert abs(avg_dist - 5.0 / 3.0) < 1e-5
    assert abs(err_rate - 2.0 / 3.0) < 1e-9


def test_image_transforms():
    """v2 image.py surface: resize_short, crops, flip, simple_transform."""
    from paddle_tpu import image

    rng = np.random.RandomState(0)
    im = rng.randint(0, 255, (60, 80, 3)).astype(np.uint8)
    r = image.resize_short(im, 30)
    assert r.shape == (30, 40, 3)  # aspect preserved, short edge 30
    c = image.center_crop(r, 24)
    assert c.shape == (24, 24, 3)
    rc = image.random_crop(r, 24, rng=np.random.RandomState(1))
    assert rc.shape == (24, 24, 3)
    f = image.left_right_flip(c)
    assert (f == c[:, ::-1]).all()
    out = image.simple_transform(im, 32, 28, is_train=True,
                                 mean=[1.0, 2.0, 3.0],
                                 rng=np.random.RandomState(2))
    assert out.shape == (3, 28, 28) and out.dtype == np.float32
    # eval path is deterministic
    a = image.simple_transform(im, 32, 28, is_train=False)
    b = image.simple_transform(im, 32, 28, is_train=False)
    assert (a == b).all()
    # bilinear resize interpolates: a 2x2 checker upsampled has midtones
    small = np.array([[0.0, 100.0], [100.0, 0.0]], np.float32)[..., None]
    big = image.resize_short(np.repeat(small, 3, axis=2), 4)
    assert 20 < float(big[1, 1].mean()) < 80


def test_prefetch_to_device_reader():
    """prefetch_to_device yields device-resident feeds ahead of use and
    propagates producer errors."""
    import jax

    from paddle_tpu import reader as rdr

    def batches():
        for i in range(4):
            yield {"x": np.full((2, 3), i, np.float32)}

    got = list(rdr.prefetch_to_device(batches, size=2)())
    assert len(got) == 4
    assert all(isinstance(b["x"], jax.Array) for b in got)
    np.testing.assert_array_equal(np.asarray(got[3]["x"]), 3.0)

    def exploding():
        yield {"x": np.zeros(2, np.float32)}
        raise RuntimeError("producer boom")

    it = rdr.prefetch_to_device(exploding, size=2)()
    next(it)
    try:
        list(it)
        assert False, "expected producer error to propagate"
    except RuntimeError as e:
        assert "boom" in str(e)


def test_prefetch_with_data_feeder_trains():
    import paddle_tpu as pt
    from paddle_tpu.models import fit_a_line

    outs = fit_a_line.build(learning_rate=0.05)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feeder = pt.DataFeeder(outs["feed"])
    rng = np.random.default_rng(0)
    w = rng.normal(size=(13, 1)).astype(np.float32)

    def batches():
        for _ in range(6):
            x = rng.normal(size=(16, 13)).astype(np.float32)
            yield [(x[i], (x[i] @ w)) for i in range(16)]

    losses = []
    for feed in pt.reader.prefetch_to_device(batches, 2, feeder.feed)():
        (c,) = exe.run(feed=feed, fetch_list=[outs["avg_cost"]])
        losses.append(float(np.asarray(c).ravel()[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_trainer_with_prefetch():
    import paddle_tpu as pt
    from paddle_tpu.models import fit_a_line

    outs = fit_a_line.build(learning_rate=0.05)
    rng = np.random.default_rng(3)
    w = rng.normal(size=(13, 1)).astype(np.float32)

    def reader():
        for _ in range(5):
            x = rng.normal(size=(8, 13)).astype(np.float32)
            yield [(x[i], x[i] @ w) for i in range(8)]

    costs = []
    tr = pt.trainer.Trainer(outs["avg_cost"], outs["feed"])
    tr.train(reader, num_passes=2, prefetch=2,
             event_handler=lambda e: costs.append(e.cost)
             if isinstance(e, pt.trainer.EndIteration) else None)
    assert len(costs) == 10
    assert np.isfinite(costs).all()
    assert costs[-1] < costs[0]


def test_auc_evaluator_matches_sklearn_on_random_data():
    """Host-side Auc (rank-sum) and the in-program auc op vs
    sklearn.roc_auc_score on random scores (VERDICT r1 item 8)."""
    sklearn_metrics = pytest.importorskip("sklearn.metrics")
    roc_auc_score = sklearn_metrics.roc_auc_score
    from paddle_tpu.evaluator import Auc

    rng = np.random.RandomState(3)
    for trial in range(5):
        n = rng.randint(20, 200)
        scores = rng.rand(n)
        if trial % 2:  # force ties
            scores = np.round(scores, 1)
        labels = rng.randint(0, 2, n)
        if labels.min() == labels.max():
            labels[0] = 1 - labels[0]
        a = Auc()
        a.update(scores, labels)
        assert abs(a.eval() - roc_auc_score(labels, scores)) < 1e-9, trial

    # the in-program bucketed auc op approximates sklearn within bucket
    # resolution
    from op_test import run_op
    scores = rng.rand(500).astype(np.float32)
    labels = rng.randint(0, 2, (500, 1)).astype(np.int64)
    got = run_op("auc", {"Out": scores.reshape(-1, 1), "Label": labels},
                 {"num_thresholds": 1000})
    expected = roc_auc_score(labels.ravel(), scores)
    assert abs(float(got["AUC"][0]) - expected) < 5e-3


def test_detection_map_evaluate_difficult():
    """Difficult-GT semantics (DetectionMAPEvaluator.cpp:106-116,184-198):
    with evaluate_difficult=False a difficult GT neither counts as a
    positive nor marks its matched detection tp/fp; with True it behaves
    like a normal GT."""
    from paddle_tpu.evaluator import DetectionMAP

    def build(evaluate_difficult):
        m = DetectionMAP(overlap_threshold=0.5, ap_version="integral",
                         evaluate_difficult=evaluate_difficult)
        # image: GT A (normal) + GT B (difficult); det1 matches B (skip),
        # det2 matches A (tp), det3 matches nothing (fp)
        m.update(
            detections=[[1, 0.9, 100, 100, 110, 110],   # on B
                        [1, 0.8, 0, 0, 10, 10],          # on A
                        [1, 0.7, 300, 300, 310, 310]],   # nothing
            gt_boxes=[[0, 0, 10, 10], [100, 100, 110, 110]],
            gt_labels=[1, 1],
            gt_difficult=[False, True],
        )
        return m

    # n_gt=1 (B excluded); rank: det1 skipped, det2 tp (P=1,R=1), det3 fp.
    # integral AP = 1.0
    assert abs(build(False).eval() - 1.0) < 1e-9
    # n_gt=2; det1 tp (P=1, R=0.5), det2 tp (P=1, R=1), det3 fp -> AP 1.0
    assert abs(build(True).eval() - 1.0) < 1e-9
    # asymmetric check: difficult-only GT class disappears entirely
    m = DetectionMAP(evaluate_difficult=False)
    m.update(detections=[[7, 0.9, 0, 0, 10, 10]],
             gt_boxes=[[0, 0, 10, 10]], gt_labels=[7],
             gt_difficult=[True])
    assert m.eval() == 0.0  # no classes with positives -> reference mAP 0
