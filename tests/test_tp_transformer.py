"""Tensor-parallel transformer recipe on the 8-device CPU mesh: tp=2
training == unsharded training, numerically — head-sharded flash
attention (shard_map over heads), row/column-sharded projections, and
the vocab-sharded fused CE head's logsumexp merge."""

import numpy as np
import jax
import pytest

import paddle_tpu as pt
from paddle_tpu.models import transformer
from paddle_tpu.parallel import api as papi
from paddle_tpu.parallel.mesh import make_mesh


VOCAB, LAYERS, HEADS, DMODEL, SEQ = 64, 2, 2, 32, 16


def _train(mesh, tp_shard, steps=4, seed=3, n_head=HEADS):
    # Sharding-invariant RNG for BOTH spellings of the comparison: the
    # legacy threefry lowering derives different values for SHARDED
    # random outputs, so the tp row-sharded weights (att_out.w, ffn2.w
    # under tp_rules' P('tp', None)) would be *initialized* differently
    # than the unsharded reference — a 1e-2-level loss offset at step 1
    # that lr=0.1 then amplifies (the long-standing tier-1 failure this
    # pins down).  The partitionable lowering derives every element from
    # its global counter regardless of layout, so sharded init ==
    # unsharded init and the test measures what it claims: tp TRAINING
    # numerics, not PRNG lowering artifacts.  Scoped here (not
    # process-wide) for the same reason Executor._rng_invariant_ctx is
    # scoped to fsdp meshes — other suites pin legacy-stream values.
    try:
        from jax._src.config import threefry_partitionable
    except Exception:  # newer jax: partitionable is the default
        import contextlib

        threefry_partitionable = lambda _on: contextlib.nullcontext()  # noqa: E731
    with threefry_partitionable(True):
        return _train_inner(mesh, tp_shard, steps, seed, n_head)


def _train_inner(mesh, tp_shard, steps, seed, n_head):
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 7
    scope = pt.Scope()
    pt.core.scope._scope_stack.append(scope)
    try:
        with pt.program_guard(main, startup):
            outs = transformer.build(
                vocab_size=VOCAB, n_layer=LAYERS, n_head=n_head,
                d_model=DMODEL, max_len=SEQ, dropout_rate=0.0,
                dtype="float32", fused_head=True, learning_rate=0.1)
        if mesh is not None:
            papi.data_parallel(main, "dp", programs=(startup,))
            if tp_shard:
                for prog in (main, startup):
                    papi.shard_parameters_by_rule(
                        prog, transformer.tp_rules())
        exe = pt.Executor(mesh=mesh, donate_state=False)
        exe.run(startup, scope=scope)
        rng = np.random.default_rng(seed)
        toks = rng.integers(0, VOCAB, (4, SEQ)).astype(np.int64)
        lbls = np.roll(toks, -1, axis=1)
        lbls[:, -1] = -1
        losses = []
        for _ in range(steps):
            (c,) = exe.run(main, feed={"tokens": toks, "labels": lbls},
                           fetch_list=[outs["avg_cost"]], scope=scope)
            losses.append(float(np.asarray(c)))
        return losses
    finally:
        pt.core.scope._scope_stack.pop()


def test_tp2_matches_unsharded():
    """dp=2 x tp=2 sharded training tracks the single-device run step
    for step (same seed, same data, f32)."""
    ref = _train(None, False)
    mesh = make_mesh({"dp": 2, "tp": 2}, devices=jax.devices()[:4])
    got = _train(mesh, True)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)
    assert got[-1] < got[0]  # it actually learns


def test_tp4_pure_tensor_parallel():
    """A pure tp mesh (dp=1): n_head=4 so tp=4 divides the heads and the
    shard_map-over-heads attention path actually engages (2 heads would
    silently fall back to the GSPMD path)."""
    ref = _train(None, False, n_head=4)
    mesh = make_mesh({"dp": 1, "tp": 4}, devices=jax.devices()[:4])
    got = _train(mesh, True, n_head=4)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_tp_rules_cover_the_sharded_params():
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        transformer.build(vocab_size=VOCAB, n_layer=1, n_head=HEADS,
                          d_model=DMODEL, max_len=SEQ, dropout_rate=0.0,
                          dtype="float32", fused_head=True)
    papi.shard_parameters_by_rule(main, transformer.tp_rules())
    specs = {v.name: getattr(v, "partition_spec", None)
             for v in main.global_block().vars.values() if v.persistable}
    sharded = {n for n, s in specs.items() if s is not None and any(s)}
    assert "block0_att_q.w" in sharded
    assert "block0_ffn2.w" in sharded
    assert "lm_head.w" in sharded
    assert "tok_emb.w" not in sharded  # embeddings replicate
