"""Elastic resilience engine (paddle_tpu/resilience/, ISSUE 8) —
retry/backoff, fault injection, watchdog supervision, the resumable
reader, full-state checkpoint discovery, the AsyncCheckpointer's
crashed-publish recovery branches, and trainer kill-and-resume
bit-exactness (in-process; the subprocess SIGKILL variant is
``python -m paddle_tpu --resilience-selftest``)."""

import os
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.observability import metrics as _obs
from paddle_tpu.resilience import checkpoint as rckpt
from paddle_tpu.resilience import faults as rfaults
from paddle_tpu.resilience import retry as rretry
from paddle_tpu.resilience.watchdog import Watchdog


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv(rfaults.ENV_VAR, raising=False)
    rfaults.reset()
    yield
    rfaults.reset()


# ------------------------------------------------------------------- retry
def test_retry_absorbs_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    before = _obs.get_registry().value("resilience.retries")
    assert rretry.retry_call(flaky, retries=4, sleep=lambda d: None) == "ok"
    assert len(calls) == 3
    assert _obs.get_registry().value("resilience.retries") == before + 2


def test_retry_gives_up_and_chains_last_error():
    def always():
        raise OSError("hard down")

    with pytest.raises(rretry.RetryError) as ei:
        rretry.retry_call(always, retries=2, sleep=lambda d: None)
    assert ei.value.attempts == 3
    assert isinstance(ei.value.last, OSError)
    # non-retryable exceptions propagate untouched, immediately
    with pytest.raises(ValueError):
        rretry.retry_call(lambda: (_ for _ in ()).throw(ValueError("x")),
                          retries=5, sleep=lambda d: None)


def test_backoff_schedule_jitter_bounds():
    bo = rretry.Backoff(base=0.1, factor=2.0, max_delay=2.0, jitter=0.0)
    assert [bo.delay(i) for i in range(6)] == [0.1, 0.2, 0.4, 0.8, 1.6, 2.0]
    jittered = rretry.Backoff(base=0.1, jitter=0.5)
    for i in range(8):
        d = jittered.delay(i)
        nominal = min(0.1 * 2.0 ** i, 2.0)
        assert 0.5 * nominal <= d <= 1.5 * nominal
    # bounded iteration
    assert len(list(rretry.Backoff(attempts=3))) == 3


# ------------------------------------------------------------------ faults
def test_fault_spec_parsing(monkeypatch):
    assert rfaults.spec() is None
    monkeypatch.setenv(rfaults.ENV_VAR, "io_error:3")
    sp = rfaults.spec()
    assert (sp.kind, sp.n, sp.point) == ("io_error", 3, "ckpt.write")
    monkeypatch.setenv(rfaults.ENV_VAR, "nope:1")
    with pytest.raises(ValueError):
        rfaults.spec()
    monkeypatch.setenv(rfaults.ENV_VAR, "sigkill:0")
    with pytest.raises(ValueError):
        rfaults.spec()


def test_fault_fires_only_on_nth_arrival(monkeypatch):
    monkeypatch.setenv(rfaults.ENV_VAR, "io_error:2")
    assert rfaults.maybe_fault("ckpt.write") is None  # arrival 1
    with pytest.raises(OSError):
        rfaults.maybe_fault("ckpt.write")             # arrival 2: fires
    assert rfaults.maybe_fault("ckpt.write") is None  # transient: once
    # other points never trip someone else's fault
    assert rfaults.maybe_fault("trainer.step") is None


def test_nan_and_reader_faults(monkeypatch):
    monkeypatch.setenv(rfaults.ENV_VAR, "nan_grad:1")
    assert rfaults.maybe_fault("trainer.step") == "nan"
    rfaults.reset()
    monkeypatch.setenv(rfaults.ENV_VAR, "reader_err:1")
    with pytest.raises(RuntimeError):
        rfaults.maybe_fault("reader.next")


def test_injected_io_error_absorbed_by_checkpoint_retry(tmp_path,
                                                        monkeypatch):
    """The ckpt.write fault point lives INSIDE the retried call: an
    injected transient OSError costs one retry, not the checkpoint."""
    from paddle_tpu.models import fit_a_line

    outs = fit_a_line.build(learning_rate=0.05)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    monkeypatch.setenv(rfaults.ENV_VAR, "io_error:1")
    before = _obs.get_registry().value("resilience.retries")
    ckpt = pt.io.AsyncCheckpointer()
    d = str(tmp_path / "ck")
    ckpt.save(d)
    ckpt.close()  # wait() inside raises if the write ultimately failed
    assert os.path.exists(os.path.join(d, "__manifest__.pkl"))
    assert _obs.get_registry().value("resilience.retries") >= before + 1


def test_injected_reader_fault_surfaces_from_train(tmp_path, monkeypatch):
    """PADDLE_TPU_FAULT=reader_err:N propagates out of Trainer.train as
    the input-pipeline exception it simulates."""
    losses = _small_model_and_losses(tmp_path, monkeypatch,
                                     fault="reader_err:3")
    assert losses["error"] is not None
    assert "injected reader exception" in str(losses["error"])
    assert len(losses["costs"]) == 2  # two steps before the fault


def test_injected_nan_poisons_step_cost(tmp_path, monkeypatch):
    losses = _small_model_and_losses(tmp_path, monkeypatch,
                                     fault="nan_grad:2")
    assert losses["error"] is None
    costs = losses["costs"]
    assert np.isnan(costs[1]) and not np.isnan(costs[0])


# ---------------------------------------------------------------- watchdog
def test_watchdog_trips_and_rearms():
    trips = []
    with Watchdog(0.05, label="t", on_trip=trips.append,
                  interval=0.01) as wd:
        deadline = time.monotonic() + 5.0
        while wd.trips < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert wd.trips == 1, "watchdog did not trip on a stalled loop"
        assert trips and trips[0] > 0.05
        wd.beat()  # recovery re-arms the edge
        deadline = time.monotonic() + 5.0
        while wd.trips < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert wd.trips == 2, "watchdog did not re-arm after a beat"
    reg = _obs.get_registry()
    assert reg.value("resilience.watchdog_trips", label="t") >= 2
    assert reg.value("resilience.watchdog_stalled", label="t") == 0.0


def test_watchdog_quiet_while_beating():
    with Watchdog(0.2, label="quiet", interval=0.02) as wd:
        for _ in range(10):
            time.sleep(0.02)
            wd.beat()
        assert wd.trips == 0


# --------------------------------------------------------- resumable reader
def test_resumable_reader_counts_and_fast_forwards():
    r = pt.reader.resumable(lambda: iter(range(10)))
    assert list(r()) == list(range(10))
    assert r.items == 10 and r.epochs == 1
    r.set_state({"items": 4})
    assert list(r()) == list(range(4, 10))
    assert r.items == 10  # position includes the fast-forwarded prefix
    # skip past the end is safe (empty remainder, no StopIteration leak)
    r.set_state({"items": 99})
    assert list(r()) == []


def test_resumable_reader_delegates_underlying_state():
    class FileLike:
        """Reader factory with its own O(1) cursor snapshot."""

        def __init__(self):
            self.pos = 0

        def state(self):
            return {"pos": self.pos}

        def set_state(self, st):
            self.pos = st["pos"]

        def __call__(self):
            for i in range(self.pos, 6):
                self.pos = i + 1
                yield i

    src = FileLike()
    r = pt.reader.resumable(src)
    it = iter(r())
    assert [next(it) for _ in range(2)] == [0, 1]
    st = r.state()
    assert st["items"] == 2 and st["underlying"] == {"pos": 2}
    src2 = FileLike()
    r2 = pt.reader.resumable(src2)
    r2.set_state(st)
    assert list(r2()) == [2, 3, 4, 5]  # no re-draw of the prefix
    assert r2.items == 6


# ------------------------------------------------- checkpoint manifest/dirs
def test_train_state_schema_roundtrip(tmp_path):
    d = tmp_path / "ck"
    d.mkdir()
    rckpt.save_train_state(str(d), {
        "global_step": 7, "pass_id": 1, "step_in_pass": 3,
        "rng_key": np.array([1, 2], np.uint32),
        "reader_state": {"items": 3},
    })
    st = rckpt.load_train_state(str(d))
    assert st["schema_version"] == rckpt.SCHEMA_VERSION
    assert st["global_step"] == 7
    np.testing.assert_array_equal(st["rng_key"], [1, 2])
    # a state from the FUTURE refuses to load
    rckpt.save_train_state(str(d), {"schema_version": 99})
    with pytest.raises(ValueError):
        rckpt.load_train_state(str(d))


def test_latest_checkpoint_skips_torn_dirs(tmp_path):
    """Discovery returns the newest LOADABLE step: torn dirs (missing
    markers / manifest) and bare .tmp leftovers are skipped."""
    import pickle

    root = tmp_path / "ckpt"

    def plant(step, complete=True, state=True):
        d = root / f"step_{step}"
        d.mkdir(parents=True)
        with open(d / "__manifest__.pkl", "wb") as f:
            pickle.dump({"__nprocs__": 1}, f)
        if complete:
            (d / "__done0__").write_text("ok")
        if state:
            rckpt.save_train_state(str(d), {"global_step": step})
        return d

    assert rckpt.latest_checkpoint(str(root)) is None
    plant(3)
    plant(6)
    plant(9, complete=False)          # writer killed before the marker
    (root / "step_12.tmp").mkdir()    # crashed mid-write leftover
    got = rckpt.latest_checkpoint(str(root))
    assert got == str(root / "step_6")
    # without a train-state sidecar the dir is complete but not resumable
    plant(15, state=False)
    assert rckpt.latest_checkpoint(str(root)) == str(root / "step_6")
    assert rckpt.latest_checkpoint(
        str(root), require_state=False) == str(root / "step_15")


def test_latest_checkpoint_honors_old_fallback(tmp_path):
    """A crash between the two publish renames leaves only step_N.old:
    discovery must still surface step_N (load_vars falls back)."""
    import pickle

    root = tmp_path / "ckpt"
    d = root / "step_5.old"
    d.mkdir(parents=True)
    with open(d / "__manifest__.pkl", "wb") as f:
        pickle.dump({"__nprocs__": 1}, f)
    (d / "__done0__").write_text("ok")
    rckpt.save_train_state(str(d), {"global_step": 5})
    assert rckpt.latest_checkpoint(str(root)) == str(root / "step_5")
    st = rckpt.load_train_state(str(root / "step_5"))
    assert st["global_step"] == 5


def test_prune_checkpoints_retention(tmp_path):
    root = tmp_path / "ckpt"
    for n in (3, 6, 9, 12):
        (root / f"step_{n}").mkdir(parents=True)
    (root / "step_3.tmp").mkdir()
    pruned = rckpt.prune_checkpoints(str(root), keep=2)
    left = sorted(os.listdir(root))
    assert left == ["step_12", "step_9"], left
    assert len(pruned) == 3  # step_3, step_3.tmp, step_6
    with pytest.raises(ValueError):
        rckpt.prune_checkpoints(str(root), keep=1)


# ------------------------------------- AsyncCheckpointer recovery branches
def _saved_params(program=None):
    program = program or pt.default_main_program()
    scope = pt.core.scope.global_scope()
    return {p.name: np.asarray(scope.get(p.name))
            for p in program.all_parameters()}


def _build_fit_a_line():
    from paddle_tpu.models import fit_a_line

    outs = fit_a_line.build(learning_rate=0.05)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    return exe, outs


def test_old_only_restore_branch(tmp_path):
    """ISSUE 8 satellite: load from a dir that exists ONLY as .old (the
    crash-between-renames window) — load_vars' fallback branch."""
    import shutil

    exe, _ = _build_fit_a_line()
    ckpt = pt.io.AsyncCheckpointer()
    d = str(tmp_path / "latest")
    ckpt.save(d, extra_state={"global_step": 1})
    ckpt.close()
    want = _saved_params()
    # simulate the torn window: published dir moved to .old, nothing at d
    shutil.move(d, d + ".old")
    scope = pt.core.scope.global_scope()
    for n, v in want.items():
        scope.update({n: np.zeros_like(v)})
    pt.io.load_persistables(exe, d)
    for n, v in want.items():
        np.testing.assert_array_equal(np.asarray(scope.get(n)), v)
    assert rckpt.load_train_state(d)["global_step"] == 1


def test_leftover_tmp_and_old_restored_before_write(tmp_path):
    """A crashed prior run's leftovers (.tmp garbage, .old-only good
    copy) are cleaned/recovered by the next save (io.py _write)."""
    exe, _ = _build_fit_a_line()
    d = str(tmp_path / "latest")
    # plant a stale .tmp (crashed mid-write last run) and an .old-only
    # good checkpoint (crashed mid-publish before that)
    os.makedirs(os.path.join(d + ".tmp", "junk"))
    ckpt = pt.io.AsyncCheckpointer()
    ckpt.save(d + ".old")  # a real snapshot parked at .old
    ckpt.wait()
    ckpt.save(d)
    ckpt.close()
    assert os.path.exists(os.path.join(d, "__manifest__.pkl"))
    assert not os.path.exists(d + ".tmp")
    assert not os.path.exists(d + ".old")
    pt.io.load_persistables(exe, d)  # loads clean


def test_raise_pending_surfaces_worker_errors(tmp_path, monkeypatch):
    """ISSUE 8 satellite: a failed background write surfaces on the NEXT
    save()/wait() — never silently."""
    _build_fit_a_line()
    ckpt = pt.io.AsyncCheckpointer()
    monkeypatch.setattr(
        pt.io.AsyncCheckpointer, "_write",
        staticmethod(lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("disk on fire"))))
    ckpt.save(str(tmp_path / "a"))
    ckpt._q.join()  # worker consumed the item and recorded its error
    # ...which the next save() surfaces synchronously
    with pytest.raises(RuntimeError, match="disk on fire"):
        ckpt.save(str(tmp_path / "b"))
    # the error swap is atomic: once raised it is consumed, and wait()
    # after the (never-queued) second save is clean
    ckpt.wait()
    ckpt.close()


def test_close_raises_pending_error(tmp_path, monkeypatch):
    _build_fit_a_line()
    ckpt = pt.io.AsyncCheckpointer()
    monkeypatch.setattr(
        pt.io.AsyncCheckpointer, "_write",
        staticmethod(lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("worker died"))))
    ckpt.save(str(tmp_path / "a"))
    with pytest.raises(RuntimeError, match="worker died"):
        ckpt.close()
    # the worker thread is shut down even though close() raised
    assert not ckpt._thread.is_alive()


def test_multiproc_snapshot_carries_sidecar_proc0_only(tmp_path,
                                                       monkeypatch):
    """The multi-process write path (tests/multihost_runner.py
    ckpt_mid_kill): process 0 writes the train-state sidecar + manifest,
    every process writes its own completion marker, and the checkpoint
    only counts as complete once ALL markers exist."""
    import paddle_tpu.io as io

    snap = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}
    d = str(tmp_path / "ck")
    state = {"global_step": 2, "rng_key": np.array([1, 2], np.uint32)}
    monkeypatch.setattr(io, "_multiproc_ids", lambda: (0, 2))
    io._write_snapshot(d, snap, extra_state=state)
    assert os.path.exists(os.path.join(d, rckpt.STATE_FILE))
    assert not rckpt.checkpoint_complete(d), \
        "complete before rank 1's marker"
    monkeypatch.setattr(io, "_multiproc_ids", lambda: (1, 2))
    io._write_snapshot(d, {}, extra_state=state)  # rank 1: markers only
    assert rckpt.checkpoint_complete(d)
    assert rckpt.load_train_state(d)["global_step"] == 2
    # write-once: re-saving into the published dir raises on both ranks
    with pytest.raises(ValueError, match="write-once"):
        io._write_snapshot(d, {}, extra_state=state)
    monkeypatch.setattr(io, "_multiproc_ids", lambda: (0, 2))
    with pytest.raises(ValueError, match="write-once"):
        io._write_snapshot(d, snap, extra_state=state)


# --------------------------------------------- trainer full-state resume
def _small_model_and_losses(tmp_path, monkeypatch, fault=None,
                            kill_after=None, resume=False,
                            steps_per_call=1, async_ckpt=True):
    """One Trainer.train run of a dropout model in a fresh scope: returns
    {"costs": [...], "error": exc_or_None, "trainer": tr}."""
    if fault:
        monkeypatch.setenv(rfaults.ENV_VAR, fault)
        rfaults.reset()
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 11
    with pt.program_guard(main, startup):
        x = pt.layers.data("x", shape=[5], dtype="float32")
        y = pt.layers.data("y", shape=[1], dtype="float32")
        h = pt.layers.fc(x, size=8, act="relu")
        h = pt.layers.dropout(h, 0.3)
        pred = pt.layers.fc(h, size=1)
        cost = pt.layers.mean(pt.layers.square_error_cost(pred, y))
        pt.optimizer.Momentum(learning_rate=0.05,
                              momentum=0.9).minimize(cost)

    def reader():
        rng = np.random.default_rng(5)
        X = rng.normal(size=(32, 5)).astype(np.float32)
        Y = X.sum(axis=1, keepdims=True).astype(np.float32)
        for i in range(4):
            yield list(zip(X[i * 8:(i + 1) * 8], Y[i * 8:(i + 1) * 8]))

    costs = []

    class Stop(Exception):
        pass

    def handler(ev):
        if type(ev).__name__ == "EndIteration":
            costs.append(ev.cost)
            if kill_after is not None and len(costs) >= kill_after:
                raise Stop

    scope = pt.Scope()
    pt.core.scope._scope_stack.append(scope)
    error = tr = None
    try:
        with pt.program_guard(main, startup):
            tr = pt.trainer.Trainer(cost, [x, y], main_program=main,
                                    startup_program=startup)
            try:
                tr.train(reader, num_passes=2, event_handler=handler,
                         checkpoint_dir=str(tmp_path / "ck"),
                         checkpoint_every_n_steps=3,
                         async_checkpoint=async_ckpt, resume=resume,
                         steps_per_call=steps_per_call)
            except Stop:
                pass
            except Exception as e:  # noqa: BLE001 — inspected by tests
                error = e
    finally:
        pt.core.scope._scope_stack.pop()
        if fault:
            monkeypatch.delenv(rfaults.ENV_VAR, raising=False)
            rfaults.reset()
    return {"costs": costs, "error": error, "trainer": tr}


def test_trainer_kill_and_resume_bit_exact(tmp_path, monkeypatch):
    """Full-state step checkpoints + resume reproduce the uninterrupted
    trajectory bit-for-bit: params, optimizer moments, RNG key (dropout
    masks!) and reader cursor all restored.  The SIGKILL subprocess
    variant on the 8-device mesh is the --resilience-selftest gate."""
    ref = _small_model_and_losses(tmp_path / "ref", monkeypatch)
    assert len(ref["costs"]) == 8 and ref["error"] is None
    part = _small_model_and_losses(tmp_path / "run", monkeypatch,
                                   kill_after=5)
    assert part["costs"] == ref["costs"][:5]
    res = _small_model_and_losses(tmp_path / "run", monkeypatch,
                                  resume=True)
    st = res["trainer"].last_resume
    assert st is not None and st["global_step"] == 3  # ckpt every 3 steps
    assert st["pass_id"] == 0 and st["step_in_pass"] == 3
    assert res["costs"] == ref["costs"][3:], \
        "resumed trajectory diverged from the uninterrupted run"
    assert _obs.get_registry().value("executor.resume_count") >= 1


def test_trainer_resume_cold_start_without_checkpoints(tmp_path,
                                                       monkeypatch):
    """resume=True over an empty checkpoint dir is a cold start, not an
    error (the first launch of an elastic job)."""
    out = _small_model_and_losses(tmp_path, monkeypatch, resume=True)
    assert out["error"] is None
    assert len(out["costs"]) == 8
    assert out["trainer"].last_resume is None


def test_trainer_fused_path_checkpoints_and_resumes(tmp_path,
                                                    monkeypatch):
    """checkpoint_every_n_steps also fires from the fused
    (steps_per_call>1) loop — at group boundaries — and the fused resume
    fast-forwards the reader correctly.  Fused grouping changes the
    device-call shape, so trajectories are compared fused-vs-fused."""
    ref = _small_model_and_losses(tmp_path / "ref", monkeypatch,
                                  steps_per_call=2)
    assert len(ref["costs"]) == 8 and ref["error"] is None
    part = _small_model_and_losses(tmp_path / "run", monkeypatch,
                                   kill_after=6, steps_per_call=2)
    ck = tmp_path / "run" / "ck"
    assert rckpt.latest_checkpoint(str(ck)) is not None
    res = _small_model_and_losses(tmp_path / "run", monkeypatch,
                                  resume=True, steps_per_call=2)
    st = res["trainer"].last_resume
    assert st is not None and st["global_step"] >= 3
    assert res["costs"] == ref["costs"][st["global_step"]:]


def test_injected_nan_poisons_fused_step_cost(tmp_path, monkeypatch):
    """nan_grad fires on the fused (steps_per_call>1) loop too — the
    poisoned batch inside the group, not the whole group."""
    out = _small_model_and_losses(tmp_path, monkeypatch,
                                  fault="nan_grad:3", steps_per_call=2)
    assert out["error"] is None
    costs = out["costs"]
    assert np.isnan(costs[2])
    assert not any(np.isnan(c) for c in costs[:2] + costs[3:])


def test_keep_checkpoints_validated_at_train_entry(tmp_path):
    """keep_checkpoints < 2 fails at train() entry, not 100 steps later
    when the first prune runs."""
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = pt.layers.data("x", shape=[2], dtype="float32")
        y = pt.layers.data("y", shape=[1], dtype="float32")
        cost = pt.layers.mean(pt.layers.square_error_cost(
            pt.layers.fc(x, size=1), y))
        pt.optimizer.SGD(learning_rate=0.1).minimize(cost)
        tr = pt.trainer.Trainer(cost, [x, y], main_program=main,
                                startup_program=startup)
        with pytest.raises(ValueError, match="keep_checkpoints"):
            tr.train(lambda: iter([]), checkpoint_dir=str(tmp_path),
                     checkpoint_every_n_steps=3, keep_checkpoints=1)


def test_trainer_checkpoint_delegates_reader_state(tmp_path, monkeypatch):
    """A resumable reader over a factory with its OWN state()/set_state()
    cursor: the step checkpoint snapshots the underlying cursor and the
    resume restores it WITHOUT re-drawing the consumed prefix — the
    non-replayable-stream case an item-count fast-forward cannot
    handle."""

    class Stream:
        """One-way batch stream: re-drawing consumed items is an error
        unless the cursor was restored through state()."""

        def __init__(self, draws):
            self.pos = 0
            self.draws = draws  # shared log of every batch handed out

        def state(self):
            return {"pos": self.pos}

        def set_state(self, st):
            self.pos = st["pos"]

        def __call__(self):
            rng = np.random.default_rng(5)
            X = rng.normal(size=(32, 5)).astype(np.float32)
            Y = X.sum(axis=1, keepdims=True).astype(np.float32)
            for i in range(self.pos, 4):
                self.pos = i + 1
                self.draws.append(i)
                yield list(zip(X[i * 8:(i + 1) * 8],
                               Y[i * 8:(i + 1) * 8]))

    def build_and_train(reader, resume):
        pt.core.unique_name.reset()
        main, startup = pt.Program(), pt.Program()
        main.random_seed = 11
        with pt.program_guard(main, startup):
            x = pt.layers.data("x", shape=[5], dtype="float32")
            y = pt.layers.data("y", shape=[1], dtype="float32")
            cost = pt.layers.mean(pt.layers.square_error_cost(
                pt.layers.fc(x, size=4), y))
            pt.optimizer.SGD(learning_rate=0.1).minimize(cost)
        costs = []

        class Stop(Exception):
            pass

        def handler(ev):
            if type(ev).__name__ == "EndIteration":
                costs.append(ev.cost)
                if not resume and len(costs) >= 3:
                    raise Stop

        scope = pt.Scope()
        pt.core.scope._scope_stack.append(scope)
        try:
            with pt.program_guard(main, startup):
                tr = pt.trainer.Trainer(cost, [x, y], main_program=main,
                                        startup_program=startup)
                try:
                    tr.train(reader, num_passes=1, event_handler=handler,
                             checkpoint_dir=str(tmp_path / "ck"),
                             checkpoint_every_n_steps=2,
                             async_checkpoint=False, resume=resume)
                except Stop:
                    pass
            return costs, tr
        finally:
            pt.core.scope._scope_stack.pop()

    draws = []
    r = pt.reader.resumable(Stream(draws))
    build_and_train(r, resume=False)  # killed after step 3, ckpt at 2
    st = rckpt.load_train_state(
        rckpt.latest_checkpoint(str(tmp_path / "ck")))
    assert st["reader_state"]["underlying"] == {"pos": 2}
    draws2 = []
    r2 = pt.reader.resumable(Stream(draws2))
    costs, tr = build_and_train(r2, resume=True)
    assert tr.last_resume["global_step"] == 2
    assert len(costs) == 2  # batches 2, 3
    assert draws2 == [2, 3], \
        f"resume re-drew consumed items: {draws2}"


def test_step_checkpoint_retention_and_telemetry(tmp_path, monkeypatch):
    """Step checkpoints prune to keep_checkpoints and record
    checkpoint.save_ms / checkpoint.bytes telemetry."""
    _small_model_and_losses(tmp_path, monkeypatch)
    ck = tmp_path / "ck"
    steps = sorted(n for n in os.listdir(ck) if n.startswith("step_"))
    assert steps == ["step_3", "step_6"], steps  # 8 steps, every 3, keep 3
    reg = _obs.get_registry()
    assert reg.value("checkpoint.saves") >= 2
    assert reg.value("checkpoint.last_bytes") > 0
    assert reg.value("checkpoint.last_save_ms") > 0
    h = reg.get("checkpoint.save_ms")
    assert h is not None and h.count >= 2


def test_reporter_jsonl_carries_resilience_fields(tmp_path, monkeypatch):
    """ISSUE 8 satellite: the trainer JSONL step records carry
    checkpoint_save_ms / checkpoint_bytes / resume_count so bench
    history can track checkpoint overhead."""
    import json

    from paddle_tpu.observability.reporter import MetricsReporter

    path = tmp_path / "run.jsonl"
    rep = MetricsReporter(log_every_n=0, jsonl_path=str(path))
    pt.core.unique_name.reset()
    from paddle_tpu.models import fit_a_line

    scope = pt.Scope()
    pt.core.scope._scope_stack.append(scope)
    try:
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            outs = fit_a_line.build(learning_rate=0.05)
            rng = np.random.default_rng(0)
            X = rng.normal(size=(16, 13)).astype(np.float32)
            Y = X.sum(axis=1, keepdims=True).astype(np.float32)
            tr = pt.trainer.Trainer(outs["avg_cost"], outs["feed"],
                                    main_program=main,
                                    startup_program=startup)
            # sync saves, so the save-at-step-2 telemetry is already in
            # the registry when step 3's JSONL record is written
            tr.train(lambda: iter([list(zip(X, Y))] * 4), num_passes=1,
                     event_handler=rep,
                     checkpoint_dir=str(tmp_path / "ck"),
                     checkpoint_every_n_steps=2, async_checkpoint=False)
        rep.close()
    finally:
        pt.core.scope._scope_stack.pop()
    steps = [json.loads(l) for l in open(path)
             if json.loads(l).get("event") == "step"]
    assert steps, "no step records"
    last = steps[-1]
    for k in ("checkpoint_save_ms", "checkpoint_bytes",
              "checkpoint_saves", "resume_count"):
        assert k in last, f"missing {k}: {sorted(last)}"
    assert last["checkpoint_saves"] >= 1
    assert last["checkpoint_bytes"] > 0
