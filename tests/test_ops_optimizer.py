"""Direct tests for every optimizer update op against a numpy port of the
reference kernel (sgd_op.h, momentum_op.h, adam_op.h, adamax_op.h,
adagrad_op.h, adadelta_op.h, decayed_adagrad_op.h, rmsprop_op.h,
ftrl_op.h, proximal_gd_op.h, proximal_adagrad_op.h), chained over several
steps so accumulator conventions (e.g. the Beta1Pow running product) are
pinned, not just a single application."""

import numpy as np
import pytest

from op_test import run_op

rng = np.random.RandomState(5)


def _p():
    return rng.randn(4, 3).astype(np.float32)


def _steps(n=3):
    return [rng.randn(4, 3).astype(np.float32) * 0.5 for _ in range(n)]


LR = np.array([0.1], np.float32)


def test_sgd():
    p = _p()
    for g in _steps():
        got = run_op("sgd", {"Param": p, "Grad": g, "LearningRate": LR})
        p = p - 0.1 * g
        np.testing.assert_allclose(got["ParamOut"], p, rtol=1e-5, atol=1e-6)
        p = got["ParamOut"]


@pytest.mark.parametrize("nesterov", [False, True])
def test_momentum(nesterov):
    p, v = _p(), np.zeros((4, 3), np.float32)
    mu = 0.9
    for g in _steps():
        got = run_op("momentum",
                     {"Param": p, "Grad": g, "Velocity": v,
                      "LearningRate": LR},
                     {"mu": mu, "use_nesterov": nesterov})
        v = mu * v + g
        p = p - (g + mu * v) * 0.1 if nesterov else p - 0.1 * v
        np.testing.assert_allclose(got["ParamOut"], p, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(got["VelocityOut"], v, rtol=1e-5,
                                   atol=1e-6)
        p, v = got["ParamOut"], got["VelocityOut"]


def test_adagrad():
    p, m = _p(), np.zeros((4, 3), np.float32)
    eps = 1e-6
    for g in _steps():
        got = run_op("adagrad", {"Param": p, "Grad": g, "Moment": m,
                                 "LearningRate": LR}, {"epsilon": eps})
        m = m + g * g
        p = p - 0.1 * g / (np.sqrt(m) + eps)
        np.testing.assert_allclose(got["ParamOut"], p, rtol=1e-5, atol=1e-6)
        p, m = got["ParamOut"], got["MomentOut"]


def test_adam_matches_textbook_bias_correction():
    beta1, beta2, eps = 0.9, 0.999, 1e-8
    p = _p()
    m1 = np.zeros((4, 3), np.float32)
    m2 = np.zeros((4, 3), np.float32)
    b1p = np.array([1.0], np.float32)  # beta^(t-1) convention, t starts 1
    b2p = np.array([1.0], np.float32)
    for t, g in enumerate(_steps(4), start=1):
        got = run_op("adam", {
            "Param": p, "Grad": g, "Moment1": m1, "Moment2": m2,
            "LearningRate": LR, "Beta1Pow": b1p, "Beta2Pow": b2p,
        }, {"beta1": beta1, "beta2": beta2, "epsilon": eps})
        m1 = beta1 * m1 + (1 - beta1) * g
        m2 = beta2 * m2 + (1 - beta2) * g * g
        lr_t = 0.1 * np.sqrt(1 - beta2 ** t) / (1 - beta1 ** t)
        p = p - lr_t * m1 / (np.sqrt(m2) + eps)
        np.testing.assert_allclose(got["ParamOut"], p, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(got["Beta1PowOut"],
                                   [beta1 ** t], rtol=1e-5)
        p, m1, m2 = got["ParamOut"], got["Moment1Out"], got["Moment2Out"]
        b1p, b2p = got["Beta1PowOut"], got["Beta2PowOut"]


def test_adamax():
    beta1, beta2, eps = 0.9, 0.999, 1e-8
    p = _p()
    m = np.zeros((4, 3), np.float32)
    u = np.zeros((4, 3), np.float32)
    b1p = np.array([1.0], np.float32)
    for t, g in enumerate(_steps(), start=1):
        got = run_op("adamax", {
            "Param": p, "Grad": g, "Moment": m, "InfNorm": u,
            "LearningRate": LR, "Beta1Pow": b1p,
        }, {"beta1": beta1, "beta2": beta2, "epsilon": eps})
        m = beta1 * m + (1 - beta1) * g
        u = np.maximum(beta2 * u, np.abs(g))
        p = p - (0.1 / (1 - beta1 ** t)) * m / (u + eps)
        np.testing.assert_allclose(got["ParamOut"], p, rtol=1e-4, atol=1e-5)
        p, m, u, b1p = (got["ParamOut"], got["MomentOut"],
                        got["InfNormOut"], got["Beta1PowOut"])


def test_adadelta():
    rho, eps = 0.95, 1e-6
    p = _p()
    asg = np.zeros((4, 3), np.float32)
    asu = np.zeros((4, 3), np.float32)
    for g in _steps():
        got = run_op("adadelta", {
            "Param": p, "Grad": g, "AvgSquaredGrad": asg,
            "AvgSquaredUpdate": asu}, {"rho": rho, "epsilon": eps})
        asg = rho * asg + (1 - rho) * g * g
        upd = -np.sqrt((asu + eps) / (asg + eps)) * g
        asu = rho * asu + (1 - rho) * upd * upd
        p = p + upd
        np.testing.assert_allclose(got["ParamOut"], p, rtol=1e-4, atol=1e-5)
        p, asg, asu = (got["ParamOut"], got["AvgSquaredGradOut"],
                       got["AvgSquaredUpdateOut"])


def test_decayed_adagrad():
    decay, eps = 0.95, 1e-6
    p, m = _p(), np.zeros((4, 3), np.float32)
    for g in _steps():
        got = run_op("decayed_adagrad",
                     {"Param": p, "Grad": g, "Moment": m,
                      "LearningRate": LR},
                     {"decay": decay, "epsilon": eps})
        m = decay * m + (1 - decay) * g * g
        p = p - 0.1 * g / (np.sqrt(m) + eps)
        np.testing.assert_allclose(got["ParamOut"], p, rtol=1e-4, atol=1e-5)
        p, m = got["ParamOut"], got["MomentOut"]


def test_rmsprop():
    eps, decay, mom_c = 1e-10, 0.9, 0.6
    p = _p()
    ms = np.zeros((4, 3), np.float32)
    mom = np.zeros((4, 3), np.float32)
    for g in _steps():
        got = run_op("rmsprop", {
            "Param": p, "Grad": g, "MeanSquare": ms, "Moment": mom,
            "LearningRate": LR},
            {"epsilon": eps, "decay": decay, "momentum": mom_c})
        ms = decay * ms + (1 - decay) * g * g
        mom = mom_c * mom + 0.1 * g / np.sqrt(ms + eps)
        p = p - mom
        np.testing.assert_allclose(got["ParamOut"], p, rtol=1e-4, atol=1e-5)
        p, ms, mom = got["ParamOut"], got["MeanSquareOut"], got["MomentOut"]


def test_ftrl():
    l1, l2, lr_power = 0.1, 0.2, -0.5
    p = _p()
    sq = np.zeros((4, 3), np.float32)
    lin = np.zeros((4, 3), np.float32)
    for g in _steps():
        got = run_op("ftrl", {
            "Param": p, "Grad": g, "SquaredAccumulator": sq,
            "LinearAccumulator": lin, "LearningRate": LR},
            {"l1": l1, "l2": l2, "lr_power": lr_power})
        new_sq = sq + g * g
        sigma = (np.sqrt(new_sq) - np.sqrt(sq)) / 0.1
        new_lin = lin + g - sigma * p
        denom = np.sqrt(new_sq) / 0.1 + 2 * l2
        p = (np.clip(new_lin, -l1, l1) - new_lin) / denom
        np.testing.assert_allclose(got["ParamOut"], p, rtol=1e-4, atol=1e-5)
        sq, lin = got["SquaredAccumOut"], got["LinearAccumOut"]
        p = got["ParamOut"]


def test_proximal_gd():
    l1, l2 = 0.05, 0.1
    p = _p()
    for g in _steps():
        got = run_op("proximal_gd",
                     {"Param": p, "Grad": g, "LearningRate": LR},
                     {"l1": l1, "l2": l2})
        prox = p - 0.1 * g
        p = (np.sign(prox) * np.maximum(np.abs(prox) - 0.1 * l1, 0.0)
             / (1.0 + 0.1 * l2))
        np.testing.assert_allclose(got["ParamOut"], p, rtol=1e-4, atol=1e-5)
        p = got["ParamOut"]


def test_proximal_adagrad():
    l1, l2 = 0.05, 0.1
    p, m = _p(), np.zeros((4, 3), np.float32)
    for g in _steps():
        got = run_op("proximal_adagrad",
                     {"Param": p, "Grad": g, "Moment": m,
                      "LearningRate": LR}, {"l1": l1, "l2": l2})
        m = m + g * g
        lr = 0.1 / np.sqrt(m)
        prox = p - lr * g
        p = (np.sign(prox) * np.maximum(np.abs(prox) - lr * l1, 0.0)
             / (1.0 + lr * l2))
        np.testing.assert_allclose(got["ParamOut"], p, rtol=1e-4, atol=1e-5)
        p, m = got["ParamOut"], got["MomentOut"]
