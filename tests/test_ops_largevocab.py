"""Large-vocab ops: hierarchical_sigmoid (tree softmax) and selective_fc
(reference paddle/gserver/layers/HierarchicalSigmoidLayer.cpp,
SelectiveFcLayer.cpp; no fluid op existed for either in v0.11 — these carry
the gserver capability)."""

import numpy as np

from op_test import check_output, check_grad, run_op

rng = np.random.RandomState(11)


def _hsig_ref(x, w, b, labels, num_classes):
    """Naive per-sample bit-code walk (SimpleCode convention)."""
    out = np.zeros((x.shape[0], 1), np.float64)
    for n, c in enumerate(labels.ravel()):
        code = int(c) + num_classes
        length = code.bit_length() - 1
        for d in range(length):
            i = (code >> (d + 1)) - 1
            bit = (code >> d) & 1
            pre = float(x[n] @ w[i] + (b[i] if b is not None else 0.0))
            pre = min(max(pre, -40.0), 40.0)
            out[n, 0] += np.log1p(np.exp(pre)) - bit * pre
    return out.astype(np.float32)


def test_hierarchical_sigmoid_vs_naive_tree_walk():
    num_classes, d, bsz = 13, 6, 5
    x = rng.randn(bsz, d).astype(np.float32)
    w = rng.randn(num_classes - 1, d).astype(np.float32)
    b = rng.randn(num_classes - 1).astype(np.float32)
    lbl = rng.randint(0, num_classes, (bsz, 1)).astype(np.int32)
    expected = _hsig_ref(x, w, b, lbl, num_classes)
    check_output(
        "hierarchical_sigmoid",
        {"X": x, "W": w, "Label": lbl, "Bias": b},
        {"Out": expected},
        attrs={"num_classes": num_classes},
        atol=1e-4, rtol=1e-4,
    )


def test_hierarchical_sigmoid_no_bias_and_pow2_classes():
    num_classes, d, bsz = 8, 4, 3
    x = rng.randn(bsz, d).astype(np.float32)
    w = rng.randn(num_classes - 1, d).astype(np.float32)
    lbl = np.array([[0], [7], [3]], np.int32)
    expected = _hsig_ref(x, w, None, lbl, num_classes)
    got = run_op("hierarchical_sigmoid", {"X": x, "W": w, "Label": lbl},
                 {"num_classes": num_classes})
    np.testing.assert_allclose(got["Out"], expected, atol=1e-4, rtol=1e-4)
    # PreOut is zero at padded (inactive) path positions
    assert got["PreOut"].shape == (bsz, 3)


def test_hierarchical_sigmoid_grad():
    num_classes, d, bsz = 6, 4, 3
    inputs = {
        "X": rng.randn(bsz, d).astype(np.float32),
        "W": rng.randn(num_classes - 1, d).astype(np.float32) * 0.5,
        "Label": rng.randint(0, num_classes, (bsz, 1)).astype(np.int32),
        "Bias": rng.randn(num_classes - 1).astype(np.float32) * 0.1,
    }
    attrs = {"num_classes": num_classes}
    for wrt in ("X", "W", "Bias"):
        check_grad("hierarchical_sigmoid", inputs, wrt, attrs=attrs,
                   output="Out", max_relative_error=5e-3)


def test_selective_fc_selected_columns():
    d, k = 5, 9
    x = rng.randn(3, d).astype(np.float32)
    w = rng.randn(k, d).astype(np.float32)
    b = rng.randn(k).astype(np.float32)
    sel = np.array([[0, 4, -1], [8, 2, 1], [3, -1, -1]], np.int32)
    expected = np.zeros((3, 3), np.float32)
    for i in range(3):
        for j in range(3):
            if sel[i, j] >= 0:
                expected[i, j] = x[i] @ w[sel[i, j]] + b[sel[i, j]]
    check_output("selective_fc", {"X": x, "W": w, "Bias": b, "Select": sel},
                 {"Out": expected}, atol=1e-4, rtol=1e-4)


def test_selective_fc_full_mode_is_fc():
    d, k = 5, 7
    x = rng.randn(4, d).astype(np.float32)
    w = rng.randn(k, d).astype(np.float32)
    b = rng.randn(k).astype(np.float32)
    check_output("selective_fc", {"X": x, "W": w, "Bias": b},
                 {"Out": x @ w.T + b}, atol=1e-4, rtol=1e-4)


def test_selective_fc_grad():
    d, k = 4, 6
    inputs = {
        "X": rng.randn(2, d).astype(np.float32),
        "W": rng.randn(k, d).astype(np.float32),
        "Bias": rng.randn(k).astype(np.float32),
        "Select": np.array([[0, 3], [5, -1]], np.int32),
    }
    for wrt in ("X", "W", "Bias"):
        check_grad("selective_fc", inputs, wrt, output="Out",
                   max_relative_error=5e-3)


def test_hsigmoid_layer_trains():
    """End-to-end: the hsigmoid layer's loss decreases under SGD and the
    selective_fc layer composes in a program."""
    import paddle_tpu as pt
    from paddle_tpu import layers

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        feat = layers.fc(x, size=16, act="tanh")
        cost = layers.hsigmoid(feat, label, num_classes=10)
        avg = layers.mean(cost)
        pt.optimizer.SGD(learning_rate=0.5).minimize(avg)

    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)
    xs = rng.randn(32, 8).astype(np.float32)
    ys = rng.randint(0, 10, (32, 1)).astype(np.int64)
    losses = []
    for _ in range(30):
        (l,) = exe.run(main, feed={"x": xs, "label": ys},
                       fetch_list=[avg], scope=scope)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_selective_fc_layer_shapes():
    import paddle_tpu as pt
    from paddle_tpu import layers

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        sel = layers.data("sel", shape=[4], dtype="int64")
        out_sel = layers.selective_fc(x, size=50, select=sel)
        out_full = layers.selective_fc(x, size=50)
    assert tuple(out_sel.shape) == (-1, 4) or out_sel.shape[1] == 4
    assert out_full.shape[1] == 50
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)
    o1, o2 = exe.run(
        main,
        feed={"x": rng.randn(3, 8).astype(np.float32),
              "sel": rng.randint(0, 50, (3, 4)).astype(np.int64)},
        fetch_list=[out_sel, out_full], scope=scope)
    assert o1.shape == (3, 4) and o2.shape == (3, 50)
